"""CODA: consensus-driven active model selection, TPU-native.

Capability parity with the reference method (reference ``coda/coda.py:171-346``
and its kernel functions at ``:14-168``), re-architected for XLA:

  * selector state is a fixed-shape pytree (Dirichlet posteriors + masks),
    not Python lists — jit/scan/vmap-able and trivially checkpointable;
  * the EIG acquisition is a vmapped pure function over *all* N points with
    candidate masking at argmax time, chunked only as a memory valve via
    ``lax.map(..., batch_size=...)`` (the reference chunks a Python loop at
    100 items/iter, ``coda/coda.py:261``);
  * the P(best) integral's serial CDF loop is replaced by a parallel
    cumulative trapezoid (see ``coda_tpu/ops/pbest.py``);
  * the consensus prefilter (drop points where every model agrees,
    ``coda/coda.py:215-224``) becomes a static boolean mask; the optional
    ``prefilter_n`` random subsample becomes a top-k over masked uniforms;
  * the default EIG is INCREMENTAL: a labeling round touches only Dirichlet
    row ``true_class``, so the (C, N, H) hypothetical-P(best) tensor is
    carried in the scan state and only the updated class row is recomputed
    per round — a C-fold FLOP cut over re-deriving everything, with scoring
    reduced to elementwise mixture entropies over the cache. ``eig_mode``
    tiers: incremental (cache fits) -> factored (tables fit) -> rowscan
    (very large C·H pools, O(H·G) temps), all computing the same integral.

Numeric choreography (grid endpoints, eps floors, +-80 clamps, fp32
everywhere, HIGHEST-precision einsums) follows the reference so the EIG
argmax ordering — and therefore the label-selection trace — matches.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.ops.beta import beta_log_pdf, cumtrapz_uniform, dirichlet_to_beta
from coda_tpu.ops.confusion import (
    create_confusion_matrices,
    ensemble_preds,
    initialize_dirichlets,
)
from coda_tpu.ops.masked import entropy2, masked_argmax_tiebreak
from coda_tpu.ops.pbest import _EPS, compute_pbest, pbest_grid, pbest_row_mixture
from coda_tpu.ops.sparse_rows import SparseRows
from coda_tpu.selectors.protocol import Selector, SelectResult
from coda_tpu.selectors.surrogate import SurrogateFit

_PRECISION = lax.Precision.HIGHEST
# reference coda/coda.py:307 uses isclose(rtol=1e-8) with torch's default
# atol=1e-8; atol dominates for tiny EIG entropy deltas
_TIE_RTOL = 1e-8
_TIE_ATOL = 1e-8


class CODAHyperparams(NamedTuple):
    prefilter_n: int = 0
    alpha: float = 0.9            # prior_strength = 1 - alpha (coda/coda.py:189)
    learning_rate: float = 0.01   # update_strength
    multiplier: float = 2.0
    disable_diag_prior: bool = False  # ablation 1
    q: str = "eig"                # acquisition: eig | iid | uncertainty (ablation 2)
    eig_chunk: int = 256          # memory valve for the EIG map
    num_points: int = 256         # P(best) integration grid
    eig_mode: str = "auto"        # auto | incremental (cached per-class P(best),
    #                               C-fold fewer FLOPs/round) | factored (MXU,
    #                               stateless) | direct (reference numeric
    #                               choreography, kept for cross-checks)
    eig_backend: str = "auto"     # auto | jnp | pallas (fused single-HBM-
    #                               pass TPU kernel for the incremental
    #                               scoring). auto = pallas on a single-
    #                               chip TPU process running the
    #                               incremental tier (3x the jnp scoring
    #                               pass on a v5e, silicon-validated
    #                               numerics — see resolve_eig_backend),
    #                               jnp everywhere else.
    n_parallel: int = 1           # replicas of this experiment sharing the
    #                               chip (e.g. vmapped seeds): multiplies the
    #                               per-replica cache/table footprints in the
    #                               "auto" eig_mode budget — 5 vmapped seeds
    #                               at M=1k/N=50k carry 5 x 2 GB caches, so
    #                               auto must fall back to factored where a
    #                               single run would stay incremental
    eig_precision: str = "highest"  # highest | high | default — matmul
    #                               precision of the EIG table einsums ONLY
    #                               (S and t passes, 6*N*H*G FLOPs). highest
    #                               = 6-pass fp32 (reference numerics, the
    #                               parity-tested default); high = 3-pass
    #                               (~2x MXU throughput on TPU); default =
    #                               1-pass bf16. Anything below highest can
    #                               reorder near-tie EIG argmaxes on TPU —
    #                               opt-in speed, not reference semantics.
    eig_cache_dtype: str = "float32"  # float32 | bfloat16 — storage dtype
    #                               of the incremental (C, N, H) P(best)
    #                               cache. bfloat16 HALVES the dominant
    #                               HBM stream of the scoring pass (the
    #                               cache read) and the tier's footprint;
    #                               scores are computed in fp32 after
    #                               upcast, but the stored probabilities
    #                               carry ~3 decimal digits, so near-tie
    #                               EIG orderings can change — opt-in
    #                               speed, not reference semantics (same
    #                               contract as eig_precision).
    eig_refresh: str = "precomputed"  # precomputed | fused — where the
    #                               incremental row-refresh einsums run.
    #                               "precomputed" (default, reference
    #                               numerics): XLA-HIGHEST einsums emit
    #                               the (N, H) replacement row, the
    #                               pallas kernel streams it in. "fused"
    #                               (opt-in, pallas backend only): the
    #                               row is computed INSIDE the scoring
    #                               kernel from O(H·G) Beta tables —
    #                               three fp32 MXU dots per tile overlap
    #                               the cache read, removing the largest
    #                               remaining XLA stage (3.2-3.7 ms at
    #                               headline) and the (N, H) round-trip.
    #                               In-kernel dots are not XLA-HIGHEST:
    #                               refreshed cache values differ from
    #                               the precomputed path by up to the
    #                               MEASURED 2.34e-4 at the headline
    #                               shape (fusedcompute_row_max_abs_diff,
    #                               PALLAS_TPU_VALIDATION_r05.json, v5e
    #                               silicon) — not "ulps": the origin is
    #                               the single-pass fp32 MXU dots
    #                               replacing 6-pass XLA-HIGHEST einsums
    #                               in the S/t_base/t_diff contractions,
    #                               whose rounding difference the
    #                               exp(S - max S) integrand then
    #                               amplifies on near-degenerate Beta
    #                               rows. The drift does NOT compound
    #                               over rounds (each refresh recomputes
    #                               its row from the Dirichlet posterior,
    #                               which both paths update identically);
    #                               the 100-round digits_h80 fused-vs-
    #                               default selection-trace agreement
    #                               test pins the long-horizon behavior.
    #                               Same opt-in contract as
    #                               eig_precision / eig_cache_dtype.
    eig_entropy: str = "exact"    # exact | approx — the log lowering of
    #                               the expected-entropy chain (the
    #                               scoring pass's N·C·H ~ 5e8 log evals
    #                               per round at headline — the invariant
    #                               ~1.2 ms VPU tail that caps the bf16
    #                               path at 3.04 ms, NOTES_r05.md).
    #                               "approx" replaces the transcendental
    #                               with a bit-extracted exponent + fixed
    #                               degree-6 mantissa polynomial on the
    #                               clamped [1e-12, 1] domain
    #                               (ops/masked.log2_approx): max |Δlog2|
    #                               ≤ 1e-5, max |Δscore| ≤ 1e-4 (measured
    #                               ~2e-5, pinned by
    #                               tests/test_fast_entropy.py), applied
    #                               consistently in the jnp AND pallas
    #                               lowerings so auto backend routing
    #                               never changes numerics class across a
    #                               fallback. Opt-in speed, not reference
    #                               semantics — same contract as
    #                               eig_precision / eig_cache_dtype /
    #                               eig_refresh.
    shard_spec: str = ""          # "" | "data=K" — declared mesh sharding
    #                               of the (H, N, C) tensor for the pallas
    #                               fast path. pallas_call is an opaque
    #                               custom call GSPMD cannot partition, so
    #                               sharded runs demote to jnp UNLESS the
    #                               caller declares the mesh here: the
    #                               scoring / fused-refresh passes then run
    #                               per data shard under shard_map (no
    #                               collectives — scoring is parallel over
    #                               N; selection argmaxes the sharded
    #                               result outside). Data-only meshes; N
    #                               must divide by the axis size.
    posterior: str = "dense"      # dense | sparse:K — Dirichlet posterior
    #                               representation. "dense" carries the
    #                               reference (H, C, C) tensor (2 GB at
    #                               ImageNet scale) through the scan and
    #                               reduces ALL of it to Beta parameters
    #                               every round. "sparse:K" keeps each
    #                               class row as diagonal + top-K
    #                               off-diagonal (value, index) pairs +
    #                               one residual mass (~(2K+2)/C of the
    #                               dense state; K=32, C=1000 -> ~15x
    #                               smaller), with label updates touching
    #                               one row per model (sparse scatter,
    #                               smallest-entry eviction into the
    #                               residual) and the per-round Beta
    #                               extraction reading O(H*K) instead of
    #                               O(H*C^2) (ops/sparse_rows.py). Row
    #                               mass is conserved exactly, so the
    #                               quadrature sees the same Betas up to
    #                               float summation order; only the exact
    #                               pi-hat column refresh reads the
    #                               share-spread reconstruction (the
    #                               default delta path never reads the
    #                               posterior at all). Incremental tier
    #                               only. sparse:K>=C is the untruncated
    #                               parity layout — bitwise equal to
    #                               dense, pinned in tier-1.
    eig_pbest: str = "quad"       # quad | amortized — the hypothetical
    #                               P(best) row-refresh integral.
    #                               "amortized" (opt-in, jnp backend +
    #                               precomputed refresh) replaces the
    #                               Beta lgamma grids + cumtrapz CDF with
    #                               the closed-form logistic-normal
    #                               (Laplace-bridge) tables of
    #                               arXiv 1905.12194, gated per round on
    #                               row concentration so the committed
    #                               2.34e-4 score contract provably
    #                               holds: rows with min(a+b) below
    #                               _AMORTIZED_MIN_CONC fall back to the
    #                               exact quadrature (see the measured
    #                               calibration at the constant). The
    #                               CACHED per-row P(best) (best-model
    #                               readout, recorder digests) always
    #                               stays quadrature-exact.
    eig_scorer: str = "exact"     # exact | surrogate:k — who scores the
    #                               round. "exact" (default, bitwise-
    #                               pinned like every ladder rung) runs
    #                               the full O(N·C·H) chain. "surrogate:k"
    #                               (opt-in, incremental tier + jnp
    #                               backend) scores all N candidates with
    #                               a carried closed-form ridge over ~16
    #                               cheap per-candidate features
    #                               (selectors/surrogate.py — the LINNA
    #                               arXiv 2203.05583 pattern), then
    #                               refreshes ONLY its top-k shortlist +
    #                               a rotating audit set through the
    #                               exact chain. The trust gate is
    #                               structural: the shortlist's exact
    #                               scores are computed anyway, so every
    #                               round measures rank agreement and
    #                               |Δscore| on the ranks that matter
    #                               (2.34e-4, the committed score-
    #                               contract bound); a violated contract
    #                               falls back to a full exact pass for
    #                               that round — bitwise the exact round
    #                               — and refolds the fit. Warmup rounds
    #                               are always exact and seed the
    #                               regression, so selection is never
    #                               driven by an unaudited score.
    #                               surrogate:k>=N is the exact-parity
    #                               configuration (bitwise, pinned).
    surrogate_prior: str = "off"  # off | pool — cross-session warm-start
    #                               of the surrogate fit. "off" (default)
    #                               is bitwise the prior-less program.
    #                               "pool" seeds a fresh fit from a
    #                               merged per-(task, pool-fingerprint)
    #                               prior aggregated from closed/demoted
    #                               sessions' normal equations
    #                               (selectors/surrogate.PriorStats —
    #                               the A/b form is mergeable by pure
    #                               sum), granting warmup-round credit;
    #                               the per-round trust gate still
    #                               audits every credited round, so
    #                               selection is never driven by an
    #                               unaudited score. The prior ARRAYS
    #                               arrive via make_coda(prior=...) or
    #                               the serve bucket's seeding hook —
    #                               this knob only declares/fingerprints
    #                               the mode (it is hashable; the stats
    #                               are not).
    pi_update: str = "auto"       # auto | delta | exact — incremental-mode
    #                               pi-hat column refresh. "auto" resolves
    #                               by backend (resolve_pi_update):
    #                               "delta" on CPU (the XLA gather is ~90x
    #                               cheaper than the einsum there) AND on
    #                               a single-chip TPU, where the pallas
    #                               DMA-gather kernel reads the H rows at
    #                               DMA bandwidth (ops/pallas_gather.py —
    #                               XLA's own TPU gather lowering runs
    #                               ~28 GB/s effective, 7.1 ms at headline
    #                               on a v5e, slower than the exact
    #                               einsum's full-tensor MXU stream at
    #                               2.8 ms); "exact" on multi-device TPU
    #                               processes, where the opaque pallas
    #                               call cannot shard. "delta" adds the exact
    #                               linear increment lr*preds[h,n,s_h] via a
    #                               contiguous gather from a once-transposed
    #                               (C, H, N) layout: O(H*N) bytes/round
    #                               instead of streaming the full (H, N, C)
    #                               tensor (C-fold traffic cut; the pi-hat
    #                               stream was HALF the round's HBM
    #                               traffic). Identical math — what differs
    #                               is float ACCUMULATION ORDER
    #                               (~1e-7/round), the same class of
    #                               deviation sharded psum reduction order
    #                               introduces by design; the full
    #                               reference-length trace is pinned equal
    #                               to "exact" in
    #                               test_pi_delta_matches_exact_recompute.
    #                               "exact" recomputes the column einsum
    #                               each round (strict reference float
    #                               choreography; also halves the
    #                               incremental tier's HBM footprint —
    #                               see resolve_eig_mode's budget).


# "auto" picks the incremental EIG only while its (C, N, H) fp32 cache fits
# comfortably on one chip; past this it falls back to the stateless factored
# kernel (the cache is exactly as large as the prediction tensor itself, so
# at the 100 GB ImageNet scale it must be sharded deliberately, not by default)
_INCR_CACHE_MAX_BYTES = 4 << 30
# under --eig-scorer surrogate:k the same residency is charged at FULL
# weight against a HIGHER comfort ceiling: the 4 GiB bound exists because
# the exact scorer also STREAMS the whole cache through every round's
# scoring pass — past it, the per-round HBM traffic (not the capacity)
# is what demands deliberate sharding. A surrogate round streams only the
# shortlist's O((k+audit)·C·H) slice (full streams confined to warmup/
# fallback rounds, <= 10% by the committed contract), so residency alone
# binds and 6 GiB still leaves >half of a v5e's 16 GB for the preds
# tensor and temps. This is what lets the C=1000 x H=2000 HF zero-shot
# pool resolve to the incremental tier under the surrogate (boundary
# pinned both ways in tests, like the PR 9 posterior term).
_SURROGATE_INCR_CACHE_MAX_BYTES = 6 << 30
# past this the factored kernel's four (C, H, num_points) fp32 Beta tables
# don't fit either and "auto" scans class rows instead. For calibration: the
# ImageNet-scale config (C=1000, H=500, G=256) needs 4 x 512 MB of tables —
# within this budget, so "auto" stays factored there; rowscan engages for
# pools ~4x beyond it (e.g. the C=1000 x H=2000+ HF zero-shot pool).
_TABLES_MAX_BYTES = 2 << 30

# eig_pbest='amortized' engagement gate: the logistic-normal closed forms
# replace the row-refresh quadrature only when the labeled row's
# min_h(a+b) clears this, else that round refreshes through the exact
# quadrature — so the committed 2.34e-4 score contract provably holds.
# Calibration (hyp-only amortized vs quad through the full scoring chain,
# worst over digits_h80/wine/breast_cancer/2 synthetic pools at
# concentration-scaled posteriors, tests/test_sparse_posterior.py):
#   min(a+b) >=  16.8 -> max |Δscore| 2.32e-4 (at the contract edge)
#   min(a+b) >=  33.6 -> max |Δscore| 1.44e-4 (the committed margin)
#   min(a+b) >=  67.2 -> max |Δscore| 9.5e-5
# The default prior (multiplier=2, alpha=0.9) sits at ~4.2 where the
# measured error is 1.4e-3 — those rounds keep the quadrature; strongly
# concentrated posteriors (multiplier >= 16, long-horizon counts) engage.
_AMORTIZED_MIN_CONC = 32.0


def resolve_pi_update(hp: "CODAHyperparams", N: int | None = None) -> str:
    """The concrete pi-hat refresh LOWERING for this config (shared with
    bench.py): "exact" | "delta" (XLA take-along-axis) | "delta_pallas"
    (the DMA-gather kernel, ``ops/pallas_gather.py``). This is the ONE
    place the lowering predicate lives — make_coda wires the gather it
    names, bench prices the bytes it names.

    auto -> delta everywhere the gather has a fast lowering: (a) CPU,
    where XLA's take-along-axis is the decisive win (O(H·N) bytes vs the
    full O(H·N·C) stream), and (b) a SINGLE-chip TPU process running ONE
    experiment, where the pallas kernel reads the H rows at DMA bandwidth
    — XLA's own TPU gather lowering runs ~28 GB/s effective on a v5e
    (7.1 ms at headline, measured round 4), slower than streaming the
    full tensor through the exact MXU einsum (2.8 ms), so every TPU
    context where the kernel can't engage resolves to "exact" instead:
    multi-device processes (the opaque pallas call cannot shard), vmapped
    batches (``n_parallel`` > 1 — the kernel's custom_vmap rule would
    fall back to the slow XLA gather, same guard as
    ``resolve_eig_backend``), and N past the kernel's single-tile VMEM
    cap. An EXPLICIT "delta" keeps delta semantics and still gets the
    kernel exactly when it is viable. Resolution reads
    ``jax.default_backend()`` at selector-build time — a host-side config
    decision, identical across hosts of a multi-host mesh.
    """
    if hp.pi_update == "exact":
        return "exact"
    import jax

    from coda_tpu.ops.pallas_gather import _MAX_TILE_N

    pallas_viable = (
        jax.default_backend() == "tpu"
        and jax.device_count() == 1
        and hp.n_parallel <= 1
        and (N is None or N <= _MAX_TILE_N)
    )
    if hp.pi_update == "delta":
        return "delta_pallas" if pallas_viable else "delta"
    # auto
    if jax.default_backend() != "tpu":
        return "delta"
    return "delta_pallas" if pallas_viable else "exact"


def shard_mesh_for(hp: "CODAHyperparams", N: int):
    """The mesh of ``hp.shard_spec`` when the sharded pallas path is
    viable for it, else None. Raises on meshes the path cannot support
    (model axis > 1; N not divisible by the data axis)."""
    if not hp.shard_spec:
        return None
    from coda_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, mesh_from_spec

    mesh = mesh_from_spec(hp.shard_spec)
    if mesh.shape[MODEL_AXIS] != 1:
        raise ValueError(
            "shard_spec with the pallas backend supports DATA-only meshes "
            f"(scoring is parallel over N); got {hp.shard_spec!r} — the "
            "P(best) exclusive product over a sharded model axis needs the "
            "jnp backend's psum"
        )
    d = mesh.shape[DATA_AXIS]
    if N % d != 0:
        raise ValueError(
            f"shard_spec {hp.shard_spec!r}: N={N} not divisible by the "
            f"data axis ({d}); pad the task or use the jnp backend"
        )
    return mesh


def resolve_eig_backend(hp: "CODAHyperparams", eig_mode: str,
                        N: int | None = None) -> str:
    """The concrete scoring backend for this config (shared with bench.py).

    auto -> "pallas" on a TPU process running the incremental tier when
    the opaque-custom-call restriction (pallas_call cannot be partitioned
    by GSPMD) cannot bite: an UNBATCHED single-chip process, or a
    multi-chip process whose data-axis sharding is DECLARED via
    ``shard_spec`` (the kernels then run per shard under shard_map — see
    ``ops/pallas_eig.eig_scores_cache_pallas_sharded``). Vmapped batches
    (``n_parallel`` > 1) resolve to "jnp" under auto: the batched
    kernels exist and are silicon-validated
    (PALLAS_TPU_VALIDATION_r05.json), but their fixed (C, ·, H)/(·, 1)
    layouts pad pathologically at the suite's small-H family shapes
    (see ``ops/pallas_eig.batched_pallas_viable``) and have not been
    shown faster than XLA's per-shape layouts there — engage them
    explicitly with eig_backend='pallas' where the shape suits them
    (C small, H large). Everywhere else — CPU/GPU, undeclared
    multi-device, non-incremental tiers — auto stays "jnp".
    """
    if hp.eig_backend != "auto":
        return hp.eig_backend
    import jax

    if eig_mode != "incremental" or jax.default_backend() != "tpu":
        return "jnp"
    if hp.eig_pbest == "amortized":
        # the amortized row refresh is a jnp-table path; auto must not
        # route scoring into the pallas kernels it cannot feed
        return "jnp"
    from coda_tpu.selectors.surrogate import parse_scorer

    if parse_scorer(hp.eig_scorer) is not None:
        # the surrogate's shortlist refresh is a jnp gather-and-score
        # path (and its hybrid vector is not the kernels' contract);
        # auto demotes to jnp under the knob, same as eig_pbest
        return "jnp"
    if hp.n_parallel <= 1 and jax.device_count() == 1:
        return "pallas"
    if hp.shard_spec and hp.n_parallel <= 1:
        try:  # an unsupported spec demotes auto to jnp instead of raising
            if N is None or shard_mesh_for(hp, N) is not None:
                return "pallas"
        except ValueError:
            return "jnp"
    return "jnp"


def resolve_eig_mode(hp: "CODAHyperparams", H: int, N: int, C: int) -> str:
    """The concrete EIG kernel tier for this config (shared with bench.py so
    reported FLOPs always describe the kernel that actually ran).

    auto -> incremental while (a) the acquisition is full-pool EIG — the
    prefilter path re-scores a different random subset each round, while the
    cache's row refresh is O(N) regardless — and (b) the (C, N, H) cache
    fits; else factored while its (C, H, G) tables fit; else rowscan.
    """
    from coda_tpu.ops.sparse_rows import parse_posterior, posterior_nbytes
    from coda_tpu.selectors.surrogate import parse_scorer

    full_pool_eig = (hp.q == "eig"
                     and not (hp.prefilter_n and hp.prefilter_n < N))
    # per-replica resident bytes of the incremental tier: the P(best)
    # cache at its storage dtype, plus the fp32 (C, H, N) transposed
    # preds layout the delta pi-hat path keeps resident — the auto budget
    # must charge for both or "fits comfortably on one chip" silently
    # becomes an OOM
    cache_bytes = jnp.dtype(hp.eig_cache_dtype).itemsize * N * C * H
    # the scorer tier picks the BUDGET the (full-weight) residency is
    # held to: the exact scorer's bound also prices the whole-cache
    # stream every scoring pass pays; the surrogate streams only its
    # shortlist slice per round, so residency alone binds and the
    # comfort ceiling is higher (see _SURROGATE_INCR_CACHE_MAX_BYTES —
    # never a discounted charge, the bytes stay resident either way)
    budget = (_SURROGATE_INCR_CACHE_MAX_BYTES
              if parse_scorer(hp.eig_scorer) is not None
              else _INCR_CACHE_MAX_BYTES)
    delta_bytes = (4 * N * C * H
                   if resolve_pi_update(hp, N).startswith("delta") else 0)
    # ...plus the POSTERIOR itself, which the scan carries alongside the
    # cache: the dense (H, C, C) tensor is 2 GB at ImageNet scale — at
    # large C it, not the cache, is what pushes a dense config out of the
    # incremental tier, and the sparse:K representation is what keeps the
    # same shape inside it (tests pin the C=1000 boundary both ways)
    post_bytes = posterior_nbytes(H, C, parse_posterior(hp.posterior))
    if hp.eig_mode != "auto":
        if hp.eig_mode == "incremental" and not full_pool_eig:
            raise ValueError(
                "eig_mode='incremental' requires the full-pool EIG "
                "acquisition (q='eig' without an active prefilter); the "
                f"requested config (q={hp.q!r}, prefilter_n={hp.prefilter_n}) "
                "would maintain a large P(best) cache that is never read"
            )
        return hp.eig_mode
    par = max(1, hp.n_parallel)
    if (full_pool_eig
            and par * (cache_bytes + delta_bytes + post_bytes)
            <= budget):
        return "incremental"
    if par * 16 * C * H * hp.num_points <= _TABLES_MAX_BYTES:
        return "factored"
    return "rowscan"


class CODAState(NamedTuple):
    dirichlets: jnp.ndarray    # (H, C, C) Dirichlet confusion posteriors
    pi_hat_xi: jnp.ndarray     # (N, C) per-item class posterior
    pi_hat: jnp.ndarray        # (C,) marginal class estimate
    unlabeled: jnp.ndarray     # (N,) bool
    # incremental-EIG cache (None unless eig_mode resolves to "incremental"):
    # P(best | class row c) under the current posterior, and under the
    # hypothetical +1 label of item n as class c. Only Dirichlet row
    # ``true_class`` changes per labeling round (see ``update``), so all other
    # rows of both tensors carry over unchanged between rounds.
    pbest_rows: Optional[jnp.ndarray] = None   # (C, H)
    # (C, N, H): class rows LEADING so the per-round row refresh is a
    # leading-index update and the two minor dims (N, H) tile onto the
    # TPU's (8, 128) physical layout with only the H pad (1000 -> 1024,
    # +2.4%) — the (N, C, H) alternative puts C in the sublane dim, and at
    # headline C=10 the pad to 16 sublanes taxes every HBM pass with 1.6x
    # the logical bytes (measured round 4 on a v5e)
    pbest_hyp: Optional[jnp.ndarray] = None    # (C, N, H)
    # unnormalized pi_hat_xi, same factorization: column c of
    # ``Σ_{h,s} dirichlets[h,c,s]·preds[h,n,s]`` depends only on Dirichlet
    # row c, so the update refreshes one column at O(N·H·C) instead of the
    # full O(N·H·C²) einsum — the dominant per-round cost at large C
    pi_xi_unnorm: Optional[jnp.ndarray] = None  # (N, C)
    # SCORE-AHEAD (incremental tier only): the EIG scores of the current
    # posterior, computed at the END of init/update rather than inside the
    # next select. Identical values, different schedule — it puts the
    # scoring pass in refresh->score order, so a pallas score custom call
    # never precedes the in-place row DUS on the carried cache (the
    # score->DUS order forced XLA to copy the full cache every
    # round: +~10 ms at headline on a v5e, profiled round 4)
    eig_scores_cached: Optional[jnp.ndarray] = None  # (N,)
    # sparse posterior representation (None unless hp.posterior is
    # 'sparse:K'): replaces ``dirichlets`` in the carry — diag/top-K
    # vals+idx/residual per class row (ops/sparse_rows.SparseRows), so a
    # labeling round DUSes one row of each small leaf instead of pushing
    # the (H, C, C) tensor through the scan
    sparse: Optional["SparseRows"] = None
    # contract-gated surrogate scorer (None unless hp.eig_scorer is
    # 'surrogate:k'): the carried ridge fit — normal equations, solved
    # weights, per-class Beta summaries, gate counters
    # (selectors/surrogate.SurrogateFit). Shape-static, so it rides the
    # scan carry and the serve export/import snapshot unchanged.
    surrogate: Optional["SurrogateFit"] = None


def update_pi_hat(
    dirichlets: jnp.ndarray, preds: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dirichlet-adjusted class posterior per item + dataset marginal.

    ``adjusted[h,n,c] = Σ_s dirichlets[h,c,s] * preds[h,n,s]`` summed over
    models (reference ``coda/coda.py:226-233``) — a batched matmul that maps
    straight onto the MXU.
    """
    pi_xi, pi = _normalize_pi(pi_unnorm(dirichlets, preds))
    return pi_xi, pi


def _pi_precision(preds: jnp.ndarray) -> lax.Precision:
    """HIGHEST for every in-budget shape; DEFAULT past the one-shot budget
    on the TPU backend, where nothing stricter compiles (see
    :func:`pi_unnorm` and ``confusion.oneshot_precision``)."""
    from coda_tpu.ops.confusion import oneshot_precision

    H, N, C = preds.shape
    return oneshot_precision(4 * H * N * C)


def pi_unnorm(dirichlets: jnp.ndarray, preds: jnp.ndarray) -> jnp.ndarray:
    """Unnormalized (N, C) class scores — the ONE pi-hat contraction kernel
    (shared by the full recompute and the incremental column cache so the
    two paths can never desync numerically)."""
    # contract models inside the einsum: the (H, N, C) adjusted tensor (2 GB
    # at M=1k, N=50k) never materializes — one MXU pass straight to (N, C).
    # Precision demotes to DEFAULT past the one-shot budget: at the true
    # ~10 GiB DomainNet scale NO HIGH/HIGHEST contraction of the tensor
    # compiles on this stack (the TPU compile helper fails outright —
    # reproduced round 5 on a v5e at H=400, N=50k, C=126, einsum and
    # per-slice-dot forms alike, while the DEFAULT einsum compiles and
    # runs). bf16 multiplies with fp32 accumulation perturb pi-hat at
    # ~1e-3 relative — confined to this beyond-one-chip scale; every
    # in-budget shape (and each shard of a sharded run, which partitions
    # this same einsum) keeps the reference-parity HIGHEST.
    return jnp.einsum("hcs,hns->nc", dirichlets, preds,
                      precision=_pi_precision(preds))


def _normalize_pi(unnorm: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(pi_hat_xi, pi_hat) from the unnormalized (N, C) class scores."""
    pi_xi = unnorm / jnp.clip(unnorm.sum(axis=-1, keepdims=True), 1e-12, None)
    pi = pi_xi.sum(axis=0)
    return pi_xi, pi / pi.sum()


def update_pi_hat_column(
    dirichlets: jnp.ndarray,   # (H, C, C) — ALREADY holding the new label
    true_class: jnp.ndarray,   # scalar int
    preds: jnp.ndarray,        # (H, N, C)
    pi_xi_unnorm: jnp.ndarray,  # (N, C) unnormalized cache
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Refresh only column ``true_class`` of the pi-hat factorization.

    ``unnorm[n,c]`` contracts Dirichlet row c with the predictions, so a
    labeling round (which touches only row ``true_class``) invalidates one
    column: one O(N·H·C) einsum instead of the full O(N·H·C²) pass.
    Returns ``(pi_hat_xi, pi_hat, new_unnorm)``.
    """
    d_t = jnp.take(dirichlets, true_class, axis=1)     # (H, C)
    return update_pi_hat_column_from_row(d_t, true_class, preds,
                                         pi_xi_unnorm)


def update_pi_hat_column_from_row(
    d_t: jnp.ndarray,          # (H, C) — Dirichlet row ``true_class``
    true_class: jnp.ndarray,   # scalar int
    preds: jnp.ndarray,        # (H, N, C)
    pi_xi_unnorm: jnp.ndarray,  # (N, C) unnormalized cache
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`update_pi_hat_column` taking the class row directly — the
    entry the sparse posterior tier feeds with its share-spread row
    reconstruction (``ops.sparse_rows.densify_row``) so the column einsum
    never needs the dense (H, C, C) tensor."""
    # precision demotes past the one-shot budget (see pi_unnorm)
    col = jnp.einsum("hs,hns->n", d_t, preds,
                     precision=_pi_precision(preds))  # (N,)
    unnorm = pi_xi_unnorm.at[:, true_class].set(col)
    pi_xi, pi = _normalize_pi(unnorm)
    return pi_xi, pi, unnorm


def update_pi_hat_column_delta(
    true_class: jnp.ndarray,    # scalar int
    pred_classes: jnp.ndarray,  # (H,) int32 — each model's hard pred at idx
    preds_by_class: jnp.ndarray,  # (C, H, N) — preds transposed once
    pi_xi_unnorm: jnp.ndarray,  # (N, C) unnormalized cache
    update_strength: float,
    gather_fn=None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Exact linear increment of the pi-hat column (the bandwidth-lean path).

    The labeling round adds ``lr * 1[s == s_h]`` to Dirichlet row
    ``true_class`` of every model h (``s_h`` = model h's hard prediction at
    the labeled point), and ``unnorm[n, c] = Σ_{h,s} d[h,c,s]·preds[h,n,s]``
    is linear in d — so the column moves by exactly
    ``lr · Σ_h preds[h, n, s_h]``. Gathering that from the (C, H, N)
    transposed layout reads H contiguous N-rows (O(H·N) bytes) instead of
    re-streaming the full (H, N, C) tensor the way the column einsum does
    (:func:`update_pi_hat_column`). ``gather_fn`` picks the lowering of
    that gather — and owns ``preds_by_class``'s layout: the default XLA
    take-along-axis over (C, H, N) (fast on CPU), or the pallas DMA-gather
    kernel over the flat (C·H, 1, Np) layout on a single-chip TPU
    (``ops/pallas_gather.gather_rows_sum_prepped`` — make_coda wires both
    sides). Identical math; only float accumulation order differs (drift
    ~1e-7/round, pinned by ``test_pi_delta_matches_exact_recompute``).
    """
    if gather_fn is None:
        from coda_tpu.ops.pallas_gather import gather_rows_sum_xla

        gather_fn = gather_rows_sum_xla
    delta = update_strength * gather_fn(preds_by_class, pred_classes)
    unnorm = pi_xi_unnorm.at[:, true_class].add(delta)
    pi_xi, pi = _normalize_pi(unnorm)
    return pi_xi, pi, unnorm


def eig_scores(
    dirichlets: jnp.ndarray,   # (H, C, C)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    hard_preds: jnp.ndarray,   # (N, H) int32 argmax predictions
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
) -> jnp.ndarray:
    """Expected information gain of labeling each point. Returns (N,).

    For every point and hypothetical true class c, apply the +1-count Beta
    update to the diagonal Beta of row c of every model (the scalable
    shortcut of reference ``batch_update_beta``, ``coda/coda.py:150-168``),
    recompute P(best | row c), propagate the delta through the class mixture,
    and take the expected entropy drop under the item's class posterior
    (reference ``coda/coda.py:235-281``).
    """
    H, C, _ = dirichlets.shape
    a_cc, b_cc = dirichlet_to_beta(dirichlets)     # (H, C)
    aT, bT = a_cc.T, b_cc.T                         # (C, H)
    pbest_before = compute_pbest(aT, bT, num_points=num_points)  # (C, H)
    mixture0 = (pi_hat[:, None] * pbest_before).sum(0)           # (H,)
    h_before = entropy2(mixture0)

    class_range = jnp.arange(C, dtype=jnp.int32)

    def item_eig(args):
        pred_n, pi_xi_n = args                      # (H,) int32, (C,)
        eq = (pred_n[None, :] == class_range[:, None]).astype(aT.dtype)  # (C, H)
        a_hyp = aT + update_weight * eq
        b_hyp = bT + update_weight * (1.0 - eq)
        pbest_hyp = compute_pbest(a_hyp, b_hyp, num_points=num_points)  # (C, H)
        # only row c changed, so the mixture delta is row c's contribution
        mix_new = mixture0[None, :] + pi_hat[:, None] * (pbest_hyp - pbest_before)
        h_after = entropy2(mix_new, axis=-1)        # (C,)
        return h_before - (pi_xi_n * h_after).sum()

    return lax.map(item_eig, (hard_preds, pi_hat_xi), batch_size=chunk)


def resolve_precision(name: str) -> lax.Precision:
    """CODAHyperparams.eig_precision -> lax.Precision (fails loudly)."""
    try:
        return {"highest": lax.Precision.HIGHEST,
                "high": lax.Precision.HIGH,
                "default": lax.Precision.DEFAULT}[name]
    except KeyError:
        raise ValueError(
            f"unknown eig_precision {name!r} (use highest/high/default)"
        ) from None


def _trapz_weights(num_points: int, dx, dtype) -> jnp.ndarray:
    """Uniform-grid trapezoid weights. Any constant scale cancels in the
    per-(n, c) normalization over models, but keep the exact rule anyway."""
    w = jnp.full((num_points,), dx, dtype).at[0].set(0.5 * dx)
    return w.at[-1].set(0.5 * dx)


def _bump_tables(a, b, x, dx, update_weight):
    """Per-model Beta grid tables for the two hypothetical-label variants.

    ``a``, ``b``: ``(..., H)`` diagonal-Beta parameters (leading axes are
    class rows when called on the full posterior, absent when called on the
    single updated row). The +1-count hypothetical update gives every model's
    Beta one of only TWO settings — "bumped" ``(a+w, b)`` when the model
    predicted the hypothesized class, else "unbumped" ``(a, b+w)`` — so the
    expensive transcendentals are O(|a| * G), independent of N.

    Returns ``(S0, dlogcdf, F_u, dF)`` with the grid axis last, where
    ``S0 = Σ_H logcdf_unbumped`` and the ``d*`` tables are bumped - unbumped.
    """
    def tab(aa, bb):
        logpdf = beta_log_pdf(x, aa[..., None], bb[..., None])   # (..., H, G)
        pdf = jnp.exp(logpdf)
        cdf = cumtrapz_uniform(pdf, dx, axis=-1)
        logcdf = jnp.log(jnp.clip(cdf, _EPS, None))
        # exp(logpdf - logcdf) <= pdf_max * 1/eps-floor; cap the exponent so
        # fp32 never overflows (binds only where the integrand is ~0 anyway)
        F = jnp.exp(jnp.clip(logpdf - logcdf, None, 85.0))
        return logcdf, F

    logcdf_u, F_u = tab(a, b + update_weight)        # model predicted != c
    logcdf_b, F_b = tab(a + update_weight, b)        # model predicted c
    return logcdf_u.sum(axis=-2), logcdf_b - logcdf_u, F_u, F_b - F_u


def _pbest_hyp_block(eq, S0, dlogcdf, F_u, dF, w_trapz,
                     precision=_PRECISION):
    """Hypothetical P(best) for a block of items: ``eq`` (B, C, H) -> (B, C, H).

    Three dense einsums over the model/grid axes — fp32 matmuls on the MXU
    instead of per-item lgamma/cumsum. The max-shift of S per (n, c) replaces
    the reference's ±80 clamp (both only affect integrand tails ~1e-35 below
    the peak; normalization over models cancels the shift exactly).
    """
    # S[n,c,g] = Σ_h logcdf of whichever variant model h takes at (n,c)
    S = S0[None] + jnp.einsum("bch,chg->bcg", eq, dlogcdf,
                              precision=precision)
    S = S - S.max(axis=-1, keepdims=True)            # underflow guard
    wE = w_trapz * jnp.exp(S)                        # (B, C, G)
    t_base = jnp.einsum("bcg,chg->bch", wE, F_u, precision=precision)
    t_diff = jnp.einsum("bcg,chg->bch", wE, dF, precision=precision)
    unnorm = t_base + eq * t_diff                    # (B, C, H)
    return unnorm / jnp.clip(unnorm.sum(-1, keepdims=True), _EPS, None)


def build_eig_cache(
    dirichlets: jnp.ndarray,   # (H, C, C)
    hard_preds: jnp.ndarray,   # (N, H) int32
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
    precision=_PRECISION,
    cache_dtype=jnp.float32,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full (pbest_rows, pbest_hyp) cache for the incremental EIG.

    One factored pass over all N items and C class rows — the same math as
    :func:`eig_scores_factored`'s table+einsum stage, run once at selector
    init (and never again: ``update_eig_cache`` refreshes single rows).
    ``cache_dtype`` is the STORAGE dtype of the (C, N, H) hypothetical
    tensor (all math stays fp32; bfloat16 storage halves the scoring
    pass's HBM stream — the eig_cache_dtype knob). The kernel computes
    (B, C, H) blocks; the single transpose to the carried (C, N, H) layout
    happens once here, never per round.
    """
    H, C, _ = dirichlets.shape
    N = hard_preds.shape[0]
    a_cc, b_cc = dirichlet_to_beta(dirichlets)
    aT, bT = a_cc.T, b_cc.T                          # (C, H)
    pbest_rows = compute_pbest(aT, bT, num_points=num_points)
    x = pbest_grid(num_points)
    dx = x[1] - x[0]
    w_trapz = _trapz_weights(num_points, dx, x.dtype)
    S0, dlogcdf, F_u, dF = _bump_tables(aT, bT, x, dx, update_weight)
    class_range = jnp.arange(C, dtype=jnp.int32)

    def blk(pred_b):                                 # (B, H) -> (B, C, H)
        eq = (pred_b[:, None, :] == class_range[None, :, None]).astype(x.dtype)
        out = _pbest_hyp_block(eq, S0, dlogcdf, F_u, dF, w_trapz, precision)
        return out.astype(cache_dtype)

    B = min(chunk, N)
    if B >= N:
        return pbest_rows, blk(hard_preds).transpose(1, 0, 2)
    # explicit (chunk, ·) blocks, padded remainder — same scheme as the
    # factored kernel's memory valve
    pad = (-N) % B
    hp_pad = jnp.pad(hard_preds, ((0, pad), (0, 0)))
    out = lax.map(blk, hp_pad.reshape((N + pad) // B, B, -1))
    return pbest_rows, out.reshape(N + pad, C, -1)[:N].transpose(1, 0, 2)


def update_eig_cache(
    dirichlets: jnp.ndarray,   # (H, C, C) — ALREADY holding the new label
    true_class: jnp.ndarray,   # scalar int
    hard_preds: jnp.ndarray,   # (N, H) int32
    pbest_rows: jnp.ndarray,   # (C, H)
    pbest_hyp: jnp.ndarray,    # (C, N, H)
    update_weight: float = 1.0,
    num_points: int = 256,
    precision=_PRECISION,
    beta_t=None,
    pbest: str = "quad",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Refresh class row ``true_class`` of the incremental-EIG cache.

    A labeling round touches only Dirichlet row ``true_class`` (reference
    semantics ``coda/coda.py:313-316``: ``dirichlets[:, y, :] += lr * onehot``),
    and both cache tensors factor per class row — the hypothetical P(best)
    normalization is per (item, row) over models — so every other row is
    bitwise carried over. Cost: O(N·H·G) einsums for one row instead of the
    full kernel's O(N·C·H·G), the C-fold saving that makes the EIG
    incremental. The (C, N, H) layout makes this a leading-index update —
    one contiguous (N, H) slice.
    """
    row_t, hyp_t = update_eig_cache_parts(
        dirichlets, true_class, hard_preds, update_weight, num_points,
        precision, beta_t=beta_t, pbest=pbest)
    return (
        pbest_rows.at[true_class].set(row_t),
        # store at the cache's own dtype (fp32 math, bf16 storage when the
        # eig_cache_dtype knob is on)
        pbest_hyp.at[true_class].set(hyp_t.astype(pbest_hyp.dtype)),
    )


def update_eig_cache_parts(
    dirichlets: jnp.ndarray,   # (H, C, C) — ALREADY holding the new label
    true_class: jnp.ndarray,   # scalar int
    hard_preds: jnp.ndarray,   # (N, H) int32
    update_weight: float = 1.0,
    num_points: int = 256,
    precision=_PRECISION,
    beta_t=None,
    pbest: str = "quad",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The refreshed class-row values WITHOUT writing them into the cache:
    ``(row_t (H,), hyp_t (N, H))``. The jnp path DUSes them in
    (:func:`update_eig_cache`); the fused pallas path hands ``hyp_t`` to
    the refresh+score kernel, which writes the row while scoring so the
    cache never round-trips through an XLA copy.

    ``beta_t``: optional precomputed ``(a_t, b_t)`` of the labeled row —
    the sparse posterior tier passes its O(H·K) compact-row reduction
    (``ops.sparse_rows.row_beta``) so the refresh never performs the
    dense (H, C, C) Beta pass; ``dirichlets`` may then be None.

    ``pbest='amortized'``: the hypothetical-row integral runs on the
    closed-form logistic-normal tables when the labeled row's min(a+b)
    clears :data:`_AMORTIZED_MIN_CONC` (the committed-contract gate),
    else falls back to the exact quadrature for that round. ``row_t`` —
    the CACHED current-posterior P(best), which feeds the best-model
    readout and the recorder's posterior digests — is always
    quadrature-exact."""
    if beta_t is not None:
        a_t, b_t = beta_t                            # (H,), (H,)
    else:
        a_cc, b_cc = dirichlet_to_beta(dirichlets)   # (H, C)
        a_t = jnp.take(a_cc, true_class, axis=1)     # (H,)
        b_t = jnp.take(b_cc, true_class, axis=1)
    eq_t = (hard_preds == true_class)                # (N, H) bool
    if pbest == "amortized":
        hyp_t = lax.cond(
            jnp.min(a_t + b_t) >= _AMORTIZED_MIN_CONC,
            lambda: _pbest_hyp_row_amortized(a_t, b_t, eq_t, update_weight,
                                             num_points, precision),
            lambda: _pbest_hyp_row(a_t, b_t, eq_t, update_weight,
                                   num_points, precision),
        )
    else:
        hyp_t = _pbest_hyp_row(a_t, b_t, eq_t, update_weight, num_points,
                               precision)
    row_t = compute_pbest(a_t, b_t, num_points=num_points)       # (H,)
    return row_t, hyp_t


def _pbest_hyp_from_tables(tables, eq_t, w_trapz, precision=_PRECISION):
    """The shared integral body of the hypothetical-row refresh: per-item
    exclusive log-cdf sum, max-shift, weighted integrand, normalization.
    ``tables`` is the ``(S0, dlogcdf, F_u, dF)`` 4-tuple — the ONE seam
    the quadrature (:func:`_bump_tables`) and amortized
    (:func:`_amortized_bump_tables`) flavors differ in, so an edit to the
    clamp/normalization choreography can never drift between them."""
    S0_t, dlogcdf_t, F_u_t, dF_t = tables
    eq = eq_t.astype(w_trapz.dtype)
    S = S0_t[None] + jnp.einsum("nh,hg->ng", eq, dlogcdf_t,
                                precision=precision)
    S = S - S.max(axis=-1, keepdims=True)
    wE = w_trapz * jnp.exp(S)                                    # (B, G)
    t_base = jnp.einsum("ng,hg->nh", wE, F_u_t, precision=precision)
    t_diff = jnp.einsum("ng,hg->nh", wE, dF_t, precision=precision)
    unnorm = t_base + eq * t_diff                                # (B, H)
    return unnorm / jnp.clip(unnorm.sum(-1, keepdims=True), _EPS, None)


def _pbest_hyp_row(a_t, b_t, eq_t, update_weight: float, num_points: int,
                   precision=_PRECISION):
    """Hypothetical P(best) for ONE class row over a batch of items.

    ``a_t``, ``b_t``: ``(H,)`` diagonal-Beta parameters of the row;
    ``eq_t``: ``(B, H)`` bool — did model h predict this class at item b.
    Returns ``(B, H)``. Temp footprint is O(H·G + B·G + B·H) — independent
    of C, so the incremental row refresh costs 1/C of the full factored
    pass, and the row-scanned EIG stays viable past the point where the
    (C, H, G) tables blow the ``_TABLES_MAX_BYTES`` budget.
    """
    x = pbest_grid(num_points)
    dx = x[1] - x[0]
    w_trapz = _trapz_weights(num_points, dx, x.dtype)
    tables = _bump_tables(a_t, b_t, x, dx, update_weight)
    return _pbest_hyp_from_tables(tables, eq_t, w_trapz, precision)


def _amortized_bump_tables(a, b, x, update_weight):
    """:func:`_bump_tables` on the amortized logistic-normal closed forms
    (arXiv 1905.12194's Laplace bridge, two-class reduction): pdf and cdf
    of each Beta variant come from ``ops.beta.logit_normal_log_pdf`` /
    ``log_cdf`` instead of lgamma grids plus the cumulative-trapezoid CDF
    construction. Same eps floor and exponent clamp; same ``(S0,
    dlogcdf, F_u, dF)`` return contract."""
    from coda_tpu.ops.beta import (
        beta_logit_normal_params,
        logit_normal_log_cdf,
        logit_normal_log_pdf,
    )

    def tab(aa, bb):
        mu, sigma = beta_logit_normal_params(aa, bb)
        logcdf = jnp.maximum(
            logit_normal_log_cdf(x, mu[..., None], sigma[..., None]),
            jnp.log(_EPS))
        logpdf = logit_normal_log_pdf(x, mu[..., None], sigma[..., None])
        F = jnp.exp(jnp.clip(logpdf - logcdf, None, 85.0))
        return logcdf, F

    logcdf_u, F_u = tab(a, b + update_weight)
    logcdf_b, F_b = tab(a + update_weight, b)
    return logcdf_u.sum(axis=-2), logcdf_b - logcdf_u, F_u, F_b - F_u


def _pbest_hyp_row_amortized(a_t, b_t, eq_t, update_weight: float,
                             num_points: int, precision=_PRECISION):
    """:func:`_pbest_hyp_row` on the amortized tables: the integral body
    is the SAME code (:func:`_pbest_hyp_from_tables`) — the two branches
    of the ``eig_pbest='amortized'`` cond differ only in where the
    per-model tables come from. Accuracy is governed by the bridge and
    improves with row concentration — the caller gates engagement on
    :data:`_AMORTIZED_MIN_CONC` (measured calibration at the constant)."""
    x = pbest_grid(num_points)
    dx = x[1] - x[0]
    w_trapz = _trapz_weights(num_points, dx, x.dtype)
    tables = _amortized_bump_tables(a_t, b_t, x, update_weight)
    return _pbest_hyp_from_tables(tables, eq_t, w_trapz, precision)


def compute_pbest_rows(aT, bT, num_points: int = 256,
                       row_chunk: int = 1) -> jnp.ndarray:
    """:func:`~coda_tpu.ops.pbest.compute_pbest` row by row: ``(C, H)`` from
    ``(C, H)`` Beta parameters with O(row_chunk·H·G) temps instead of the
    one-shot kernel's (C, H, G)."""
    return lax.map(
        lambda ab: compute_pbest(ab[0], ab[1], num_points=num_points),
        (aT, bT), batch_size=min(row_chunk, aT.shape[0]),
    )


def eig_scores_rowscan(
    dirichlets: jnp.ndarray,   # (H, C, C)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    hard_preds: jnp.ndarray,   # (N, H) int32 argmax predictions
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
    precision=_PRECISION,
    approx: bool = False,
) -> jnp.ndarray:
    """EIG of labeling each point, scanned over class rows. Returns (N,).

    Same integral as :func:`eig_scores_factored`, restructured for large
    C·H: the factored kernel materializes four (C, H, G) Beta tables — 512 MB
    each at the ImageNet-scale config (C=1000, H=500, G=256), which still
    fits, but growing linearly with the model pool (the C=1000 x H=2000+ HF
    zero-shot pool puts them past ``_TABLES_MAX_BYTES``). Here a ``lax.scan``
    visits one class row at a time — O(H·G) tables, O(chunk·G) integrand —
    and accumulates each row's expected-entropy contribution
    ``pi_hat_xi[:, c] * H(mixture | label c)`` into a running (N,) sum.
    FLOPs are identical to the factored kernel; only temp memory changes.
    """
    H, C, _ = dirichlets.shape
    N = hard_preds.shape[0]
    a_cc, b_cc = dirichlet_to_beta(dirichlets)
    aT, bT = a_cc.T, b_cc.T                          # (C, H)
    pbest_before = compute_pbest_rows(aT, bT, num_points=num_points)
    mixture0 = (pi_hat[:, None] * pbest_before).sum(0)           # (H,)
    h_before = entropy2(mixture0, approx=approx)

    class_range = jnp.arange(C, dtype=jnp.int32)
    # pad the (cheap, int32) item axis once so every class row sees the same
    # static (n_blocks, B, H) blocking
    B = min(chunk, N)
    pad = (-N) % B
    hp_blocks = jnp.pad(hard_preds, ((0, pad), (0, 0))).reshape(
        (N + pad) // B, B, -1
    )

    def class_row(acc, xs):
        c_idx, a_t, b_t, before_t, pi_c = xs

        def blk(pred_b):                             # (B, H) -> (B,)
            hyp = _pbest_hyp_row(a_t, b_t, pred_b == c_idx,
                                 update_weight, num_points, precision)
            mix = mixture0[None] + pi_c * (hyp - before_t[None])
            return entropy2(mix, axis=-1, approx=approx)

        h_after_c = lax.map(blk, hp_blocks).reshape(-1)[:N]
        return acc + pi_hat_xi[:, c_idx] * h_after_c, None

    acc, _ = lax.scan(
        class_row, jnp.zeros((N,), mixture0.dtype),
        (class_range, aT, bT, pbest_before, pi_hat),
    )
    return h_before - acc


def eig_scores_from_cache(
    pbest_rows: jnp.ndarray,   # (C, H)
    pbest_hyp: jnp.ndarray,    # (C, N, H)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    chunk: int = 256,
    approx: bool = False,
) -> jnp.ndarray:
    """EIG of labeling each point from the incremental cache. Returns (N,).

    With the hypothetical P(best) tensors cached, scoring a round is pure
    elementwise work + reductions — O(N·C·H) with no transcendental tables
    and no matmuls — evaluated in (C, B, H) blocks over the N axis so the
    mixture temp stays a fraction of the cache itself. Matches
    :func:`eig_scores_factored`'s tail exactly (same mixture-delta and
    entropy expressions). Blocks are dynamic slices of axis 1 (the layout
    keeps N second); a ragged final block re-covers the tail of the
    previous one and recomputes identical values for the overlap. The
    block start is clamped EXPLICITLY rather than left to
    dynamic_slice's own out-of-bounds clamping: under vmap the batched
    slice lowers to a gather, and out-of-bounds gather indices are
    implementation-defined on TPU — the suite's vmapped seeds read
    garbage in the ragged block (reproduced on a v5e, round 5: O(1)
    score errors exactly when chunk did not divide N; every
    N-divisible shape was bit-clean, which is why round 4's validation
    missed it).

    ``approx``: the ``eig_entropy='approx'`` lowering — every entropy in
    the chain (h_before AND the per-block h_after) runs through
    :func:`~coda_tpu.ops.masked.log2_approx`, matching the pallas
    kernels' approx flavor so the two backends stay interchangeable.
    """
    mixture0 = (pi_hat[:, None] * pbest_rows).sum(0)             # (H,)
    h_before = entropy2(mixture0, approx=approx)
    N = pbest_hyp.shape[1]
    B = min(chunk, N)

    def block(i, acc):
        start = jnp.minimum(i * B, N - B)
        hyp_b = lax.dynamic_slice_in_dim(pbest_hyp, start, B, axis=1)
        pi_xi_b = lax.dynamic_slice_in_dim(pi_hat_xi, start, B, axis=0)
        # upcast per block: storage may be bf16 (eig_cache_dtype); the
        # mixture/entropy math always runs fp32
        hyp_b = hyp_b.astype(mixture0.dtype)         # (C, B, H)
        mix = mixture0[None, None, :] + pi_hat[:, None, None] * (
            hyp_b - pbest_rows[:, None, :])
        h_after = entropy2(mix, axis=-1, approx=approx)  # (C, B)
        # reduce classes over axis 0 of (C, B) — the SAME reduction
        # structure as the pallas kernels' stacked class terms, so the two
        # backends agree to ~1 ulp instead of O(C·ulp) reduction-order
        # drift (the class terms nearly cancel against h_before)
        s = h_before - (pi_xi_b.T * h_after).sum(axis=0)  # (B,)
        return lax.dynamic_update_slice_in_dim(acc, s, start, axis=0)

    out0 = jnp.zeros((N,), mixture0.dtype)
    return lax.fori_loop(0, -(-N // B), block, out0)


def eig_scores_factored(
    dirichlets: jnp.ndarray,   # (H, C, C)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    hard_preds: jnp.ndarray,   # (N, H) int32 argmax predictions
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
    precision=_PRECISION,
    approx: bool = False,
) -> jnp.ndarray:
    """EIG of labeling each point, factored for the MXU. Returns (N,).

    Same integral as :func:`eig_scores`, reorganized around one observation:
    the hypothetical +1-count update for (item n, class c) gives every model's
    row-c Beta one of only TWO parameter settings — "bumped" ``(a+w, b)`` when
    the model predicted c at n, else "unbumped" ``(a, b+w)``. So all Beta
    pdf/cdf grids are precomputed once per step at O(C*H*G) transcendentals
    (independent of N), and the per-item integral

        P(h best | c) ∝ ∫ pdf_h(x) * Π_{h'≠h} cdf_{h'}(x) dx
                      = Σ_g w_g * exp(S_{n,c,g} - logcdf_{v,h,g}) * pdf_{v,h,g}

    with ``S = Σ_h logcdf`` becomes three einsums over the model axis —
    dense fp32 matmuls on the MXU instead of per-item lgamma/cumsum. The
    max-shift of S per (n, c) replaces the reference's ±80 clamp (both only
    affect integrand tails ~1e-35 below the peak; normalization over models
    cancels the shift exactly). Everything else — grid, eps floors, trapezoid
    rule, mixture delta — matches :func:`eig_scores` / reference
    ``coda/coda.py:235-281``.
    """
    H, C, _ = dirichlets.shape
    a_cc, b_cc = dirichlet_to_beta(dirichlets)       # (H, C)
    aT, bT = a_cc.T, b_cc.T                          # (C, H)
    pbest_before = compute_pbest(aT, bT, num_points=num_points)  # (C, H)
    mixture0 = (pi_hat[:, None] * pbest_before).sum(0)           # (H,)
    h_before = entropy2(mixture0, approx=approx)

    x = pbest_grid(num_points)                       # (G,)
    dx = x[1] - x[0]
    w_trapz = _trapz_weights(num_points, dx, x.dtype)
    S0, dlogcdf, F_u, dF = _bump_tables(aT, bT, x, dx, update_weight)

    class_range = jnp.arange(C, dtype=jnp.int32)

    def chunk_eig(args):
        pred_b, pi_xi_b = args                       # (B, H) int32, (B, C)
        eq = (pred_b[:, None, :] == class_range[None, :, None]).astype(x.dtype)
        pbest_hyp = _pbest_hyp_block(eq, S0, dlogcdf, F_u, dF, w_trapz,
                                     precision)
        # only row c changed; propagate the delta through the class mixture
        mix_new = mixture0[None, None] + pi_hat[None, :, None] * (
            pbest_hyp - pbest_before[None]
        )
        h_after = entropy2(mix_new, axis=-1, approx=approx)  # (B, C)
        return h_before - (pi_xi_b * h_after).sum(-1)

    N = hard_preds.shape[0]
    if chunk >= N:
        return chunk_eig((hard_preds, pi_hat_xi))

    # memory valve: scan over explicit (chunk, ·) blocks so each step is a
    # handful of dense (B,C,H)/(B,C,G) matmuls; pad the remainder
    pad = (-N) % chunk
    hp_pad = jnp.pad(hard_preds, ((0, pad), (0, 0)))
    px_pad = jnp.pad(pi_hat_xi, ((0, pad), (0, 0)))
    n_chunks = (N + pad) // chunk
    blocks = (
        hp_pad.reshape(n_chunks, chunk, -1),
        px_pad.reshape(n_chunks, chunk, -1),
    )
    out = lax.map(chunk_eig, blocks)                 # (n_chunks, chunk)
    return out.reshape(-1)[:N]


def _disagreement_mask(hard_preds: jnp.ndarray, C: int) -> jnp.ndarray:
    """Points where at least one model disagrees with the majority vote.

    The reference uses ``torch.mode`` over models (``coda/coda.py:215-219``);
    here the majority is the argmax of one-hot vote counts (identical choice:
    both pick the smallest modal class). Blocked over items so the (B, H, C)
    one-hot temp stays ~64 MB even at ImageNet scale (H=500, C=1000).
    """
    N, H = hard_preds.shape

    def item_majority(pred_n):                       # (H,) -> scalar
        votes = jax.nn.one_hot(pred_n, C, dtype=jnp.int32).sum(axis=0)
        return jnp.argmax(votes)

    B = max(1, min(N, (64 << 20) // max(1, 4 * H * C)))
    maj = lax.map(item_majority, hard_preds, batch_size=B)              # (N,)
    return (hard_preds != maj[:, None]).any(axis=-1)


def make_coda(
    preds: jnp.ndarray,
    hp: Optional[CODAHyperparams] = None,
    name: str = "coda",
    prior=None,
) -> Selector:
    """Build the CODA selector closed over a prediction tensor.

    ``prior``: an optional :class:`~coda_tpu.selectors.surrogate.
    PriorStats` — the merged cross-session pool the init seeds the
    surrogate fit from (requires ``hp.surrogate_prior='pool'``; the
    engine/CLI path passes it here, the serve path seeds per-admission
    at the bucket instead so a live pool can keep evolving without
    retracing)."""
    hp = hp or CODAHyperparams()
    H, N, C = preds.shape
    prior_strength = 1.0 - hp.alpha
    update_strength = hp.learning_rate

    if hp.pi_update not in ("auto", "delta", "exact"):
        raise ValueError(f"unknown pi_update {hp.pi_update!r} "
                         "(use 'auto', 'delta' or 'exact')")
    # resolve_pi_update names the concrete lowering; this just wires it
    pi_update = resolve_pi_update(hp, N)
    pi_gather = None
    if pi_update == "delta_pallas":
        from coda_tpu.ops.pallas_gather import gather_rows_sum_prepped

        def pi_gather(flat, s, _N=N):
            return gather_rows_sum_prepped(flat, s, _N)
    # statics (functions of preds only)
    hard_preds = preds.argmax(-1).T.astype(jnp.int32)     # (N, H)
    disagree = _disagreement_mask(hard_preds, C)          # (N,)
    ens_hard = ensemble_preds(preds).argmax(-1)           # consensus pseudo-labels
    soft_conf = create_confusion_matrices(ens_hard, preds, mode="soft")
    dirichlets0 = hp.multiplier * initialize_dirichlets(
        soft_conf, prior_strength, hp.disable_diag_prior
    )
    if hp.q == "uncertainty":
        from coda_tpu.selectors.uncertainty import uncertainty_scores
        unc_scores = uncertainty_scores(preds)            # (N,)

    use_prefilter = hp.q == "eig" and hp.prefilter_n and hp.prefilter_n < N
    eig_mode = resolve_eig_mode(hp, H, N, C)
    eig_precision = resolve_precision(hp.eig_precision)
    from coda_tpu.ops.sparse_rows import parse_posterior

    sparse_k = parse_posterior(hp.posterior)  # None = dense
    if sparse_k is not None and eig_mode != "incremental":
        raise ValueError(
            "posterior='sparse:K' requires the incremental EIG tier "
            f"(this config resolved to eig_mode={eig_mode!r}): the dense "
            "recompute tiers re-read the full posterior every round, so a "
            "sparse carry would be densified right back — shrink the "
            "config into the incremental budget or use posterior='dense'"
        )
    if hp.eig_pbest not in ("quad", "amortized"):
        raise ValueError(f"unknown eig_pbest {hp.eig_pbest!r} "
                         "(use 'quad' or 'amortized')")
    if hp.eig_pbest == "amortized" and eig_mode != "incremental":
        raise ValueError(
            "eig_pbest='amortized' replaces the incremental row-refresh "
            f"quadrature; this config resolved to eig_mode={eig_mode!r} "
            "where it would silently not apply"
        )
    if eig_mode == "direct" and hp.eig_precision != "highest":
        raise ValueError(
            "eig_mode='direct' is the reference-choreography cross-check "
            "kernel and always runs at HIGHEST precision; "
            f"eig_precision={hp.eig_precision!r} would silently not apply"
        )
    if hp.eig_entropy not in ("exact", "approx"):
        raise ValueError(f"unknown eig_entropy {hp.eig_entropy!r} "
                         "(use 'exact' or 'approx')")
    approx_entropy = hp.eig_entropy == "approx"
    if eig_mode == "direct" and approx_entropy:
        raise ValueError(
            "eig_mode='direct' is the reference-choreography cross-check "
            "kernel and always uses the exact entropy lowering; "
            "eig_entropy='approx' would silently not apply"
        )
    # the direct kernel takes no precision/entropy parameters (guards above)
    eig_kwargs = ({} if eig_mode == "direct"
                  else {"precision": eig_precision,
                        "approx": approx_entropy})
    incremental = eig_mode == "incremental"
    # (C, H, N) layout for the delta pi-hat gather, built OUTSIDE the scan
    # step so it is a loop constant (materialized once per experiment), not
    # re-transposed every round; only the incremental tier reads it. The
    # pallas DMA-gather consumes the flat (C·H, 1, Np) variant instead
    # (prep_gather_layout — Mosaic cannot slice single sublane rows out of
    # the tiled 3-D buffer); ``preds_by_class``'s layout is owned by
    # whichever gather the update uses
    preds_by_class = None
    if incremental and pi_update.startswith("delta"):
        preds_by_class = jnp.transpose(preds, (2, 0, 1))
        if pi_gather is not None:
            from coda_tpu.ops.pallas_gather import prep_gather_layout

            preds_by_class = prep_gather_layout(preds_by_class)
    if hp.eig_cache_dtype not in ("float32", "bfloat16"):
        raise ValueError(f"unknown eig_cache_dtype {hp.eig_cache_dtype!r} "
                         "(use 'float32' or 'bfloat16')")
    cache_dtype = jnp.dtype(hp.eig_cache_dtype)
    if hp.eig_backend not in ("auto", "jnp", "pallas"):
        raise ValueError(f"unknown eig_backend {hp.eig_backend!r} "
                         "(use 'auto', 'jnp' or 'pallas')")
    eig_backend = resolve_eig_backend(hp, eig_mode, N)
    shard_mesh = None
    if eig_backend == "pallas":
        if not incremental:
            raise ValueError(
                "eig_backend='pallas' accelerates the incremental scoring "
                f"pass, but this config resolved to eig_mode={eig_mode!r} — "
                "it would silently never run; use the jnp backend here"
            )
        # a DECLARED data-axis sharding routes the kernels through
        # shard_map (raises on unsupported specs when pallas is explicit)
        shard_mesh = shard_mesh_for(hp, N)
        # NOTE: this guard only sees a CONCRETE array's sharding. Under the
        # preds-as-argument jit pattern preds is a tracer here and the
        # sharding is unknowable at trace time — library users combining a
        # sharded traced tensor with the pallas backend must declare the
        # mesh via hp.shard_spec (the CLI's --mesh does this).
        sharding = getattr(preds, "sharding", None)
        if shard_mesh is None and sharding is not None and getattr(
                sharding, "num_devices", 1) > 1 and not getattr(
                sharding, "is_fully_replicated", False):
            raise ValueError(
                "eig_backend='pallas' on a sharded (H, N, C) tensor needs "
                "the mesh DECLARED via shard_spec: pallas_call is an opaque "
                "custom call GSPMD cannot partition, so an undeclared "
                "sharded tensor would be all-gathered per device"
            )

    if hp.eig_refresh not in ("precomputed", "fused"):
        raise ValueError(f"unknown eig_refresh {hp.eig_refresh!r} "
                         "(use 'precomputed' or 'fused')")
    fused_refresh = hp.eig_refresh == "fused"
    if fused_refresh and (eig_backend != "pallas" or shard_mesh is not None
                          or hp.n_parallel > 1):
        raise ValueError(
            "eig_refresh='fused' computes the replacement row inside the "
            "single-chip pallas scoring kernel; it requires the pallas "
            "backend and supports neither shard_spec nor vmapped batches "
            f"(got backend={eig_backend!r}, shard_spec={hp.shard_spec!r}, "
            f"n_parallel={hp.n_parallel})"
        )
    if hp.eig_pbest == "amortized" and (eig_backend != "jnp"
                                        or fused_refresh):
        raise ValueError(
            "eig_pbest='amortized' runs the row refresh through the jnp "
            "logistic-normal tables; the pallas kernels compute their own "
            f"Beta tables (got backend={eig_backend!r}, "
            f"eig_refresh={hp.eig_refresh!r}) — it would silently not "
            "apply"
        )
    from coda_tpu.selectors.surrogate import parse_prior, parse_scorer

    scorer_k = parse_scorer(hp.eig_scorer)  # None = exact
    prior_on = parse_prior(hp.surrogate_prior)
    if prior_on and scorer_k is None:
        raise ValueError(
            "surrogate_prior='pool' warm-starts the carried surrogate "
            "fit; eig_scorer='exact' carries none — it would silently "
            "not apply (use eig_scorer='surrogate:k' or "
            "surrogate_prior='off')"
        )
    if prior is not None and not prior_on:
        raise ValueError(
            "a prior was passed but surrogate_prior='off' — seeding "
            "under the off knob would break the off-config bitwise pin; "
            "set surrogate_prior='pool'"
        )
    if scorer_k is not None and eig_mode != "incremental":
        raise ValueError(
            "eig_scorer='surrogate:k' amortizes the incremental tier's "
            f"scoring pass; this config resolved to eig_mode={eig_mode!r} "
            "where the shortlist refresh has no carried cache to read — "
            "shrink the config into the incremental budget or use "
            "eig_scorer='exact'"
        )
    if scorer_k is not None and eig_backend == "pallas":
        raise ValueError(
            "eig_scorer='surrogate:k' scores through the jnp shortlist "
            "gather; the pallas kernels score the full pool in one fused "
            "pass and cannot take the hybrid vector (auto demotes to jnp "
            "under the knob) — drop eig_backend='pallas' or the surrogate"
        )

    def _score_cache(rows, hyp, pi, pi_xi):
        """The incremental scoring pass, backend-dispatched.

        The whole body sits in one ``named_scope`` so the N·C·H scoring
        chain is attributable as a block in a ``--profile-dir`` device
        trace — the region the telemetry layer's host spans bracket."""
        with jax.named_scope("eig/score_cache"):
            return _score_cache_impl(rows, hyp, pi, pi_xi)

    def _score_cache_impl(rows, hyp, pi, pi_xi):
        if eig_backend == "pallas":
            if shard_mesh is not None:
                from coda_tpu.ops.pallas_eig import (
                    eig_scores_cache_pallas_sharded,
                )

                return eig_scores_cache_pallas_sharded(
                    rows, hyp, pi, pi_xi, mesh=shard_mesh,
                    block=hp.eig_chunk, approx=approx_entropy)
            from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas

            return eig_scores_cache_pallas(rows, hyp, pi, pi_xi,
                                           block=hp.eig_chunk,
                                           approx=approx_entropy)
        return eig_scores_from_cache(rows, hyp, pi, pi_xi,
                                     chunk=hp.eig_chunk,
                                     approx=approx_entropy)

    def _exact_score_rows(rows, hyp, pi, pi_xi, sel):
        """The exact chain on a row subset (the surrogate's shortlist
        refresh): one ``lax.map`` over the selected rows, each reading
        its (C, H) cache column by dynamic slice — O(m·C·H) cache bytes
        streamed ONCE, with no materialized (C, m, H) gather copy (an
        ``axis=1`` take at the imagenet preset copied 136 MB per round
        and halved the measured speedup). Per-row math is exactly
        ``eig_scores_from_cache``'s block body — same mixture delta,
        same class-axis reduction structure, same fp32 upcast — so a
        selected row's score is bitwise the full pass's value for that
        row (pinned by the k >= N parity test)."""
        mixture0 = (pi[:, None] * rows).sum(0)               # (H,)
        h_before = entropy2(mixture0, approx=approx_entropy)

        def one(i):
            hyp_i = lax.dynamic_slice_in_dim(hyp, i, 1, axis=1)  # (C,1,H)
            hyp_i = hyp_i.astype(mixture0.dtype)
            mix = mixture0[None, None, :] + pi[:, None, None] * (
                hyp_i - rows[:, None, :])
            h_after = entropy2(mix, axis=-1,
                               approx=approx_entropy)        # (C, 1)
            pi_i = lax.dynamic_slice_in_dim(pi_xi, i, 1, axis=0)  # (1, C)
            return (h_before - (pi_i.T * h_after).sum(axis=0))[0]

        return lax.map(one, sel)

    def _next_cand(unlabeled_new):
        """The NEXT select's candidate mask (same rule as _candidates,
        on the post-update unlabeled set) — what the surrogate shortlist
        must cover."""
        cand0 = disagree & unlabeled_new
        return jnp.where(cand0.any(), cand0, unlabeled_new)

    def _surrogate_scores(fit, prev_scores, unlabeled_new, rows, hyp, pi,
                          pi_xi, true_classes, a_t, b_t):
        """The contract-gated scoring pass replacing ``_score_cache``
        (jnp incremental path only — validated above). ``true_classes``
        (q,), ``a_t``/``b_t`` (q, H): the labeled rows' Beta parameters
        the cache refresh already extracted."""
        from coda_tpu.selectors import surrogate as sg

        fit = sg.refresh_class_feats(fit, true_classes, a_t, b_t)
        feats = sg.build_features(prev_scores, pi_xi, pi, fit.cls_feats,
                                  rows, hyp, hard_preds, true_classes)
        with jax.named_scope("eig/surrogate"):
            return sg.surrogate_score_round(
                fit, feats, _next_cand(unlabeled_new), scorer_k,
                lambda sel: _exact_score_rows(rows, hyp, pi, pi_xi, sel),
                lambda: _score_cache(rows, hyp, pi, pi_xi))

    def init(key):
        del key  # CODA's initialization is deterministic
        unnorm = pi_unnorm(dirichlets0, preds)
        pi_xi, pi = _normalize_pi(unnorm)
        rows, hyp = (
            build_eig_cache(dirichlets0, hard_preds,
                            num_points=hp.num_points, chunk=hp.eig_chunk,
                            precision=eig_precision,
                            cache_dtype=cache_dtype)
            if incremental else (None, None)
        )
        if sparse_k is not None:
            from coda_tpu.ops.sparse_rows import sparsify

            # everything above — pi-hat, the EIG cache — is built EXACTLY
            # from the dense prior (a one-time trace-level cost); only the
            # carried representation is compressed
            sparse0, dense0 = sparsify(dirichlets0, sparse_k), None
        else:
            sparse0, dense0 = None, dirichlets0
        fit0 = None
        if scorer_k is not None:
            from coda_tpu.selectors.surrogate import init_fit

            # init is always exact (round 0 of the warmup); the fit
            # starts zeroed, seeded with the prior's class summaries
            a0, b0 = dirichlet_to_beta(dirichlets0)
            fit0 = init_fit(a0.T, b0.T)
            if prior_on and prior is not None:
                from coda_tpu.selectors.surrogate import seed_fit

                # the pool only contributes the regression sufficient
                # statistics (A, b, n) and warmup credit; the class
                # summaries above stay this session's own
                fit0 = seed_fit(fit0, prior)
        return CODAState(
            dirichlets=dense0,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=jnp.ones((N,), dtype=bool),
            pbest_rows=rows,
            pbest_hyp=hyp,
            pi_xi_unnorm=unnorm if incremental else None,
            eig_scores_cached=(_score_cache(rows, hyp, pi, pi_xi)
                               if incremental else None),
            sparse=sparse0,
            surrogate=fit0,
        )

    def _candidates(state: CODAState) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(candidate mask, may_subsample).

        Reference order (``coda/coda.py:239,215-224``): the disagreement
        filter runs first; only a *non-empty* filtered set is subsampled.
        The all-agreement fallback to the full unlabeled set is never
        subsampled.
        """
        cand0 = disagree & state.unlabeled
        empty = ~cand0.any()
        cand = jnp.where(empty, state.unlabeled, cand0)
        return cand, ~empty

    if eig_mode in ("factored", "incremental"):
        eig_fn = eig_scores_factored
    elif eig_mode == "rowscan":
        eig_fn = eig_scores_rowscan
    elif eig_mode == "direct":
        eig_fn = eig_scores
    else:
        raise ValueError(f"unknown eig_mode {eig_mode!r}")

    def _eig_select_full(state: CODAState, cand, k_tie) -> SelectResult:
        """Score every point, mask to the candidate set at argmax time."""
        if incremental:
            # score-ahead: init/update already computed these scores for
            # the carried posterior (see CODAState.eig_scores_cached)
            scores = state.eig_scores_cached
        else:
            with jax.named_scope("eig/scores"):
                scores = eig_fn(
                    state.dirichlets, state.pi_hat, state.pi_hat_xi,
                    hard_preds, num_points=hp.num_points,
                    chunk=hp.eig_chunk, **eig_kwargs,
                )
        idx, n_ties = masked_argmax_tiebreak(k_tie, scores, cand,
                                             rtol=_TIE_RTOL, atol=_TIE_ATOL)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=n_ties > 1,
            scores=jnp.where(cand, scores, -jnp.inf),
        )

    def _eig_select_prefiltered(state: CODAState, cand, k_sub,
                                k_tie) -> SelectResult:
        """Fixed-budget random subsample of the candidates (the speed valve:
        EIG runs on prefilter_n points, not N). top-k of masked uniforms = a
        uniform random subset; when fewer than prefilter_n candidates exist,
        the invalid (masked) slots are excluded again at argmax time, so the
        pool is exactly the candidate set and no subsampling happened."""
        u = jnp.where(cand, jax.random.uniform(k_sub, (N,)), -1.0)
        _, cand_idx = jax.lax.top_k(u, hp.prefilter_n)   # (K,)
        valid = u[cand_idx] >= 0.0
        scores_sub = eig_fn(
            state.dirichlets, state.pi_hat, state.pi_hat_xi[cand_idx],
            hard_preds[cand_idx],
            num_points=hp.num_points,
            chunk=min(hp.eig_chunk, hp.prefilter_n), **eig_kwargs,
        )
        local, n_ties = masked_argmax_tiebreak(
            k_tie, scores_sub, valid, rtol=_TIE_RTOL, atol=_TIE_ATOL
        )
        subsampled = cand.sum() > hp.prefilter_n
        # scatter the subset's scores back to full N so the recorder trace
        # has one fixed-shape score vector in both lax.cond branches
        scores_full = jnp.full((N,), -jnp.inf, jnp.float32).at[cand_idx].set(
            jnp.where(valid, scores_sub, -jnp.inf))
        return SelectResult(
            idx=cand_idx[local].astype(jnp.int32),
            prob=scores_sub[local],
            stochastic=(n_ties > 1) | subsampled,
            scores=scores_full,
        )

    def select(state: CODAState, key) -> SelectResult:
        k_sub, k_tie = jax.random.split(key)
        cand, may_subsample = _candidates(state)

        if hp.q == "eig" and not use_prefilter:
            return _eig_select_full(state, cand, k_tie)
        if use_prefilter:
            # only a non-empty *disagreement* set may be subsampled; the
            # all-agreement fallback scores every unlabeled point, exactly
            # like the reference (`_prefilter(...) or self.unlabeled_idxs`,
            # coda/coda.py:239 — the fallback never passes through the
            # random.sample branch)
            return lax.cond(
                may_subsample,
                lambda s: _eig_select_prefiltered(s, cand, k_sub, k_tie),
                lambda s: _eig_select_full(s, cand, k_tie),
                state,
            )

        # the ablation acquisitions (cheap scores) subsample via the mask
        # *before* scores are computed, so the iid probability is 1/|pool|
        # of the subsampled pool (reference computes cand first, then q_vals)
        subsampled = jnp.asarray(False)
        if hp.prefilter_n and hp.prefilter_n < N:
            u = jnp.where(cand, jax.random.uniform(k_sub, (N,)), -1.0)
            kth = jnp.sort(u)[N - hp.prefilter_n]
            take = may_subsample & (cand.sum() > hp.prefilter_n)
            cand = jnp.where(take, cand & (u >= kth), cand)
            subsampled = take

        if hp.q == "iid":
            scores = jnp.full((N,), 1.0) / jnp.clip(cand.sum(), 1, None)
        elif hp.q == "uncertainty":
            scores = unc_scores
        else:
            raise NotImplementedError(hp.q)

        idx, n_ties = masked_argmax_tiebreak(k_tie, scores, cand,
                                             rtol=_TIE_RTOL, atol=_TIE_ATOL)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=(n_ties > 1) | subsampled,
            scores=jnp.where(cand, scores, -jnp.inf),
        )

    def _greedy_overlap_topq(state: CODAState, scores, cand, k_tie,
                             q: int) -> SelectResult:
        """Greedy top-q EIG with an information-overlap penalty, as a
        cached re-rank of ONE scoring pass.

        After each pick, remaining candidates are discounted by how much
        their hypothetical-label effect concentrates on the same class
        rows / P(best) mass as the points already taken: each candidate
        n carries two unit feature vectors — its class-row hit
        distribution ``pi_hat_xi[n]`` (which Dirichlet rows its label
        would touch, in expectation) and, on the incremental tier, its
        expected |ΔP(best)| profile over models read straight from the
        carried ``pbest_hyp`` cache — and the penalty is the running max
        cosine overlap with the picked set. Scores multiply by
        ``(1 - penalty)``, so a fully-redundant point re-ranks toward
        zero while independent points keep their raw EIG. The re-rank
        runs on the top-M score pool (M = max(32, 8q)) — the greedy
        argmax only ever reaches deep into the pool when most of it is
        redundant, and M bounds that reach statically.
        """
        M = min(N, max(32, 8 * q))
        # the pool: candidates by score; unlabeled non-candidates at a
        # huge-but-finite sentinel so a candidate set smaller than q
        # falls back to unlabeled points, never to labeled ones
        pool_scores = jnp.where(
            cand, scores,
            jnp.where(state.unlabeled, -1e30, -jnp.inf))
        top_scores, pool = lax.top_k(pool_scores, M)       # (M,)
        valid = top_scores > -1e29                          # real candidates
        pi_xi_p = state.pi_hat_xi[pool]                     # (M, C)
        U = pi_xi_p / jnp.clip(
            jnp.linalg.norm(pi_xi_p, axis=1, keepdims=True), 1e-12, None)
        feats = [U]
        if incremental:
            # expected P(best)-mass displacement per model, off the cache:
            # E[n, h] ≈ Σ_c pi_xi[n, c] · pi_hat[c] · |hyp[c, n, h] − rows[c, h]|
            # restricted to each candidate's top-kc likeliest labels —
            # the weight pi_xi[n, c]·pi_hat[c] concentrates the expected
            # displacement there, and the restriction turns an O(C·M·H)
            # read of the cache (84 ms/round at the C=1000 preset,
            # measured — it alone would eat the batching win) into an
            # O(kc·M·H) gather
            kc = min(8, C)
            w_full = pi_xi_p * state.pi_hat[None, :]        # (M, C)
            wv, ci = lax.top_k(w_full, kc)                  # (M, kc)
            hyp_sel = state.pbest_hyp[ci, pool[:, None], :].astype(
                jnp.float32)                                # (M, kc, H)
            rows_sel = state.pbest_rows[ci]                 # (M, kc, H)
            E = jnp.einsum("mk,mkh->mh", wv,
                           jnp.abs(hyp_sel - rows_sel))     # (M, H)
            feats.append(E / jnp.clip(
                jnp.linalg.norm(E, axis=1, keepdims=True), 1e-12, None))
        F = jnp.concatenate(feats, axis=1) / jnp.sqrt(float(len(feats)))
        # (M, C[+H]); <F_i, F_j> = mean of the per-feature cosines

        keys = jax.random.split(k_tie, q)

        def pick(carry, kt):
            pen, taken = carry
            eff = top_scores * (1.0 - pen)
            avail = valid & ~taken
            fb = state.unlabeled[pool] & ~taken
            use = jnp.where(avail.any(), avail, fb)
            loc, n_ties = masked_argmax_tiebreak(
                kt, jnp.where(avail, eff, -jnp.inf), use,
                rtol=_TIE_RTOL, atol=_TIE_ATOL)
            overlap = jnp.clip(F @ F[loc], 0.0, 1.0)        # (M,)
            return ((jnp.maximum(pen, overlap), taken.at[loc].set(True)),
                    (loc, n_ties > 1))

        (_, _), (locs, ties) = lax.scan(
            pick, (jnp.zeros((M,)), jnp.zeros((M,), bool)), keys)
        return SelectResult(
            idx=pool[locs].astype(jnp.int32),
            prob=jnp.where(valid[locs], top_scores[locs],
                           -jnp.inf).astype(jnp.float32),
            stochastic=ties.any(),
            scores=jnp.where(cand, scores, -jnp.inf),
        )

    def select_q(state: CODAState, key, q: int) -> SelectResult:
        """q-wide acquisition for the full-pool EIG: the one scoring pass
        the round already paid (score-ahead on the incremental tier),
        then the greedy overlap-penalized re-rank. Key choreography
        mirrors ``select`` (split; the sub key is unused here, exactly as
        in the unprefiltered q=1 path)."""
        k_sub, k_tie = jax.random.split(key)
        del k_sub
        cand, _ = _candidates(state)
        if incremental:
            scores = state.eig_scores_cached
        else:
            with jax.named_scope("eig/scores"):
                scores = eig_fn(
                    state.dirichlets, state.pi_hat, state.pi_hat_xi,
                    hard_preds, num_points=hp.num_points,
                    chunk=hp.eig_chunk, **eig_kwargs,
                )
        return _greedy_overlap_topq(state, scores, cand, k_tie, q)

    def _update_q_impl(state: CODAState, idxs, true_classes, probs,
                       ws=None) -> CODAState:
        """All q oracle answers as ONE fused update: a single multi-row
        posterior scatter (``ops.sparse_rows.scatter_rows`` / one dense
        scatter-add), ONE batched pi-hat column refresh, ONE batched
        multi-row EIG-cache refresh from the FINAL posterior (duplicate
        class rows recompute identical values — the row refresh depends
        only on the end state), and one scoring pass — per-round cost
        approaches 1 scoring pass + 1 update instead of q of each.

        ``ws`` ((q,) traced, optional) are per-answer reliability weights
        scaling each answer's posterior increment — the crowd-oracle
        path. ``ws=None`` is a static branch reproducing the unweighted
        jaxpr exactly (the exact pi / cache refreshes read the FINAL
        posterior, so they are weight-automatic)."""
        del probs
        preds_at = hard_preds[idxs]                     # (q, H)
        if sparse_k is not None:
            from coda_tpu.ops.sparse_rows import (
                densify_row,
                row_beta,
                scatter_rows,
            )

            sparse = scatter_rows(state.sparse, true_classes, preds_at,
                                  update_strength, weights=ws)
            dirichlets = None
        else:
            sparse = None
            onehot = jax.nn.one_hot(preds_at, C, dtype=preds.dtype)
            # q scalar-index row adds, NOT one fancy-index scatter: a
            # dynamic-index DUS updates the scan-carried (H, C, C) tensor
            # in place, while an index-ARRAY scatter makes XLA copy the
            # whole posterior every round (the 512 MB cache copy below,
            # same story). Sequential adds also sequence duplicate rows
            # exactly.
            dirichlets = state.dirichlets
            for j in range(preds_at.shape[0]):
                eff_j = (update_strength if ws is None
                         else update_strength * ws[j])
                dirichlets = dirichlets.at[:, true_classes[j], :].add(
                    eff_j * onehot[j])
        if incremental:
            if pi_update.startswith("delta"):
                if pi_gather is None:
                    from coda_tpu.ops.pallas_gather import (
                        gather_rows_sum_xla as _gfn,
                    )
                else:
                    _gfn = pi_gather
                gathered = jax.vmap(
                    _gfn, in_axes=(None, 0))(preds_by_class, preds_at)
                deltas = (update_strength * gathered if ws is None
                          else (update_strength * ws)[:, None] * gathered)
                unnorm = state.pi_xi_unnorm.at[:, true_classes].add(
                    deltas.T)
                pi_xi, pi = _normalize_pi(unnorm)
            else:
                # exact column refresh from the FINAL posterior rows:
                # duplicates produce identical columns, so the scatter's
                # winner is immaterial
                if sparse_k is not None:
                    rows_d = jax.vmap(
                        lambda c: densify_row(sparse, c))(true_classes)
                else:
                    rows_d = jnp.moveaxis(
                        jnp.take(dirichlets, true_classes, axis=1), 1, 0)
                cols = jnp.einsum("qhs,hns->qn", rows_d, preds,
                                  precision=_pi_precision(preds))
                unnorm = state.pi_xi_unnorm.at[:, true_classes].set(cols.T)
                pi_xi, pi = _normalize_pi(unnorm)
            # ONE batched multi-row cache refresh (the q=1 path's
            # update_eig_cache_parts, vmapped over the touched rows)
            if sparse_k is not None:
                a_t, b_t = jax.vmap(
                    lambda c: row_beta(sparse, c))(true_classes)  # (q, H)
            else:
                a_cc, b_cc = dirichlet_to_beta(dirichlets)
                a_t = a_cc.T[true_classes]                  # (q, H)
                b_t = b_cc.T[true_classes]
            eq = hard_preds[None, :, :] == true_classes[:, None, None]

            def _hyp_row(a_r, b_r, eq_r):
                if hp.eig_pbest == "amortized":
                    # under vmap the cond lowers to a select (both
                    # branches run) — the gate still decides the VALUE,
                    # so the score contract holds; batched rounds pay
                    # both table flavors for the touched rows
                    return lax.cond(
                        jnp.min(a_r + b_r) >= _AMORTIZED_MIN_CONC,
                        lambda: _pbest_hyp_row_amortized(
                            a_r, b_r, eq_r, 1.0, hp.num_points,
                            eig_precision),
                        lambda: _pbest_hyp_row(
                            a_r, b_r, eq_r, 1.0, hp.num_points,
                            eig_precision),
                    )
                return _pbest_hyp_row(a_r, b_r, eq_r, 1.0, hp.num_points,
                                      eig_precision)

            hyp_ts = jax.vmap(_hyp_row)(a_t, b_t, eq)       # (q, N, H)
            row_ts = compute_pbest(a_t, b_t,
                                   num_points=hp.num_points)  # (q, H)
            # write back as q scalar-index DUSes (in-place on the scan
            # carry), NOT one `.at[index_array].set` scatter — the
            # scatter lowering copies the whole (C, N, H) cache (512 MB
            # at the imagenet preset, ~half the batched round's wall
            # when measured). Duplicate rows: later writes win, and
            # their values are identical (refreshed from the same final
            # posterior).
            rows, hyp = state.pbest_rows, state.pbest_hyp
            for j in range(row_ts.shape[0]):
                rows = rows.at[true_classes[j]].set(row_ts[j])
                hyp = hyp.at[true_classes[j]].set(
                    hyp_ts[j].astype(hyp.dtype))
            unlabeled_new = state.unlabeled.at[idxs].set(False)
            if scorer_k is not None:
                scores, fit = _surrogate_scores(
                    state.surrogate, state.eig_scores_cached,
                    unlabeled_new, rows, hyp, pi, pi_xi,
                    true_classes, a_t, b_t)
            else:
                scores, fit = _score_cache(rows, hyp, pi, pi_xi), None
        else:
            pi_xi, pi = update_pi_hat(dirichlets, preds)
            unnorm = rows = hyp = scores = fit = None
            unlabeled_new = state.unlabeled.at[idxs].set(False)
        return CODAState(
            dirichlets=dirichlets,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=unlabeled_new,
            pbest_rows=rows,
            pbest_hyp=hyp,
            pi_xi_unnorm=unnorm,
            eig_scores_cached=scores,
            sparse=sparse,
            surrogate=fit,
        )

    def update_q(state: CODAState, idxs, true_classes, probs) -> CODAState:
        return _update_q_impl(state, idxs, true_classes, probs)

    def update_qw(state: CODAState, idxs, true_classes, probs,
                  ws) -> CODAState:
        """The reliability-weighted fused update (crowd oracle): answer
        j's increment is scaled by ``ws[j]``. w=1 everywhere is bitwise
        ``update_q``; w=0 answers are structural no-ops."""
        return _update_q_impl(state, idxs, true_classes, probs, ws=ws)

    def _update_impl(state: CODAState, idx, true_class, prob,
                     w=None) -> CODAState:
        del prob
        # w (optional traced scalar) scales the posterior increment —
        # effective strength = learning_rate * w. None is a static branch
        # producing the unweighted jaxpr (eff stays the Python float).
        eff = update_strength if w is None else update_strength * w
        pred_at = hard_preds[idx]                       # (H,) int32
        if sparse_k is not None:
            from coda_tpu.ops.sparse_rows import (
                densify_row,
                row_beta,
                scatter_row,
            )

            # one-row sparse scatter (smallest-entry eviction into the
            # residual) instead of pushing the (H, C, C) tensor through
            # the carry; the labeled row's Beta parameters come from the
            # O(H*K) compact reduction, not a dense (H, C, C) pass
            sparse = scatter_row(state.sparse, true_class, pred_at,
                                 update_strength, weight=w)
            dirichlets = None
            beta_t = row_beta(sparse, true_class)
        else:
            sparse = None
            onehot = jax.nn.one_hot(pred_at, C, dtype=preds.dtype)  # (H, C)
            dirichlets = state.dirichlets.at[:, true_class, :].add(
                eff * onehot
            )
            beta_t = None
        if incremental:
            if pi_update.startswith("delta"):
                pi_xi, pi, unnorm = update_pi_hat_column_delta(
                    true_class, pred_at, preds_by_class,
                    state.pi_xi_unnorm, eff,
                    gather_fn=pi_gather,
                )
            elif sparse_k is not None:
                pi_xi, pi, unnorm = update_pi_hat_column_from_row(
                    densify_row(sparse, true_class), true_class, preds,
                    state.pi_xi_unnorm
                )
            else:
                pi_xi, pi, unnorm = update_pi_hat_column(
                    dirichlets, true_class, preds, state.pi_xi_unnorm
                )
            if eig_backend == "pallas" and fused_refresh:
                # fully-fused: the replacement row is computed IN-KERNEL
                # from the labeled class's Beta tables (opt-in numerics)
                from coda_tpu.ops.pallas_eig import (
                    eig_scores_refresh_compute_pallas,
                )

                if beta_t is not None:
                    a_t, b_t = beta_t
                else:
                    a_cc, b_cc = dirichlet_to_beta(dirichlets)
                    a_t = jnp.take(a_cc, true_class, axis=1)
                    b_t = jnp.take(b_cc, true_class, axis=1)
                rows = state.pbest_rows.at[true_class].set(
                    compute_pbest(a_t, b_t, num_points=hp.num_points))
                scores, hyp = eig_scores_refresh_compute_pallas(
                    rows, state.pbest_hyp, a_t, b_t, hard_preds,
                    true_class, pi, pi_xi, num_points=hp.num_points,
                    block=hp.eig_chunk, approx=approx_entropy)
            elif eig_backend == "pallas":
                # fused refresh+score: the cache is donated through the
                # kernel, so the scan carry never pays the XLA defensive
                # copy a DUS + opaque-custom-call sequence provokes
                row_t, hyp_t = update_eig_cache_parts(
                    dirichlets, true_class, hard_preds,
                    num_points=hp.num_points, precision=eig_precision,
                    beta_t=beta_t)
                rows = state.pbest_rows.at[true_class].set(row_t)
                if shard_mesh is not None:
                    from coda_tpu.ops.pallas_eig import (
                        eig_scores_refresh_pallas_sharded,
                    )

                    scores, hyp = eig_scores_refresh_pallas_sharded(
                        rows, state.pbest_hyp, hyp_t, true_class, pi,
                        pi_xi, mesh=shard_mesh, block=hp.eig_chunk,
                        approx=approx_entropy)
                else:
                    from coda_tpu.ops.pallas_eig import (
                        eig_scores_refresh_pallas,
                    )

                    scores, hyp = eig_scores_refresh_pallas(
                        rows, state.pbest_hyp, hyp_t, true_class, pi,
                        pi_xi, block=hp.eig_chunk,
                        approx=approx_entropy)
            else:
                if scorer_k is not None and beta_t is None:
                    # the surrogate's class-summary refresh needs the
                    # labeled row's Betas; extract once here and hand
                    # them to the cache refresh too (which would
                    # otherwise re-derive them internally)
                    a_cc, b_cc = dirichlet_to_beta(dirichlets)
                    beta_t = (jnp.take(a_cc, true_class, axis=1),
                              jnp.take(b_cc, true_class, axis=1))
                rows, hyp = update_eig_cache(
                    dirichlets, true_class, hard_preds,
                    state.pbest_rows, state.pbest_hyp,
                    num_points=hp.num_points, precision=eig_precision,
                    beta_t=beta_t, pbest=hp.eig_pbest)
                if scorer_k is not None:
                    unlabeled_new = state.unlabeled.at[idx].set(False)
                    tcs = jnp.asarray(true_class, jnp.int32)[None]
                    scores, fit = _surrogate_scores(
                        state.surrogate, state.eig_scores_cached,
                        unlabeled_new, rows, hyp, pi, pi_xi, tcs,
                        beta_t[0][None], beta_t[1][None])
                else:
                    scores = _score_cache(rows, hyp, pi, pi_xi)
        else:
            pi_xi, pi = update_pi_hat(dirichlets, preds)
            unnorm = rows = hyp = scores = None
        return CODAState(
            dirichlets=dirichlets,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=state.unlabeled.at[idx].set(False),
            pbest_rows=rows,
            pbest_hyp=hyp,
            pi_xi_unnorm=unnorm,
            eig_scores_cached=scores,
            sparse=sparse,
            surrogate=(fit if scorer_k is not None else None),
        )

    def update(state: CODAState, idx, true_class, prob) -> CODAState:
        return _update_impl(state, idx, true_class, prob)

    def update_w(state: CODAState, idx, true_class, prob, w) -> CODAState:
        """The reliability-weighted single-label update (crowd oracle).
        w=1 is bitwise ``update``; w=0 is a structural posterior no-op
        (the point is still marked labeled — an answered round consumes
        its point regardless of how much the posterior trusts it)."""
        return _update_impl(state, idx, true_class, prob, w=w)

    def get_pbest(state: CODAState) -> jnp.ndarray:
        if incremental:
            # the cached per-row P(best) is exactly compute_pbest of the
            # current posterior; only the pi-hat mixture is recomputed
            return (state.pi_hat[:, None] * state.pbest_rows).sum(0)
        if eig_mode == "rowscan":  # large C: avoid the (C, H, G) temp
            a_cc, b_cc = dirichlet_to_beta(state.dirichlets)
            rows = compute_pbest_rows(a_cc.T, b_cc.T,
                                      num_points=hp.num_points)
            return (state.pi_hat[:, None] * rows).sum(0)
        return pbest_row_mixture(state.dirichlets, state.pi_hat,
                                 num_points=hp.num_points)  # (H,)

    def best(state: CODAState, key):
        del key  # reference uses plain argmax here (coda/coda.py:346)
        return jnp.argmax(get_pbest(state)).astype(jnp.int32), jnp.asarray(False)

    extras = {"get_pbest": get_pbest, "eig_scores": eig_scores}
    if incremental:
        # the standalone exact scoring pass on a carried state — the
        # baseline side of the scoring-pass speedup microbench
        # (scripts/bench_surrogate.py)
        extras["score_exact"] = lambda st: _score_cache(
            st.pbest_rows, st.pbest_hyp, st.pi_hat, st.pi_hat_xi)
    if scorer_k is not None:
        # per-round fallback flag for the flight recorder's RoundTrace
        # tap (engine/loop.make_round_trace)
        extras["scorer_round_stats"] = (
            lambda st: st.surrogate.last_fallback)

        def _score_surrogate_pass(st, tcs):
            """The surviving-round surrogate pass on a carried state
            (features -> predict -> shortlist exact refresh -> gate ->
            hybrid + refold), isolated for the microbench."""
            from coda_tpu.selectors import surrogate as sg

            fit = st.surrogate
            feats = sg.build_features(
                st.eig_scores_cached, st.pi_hat_xi, st.pi_hat,
                fit.cls_feats, st.pbest_rows, st.pbest_hyp, hard_preds,
                tcs)
            scores, fit, _ = sg.hybrid_score_pass(
                fit, feats, _next_cand(st.unlabeled), scorer_k,
                lambda sel: _exact_score_rows(
                    st.pbest_rows, st.pbest_hyp, st.pi_hat,
                    st.pi_hat_xi, sel))
            return scores, fit

        extras["score_surrogate"] = _score_surrogate_pass

    return Selector(
        name=name,
        init=init,
        select=select,
        update=update,
        best=best,
        # batched acquisition (--acq-batch q): the native greedy-EIG
        # overlap re-rank covers the full-pool EIG; prefilter/ablation
        # acquisitions derive a generic greedy top-q from their score
        # vector (selectors/batch.py). The fused multi-row update_q is a
        # jnp-path program — the pallas backends' in-kernel refresh is
        # single-row, so they fall back to batch.py's sequential scan
        # (select stays one pass either way).
        select_q=(select_q if hp.q == "eig" and not use_prefilter
                  else None),
        update_q=(None if eig_backend == "pallas" else update_q),
        # weighted (crowd) updates: the single-label update_w threads the
        # weight through the same jnp-level scatter/pi lines on every
        # backend; the fused update_qw mirrors update_q's pallas gate
        update_w=update_w,
        update_qw=(None if eig_backend == "pallas" else update_qw),
        always_stochastic=False,
        hyperparams=dict(hp._asdict()),
        hyperparam_defaults=dict(CODAHyperparams()._asdict()),
        extras=extras,
    )
