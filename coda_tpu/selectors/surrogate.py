"""Contract-gated EIG surrogate: learned score amortization.

The next rung of the numerics ladder after the Laplace-bridge row refresh
(``--eig-pbest amortized``, arXiv 1905.12194): CODA's per-round cost at the
imagenet preset (C=1000, H=500, sparse:32) is dominated by the ONE full
scoring pass — the O(N·C·H) elementwise sweep over the incremental P(best)
cache (``eig_scores_from_cache``) — even though only the top handful of
candidates can ever be selected. Following the LINNA pattern
(arXiv 2203.05583: a small learned surrogate predicting an expensive
metric from cheap summaries, trusted only inside a measured contract),
``--eig-scorer surrogate:k`` replaces the full pass with

  1. a **closed-form ridge regressor** over ``N_FEATURES`` cheap per-
     candidate features the state already carries (pi-hat class-hit
     moments, expected |ΔP(best)| profile summaries gathered from the
     ``pbest_hyp`` cache at each candidate's top likeliest labels — the
     same features PR 11's overlap re-rank reads — per-class Beta
     concentration summaries, and the carried previous-round score),
     scoring ALL N candidates in one fused O(N·F) jnp pass;
  2. an **exact shortlist refresh**: the surrogate's top-k rows plus a
     small rotating audit set are re-scored through the exact chain
     (``eig_scores_from_cache`` on the gathered cache columns — identical
     per-row float choreography, pinned), so the score a selection is
     made at is always the exact chain's value;
  3. a **structural trust gate**, measured every round because the
     shortlist's exact scores are computed anyway:

       * *escape*: an unrefreshed candidate's prediction reaching the
         refreshed set's BEST exact score (within the argmax tie
         tolerance) could win the selection on an unaudited value —
         fallback (predictions between the shortlist's tail and its
         peak are fine: they cannot flip the argmax);
       * *audit rank*: a rotating audit row (outside the shortlist)
         whose exact score outranks the shortlist tail means the
         surrogate's ranking missed a candidate — fallback;
       * *score contract*: |prediction − exact| beyond the committed
         :data:`SURROGATE_SCORE_TOL` (the repo's 2.34e-4 score-contract
         bound) on the top :data:`SURROGATE_GATE_TOPR` exact-ranked
         shortlist rows — the ranks that drive selection — means the fit
         is off-distribution — fallback.

     A violated contract falls back to a FULL exact pass for that round
     (bitwise the ``eig_scorer='exact'`` round) and refolds the fit with
     the full round's (features, exact score) pairs. Warmup rounds
     (:data:`SURROGATE_WARMUP_ROUNDS`) are always exact and seed the
     regression the same way, so the argmax can provably never be driven
     by an unaudited score (see "Scope of the exactness guarantee"
     below for batched picks 2..q).

The fit itself is a shape-static ``jnp.linalg.solve`` on an
``(N_FEATURES, N_FEATURES)`` normal equation carried in ``CODAState``
(:class:`SurrogateFit`), refreshed every round with exponential
forgetting — it composes with ``lax.scan``, the sparse posterior tier,
``--acq-batch q``, and the serving slab (the fit leaves ride the
generic state pytree through export/import/migrate bitwise).

Scope of the exactness guarantee: the ARGMAX — the q=1 selection, and
pick 1 of a batched round — always lands on an exactly-scored row (the
escape gate falls back otherwise; test-pinned bitwise). Batched picks
2..q re-rank the hybrid vector under the information-overlap penalty
and may reach surrogate-scored rows when the exactly-scored pool is
exhausted by redundancy — those labels are guarded by the committed
regret envelope (the same contract class as ``acq_batch`` itself), not
by per-pick exactness.

``surrogate:k`` with ``k >= N`` is the parity configuration: the
shortlist covers every row, so each round's score vector is bitwise the
exact scorer's (pinned in tier-1) — the same ladder idiom as
``sparse:K>=C``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from coda_tpu.ops.masked import entropy2

#: feature-vector width of the ridge regressor (the "~16-feature normal
#: equation" of the rung's contract) — see :func:`build_features`
N_FEATURES = 16

#: rounds that are ALWAYS exact and seed the regression before the
#: surrogate may score a round (selection is never driven by a fit that
#: has not seen full-pass evidence)
SURROGATE_WARMUP_ROUNDS = 10

#: the committed score-contract bound the gate holds predictions to on
#: the ranks that matter (the top exact-ranked shortlist rows): the same
#: MEASURED 2.34e-4 the cross-backend / fused-refresh / sparse rungs
#: commit to (telemetry/recorder.CROSS_BACKEND_SCORE_TOL). Calibration on
#: the real-digits 100-round trace (seeds 0-2, surrogate:32, warmup 10):
#: steady-state |pred − exact| on the top-4 exact rows sits at ~2e-5
#: median / ~1.2e-4 p95 once the forgetting-window fit has folded ~6
#: rounds of pairs — the bound trips on genuine distribution shifts
#: (posterior regime changes), not on converged-fit noise.
SURROGATE_SCORE_TOL = 2.34e-4

#: how many top exact-ranked shortlist rows the score contract is
#: enforced on ("ranks that matter": the rows selection can actually
#: reach — the argmax row and its immediate runners-up)
SURROGATE_GATE_TOPR = 4

#: rotating audit rows exact-scored OUTSIDE the shortlist each round
SURROGATE_AUDIT_ROWS = 4

#: per-candidate top likeliest labels the |ΔP(best)| feature gather
#: reads from the pbest_hyp cache (the PR 11 re-rank's kc — the full-C
#: read is the cost the surrogate exists to avoid)
SURROGATE_FEATURE_KC = 8

#: ridge regularizer (relative to the accumulated sample count) and the
#: exponential forgetting factor of the normal equations — the fit
#: tracks the slowly drifting posterior instead of averaging over the
#: whole history
SURROGATE_RIDGE_LAMBDA = 1e-4
SURROGATE_FIT_DECAY = 0.9

#: cap on the effective pair mass a merged cross-session prior may carry
#: into a fresh fit: the prior should shortcut warmup, not outweigh the
#: session's own evidence forever (the per-round SURROGATE_FIT_DECAY
#: halves its influence in ~7 rounds either way; the cap bounds the
#: transient)
SURROGATE_PRIOR_MAX_PAIRS = 4096.0

#: pool forgetting: each contribution folds as
#: ``pool' = merge_fits(scale_prior(pool, DECAY), contribution)`` so the
#: shared prior tracks the recent session population instead of averaging
#: over its whole history (the cross-session analogue of
#: SURROGATE_FIT_DECAY)
SURROGATE_PRIOR_DECAY = 0.98

#: a session's fit must have survived at least this many labeling rounds
#: before its statistics are folded into the shared pool — an immature
#: fit (mid-warmup close) carries no trustworthy normal-equation mass
SURROGATE_PRIOR_MIN_ROUNDS = SURROGATE_WARMUP_ROUNDS

# deterministic audit rotation stride (coprime-ish large prime): the
# update step has no PRNG key (score-ahead runs inside update), so audit
# coverage rotates on the carried round counter instead
_AUDIT_PRIME = 2654435761


def parse_scorer(spec: str) -> Optional[int]:
    """``'exact'`` -> None; ``'surrogate:k'`` -> k (>= 1). Fails loudly on
    anything else — the CLI forwards the string verbatim."""
    if spec == "exact":
        return None
    if isinstance(spec, str) and spec.startswith("surrogate:"):
        try:
            k = int(spec.split(":", 1)[1])
        except ValueError:
            k = 0
        if k >= 1:
            return k
    raise ValueError(
        f"unknown eig_scorer {spec!r} (use 'exact' or 'surrogate:k' with "
        "integer k >= 1, e.g. 'surrogate:64')")


def gate_pressure(margin, tol: float = SURROGATE_SCORE_TOL) -> float:
    """Map the live escape-gate margin (``surrogate_stats``'s
    ``contract_margin``) into a [0, ∞) drift observable for the
    decision-quality plane (``telemetry/quality.py``): 0 when the exact
    shortlist dominates every unrefreshed prediction by ≥ ``tol``
    (plenty of headroom), 1.0 exactly at a zero margin (the escape gate
    about to trip), > 1 once the gate is actively forcing fallbacks. A
    pre-warmup / absent margin (None or non-finite) reads as 0 — no
    surrogate round has been gated yet, so there is nothing drifting."""
    if margin is None:
        return 0.0
    m = float(margin)
    if not np.isfinite(m):
        return 0.0
    return max(0.0, 1.0 - m / float(tol))


class SurrogateFit(NamedTuple):
    """The carried surrogate state: normal equations + solved weights +
    per-class Beta summaries + the gate's evidence counters.

    Every leaf is shape-static, so the fit rides the ``lax.scan`` carry,
    the serving slab's slot axis, and the export/import snapshot path
    without special cases."""

    A: jnp.ndarray          # (F, F) decayed Fᵀ·F normal-equation matrix
    b: jnp.ndarray          # (F,)   decayed Fᵀ·y right-hand side
    w: jnp.ndarray          # (F,)   current ridge solution
    n: jnp.ndarray          # scalar f32 — decayed accumulated pair count
    # per-class Beta concentration summaries (the feature columns only
    # the labeled row of which changes per round): [log1p(mean_h conc),
    # log1p(min_h conc), mean_h accuracy]
    cls_feats: jnp.ndarray  # (C, 3) f32
    rounds: jnp.ndarray     # scalar i32 — labeling rounds seen
    fallbacks: jnp.ndarray  # scalar i32 — contract-violation fallbacks
    fits: jnp.ndarray       # scalar i32 — normal-equation refolds/solves
    last_fallback: jnp.ndarray  # scalar bool — did THIS round fall back?
    # min exact shortlist score minus max unrefreshed prediction of the
    # last gated round: the escape-gate margin (healthy > 0; the gauge
    # serve /metrics exposes)
    margin: jnp.ndarray     # scalar f32
    # cross-session prior bookkeeping (--surrogate-prior pool; both stay
    # 0 with the prior off, which keeps the off-config round program
    # bitwise the PR 14 one):
    # warmup-round credit granted by a merged pool prior at seed time —
    # the warm condition counts (rounds + prior_rounds), so a mature
    # prior shortens or skips the 10 exact warmup rounds while every
    # served round still passes the trust gate
    prior_rounds: jnp.ndarray   # scalar i32
    # gate fallbacks that fired while the session was still inside the
    # warmup window it only skipped BECAUSE of the prior (rounds <
    # SURROGATE_WARMUP_ROUNDS <= rounds + prior_rounds): the pool prior
    # being rejected by the per-round contract, counted separately so
    # /metrics can show the fallback safety net actually catching it
    prior_rejects: jnp.ndarray  # scalar i32


def class_feats_from_beta(a_row: jnp.ndarray, b_row: jnp.ndarray
                          ) -> jnp.ndarray:
    """(3,) summary of one class row's per-model diagonal Betas ``(H,)``:
    log1p mean/min total concentration and mean accuracy estimate."""
    conc = a_row + b_row
    return jnp.stack([
        jnp.log1p(jnp.mean(conc)),
        jnp.log1p(jnp.min(conc)),
        jnp.mean(a_row / jnp.clip(conc, 1e-12, None)),
    ]).astype(jnp.float32)


def init_fit(a_cc_T: jnp.ndarray, b_cc_T: jnp.ndarray) -> SurrogateFit:
    """Zeroed fit seeded with the init posterior's per-class summaries.

    ``a_cc_T``/``b_cc_T``: (C, H) diagonal-Beta parameters of every class
    row (init builds them anyway for the EIG cache)."""
    F = N_FEATURES
    cls = jax.vmap(class_feats_from_beta)(a_cc_T, b_cc_T)  # (C, 3)
    z32 = jnp.asarray(0, jnp.int32)
    return SurrogateFit(
        A=jnp.zeros((F, F), jnp.float32),
        b=jnp.zeros((F,), jnp.float32),
        w=jnp.zeros((F,), jnp.float32),
        n=jnp.asarray(0.0, jnp.float32),
        cls_feats=cls,
        rounds=z32, fallbacks=z32, fits=z32,
        last_fallback=jnp.asarray(False),
        margin=jnp.asarray(jnp.nan, jnp.float32),
        prior_rounds=z32, prior_rejects=z32,
    )


def refresh_class_feats(fit: SurrogateFit, true_classes: jnp.ndarray,
                        a_t: jnp.ndarray, b_t: jnp.ndarray) -> SurrogateFit:
    """Refresh the touched class rows' summary columns. ``true_classes``
    is (q,) int32; ``a_t``/``b_t`` are (q, H) — the same labeled-row Beta
    parameters the cache refresh already extracted (dense take or the
    sparse tier's O(H·K) compact reduction), so this costs O(q·H)."""
    rows = jax.vmap(class_feats_from_beta)(a_t, b_t)       # (q, 3)
    cls = fit.cls_feats
    for j in range(rows.shape[0]):  # q static scalar-index DUSes
        cls = cls.at[true_classes[j]].set(rows[j])
    return fit._replace(cls_feats=cls)


def build_features(prev_scores: jnp.ndarray,   # (N,) last round's vector
                   pi_hat_xi: jnp.ndarray,     # (N, C)
                   pi_hat: jnp.ndarray,        # (C,)
                   cls_feats: jnp.ndarray,     # (C, 3)
                   pbest_rows: jnp.ndarray,    # (C, H)
                   pbest_hyp: jnp.ndarray,     # (C, N, H) (storage dtype)
                   hard_preds: jnp.ndarray,    # (N, H) int32
                   true_classes: jnp.ndarray,  # (q,) int32 touched rows
                   ) -> jnp.ndarray:
    """The (N, :data:`N_FEATURES`) design matrix — every column O(N·C),
    O(N·H) or O(N·kc·H), never the O(N·C·H) full-cache sweep.

    Feature groups (all fp32):

      * the carried previous-round score (the autoregressive anchor —
        between rounds only the labeled class row's contribution moves);
      * pi-hat class-hit moments: max, runner-up, entropy, collision
        mass (which Dirichlet rows this candidate's label would touch,
        and how concentrated that hit distribution is);
      * round coupling: the candidate's weight on the just-labeled
        class(es) and the fraction of models predicting them (how much
        THIS round's refresh moved this candidate's integrand);
      * per-class Beta concentration summaries, expectation-weighted by
        the candidate's class posterior (the amortized rung showed
        concentration is what governs integral smoothness);
      * expected |ΔP(best)| profile summaries off the ``pbest_hyp``
        cache at the candidate's top :data:`SURROGATE_FEATURE_KC`
        likeliest labels (sum / max / L2 / alignment with the current
        P(best) mixture) — PR 11's re-rank features;
      * two curvature/interaction columns (prev², prev·touch-weight).
    """
    N, C = pi_hat_xi.shape
    prev = prev_scores.astype(jnp.float32)
    finite_prev = jnp.where(jnp.isfinite(prev), prev, 0.0)

    # pi-hat class-hit moments
    top2 = lax.top_k(pi_hat_xi, min(2, C))[0]            # (N, <=2)
    p_max = top2[:, 0]
    p_2nd = top2[:, -1]
    p_ent = entropy2(pi_hat_xi, axis=-1)
    p_coll = jnp.sum(pi_hat_xi * pi_hat_xi, axis=-1)

    # round coupling with the touched class rows
    w_t = pi_hat_xi[:, true_classes].sum(axis=-1)        # (N,)
    eq_t = jnp.mean(
        (hard_preds[:, None, :] == true_classes[None, :, None])
        .astype(jnp.float32), axis=(1, 2))               # (N,)

    # expectation-weighted per-class Beta summaries: (N, C) @ (C, 3)
    conc = pi_hat_xi @ cls_feats                         # (N, 3)

    # expected |dP(best)| profile from the cache, restricted to each
    # candidate's top-kc likeliest labels (the O(kc·N·H) gather that
    # replaces the 84 ms/round full-C read — measured, PR 11)
    kc = min(SURROGATE_FEATURE_KC, C)
    w_full = pi_hat_xi * pi_hat[None, :]                 # (N, C)
    wv, ci = lax.top_k(w_full, kc)                       # (N, kc)
    hyp_sel = pbest_hyp[ci, jnp.arange(N)[:, None], :].astype(
        jnp.float32)                                     # (N, kc, H)
    rows_sel = pbest_rows[ci]                            # (N, kc, H)
    E = jnp.einsum("nk,nkh->nh", wv,
                   jnp.abs(hyp_sel - rows_sel))          # (N, H)
    e_sum = E.sum(axis=-1)
    e_max = E.max(axis=-1)
    e_l2 = jnp.sqrt(jnp.sum(E * E, axis=-1))
    mix = (pi_hat[:, None] * pbest_rows).sum(0)          # (H,)
    mix = mix / jnp.clip(mix.sum(), 1e-12, None)
    e_mix = E @ mix                                      # (N,)

    feats = jnp.stack([
        jnp.ones((N,), jnp.float32),
        finite_prev,
        p_max, p_2nd, p_ent, p_coll,
        w_t, eq_t,
        conc[:, 0], conc[:, 1], conc[:, 2],
        e_sum, e_max, e_l2, e_mix,
        finite_prev * w_t,
    ], axis=1)
    assert feats.shape[1] == N_FEATURES
    return feats


def _prev_anchor(feats: jnp.ndarray) -> jnp.ndarray:
    """The previous-round score column of the design matrix (finite-
    masked at build time). The regressor predicts the RESIDUAL against
    it: between rounds only the labeled class row's contribution moves,
    so the residual is small and smooth where the raw score is not — and
    the anchor coefficient never fights the ridge penalty."""
    return feats[:, 1]


def predict(fit: SurrogateFit, feats: jnp.ndarray) -> jnp.ndarray:
    """(N,) surrogate scores: the carried previous score plus the
    ridge-predicted residual — one fused matvec."""
    return _prev_anchor(feats) + feats @ fit.w


def fold_pairs(fit: SurrogateFit, feats: jnp.ndarray,
               targets: jnp.ndarray, mask: jnp.ndarray) -> SurrogateFit:
    """Refold the normal equations with this round's (features, exact
    score) pairs and re-solve the ridge — the per-round closed-form fit
    (targets enter as residuals against the previous-score anchor).

    ``mask``: (N,) bool — which rows carry a trustworthy exact target
    (all candidates on a full/warmup/fallback round, the refreshed
    shortlist+audit rows on a surrogate round)."""
    m = mask.astype(jnp.float32)
    fm = feats * m[:, None]
    resid = targets - _prev_anchor(feats)
    tm = jnp.where(mask & jnp.isfinite(resid), resid, 0.0)
    A = SURROGATE_FIT_DECAY * fit.A + fm.T @ fm
    b = SURROGATE_FIT_DECAY * fit.b + fm.T @ tm
    n = SURROGATE_FIT_DECAY * fit.n + m.sum()
    lam = SURROGATE_RIDGE_LAMBDA * jnp.clip(n, 1.0, None)
    w = jnp.linalg.solve(
        A + lam * jnp.eye(N_FEATURES, dtype=A.dtype), b)
    # a degenerate system (first rounds, all-masked) must not poison the
    # carry with NaNs — predictions then stay 0 and warmup/exact rounds
    # keep selection correct regardless
    w = jnp.where(jnp.isfinite(w), w, 0.0)
    return fit._replace(A=A, b=b, w=w, n=n, fits=fit.fits + 1)


def audit_rows(fit: SurrogateFit, N: int,
               n_audit: int = SURROGATE_AUDIT_ROWS) -> jnp.ndarray:
    """The round's rotating deterministic audit set: ``n_audit`` row
    indices stridden across the pool, rotated by the carried round
    counter (the score-ahead update step has no PRNG key; determinism
    here is also what keeps replay bitwise)."""
    n_audit = max(1, min(n_audit, N))
    stride = max(1, N // n_audit)
    base = (fit.rounds.astype(jnp.uint32) * jnp.uint32(_AUDIT_PRIME))
    offs = jnp.arange(n_audit, dtype=jnp.uint32) * jnp.uint32(stride)
    return ((base + offs) % jnp.uint32(N)).astype(jnp.int32)


class GateVerdict(NamedTuple):
    """The per-round trust-gate measurement (all scalars)."""

    violated: jnp.ndarray       # bool — any condition tripped
    escape: jnp.ndarray         # bool — an unrefreshed pred reached the
    #                             refreshed set's best exact score
    audit_outrank: jnp.ndarray  # bool — audit row beat the shortlist tail
    delta: jnp.ndarray          # f32 — max |pred − exact| on top ranks
    margin: jnp.ndarray         # f32 — best refreshed exact score minus
    #                             the best unrefreshed prediction


def measure_gate(pred: jnp.ndarray,        # (N,) surrogate predictions
                 exact_sel: jnp.ndarray,   # (m,) exact scores at sel
                 sel: jnp.ndarray,         # (m,) = [shortlist | audit]
                 k: int,                   # shortlist width
                 cand: jnp.ndarray,        # (N,) bool candidate mask
                 refreshed: jnp.ndarray,   # (N,) bool — rows in sel
                 ) -> GateVerdict:
    """Measure the three contract conditions (module docstring)."""
    short_sel = sel[:k]
    short_exact = exact_sel[:k]
    short_valid = cand[short_sel]
    audit_sel = sel[k:]
    audit_exact = exact_sel[k:]
    # an audit row that also made the shortlist is not an independent
    # spot check (k >= N parity runs hit this every round)
    in_short = (audit_sel[:, None] == short_sel[None, :]).any(axis=1)
    audit_valid = cand[audit_sel] & ~in_short

    floor = jnp.min(jnp.where(short_valid, short_exact, jnp.inf))
    peak = jnp.max(jnp.where(short_valid, short_exact, -jnp.inf))
    peak = jnp.maximum(peak, jnp.max(
        jnp.where(audit_valid, audit_exact, -jnp.inf)))
    max_unref = jnp.max(
        jnp.where(cand & ~refreshed, pred, -jnp.inf))
    # an unrefreshed prediction that reaches the refreshed set's best
    # exact score (within the argmax TIE tolerance — coda's isclose
    # rtol=atol=1e-8, so a tied unrefreshed row could win the random
    # tie-break) could drive the selection on an unaudited value — the
    # one ordering the surrogate is never trusted to make alone
    tie_slack = 1e-8 + 1e-8 * jnp.abs(peak)
    escape = max_unref >= peak - tie_slack
    # rank agreement, judged at the committed score contract: an audit
    # row beating the shortlist TAIL by less than the contract bound is
    # rank noise on a flat tail (the interchangeable-ranks region), not
    # a missed candidate
    audit_outrank = jnp.any(
        audit_valid & (audit_exact > floor + SURROGATE_SCORE_TOL))
    # score contract on the ranks that matter: the top-R exact-ranked
    # valid shortlist rows
    r = min(SURROGATE_GATE_TOPR, k)
    top_exact, top_loc = lax.top_k(
        jnp.where(short_valid, short_exact, -jnp.inf), r)
    pred_at = pred[short_sel[top_loc]]
    delta = jnp.max(jnp.where(jnp.isfinite(top_exact),
                              jnp.abs(pred_at - top_exact), 0.0))
    violated = escape | audit_outrank | (delta > SURROGATE_SCORE_TOL)
    return GateVerdict(violated=violated, escape=escape,
                       audit_outrank=audit_outrank, delta=delta,
                       margin=(peak - max_unref).astype(jnp.float32))


def propose_shortlist(fit: SurrogateFit, feats: jnp.ndarray,
                      cand: jnp.ndarray, k: int, exact_rows_fn) -> tuple:
    """Predict, shortlist, exact-refresh, measure: the shared first half
    of a surrogate round. Returns ``(pred, sel, exact_sel, refreshed,
    verdict)``."""
    N = feats.shape[0]
    k = max(1, min(k, N))
    pred = predict(fit, feats)
    # shortlist: top-k predictions over the candidate set; candidate
    # pools smaller than k degrade to exact-everywhere naturally (the
    # non-candidates gathered here are refreshed but can never be picked)
    _, short = lax.top_k(jnp.where(cand, pred, -jnp.inf), k)
    sel = jnp.concatenate([short.astype(jnp.int32),
                           audit_rows(fit, N)])
    exact_sel = exact_rows_fn(sel)
    refreshed = jnp.zeros((N,), bool).at[sel].set(True)
    verdict = measure_gate(pred, exact_sel, sel, k, cand, refreshed)
    return pred, sel, exact_sel, refreshed, verdict


def hybrid_score_pass(fit: SurrogateFit, feats: jnp.ndarray,
                      cand: jnp.ndarray, k: int, exact_rows_fn) -> tuple:
    """The surviving-round scoring pass in isolation (no warmup/fallback
    cond): hybrid vector + refolded fit + the gate verdict. This is the
    program the scoring-pass speedup microbench times against the exact
    full pass (scripts/bench_surrogate.py)."""
    pred, sel, exact_sel, refreshed, verdict = propose_shortlist(
        fit, feats, cand, k, exact_rows_fn)
    scores = pred.at[sel].set(exact_sel)
    fit = fold_pairs(fit, feats, scores, refreshed & cand)
    return scores, fit, verdict


def surrogate_score_round(fit: SurrogateFit,
                          feats: jnp.ndarray,        # (N, F)
                          cand: jnp.ndarray,         # (N,) bool
                          k: int,
                          exact_rows_fn,             # (sel,) -> (m,)
                          exact_full_fn,             # () -> (N,)
                          ) -> tuple:
    """One scored round under the contract: returns ``(scores, fit')``.

    Warmup (``fit.rounds < SURROGATE_WARMUP_ROUNDS``) and gate-violation
    rounds run ``exact_full_fn`` — bitwise the exact scorer's round — and
    refold the fit from every candidate's pair; surviving rounds return
    the hybrid vector (exact on the refreshed shortlist+audit rows,
    predictions elsewhere) and refold from the refreshed pairs. Both
    branches produce identical shapes, so the whole thing sits inside the
    ``lax.scan`` step (a real branch under jit/scan — only one side runs
    per round; under ``vmap`` — batched seeds, the TPU slab lowering —
    the cond lowers to a select and both sides execute, so the speedup is
    a single-run property, like the pallas fast paths).
    """
    N = feats.shape[0]
    m = max(1, min(k, N)) + max(1, min(SURROGATE_AUDIT_ROWS, N))
    # a merged cross-session prior grants warmup-round credit
    # (prior_rounds, 0 with --surrogate-prior off — the PR 14 condition
    # exactly): credited rounds skip the always-exact warmup pass, but
    # every skipped round still runs propose -> gate -> fallback, so
    # selection is never driven by an unaudited score
    warm = (fit.rounds + fit.prior_rounds) < SURROGATE_WARMUP_ROUNDS

    def propose():
        return propose_shortlist(fit, feats, cand, k, exact_rows_fn)

    def skip_propose():
        # warmup: the round is a full exact pass regardless, so don't
        # pay the shortlist refresh just to discard its verdict (at the
        # imagenet preset that is ~27% of a full pass per warmup round);
        # the margin carries over so the gauge never reads a zero
        z = GateVerdict(violated=jnp.asarray(False),
                        escape=jnp.asarray(False),
                        audit_outrank=jnp.asarray(False),
                        delta=jnp.asarray(0.0, jnp.float32),
                        margin=fit.margin)
        return (jnp.zeros((N,), jnp.float32),
                jnp.zeros((m,), jnp.int32),
                jnp.zeros((m,), jnp.float32),
                jnp.zeros((N,), bool), z)

    pred, sel, exact_sel, refreshed, verdict = lax.cond(
        warm, skip_propose, propose)
    need_full = warm | verdict.violated

    def full_round():
        scores = exact_full_fn()
        return scores, cand

    def hybrid_round():
        scores = pred.at[sel].set(exact_sel)
        return scores, refreshed & cand

    scores, pair_mask = lax.cond(need_full, full_round, hybrid_round)
    fit = fold_pairs(fit, feats, scores, pair_mask)
    fell_back = verdict.violated & ~warm
    # a fallback inside the window the prior skipped is the gate
    # REJECTING the pool prior (the round still ran exact — nothing was
    # lost; the counter is the prior's audit trail)
    prior_reject = fell_back & (fit.rounds < SURROGATE_WARMUP_ROUNDS)
    fit = fit._replace(
        rounds=fit.rounds + 1,
        fallbacks=fit.fallbacks + fell_back.astype(jnp.int32),
        last_fallback=fell_back,
        margin=verdict.margin,
        prior_rejects=fit.prior_rejects + prior_reject.astype(jnp.int32),
    )
    return scores, fit


# ---------------------------------------------------------------------------
# cross-session prior pool (--surrogate-prior pool)
# ---------------------------------------------------------------------------

def parse_prior(spec: str) -> bool:
    """``'off'`` -> False; ``'pool'`` -> True. Fails loudly on anything
    else — the CLI forwards the string verbatim."""
    if spec == "off":
        return False
    if spec == "pool":
        return True
    raise ValueError(
        f"unknown surrogate_prior {spec!r} (use 'off' or 'pool')")


class PriorStats(NamedTuple):
    """Host-side mergeable cross-session surrogate prior.

    The A/b normal-equation form is mergeable BY CONSTRUCTION: A = ΣFᵀF
    and b = ΣFᵀy are sums over (feature, exact-score) pairs, so merging
    two sessions' statistics is a pure elementwise sum — commutative
    bitwise (IEEE a+b == b+a), associative to fp rounding, with the
    all-zeros pool as an exact neutral element (x + 0.0 == x for every
    finite x, and the counters are exact integers in f64 at any
    realistic scale). ``merge_fits`` below is that sum, property-tested
    in tests/test_prior.py.

    Everything is float64 numpy on the host: the pool lives outside the
    jit boundary (serve admission / tracking store / router transport)
    and is cast to f32 only at :func:`seed_fit` time.
    """

    A: np.ndarray       # (F, F) f64 — summed decayed FᵀF
    b: np.ndarray       # (F,)   f64 — summed decayed Fᵀy
    n: float            # summed decayed pair count
    rounds: float       # summed labeling rounds of the contributors
    sessions: float     # contributing sessions folded in (decays too)


def empty_prior() -> PriorStats:
    """The neutral element: merge_fits(empty_prior(), p) == p bitwise."""
    F = N_FEATURES
    return PriorStats(A=np.zeros((F, F), np.float64),
                      b=np.zeros((F,), np.float64),
                      n=0.0, rounds=0.0, sessions=0.0)


def prior_from_fit(A, b, n, rounds) -> PriorStats:
    """One closed/demoted session's contribution, from its carried
    :class:`SurrogateFit` leaves (host copies). A fit that accumulated
    nothing (n == 0 — e.g. the w=0-count fit of a session closed before
    its first label) contributes the exact neutral element, so folding
    it into a pool is a bitwise no-op."""
    A = np.asarray(A, np.float64).reshape(N_FEATURES, N_FEATURES)
    b = np.asarray(b, np.float64).reshape(N_FEATURES)
    n = float(np.asarray(n))
    if not np.isfinite(n) or n <= 0.0:
        return empty_prior()
    return PriorStats(A=A, b=b, n=n, rounds=float(np.asarray(rounds)),
                      sessions=1.0)


def merge_fits(p: PriorStats, q: PriorStats) -> PriorStats:
    """The pool merge: a pure elementwise sum (see :class:`PriorStats`
    for why that is correct). No decay here — decay is the FOLD policy
    (:func:`fold_prior`), kept out of the merge so the merge stays
    commutative/associative/neutral-element clean."""
    return PriorStats(A=p.A + q.A, b=p.b + q.b, n=p.n + q.n,
                      rounds=p.rounds + q.rounds,
                      sessions=p.sessions + q.sessions)


def merge_many(priors) -> PriorStats:
    """Left fold of :func:`merge_fits` over ``priors`` starting from the
    neutral element — merge-of-one is the identity (property-tested)."""
    out = empty_prior()
    for p in priors:
        out = merge_fits(out, p)
    return out


def scale_prior(p: PriorStats, gamma: float) -> PriorStats:
    """Uniformly scale a pool's mass (the decay/cap primitive)."""
    g = float(gamma)
    return PriorStats(A=p.A * g, b=p.b * g, n=p.n * g,
                      rounds=p.rounds * g, sessions=p.sessions * g)


def clip_prior(p: PriorStats,
               max_pairs: float = SURROGATE_PRIOR_MAX_PAIRS) -> PriorStats:
    """Bound the effective pair mass (A/b/n scale together so the ridge
    solution is unchanged; only the prior's WEIGHT against the session's
    own incoming pairs is capped). rounds/sessions are provenance, not
    mass — they stay."""
    if p.n <= max_pairs:
        return p
    g = max_pairs / p.n
    return p._replace(A=p.A * g, b=p.b * g, n=p.n * g)


def fold_prior(pool: PriorStats, contribution: PriorStats,
               decay: float = SURROGATE_PRIOR_DECAY) -> PriorStats:
    """The pool's fold policy: exponential forgetting of the existing
    pool, then the pure-sum merge, then the mass cap."""
    return clip_prior(merge_fits(scale_prior(pool, decay), contribution))


def prior_warmup_credit(p: PriorStats) -> int:
    """Warmup rounds a seeded session may skip: the pool's accumulated
    round evidence, capped at the full warmup — a pool that has seen a
    full warmup's worth of labeling rounds earns the full skip, a
    thinner one earns a partial shortening, an empty one earns none.
    The per-round trust gate still audits every skipped round."""
    if p.n <= 0.0:
        return 0
    return int(min(float(SURROGATE_WARMUP_ROUNDS), p.rounds))


def seed_fit(fit: SurrogateFit, p: PriorStats) -> SurrogateFit:
    """A fresh session's fit, warm-started from a merged pool prior:
    the prior's normal equations are added to the (zeroed) fit's, the
    ridge is re-solved, and the warmup credit is granted. The session's
    per-round folds then decay the prior mass exactly like old own
    evidence (SURROGATE_FIT_DECAY). cls_feats are NOT transferred — the
    fresh init posterior's class summaries are the correct features for
    THIS session's rounds."""
    credit = prior_warmup_credit(p)
    if credit == 0 and p.n <= 0.0:
        return fit
    A = fit.A + jnp.asarray(p.A, jnp.float32)
    b = fit.b + jnp.asarray(p.b, jnp.float32)
    n = fit.n + jnp.asarray(p.n, jnp.float32)
    lam = SURROGATE_RIDGE_LAMBDA * jnp.clip(n, 1.0, None)
    w = jnp.linalg.solve(A + lam * jnp.eye(N_FEATURES, dtype=A.dtype), b)
    w = jnp.where(jnp.isfinite(w), w, 0.0)
    return fit._replace(
        A=A, b=b, w=w, n=n,
        prior_rounds=fit.prior_rounds + jnp.asarray(credit, jnp.int32))


def prior_to_dict(p: PriorStats) -> dict:
    """JSON-safe form (router transport, tracking-store persistence)."""
    return {"v": 1, "A": np.asarray(p.A, np.float64).tolist(),
            "b": np.asarray(p.b, np.float64).tolist(),
            "n": float(p.n), "rounds": float(p.rounds),
            "sessions": float(p.sessions)}


def prior_from_dict(d: dict) -> PriorStats:
    if int(d.get("v", 1)) != 1:
        raise ValueError(f"unknown prior stats version {d.get('v')!r}")
    return PriorStats(
        A=np.asarray(d["A"], np.float64).reshape(N_FEATURES, N_FEATURES),
        b=np.asarray(d["b"], np.float64).reshape(N_FEATURES),
        n=float(d["n"]), rounds=float(d["rounds"]),
        sessions=float(d.get("sessions", 0.0)))


def prior_digest(p: PriorStats) -> str:
    """Short stable digest of a pool prior's VALUES — the recorder
    stamps it next to the surrogate_prior knob so two prior-seeded
    records are comparable only when they were seeded from the same
    pool state."""
    import hashlib

    h = hashlib.blake2b(digest_size=8)
    h.update(np.asarray(p.A, np.float64).tobytes())
    h.update(np.asarray(p.b, np.float64).tobytes())
    h.update(np.float64(p.n).tobytes())
    h.update(np.float64(p.rounds).tobytes())
    return h.hexdigest()
