"""VMA: Variance Minimization for Active Model Selection (Matsuura & Hara 2023).

Capability parity with reference ``coda/baselines/vma.py``: acquisition
weight of a point is the summed pairwise loss disagreement
``Σ_{h'>h} |loss_h(x) - loss_h'(x)|`` (losses under the ensemble surrogate),
sampled proportionally; LURE risk readout inherited from ActiveTesting.

TPU-native kernel: the reference materializes an ``(H, H, N)`` broadcast and
an upper-triangular mask — O(H²N) memory and FLOPs, hopeless at M=1000.
The identical scores come from the classic sorted-values identity

    Σ_{i<j} |a_i - a_j| = Σ_k (2k - H + 1) · a_(k)   (a_(k) ascending)

which is one sort over H per point: O(N·H log H), no H² tensor. The scores
are static (surrogate fixed), computed once in the factory.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from coda_tpu.losses import accuracy_loss
from coda_tpu.selectors.activetesting import (
    make_activetesting,
    surrogate_expected_losses,
)
from coda_tpu.selectors.protocol import Selector


def pairwise_absdiff_sum(values: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """``Σ_{i<j} |v_i - v_j|`` along ``axis`` via the sorted identity."""
    v = jnp.moveaxis(values, axis, -1)
    H = v.shape[-1]
    v_sorted = jnp.sort(v, axis=-1)
    coeff = (2.0 * jnp.arange(H, dtype=v.dtype) - (H - 1.0))
    return (coeff * v_sorted).sum(axis=-1)


def vma_scores(preds: jnp.ndarray) -> jnp.ndarray:
    """(N,) pairwise-disagreement acquisition scores."""
    losses_all = surrogate_expected_losses(preds)  # (H, N)
    return pairwise_absdiff_sum(losses_all, axis=0)


def make_vma(
    preds: jnp.ndarray,
    loss_fn: Callable = accuracy_loss,
    budget: int = 128,
    name: str = "vma",
) -> Selector:
    sel = make_activetesting(
        preds, loss_fn=loss_fn, budget=budget, name=name,
        acquisition_scores=vma_scores(preds),
    )
    return sel
