"""Committee-uncertainty baseline.

Capability parity with reference ``coda/baselines/uncertainty.py``: select
the unlabeled point with the highest entropy of the ensemble-mean prediction
(natural log, 1e-8 epsilon); risk-based best-model readout as IID. The
acquisition is non-adaptive, so the per-point scores are computed once in the
factory and reused every round.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from coda_tpu.losses import accuracy_loss
from coda_tpu.ops.masked import masked_argmax_tiebreak
from coda_tpu.selectors.iid import make_risk_readout
from coda_tpu.selectors.protocol import Selector, SelectResult


def uncertainty_scores(preds: jnp.ndarray, epsilon: float = 1e-8) -> jnp.ndarray:
    """Entropy (nats) of the mean-over-models prediction, per point. (N,)"""
    mean_p = preds.mean(axis=0)
    return -(mean_p * jnp.log(mean_p + epsilon)).sum(axis=-1)


def make_uncertainty(
    preds: jnp.ndarray,
    loss_fn: Callable = accuracy_loss,
    name: str = "uncertainty",
) -> Selector:
    H, N, C = preds.shape
    scores = uncertainty_scores(preds)  # static: non-adaptive acquisition
    init_state, risk, best, update = make_risk_readout(preds, loss_fn)

    def init(key):
        del key
        return init_state()

    def select(state, key) -> SelectResult:
        idx, n_ties = masked_argmax_tiebreak(key, scores, state.unlabeled)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=n_ties > 1,
            scores=jnp.where(state.unlabeled, scores, -jnp.inf),
        )

    return Selector(
        name=name, init=init, select=select, update=update, best=best,
        always_stochastic=False, extras={"risk": risk},
    )
