"""ModelPicker (Karimi et al.): multiplicative-weights posterior over models.

Capability parity with reference ``coda/baselines/modelpicker.py``:
  * posterior over models updated multiplicatively by ``γ^agreement`` with
    ``γ = (1-ε)/ε`` and per-task tuned ε (TASK_EPS table);
  * acquisition = the unlabeled *disagreement* point minimizing the expected
    posterior entropy over hypothetical labels (uniform over classes);
  * best model = argmax of correct-prediction counts, random tie-break.

TPU shape: the per-point expected entropy is a CLOSED FORM over two
scatter-add bucket sums (see :func:`expected_entropies` — the reference
loops classes in Python, keeping an ``(N_u, H)`` float tensor per class and
a softmax per point). Disagreement-vs-first-model mask is static, computed
once.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.ops.masked import entropy2, masked_argmin_tiebreak
from coda_tpu.selectors.protocol import Selector, SelectResult

# Per-task tuned epsilons (reference coda/baselines/modelpicker.py:5-35).
TASK_EPS = {
    "imagenet_v2_matched-frequency": 0.48,
    "cifar10_4070": 0.47,
    "cifar10_5592": 0.47,
    "pacs": 0.45,
    "glue/cola": 0.45,
    "glue/mnli": 0.43,
    "glue/qnli": 0.44,
    "glue/qqp": 0.47,
    "glue/rte": 0.39,
    "glue/sst2": 0.36,
    "real_clipart": 0.42,
    "real_painting": 0.35,
    "real_sketch": 0.45,
    "sketch_real": 0.35,
    "sketch_clipart": 0.35,
    "sketch_painting": 0.37,
    "clipart_painting": 0.45,
    "clipart_real": 0.45,
    "clipart_sketch": 0.43,
    "painting_sketch": 0.39,
    "painting_real": 0.44,
    "painting_clipart": 0.39,
    "iwildcam": 0.49,
    "civilcomments": 0.46,
    "fmow": 0.44,
    "camelyon": 0.47,
    # tuned with THIS framework's scripts/modelselector_eps_gridsearch.py on
    # the committed real tasks (runs/best_epsilons_real.json, 200
    # realisations x pool 300 x budget 150; see REAL_TASK.md), not copied
    # from anywhere
    "digits": 0.39,
    "breast_cancer": 0.35,
    "wine": 0.37,
    "iris": 0.36,            # 200 realisations x pool 80 x budget 60 on the
    #                           committed 0.7-eval-split build (N=105)
    "digits_shift": 0.44,
    "pyfiles": 0.36,         # document-type text task (C=5, N=500)
    "digits_h80": 0.36,      # 80-model MSV-shaped pool on the NIST scans
}
DEFAULT_EPS = 0.46


def _bucket_sums(hard_preds, w, wlw, C: int, impl: str | None = None):
    """The two weighted per-class bucket sums ``t1[n, c] = Σ_{h: pred=c} w_h``
    and ``t2`` (same with ``w·ln w``), as (N, C) f32 pairs.

    Two lowerings of the same sums (identical values up to float
    accumulation order, pinned by ``test_modelpicker_bucket_impls_agree``):

      * ``scatter`` — O(N·H) scatter-add updates; the fast CPU lowering.
      * ``scan`` — ``lax.scan`` over models accumulating one-hot buckets,
        O(N·C·H) regular VPU work. The TPU lowering: scatters serialize
        there, and the scatter lowering under the suite's task x seed
        DOUBLE vmap hard-crashed the TPU worker at >=24 replicas of the
        DomainNet shape (reproduced round 5 on a v5e; the scan runs the
        same 48-replica batch fine).

    ``impl=None`` picks by backend at trace time: ``scan`` ONLY on the TPU
    whose scatters motivated it — on CPU and GPU scatter-add is the fast
    path, and the serialized O(N·C·H) scan would be a regression
    (ADVICE round 5).
    """
    N, H = hard_preds.shape
    if impl is None:
        impl = "scan" if jax.default_backend() == "tpu" else "scatter"
    if impl == "scatter":
        rows = jnp.broadcast_to(
            jnp.arange(N, dtype=jnp.int32)[:, None], (N, H))
        t1 = jnp.zeros((N, C), jnp.float32).at[rows, hard_preds].add(
            jnp.broadcast_to(w[None, :], (N, H)))
        t2 = jnp.zeros((N, C), jnp.float32).at[rows, hard_preds].add(
            jnp.broadcast_to(wlw[None, :], (N, H)))
        return t1, t2

    def step(acc, inp):
        t1_a, t2_a = acc
        pred_h, w_h, wlw_h = inp
        oh = jax.nn.one_hot(pred_h, C, dtype=jnp.float32)
        return (t1_a + w_h * oh, t2_a + wlw_h * oh), None

    zero = jnp.zeros((N, C), jnp.float32)
    (t1, t2), _ = lax.scan(step, (zero, zero), (hard_preds.T, w, wlw))
    return t1, t2


class ModelPickerState(NamedTuple):
    unlabeled: jnp.ndarray       # (N,) bool
    posterior: jnp.ndarray       # (H,)
    correct_counts: jnp.ndarray  # (H,) int32
    n_labeled: jnp.ndarray       # scalar int32


def expected_entropies(
    hard_preds: jnp.ndarray,  # (N, H) int32
    posterior: jnp.ndarray,   # (H,)
    gamma: float,
    C: int,
) -> jnp.ndarray:
    """Mean posterior entropy over hypothetical class labels, per point. (N,)

    Closed form instead of a softmax per (point, class): the hypothetical
    logits take only TWO values per model — ``log w_h + log γ`` when model
    h's prediction agrees with the hypothesized class, ``log w_h`` when it
    doesn't — so with the bucketed sums

        T1[n, c] = Σ_{h: pred_h(n)=c} w_h
        T2[n, c] = Σ_{h: pred_h(n)=c} w_h·ln w_h
        W = Σ_h w_h,  L = Σ_h w_h·ln w_h,  Z = W + (γ-1)·T1

    the post-update entropy is exactly

        H(n, c) = ln Z − (L + (γ-1)·T2 + γ·ln γ·T1) / Z    [nats]

    (from q_h = w_h γ^{a_h}/Z with a_h ∈ {0,1}). T1/T2 are scatter-adds
    over the (N, H) prediction table — O(N·H) work and ~N·C
    transcendentals per round instead of the softmax's ~2·N·C·H, an H-fold
    cut in the op class that dominates both CPU suite time and VPU load.
    Same math as the softmax path; only float accumulation order differs.
    """
    N, H = hard_preds.shape
    gamma = jnp.asarray(gamma, jnp.float32)
    log_gamma = jnp.log(gamma)
    w = jnp.clip(posterior, 1e-38, None).astype(jnp.float32)
    log_w = jnp.log(w)
    wlw = w * log_w
    W = w.sum()
    L = wlw.sum()

    t1, t2 = _bucket_sums(hard_preds, w, wlw, C)

    Z = W + (gamma - 1.0) * t1                                   # (N, C)
    ent_nat = jnp.log(Z) - (L + (gamma - 1.0) * t2
                            + gamma * log_gamma * t1) / Z
    # entropy2 reports bits; the reference's expected entropy is the mean
    # over hypothetical classes (uniform)
    return ent_nat.mean(axis=-1) / jnp.log(jnp.asarray(2.0, jnp.float32))


def make_modelpicker(
    preds: jnp.ndarray,
    epsilon=DEFAULT_EPS,
    name: str = "model_picker",
) -> Selector:
    """``epsilon`` may be a Python float (baked into the program) or a
    traced jnp scalar — the suite passes the per-task tuned ε as a runtime
    argument so ONE executable serves all 26 tasks (ε enters only through
    γ = (1-ε)/ε, which flows through the entropy/update math unchanged)."""
    H, N, C = preds.shape
    traced_eps = isinstance(epsilon, jax.core.Tracer)
    if not traced_eps:
        epsilon = float(epsilon)
    gamma = (1.0 - epsilon) / epsilon
    hard_preds = preds.argmax(-1).T.astype(jnp.int32)  # (N, H)
    # points where any model disagrees with model 0 (reference :46-48)
    disagree = (hard_preds != hard_preds[:, :1]).any(axis=1)

    # Where the prediction tensor is concrete (CLI / bench path), the
    # disagreement set is static — score ONLY those points each round. This
    # is exact, not an approximation: at a full-agreement point every
    # hypothetical class shifts all model logits by the same constant, and
    # the entropy is shift-invariant, so its expected entropy is the
    # posterior's own entropy — one scalar, identical for every such point
    # (the full kernel computes the same value for all of them, equal to
    # entropy2(posterior) up to float accumulation order). Under a tracer
    # (selector built inside jit) the set isn't static; keep full scoring.
    import numpy as np

    static_cand = None
    if not isinstance(preds, jax.core.Tracer):
        idxs = np.flatnonzero(np.asarray(disagree))
        if 0 < idxs.size < N:
            static_cand = jnp.asarray(idxs, jnp.int32)
            hard_sub = hard_preds[static_cand]         # (K, H)

    def init(key):
        del key
        return ModelPickerState(
            unlabeled=jnp.ones((N,), dtype=bool),
            posterior=jnp.full((H,), 1.0 / H, dtype=jnp.float32),
            correct_counts=jnp.zeros((H,), dtype=jnp.int32),
            n_labeled=jnp.asarray(0, jnp.int32),
        )

    def select(state, key) -> SelectResult:
        if static_cand is not None:
            ent_sub = expected_entropies(hard_sub, state.posterior, gamma, C)
            h_agree = entropy2(state.posterior)
            ent = jnp.full((N,), h_agree).at[static_cand].set(ent_sub)
        else:
            ent = expected_entropies(hard_preds, state.posterior, gamma, C)
        # restrict to disagreement points when any remain unlabeled
        # (reference sets agreement entropies to +inf only if mask.any())
        cand = disagree & state.unlabeled
        cand = jnp.where(cand.any(), cand, state.unlabeled)
        idx, _ = masked_argmin_tiebreak(key, ent, cand)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=1.0 / state.unlabeled.sum().astype(jnp.float32),
            stochastic=jnp.asarray(True),
            # argmin acquisition -> negate so the recorder's higher-is-better
            # top-k convention holds
            scores=jnp.where(cand, -ent, -jnp.inf),
        )

    def update(state, idx, true_class, prob):
        del prob
        pred_i = hard_preds[idx]                      # (H,)
        agree = (pred_i == true_class).astype(jnp.float32)
        post = state.posterior * jnp.power(gamma, agree)
        post = post / post.sum()
        return ModelPickerState(
            unlabeled=state.unlabeled.at[idx].set(False),
            posterior=post,
            correct_counts=state.correct_counts + agree.astype(jnp.int32),
            n_labeled=state.n_labeled + 1,
        )

    def select_q(state, key, q: int) -> SelectResult:
        """Argmin top-q: the q lowest-expected-entropy candidates from the
        ONE closed-form scoring pass (no re-scoring between picks — the
        multiplicative posterior only moves once the batch of answers
        lands), each pick breaking its ties with its own key like the
        q=1 argmin."""
        if static_cand is not None:
            ent_sub = expected_entropies(hard_sub, state.posterior, gamma, C)
            h_agree = entropy2(state.posterior)
            ent = jnp.full((N,), h_agree).at[static_cand].set(ent_sub)
        else:
            ent = expected_entropies(hard_preds, state.posterior, gamma, C)
        cand = disagree & state.unlabeled
        cand = jnp.where(cand.any(), cand, state.unlabeled)
        prob = 1.0 / state.unlabeled.sum().astype(jnp.float32)
        keys = jax.random.split(key, q)

        def pick(carry, kt):
            taken = carry
            avail = cand & ~taken
            # a candidate set smaller than q falls back to any unlabeled
            use = jnp.where(avail.any(), avail,
                            state.unlabeled & ~taken)
            idx_t, _ = masked_argmin_tiebreak(kt, ent, use)
            return taken.at[idx_t].set(True), idx_t.astype(jnp.int32)

        _, idxs = lax.scan(pick, jnp.zeros((N,), bool), keys)
        return SelectResult(
            idx=idxs,
            prob=jnp.full((q,), prob, jnp.float32),
            stochastic=jnp.asarray(True),
            scores=jnp.where(cand, -ent, -jnp.inf),
        )

    def update_q(state, idxs, true_classes, probs):
        """One fused multiplicative update: the posterior moves by
        ``γ^(Σ_j agreement_j)`` with a single normalization (same
        posterior as q sequential updates up to float order — each
        sequential step's normalizer cancels in the product)."""
        del probs
        q = idxs.shape[0]
        pred_q = hard_preds[idxs]                     # (q, H)
        agree = (pred_q == true_classes[:, None]).astype(jnp.float32)
        a_sum = agree.sum(axis=0)                     # (H,)
        post = state.posterior * jnp.power(gamma, a_sum)
        post = post / post.sum()
        return ModelPickerState(
            unlabeled=state.unlabeled.at[idxs].set(False),
            posterior=post,
            correct_counts=state.correct_counts + a_sum.astype(jnp.int32),
            n_labeled=state.n_labeled + q,
        )

    def best(state, key):
        k_tie, k_rand = jax.random.split(key)
        idx, n_ties = masked_argmin_tiebreak(
            k_tie, -state.correct_counts.astype(jnp.float32),
            jnp.ones((H,), dtype=bool),
        )
        rand_idx = jax.random.randint(k_rand, (), 0, H)
        chose_random = (state.n_labeled == 0) | (n_ties > 1)
        return (jnp.where(state.n_labeled > 0, idx, rand_idx).astype(jnp.int32),
                chose_random)

    return Selector(
        name=name, init=init, select=select, update=update, best=best,
        select_q=select_q, update_q=update_q,
        always_stochastic=True,
        hyperparams={"epsilon": None if traced_eps else epsilon},
        # the multiplicative-weights posterior IS this method's P(best)
        # analog — exposed under the same extras key as CODA's so the
        # flight recorder's posterior digest covers both posterior methods
        extras={"get_pbest": lambda s: s.posterior},
    )
