"""Multi-host runtime: the distributed communication backend.

The reference's only distribution mechanism is SLURM job fan-out with no
inter-process communication (reference ``scripts/launch_all_methods.py:135-153``
— srun is pure job placement; there is no NCCL/MPI anywhere in its tree).
The TPU-native backend is ``jax.distributed`` + SPMD over a global mesh:

  * every host calls :func:`initialize` (coordinator address + process id,
    from flags or the TPU pod environment), after which ``jax.devices()``
    spans the whole pod slice;
  * the same jitted selector program then runs on a mesh over all global
    devices — XLA inserts the collectives (psum/all-gather for the pi-hat
    sums and P(best) normalization, a global argmax for selection), routed
    over ICI within a slice and DCN across slices;
  * there is deliberately NO hand-written send/recv layer: collective choice
    and scheduling belong to the compiler (SURVEY.md §5 "distributed
    communication backend").

Single-process runs (tests, one chip, CPU) skip initialization entirely.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host runtime; returns True if distributed mode is on.

    Arguments default to the standard environment (``JAX_COORDINATOR``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``; on TPU pods jax can infer all
    three from the metadata server, so bare ``initialize()`` works there).
    A single-process configuration is a no-op returning False.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))

    import jax

    if num_processes <= 1 and coordinator_address is None:
        return False
    # XLA's default CPU client refuses cross-process computations
    # ("Multiprocess computations aren't implemented on the CPU backend");
    # jaxlib ships a gloo-based host-side collectives implementation that
    # must be selected before the backend initializes. TPU/GPU backends
    # ignore the setting. The guard only covers jax versions that predate
    # the config option; a jaxlib built WITHOUT gloo accepts the setting
    # here and fails later, when jax.distributed.initialize (or the first
    # computation) creates the CPU client.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def is_primary() -> bool:
    """True on the process that should own logging / checkpoint writes."""
    import jax

    return jax.process_index() == 0
