from coda_tpu.parallel.mesh import (
    MODEL_AXIS,
    DATA_AXIS,
    make_mesh,
    mesh_from_spec,
    preds_sharding,
    replicated,
)
from coda_tpu.parallel.distributed import initialize, is_primary

__all__ = [
    "MODEL_AXIS",
    "DATA_AXIS",
    "make_mesh",
    "mesh_from_spec",
    "preds_sharding",
    "replicated",
    "initialize",
    "is_primary",
]
