"""Device meshes and shardings for the ``(H, N, C)`` prediction tensor.

The reference has no intra-process parallelism at all — its only concurrency
is SLURM job fan-out (reference ``scripts/launch_all_methods.py:135-153``).
The TPU-native scale story instead shards the prediction tensor itself over a
``jax.sharding.Mesh``:

  * ``data`` axis (shards N): the EIG / acquisition scoring — the hot loop —
    is embarrassingly parallel over points; each chip scores its N-shard and
    the selection argmax reduces over ICI. This is the moral analog of
    context parallelism: the "long axis" of this workload is N (up to 50k+).
  * ``model`` axis (shards H): the P(best) integral compares H Beta
    distributions through an exclusive log-CDF product — a ``psum`` of
    per-model log-CDF grids recovers the product exactly, so H can scale to
    1000+ models (the HF zero-shot pool) without replicating the tensor.

At ImageNet scale (M=500 x N=50k x C=1000 fp32 ~ 100 GB) sharding is
mandatory: no single chip's HBM can hold the tensor. All shardings are
``NamedSharding`` so the same jitted program runs on 1 chip or a full pod
with XLA inserting collectives.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"  # shards H (the candidate-model pool)
DATA_AXIS = "data"    # shards N (the unlabeled data points)


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the jax versions this repo runs on.

    Newer jax exposes it top-level with a ``check_vma`` kwarg; 0.4.x has
    ``jax.experimental.shard_map.shard_map`` with the same check named
    ``check_rep``; the releases in between promoted the function before
    renaming the kwarg — so the two drifts are detected INDEPENDENTLY
    (attribute lookup for the function, signature inspection for the
    kwarg name). Same semantics either way; this shim exists so the
    sharded pallas fast path (and the multichip dryrun that validates
    it) runs on all three eras instead of AttributeError/TypeError'ing.
    """
    import inspect

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    check_kwarg = ("check_vma"
                   if "check_vma" in inspect.signature(fn).parameters
                   else "check_rep")
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{check_kwarg: check_vma})


def make_mesh(
    data: int = 1,
    model: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """A ``(data, model)`` mesh over the first ``data*model`` devices."""
    devices = devices if devices is not None else jax.devices()
    n = data * model
    if n > len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {n} devices, have {len(devices)}"
        )
    dev_array = np.asarray(devices[:n]).reshape(data, model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def mesh_from_spec(spec: str, devices: Optional[list] = None) -> Mesh:
    """Parse ``'data=4'`` / ``'data=4,model=2'`` into a mesh."""
    sizes = {DATA_AXIS: 1, MODEL_AXIS: 1}
    for part in spec.split(","):
        k, v = part.split("=")
        k = k.strip()
        if k not in sizes:
            raise ValueError(f"unknown mesh axis {k!r} (use data/model)")
        sizes[k] = int(v)
    return make_mesh(data=sizes[DATA_AXIS], model=sizes[MODEL_AXIS],
                     devices=devices)


def preds_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the ``(H, N, C)`` tensor: H over model, N over data."""
    return NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
