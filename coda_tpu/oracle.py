"""Ground-truth oracle: label server + per-model true mean losses.

Capability parity with the reference ``Oracle`` (reference
``coda/oracle.py:2-24``): ``true_losses`` gives each model's mean loss over
the full labeled dataset; calling the oracle with an index returns the true
class of that point.

TPU-native shape: ``true_losses`` is a pure function (H, N, C) x (N,) -> (H,)
usable inside jit/scan; the class wrapper exists for the interactive
(host-driven) demo path.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from coda_tpu.losses import accuracy_loss


def true_losses(
    preds: jnp.ndarray,
    labels: jnp.ndarray,
    loss_fn: Callable = accuracy_loss,
) -> jnp.ndarray:
    """Mean loss of every model over all N points. Returns (H,) float32."""
    # loss_fn broadcasts over (H, N, C) x (N,) -> (H, N)
    return loss_fn(preds, labels[None, :]).mean(axis=1)


class Oracle:
    """Label server over a dataset with known ground truth."""

    def __init__(self, dataset, loss_fn: Callable = accuracy_loss):
        if dataset.labels is None:
            raise ValueError("Oracle needs labels!")
        self.dataset = dataset
        self.labels = dataset.labels
        self.loss_fn = loss_fn

    def true_losses(self, preds: jnp.ndarray) -> jnp.ndarray:
        return true_losses(preds, self.labels, self.loss_fn)

    def __call__(self, idx) -> int:
        return int(self.labels[idx])

    def answer_batch(self, idxs) -> list[int]:
        """All q labels of a q-wide round in ONE host sync: a single
        fancy-index gather + one ``np.asarray`` device read, instead of
        the q separate ``int(...)`` round-trips the scalar ``__call__``
        loop pays. Pinned identical to ``[self(i) for i in idxs]``."""
        import numpy as np

        idxs = np.asarray(idxs, dtype=np.int64)
        return [int(v) for v in np.asarray(self.labels)[idxs]]
