"""Consensus (ensemble) pseudo-labels and Dirichlet confusion-matrix priors.

Capability parity with reference ``coda/util.py:7-14`` (mean ensemble) and
``coda/coda.py:28-63`` (soft confusion vs. pseudo-labels; diag-favoring
prior). The confusion einsum is a batched matmul — on TPU it runs on the MXU;
precision is pinned to HIGHEST because the downstream EIG argmax ordering is
sensitive to low-precision accumulation (bf16 passes would perturb it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_PRECISION = jax.lax.Precision.HIGHEST

# Above this preds size the one-shot einsums stream the model axis in a
# fori_loop of leading-index slices instead: XLA's layout assignment
# materializes a RELAYOUT COPY of the full (H, N, C) operand for the
# h,s-contracting einsums, and at the reference's true DomainNet scale
# (9.4 GiB) two copies exceed a v5e's 16 GB HBM — the compile's memory
# planner fails outright (reproduced round 5; 7 GiB compiles, 9.4 does
# not). Leading-index slices need no relayout, and the loop's (N, C)
# accumulator is trivially small. Shared by coda.pi_unnorm.
PREDS_ONESHOT_MAX_BYTES = 4 << 30


def ensemble_preds(preds: jnp.ndarray) -> jnp.ndarray:
    """Mean prediction over models: ``(H, N, C) -> (N, C)``."""
    return preds.mean(axis=0)


def create_confusion_matrices(
    true_labels: jnp.ndarray,
    model_predictions: jnp.ndarray,
    mode: str = "hard",
) -> jnp.ndarray:
    """Row-normalized confusion matrices vs. (pseudo-)labels.

    Args:
      true_labels: ``(N,)`` int class labels (typically ensemble pseudo-labels).
      model_predictions: ``(H, N, C)`` post-softmax scores.
      mode: 'hard' uses one-hot argmax predictions; 'soft' uses the scores.
    Returns:
      ``(H, C, C)`` confusion matrices, rows normalized (floor 1e-6).
    """
    H, N, C = model_predictions.shape
    true_one_hot = jax.nn.one_hot(true_labels, C, dtype=jnp.float32)
    if mode == "hard":
        p = jax.nn.one_hot(model_predictions.argmax(-1), C, dtype=jnp.float32)
    elif mode == "soft":
        p = model_predictions
    else:
        raise ValueError(mode)
    if mode == "soft" and 4 * H * N * C > PREDS_ONESHOT_MAX_BYTES:
        # stream models: per h one (C, N) x (N, C) MXU matmul — same
        # contraction, no (H, N, C) relayout copy (see the constant above)
        # DEFAULT matmul precision: HIGH/HIGHEST contractions of a
        # ~10 GiB operand do not compile on this stack (see the coda.py
        # streamed-branch note); soft-confusion entries are row-
        # normalized sums of ~N softmax scores, ~1e-3-relative tolerant
        t = true_one_hot.T                           # (C, N)

        def body(h, acc):
            return acc.at[h].set(jnp.dot(t, p[h]))

        conf = jax.lax.fori_loop(
            0, H, body, jnp.zeros((H, C, C), jnp.float32))
    else:
        conf = jnp.einsum("nc,hnj->hcj", true_one_hot, p,
                          precision=_PRECISION)
    return conf / jnp.clip(conf.sum(-1, keepdims=True), 1e-6, None)


def initialize_dirichlets(
    soft_confusion: jnp.ndarray,
    prior_strength: float,
    disable_diag_prior: bool = False,
) -> jnp.ndarray:
    """Prior + evidence: diag-favoring base plus scaled soft confusion.

    Base is diag=1.0 / off-diag=1/(C-1), or the uniform 2/C ablation variant
    (2 pseudo-counts per row either way).
    """
    H, C, _ = soft_confusion.shape
    if disable_diag_prior:
        base = jnp.full((C, C), 2.0 / C, dtype=soft_confusion.dtype)
    else:
        base = jnp.full((C, C), 1.0 / (C - 1), dtype=soft_confusion.dtype)
        base = jnp.fill_diagonal(base, 1.0, inplace=False)
    return base[None] + prior_strength * soft_confusion
