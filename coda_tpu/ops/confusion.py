"""Consensus (ensemble) pseudo-labels and Dirichlet confusion-matrix priors.

Capability parity with reference ``coda/util.py:7-14`` (mean ensemble) and
``coda/coda.py:28-63`` (soft confusion vs. pseudo-labels; diag-favoring
prior). The confusion einsum is a batched matmul — on TPU it runs on the MXU;
precision is pinned to HIGHEST because the downstream EIG argmax ordering is
sensitive to low-precision accumulation (bf16 passes would perturb it).
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

_PRECISION = jax.lax.Precision.HIGHEST

# Above this preds size the big contractions demote to DEFAULT matmul
# precision: no HIGH/HIGHEST contraction of a ~10 GiB fp32 operand
# compiles on this TPU stack (the compile helper fails outright —
# reproduced round 5 on a v5e; ~7 GiB compiles, 9.4 GiB does not,
# einsum and per-slice-dot forms alike, while DEFAULT compiles and
# runs). The threshold sits at the measured known-good bound. The einsum
# FORM is kept at every size — it partitions under GSPMD, where each
# shard is small and keeps reference numerics.
# Shared by coda.pi_unnorm / update_pi_hat_column.
PREDS_ONESHOT_MAX_BYTES = 7 << 30

# The demotion is a workaround for that TPU-stack compile failure ONLY: on
# CPU, HIGHEST is fp32 anyway, and on GPU DEFAULT would enable tf32 and
# silently break reference-parity numerics for operands where HIGHEST
# compiles fine. Every other numerics knob in this codebase
# (eig_precision, eig_cache_dtype, eig_refresh) is opt-in; this automatic
# one stays scoped to the backend that forces it. (Module-level so tests
# can widen it to exercise the demoted path on the CPU backend.)
_DEMOTE_BACKENDS = ("tpu",)

_warned_demotion = False


def oneshot_precision(preds_bytes: int) -> jax.lax.Precision:
    """Matmul precision for a one-shot contraction of a ``preds_bytes``-big
    operand: HIGHEST everywhere except past the compile bound on the
    backends that cannot compile it (see ``PREDS_ONESHOT_MAX_BYTES``).
    Warns once per process when the demotion engages — it is the one
    automatic numerics change in the codebase."""
    global _warned_demotion
    if (preds_bytes <= PREDS_ONESHOT_MAX_BYTES
            or jax.default_backend() not in _DEMOTE_BACKENDS):
        return _PRECISION
    if not _warned_demotion:
        _warned_demotion = True
        warnings.warn(
            f"prediction tensor ({preds_bytes / (1 << 30):.1f} GiB) exceeds "
            f"the {PREDS_ONESHOT_MAX_BYTES >> 30} GiB one-shot HIGHEST-"
            "precision compile bound on this backend; demoting its big "
            "contractions (pi-hat, soft confusion) to DEFAULT matmul "
            "precision (~1e-3-relative drift). Shard the tensor over a "
            "mesh (--mesh data=K) to keep reference-parity HIGHEST.",
            stacklevel=3,
        )
    return jax.lax.Precision.DEFAULT


def ensemble_preds(preds: jnp.ndarray) -> jnp.ndarray:
    """Mean prediction over models: ``(H, N, C) -> (N, C)``."""
    return preds.mean(axis=0)


def create_confusion_matrices(
    true_labels: jnp.ndarray,
    model_predictions: jnp.ndarray,
    mode: str = "hard",
) -> jnp.ndarray:
    """Row-normalized confusion matrices vs. (pseudo-)labels.

    Args:
      true_labels: ``(N,)`` int class labels (typically ensemble pseudo-labels).
      model_predictions: ``(H, N, C)`` post-softmax scores.
      mode: 'hard' uses one-hot argmax predictions; 'soft' uses the scores.
    Returns:
      ``(H, C, C)`` confusion matrices, rows normalized (floor 1e-6).
    """
    H, N, C = model_predictions.shape
    true_one_hot = jax.nn.one_hot(true_labels, C, dtype=jnp.float32)
    if mode == "hard":
        p = jax.nn.one_hot(model_predictions.argmax(-1), C, dtype=jnp.float32)
    elif mode == "soft":
        p = model_predictions
    else:
        raise ValueError(mode)
    # DEFAULT matmul precision past the one-shot budget, TPU only: HIGH/
    # HIGHEST contractions of a ~10 GiB operand do not compile on that
    # stack (see coda.pi_unnorm); soft-confusion entries are row-normalized
    # sums of ~N softmax scores, ~1e-3-relative tolerant. The einsum FORM
    # is kept either way — it partitions under GSPMD (a streamed fori_loop
    # over the model-sharded axis blew per-device temps 6x in the 100 GB
    # AOT memory plan).
    prec = (oneshot_precision(4 * H * N * C) if mode == "soft"
            else _PRECISION)
    conf = jnp.einsum("nc,hnj->hcj", true_one_hot, p, precision=prec)
    return conf / jnp.clip(conf.sum(-1, keepdims=True), 1e-6, None)


def initialize_dirichlets(
    soft_confusion: jnp.ndarray,
    prior_strength: float,
    disable_diag_prior: bool = False,
) -> jnp.ndarray:
    """Prior + evidence: diag-favoring base plus scaled soft confusion.

    Base is diag=1.0 / off-diag=1/(C-1), or the uniform 2/C ablation variant
    (2 pseudo-counts per row either way).
    """
    H, C, _ = soft_confusion.shape
    if disable_diag_prior:
        base = jnp.full((C, C), 2.0 / C, dtype=soft_confusion.dtype)
    else:
        base = jnp.full((C, C), 1.0 / (C - 1), dtype=soft_confusion.dtype)
        base = jnp.fill_diagonal(base, 1.0, inplace=False)
    return base[None] + prior_strength * soft_confusion
