"""Consensus (ensemble) pseudo-labels and Dirichlet confusion-matrix priors.

Capability parity with reference ``coda/util.py:7-14`` (mean ensemble) and
``coda/coda.py:28-63`` (soft confusion vs. pseudo-labels; diag-favoring
prior). The confusion einsum is a batched matmul — on TPU it runs on the MXU;
precision is pinned to HIGHEST because the downstream EIG argmax ordering is
sensitive to low-precision accumulation (bf16 passes would perturb it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_PRECISION = jax.lax.Precision.HIGHEST

# Above this preds size the big contractions demote to DEFAULT matmul
# precision: no HIGH/HIGHEST contraction of a ~10 GiB fp32 operand
# compiles on this TPU stack (the compile helper fails outright —
# reproduced round 5 on a v5e; ~7 GiB compiles, 9.4 GiB does not,
# einsum and per-slice-dot forms alike, while DEFAULT compiles and
# runs). The einsum FORM is kept at every size — it partitions under
# GSPMD, where each shard is small and keeps reference numerics.
# Shared by coda.pi_unnorm / update_pi_hat_column.
PREDS_ONESHOT_MAX_BYTES = 4 << 30


def ensemble_preds(preds: jnp.ndarray) -> jnp.ndarray:
    """Mean prediction over models: ``(H, N, C) -> (N, C)``."""
    return preds.mean(axis=0)


def create_confusion_matrices(
    true_labels: jnp.ndarray,
    model_predictions: jnp.ndarray,
    mode: str = "hard",
) -> jnp.ndarray:
    """Row-normalized confusion matrices vs. (pseudo-)labels.

    Args:
      true_labels: ``(N,)`` int class labels (typically ensemble pseudo-labels).
      model_predictions: ``(H, N, C)`` post-softmax scores.
      mode: 'hard' uses one-hot argmax predictions; 'soft' uses the scores.
    Returns:
      ``(H, C, C)`` confusion matrices, rows normalized (floor 1e-6).
    """
    H, N, C = model_predictions.shape
    true_one_hot = jax.nn.one_hot(true_labels, C, dtype=jnp.float32)
    if mode == "hard":
        p = jax.nn.one_hot(model_predictions.argmax(-1), C, dtype=jnp.float32)
    elif mode == "soft":
        p = model_predictions
    else:
        raise ValueError(mode)
    # DEFAULT matmul precision past the one-shot budget: HIGH/HIGHEST
    # contractions of a ~10 GiB operand do not compile on this stack (see
    # coda.pi_unnorm); soft-confusion entries are row-normalized sums of
    # ~N softmax scores, ~1e-3-relative tolerant. The einsum FORM is kept
    # either way — it partitions under GSPMD (a streamed fori_loop over
    # the model-sharded axis blew per-device temps 6x in the 100 GB AOT
    # memory plan).
    prec = (None if mode == "soft" and 4 * H * N * C
            > PREDS_ONESHOT_MAX_BYTES else _PRECISION)
    conf = jnp.einsum("nc,hnj->hcj", true_one_hot, p, precision=prec)
    return conf / jnp.clip(conf.sum(-1, keepdims=True), 1e-6, None)


def initialize_dirichlets(
    soft_confusion: jnp.ndarray,
    prior_strength: float,
    disable_diag_prior: bool = False,
) -> jnp.ndarray:
    """Prior + evidence: diag-favoring base plus scaled soft confusion.

    Base is diag=1.0 / off-diag=1/(C-1), or the uniform 2/C ablation variant
    (2 pseudo-counts per row either way).
    """
    H, C, _ = soft_confusion.shape
    if disable_diag_prior:
        base = jnp.full((C, C), 2.0 / C, dtype=soft_confusion.dtype)
    else:
        base = jnp.full((C, C), 1.0 / (C - 1), dtype=soft_confusion.dtype)
        base = jnp.fill_diagonal(base, 1.0, inplace=False)
    return base[None] + prior_strength * soft_confusion
