"""Pallas TPU kernel for the delta pi-hat gather.

The delta pi-hat refresh (``update_pi_hat_column_delta``) needs
``(N,) = Σ_h preds_by_class[s_h, h, :]`` — one contiguous N-row per model,
picked by that model's hard prediction ``s_h`` at the freshly-labeled item.
That is O(H·N) bytes (0.2 GB at headline), a C-fold traffic cut over the
exact column einsum's full-tensor stream — but XLA lowers the
take-along-axis gather at ~28 GB/s effective on a v5e (7.1 ms, measured
round 4), SLOWER than streaming all 2 GB through the MXU (2.8 ms). This
kernel issues the row reads as explicit double-buffered DMAs from HBM
(scalar-prefetched row indices, ``make_async_copy`` per model row) and
accumulates in VMEM: the gather runs at DMA bandwidth instead of XLA's
scalar-gather lowering.

Layout contract: the source must be pre-flattened ONCE (a loop constant —
:func:`prep_gather_layout`) to ``(C·H, 1, Np)`` with N lane-padded to Np.
A direct ``(1, 1, N)`` slice of the natural (C, H, N) tensor is rejected
by Mosaic — the HBM buffer is (8, 128)-tiled over its two minor dims, and
a size-1 slice of the sublane (H) dim violates the tiling ("Slice shape
along dimension 1 must be aligned to tiling (8)", observed on a v5e). In
the flat layout the sliced axis is the LEADING dim (unconstrained), the
size-1 sublane dim spans its axis, and every row sits at a lane-aligned
offset.

Single-tile over N: the row buffers (2 DMA slots + accumulator + out) must
fit VMEM, which caps Np at ``_MAX_TILE_N`` (~0.5M lanes = 4 x 2 MB).
Incremental caches put N far below that at any C·H the tier accepts;
beyond the cap ``resolve_pi_update`` keeps the exact einsum instead. On
non-TPU backends the XLA path is both the fast one and the default; the
kernel runs in interpret mode only under tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MAX_TILE_N = 1 << 19  # lanes: 2 DMA slots + acc + out at fp32 ~ 8 MB VMEM


def gather_rows_sum_xla(preds_by_class: jnp.ndarray,
                        pred_classes: jnp.ndarray) -> jnp.ndarray:
    """The XLA lowering over the natural (C, H, N) layout: take-along-axis
    + sum. Fast on CPU; the vmap and non-TPU fallback."""
    sel = jnp.take_along_axis(
        preds_by_class, pred_classes[None, :, None], axis=0
    )[0]                                              # (H, N)
    return sel.sum(0)


def prep_gather_layout(preds_by_class: jnp.ndarray) -> jnp.ndarray:
    """(C, H, N) -> (C·H, 1, Np) DMA-sliceable layout (build ONCE per
    experiment, outside the scan step — it copies the whole tensor)."""
    C, H, N = preds_by_class.shape
    Np = -(-N // 128) * 128
    return jnp.pad(
        preds_by_class, ((0, 0), (0, 0), (0, Np - N))
    ).reshape(C * H, 1, Np)


def _gather_kernel(s_ref, src_ref, out_ref, scratch, sems):
    """Double-buffered row gather-accumulate, one grid step.

    s (H,) int32 scalar-prefetch; src (C·H, 1, Np) stays in HBM (pl.ANY);
    scratch (2, 1, Np) VMEM slots; out (1, Np). Row h lives at flat index
    ``s_h · H + h``.
    """
    H = s_ref.shape[0]

    def row_copy(h, slot):
        return pltpu.make_async_copy(
            src_ref.at[s_ref[h] * H + h], scratch.at[slot], sems.at[slot])

    row_copy(0, 0).start()

    def body(h, acc):
        slot = h % 2

        @pl.when(h + 1 < H)
        def _():
            row_copy(h + 1, (h + 1) % 2).start()

        row_copy(h, slot).wait()
        return acc + scratch[slot]

    out_ref[:] = lax.fori_loop(
        0, H, body, jnp.zeros(out_ref.shape, out_ref.dtype))


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def gather_rows_sum_prepped(
    flat: jnp.ndarray,            # (C·H, 1, Np) from prep_gather_layout
    pred_classes: jnp.ndarray,    # (H,) int32 — per-model hard pred at idx
    n: int,                       # the true (unpadded) N
    interpret: bool | None = None,
) -> jnp.ndarray:
    """(n,) sum of one row per model, row ``pred_classes[h]·H + h`` of the
    flat layout. DMA-gather kernel on a real TPU (interpret elsewhere);
    under vmap (suite seed batches) the XLA path over a reshaped view —
    a batched pallas call would multiply the DMA count, not the row size.
    """
    CH, _, Np = flat.shape
    H = pred_classes.shape[0]
    if interpret is None:  # Mosaic compiles only on real TPUs
        interpret = jax.default_backend() != "tpu"

    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(flat, pred_classes):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((1, Np), lambda i, s: (0, 0)),
            scratch_shapes=[pltpu.VMEM((2, 1, Np), jnp.float32),
                            pltpu.SemaphoreType.DMA((2,))],
        )
        out = pl.pallas_call(
            _gather_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((1, Np), jnp.float32),
            interpret=interpret,
        )(pred_classes, flat)
        return out[0, :n]

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, flat_b, s_b):
        in_axes = [0 if b else None for b in in_batched]

        def one(flat, s):
            by_class = flat.reshape(CH // H, H, Np)[:, :, :n]
            return gather_rows_sum_xla(by_class, s)

        out = jax.vmap(one, in_axes=in_axes)(flat_b, s_b)
        return out, True

    return _call(flat, pred_classes)
