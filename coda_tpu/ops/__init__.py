from coda_tpu.ops.beta import beta_log_pdf, cumtrapz_uniform, dirichlet_to_beta
from coda_tpu.ops.pbest import compute_pbest, pbest_row_mixture
from coda_tpu.ops.confusion import (
    create_confusion_matrices,
    ensemble_preds,
    initialize_dirichlets,
)
from coda_tpu.ops.masked import (
    entropy2,
    masked_argmax_tiebreak,
    masked_argmin_tiebreak,
    masked_categorical,
)

__all__ = [
    "beta_log_pdf",
    "cumtrapz_uniform",
    "dirichlet_to_beta",
    "compute_pbest",
    "pbest_row_mixture",
    "create_confusion_matrices",
    "ensemble_preds",
    "initialize_dirichlets",
    "entropy2",
    "masked_argmax_tiebreak",
    "masked_argmin_tiebreak",
    "masked_categorical",
]
