"""Sparse top-K class-row Dirichlet posteriors — the large-C state tier.

The dense CODA posterior is ``(H, C, C)`` — 2 GB at ImageNet scale
(H=500, C=1000 fp32), carried through every ``lax.scan`` round even
though a labeling round touches ONE class row per model and real
confusion mass concentrates on a few classes per row
(``IMAGENET_VIRTUAL_r05.json``: the dense state and its scan copies, not
the EIG tables, dominate the 736-1207 s rounds). Here each class row
keeps

  * its **diagonal** entry exactly (``diag``, (H, C)) — the parameter the
    Beta/EIG quadrature actually consumes,
  * its **top-K off-diagonal** entries as values + int32 column indices
    (``vals``/``idx``, (H, C, K)),
  * one shared **residual** mass for the untracked remainder
    (``resid``, (H, C)), spread uniformly over the ``C-1-K`` untracked
    columns when a dense row must be reconstructed.

Total: ``(2K+2)/C`` of the dense state (K=32, C=1000 -> ~15x smaller).
Because every update conserves row mass exactly (tracked adds are exact;
an untracked add moves its uniform share out of the residual and either
evicts the smallest tracked entry back into it or returns the whole
increment), the diagonal AND the row's total off-diagonal mass — the two
numbers ``dirichlet_to_beta`` reduces a row to — stay exact up to float
summation order. The EIG quadrature therefore sees the same Betas as the
dense path; only consumers of off-diagonal *structure* (the exact pi-hat
column einsum, which :func:`densify_row` serves with the share-spread
reconstruction) are approximated. With the default bandwidth-lean
``pi_update='delta'`` path (which never reads the posterior) the sparse
tier tracks dense to summation-order ulps — far inside the documented
2.34e-4 score contract.

**Parity layout** (``K >= C``): ``vals`` stores the full dense rows
(diagonal included at its column position), ``idx`` is the identity and
``resid`` is zero. Updates then apply the same float ops to the same
values as the dense ``.at[:, c, :].add`` path, so ``sparse:K=C`` is
bitwise-equal to dense — the tier-1 parity rung, not a compression.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from coda_tpu.ops.beta import sparse_rows_to_beta


class SparseRows(NamedTuple):
    """Sparse class-row posterior state (a pytree; scan-carry friendly:
    a labeling round DUSes one row of each leaf)."""

    diag: jnp.ndarray   # (H, C) f32 — exact diagonal concentrations
    vals: jnp.ndarray   # (H, C, K) f32 — top-K off-diag (K=C: full rows)
    idx: jnp.ndarray    # (H, C, K) int32 — their column indices
    resid: jnp.ndarray  # (H, C) f32 — untracked off-diag mass (K=C: zero)

    @property
    def n_classes(self) -> int:
        return self.diag.shape[-1]

    @property
    def k(self) -> int:
        return self.vals.shape[-1]

    @property
    def full(self) -> bool:
        """The K=C parity layout (vals = dense rows, diagonal included)."""
        return self.k == self.n_classes


def parse_posterior(spec: str) -> Optional[int]:
    """``'dense'`` -> None; ``'sparse:K'`` -> K (>= 1). Fails loudly on
    anything else — the CLI forwards the string verbatim."""
    if spec == "dense":
        return None
    if spec.startswith("sparse:"):
        try:
            k = int(spec.split(":", 1)[1])
        except ValueError:
            k = 0
        if k >= 1:
            return k
    raise ValueError(
        f"unknown posterior {spec!r} (use 'dense' or 'sparse:K' with "
        "integer K >= 1, e.g. 'sparse:32')")


def posterior_nbytes(H: int, C: int, k: Optional[int]) -> int:
    """Resident bytes of the posterior representation (the term the auto
    eig_mode budget charges — dense is the (H, C, C) fp32 tensor, sparse
    is diag + resid + K (value, index) pairs per row)."""
    if k is None:
        return 4 * H * C * C
    k_eff = min(k, C)
    return H * C * (8 + 8 * k_eff)


def sparsify(dirichlets: jnp.ndarray, k: int) -> SparseRows:
    """Compress a dense ``(H, C, C)`` posterior into :class:`SparseRows`.

    ``k >= C`` selects the parity layout (no truncation). Otherwise the
    top-``k`` off-diagonal entries per row are kept exactly and the
    remainder is folded into the residual, so row totals are preserved.
    """
    H, C, _ = dirichlets.shape
    if k >= C:
        return SparseRows(
            diag=jnp.diagonal(dirichlets, axis1=-2, axis2=-1),
            vals=dirichlets,
            idx=jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32),
                                 (H, C, C)),
            resid=jnp.zeros((H, C), dirichlets.dtype),
        )
    k = min(k, C - 1)
    diag = jnp.diagonal(dirichlets, axis1=-2, axis2=-1)        # (H, C)
    eye = jnp.eye(C, dtype=bool)
    offdiag = jnp.where(eye, -jnp.inf, dirichlets)
    vals, idx = jax.lax.top_k(offdiag, k)                      # (H, C, k)
    resid = dirichlets.sum(-1) - diag - vals.sum(-1)
    return SparseRows(diag=diag, vals=vals, idx=idx.astype(jnp.int32),
                      resid=resid)


def to_beta(s: SparseRows) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(a_cc, b_cc)`` each (H, C) — the compact-row analog of
    ``ops.beta.dirichlet_to_beta``, reading O(H*C*K) instead of the dense
    O(H*C*C)."""
    return sparse_rows_to_beta(s.diag, s.vals, s.resid,
                               includes_diag=s.full)


def row_beta(s: SparseRows, c: jnp.ndarray
             ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(a_t, b_t)`` each (H,) for class row ``c`` — the per-round Beta
    extraction, O(H*K) bytes instead of the dense path's full (H, C, C)
    reduction (the dominant posterior read at large C)."""
    a_t = jnp.take(s.diag, c, axis=1)                          # (H,)
    rv = jnp.take(s.vals, c, axis=1)                           # (H, K)
    if s.full:
        return a_t, rv.sum(-1) - a_t
    return a_t, rv.sum(-1) + jnp.take(s.resid, c, axis=1)


def _scatter_into_row(dcol, rv, ri, r, true_class, pred_classes, lr: float,
                      C: int, K: int, w=None):
    """The per-row scatter core on COMPACT row leaves: ``(dcol (H,),
    rv (H, K), ri (H, K), r (H,))`` -> the same four, updated. Shared by
    the single-row :func:`scatter_row` and the multi-row
    :func:`scatter_rows` so the eviction/mass choreography can never
    drift between them (the float ops are exactly the pre-refactor
    single-row body's).

    ``w`` is an optional traced scalar reliability weight: the effective
    increment becomes ``lr * w``. ``w=None`` is a static Python branch
    using the float ``lr`` directly — the pre-weighting jaxpr, so the
    clean ladder cannot drift. ``w=1`` multiplies by 1.0 (bitwise
    identity); ``w=0`` must be a STRUCTURAL no-op, so the untracked
    insert (which would otherwise evict a tracked entry on the strength
    of the residual share alone) is gated on ``w > 0``.
    """
    H = dcol.shape[0]
    eff = lr if w is None else lr * w
    is_diag = pred_classes == true_class                       # (H,)
    hit = ri == pred_classes[:, None]                          # (H, K)
    tracked = hit & (~is_diag)[:, None]
    rv1 = rv + eff * tracked.astype(rv.dtype)
    hit_any = hit.any(-1)

    n_untracked = C - 1 - K                                    # static
    share = r / max(n_untracked, 1)
    v_new = share + eff
    m_pos = jnp.argmin(rv, axis=-1)                            # (H,)
    m_val = jnp.take_along_axis(rv, m_pos[:, None], axis=-1)[:, 0]
    miss = (~is_diag) & (~hit_any) if n_untracked > 0 else jnp.zeros(
        (H,), bool)
    insert = miss & (v_new > m_val)
    if w is not None:
        insert = insert & (w > 0)
    sel = insert[:, None] & (jnp.arange(K) == m_pos[:, None])  # (H, K)
    rv2 = jnp.where(sel, v_new[:, None], rv1)
    ri2 = jnp.where(sel, pred_classes[:, None], ri)
    # residual: evicted entry in, departed share out; or absorb the whole
    # increment when the new entry would not rank
    r2 = r + jnp.where(insert, m_val - share,
                       jnp.where(miss, eff, 0.0))
    diag1 = dcol + eff * is_diag.astype(dcol.dtype)
    return diag1, rv2, ri2, r2


def scatter_row(s: SparseRows, true_class: jnp.ndarray,
                pred_classes: jnp.ndarray, lr: float,
                weight=None) -> SparseRows:
    """One labeling round: add ``lr`` at ``(h, true_class, pred_classes[h])``
    for every model h — the sparse analog of the dense
    ``dirichlets.at[:, true_class, :].add(lr * onehot)``.

    Tracked columns (and the diagonal) take the increment exactly. An
    untracked column takes its uniform residual share out, adds ``lr``,
    and is inserted by EVICTING the smallest tracked entry back into the
    residual — unless it still would not rank, in which case the whole
    increment is absorbed by the residual. Row mass is conserved by every
    branch, so the row's Beta reduction stays exact (see module doc).

    ``weight`` (optional traced scalar) scales the increment to
    ``lr * weight`` — the reliability-weighted crowd update. ``None`` is
    a static branch reproducing the unweighted jaxpr; ``1.0`` is bitwise
    the exact update; ``0.0`` is a structural no-op (see
    :func:`_scatter_into_row`).
    """
    H, C = s.diag.shape
    K = s.k
    rv = jnp.take(s.vals, true_class, axis=1)                  # (H, K)
    dcol = jnp.take(s.diag, true_class, axis=1)                # (H,)
    eff = lr if weight is None else lr * weight

    if s.full:
        # parity layout: the same float add at the same position the
        # dense one-hot path performs (adding lr*0.0 elsewhere is a
        # bitwise no-op on positive concentrations)
        onehot = jax.nn.one_hot(pred_classes, C, dtype=rv.dtype)
        rv1 = rv + eff * onehot
        diag1 = dcol + eff * jnp.take(onehot, true_class, axis=1)
        return s._replace(vals=s.vals.at[:, true_class, :].set(rv1),
                          diag=s.diag.at[:, true_class].set(diag1))

    ri = jnp.take(s.idx, true_class, axis=1)                   # (H, K)
    r = jnp.take(s.resid, true_class, axis=1)                  # (H,)
    diag1, rv2, ri2, r2 = _scatter_into_row(
        dcol, rv, ri, r, true_class, pred_classes, lr, C, K, w=weight)
    return SparseRows(
        diag=s.diag.at[:, true_class].set(diag1),
        vals=s.vals.at[:, true_class, :].set(rv2),
        idx=s.idx.at[:, true_class, :].set(ri2),
        resid=s.resid.at[:, true_class].set(r2),
    )


def scatter_rows(s: SparseRows, true_classes: jnp.ndarray,
                 pred_classes: jnp.ndarray, lr: float,
                 weights=None) -> SparseRows:
    """One FUSED multi-row scatter: ``q`` oracle answers applied in a
    single pass — ``true_classes`` (q,) int32, ``pred_classes`` (q, H)
    int32 (each answer's per-model hard predictions). The batched analog
    of calling :func:`scatter_row` q times, with ONE gather of the
    touched rows' compact leaves up front; all chained row arithmetic
    runs on those compact (q, H, K) copies, and only the final per-row
    results are written back to the carry.

    Within-batch collisions (two answers landing on the same class row)
    are SEQUENCED: answer j's row update starts from the result of the
    latest j' < j with the same ``true_class`` (chained on the compact
    gathered copies — q is static and small, so the chain unrolls), and
    the write-back keeps only each row's LAST result. Every chained step
    runs the exact :func:`_scatter_into_row` core, so per-row mass
    conservation — and therefore the Beta reduction the EIG quadrature
    consumes — holds for the batch exactly as for q sequential rounds.

    ``weights`` (optional (q,) traced) scales answer j's increment to
    ``lr * weights[j]`` — the per-answer reliability weights of the
    crowd-oracle update. ``None`` reproduces the unweighted jaxpr;
    all-ones is bitwise the exact update; a zero weight is a structural
    no-op for its answer.
    """
    q = int(true_classes.shape[0])
    if q == 1:
        return scatter_row(s, true_classes[0], pred_classes[0], lr,
                           weight=None if weights is None else weights[0])
    H, C = s.diag.shape
    K = s.k

    if s.full:
        # parity layout: one scatter-add of all q one-hot increments
        # (duplicate rows accumulate — addition is the whole update)
        onehot = jax.nn.one_hot(pred_classes, C, dtype=s.vals.dtype)  # (q,H,C)
        if weights is None:
            inc = lr * jnp.transpose(onehot, (1, 0, 2))
            diag_inc = lr * (pred_classes == true_classes[:, None]).astype(
                s.diag.dtype)                                  # (q, H)
        else:
            eff = lr * weights                                 # (q,)
            inc = jnp.transpose(eff[:, None, None] * onehot, (1, 0, 2))
            diag_inc = eff[:, None] * (
                pred_classes == true_classes[:, None]).astype(s.diag.dtype)
        vals = s.vals.at[:, true_classes, :].add(inc)
        diag = s.diag.at[:, true_classes].add(diag_inc.T)
        return s._replace(vals=vals, diag=diag)

    # one gather of the q touched rows' compact leaves
    dcols = jnp.take(s.diag, true_classes, axis=1).T           # (q, H)
    rvs = jnp.moveaxis(jnp.take(s.vals, true_classes, axis=1), 1, 0)
    ris = jnp.moveaxis(jnp.take(s.idx, true_classes, axis=1), 1, 0)
    rs = jnp.take(s.resid, true_classes, axis=1).T             # (q, H)
    outs = []                                                  # per-answer
    for j in range(q):
        dcol, rv, ri, r = dcols[j], rvs[j], ris[j], rs[j]
        # chain duplicates: start from the latest earlier answer that
        # touched this row (same-row collision sequencing)
        for j2 in range(j):
            same = true_classes[j] == true_classes[j2]
            d2, rv2_, ri2_, r2_ = outs[j2]
            dcol = jnp.where(same, d2, dcol)
            rv = jnp.where(same, rv2_, rv)
            ri = jnp.where(same, ri2_, ri)
            r = jnp.where(same, r2_, r)
        outs.append(_scatter_into_row(
            dcol, rv, ri, r, true_classes[j], pred_classes[j], lr, C, K,
            w=None if weights is None else weights[j]))
    # write-back, earliest first so a duplicated row keeps its LAST result
    diag, vals, idx, resid = s.diag, s.vals, s.idx, s.resid
    for j in range(q):
        d1, rv1, ri1, r1 = outs[j]
        tc = true_classes[j]
        diag = diag.at[:, tc].set(d1)
        vals = vals.at[:, tc, :].set(rv1)
        idx = idx.at[:, tc, :].set(ri1)
        resid = resid.at[:, tc].set(r1)
    return SparseRows(diag=diag, vals=vals, idx=idx, resid=resid)


def densify_row(s: SparseRows, c: jnp.ndarray) -> jnp.ndarray:
    """Dense ``(H, C)`` reconstruction of class row ``c`` — tracked
    entries exact, untracked columns at the uniform residual share (the
    input the exact pi-hat column refresh consumes in sparse mode)."""
    H, C = s.diag.shape
    rv = jnp.take(s.vals, c, axis=1)                           # (H, K)
    if s.full:
        return rv
    ri = jnp.take(s.idx, c, axis=1)
    r = jnp.take(s.resid, c, axis=1)
    share = r / max(C - 1 - s.k, 1)
    row = jnp.broadcast_to(share[:, None], (H, C))
    row = jax.vmap(lambda rr, vv, ii: rr.at[ii].set(vv))(row, rv, ri)
    cols = jnp.arange(C)
    return jnp.where(cols[None, :] == c, jnp.take(s.diag, c, axis=1)[:, None],
                     row)


def densify(s: SparseRows) -> jnp.ndarray:
    """Full dense ``(H, C, C)`` reconstruction (tests/debugging only —
    defeats the point in production)."""
    C = s.n_classes
    rows = [densify_row(s, jnp.asarray(c)) for c in range(C)]
    return jnp.stack(rows, axis=1)


def state_nbytes(s: SparseRows) -> int:
    """Actual resident bytes of a concrete sparse state."""
    return sum(int(np_leaf.size) * np_leaf.dtype.itemsize
               for np_leaf in jax.tree_util.tree_leaves(s))
