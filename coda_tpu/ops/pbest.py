"""P(model h is best) via the Beta order-statistic integral.

The probability that model h's (Beta-distributed) per-class accuracy exceeds
every other model's is

    P(h best) = ∫ pdf_h(x) * Π_{h'≠h} cdf_{h'}(x) dx,

evaluated numerically on a fixed 256-point grid and normalized (capability
parity with reference ``coda/coda.py:77-119``, including its numeric
choreography: grid endpoints 1e-6, cdf floor 1e-30, ±80 clamp on the
exclusive log-product, trapezoid quadrature). The reference's serial
256-iteration CDF loop is replaced by a parallel cumulative trapezoid
(``cumtrapz_uniform``) so the whole kernel is a few fused elementwise passes
plus reductions — ideal for XLA on TPU. All math is fp32.
"""

from __future__ import annotations

import jax.numpy as jnp

from coda_tpu.ops.beta import beta_log_pdf, cumtrapz_uniform, dirichlet_to_beta
from coda_tpu.utils.checks import jit_check_finite

NUM_POINTS = 256  # integration grid size (reference coda/coda.py:79)
_EPS = 1e-30
_LOG_CLAMP = 80.0
_GRID_LO = 1e-6


def pbest_grid(num_points: int = NUM_POINTS) -> jnp.ndarray:
    """The fixed integration grid in (0, 1)."""
    return jnp.linspace(_GRID_LO, 1.0 - _GRID_LO, num_points, dtype=jnp.float32)


def compute_pbest(
    alpha: jnp.ndarray,
    beta: jnp.ndarray,
    num_points: int = NUM_POINTS,
    eps: float = _EPS,
) -> jnp.ndarray:
    """P(h best) over the last axis H of Beta parameters.

    Args:
      alpha, beta: ``(..., H)`` Beta parameters — one distribution per model,
        compared against each other along the last axis.
    Returns:
      ``(..., H)`` normalized probabilities that each model is best.
    """
    x = pbest_grid(num_points)  # (P,)
    dx = x[1] - x[0]

    # (..., H, P) log-pdf on the grid
    logpdf = beta_log_pdf(x, alpha[..., None], beta[..., None])
    pdf = jnp.exp(logpdf)
    jit_check_finite(pdf, "pbest.pdf")

    cdf = cumtrapz_uniform(pdf, dx, axis=-1)
    log_cdf = jnp.log(jnp.clip(cdf, eps, None))

    # exclusive product over models, in log space, clamped like the reference
    # to avoid inf when many tiny cdfs multiply (coda/coda.py:104-107)
    log_prod_excl = jnp.clip(
        log_cdf.sum(axis=-2, keepdims=True) - log_cdf, -_LOG_CLAMP, _LOG_CLAMP
    )
    integrand = pdf * jnp.exp(log_prod_excl)
    jit_check_finite(integrand, "pbest.integrand")

    prob = jnp.trapezoid(integrand, x, axis=-1)  # (..., H)
    prob = prob / jnp.clip(prob.sum(axis=-1, keepdims=True), eps, None)
    jit_check_finite(prob, "pbest.normalized")
    return prob


def pbest_row_mixture(
    dirichlets: jnp.ndarray,
    pi_hat: jnp.ndarray,
    num_points: int = NUM_POINTS,
) -> jnp.ndarray:
    """Marginal P(h best) under the estimated class prior.

    Args:
      dirichlets: ``(..., H, C, C)`` per-model Dirichlet confusion posteriors.
      pi_hat: ``(C,)`` estimated marginal class distribution.
    Returns:
      ``(..., H)``: ``Σ_c P(h best | class c) * pi_hat(c)`` (reference
      ``coda/coda.py:122-147``).
    """
    alpha_cc, beta_cc = dirichlet_to_beta(dirichlets)  # (..., H, C)
    # compare models per class-row: move H to the last axis -> (..., C, H)
    a = jnp.swapaxes(alpha_cc, -1, -2)
    b = jnp.swapaxes(beta_cc, -1, -2)
    prob_best_per_row = compute_pbest(a, b, num_points=num_points)  # (..., C, H)
    return (prob_best_per_row * pi_hat[..., :, None]).sum(axis=-2)  # (..., H)
