"""Pallas TPU kernel for the incremental-EIG scoring pass.

The incremental CODA selector scores a round by streaming the cached
``(N, C, H)`` hypothetical-P(best) tensor once and reducing it to ``(N,)``
expected-entropy drops (see ``coda_tpu.selectors.coda.eig_scores_from_cache``
— identical math). At the headline config the cache is 2 GB, so the pass is
HBM-bandwidth-bound; this kernel tiles N into VMEM-resident blocks and fuses
the whole chain — mixture delta, clamp, log2 entropy, class mixture — into
one read of each cache element, with no intermediate (B, C, H) tensors ever
returning to HBM.

The jnp reference path remains the default everywhere; the kernel is opt-in
via ``CODAHyperparams(eig_backend="pallas")`` / ``--eig-backend pallas``. On
non-TPU backends it runs in interpreter mode (tests exercise it on CPU).
Single-device only: ``pallas_call`` is an opaque custom call that GSPMD
cannot partition, so ``make_coda`` rejects the combination of this backend
with a multi-device-sharded prediction tensor.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENTROPY_FLOOR = 1e-12  # reference clamp, see ops/masked.py entropy2


def _score_block_kernel(mixture0_ref, h_before_ref, pi_hat_ref, rows_ref,
                        hyp_ref, pi_xi_ref, out_ref):
    """One N-tile: (B, C, H) cache block -> (B, 1) scores.

    Refs: mixture0 (1, H); h_before (1, 1); pi_hat (1, C); rows (C, H);
    hyp (B, C, H); pi_xi (B, C); out (B, 1) — 2-D so the N-tile only needs
    sublane (x8) alignment, not the x128 lane alignment a 1-D out would.
    """
    mixture0 = mixture0_ref[0, :]                    # (H,)
    pi_hat = pi_hat_ref[0, :]                        # (C,)
    # storage may be bf16 (eig_cache_dtype); all math runs fp32
    hyp = hyp_ref[:].astype(mixture0.dtype)          # (B, C, H)
    delta = hyp - rows_ref[:][None]                  # (B, C, H)
    mix = mixture0[None, None, :] + pi_hat[None, :, None] * delta
    p = jnp.maximum(mix, _ENTROPY_FLOOR)
    h_after = -(p * (jnp.log(p) * 1.4426950408889634)).sum(axis=-1)  # (B, C)
    scores = h_before_ref[0, 0] - (pi_xi_ref[:] * h_after).sum(axis=-1)
    out_ref[:] = scores[:, None]


_SCOPED_VMEM_BYTES = 16 << 20  # Mosaic's default scoped-vmem limit
_VMEM_MARGIN_BYTES = 1 << 20   # stack + the single-buffered broadcast refs
# the pipelined grid operands (hyp tile, pi_xi tile, out tile) are DOUBLE-
# buffered by pallas; the budget below models 2x their padded footprint.
# First hardware run (round 4) proved the point: an 8 MB tile target that
# ignored double buffering landed at 16.12 MB scoped — 128.5 KB over the
# 16 MB limit (2x8 MB hyp + 2x64 KB padded out + small refs), and Mosaic
# refused to compile.
_VMEM_TILE_BYTES = (_SCOPED_VMEM_BYTES - _VMEM_MARGIN_BYTES) // 2


def _padded_row_bytes(C: int, H: int, itemsize: int = 4) -> int:
    """Physical VMEM bytes of ONE N-row of the (B, C, H) cache tile.

    Mosaic lays vector memory out in (8, 128) fp32 / (16, 128) bf16 tiles
    over the two minor dims, so a (C, H) slice occupies
    ceil(C/sub)*sub x ceil(H/128)*128 elements regardless of the logical
    shape — at the headline (C=10, H=1000) fp32 that is 16 x 1024 = 1.6x
    the logical bytes. Budgeting with logical sizes would overshoot VMEM
    by exactly that factor on the first hardware run.
    """
    sub = 16 if itemsize == 2 else 8
    Cp = -(-C // sub) * sub
    Hp = -(-H // 128) * 128
    return itemsize * Cp * Hp


def choose_block(N: int, C: int, H: int, block: int = 0,
                 itemsize: int = 4) -> int:
    """The N-tile size: sublane-aligned (x8) under the VMEM budget, or all
    of N when it fits — the two shapes Mosaic accepts for the (B, C) /
    (B, 1) blocks without host-padding the cache. The budget is computed
    against the PADDED physical tile (see :func:`_padded_row_bytes`) at
    the cache's ``itemsize``. The x8 hardware minimum wins over a smaller
    caller ``block`` cap (a cap below 8 cannot lower the tile's VMEM
    footprint further)."""
    # budget against the FP32 COMPUTE footprint even for bf16 storage: the
    # kernel upcasts the whole tile (delta/mix/entropy run fp32), so a
    # bf16-sized cap would double B and blow VMEM on hardware — bf16's win
    # is the halved HBM stream, not a bigger tile
    # pi_xi (B, C) and out (B, 1) rows, padded to the 128-lane minor dim
    xi_row = 4 * (-(-C // 128) * 128)
    out_row = 4 * 128
    per_row = _padded_row_bytes(C, H, max(itemsize, 4)) + xi_row + out_row
    vmem_cap = max(8, _VMEM_TILE_BYTES // max(1, per_row))
    cap = min(block, vmem_cap) if block else vmem_cap
    if N <= max(cap, 8):
        return N
    return max(8, (cap // 8) * 8)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def eig_scores_cache_pallas(
    pbest_rows: jnp.ndarray,   # (C, H)
    pbest_hyp: jnp.ndarray,    # (N, C, H)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    block: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """(N,) EIG scores from the incremental cache, fused in one HBM pass.

    Matches ``eig_scores_from_cache`` numerics: same mixture-delta, the same
    1e-12 entropy floor, log2 via ln·log2(e) (the same lowering XLA emits
    for ``jnp.log2``). ``block`` is a CAP on the N-tile; the actual tile
    targets ~7.5 MB of VMEM per (B, C, H) block — half the 16 MB scoped
    limit minus a margin, because pallas double-buffers the pipelined
    operands (fp32 compute footprint regardless of storage dtype; block=0
    means "derive from VMEM alone"). The x8 sublane minimum floors the
    tile at 8 rows =
    32*C*H bytes, which exceeds the target once C*H > ~256k elements and
    keeps growing linearly with C*H — that regime is exercised only in
    interpret-mode tests, not on hardware (the jnp path is the safe choice
    there).

    Blocking obeys the TPU tiling rules (a block dim must be a multiple of
    its hardware tile or span the whole array dim): the (C, H) minor dims
    always span the array, the N-tile is sublane-aligned (x8) — legal for
    the (B, C) pi_xi block and the (B, 1) out block — and a ragged final
    block is left to pallas' edge masking rather than host-padding the
    cache (a jnp.pad here would copy the whole 2 GB tensor every round, on
    a pass whose point is a single HBM read).
    """
    if interpret is None:  # Mosaic compiles only on real TPUs
        interpret = jax.default_backend() != "tpu"
    N, C, H = pbest_hyp.shape
    B = choose_block(N, C, H, block, itemsize=pbest_hyp.dtype.itemsize)
    mixture0 = (pi_hat[:, None] * pbest_rows).sum(0)             # (H,)
    pc = jnp.clip(mixture0, _ENTROPY_FLOOR, None)
    h_before = -(pc * jnp.log2(pc)).sum()

    n_blocks = -(-N // B)

    out = pl.pallas_call(
        _score_block_kernel,
        out_shape=jax.ShapeDtypeStruct((N, 1), mixture0.dtype),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, H), lambda i: (0, 0)),          # mixture0
            pl.BlockSpec((1, 1), lambda i: (0, 0)),          # h_before
            pl.BlockSpec((1, C), lambda i: (0, 0)),          # pi_hat
            pl.BlockSpec((C, H), lambda i: (0, 0)),          # rows
            pl.BlockSpec((B, C, H), lambda i: (i, 0, 0)),    # hyp tile
            pl.BlockSpec((B, C), lambda i: (i, 0)),          # pi_xi tile
        ],
        out_specs=pl.BlockSpec((B, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(
        mixture0[None, :],
        h_before[None, None],
        pi_hat[None, :],
        pbest_rows,
        pbest_hyp,
        pi_hat_xi,
    )
    return out[:, 0]
