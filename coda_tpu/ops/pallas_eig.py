"""Pallas TPU kernels for the incremental-EIG scoring pass.

The incremental CODA selector scores a round by streaming the cached
``(C, N, H)`` hypothetical-P(best) tensor once and reducing it to ``(N,)``
expected-entropy drops (see ``coda_tpu.selectors.coda.eig_scores_from_cache``
— identical math). At the headline config the cache is 2 GB, so the pass is
HBM-bandwidth-bound; these kernels tile N into VMEM-resident blocks and fuse
the whole chain — mixture delta, clamp, log2 entropy, class mixture — into
one read of each cache element, with no intermediate tensors ever returning
to HBM.

Layout: the cache is carried ``(C, N, H)`` — N and H in the two minor dims —
so the (8, 128) physical tiling pads only H (1000 -> 1024, +2.4%). The
previous ``(N, C, H)`` layout put C in the sublane dim, and at the headline
C=10 the pad to 16 sublanes taxed EVERY HBM pass with 1.6x the logical
bytes (measured round 4: the fused kernel moved 6.7 GB physical for 4.2 GB
logical). The row refresh also becomes a leading-index update, and the fused
kernel writes ONLY the refreshed class row — a ``(1, N, H)`` slice — via
scalar-prefetch block indexing instead of rewriting the whole cache.

Kernel-shape notes (hardware-calibrated on a v5e, round 4): the bodies are
fully vectorized over the (C, B, H) tile — a per-class Python loop with
``pi_xi_ref[:, ci]`` lane extracts and 1-D (B,) intermediates lowered to
relayout-heavy Mosaic code that ran SLOWER than the XLA jnp path (10.1 vs
6.2 ms at headline). Every broadcast operand is pre-shaped in XLA (pi_hat
``(C, 1, 1)``, rows ``(C, 1, H)``, pi_xi transposed to ``(C, N, 1)``) so
the kernel contains no transposes or relayouts: the weighted class
reduction is ``(pi_xi_t * h_after).sum(axis=0)`` on ``(C, B, 1)`` operands,
whose output IS the ``(B, 1)`` score block. The ``(C, N, 1)`` pi_xi layout
is legal tiling because its LANE dim is the size-1 axis (lane dim must be a
multiple of 128 or span the array), while a ``(C, B)`` tile of a ``(C, N)``
array would put B in the lane dim and be rejected for B % 128 != 0.

The jnp reference path remains the default everywhere; the kernel is opt-in
via ``CODAHyperparams(eig_backend="pallas")`` / ``--eig-backend pallas``. On
non-TPU backends it runs in interpreter mode (tests exercise it on CPU,
including the row-only aliased write: interpret mode preserves the donated
buffer's unwritten blocks, verified in tests/test_pallas_eig.py).

``pallas_call`` is an opaque custom call that GSPMD cannot partition, so
multi-device execution takes one of two EXPLICIT routes instead of silent
demotion: (a) vmapped batches (suite seeds/tasks) dispatch via custom_vmap
to the *batched* kernels — the batch is an extra grid axis with unbatched
tile shapes; (b) a data-axis-sharded tensor whose mesh is DECLARED via
``CODAHyperparams(shard_spec="data=K")`` runs the kernels per shard under
``jax.shard_map`` (scoring is embarrassingly parallel over N, so the
sharded wrappers need no collectives). An undeclared multi-device-sharded
tensor still raises in ``make_coda``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from coda_tpu.ops.masked import entropy2, log2_approx

_ENTROPY_FLOOR = 1e-12  # reference clamp, see ops/masked.py entropy2
_LOG2E = 1.4426950408889634

_SCOPED_VMEM_BYTES = 16 << 20  # Mosaic's default scoped-vmem limit
_VMEM_MARGIN_BYTES = 3 << 19   # 1.5 MB: the single-buffered broadcast refs
#                                (mixture0/pi_hat/rows) + fixed stack slop
# the pipelined grid operands (cache tile, row tiles, score tile) are DOUBLE-
# buffered by pallas — the budget models 2x their padded footprint — and the
# kernel body's live fp32 vector temporaries land on the scoped-vmem STACK
# (single-buffered). Both terms are hardware-calibrated on a v5e (round 4):
# an 8 MB tile target that ignored double buffering landed 128.5 KB over
# the 16 MB limit, and a budget that ignored the stack temps landed 1.45 MB
# over at a ragged shape.
_TEMP_TILES = 2  # live fp32 (C, B, Hp)-shaped kernel temporaries (the
#                  delta/mix chain before the entropy reduce), per unit of B


def _lane_padded(H: int) -> int:
    """H rounded up to the 128-lane minor-dim tile."""
    return -(-H // 128) * 128


# The batched kernels' HBM operands carry the kernel's fixed layout, and XLA
# materializes them PADDED before the custom call: the (S, C, N, 1) pi_xi
# operand lane-pads 1 -> 128 (a 128x expansion — 14.4 GB at a 12-task
# DomainNet batch, measured OOM on a 16 GB v5e), and the (S, C, N, H) cache
# lane-pads H. The layout is tuned for the headline regime (C small, H
# large); batched calls whose PHYSICAL operand footprint exceeds this
# budget fall back to the jnp composition, whose layouts XLA chooses
# per-shape.
_BATCHED_PADDED_MAX_BYTES = 6 << 30


def _batched_padded_bytes(S: int, C: int, N: int, H: int,
                          itemsize: int) -> int:
    """Physical HBM bytes of the batched kernels' two big operands."""
    cache = S * C * N * _lane_padded(H) * itemsize
    pi_xi = S * C * N * 128 * 4
    return cache + pi_xi


def batched_pallas_viable(S: int, C: int, N: int, H: int,
                          itemsize: int = 4) -> bool:
    return _batched_padded_bytes(S, C, N, H, itemsize) \
        <= _BATCHED_PADDED_MAX_BYTES


def choose_block(N: int, C: int, H: int, block: int = 0,
                 itemsize: int = 4, fused: bool = False,
                 table_bytes: int = 0) -> int:
    """The N-tile size: sublane-aligned under the VMEM budget, or all of N
    when it fits.

    The cache tile is ``(C, B, H)`` — B in the sublane dim, so it must be a
    multiple of the hardware sublane tile (8 fp32 / 16 bf16) or span N; H
    pads to the 128-lane tile. Per unit of B the pipelined streams cost
    ``itemsize*C*Hp`` (cache tile) plus, for the fused kernel, the fp32
    ``hyp_t`` row in and the storage-width refreshed row out, plus the
    lane-padded ``(C, B, 1)`` pi_xi and ``(B, 1)`` score rows; the fp32
    compute temporaries add ``_TEMP_TILES`` single-buffered (C, B, Hp)
    tiles. The x8/x16 hardware minimum wins over a smaller caller ``block``
    cap (a cap below the sublane tile cannot lower the VMEM footprint
    further). ``table_bytes``: grid-constant operand bytes (the
    fused-compute kernel's Beta tables) deducted from the budget."""
    sub = 16 if itemsize == 2 else 8
    Hp = _lane_padded(H)
    stream_row = itemsize * C * Hp
    if fused:
        stream_row += (4 + itemsize) * Hp    # hyp_t in (fp32) + row out
    stream_row += 4 * 128 * C + 4 * 128      # pi_xi_t rows + score row
    # solve 2*B*stream_row (double-buffered pipeline) + B*temp_row (stack
    # temps, single-buffered) + margin <= the scoped limit for B
    temp_row = _TEMP_TILES * 4 * C * Hp
    budget = _SCOPED_VMEM_BYTES - _VMEM_MARGIN_BYTES - table_bytes
    vmem_cap = max(sub, budget // max(1, 2 * stream_row + temp_row))
    cap = min(block, vmem_cap) if block else vmem_cap
    if N <= max(cap, sub):
        return N
    return max(sub, (cap // sub) * sub)


def _weighted_entropy_scores(hyp, mixture0_ref, h_before_ref, pi_hat_ref,
                             rows_ref, pi_xi_t_ref, approx: bool = False):
    """(B, 1) scores from a fp32 (C, B, H) tile — the shared kernel tail.

    All math fp32, fully vectorized; reduction order matches the jnp
    path's (entropy over H, then weighted class sum over axis 0).
    ``approx`` (the ``eig_entropy='approx'`` opt-in) swaps the
    transcendental log for the bit-manipulation + polynomial
    ``log2_approx`` — integer VPU ops + FMAs, same lowering as the jnp
    path's approx flavor (ops/masked.py), cutting the N·C·H ~ 5e8 log
    evaluations that are the bf16 headline's VPU tail."""
    delta = hyp - rows_ref[:]                            # (C, B, H)-(C,1,H)
    mix = mixture0_ref[:] + pi_hat_ref[:] * delta
    p = jnp.maximum(mix, _ENTROPY_FLOOR)
    log2p = log2_approx(p) if approx else jnp.log(p) * _LOG2E
    h_after = -(p * log2p).sum(axis=-1, keepdims=True)
    return h_before_ref[0, 0] - (pi_xi_t_ref[:] * h_after).sum(axis=0)


def _score_block_kernel(approx, mixture0_ref, h_before_ref, pi_hat_ref,
                        rows_ref, hyp_ref, pi_xi_t_ref, out_ref):
    """One N-tile: (C, B, H) cache block -> (B, 1) scores.

    Refs: mixture0 (1, 1, H); h_before (1, 1); pi_hat (C, 1, 1); rows
    (C, 1, H); hyp (C, B, H); pi_xi_t (C, B, 1); out (B, 1) — 2-D so the
    N-tile only needs sublane (x8) alignment. Storage may be bf16
    (eig_cache_dtype); all math runs fp32. ``approx`` is bound statically
    via functools.partial at the pallas_call site.
    """
    hyp = hyp_ref[:].astype(jnp.float32)
    out_ref[:] = _weighted_entropy_scores(
        hyp, mixture0_ref, h_before_ref, pi_hat_ref, rows_ref, pi_xi_t_ref,
        approx=approx)


@functools.partial(jax.jit, static_argnames=("block", "interpret", "approx"))
def eig_scores_cache_pallas(
    pbest_rows: jnp.ndarray,   # (C, H)
    pbest_hyp: jnp.ndarray,    # (C, N, H)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> jnp.ndarray:
    """(N,) EIG scores from the incremental cache, fused in one HBM pass.

    Matches ``eig_scores_from_cache`` numerics: same mixture-delta, the same
    1e-12 entropy floor, log2 via ln·log2(e) (the same lowering XLA emits
    for ``jnp.log2``), same reduction order. ``approx`` selects the
    eig_entropy='approx' lowering of the whole chain (the same
    ``log2_approx`` the jnp path uses, so backend fallbacks never change
    numerics class). ``block`` is a CAP on the
    N-tile; the actual tile is derived from the VMEM budget (see
    :func:`choose_block`; block=0 means "derive from VMEM alone").

    Blocking obeys the TPU tiling rules (a block dim must be a multiple of
    its hardware tile or span the whole array dim): the H minor dim always
    spans the array, the N-tile is sublane-aligned (x8 fp32 / x16 bf16) —
    legal for the (C, B, 1) pi_xi block and the (B, 1) out block — and a
    ragged final block is left to pallas' edge masking rather than
    host-padding the cache (a jnp.pad here would copy the whole 2 GB tensor
    every round, on a pass whose point is a single HBM read).
    """
    if interpret is None:  # Mosaic compiles only on real TPUs
        interpret = jax.default_backend() != "tpu"

    # under vmap, dispatch to the EXPLICITLY batched kernel (grid over the
    # batch axis, so each grid step keeps the unbatched tile shapes) when
    # every operand carries the batch — pallas' AUTOMATIC vmap batching
    # would instead add a block dimension whose (8, 128) padding inflates
    # the small (B, 1) tiles into full lane-rows (the suite's width-1 seed
    # probe hit scoped-VMEM OOM exactly this way on a v5e, round 4). A
    # partially-batched call (some operand shared across the batch) falls
    # back to the jnp composition.
    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi):
        return _scores_impl(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi,
                            block, interpret, approx)

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, rows_b, hyp_b, pi_b, pi_xi_b):
        if all(in_batched) and batched_pallas_viable(
                hyp_b.shape[0], hyp_b.shape[1], hyp_b.shape[2],
                hyp_b.shape[3], hyp_b.dtype.itemsize):
            return eig_scores_cache_pallas_batched(
                rows_b, hyp_b, pi_b, pi_xi_b, block=block,
                interpret=interpret, approx=approx), True
        from coda_tpu.selectors.coda import eig_scores_from_cache

        in_axes = [0 if b else None for b in in_batched]
        out = jax.vmap(
            lambda r, h, p, px: eig_scores_from_cache(
                r, h, p, px, chunk=block or 2048, approx=approx),
            in_axes=in_axes,
        )(rows_b, hyp_b, pi_b, pi_xi_b)
        return out, True

    return _call(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi)


@functools.partial(jax.jit, static_argnames=("block", "interpret", "approx"))
def eig_scores_cache_pallas_batched(
    pbest_rows: jnp.ndarray,   # (S, C, H)
    pbest_hyp: jnp.ndarray,    # (S, C, N, H)
    pi_hat: jnp.ndarray,       # (S, C)
    pi_hat_xi: jnp.ndarray,    # (S, N, C)
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> jnp.ndarray:
    """(S, N) EIG scores for a BATCH of incremental caches in one kernel.

    The batch (the suite's vmapped seeds / stacked tasks) is an extra
    leading GRID dimension — each grid step processes one replica's
    (C, B, H) tile with exactly the unbatched kernel's block shapes and
    VMEM footprint, so batching multiplies grid steps, not tile padding.
    Per-replica numerics identical to :func:`eig_scores_cache_pallas`.
    Nested vmaps (tasks over seeds) flatten into the one batch axis via
    the custom_vmap rule below.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(rows, hyp, pi, pi_xi):
        return _scores_impl_batched(rows, hyp, pi, pi_xi, block, interpret,
                                    approx)

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, rows_b, hyp_b, pi_b, pi_xi_b):
        if not all(in_batched):
            from coda_tpu.selectors.coda import eig_scores_from_cache

            in_axes = [0 if b else None for b in in_batched]
            out = jax.vmap(
                lambda r, h, p, px: jax.vmap(
                    lambda r2, h2, p2, px2: eig_scores_from_cache(
                        r2, h2, p2, px2, chunk=block or 2048,
                        approx=approx)
                )(r, h, p, px),
                in_axes=in_axes,
            )(rows_b, hyp_b, pi_b, pi_xi_b)
            return out, True
        # flatten (T, S, ...) -> (T*S, ...) and recurse into the batched
        # kernel — arbitrary vmap nesting collapses to one grid axis.
        # The padded-operand budget must be re-checked at the FLATTENED
        # batch size (the inner dispatch only saw S replicas).
        T, S = rows_b.shape[0], rows_b.shape[1]
        TS, C2, N2, H2 = T * S, hyp_b.shape[2], hyp_b.shape[3], \
            hyp_b.shape[4]
        if not batched_pallas_viable(TS, C2, N2, H2,
                                     hyp_b.dtype.itemsize):
            from coda_tpu.selectors.coda import eig_scores_from_cache

            out = jax.vmap(jax.vmap(
                lambda r, h, p, px: eig_scores_from_cache(
                    r, h, p, px, chunk=block or 2048, approx=approx)))(
                rows_b, hyp_b, pi_b, pi_xi_b)
            return out, True

        def flat(x):
            return x.reshape((TS,) + x.shape[2:])

        out = eig_scores_cache_pallas_batched(
            flat(rows_b), flat(hyp_b), flat(pi_b), flat(pi_xi_b),
            block=block, interpret=interpret, approx=approx)
        return out.reshape(T, S, -1), True

    return _call(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi)


def _refresh_compute_score_kernel(approx, c_sp_ref, mixture0_ref,
                                  h_before_ref, pi_hat_ref, rows_ref,
                                  s0_ref, dlog_ref, fu_t_ref, df_t_ref,
                                  wtr_ref, hp_ref, pi_xi_t_ref, hyp_ref,
                                  score_ref, row_out_ref):
    """One N-tile of the fully-fused refresh: computes the replacement
    class row IN-KERNEL from the Beta grid tables (three MXU dots per
    tile — the work the precomputed path does as XLA einsums), then
    scores the tile with the fresh row, writing only that row back.

    Refs: c (1,) scalar-prefetch; mixture0 (1, 1, H); h_before (1, 1);
    pi_hat (C, 1, 1); rows (C, 1, H); s0 (1, G); dlog/fu_t/df_t —
    dlogcdf (H, G) and the F tables PRE-TRANSPOSED to (G, H) so the
    kernel contains no transposes; wtr (1, G) trapezoid weights; hp
    (B, H) int32 hard preds; pi_xi_t (C, B, 1); hyp (C, B, H) cache
    tile. Out: score (B, 1), row_out (1, B, H).
    """
    c = c_sp_ref[0]
    eq = (hp_ref[:] == c).astype(jnp.float32)            # (B, H)
    # S[n, g] = S0[g] + eq @ dlogcdf  — fp32 MXU dot
    s = s0_ref[:] + jax.lax.dot_general(
        eq, dlog_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (B, G)
    s = s - s.max(axis=-1, keepdims=True)
    w_e = wtr_ref[:] * jnp.exp(s)                        # (B, G)
    t_base = jax.lax.dot_general(
        w_e, fu_t_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (B, H)
    t_diff = jax.lax.dot_general(
        w_e, df_t_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    unnorm = t_base + eq * t_diff
    row_new = unnorm / jnp.clip(
        unnorm.sum(-1, keepdims=True), 1e-30, None)
    row_store = row_new.astype(hyp_ref.dtype)            # (B, H)
    row_out_ref[:] = row_store[None]
    row_f32 = row_store.astype(jnp.float32)
    cls = lax.broadcasted_iota(jnp.int32, (hyp_ref.shape[0], 1, 1), 0)
    hyp = jnp.where(cls == c, row_f32[None],
                    hyp_ref[:].astype(jnp.float32))
    score_ref[:] = _weighted_entropy_scores(
        hyp, mixture0_ref, h_before_ref, pi_hat_ref, rows_ref, pi_xi_t_ref,
        approx=approx)


@functools.partial(jax.jit,
                   static_argnames=("num_points", "block", "interpret",
                                    "approx"))
def eig_scores_refresh_compute_pallas(
    pbest_rows: jnp.ndarray,   # (C, H) — ALREADY holding the refreshed row
    pbest_hyp: jnp.ndarray,    # (C, N, H) — still holding the OLD row
    a_t: jnp.ndarray,          # (H,) diagonal-Beta a of the labeled class
    b_t: jnp.ndarray,          # (H,)
    hard_preds: jnp.ndarray,   # (N, H) int32
    true_class: jnp.ndarray,   # scalar int
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    update_weight: float = 1.0,
    num_points: int = 256,
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fully-fused refresh+score: the replacement row is COMPUTED inside
    the scoring kernel from O(H·G) Beta tables, so the refresh einsums
    (6·N·H·G FLOPs — the largest remaining XLA stage, 3.2-3.7 ms at
    headline, PROFILE_TPU_r04) overlap the 2 GB cache read instead of
    preceding it, and the (N, H) hyp_t intermediate never exists.

    OPT-IN numerics (``eig_refresh='fused'``): the in-kernel fp32 MXU
    dots replace XLA-HIGHEST einsums, so refreshed cache VALUES differ
    from the precomputed path by up to the MEASURED 2.34e-4 at the
    headline shape (``fusedcompute_row_max_abs_diff``,
    PALLAS_TPU_VALIDATION_r05.json, v5e silicon): the single-pass fp32
    accumulation's rounding difference is amplified by the
    ``exp(S - max S)`` integrand on near-degenerate Beta rows. The drift
    does not compound across rounds (each refresh recomputes its row
    from the identically-updated Dirichlet posterior); long-horizon
    behavior is pinned by the 100-round digits_h80 trace-agreement test.
    Same contract as ``eig_precision``/``eig_cache_dtype``. ``approx``
    additionally selects the eig_entropy='approx' scoring tail. No
    vmap/sharding variants: the lever targets the single-chip headline;
    batched callers raise (resolve via the precomputed path there).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    from coda_tpu.ops.pbest import pbest_grid
    from coda_tpu.selectors.coda import _bump_tables, _trapz_weights

    C, N, H = pbest_hyp.shape
    G = num_points
    x = pbest_grid(G)
    dx = x[1] - x[0]
    w_trapz = _trapz_weights(G, dx, x.dtype)
    s0, dlogcdf, f_u, d_f = _bump_tables(a_t, b_t, x, dx, update_weight)
    # F tables pre-transposed once (O(H·G), trivial next to the cache)
    fu_t = f_u.T                                          # (G, H)
    df_t = d_f.T
    # grid-constant table operands, padded, double-buffer-conservative
    tables = 2 * 4 * (H * G + 2 * G * _lane_padded(H) + 2 * G)
    B = choose_block(N, C, H, block, itemsize=pbest_hyp.dtype.itemsize,
                     fused=True, table_bytes=tables)
    mixture0, h_before = _mixture_stats(pbest_rows, pi_hat, approx=approx)
    n_blocks = -(-N // B)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1, H), lambda i, c: (0, 0, 0)),  # mixture0
            pl.BlockSpec((1, 1), lambda i, c: (0, 0)),        # h_before
            pl.BlockSpec((C, 1, 1), lambda i, c: (0, 0, 0)),  # pi_hat
            pl.BlockSpec((C, 1, H), lambda i, c: (0, 0, 0)),  # rows
            pl.BlockSpec((1, num_points), lambda i, c: (0, 0)),   # S0
            pl.BlockSpec((H, num_points), lambda i, c: (0, 0)),   # dlogcdf
            pl.BlockSpec((num_points, H), lambda i, c: (0, 0)),   # F_u^T
            pl.BlockSpec((num_points, H), lambda i, c: (0, 0)),   # dF^T
            pl.BlockSpec((1, num_points), lambda i, c: (0, 0)),   # w_trapz
            pl.BlockSpec((B, H), lambda i, c: (i, 0)),        # hard preds
            pl.BlockSpec((C, B, 1), lambda i, c: (0, i, 0)),  # pi_xi_t
            pl.BlockSpec((C, B, H), lambda i, c: (0, i, 0)),  # cache tile
        ],
        out_specs=(
            pl.BlockSpec((B, 1), lambda i, c: (i, 0)),
            pl.BlockSpec((1, B, H), lambda i, c: (c[0], i, 0)),
        ),
    )
    scores, hyp_out = pl.pallas_call(
        functools.partial(_refresh_compute_score_kernel, approx),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct(pbest_hyp.shape, pbest_hyp.dtype),
        ),
        # cache operand (12th incl. the scalar prefetch at 0) aliases the
        # updated-cache output
        input_output_aliases={12: 1},
        interpret=interpret,
    )(
        jnp.asarray(true_class, jnp.int32)[None],
        mixture0,
        h_before,
        pi_hat[:, None, None],
        pbest_rows[:, None, :],
        s0[None, :],
        dlogcdf,
        fu_t,
        df_t,
        w_trapz[None, :],
        hard_preds,
        pi_hat_xi.T[:, :, None],
        pbest_hyp,
    )
    return scores[:, 0], hyp_out


def eig_scores_cache_pallas_sharded(
    pbest_rows: jnp.ndarray,   # (C, H) — replicated
    pbest_hyp: jnp.ndarray,    # (C, N, H) — N sharded over the data axis
    pi_hat: jnp.ndarray,       # (C,) — replicated
    pi_hat_xi: jnp.ndarray,    # (N, C) — N sharded
    mesh,
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> jnp.ndarray:
    """(N,) scores with the pallas kernel running PER DATA SHARD.

    ``pallas_call`` is an opaque custom call GSPMD cannot partition, so a
    multi-device run would all-gather the cache per chip; ``shard_map``
    over the mesh's data axis instead hands each device its local
    (C, N/d, H) block — the scoring pass is embarrassingly parallel over
    N (scores reduce over nothing), so no collectives are needed at all;
    the selection argmax happens outside on the sharded (N,) result.
    Requires N divisible by the data-axis size (callers resolve to the
    jnp path otherwise).
    """
    from jax.sharding import PartitionSpec as P

    from coda_tpu.parallel.mesh import DATA_AXIS, shard_map_compat

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def local(rows, hyp, pi, pi_xi):
        return _scores_impl(rows, hyp, pi, pi_xi, block, interpret, approx)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-
    # axes annotation, which the default vma check rejects; the specs above
    # state the sharding contract explicitly
    return shard_map_compat(
        local, mesh=mesh,
        in_specs=(P(), P(None, DATA_AXIS, None), P(), P(DATA_AXIS, None)),
        out_specs=P(DATA_AXIS), check_vma=False,
    )(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi)


def eig_scores_refresh_pallas_sharded(
    pbest_rows: jnp.ndarray,   # (C, H) replicated — ALREADY refreshed
    pbest_hyp: jnp.ndarray,    # (C, N, H) — N sharded, OLD row
    hyp_t: jnp.ndarray,        # (N, H) — N sharded
    true_class: jnp.ndarray,   # scalar, replicated
    pi_hat: jnp.ndarray,       # (C,) replicated
    pi_hat_xi: jnp.ndarray,    # (N, C) — N sharded
    mesh,
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused refresh+score per data shard: ``(scores (N,), cache)``.

    Each device refreshes its own (1, N/d, H) slice of the class row and
    scores its local block — the donated-cache row-only write works
    per shard, and the carried cache stays sharded across scan rounds.
    """
    from jax.sharding import PartitionSpec as P

    from coda_tpu.parallel.mesh import DATA_AXIS, shard_map_compat

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def local(rows, hyp, hyp_t, c, pi, pi_xi):
        return _refresh_impl(rows, hyp, hyp_t, c, pi, pi_xi, block,
                             interpret, approx)

    return shard_map_compat(
        local, mesh=mesh,
        in_specs=(P(), P(None, DATA_AXIS, None), P(DATA_AXIS, None), P(),
                  P(), P(DATA_AXIS, None)),
        out_specs=(P(DATA_AXIS), P(None, DATA_AXIS, None)),
        check_vma=False,
    )(pbest_rows, pbest_hyp, hyp_t, jnp.asarray(true_class, jnp.int32),
      pi_hat, pi_hat_xi)


def _batched_score_kernel(approx, mixture0_ref, h_before_ref, pi_hat_ref,
                          rows_ref, hyp_ref, pi_xi_t_ref, out_ref):
    """One (replica, N-tile) grid step: refs carry a leading size-1 batch
    block; the math is :func:`_score_block_kernel`'s exactly."""
    hyp = hyp_ref[0].astype(jnp.float32)
    out_ref[0] = _weighted_entropy_scores(
        hyp, mixture0_ref[0], h_before_ref[0], pi_hat_ref[0], rows_ref[0],
        pi_xi_t_ref[0], approx=approx)


def _scores_impl_batched(rows, hyp, pi, pi_xi, block: int,
                         interpret: bool,
                         approx: bool = False) -> jnp.ndarray:
    S, C, N, H = hyp.shape
    B = choose_block(N, C, H, block, itemsize=hyp.dtype.itemsize)
    # _mixture_stats already emits (1, 1, H)/(1, 1) per replica, so the
    # vmap lands exactly on the (S, 1, 1, H)/(S, 1, 1) operand shapes
    mixture0, h_before = jax.vmap(
        functools.partial(_mixture_stats, approx=approx))(rows, pi)
    n_blocks = -(-N // B)

    out = pl.pallas_call(
        functools.partial(_batched_score_kernel, approx),
        out_shape=jax.ShapeDtypeStruct((S, N, 1), jnp.float32),
        grid=(S, n_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, 1, H), lambda s, i: (s, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda s, i: (s, 0, 0)),
            pl.BlockSpec((1, C, 1, 1), lambda s, i: (s, 0, 0, 0)),
            pl.BlockSpec((1, C, 1, H), lambda s, i: (s, 0, 0, 0)),
            pl.BlockSpec((1, C, B, H), lambda s, i: (s, 0, i, 0)),
            pl.BlockSpec((1, C, B, 1), lambda s, i: (s, 0, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, B, 1), lambda s, i: (s, i, 0)),
        interpret=interpret,
    )(
        mixture0,                          # (S, 1, 1, H)
        h_before,                          # (S, 1, 1)
        pi[:, :, None, None],              # (S, C, 1, 1)
        rows[:, :, None, :],               # (S, C, 1, H)
        hyp,                               # (S, C, N, H)
        jnp.swapaxes(pi_xi, 1, 2)[..., None],  # (S, C, N, 1)
    )
    return out[:, :, 0]


def _mixture_stats(pbest_rows, pi_hat, approx: bool = False):
    """(mixture0 (1,1,H), h_before (1,1)) — the cheap pre-kernel scalars.

    ``approx`` must match the kernel tail's flavor: h_before and h_after
    enter the same subtraction, so a mixed lowering would forfeit the
    error cancellation the Δscore bound relies on."""
    mixture0 = (pi_hat[:, None] * pbest_rows).sum(0)             # (H,)
    h_before = entropy2(mixture0, approx=approx)
    return mixture0[None, None, :], h_before[None, None]


def _scores_impl(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi,
                 block: int, interpret: bool,
                 approx: bool = False) -> jnp.ndarray:
    C, N, H = pbest_hyp.shape
    B = choose_block(N, C, H, block, itemsize=pbest_hyp.dtype.itemsize)
    mixture0, h_before = _mixture_stats(pbest_rows, pi_hat, approx=approx)
    n_blocks = -(-N // B)

    out = pl.pallas_call(
        functools.partial(_score_block_kernel, approx),
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.float32),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1, H), lambda i: (0, 0, 0)),    # mixture0
            pl.BlockSpec((1, 1), lambda i: (0, 0)),          # h_before
            pl.BlockSpec((C, 1, 1), lambda i: (0, 0, 0)),    # pi_hat
            pl.BlockSpec((C, 1, H), lambda i: (0, 0, 0)),    # rows
            pl.BlockSpec((C, B, H), lambda i: (0, i, 0)),    # cache tile
            pl.BlockSpec((C, B, 1), lambda i: (0, i, 0)),    # pi_xi_t tile
        ],
        out_specs=pl.BlockSpec((B, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(
        mixture0,
        h_before,
        pi_hat[:, None, None],
        pbest_rows[:, None, :],
        pbest_hyp,
        pi_hat_xi.T[:, :, None],
    )
    return out[:, 0]


def _refresh_score_kernel(approx, c_sp_ref, mixture0_ref, h_before_ref,
                          pi_hat_ref, rows_ref, hyp_t_ref, pi_xi_t_ref,
                          hyp_ref, score_ref, row_out_ref):
    """One N-tile of the fused refresh+score pass.

    Scores the (C, B, H) cache tile with class row ``c`` read from the
    freshly-computed ``hyp_t`` values IN-REGISTER (same math as
    :func:`_score_block_kernel`), and writes ONLY the refreshed row: the
    output cache buffer is aliased to the input and the row-out BlockSpec
    targets ``(c, i, 0)`` via the scalar-prefetched class index, so the
    other C-1 rows never move — neither the defensive whole-tensor copy
    XLA inserts when an opaque custom call follows an in-place
    dynamic-update-slice on a loop carry (profiled: +~9 ms/round at
    headline on a v5e), nor the full-cache writeback the first fused
    kernel paid.
    """
    c = c_sp_ref[0]
    # round the replacement row through the STORAGE dtype first: the
    # DUS-then-score contract (and the jnp backend) scores the bf16-rounded
    # row when eig_cache_dtype='bfloat16', not the raw fp32 values
    row_store = hyp_t_ref[:].astype(hyp_ref.dtype)       # (B, H)
    row_out_ref[:] = row_store[None]
    row_new = row_store.astype(jnp.float32)
    cls = lax.broadcasted_iota(jnp.int32, (hyp_ref.shape[0], 1, 1), 0)
    hyp = jnp.where(cls == c, row_new[None], hyp_ref[:].astype(jnp.float32))
    score_ref[:] = _weighted_entropy_scores(
        hyp, mixture0_ref, h_before_ref, pi_hat_ref, rows_ref, pi_xi_t_ref,
        approx=approx)


@functools.partial(jax.jit, static_argnames=("block", "interpret", "approx"))
def eig_scores_refresh_pallas(
    pbest_rows: jnp.ndarray,   # (C, H) — ALREADY holding the refreshed row
    pbest_hyp: jnp.ndarray,    # (C, N, H) — still holding the OLD row
    hyp_t: jnp.ndarray,        # (N, H) replacement values for class row c
    true_class: jnp.ndarray,   # scalar int
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused cache-row refresh + EIG scoring: one HBM read of the cache,
    one row write.

    Returns ``(scores (N,), updated cache (C, N, H))``. Numerically equal
    to ``pbest_hyp.at[c].set(hyp_t)`` followed by
    :func:`eig_scores_cache_pallas` — what changes is the dataflow: the
    update happens in-register inside the scoring pass, the cache buffer
    is DONATED through the call (``input_output_aliases``), and only the
    refreshed ``(1, N, H)`` row is written back (the row-out BlockSpec's
    index map reads the scalar-prefetched class index), so a scan carrying
    the cache pays one 2 GB read + one 0.2 GB write per round instead of
    the read + full write + defensive copy the separate DUS + opaque-call
    sequence provokes. ``pbest_rows`` must already hold the refreshed row
    (it is (C, H) — the DUS on it is trivially cheap in XLA); ``pbest_hyp``
    must hold the pre-update rows.

    Interpret-mode semantics match hardware for the unwritten blocks too:
    the aliased (donated) buffer keeps the input's values wherever the
    grid never writes, on both paths (pinned by
    tests/test_pallas_eig.py::test_refresh_preserves_untouched_rows).
    """
    if interpret is None:  # Mosaic compiles only on real TPUs
        interpret = jax.default_backend() != "tpu"

    # same vmap strategy as eig_scores_cache_pallas: a fully-batched call
    # dispatches to the explicitly batched kernel (batch = extra grid
    # axis, unbatched tile shapes); partial batching falls back to the
    # equivalent DUS-then-score jnp composition
    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat, pi_hat_xi):
        return _refresh_impl(pbest_rows, pbest_hyp, hyp_t, true_class,
                             pi_hat, pi_hat_xi, block, interpret, approx)

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, rows_b, hyp_b, hyp_t_b, c_b,
                   pi_b, pi_xi_b):
        if all(in_batched) and batched_pallas_viable(
                hyp_b.shape[0], hyp_b.shape[1], hyp_b.shape[2],
                hyp_b.shape[3], hyp_b.dtype.itemsize):
            return eig_scores_refresh_pallas_batched(
                rows_b, hyp_b, hyp_t_b, c_b, pi_b, pi_xi_b, block=block,
                interpret=interpret, approx=approx), (True, True)
        from coda_tpu.selectors.coda import eig_scores_from_cache

        in_axes = [0 if b else None for b in in_batched]

        def one(rows, hyp, hyp_t, c, pi, pi_xi):
            hyp2 = hyp.at[c].set(hyp_t.astype(hyp.dtype))
            scores = eig_scores_from_cache(rows, hyp2, pi, pi_xi,
                                           chunk=block or 2048,
                                           approx=approx)
            return scores, hyp2

        out = jax.vmap(one, in_axes=in_axes)(
            rows_b, hyp_b, hyp_t_b, c_b, pi_b, pi_xi_b)
        return out, (True, True)

    return _call(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat,
                 pi_hat_xi)


@functools.partial(jax.jit, static_argnames=("block", "interpret", "approx"))
def eig_scores_refresh_pallas_batched(
    pbest_rows: jnp.ndarray,   # (S, C, H) — ALREADY holding refreshed rows
    pbest_hyp: jnp.ndarray,    # (S, C, N, H) — still holding the OLD rows
    hyp_t: jnp.ndarray,        # (S, N, H) replacement rows
    true_class: jnp.ndarray,   # (S,) int
    pi_hat: jnp.ndarray,       # (S, C)
    pi_hat_xi: jnp.ndarray,    # (S, N, C)
    block: int = 0,
    interpret: bool | None = None,
    approx: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused refresh+score for a BATCH of caches: ``(scores (S, N),
    updated cache (S, C, N, H))``.

    Batch = leading grid axis (same tile shapes and VMEM budget as the
    unbatched kernel); each replica's refreshed class row comes from its
    own scalar-prefetched index, so the row-only aliased write works per
    replica. Per-replica numerics identical to
    :func:`eig_scores_refresh_pallas`.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(rows, hyp, hyp_t, cls, pi, pi_xi):
        return _refresh_impl_batched(rows, hyp, hyp_t, cls, pi, pi_xi,
                                     block, interpret, approx)

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, rows_b, hyp_b, hyp_t_b, c_b,
                   pi_b, pi_xi_b):
        def one2(r, h, ht, c, p, px):
            # the shared jnp fallback: DUS the row, then score
            from coda_tpu.selectors.coda import eig_scores_from_cache

            h2 = h.at[c].set(ht.astype(h.dtype))
            return eig_scores_from_cache(
                r, h2, p, px, chunk=block or 2048, approx=approx), h2

        if not all(in_batched):
            in_axes = [0 if b else None for b in in_batched]

            def one(rows, hyp, hyp_t, cls, pi, pi_xi):
                return jax.vmap(one2)(rows, hyp, hyp_t, cls, pi, pi_xi)

            out = jax.vmap(one, in_axes=in_axes)(
                rows_b, hyp_b, hyp_t_b, c_b, pi_b, pi_xi_b)
            return out, (True, True)
        T, S = rows_b.shape[0], rows_b.shape[1]
        TS, C2, N2, H2 = T * S, hyp_b.shape[2], hyp_b.shape[3], \
            hyp_b.shape[4]
        if not batched_pallas_viable(TS, C2, N2, H2,
                                     hyp_b.dtype.itemsize):
            out = jax.vmap(jax.vmap(one2))(
                rows_b, hyp_b, hyp_t_b, c_b, pi_b, pi_xi_b)
            return out, (True, True)

        def flat(x):
            return x.reshape((TS,) + x.shape[2:])

        scores, hyp_out = eig_scores_refresh_pallas_batched(
            flat(rows_b), flat(hyp_b), flat(hyp_t_b), flat(c_b),
            flat(pi_b), flat(pi_xi_b), block=block, interpret=interpret,
            approx=approx)
        return (scores.reshape((T, S) + scores.shape[1:]),
                hyp_out.reshape((T, S) + hyp_out.shape[1:])), (True, True)

    return _call(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat,
                 pi_hat_xi)


def _batched_refresh_kernel(approx, c_sp_ref, mixture0_ref, h_before_ref,
                            pi_hat_ref, rows_ref, hyp_t_ref, pi_xi_t_ref,
                            hyp_ref, score_ref, row_out_ref):
    """One (replica, N-tile) grid step of the batched fused pass — the
    math of :func:`_refresh_score_kernel` on this replica's blocks."""
    c = c_sp_ref[pl.program_id(0)]
    row_store = hyp_t_ref[0].astype(hyp_ref.dtype)       # (B, H)
    row_out_ref[0] = row_store[None]
    row_new = row_store.astype(jnp.float32)
    cls = lax.broadcasted_iota(jnp.int32, (hyp_ref.shape[1], 1, 1), 0)
    hyp = jnp.where(cls == c, row_new[None],
                    hyp_ref[0].astype(jnp.float32))
    score_ref[0] = _weighted_entropy_scores(
        hyp, mixture0_ref[0], h_before_ref[0], pi_hat_ref[0], rows_ref[0],
        pi_xi_t_ref[0], approx=approx)


def _refresh_impl_batched(rows, hyp, hyp_t, cls, pi, pi_xi, block: int,
                          interpret: bool, approx: bool = False):
    S, C, N, H = hyp.shape
    B = choose_block(N, C, H, block, itemsize=hyp.dtype.itemsize,
                     fused=True)
    mixture0, h_before = jax.vmap(
        functools.partial(_mixture_stats, approx=approx))(rows, pi)
    n_blocks = -(-N // B)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, n_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, 1, H), lambda s, i, c: (s, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda s, i, c: (s, 0, 0)),
            pl.BlockSpec((1, C, 1, 1), lambda s, i, c: (s, 0, 0, 0)),
            pl.BlockSpec((1, C, 1, H), lambda s, i, c: (s, 0, 0, 0)),
            pl.BlockSpec((1, B, H), lambda s, i, c: (s, i, 0)),  # hyp_t
            pl.BlockSpec((1, C, B, 1), lambda s, i, c: (s, 0, i, 0)),
            pl.BlockSpec((1, C, B, H), lambda s, i, c: (s, 0, i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, B, 1), lambda s, i, c: (s, i, 0)),
            # each replica's refreshed class row only, at its own
            # scalar-prefetched index
            pl.BlockSpec((1, 1, B, H), lambda s, i, c: (s, c[s], i, 0)),
        ),
    )
    scores, hyp_out = pl.pallas_call(
        functools.partial(_batched_refresh_kernel, approx),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, N, 1), jnp.float32),
            jax.ShapeDtypeStruct(hyp.shape, hyp.dtype),
        ),
        input_output_aliases={7: 1},
        interpret=interpret,
    )(
        jnp.asarray(cls, jnp.int32),
        mixture0,
        h_before,
        pi[:, :, None, None],
        rows[:, :, None, :],
        hyp_t,
        jnp.swapaxes(pi_xi, 1, 2)[..., None],
        hyp,
    )
    return scores[:, :, 0], hyp_out


def _refresh_impl(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat,
                  pi_hat_xi, block: int, interpret: bool,
                  approx: bool = False):
    C, N, H = pbest_hyp.shape
    B = choose_block(N, C, H, block, itemsize=pbest_hyp.dtype.itemsize,
                     fused=True)
    mixture0, h_before = _mixture_stats(pbest_rows, pi_hat, approx=approx)
    n_blocks = -(-N // B)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1, H), lambda i, c: (0, 0, 0)),  # mixture0
            pl.BlockSpec((1, 1), lambda i, c: (0, 0)),        # h_before
            pl.BlockSpec((C, 1, 1), lambda i, c: (0, 0, 0)),  # pi_hat
            pl.BlockSpec((C, 1, H), lambda i, c: (0, 0, 0)),  # rows
            pl.BlockSpec((B, H), lambda i, c: (i, 0)),        # hyp_t tile
            pl.BlockSpec((C, B, 1), lambda i, c: (0, i, 0)),  # pi_xi_t
            pl.BlockSpec((C, B, H), lambda i, c: (0, i, 0)),  # cache tile
        ],
        out_specs=(
            pl.BlockSpec((B, 1), lambda i, c: (i, 0)),
            # the refreshed class row ONLY — indexed by the prefetched
            # scalar, so the write lands at (c, i*B, 0)
            pl.BlockSpec((1, B, H), lambda i, c: (c[0], i, 0)),
        ),
    )
    scores, hyp_out = pl.pallas_call(
        functools.partial(_refresh_score_kernel, approx),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct(pbest_hyp.shape, pbest_hyp.dtype),
        ),
        # donate the cache: input 7 (cache, counting the scalar-prefetch
        # operand at 0) aliases output 1 (the updated cache)
        input_output_aliases={7: 1},
        interpret=interpret,
    )(
        jnp.asarray(true_class, jnp.int32)[None],
        mixture0,
        h_before,
        pi_hat[:, None, None],
        pbest_rows[:, None, :],
        hyp_t,
        pi_hat_xi.T[:, :, None],
        pbest_hyp,
    )
    return scores[:, 0], hyp_out
