"""Pallas TPU kernel for the incremental-EIG scoring pass.

The incremental CODA selector scores a round by streaming the cached
``(N, C, H)`` hypothetical-P(best) tensor once and reducing it to ``(N,)``
expected-entropy drops (see ``coda_tpu.selectors.coda.eig_scores_from_cache``
— identical math). At the headline config the cache is 2 GB, so the pass is
HBM-bandwidth-bound; this kernel tiles N into VMEM-resident blocks and fuses
the whole chain — mixture delta, clamp, log2 entropy, class mixture — into
one read of each cache element, with no intermediate (B, C, H) tensors ever
returning to HBM.

The jnp reference path remains the default everywhere; the kernel is opt-in
via ``CODAHyperparams(eig_backend="pallas")`` / ``--eig-backend pallas``. On
non-TPU backends it runs in interpreter mode (tests exercise it on CPU).
Single-device only: ``pallas_call`` is an opaque custom call that GSPMD
cannot partition, so ``make_coda`` rejects the combination of this backend
with a multi-device-sharded prediction tensor.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENTROPY_FLOOR = 1e-12  # reference clamp, see ops/masked.py entropy2


def _score_block_kernel(mixture0_ref, h_before_ref, pi_hat_ref, rows_ref,
                        hyp_ref, pi_xi_ref, out_ref):
    """One N-tile: (B, C, H) cache block -> (B, 1) scores.

    Refs: mixture0 (1, H); h_before (1, 1); pi_hat (1, C); rows (C, H);
    hyp (B, C, H); pi_xi (B, C); out (B, 1) — 2-D so the N-tile only needs
    sublane (x8) alignment, not the x128 lane alignment a 1-D out would.
    """
    mixture0 = mixture0_ref[0, :]                    # (H,)
    pi_hat = pi_hat_ref[0, :]                        # (C,)
    # storage may be bf16 (eig_cache_dtype); all math runs fp32
    hyp = hyp_ref[:].astype(mixture0.dtype)          # (B, C, H)
    delta = hyp - rows_ref[:][None]                  # (B, C, H)
    mix = mixture0[None, None, :] + pi_hat[None, :, None] * delta
    p = jnp.maximum(mix, _ENTROPY_FLOOR)
    h_after = -(p * (jnp.log(p) * 1.4426950408889634)).sum(axis=-1)  # (B, C)
    scores = h_before_ref[0, 0] - (pi_xi_ref[:] * h_after).sum(axis=-1)
    out_ref[:] = scores[:, None]


_SCOPED_VMEM_BYTES = 16 << 20  # Mosaic's default scoped-vmem limit
_VMEM_MARGIN_BYTES = 1 << 20   # stack + the single-buffered broadcast refs
# the pipelined grid operands (hyp tile, pi_xi tile, out tile) are DOUBLE-
# buffered by pallas; the budget below models 2x their padded footprint.
# First hardware run (round 4) proved the point: an 8 MB tile target that
# ignored double buffering landed at 16.12 MB scoped — 128.5 KB over the
# 16 MB limit (2x8 MB hyp + 2x64 KB padded out + small refs), and Mosaic
# refused to compile.
_VMEM_TILE_BYTES = (_SCOPED_VMEM_BYTES - _VMEM_MARGIN_BYTES) // 2


def _padded_row_bytes(C: int, H: int, itemsize: int = 4) -> int:
    """Physical VMEM bytes of ONE N-row of the (B, C, H) cache tile.

    Mosaic lays vector memory out in (8, 128) fp32 / (16, 128) bf16 tiles
    over the two minor dims, so a (C, H) slice occupies
    ceil(C/sub)*sub x ceil(H/128)*128 elements regardless of the logical
    shape — at the headline (C=10, H=1000) fp32 that is 16 x 1024 = 1.6x
    the logical bytes. Budgeting with logical sizes would overshoot VMEM
    by exactly that factor on the first hardware run.
    """
    sub = 16 if itemsize == 2 else 8
    Cp = -(-C // sub) * sub
    Hp = -(-H // 128) * 128
    return itemsize * Cp * Hp


def choose_block(N: int, C: int, H: int, block: int = 0,
                 itemsize: int = 4, n_cache_streams: int = 1) -> int:
    """The N-tile size: sublane-aligned (x8) under the VMEM budget, or all
    of N when it fits — the two shapes Mosaic accepts for the (B, C) /
    (B, 1) blocks without host-padding the cache. The budget is computed
    against the PADDED physical tile (see :func:`_padded_row_bytes`) at
    the cache's ``itemsize``. The x8 hardware minimum wins over a smaller
    caller ``block`` cap (a cap below 8 cannot lower the tile's VMEM
    footprint further)."""
    # budget against the FP32 COMPUTE footprint even for bf16 storage: the
    # kernel upcasts the whole tile (delta/mix/entropy run fp32), so a
    # bf16-sized cap would double B and blow VMEM on hardware — bf16's win
    # is the halved HBM stream, not a bigger tile
    # pi_xi (B, C) and out (B, 1) rows, padded to the 128-lane minor dim
    xi_row = 4 * (-(-C // 128) * 128)
    out_row = 4 * 128
    # n_cache_streams: how many (B, C, H)-shaped tiles the kernel pipelines
    # per N-row — 1 for the score-only kernel, 2 for the fused
    # refresh+score kernel (cache in + aliased cache out), which also
    # streams the (B, H) replacement-row tile
    hyp_t_row = 4 * (-(-H // 128) * 128) if n_cache_streams > 1 else 0
    per_row = (n_cache_streams * _padded_row_bytes(C, H, max(itemsize, 4))
               + hyp_t_row + xi_row + out_row)
    vmem_cap = max(8, _VMEM_TILE_BYTES // max(1, per_row))
    cap = min(block, vmem_cap) if block else vmem_cap
    if N <= max(cap, 8):
        return N
    return max(8, (cap // 8) * 8)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def eig_scores_cache_pallas(
    pbest_rows: jnp.ndarray,   # (C, H)
    pbest_hyp: jnp.ndarray,    # (N, C, H)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    block: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """(N,) EIG scores from the incremental cache, fused in one HBM pass.

    Matches ``eig_scores_from_cache`` numerics: same mixture-delta, the same
    1e-12 entropy floor, log2 via ln·log2(e) (the same lowering XLA emits
    for ``jnp.log2``). ``block`` is a CAP on the N-tile; the actual tile
    targets ~7.5 MB of VMEM per (B, C, H) block — half the 16 MB scoped
    limit minus a margin, because pallas double-buffers the pipelined
    operands (fp32 compute footprint regardless of storage dtype; block=0
    means "derive from VMEM alone"). The x8 sublane minimum floors the
    tile at 8 rows =
    32*C*H bytes, which exceeds the target once C*H > ~256k elements and
    keeps growing linearly with C*H — that regime is exercised only in
    interpret-mode tests, not on hardware (the jnp path is the safe choice
    there).

    Blocking obeys the TPU tiling rules (a block dim must be a multiple of
    its hardware tile or span the whole array dim): the (C, H) minor dims
    always span the array, the N-tile is sublane-aligned (x8) — legal for
    the (B, C) pi_xi block and the (B, 1) out block — and a ragged final
    block is left to pallas' edge masking rather than host-padding the
    cache (a jnp.pad here would copy the whole 2 GB tensor every round, on
    a pass whose point is a single HBM read).
    """
    if interpret is None:  # Mosaic compiles only on real TPUs
        interpret = jax.default_backend() != "tpu"

    # under vmap, fall back to the jnp path: a batched pallas_call turns
    # the batch into an extra grid/block dimension whose (8, 128) padding
    # inflates the small (B, 1)/(B, C) tiles into full lane-rows — the
    # suite's width-1 seed probe hit scoped-VMEM OOM exactly this way on a
    # v5e (16.44M vs the 16M limit at the msv shape) — and batched runs
    # are multi-experiment workloads where the XLA path is the right tier
    # anyway (same reasoning as resolve_eig_backend's n_parallel guard)
    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi):
        return _scores_impl(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi,
                            block, interpret)

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, rows_b, hyp_b, pi_b, pi_xi_b):
        from coda_tpu.selectors.coda import eig_scores_from_cache

        in_axes = [0 if b else None for b in in_batched]
        out = jax.vmap(
            lambda r, h, p, px: eig_scores_from_cache(
                r, h, p, px, chunk=block or 2048),
            in_axes=in_axes,
        )(rows_b, hyp_b, pi_b, pi_xi_b)
        return out, True

    return _call(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi)


def _scores_impl(pbest_rows, pbest_hyp, pi_hat, pi_hat_xi,
                 block: int, interpret: bool) -> jnp.ndarray:
    N, C, H = pbest_hyp.shape
    B = choose_block(N, C, H, block, itemsize=pbest_hyp.dtype.itemsize)
    mixture0 = (pi_hat[:, None] * pbest_rows).sum(0)             # (H,)
    pc = jnp.clip(mixture0, _ENTROPY_FLOOR, None)
    h_before = -(pc * jnp.log2(pc)).sum()

    n_blocks = -(-N // B)

    out = pl.pallas_call(
        _score_block_kernel,
        out_shape=jax.ShapeDtypeStruct((N, 1), mixture0.dtype),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, H), lambda i: (0, 0)),          # mixture0
            pl.BlockSpec((1, 1), lambda i: (0, 0)),          # h_before
            pl.BlockSpec((1, C), lambda i: (0, 0)),          # pi_hat
            pl.BlockSpec((C, H), lambda i: (0, 0)),          # rows
            pl.BlockSpec((B, C, H), lambda i: (i, 0, 0)),    # hyp tile
            pl.BlockSpec((B, C), lambda i: (i, 0)),          # pi_xi tile
        ],
        out_specs=pl.BlockSpec((B, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(
        mixture0[None, :],
        h_before[None, None],
        pi_hat[None, :],
        pbest_rows,
        pbest_hyp,
        pi_hat_xi,
    )
    return out[:, 0]


def _refresh_score_kernel(c_ref, mixture0_ref, h_before_ref, pi_hat_ref,
                          rows_ref, hyp_t_ref, pi_xi_ref, hyp_ref,
                          score_ref, hyp_out_ref):
    """One N-tile of the fused refresh+score pass.

    Replaces class row ``c`` of the (B, C, H) cache tile with the
    freshly-computed ``hyp_t`` values IN-REGISTER, scores the updated
    tile (same math as :func:`_score_block_kernel`), and writes both the
    scores and the updated tile — the output cache buffer is aliased to
    the input, so the cache flows through the call without the defensive
    whole-tensor copy XLA inserts when an opaque custom call follows an
    in-place dynamic-update-slice on a loop carry (profiled: +~9 ms/round
    at headline on a v5e).
    """
    c = c_ref[0, 0]
    mixture0 = mixture0_ref[0, :]                    # (H,)
    pi_hat = pi_hat_ref[0, :]                        # (C,)
    hyp = hyp_ref[:].astype(mixture0.dtype)          # (B, C, H) old rows
    # round the replacement row through the STORAGE dtype first: the
    # DUS-then-score contract (and the jnp backend) scores the bf16-rounded
    # row when eig_cache_dtype='bfloat16', not the raw fp32 values
    row_new = hyp_t_ref[:].astype(hyp_ref.dtype).astype(mixture0.dtype)
    cls = jax.lax.broadcasted_iota(jnp.int32, (1, hyp.shape[1], 1), 1)
    upd = jnp.where(cls == c, row_new[:, None, :], hyp)
    hyp_out_ref[:] = upd.astype(hyp_ref.dtype)
    delta = upd - rows_ref[:][None].astype(mixture0.dtype)
    mix = mixture0[None, None, :] + pi_hat[None, :, None] * delta
    p = jnp.maximum(mix, _ENTROPY_FLOOR)
    h_after = -(p * (jnp.log(p) * 1.4426950408889634)).sum(axis=-1)  # (B, C)
    scores = h_before_ref[0, 0] - (pi_xi_ref[:] * h_after).sum(axis=-1)
    score_ref[:] = scores[:, None]


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def eig_scores_refresh_pallas(
    pbest_rows: jnp.ndarray,   # (C, H) — ALREADY holding the refreshed row
    pbest_hyp: jnp.ndarray,    # (N, C, H) — still holding the OLD row
    hyp_t: jnp.ndarray,        # (N, H) replacement values for class row c
    true_class: jnp.ndarray,   # scalar int
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    block: int = 0,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused cache-row refresh + EIG scoring: one HBM pass over the cache.

    Returns ``(scores (N,), updated cache (N, C, H))``. Numerically equal
    to ``pbest_hyp.at[:, c, :].set(hyp_t)`` followed by
    :func:`eig_scores_cache_pallas` — what changes is the dataflow: the
    update happens in-register inside the scoring pass and the cache
    buffer is DONATED through the call (``input_output_aliases``), so a
    scan carrying the cache never pays the XLA defensive copy that the
    separate DUS + opaque-custom-call sequence provokes (see
    ``_refresh_score_kernel``). ``pbest_rows`` must already hold the
    refreshed row (it is (C, H) — the DUS on it is trivially cheap in
    XLA); ``pbest_hyp`` must hold the pre-update rows.

    Every output element is written (full-tile write), so interpret-mode
    semantics match hardware exactly and the CPU tests remain valid.
    """
    if interpret is None:  # Mosaic compiles only on real TPUs
        interpret = jax.default_backend() != "tpu"

    # same vmap fallback as eig_scores_cache_pallas: batched pallas tiles
    # pad pathologically, so a vmapped caller gets the equivalent
    # DUS-then-score jnp composition instead
    from jax import custom_batching

    @custom_batching.custom_vmap
    def _call(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat, pi_hat_xi):
        return _refresh_impl(pbest_rows, pbest_hyp, hyp_t, true_class,
                             pi_hat, pi_hat_xi, block, interpret)

    @_call.def_vmap
    def _call_vmap(axis_size, in_batched, rows_b, hyp_b, hyp_t_b, c_b,
                   pi_b, pi_xi_b):
        from coda_tpu.selectors.coda import eig_scores_from_cache

        in_axes = [0 if b else None for b in in_batched]

        def one(rows, hyp, hyp_t, c, pi, pi_xi):
            hyp2 = hyp.at[:, c, :].set(hyp_t.astype(hyp.dtype))
            scores = eig_scores_from_cache(rows, hyp2, pi, pi_xi,
                                           chunk=block or 2048)
            return scores, hyp2

        out = jax.vmap(one, in_axes=in_axes)(
            rows_b, hyp_b, hyp_t_b, c_b, pi_b, pi_xi_b)
        return out, (True, True)

    return _call(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat,
                 pi_hat_xi)


def _refresh_impl(pbest_rows, pbest_hyp, hyp_t, true_class, pi_hat,
                  pi_hat_xi, block: int, interpret: bool):
    N, C, H = pbest_hyp.shape
    B = choose_block(N, C, H, block, itemsize=pbest_hyp.dtype.itemsize,
                     n_cache_streams=2)
    mixture0 = (pi_hat[:, None] * pbest_rows).sum(0)             # (H,)
    pc = jnp.clip(mixture0, _ENTROPY_FLOOR, None)
    h_before = -(pc * jnp.log2(pc)).sum()

    n_blocks = -(-N // B)

    scores, hyp_out = pl.pallas_call(
        _refresh_score_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((N, 1), mixture0.dtype),
            jax.ShapeDtypeStruct(pbest_hyp.shape, pbest_hyp.dtype),
        ),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),          # true_class
            pl.BlockSpec((1, H), lambda i: (0, 0)),          # mixture0
            pl.BlockSpec((1, 1), lambda i: (0, 0)),          # h_before
            pl.BlockSpec((1, C), lambda i: (0, 0)),          # pi_hat
            pl.BlockSpec((C, H), lambda i: (0, 0)),          # rows
            pl.BlockSpec((B, H), lambda i: (i, 0)),          # hyp_t tile
            pl.BlockSpec((B, C), lambda i: (i, 0)),          # pi_xi tile
            pl.BlockSpec((B, C, H), lambda i: (i, 0, 0)),    # hyp tile
        ],
        out_specs=(
            pl.BlockSpec((B, 1), lambda i: (i, 0)),
            pl.BlockSpec((B, C, H), lambda i: (i, 0, 0)),
        ),
        # donate the cache: input 7 (hyp) aliases output 1 (hyp_out)
        input_output_aliases={7: 1},
        interpret=interpret,
    )(
        jnp.asarray(true_class, jnp.int32)[None, None],
        mixture0[None, :],
        h_before[None, None],
        pi_hat[None, :],
        pbest_rows,
        hyp_t,
        pi_hat_xi,
        pbest_hyp,
    )
    return scores[:, 0], hyp_out
