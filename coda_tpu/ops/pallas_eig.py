"""Pallas TPU kernel for the incremental-EIG scoring pass.

The incremental CODA selector scores a round by streaming the cached
``(N, C, H)`` hypothetical-P(best) tensor once and reducing it to ``(N,)``
expected-entropy drops (see ``coda_tpu.selectors.coda.eig_scores_from_cache``
— identical math). At the headline config the cache is 2 GB, so the pass is
HBM-bandwidth-bound; this kernel tiles N into VMEM-resident blocks and fuses
the whole chain — mixture delta, clamp, log2 entropy, class mixture — into
one read of each cache element, with no intermediate (B, C, H) tensors ever
returning to HBM.

The jnp reference path remains the default everywhere; the kernel is opt-in
via ``CODAHyperparams(eig_backend="pallas")`` / ``--eig-backend pallas``. On
non-TPU backends it runs in interpreter mode (tests exercise it on CPU).
Single-device only: ``pallas_call`` is an opaque custom call that GSPMD
cannot partition, so ``make_coda`` rejects the combination of this backend
with a multi-device-sharded prediction tensor.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENTROPY_FLOOR = 1e-12  # reference clamp, see ops/masked.py entropy2


def _score_block_kernel(mixture0_ref, h_before_ref, pi_hat_ref, rows_ref,
                        hyp_ref, pi_xi_ref, out_ref):
    """One N-tile: (B, C, H) cache block -> (B,) scores.

    Refs: mixture0 (1, H); h_before (1, 1); pi_hat (1, C); rows (C, H);
    hyp (B, C, H); pi_xi (B, C); out (B,).
    """
    mixture0 = mixture0_ref[0, :]                    # (H,)
    pi_hat = pi_hat_ref[0, :]                        # (C,)
    hyp = hyp_ref[:]                                 # (B, C, H)
    delta = hyp - rows_ref[:][None]                  # (B, C, H)
    mix = mixture0[None, None, :] + pi_hat[None, :, None] * delta
    p = jnp.maximum(mix, _ENTROPY_FLOOR)
    h_after = -(p * (jnp.log(p) * 1.4426950408889634)).sum(axis=-1)  # (B, C)
    out_ref[:] = h_before_ref[0, 0] - (pi_xi_ref[:] * h_after).sum(axis=-1)


_VMEM_TILE_BYTES = 4 << 20  # target VMEM footprint of one (B, C, H) tile


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def eig_scores_cache_pallas(
    pbest_rows: jnp.ndarray,   # (C, H)
    pbest_hyp: jnp.ndarray,    # (N, C, H)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    block: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """(N,) EIG scores from the incremental cache, fused in one HBM pass.

    Matches ``eig_scores_from_cache`` numerics: same mixture-delta, the same
    1e-12 entropy floor, log2 via ln·log2(e) (the same lowering XLA emits
    for ``jnp.log2``). ``block`` is a CAP on the N-tile; the actual tile is
    bounded so one (B, C, H) fp32 block stays within ~4 MB of VMEM
    (block=0 means "derive from VMEM alone").
    """
    N, C, H = pbest_hyp.shape
    vmem_cap = max(8, _VMEM_TILE_BYTES // max(1, 4 * C * H))
    cap = min(block, vmem_cap) if block else vmem_cap
    # prefer the largest tile <= cap that DIVIDES N: a ragged grid needs
    # jnp.pad of the whole (N, C, H) cache, i.e. a full HBM copy per round
    # on a pass whose point is a single HBM read. Fall back to padding only
    # when N has no usable divisor (e.g. prime N) — correct, just slower.
    block = next((b for b in range(min(cap, N), 0, -1) if N % b == 0), 1)
    if block < max(8, cap // 4):
        block = min(cap, N)
    mixture0 = (pi_hat[:, None] * pbest_rows).sum(0)             # (H,)
    pc = jnp.clip(mixture0, _ENTROPY_FLOOR, None)
    h_before = -(pc * jnp.log2(pc)).sum()

    B = min(block, N)
    pad = (-N) % B
    hyp_p = jnp.pad(pbest_hyp, ((0, pad), (0, 0), (0, 0)))
    # padded rows score garbage into padded out slots; sliced off below
    pi_xi_p = jnp.pad(pi_hat_xi, ((0, pad), (0, 0)))
    n_blocks = (N + pad) // B

    out = pl.pallas_call(
        _score_block_kernel,
        out_shape=jax.ShapeDtypeStruct((N + pad,), pbest_hyp.dtype),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, H), lambda i: (0, 0)),          # mixture0
            pl.BlockSpec((1, 1), lambda i: (0, 0)),          # h_before
            pl.BlockSpec((1, C), lambda i: (0, 0)),          # pi_hat
            pl.BlockSpec((C, H), lambda i: (0, 0)),          # rows
            pl.BlockSpec((B, C, H), lambda i: (i, 0, 0)),    # hyp tile
            pl.BlockSpec((B, C), lambda i: (i, 0)),          # pi_xi tile
        ],
        out_specs=pl.BlockSpec((B,), lambda i: (i,)),
        interpret=interpret,
    )(
        mixture0[None, :],
        h_before[None, None],
        pi_hat[None, :],
        pbest_rows,
        hyp_p,
        pi_xi_p,
    )
    return out[:N]
