"""Fixed-shape selection primitives: masked argmax/argmin with random
tie-breaking, masked categorical sampling, and base-2 entropy.

The reference mutates Python lists (``unlabeled_idxs.remove``) and tie-breaks
with the host RNG (e.g. ``coda/coda.py:306-311``); under jit those become
boolean masks and JAX PRNG keys. Tie-break semantics are preserved: when a
unique extremum exists the result is its (first) index, matching
``torch.argmax``; among ties the choice is uniform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -jnp.inf

# Degree-6 Chebyshev-fitted polynomial for log2(m) on the reduced mantissa
# m in [1, 2), evaluated in t = m - 1 (Horner, ascending coefficients).
# Max fit error 5.1e-6 over the interval; the fp32 end-to-end error of
# log2_approx over the whole clamped entropy domain [1e-12, 1] measures
# 6.9e-6 (see tests/test_fast_entropy.py, which pins the 1e-5 bound).
# Seven FMAs + integer bit ops on the VPU replace the transcendental log
# lowering — the point of the eig_entropy='approx' scoring path, whose
# N*C*H ~ 5e8 log evaluations per round are the bf16 headline's limiter
# (NOTES_r05.md: the invariant ~1.2 ms VPU entropy tail).
_LOG2_POLY = (
    5.065333097742375e-06,
    1.4423954826705712,
    -0.7169868747328294,
    0.45385624123395407,
    -0.27235315795334314,
    0.11790518317842658,
    -0.0248256066155325,
)


def log2_approx(x: jnp.ndarray) -> jnp.ndarray:
    """Fast fp32 log2 for POSITIVE NORMAL floats (the clamped simplex
    domain [1e-12, 1] of the entropy chain — callers clamp first).

    The IEEE-754 exponent is extracted with integer bit manipulation
    (``x = m * 2^e``, ``log2(x) = e + log2(m)``) and ``log2(m)`` comes
    from the fixed-degree :data:`_LOG2_POLY` — no transcendental, only
    VPU-friendly integer ops and FMAs, the same ops in the XLA lowering
    and inside the Mosaic kernels (``lax.bitcast_convert_type`` and
    int32 shifts lower on both). NaN/inf/zero/denormal inputs are NOT
    handled (the 1e-12 entropy floor exceeds the 1.18e-38 fp32 normal
    minimum by 26 binades, so the clamp makes them unreachable).
    """
    x = x.astype(jnp.float32)
    xi = lax.bitcast_convert_type(x, jnp.int32)
    e = jnp.right_shift(xi, 23) - 127
    m = lax.bitcast_convert_type(
        jnp.bitwise_or(jnp.bitwise_and(xi, 0x007FFFFF), 0x3F800000),
        jnp.float32,
    )
    t = m - 1.0
    p = jnp.float32(_LOG2_POLY[-1])
    for c in _LOG2_POLY[-2::-1]:
        p = p * t + jnp.float32(c)
    return e.astype(jnp.float32) + p


def entropy2(p: jnp.ndarray, axis: int = -1, floor: float = 1e-12,
             approx: bool = False) -> jnp.ndarray:
    """Shannon entropy in bits with the reference's 1e-12 floor clamp.

    ``approx=True`` swaps the transcendental ``log2`` for
    :func:`log2_approx` (the ``eig_entropy='approx'`` opt-in: max
    |Δlog2| ≤ 1e-5 on the clamped domain, so |ΔH| of a simplex row is
    bounded by the same — errors scale with Σp). The default stays
    byte-identical to the reference lowering.
    """
    pc = jnp.clip(p, floor, None)
    if approx:
        return -(pc * log2_approx(pc)).sum(axis=axis)
    return -(pc * jnp.log2(pc)).sum(axis=axis)


def _uniform_tiebreak(key: jax.Array, ties: jnp.ndarray) -> jnp.ndarray:
    """Uniformly pick one True position of ``ties``; returns scalar int index."""
    u = jax.random.uniform(key, ties.shape)
    return jnp.argmax(jnp.where(ties, u, -1.0))


def masked_argmax_tiebreak(
    key: jax.Array,
    scores: jnp.ndarray,
    mask: jnp.ndarray,
    rtol: float = 0.0,
    atol: float = 0.0,
):
    """Argmax of ``scores`` over positions where ``mask``; uniform among ties.

    Ties are positions with ``isclose(score, max, rtol, atol)`` when a
    tolerance is given (the reference's EIG tie rule is rtol=1e-8 with
    torch's default atol=1e-8 — atol dominates for tiny EIG deltas), else
    exact equality.

    Returns ``(idx, tie_count)`` — ``tie_count > 1`` means the choice was
    stochastic (drives the reference's ``stochastic`` early-stop flag).
    """
    masked = jnp.where(mask, scores, _NEG_INF)
    best = masked.max()
    if rtol > 0 or atol > 0:
        ties = jnp.isclose(masked, best, rtol=rtol, atol=atol) & mask
    else:
        ties = (masked == best) & mask
    n_ties = ties.sum()
    idx_first = jnp.argmax(masked)
    idx_rand = _uniform_tiebreak(key, ties)
    idx = jnp.where(n_ties > 1, idx_rand, idx_first)
    return idx, n_ties


def masked_argmin_tiebreak(key, scores, mask, rtol: float = 0.0,
                           atol: float = 0.0):
    """Argmin counterpart of :func:`masked_argmax_tiebreak`."""
    idx, n_ties = masked_argmax_tiebreak(key, -scores, mask, rtol=rtol,
                                         atol=atol)
    return idx, n_ties


def masked_categorical(
    key: jax.Array,
    weights: jnp.ndarray,
    mask: jnp.ndarray,
):
    """Sample an index proportionally to ``weights`` restricted to ``mask``.

    Returns ``(idx, prob)`` where ``prob`` is the normalized probability of
    the sampled index (the selection probability the LURE estimator needs).
    """
    w = jnp.where(mask, jnp.clip(weights, 0.0, None), 0.0)
    total = w.sum()
    # degenerate fallback: uniform over the mask (reference vma.py:46-49)
    n_mask = jnp.clip(mask.sum(), 1, None)
    probs = jnp.where(total > 1e-12, w / jnp.clip(total, 1e-30, None),
                      mask.astype(w.dtype) / n_mask)
    logits = jnp.log(jnp.clip(probs, 1e-38, None))
    logits = jnp.where(probs > 0, logits, _NEG_INF)
    idx = jax.random.categorical(key, logits)
    return idx, probs[idx]
