"""Fixed-shape selection primitives: masked argmax/argmin with random
tie-breaking, masked categorical sampling, and base-2 entropy.

The reference mutates Python lists (``unlabeled_idxs.remove``) and tie-breaks
with the host RNG (e.g. ``coda/coda.py:306-311``); under jit those become
boolean masks and JAX PRNG keys. Tie-break semantics are preserved: when a
unique extremum exists the result is its (first) index, matching
``torch.argmax``; among ties the choice is uniform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -jnp.inf


def entropy2(p: jnp.ndarray, axis: int = -1, floor: float = 1e-12) -> jnp.ndarray:
    """Shannon entropy in bits with the reference's 1e-12 floor clamp."""
    pc = jnp.clip(p, floor, None)
    return -(pc * jnp.log2(pc)).sum(axis=axis)


def _uniform_tiebreak(key: jax.Array, ties: jnp.ndarray) -> jnp.ndarray:
    """Uniformly pick one True position of ``ties``; returns scalar int index."""
    u = jax.random.uniform(key, ties.shape)
    return jnp.argmax(jnp.where(ties, u, -1.0))


def masked_argmax_tiebreak(
    key: jax.Array,
    scores: jnp.ndarray,
    mask: jnp.ndarray,
    rtol: float = 0.0,
    atol: float = 0.0,
):
    """Argmax of ``scores`` over positions where ``mask``; uniform among ties.

    Ties are positions with ``isclose(score, max, rtol, atol)`` when a
    tolerance is given (the reference's EIG tie rule is rtol=1e-8 with
    torch's default atol=1e-8 — atol dominates for tiny EIG deltas), else
    exact equality.

    Returns ``(idx, tie_count)`` — ``tie_count > 1`` means the choice was
    stochastic (drives the reference's ``stochastic`` early-stop flag).
    """
    masked = jnp.where(mask, scores, _NEG_INF)
    best = masked.max()
    if rtol > 0 or atol > 0:
        ties = jnp.isclose(masked, best, rtol=rtol, atol=atol) & mask
    else:
        ties = (masked == best) & mask
    n_ties = ties.sum()
    idx_first = jnp.argmax(masked)
    idx_rand = _uniform_tiebreak(key, ties)
    idx = jnp.where(n_ties > 1, idx_rand, idx_first)
    return idx, n_ties


def masked_argmin_tiebreak(key, scores, mask, rtol: float = 0.0,
                           atol: float = 0.0):
    """Argmin counterpart of :func:`masked_argmax_tiebreak`."""
    idx, n_ties = masked_argmax_tiebreak(key, -scores, mask, rtol=rtol,
                                         atol=atol)
    return idx, n_ties


def masked_categorical(
    key: jax.Array,
    weights: jnp.ndarray,
    mask: jnp.ndarray,
):
    """Sample an index proportionally to ``weights`` restricted to ``mask``.

    Returns ``(idx, prob)`` where ``prob`` is the normalized probability of
    the sampled index (the selection probability the LURE estimator needs).
    """
    w = jnp.where(mask, jnp.clip(weights, 0.0, None), 0.0)
    total = w.sum()
    # degenerate fallback: uniform over the mask (reference vma.py:46-49)
    n_mask = jnp.clip(mask.sum(), 1, None)
    probs = jnp.where(total > 1e-12, w / jnp.clip(total, 1e-30, None),
                      mask.astype(w.dtype) / n_mask)
    logits = jnp.log(jnp.clip(probs, 1e-38, None))
    logits = jnp.where(probs > 0, logits, _NEG_INF)
    idx = jax.random.categorical(key, logits)
    return idx, probs[idx]
