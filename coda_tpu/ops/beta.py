"""Beta-distribution primitives for the P(best) kernel.

Semantics match the reference's Dirichlet-diagonal -> Beta reduction
(reference ``coda/coda.py:14-25``) and its use of ``torch.distributions.Beta
.log_prob`` on a fixed grid (``coda/coda.py:94``). Everything here is a pure
function of arrays, fp32, with no data-dependent control flow — safe under
jit/vmap/shard_map.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def dirichlet_to_beta(alpha_dirichlet: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Diagonal Beta marginals of per-row Dirichlets.

    Args:
      alpha_dirichlet: ``(..., C, C)`` Dirichlet concentration rows.
    Returns:
      ``(alpha_cc, beta_cc)`` each ``(..., C)``: for row c, the marginal of
      the diagonal entry is Beta(alpha_cc, beta_cc) with
      ``beta_cc = row_sum - alpha_cc``.
    """
    C = alpha_dirichlet.shape[-1]
    alpha_cc = jnp.diagonal(alpha_dirichlet, axis1=-2, axis2=-1)
    beta_cc = alpha_dirichlet.sum(axis=-1) - alpha_cc
    return alpha_cc, beta_cc


def beta_log_pdf(x: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """log Beta(a, b) pdf at x; broadcasts. Same formula torch uses:
    ``(a-1)log x + (b-1)log1p(-x) + lgamma(a+b) - lgamma(a) - lgamma(b)``.
    """
    return (
        (a - 1.0) * jnp.log(x)
        + (b - 1.0) * jnp.log1p(-x)
        + lax.lgamma(a + b)
        - lax.lgamma(a)
        - lax.lgamma(b)
    )


def cumtrapz_uniform(y: jnp.ndarray, dx, axis: int = -1) -> jnp.ndarray:
    """Cumulative trapezoid integral over a uniform grid, zero-initialized.

    The reference accumulates the CDF with a 256-step sequential Python loop
    (``coda/coda.py:98-101``); on TPU that serializes. The identical values
    come from one ``cumsum`` over the per-interval trapezoid areas — O(log P)
    depth instead of O(P) sequential steps.
    """
    y = jnp.moveaxis(y, axis, -1)
    areas = 0.5 * (y[..., 1:] + y[..., :-1]) * dx
    csum = jnp.cumsum(areas, axis=-1)
    out = jnp.concatenate([jnp.zeros_like(y[..., :1]), csum], axis=-1)
    return jnp.moveaxis(out, -1, axis)
