"""Beta-distribution primitives for the P(best) kernel.

Semantics match the reference's Dirichlet-diagonal -> Beta reduction
(reference ``coda/coda.py:14-25``) and its use of ``torch.distributions.Beta
.log_prob`` on a fixed grid (``coda/coda.py:94``). Everything here is a pure
function of arrays, fp32, with no data-dependent control flow — safe under
jit/vmap/shard_map.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def dirichlet_to_beta(alpha_dirichlet: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Diagonal Beta marginals of per-row Dirichlets.

    Args:
      alpha_dirichlet: ``(..., C, C)`` Dirichlet concentration rows.
    Returns:
      ``(alpha_cc, beta_cc)`` each ``(..., C)``: for row c, the marginal of
      the diagonal entry is Beta(alpha_cc, beta_cc) with
      ``beta_cc = row_sum - alpha_cc``.
    """
    C = alpha_dirichlet.shape[-1]
    alpha_cc = jnp.diagonal(alpha_dirichlet, axis1=-2, axis2=-1)
    beta_cc = alpha_dirichlet.sum(axis=-1) - alpha_cc
    return alpha_cc, beta_cc


def beta_log_pdf(x: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """log Beta(a, b) pdf at x; broadcasts. Same formula torch uses:
    ``(a-1)log x + (b-1)log1p(-x) + lgamma(a+b) - lgamma(a) - lgamma(b)``.
    """
    return (
        (a - 1.0) * jnp.log(x)
        + (b - 1.0) * jnp.log1p(-x)
        + lax.lgamma(a + b)
        - lax.lgamma(a)
        - lax.lgamma(b)
    )


def sparse_rows_to_beta(
    diag: jnp.ndarray, vals: jnp.ndarray, resid: jnp.ndarray,
    *, includes_diag: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Diagonal Beta marginals straight from COMPACT class rows.

    The sparse posterior tier (``ops/sparse_rows.py``) stores each
    Dirichlet row as its diagonal, top-K off-diagonal values, and one
    residual mass. The Beta reduction only needs the diagonal and the
    row's total off-diagonal mass, so the compact form feeds the G-point
    quadrature directly — temps scale with K, not C (the dense
    :func:`dirichlet_to_beta` reduces the full ``(..., C, C)`` tensor,
    a 2 GB read per round at ImageNet scale).

    Args:
      diag:  ``(..., C)`` exact diagonal concentrations.
      vals:  ``(..., C, K)`` tracked off-diagonal values — or, in the
        K=C parity layout (``includes_diag=True``), the full dense rows
        with the diagonal at its column position.
      resid: ``(..., C)`` untracked off-diagonal mass (zero in the
        parity layout).
    Returns:
      ``(alpha_cc, beta_cc)`` each ``(..., C)``.
    """
    if includes_diag:
        return diag, vals.sum(axis=-1) - diag
    return diag, vals.sum(axis=-1) + resid


# -- amortized predictive-uncertainty approximation (arXiv 1905.12194) -----
#
# The Laplace-bridge / logistic-normal moment matching of 1905.12194 maps
# a Dirichlet to a Gaussian in softmax basis; its two-class reduction maps
# Beta(a, b) to logit(X) ~ N(digamma(a) - digamma(b),
# polygamma(1, a) + polygamma(1, b)). pdf and cdf of X then have CLOSED
# forms (Gaussian phi / log-ndtr of the logit) — no lgamma grids and no
# cumulative-trapezoid CDF construction, which is what lets the
# ``eig_pbest='amortized'`` rung replace the Beta quadrature tables.

def beta_logit_normal_params(
    a: jnp.ndarray, b: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Logistic-normal (Laplace-bridge) parameters of Beta(a, b):
    ``(mu, sigma)`` of the matched Gaussian in logit space."""
    from jax.scipy.special import digamma, polygamma

    mu = digamma(a) - digamma(b)
    var = polygamma(1, a) + polygamma(1, b)
    return mu, jnp.sqrt(var)


def logit_normal_log_pdf(x: jnp.ndarray, mu: jnp.ndarray,
                         sigma: jnp.ndarray) -> jnp.ndarray:
    """log pdf at x in (0, 1) of the logistic-normal; broadcasts."""
    z = (jnp.log(x) - jnp.log1p(-x) - mu) / sigma
    return (-0.5 * z * z - 0.5 * jnp.log(2.0 * jnp.pi) - jnp.log(sigma)
            - jnp.log(x) - jnp.log1p(-x))


def logit_normal_log_cdf(x: jnp.ndarray, mu: jnp.ndarray,
                         sigma: jnp.ndarray) -> jnp.ndarray:
    """log cdf at x in (0, 1) of the logistic-normal — closed form
    (``log_ndtr``), replacing the quadrature's cumtrapz+log chain."""
    from jax.scipy.special import log_ndtr

    z = (jnp.log(x) - jnp.log1p(-x) - mu) / sigma
    return log_ndtr(z)


def cumtrapz_uniform(y: jnp.ndarray, dx, axis: int = -1) -> jnp.ndarray:
    """Cumulative trapezoid integral over a uniform grid, zero-initialized.

    The reference accumulates the CDF with a 256-step sequential Python loop
    (``coda/coda.py:98-101``); on TPU that serializes. The identical values
    come from one ``cumsum`` over the per-interval trapezoid areas — O(log P)
    depth instead of O(P) sequential steps.
    """
    y = jnp.moveaxis(y, axis, -1)
    areas = 0.5 * (y[..., 1:] + y[..., :-1]) * dx
    csum = jnp.cumsum(areas, axis=-1)
    out = jnp.concatenate([jnp.zeros_like(y[..., :1]), csum], axis=-1)
    return jnp.moveaxis(out, -1, axis)
