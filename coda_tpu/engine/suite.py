"""In-process benchmark suite: all tasks x methods x seeds in one process.

The reference fans the same sweep out as one SLURM job per task-method pair
(reference ``scripts/launch_all_methods.py:135-153``), so every job pays
process startup, data load, and warm-up — and needs a cluster. On TPU the
whole sweep fits one process:

  * seeds are a ``vmap`` axis (not serial reruns);
  * each method's experiment program takes the prediction tensor as a traced
    argument (``make_batched_experiment_fn``), so the jit compile cache is
    keyed by *shape*, not task — the 12 DomainNet126 tasks share one
    executable per method, GLUE tasks likewise;
  * tasks are grouped by shape and run back-to-back on-device, with metrics
    streamed to the tracking store afterward.

``scripts/run_suite.py`` is the CLI; the SLURM launcher remains for
multi-node fan-out where one host's HBM can't hold a task. On multi-chip
hosts ``run_batched(devices=...)`` hands the dispatch loop to the
task-parallel scheduler (``engine/scheduler.py``): independent
(family-chunk, method) dispatches placed on distinct devices, LPT-ordered
from the per-family warm cost profile, results harvested through a
deferred pending-futures queue — bitwise-identical to serial dispatch.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np

from coda_tpu.engine.loop import make_batched_experiment_fn
from coda_tpu.losses import LOSS_FNS


@dataclass
class PendingBatch:
    """One in-flight ``run_batched`` dispatch awaiting host harvest.

    ``r0``/``rest`` are device results whose computation (and, once
    ``copy_to_host_async`` has been issued, device-to-host copy) may still
    be running — jax's async dispatch returns them as futures. Harvesting
    (:meth:`SuiteRunner._harvest_batch`) blocks on them; everything the
    harvest needs to unpack, log, and attribute the chunk rides here."""

    names: list
    method: str
    shape: tuple
    cold: bool
    r0: object
    rest: object
    t_start: float          # perf_counter at dispatch
    device: object = None   # jax Device under scheduled placement, else None
    cost: float = 0.0       # scheduler's relative LPT weight (telemetry)
    heavy: bool = False     # memory-heavy (method has a batch_caps entry)
    t_end: float = field(default=0.0)  # set by harvest
    aux: object = None      # flight-recorder RunTraceAux of the probe
    #                         dispatch (record_dir runners only)
    resolved: list = field(default_factory=list)  # per-task resolved
    #                         hyperparams (the record's knob block)

def family_of(name: str) -> str:
    """Task-name family: the prefix before a trailing ``_<index>``
    (``domainnet_3`` -> ``domainnet``); a name without a numeric suffix is
    its own family. The ONE definition shared by the warm profiles and the
    scheduler's LPT cost model, so profile keys always match cost keys."""
    fam, _, idx = name.rpartition("_")
    return fam if fam and idx.isdigit() else name


def _warm_profile(pairs) -> tuple[dict, dict]:
    """Per-method and per-family WARM seconds from the pair records.

    "Warm" = pairs that did not pay a jit compile (``cold`` False), so on
    a steady-state rerun — where every executable is cached — these ARE
    the per-method / per-family steady-state breakdown the cold-inclusive
    ``per_method_s`` cannot provide (a method whose 26 pairs are all cold
    reports compile time, not compute). Family per :func:`family_of`.
    """
    per_method: dict = {}
    per_family: dict = {}
    for p in pairs:
        if p.get("cold"):
            continue
        fam = family_of(p["task"])
        per_method[p["method"]] = per_method.get(p["method"], 0.0) \
            + p["seconds"]
        per_family[fam] = per_family.get(fam, 0.0) + p["seconds"]
    return ({k: round(v, 3) for k, v in per_method.items()},
            {k: round(v, 3) for k, v in per_family.items()})


# Hyperparams passed to the jitted program as TRACED runtime scalars instead
# of being baked into the executable: the per-task tuned values then share
# one compile (and one task-batch group) across tasks. ModelPicker's ε is
# the one task-dependent hyperparam in the benchmark (reference
# ``coda/baselines/modelpicker.py:5-35``).
RUNTIME_HYPERPARAMS = {"model_picker": ("epsilon",)}


class SuiteRunner:
    """Runs (task, method) pairs, reusing compiled programs across tasks.

    One jitted callable is kept per (method-config, iters) pair; jax's
    compile cache then re-specializes per tensor shape only — running the
    whole 26-task reference benchmark costs a handful of compiles, not
    26 x methods.
    """

    def __init__(self, iters: int = 100, seeds: int = 5, loss: str = "acc",
                 dedup_seeds: bool = True, telemetry=None,
                 record_dir: Optional[str] = None, record_topk: int = 8,
                 cost_capture: bool = True):
        import jax

        self.iters = iters
        self.seeds = seeds
        self.loss_fn = LOSS_FNS[loss]
        self._loss_name = loss
        # decision flight recorder: with a record_dir, every (task, method)
        # pair's seed-0 PROBE dispatch carries the per-round provenance tap
        # and lands as one record under the per-(family, method) stream
        # `<record_dir>/<family>__<method>/<task>/` — the probe is the one
        # program both the dedup and batched paths always run, so stream
        # coverage is uniform across execution modes. Replay/diff with
        # `python -m coda_tpu.cli replay <dir> [--against <dir>]`.
        self.record_dir = record_dir
        self.record_topk = int(record_topk)
        self._digests: dict = {}   # task name -> dataset digest (hash once)
        # optional telemetry.Telemetry: every dispatch becomes a span on its
        # device lane, cold dispatches feed the recompile-fallback counter,
        # and HBM watermarks are sampled after each harvest
        self.telemetry = telemetry
        # the reference's deterministic-method optimization (reference
        # main.py:128-130,166-168): run seed 0 alone; only when the method
        # reports randomness actually mattered (ties, sampling) run the
        # remaining seeds. Cuts 5x compute for CODA/uncertainty on tie-free
        # tasks at the cost of one extra (1-seed) compile per method.
        self.dedup_seeds = dedup_seeds
        # per-executable cost attribution (telemetry/costs.py): each jitted
        # experiment program is wrapped in a CostTracked that AOT-compiles
        # per argument signature (the same one compile the jit cache would
        # pay) and harvests XLA's cost/memory analysis — so the scheduler's
        # per-device executables and the serial path's per-shape programs
        # all land in the process cost book with FLOPs/bytes/roofline.
        # This per-runner knob composes with the process-wide kill switch
        # (costs.set_enabled, the cli's --no-cost-capture): harvesting
        # happens only when BOTH are on.
        self.cost_capture = bool(cost_capture)
        self._jitted: dict = {}
        # cold attribution persists across run()/run_batched() calls, like
        # the jit cache it mirrors: a warm RERUN on the same runner pays no
        # compiles, so none of its pairs may be marked cold — that would
        # silently drop the first pair of every shape from the
        # per-method/per-family warm (steady-state) profile
        self._seen_shapes: set = set()
        self._keys = jax.numpy.stack(
            [jax.random.PRNGKey(s) for s in range(seeds)]
        )
        self._jax = jax

    def _tele_cold(self, cold: bool) -> None:
        """Feed the telemetry recompile evidence from the runner's own
        shape-keyed cold attribution — the timing-based fallback that stays
        live even where ``jax.monitoring`` hooks are unavailable."""
        if cold and self.telemetry is not None:
            self.telemetry.counter(
                "suite_cold_dispatches_total",
                "Suite dispatches that paid a jit compile "
                "(shape-keyed cold attribution)").inc()

    def _tele_span(self, name: str, device, t_start: float, t_end: float,
                   attrs: Optional[dict] = None) -> None:
        """Record one finished dispatch as a span on its device lane and
        sample that device's HBM watermark (no-op without telemetry)."""
        tele = self.telemetry
        if tele is None:
            return
        dev_id = device.id if device is not None else 0
        tele.spans.record(name, lane=f"device:{dev_id}",
                          t_start=t_start, t_end=t_end, attrs=attrs)
        tele.sample_devices([device] if device is not None else None)

    def _dataset_digest(self, name: str, preds=None, labels=None):
        """Hash a task's tensors once per runner (records of every method
        share the cached digest)."""
        if name not in self._digests and preds is not None:
            from coda_tpu.telemetry.recorder import dataset_digest

            self._digests[name] = dataset_digest(preds, labels)
        return self._digests.get(name)

    def _write_record_stream(self, task: str, method: str, shape, result,
                             aux, resolved: Optional[dict],
                             n_parallel: int, dataset=None) -> str:
        """Write one probe record into the per-(family, method) stream
        ``<record_dir>/<family>__<method>/<task>/``."""
        from coda_tpu.telemetry.recorder import (
            RunRecord,
            environment_fingerprint,
            stream_dir,
        )

        digest = self._dataset_digest(
            task, getattr(dataset, "preds", None),
            getattr(dataset, "labels", None))
        knobs = dict(resolved or {})
        knobs.update(method=method, loss=self._loss_name, iters=self.iters,
                     n_parallel=n_parallel)
        fp = environment_fingerprint(knobs=knobs)
        fp["dataset"] = {"name": task, "shape": list(shape),
                         "digest": digest}
        seeds_rec = int(np.asarray(result.chosen_idx).shape[0])
        rec = RunRecord.from_result(
            result, aux, fp,
            run={"task": task, "method": method, "iters": self.iters,
                 "loss": self._loss_name, "seeds": seeds_rec,
                 "stream": "suite"})
        out = stream_dir(self.record_dir, f"{family_of(task)}__{method}",
                         task)
        rec.save(out, registry=(self.telemetry.registry
                                if self.telemetry is not None else None))
        return out

    def _resolved_args(self, method: str, method_args: Optional[dict],
                       task_name: str) -> dict:
        """Method hyperparams with task-dependent values resolved.

        Task-dependent hyperparams must be resolved BEFORE a jit-cache key
        is formed: ``build_selector_factory`` bakes them into the jitted
        closure, so two tasks with different tuned values must not share an
        executable (but tasks resolving to the same value still do)."""
        resolved = dict(method_args or {})
        if method == "model_picker" and "epsilon" not in resolved:
            from coda_tpu.selectors import TASK_EPS
            from coda_tpu.selectors.modelpicker import DEFAULT_EPS

            resolved["epsilon"] = TASK_EPS.get(task_name, DEFAULT_EPS)
        return resolved

    def _static_resolved(self, resolved: dict, method: str) -> dict:
        """The subset of resolved hyperparams that keys an executable —
        runtime-traced ones (ModelPicker's ε) are excluded."""
        runtime = RUNTIME_HYPERPARAMS.get(method, ())
        return {k: v for k, v in resolved.items() if k not in runtime}

    def _extra_args(self, method: str, resolved_list: Sequence[dict],
                    batched: bool = False):
        """Runtime-hyperparam tuple for a call: each entry is a f32 scalar
        (``run_one``) or a (T,) array (``run_batched`` — always rank 1,
        the task-axis vmap maps it with in_axes=0 even at T=1)."""
        runtime = RUNTIME_HYPERPARAMS.get(method, ())
        jnp = self._jax.numpy
        out = []
        for k in runtime:
            vals = [r[k] for r in resolved_list]
            out.append(jnp.asarray(vals if batched else vals[0],
                                   jnp.float32))
        return tuple(out)

    def _fn_for(self, method: str, method_args: Optional[dict],
                task_name: str, width: int = 1, n_tasks: int = 0,
                record: bool = False):
        # ``width`` = how many seed replicas this executable batches (the
        # dedup path runs batches of 1 and seeds-1): it keys the cache and
        # feeds the auto eig_mode memory budget, so the 1-seed probe is
        # never forced off the incremental kernel by replicas that don't
        # share its program. ``n_tasks`` > 0 wraps the experiment in a
        # second vmap over a leading TASK axis (the run_batched path) —
        # the budget then sees width x n_tasks replicas. ``record`` builds
        # the flight-recorder program (returns ``(result, aux)``; the base
        # outputs' trajectory is the unrecorded program's).
        from coda_tpu.cli import build_selector_factory, parse_args

        resolved = self._resolved_args(method, method_args, task_name)
        runtime = RUNTIME_HYPERPARAMS.get(method, ())
        static = self._static_resolved(resolved, method)
        trace_k = self.record_topk if record else 0
        key = (method, tuple(sorted(static.items())), width, n_tasks,
               trace_k)
        if key not in self._jitted:
            args = parse_args([])
            args.method = method
            args.loss = [k for k, v in LOSS_FNS.items() if v is self.loss_fn][0]
            args.iters = self.iters
            args.n_parallel = max(1, width * max(1, n_tasks))
            for k, v in static.items():
                setattr(args, k, v)
            if method == "model_picker" and "epsilon" in runtime:
                from coda_tpu.selectors import make_modelpicker

                def factory(preds, eps):
                    return make_modelpicker(preds, epsilon=eps)
            else:
                factory = build_selector_factory(args, task_name)
            fn = make_batched_experiment_fn(factory, self.iters,
                                            self.loss_fn, trace_k=trace_k)
            if n_tasks:
                # (T, H, N, C) preds, (T, N) labels, shared seed keys,
                # per-task runtime hyperparams (T,)
                in_axes = (0, 0, None) + (0,) * len(runtime)
                fn = self._jax.vmap(fn, in_axes=in_axes)
            jfn = self._jax.jit(fn)
            if self.cost_capture:
                import hashlib

                from coda_tpu.telemetry.costs import CostTracked

                label = (f"suite/{method}/w{width}"
                         + (f"/x{n_tasks}" if n_tasks else "")
                         + ("/rec" if trace_k else ""))
                if static:
                    # static hyperparams key the _jitted cache; they must
                    # key the cost-book name too or two configs of one
                    # method would silently overwrite each other's entry
                    label += "/h" + hashlib.sha256(
                        repr(sorted(static.items())).encode()
                    ).hexdigest()[:6]
                jfn = CostTracked(
                    jfn, name=label, site="suite",
                    registry=(self.telemetry.registry
                              if self.telemetry is not None else None),
                    extra={"method": method, "width": width,
                           "n_tasks": n_tasks})
            self._jitted[key] = jfn
        return self._jitted[key]

    def run_one(self, method: str, dataset, method_args: Optional[dict] = None):
        """One task-method pair, all seeds batched. Returns ExperimentResult.

        Under ``dedup_seeds`` the seed-0 probe (width 1) and the remaining
        seeds (width ``seeds - 1``) are separate jit programs, and under the
        ``eig_mode='auto'`` budget the two widths can resolve to DIFFERENT
        kernel tiers. The tiers are score-parity-tested against each other,
        but a near-tie EIG argmax can still diverge between tiers, so for
        stochastic methods seed 0's trace is not strictly exchangeable with
        the other seeds'. Deliberate: total device work stays exactly
        ``seeds`` experiments; pin ``eig_mode`` explicitly if strict
        cross-seed tier homogeneity matters more than the auto budget.
        """
        resolved_one = self._resolved_args(method, method_args, dataset.name)
        extra = self._extra_args(method, [resolved_one])
        record = bool(self.record_dir)
        if self.dedup_seeds and self.seeds > 1:
            fn = self._fn_for(method, method_args, dataset.name, width=1,
                              record=record)
            # seed 0 runs alone; deterministic -> broadcast, stochastic ->
            # run only the REMAINING seeds and concatenate (the probe result
            # is kept, never recomputed). Total device work is exactly
            # ``seeds`` experiments either way; two batch sizes (1, seeds-1)
            # get compiled per method instead of one.
            r0 = fn(dataset.preds, dataset.labels, self._keys[:1], *extra)
            if record:
                r0, aux = r0
                self._write_record_stream(dataset.name, method,
                                          dataset.shape, r0, aux,
                                          resolved_one, n_parallel=1,
                                          dataset=dataset)
            if not bool(np.asarray(r0.stochastic)[0]):
                # deterministic run: every seed is identical — broadcast
                return type(r0)(*[
                    np.repeat(np.asarray(x), self.seeds, axis=0) for x in r0
                ])
            rest_fn = self._fn_for(method, method_args, dataset.name,
                                   width=self.seeds - 1)
            rest = rest_fn(dataset.preds, dataset.labels, self._keys[1:],
                           *extra)
            return type(r0)(*[
                np.concatenate([np.asarray(a), np.asarray(b)], axis=0)
                for a, b in zip(r0, rest)
            ])
        fn = self._fn_for(method, method_args, dataset.name,
                          width=self.seeds, record=record)
        res = fn(dataset.preds, dataset.labels, self._keys, *extra)
        if record:
            res, aux = res
            self._write_record_stream(dataset.name, method, dataset.shape,
                                      res, aux, resolved_one,
                                      n_parallel=self.seeds, dataset=dataset)
        return res

    def run(
        self,
        datasets: Sequence,
        methods: Sequence[str],
        store=None,
        force_rerun: bool = False,
        method_args: Optional[dict] = None,
        progress: Callable[[str], None] = print,
    ) -> dict:
        """The full sweep. Returns {(task, method): ExperimentResult}.

        Tasks are ordered by shape so same-shape tasks run consecutively off
        one compiled program. With a tracking ``store``, finished task-method
        pairs are skipped (the reference launcher's DB-checked resume,
        ``scripts/launch_all_methods.py:30-43``) and results land in the same
        experiment -> parent -> seed-child layout the analysis SQL expects.
        """
        results: dict = {}
        # items may be Datasets or zero-arg loaders (lazy: the 26-task
        # reference benchmark sums to ~60 GB of tensors — far over one
        # chip's HBM — so tasks must be loaded/freed one at a time).
        # Concrete datasets are ordered by shape for compile reuse; loaders
        # keep caller order (callers sort by file size).
        datasets = sorted(
            datasets,
            key=lambda d: (0,) + tuple(d.shape) if hasattr(d, "shape")
            else (1,),
        )
        t_start = time.perf_counter()
        t_load = 0.0
        t_compute = 0.0
        pairs: list = []  # per task-method timing records (for BENCH_SUITE)
        seen_shapes = self._seen_shapes
        for ds_or_loader in datasets:
            lazy = callable(ds_or_loader)
            t0 = time.perf_counter()
            ds = ds_or_loader() if lazy else ds_or_loader
            t_load += time.perf_counter() - t0
            for method in methods:
                if store is not None and not force_rerun and _finished(
                    store, ds.name, method, self.seeds
                ):
                    progress(f"skip {ds.name}/{method} (finished)")
                    continue
                # cold attribution mirrors the jit-cache granularity: the
                # executable keys on (method, static resolved hyperparams,
                # width) and re-specializes per shape — runtime-traced
                # hyperparams (ModelPicker's ε) deliberately absent
                shape_key = (method, tuple(sorted(self._static_resolved(
                    self._resolved_args(method, method_args, ds.name),
                    method).items())), tuple(ds.shape))
                cold = shape_key not in seen_shapes  # first run pays compile
                seen_shapes.add(shape_key)
                self._tele_cold(cold)
                t0 = time.perf_counter()
                res = self.run_one(method, ds, method_args)
                res = _to_host(res)  # sync + free device result buffers
                t1 = time.perf_counter()
                dt = t1 - t0
                t_compute += dt
                self._tele_span(f"{ds.name}/{method}", None, t0, t1,
                                {"task": ds.name, "method": method,
                                 "cold": cold})
                pairs.append({"task": ds.name, "method": method,
                              "shape": list(ds.shape), "seconds": dt,
                              "cold": cold})
                progress(f"{ds.name}/{method}: {self.seeds} seeds x "
                         f"{self.iters} iters in {dt:.2f}s"
                         f"{' (incl. compile)' if cold else ''}")
                results[(ds.name, method)] = res
                if store is not None:
                    _log(store, ds.name, method, res, self.seeds, self.iters)
            if lazy:
                del ds  # drop the device tensor before the next task loads
        total = time.perf_counter() - t_start
        warm_m, warm_f = _warm_profile(pairs)
        self.last_stats = {"total_s": total, "load_s": t_load,
                           "compute_s": t_compute,
                           "compute_device_s": t_compute, "pairs": pairs,
                           "per_method_warm_s": warm_m,
                           "per_family_warm_s": warm_f}
        progress(f"suite: {len(results)} task-method pairs in {total:.2f}s "
                 f"(compute {t_compute:.2f}s, data load {t_load:.2f}s)")
        return results

    def run_batched(
        self,
        groups: Sequence[Sequence],
        methods: Sequence[str],
        store=None,
        force_rerun: bool = False,
        method_args: Optional[dict] = None,
        batch_caps: Optional[dict] = None,
        progress: Callable[[str], None] = print,
        devices=None,
        schedule: str = "lpt",
        cost_profile: Optional[dict] = None,
        max_inflight: int = 2,
        hosts=None,
    ) -> dict:
        """The sweep with same-shape tasks BATCHED into one program.

        ``groups``: lists of datasets-or-loaders; within a group every task
        must share its (H, N, C) shape and resolve identical *static* method
        hyperparams (runtime-traced ones — ModelPicker's per-task ε — ride
        along as a (T,) argument, so mixed tuned values batch fine).
        Each (group, method) pair costs TWO program dispatches (the width-1
        seed probe over all T tasks, then the remaining seeds), instead of
        ``run``'s one-or-two per task — the dispatch-count lever for hosts
        where per-program latency dominates the suite (measured round 4:
        the 156-pair sweep on a tunneled v5e was ~80% per-dispatch floor).

        Semantics match ``run`` + ``dedup_seeds`` exactly: per task, a
        deterministic probe broadcasts and the rest-batch result is
        DISCARDED (the rest batch is computed unconditionally here — the
        price of batching is wasted rest-compute for deterministic tasks,
        cheap on an accelerator; the statistical contract is unchanged).
        With a ``store``, only the UNFINISHED subset of a group is stacked
        and dispatched (``run``'s resume semantics — finished tasks are
        skipped, not recomputed; a partial subset keys a separate T so it
        costs one extra compile per distinct todo-count).
        ``batch_caps`` maps method -> max tasks per dispatch (an int, or a
        callable ``(H, N, C) -> int`` evaluated per group shape):
        memory-heavy methods (CODA's per-replica incremental cache is as
        large as the prediction tensor itself) split a group into
        sub-batches so the auto eig_mode budget keeps the fast tier, while
        cheap methods still batch the whole group.
        Tasks inside a group share one vmapped executable, so the auto
        eig_mode budget sees T x width replicas and may resolve a
        different tier than ``run`` would — the tiers are
        score-parity-tested, same caveat as ``run_one``'s dedup note.
        Sharded prediction tensors are not supported here (the task axis
        would need its own mesh dimension); use ``run``.

        ``devices`` opts into the task-parallel scheduler
        (``engine/scheduler.py``): independent (chunk, method) dispatches
        are placed on distinct local devices — 'auto' (all local devices),
        an int count, or an explicit device list — ordered
        longest-processing-time-first by ``schedule='lpt'`` using
        ``cost_profile`` (a ``per_family_warm_s``/``per_method_warm_s``
        dict from a prior run's ``last_stats`` or a committed bench
        artifact; uniform weights when absent), with results harvested
        through a deferred pending-futures queue instead of an inline
        blocking copy. ``max_inflight`` bounds queued chunks per device;
        methods with a ``batch_caps`` entry are treated as memory-heavy
        and are never co-resident with another heavy chunk on one device.
        Placement never changes numerics: the scheduled results are
        bitwise identical to ``devices=None`` (same executables, same
        seed keys — pinned by ``tests/test_scheduler.py``).
        ``devices=None`` (default) is the serial path.
        ``hosts`` (with ``devices``) opts into two-level FLEET placement:
        chunks go to host groups by weighted LPT, then to devices within
        each group (``engine/scheduler.plan_fleet_schedule``) — still
        bitwise identical; see ``run_scheduled``.
        """
        if devices is not None:
            from coda_tpu.engine.scheduler import run_scheduled

            return run_scheduled(
                self, groups, methods, store=store, force_rerun=force_rerun,
                method_args=method_args, batch_caps=batch_caps,
                progress=progress, devices=devices, schedule=schedule,
                cost_profile=cost_profile, max_inflight=max_inflight,
                hosts=hosts)
        results: dict = {}
        t_start = time.perf_counter()
        t_load = 0.0
        t_compute = 0.0
        pairs: list = []
        seen_shapes = self._seen_shapes
        for group in groups:
            t0 = time.perf_counter()
            datasets = [d() if callable(d) else d for d in group]
            t_load += time.perf_counter() - t0
            names, planned = self._plan_group(
                datasets, methods, store, force_rerun, batch_caps, progress)
            for method, chunk in planned:
                pend = self._launch_batch(
                    chunk, names, datasets, method, method_args,
                    datasets[0].shape, seen_shapes)
                self._harvest_batch(pend, store, pairs, results,
                                    progress)
                # serial: each chunk's wall IS its device time (the
                # harvest blocks inline), so the two compute totals
                # coincide here — they diverge under the scheduler
                t_compute += pend.t_end - pend.t_start
        total = time.perf_counter() - t_start
        warm_m, warm_f = _warm_profile(pairs)
        self.last_stats = {"total_s": total, "load_s": t_load,
                           "compute_s": t_compute,
                           "compute_device_s": t_compute, "pairs": pairs,
                           "per_method_warm_s": warm_m,
                           "per_family_warm_s": warm_f,
                           "n_devices": 1, "schedule": "serial",
                           "device_timeline": {}, "occupancy": {}}
        progress(f"suite[batched]: {len(results)} task-method pairs in "
                 f"{total:.2f}s (compute {t_compute:.2f}s, data load "
                 f"{t_load:.2f}s)")
        return results

    def _plan_group(self, datasets, methods, store, force_rerun,
                    batch_caps, progress):
        """Validate one loaded group and enumerate its dispatch chunks as
        ``(method, todo_indices)`` pairs — the resume-skip and batch_caps
        chunking shared VERBATIM by the serial loop and the scheduler's
        plan phase (the scheduler's bitwise-parity contract requires the
        chunking, and therefore the executables' T keys, to be identical
        in both paths)."""
        shapes = {tuple(d.shape) for d in datasets}
        if len(shapes) != 1:
            raise ValueError(
                f"run_batched group mixes shapes {sorted(shapes)}; "
                "group tasks by shape"
            )
        names = [d.name for d in datasets]
        planned = []
        for method in methods:
            todo = [
                i for i, n in enumerate(names)
                if force_rerun or not (store is not None and _finished(
                    store, n, method, self.seeds))
            ]
            for i, n in enumerate(names):
                if i not in todo:
                    progress(f"skip {n}/{method} (finished)")
            if not todo:
                continue
            cap = (batch_caps or {}).get(method)
            if callable(cap):
                cap = cap(*datasets[0].shape)
            cap = cap or len(todo)
            planned += [(method, todo[j:j + cap])
                        for j in range(0, len(todo), cap)]
        return names, planned

    def _launch_batch(self, todo, names, datasets, method, method_args,
                      shape, seen_shapes, device=None,
                      cost: float = 0.0) -> PendingBatch:
        """Stack and DISPATCH one chunk of ``todo``'s tasks for one method;
        returns a :class:`PendingBatch` whose device results are still
        in flight (jax async dispatch). The serial path harvests it
        immediately; the scheduler queues it and harvests later so the
        next chunk's host-side stacking overlaps this one's compute."""
        resolved = [self._resolved_args(method, method_args,
                                        names[i]) for i in todo]
        statics = [self._static_resolved(r, method) for r in resolved]
        if any(s != statics[0] for s in statics[1:]):
            raise ValueError(
                f"run_batched: method {method!r} resolves different "
                f"static hyperparams across the group "
                f"{[names[i] for i in todo]}; run these tasks "
                "unbatched"
            )
        T = len(todo)
        # Stack exactly the todo subset from the per-task arrays, per
        # dispatch. The former shape — stack the WHOLE group once, then
        # device-gather `preds[todo]` for partial (resume) batches —
        # transiently held up to ~2x the group's prediction-tensor
        # footprint in HBM, exactly for the memory-heavy method families
        # batch_caps exists to protect (ADVICE round 5). Here the stacked
        # operand never exceeds the dispatched subset, at the cost of
        # re-stacking per (method, chunk) when the group is dispatched
        # whole.
        jnp = self._jax.numpy
        names_m = [names[i] for i in todo]
        keys0, keys_rest = self._keys[:1], self._keys[1:]
        if device is None:
            preds_m = jnp.stack([datasets[i].preds for i in todo])
            labels_m = jnp.stack([datasets[i].labels for i in todo])
        else:
            # scheduled placement: stack on HOST, commit the operands to
            # the target device — jit then runs the per-device executable
            # there. The seed keys ride along committed too (mixing a
            # committed operand with uncommitted keys would work, but
            # pinning everything keeps placement explicit). Pure copies:
            # bitwise identical to the jnp.stack path above.
            put = lambda x: self._jax.device_put(x, device)
            preds_m = put(np.stack(
                [np.asarray(datasets[i].preds) for i in todo]))
            labels_m = put(np.stack(
                [np.asarray(datasets[i].labels) for i in todo]))
            keys0, keys_rest = put(keys0), put(keys_rest)
        extra = self._extra_args(method, resolved, batched=True)
        if device is not None:
            extra = tuple(self._jax.device_put(e, device) for e in extra)
        shape_key = (method, tuple(sorted(statics[0].items())),
                     tuple(shape), T)
        if device is not None:
            # per-device executables each pay their own compile; attribute
            # cold per placement so the warm profile stays compile-free
            shape_key += (device.id,)
        cold = shape_key not in seen_shapes
        seen_shapes.add(shape_key)
        self._tele_cold(cold)
        record = bool(self.record_dir)
        if record:
            # hash each task's tensors once (cached by name) while they are
            # still at hand — the harvest may run after the group is freed
            for i in todo:
                self._dataset_digest(names[i], datasets[i].preds,
                                     datasets[i].labels)
        t0 = time.perf_counter()
        probe_fn = self._fn_for(method, method_args, names_m[0],
                                width=1, n_tasks=T, record=record)
        r0 = probe_fn(preds_m, labels_m, keys0, *extra)
        aux = None
        if record:
            r0, aux = r0
        rest = None
        if self.seeds > 1:
            rest_fn = self._fn_for(method, method_args, names_m[0],
                                   width=self.seeds - 1, n_tasks=T)
            rest = rest_fn(preds_m, labels_m, keys_rest, *extra)
        if device is not None:
            # start the device-to-host copies NOW so they overlap later
            # dispatches; the harvest's np.asarray then finds them done
            for leaf in self._jax.tree_util.tree_leaves((r0, rest, aux)):
                leaf.copy_to_host_async()
        return PendingBatch(names=names_m, method=method,
                            shape=tuple(shape), cold=cold, r0=r0,
                            rest=rest, t_start=t0, device=device,
                            cost=cost, aux=aux, resolved=resolved)

    def _harvest_batch(self, pend: PendingBatch, store, pairs, results,
                       progress) -> None:
        """Block on one pending dispatch, unpack per task (probe
        broadcast / rest concat), log, and append timing records. Under
        the scheduler a chunk's recorded ``seconds`` spans dispatch to
        harvest-complete on ITS device — wall time that includes queue
        wait there, which is why ``compute_device_s`` (the sum of these)
        exceeds ``compute_s`` (the compute region's wall clock) exactly
        when placement achieves concurrency."""
        r0 = _to_host(pend.r0)
        rest = _to_host(pend.rest) if pend.rest is not None else None
        pend.t_end = time.perf_counter()
        dt = pend.t_end - pend.t_start
        self._tele_span(
            f"{pend.method}[x{len(pend.names)}]", pend.device,
            pend.t_start, pend.t_end,
            {"method": pend.method, "tasks": list(pend.names),
             "cold": pend.cold, "est_cost": round(pend.cost, 4)})
        T = len(pend.names)
        method, cold = pend.method, pend.cold
        aux_host = (self._jax.tree.map(np.asarray, pend.aux)
                    if pend.aux is not None else None)
        for t, name in enumerate(pend.names):
            r0_t = type(r0)(*[x[t] for x in r0])
            if aux_host is not None:
                self._write_record_stream(
                    name, method, pend.shape, r0_t,
                    self._jax.tree.map(lambda x: x[t], aux_host),
                    pend.resolved[t] if t < len(pend.resolved) else {},
                    n_parallel=T)
            if rest is None or not bool(np.asarray(
                    r0_t.stochastic)[0]):
                res = type(r0)(*[
                    np.repeat(np.asarray(x), self.seeds, axis=0)
                    for x in r0_t
                ])
            else:
                res = type(r0)(*[
                    np.concatenate(
                        [np.asarray(a), np.asarray(b)[t]], axis=0)
                    for a, b in zip(r0_t, rest)
                ])
            results[(name, method)] = res
            rec = {"task": name, "method": method,
                   "shape": list(pend.shape),
                   "seconds": dt / T, "cold": cold,
                   "batched": T}
            if pend.device is not None:
                rec["device"] = pend.device.id
            pairs.append(rec)
            if store is not None:
                _log(store, name, method, res, self.seeds,
                     self.iters)
        dev = f" @dev{pend.device.id}" if pend.device is not None else ""
        progress(f"[batch x{T}]{dev} {'/'.join(pend.names[:3])}"
                 f"{'...' if T > 3 else ''}/{method}: "
                 f"{self.seeds} seeds x {self.iters} iters in "
                 f"{dt:.2f}s{' (incl. compile)' if cold else ''}")


def _to_host(res):
    """Materialize an ExperimentResult on host (frees device buffers)."""
    return type(res)(*[np.asarray(x) for x in res])


def _finished(store, task: str, method: str, seeds: int) -> bool:
    return all(
        store.is_finished(task, f"{task}-{method}-{s}") for s in range(seeds)
    )


def _log(store, task: str, method: str, res, seeds: int, iters: int) -> None:
    """Log every seed child, always. Seed dedup is a *compute* optimization
    (``run_one`` broadcasts the seed-0 result); logging the broadcast copies
    keeps the DB layout identical for deterministic and stochastic pairs, so
    ``_finished``'s all-children resume check and the reference analysis SQL
    (mean over child runs) need no special cases. The per-seed ``stochastic``
    flag is trajectory-dependent for tie-break methods, so it must not gate
    which seeds get logged."""
    regrets = np.asarray(res.regret)
    cums = np.asarray(res.cumulative_regret)
    stoch = np.asarray(res.stochastic)
    with store.run(task, f"{task}-{method}",
                   params={"method": method, "iters": iters}) as parent:
        for s in range(seeds):
            with store.run(task, f"{task}-{method}-{s}", parent=parent,
                           params={"seed": s,
                                   "stochastic": bool(stoch[s])}) as r:
                r.log_metric_series("regret", regrets[s], start_step=1)
                r.log_metric_series("cumulative regret", cums[s],
                                    start_step=1)
