"""Deterministic replay + divergence triage over flight-recorder records.

The verify half of the decision flight recorder
(``coda_tpu/telemetry/recorder.py``). A recorded run is re-executed through
the **identical recording program** (same ``make_batched_experiment_fn``
trace, same seed-batch width, the recorded root keys as input), so on the
same backend with unchanged knobs the replay is bitwise the recorded run —
any other contract would make XLA fusion choices look like bugs (a
teacher-forced variant was tried first and drifts ~1 ulp on CPU purely from
graph-shape-dependent fusion). The recorded per-round keys and oracle
answers are then *verified* against the replay: a ``round_key`` mismatch
means the key derivation itself changed (its own triage class), and a
``true_class`` mismatch downstream of an idx flip shows the oracle was
consulted differently. Divergence is reported at the FIRST diverging round
— rounds before it agree by definition, rounds after it may cascade and are
reported per quantity but not re-classified. Three comparison modes, one
code path:

  * **replay vs its record** (``python -m coda_tpu.cli replay <dir>``):
    bitwise parity expected on the same backend with the same knobs;
  * **record vs record** (``--against``): e.g. a pallas capture vs an XLA
    capture, or bf16 vs exact — compared under the documented cross-backend
    score contract (``CROSS_BACKEND_SCORE_TOL`` = 2.34e-4);
  * the **dryrun/suite verifiers** (``scripts/dryrun_multichip.py``) reuse
    :func:`compare_records` instead of hand-rolled asserts.

On mismatch the triage report names the first diverging round and the first
diverging quantity, classified as:

  * ``score-delta`` — the acquisition scores themselves moved beyond
    tolerance (numerics change in the scoring chain);
  * ``tie-break-flip`` — scores agree within tolerance but the pick
    changed (near-tie argmax order flipped, e.g. across lowerings);
  * ``posterior-drift`` — decisions agree but the posterior digest
    (P(best) max/entropy or the best-model readout) moved (numerics change
    in the update/readout chain);
  * ``metric-drift`` — only derived metrics (regret) moved.

This turns NOTES_r07-class parity bugs (threefry/GSPMD tie-break
divergence, found by hand in PR 4) into a one-command diagnosis.
"""

from __future__ import annotations

import argparse
import json
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from coda_tpu.telemetry.recorder import (
    CROSS_BACKEND_SCORE_TOL,
    RunRecord,
    dataset_digest,
    environment_fingerprint,
)

# quantity -> triage class, in causal order: a key mismatch explains a
# score delta explains a flip explains posterior drift explains metric
# drift, so the FIRST diverging group at the first diverging round names
# the root cause
_QUANTITY_GROUPS = (
    ("key-drift", ("round_key",)),
    ("score-delta", ("topk_score", "chosen_score", "select_prob")),
    ("tie-break-flip", ("chosen_idx", "true_class")),
    ("posterior-drift", ("pbest_max", "pbest_entropy", "best_model")),
    ("metric-drift", ("regret", "cumulative_regret", "runner_up_gap",
                      "surrogate_fallback")),
)
_INT_QUANTITIES = {"chosen_idx", "true_class", "best_model", "round_key"}


def replay_record(record: RunRecord, selector_factory, preds, labels,
                  loss: str = "acc") -> dict:
    """Re-execute a record's program and return the replayed arrays.

    Runs the IDENTICAL recording program — same
    ``make_batched_experiment_fn(trace_k=...)`` trace, same seed-batch
    width, preds as a traced jit argument — seeded with the record's root
    keys. Same backend + same knobs ⇒ bitwise the recorded arrays."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.engine.loop import make_batched_experiment_fn
    from coda_tpu.losses import LOSS_FNS

    run = record.meta.get("run", {})
    iters = int(run.get("iters", record.rounds))
    fn = make_batched_experiment_fn(
        selector_factory, iters, LOSS_FNS[loss],
        trace_k=int(record.meta.get("trace_k", 8)),
        # re-execute the identical q-wide program: a batched record
        # replays through the same select_q/update_q trace it recorded
        acq_batch=record.acq_batch)
    keys = jnp.asarray(record.arrays["root_key"], jnp.uint32)
    result, aux = jax.jit(fn)(preds, labels, keys)
    return {
        "chosen_idx": np.asarray(result.chosen_idx),
        "true_class": np.asarray(result.true_class),
        "best_model": np.asarray(result.best_model),
        "regret": np.asarray(result.regret),
        "cumulative_regret": np.asarray(result.cumulative_regret),
        "select_prob": np.asarray(result.select_prob),
        "round_key": np.asarray(aux.trace.round_key),
        "topk_idx": np.asarray(aux.trace.topk_idx),
        "topk_score": np.asarray(aux.trace.topk_score),
        "chosen_score": np.asarray(aux.trace.chosen_score),
        "runner_up_gap": np.asarray(aux.trace.runner_up_gap),
        "pbest_max": np.asarray(aux.trace.pbest_max),
        "pbest_entropy": np.asarray(aux.trace.pbest_entropy),
        "surrogate_fallback": np.asarray(aux.trace.surrogate_fallback),
    }


# ---------------------------------------------------------------------------
# comparison + triage (pure numpy — also drives record-vs-record mode)
# ---------------------------------------------------------------------------

def _record_knobs(record: RunRecord) -> dict:
    """A record's fingerprinted knob dict, NORMALIZED for comparison:
    knobs that predate a record are filled with the default the replay
    would rebuild them at (``eig_scorer`` missing == ``'exact'`` — the
    knob landed in PR 14, and without this a fresh exact capture vs any
    older record would spuriously 'differ' on it and silently loosen the
    auto tolerance from bitwise to the 2.34e-4 contract)."""
    knobs = dict(record.meta.get("fingerprint", {}).get("knobs", {}) or {})
    knobs.setdefault("eig_scorer", "exact")
    # crowd-oracle knobs (PR 18): a CLEAN oracle runs the plain-oracle
    # program bitwise, so 'clean'/'none' normalizes to ABSENT — a pre-v4
    # record vs a fresh clean-crowd capture must take the bitwise path,
    # not spuriously 'differ' on a knob that changes nothing. The
    # satellite knobs only mean anything under a noisy spec, so they are
    # dropped alongside it.
    if knobs.get("oracle_noise") in (None, "clean", "none"):
        for key in ("oracle_noise", "oracle_annotators",
                    "oracle_reliability"):
            knobs.pop(key, None)
    # cross-session prior (PR 18): 'off' runs the pre-pool program
    # bitwise, so it normalizes to ABSENT — a pre-pool record vs a fresh
    # --surrogate-prior off capture compares bitwise (the PR-14 pin); the
    # pool-digest satellite knob means nothing without the mode
    if knobs.get("surrogate_prior") in (None, "off"):
        knobs.pop("surrogate_prior", None)
        knobs.pop("surrogate_prior_digest", None)
    return knobs


def _rows_equal(a: np.ndarray, b: np.ndarray, tol: float) -> np.ndarray:
    """(T,) bool: per-round equality, reducing trailing axes. ``tol=0`` is
    bitwise-for-floats (NaN==NaN so an absent posterior digest never
    diverges); integers always compare exact."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.dtype.kind in "iub" or tol == 0.0:
        eq = (a == b)
        if a.dtype.kind == "f":
            eq |= np.isnan(a) & np.isnan(b)
    else:
        eq = np.isclose(a.astype(np.float64), b.astype(np.float64),
                        rtol=0.0, atol=tol, equal_nan=True)
        # two -inf (masked non-candidates) are equal; isclose(inf,inf) is
        # already True, but inf-vs-finite must stay a divergence
    while eq.ndim > 1:
        eq = eq.all(axis=-1)
    return eq


def _max_delta(a: np.ndarray, b: np.ndarray) -> float:
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    d = np.abs(a - b)
    d = np.where(np.isnan(a) & np.isnan(b), 0.0, d)
    # NaN on exactly ONE side is a structural difference (a posterior digest
    # present in one record, absent in the other) — report it as inf, never
    # drop it (nanmax would) or let it poison the max (plain max of NaN)
    d = np.where(np.isnan(a) ^ np.isnan(b), np.inf, d)
    d = np.where(np.isinf(a) & np.isinf(b) & (np.sign(a) == np.sign(b)),
                 0.0, d)
    return float(np.max(d)) if d.size else 0.0


@dataclass
class SeedTriage:
    """Divergence verdict for one seed of a record comparison."""

    seed: int
    parity: bool
    first_divergent_round: Optional[int] = None
    quantity: Optional[str] = None
    classification: Optional[str] = None
    # per-quantity evidence: first diverging round + max |delta| over rounds
    quantities: dict = field(default_factory=dict)
    note: str = ""

    def to_dict(self) -> dict:
        return {
            "seed": self.seed, "parity": self.parity,
            "first_divergent_round": self.first_divergent_round,
            "quantity": self.quantity,
            "classification": self.classification,
            "quantities": self.quantities, "note": self.note,
        }


@dataclass
class ReplayReport:
    """Aggregate verdict of a replay/record comparison."""

    mode: str                    # "replay" | "records"
    score_tol: float
    seeds: list = field(default_factory=list)   # [SeedTriage]
    meta: dict = field(default_factory=dict)

    @property
    def parity(self) -> bool:
        return all(s.parity for s in self.seeds)

    def to_dict(self) -> dict:
        return {
            "mode": self.mode, "parity": self.parity,
            "score_tol": self.score_tol,
            "seeds": [s.to_dict() for s in self.seeds],
            "meta": self.meta,
        }


def compare_seed(rec: dict, rep: dict, score_tol: float = 0.0,
                 seed: int = 0,
                 int_tol_quantities: tuple = ()) -> SeedTriage:
    """Triage one seed's recorded-vs-replayed (or A-vs-B) round arrays.

    ``score_tol`` bounds every float quantity; integer decision quantities
    always compare exact. The first diverging round is located across ALL
    quantities, then classified by the causally-first diverging group at
    that round (see module docstring)."""
    first_by_q: dict = {}
    deltas: dict = {}
    T = int(np.asarray(rec["chosen_idx"]).shape[0])
    for cls_name, quantities in _QUANTITY_GROUPS:
        for q in quantities:
            if q not in rec or q not in rep:
                continue
            # the runner-up gap is a DIFFERENCE of two tol-bounded scores,
            # so its honest bound is 2·tol — comparing it at 1·tol would
            # double-count drift the score comparison already admitted
            tol_q = 2.0 * score_tol if q == "runner_up_gap" else score_tol
            eq = _rows_equal(rec[q], rep[q], tol_q)
            div = np.nonzero(~eq)[0]
            if div.size:
                first_by_q[q] = int(div[0])
                if q not in _INT_QUANTITIES:
                    deltas[q] = _max_delta(rec[q], rep[q])
    if not first_by_q:
        return SeedTriage(seed=seed, parity=True,
                          quantities={"rounds_compared": T})
    t0 = min(first_by_q.values())
    quantity = None
    classification = None
    for cls_name, quantities in _QUANTITY_GROUPS:
        hit = [q for q in quantities if first_by_q.get(q) == t0]
        if hit:
            quantity = hit[0]
            classification = cls_name
            break
    note = ""
    if classification == "tie-break-flip":
        gap = float(np.asarray(rec["runner_up_gap"])[t0])
        note = (f"recorded runner-up gap at round {t0} is {gap:.3e} — "
                f"{'a near-tie; ' if abs(gap) <= max(score_tol, 1e-6) else ''}"
                "scores agree within tolerance but the argmax pick changed")
    info = {q: {"first_divergent_round": r,
                "max_abs_delta": deltas.get(q)}
            for q, r in sorted(first_by_q.items())}
    return SeedTriage(seed=seed, parity=False, first_divergent_round=t0,
                      quantity=quantity, classification=classification,
                      quantities=info, note=note)


def _label_aligned_cum(record: RunRecord, seed: int) -> np.ndarray:
    """Label-indexed cumulative regret of one seed: entry L-1 is the
    cumulative regret after L labels. For q > 1 records each round's
    regret counts its q labels (the engine's label-weighted trace already
    does; re-derive from ``regret`` so v1 and v2 align identically)."""
    q = record.acq_batch
    regret = np.asarray(record.arrays["regret"][seed], np.float64)
    cum = np.cumsum(q * regret)
    return np.repeat(cum, q)  # constant within a round's q labels


def _compare_records_envelope(a: RunRecord, b: RunRecord,
                              classification: str, meta_key: str,
                              label_a: str, label_b: str,
                              force_diff_key: Optional[str] = None
                              ) -> ReplayReport:
    """The shared label-aligned regret-envelope comparison behind every
    knob diff where the two records run genuinely DIFFERENT acquisition
    programs (different ``acq_batch`` widths, different ``eig_scorer``
    rungs): per-round decision parity is not a meaningful contract there
    — what is, is the regret ENVELOPE at equal label budgets. Aligns
    both records' label-weighted cumulative-regret curves on the common
    label prefix and reports, per seed, the final gap/ratio and the
    worst aligned gap under the given triage ``classification``. Parity
    is never claimed."""
    report = ReplayReport(mode="records", score_tol=0.0, meta={
        "a": a.meta.get("run", {}), "b": b.meta.get("run", {}),
        "backend_a": a.meta.get("fingerprint", {}).get("backend"),
        "backend_b": b.meta.get("fingerprint", {}).get("backend"),
    })
    knobs_a = _record_knobs(a)
    knobs_b = _record_knobs(b)
    diff = {key: [knobs_a.get(key), knobs_b.get(key)]
            for key in sorted(set(knobs_a) | set(knobs_b))
            if knobs_a.get(key) != knobs_b.get(key)}
    if force_diff_key:
        diff.setdefault(force_diff_key, [a.acq_batch, b.acq_batch])
    report.meta["knob_diff"] = diff
    n_seeds = min(a.seeds, b.seeds)
    if a.seeds != b.seeds:
        report.meta["seed_count_mismatch"] = {"a": a.seeds, "b": b.seeds,
                                              "compared": n_seeds}
    per_seed = []
    for s in range(n_seeds):
        ca = _label_aligned_cum(a, s)
        cb = _label_aligned_cum(b, s)
        L = min(ca.shape[0], cb.shape[0])
        ca, cb = ca[:L], cb[:L]
        gap = cb - ca
        final_ratio = (float(cb[-1] / ca[-1]) if ca[-1] > 0
                       else (1.0 if cb[-1] <= 0 else float("inf")))
        info = {
            "labels_compared": int(L),
            "final_cum_a": float(ca[-1]), "final_cum_b": float(cb[-1]),
            "final_gap": float(gap[-1]),
            "max_aligned_gap": float(np.max(gap)),
            "final_ratio_b_over_a": final_ratio,
        }
        per_seed.append(info)
        report.seeds.append(SeedTriage(
            seed=s, parity=False, first_divergent_round=0,
            quantity="cumulative_regret",
            classification=classification,
            quantities={"cumulative_regret": info},
            note=(f"label-aligned regret envelope over {L} labels: "
                  f"final {ca[-1]:.4f} ({label_a}) vs "
                  f"{cb[-1]:.4f} ({label_b}), "
                  f"ratio {final_ratio:.3f}, "
                  f"max aligned gap {np.max(gap):.4f}")))
    report.meta[meta_key] = {
        "a": label_a, "b": label_b, "seeds": per_seed,
        "max_final_ratio_b_over_a": max(
            (i["final_ratio_b_over_a"] for i in per_seed), default=None),
        "max_aligned_gap": max(
            (i["max_aligned_gap"] for i in per_seed), default=None),
    }
    return report


def compare_records_batchq(a: RunRecord, b: RunRecord) -> ReplayReport:
    """The q-vs-q' comparison (``--against`` across different acq_batch
    knobs); triage class ``acq-batch-envelope``."""
    report = _compare_records_envelope(
        a, b, classification="acq-batch-envelope",
        meta_key="batchq_envelope",
        label_a=f"q={a.acq_batch}", label_b=f"q={b.acq_batch}",
        force_diff_key="acq_batch")
    report.meta["batchq_envelope"].update(
        {"q_a": a.acq_batch, "q_b": b.acq_batch})
    return report


def _scorer_knob(record: RunRecord) -> str:
    return str(record.meta.get("fingerprint", {}).get("knobs", {}).get(
        "eig_scorer") or "exact")


def compare_records_scorer(a: RunRecord, b: RunRecord) -> ReplayReport:
    """The surrogate-vs-exact comparison (``--against`` across different
    ``eig_scorer`` rungs): the surrogate's score VECTOR legitimately
    differs outside the refreshed shortlist (unrefreshed rows carry
    predictions), so a score tolerance would report a fake divergence —
    the honest contract is the regret envelope at equal label budgets.
    Triage class ``eig-scorer-envelope`` — the knob-diff path
    ``cli replay --against`` auto-resolves to."""
    report = _compare_records_envelope(
        a, b, classification="eig-scorer-envelope",
        meta_key="scorer_envelope",
        label_a=f"eig_scorer={_scorer_knob(a)}",
        label_b=f"eig_scorer={_scorer_knob(b)}")
    report.meta["scorer_envelope"].update(
        {"scorer_a": _scorer_knob(a), "scorer_b": _scorer_knob(b)})
    return report


def _oracle_knob(record: RunRecord) -> str:
    """A record's normalized ``--oracle-noise`` spec: 'clean' when absent
    (every pre-v4 record) or when explicitly clean."""
    spec = record.meta.get("fingerprint", {}).get("knobs", {}).get(
        "oracle_noise")
    return "clean" if spec in (None, "clean", "none") else str(spec)


def compare_records_oracle(a: RunRecord, b: RunRecord) -> ReplayReport:
    """The clean-vs-noisy (or noisy-vs-noisy) oracle comparison
    (``--against`` across different ``--oracle-noise`` specs): a noisy
    crowd legitimately labels with corrupted answers, so per-round
    decision parity is not the contract — the regret ENVELOPE at equal
    label budgets is (how much selection quality the noise model costs).
    Triage class ``oracle-noise-envelope``, the crowd analogue of
    ``acq-batch-envelope``."""
    report = _compare_records_envelope(
        a, b, classification="oracle-noise-envelope",
        meta_key="oracle_envelope",
        label_a=f"oracle={_oracle_knob(a)}",
        label_b=f"oracle={_oracle_knob(b)}")
    report.meta["oracle_envelope"].update(
        {"oracle_a": _oracle_knob(a), "oracle_b": _oracle_knob(b)})
    return report


def _prior_knob(record: RunRecord) -> str:
    """A record's normalized ``--surrogate-prior`` mode, digest-qualified:
    'off' when absent (every pre-pool record); a pool-seeded record is
    ``pool@<digest>`` — two runs seeded from DIFFERENT pools ran
    different warm-starts and must not be conflated."""
    knobs = record.meta.get("fingerprint", {}).get("knobs", {}) or {}
    mode = knobs.get("surrogate_prior")
    if mode in (None, "off"):
        return "off"
    digest = knobs.get("surrogate_prior_digest")
    return f"{mode}@{digest}" if digest else str(mode)


def compare_records_prior(a: RunRecord, b: RunRecord) -> ReplayReport:
    """The warm-vs-cold comparison (``--against`` across different
    ``--surrogate-prior`` modes, or across different pool digests): a
    pool-seeded run legitimately skips already-paid exact warmup rounds,
    so per-round decision parity is not the contract — the regret
    ENVELOPE at equal label budgets is (how much selection quality the
    transferred prior costs, which the BENCH_PRIOR gate bounds at 1.05x
    + 0.02 absolute). Triage class ``surrogate-prior-envelope``."""
    report = _compare_records_envelope(
        a, b, classification="surrogate-prior-envelope",
        meta_key="prior_envelope",
        label_a=f"surrogate_prior={_prior_knob(a)}",
        label_b=f"surrogate_prior={_prior_knob(b)}")
    report.meta["prior_envelope"].update(
        {"prior_a": _prior_knob(a), "prior_b": _prior_knob(b)})
    return report


def compare_records(a: RunRecord, b: RunRecord,
                    score_tol: float = 0.0) -> ReplayReport:
    """Direct record-vs-record comparison (no re-execution): the shared
    verifier behind ``replay --against`` and the multichip dryrun's
    pallas-vs-XLA / sharded-vs-serial checks.

    Records captured with different ``--record-topk`` compare on the
    common top-k prefix; a seed-count mismatch compares the common seeds
    and is surfaced in the report meta + triage text (never silently
    called full parity). Records captured at different ``acq_batch``
    widths — or different ``eig_scorer`` rungs — route through the
    label-aligned regret-envelope comparison
    (:func:`compare_records_batchq` / :func:`compare_records_scorer`) —
    the knob-diff path, like dense-vs-sparse, but with budget alignment
    instead of a score tolerance since the two acquisition programs
    genuinely differ."""
    if a.acq_batch != b.acq_batch:
        return compare_records_batchq(a, b)
    if _scorer_knob(a) != _scorer_knob(b):
        return compare_records_scorer(a, b)
    if _oracle_knob(a) != _oracle_knob(b):
        return compare_records_oracle(a, b)
    if _prior_knob(a) != _prior_knob(b):
        return compare_records_prior(a, b)
    if a.rounds != b.rounds:
        raise ValueError(
            f"records disagree on round count ({a.rounds} vs {b.rounds}); "
            "nothing round-aligned to compare")
    report = ReplayReport(mode="records", score_tol=score_tol, meta={
        "a": a.meta.get("run", {}), "b": b.meta.get("run", {}),
        "backend_a": a.meta.get("fingerprint", {}).get("backend"),
        "backend_b": b.meta.get("fingerprint", {}).get("backend"),
    })
    # name the knobs the two sides disagree on (e.g. posterior=dense vs
    # sparse:32) — the reason the auto tolerance dropped to the score
    # contract, surfaced instead of leaving the reader to diff fingerprints
    knobs_a = _record_knobs(a)
    knobs_b = _record_knobs(b)
    diff = {key: [knobs_a.get(key), knobs_b.get(key)]
            for key in sorted(set(knobs_a) | set(knobs_b))
            if knobs_a.get(key) != knobs_b.get(key)}
    if diff:
        report.meta["knob_diff"] = diff
    k = min(int(a.meta.get("trace_k", 8)), int(b.meta.get("trace_k", 8)))
    if a.meta.get("trace_k") != b.meta.get("trace_k"):
        report.meta["trace_k_compared"] = k
    n_seeds = min(a.seeds, b.seeds)
    if a.seeds != b.seeds:
        report.meta["seed_count_mismatch"] = {"a": a.seeds, "b": b.seeds,
                                              "compared": n_seeds}
    def _trim(arr_dict):
        return {key: (v[:, :k] if key in ("topk_idx", "topk_score")
                      else v) for key, v in arr_dict.items()}
    for s in range(n_seeds):
        report.seeds.append(compare_seed(_trim(a.seed_arrays(s)),
                                         _trim(b.seed_arrays(s)),
                                         score_tol=score_tol, seed=s))
    return report


def verify_replay(record: RunRecord, selector_factory, preds, labels,
                  loss: str = "acc", score_tol: float = 0.0, seeds=None,
                  registry=None) -> ReplayReport:
    """Re-execute ``record`` through its own program and triage each seed;
    feeds the ``replay_verified_total`` / ``replay_divergent_total``
    counters."""
    from coda_tpu.telemetry.registry import get_registry

    report = ReplayReport(mode="replay", score_tol=score_tol,
                          meta={"run": record.meta.get("run", {})})
    replayed = replay_record(record, selector_factory, preds, labels,
                             loss=loss)
    for s in (range(record.seeds) if seeds is None else seeds):
        rec = record.seed_arrays(s)
        rep = {k: v[s] for k, v in replayed.items()}
        report.seeds.append(compare_seed(rec, rep, score_tol=score_tol,
                                         seed=s))
    reg = registry if registry is not None else get_registry()
    if report.parity:
        reg.counter("replay_verified_total",
                    "Replay verifications that matched their record").inc()
    else:
        reg.counter("replay_divergent_total",
                    "Replay verifications that diverged from their "
                    "record").inc()
    return report


def record_calibration(record: RunRecord) -> dict:
    """P(best)-vs-realized-best calibration of one ground-truth record.

    A flight record carries both sides of the question the online
    monitor cannot answer in production: per round, ``pbest_max`` is the
    posterior mass the method put on its current argmax model, and
    ``regret`` is that pick's accuracy gap to the true best — the argmax
    WAS (one of) the realized best exactly when the regret is 0. The
    reliability curve over the two (``telemetry/quality.py``'s binning)
    is the suite/bench calibration verdict for the amortized-gate and
    surrogate rungs — an online curve, not just the 2.34e-4 static
    bound. Per-seed curves plus the pooled verdict."""
    from coda_tpu.telemetry.quality import pbest_calibration

    out = {"seeds": [], "pooled": pbest_calibration(
        record.arrays["pbest_max"], record.arrays["regret"])}
    for s in range(record.seeds):
        out["seeds"].append(pbest_calibration(
            record.arrays["pbest_max"][s], record.arrays["regret"][s]))
    return out


def format_triage(report: ReplayReport) -> str:
    """Human-readable verdict block (the CLI's stdout)."""
    lines = []
    tol = ("bitwise" if report.score_tol == 0.0
           else f"|Δscore| ≤ {report.score_tol:g}")
    lines.append(f"replay[{report.mode}] contract: {tol}")
    mism = report.meta.get("seed_count_mismatch")
    if mism:
        lines.append(
            f"  WARNING: seed counts differ (a={mism['a']}, b={mism['b']})"
            f" — only the {mism['compared']} common seed(s) were compared;"
            " this verdict covers nothing beyond them")
    if "trace_k_compared" in report.meta:
        lines.append(f"  note: records carry different top-k widths; "
                     f"compared the common top-"
                     f"{report.meta['trace_k_compared']} prefix")
    if report.meta.get("knob_diff"):
        pairs = ", ".join(f"{k}: {va!r} vs {vb!r}" for k, (va, vb)
                          in report.meta["knob_diff"].items())
        contract = ("the label-aligned regret envelope"
                    if (report.meta.get("batchq_envelope")
                        or report.meta.get("scorer_envelope")
                        or report.meta.get("oracle_envelope")
                        or report.meta.get("prior_envelope"))
                    else ("BITWISE equality (score-tol 0 despite the "
                          "knob diff)" if report.score_tol == 0.0
                          else "the documented score contract"))
        lines.append(f"  knobs differ ({pairs}) — compared under "
                     f"{contract}, not bitwise")
    env = report.meta.get("batchq_envelope")
    if env:
        lines.append(
            f"  acq-batch envelope: q={env['q_a']} vs q={env['q_b']}, "
            f"worst final cum-regret ratio "
            f"{env['max_final_ratio_b_over_a']:.3f}, worst aligned gap "
            f"{env['max_aligned_gap']:.4f}")
    env = report.meta.get("scorer_envelope")
    if env:
        lines.append(
            f"  eig-scorer envelope: {env['scorer_a']} vs "
            f"{env['scorer_b']}, worst final cum-regret ratio "
            f"{env['max_final_ratio_b_over_a']:.3f}, worst aligned gap "
            f"{env['max_aligned_gap']:.4f}")
    env = report.meta.get("oracle_envelope")
    if env:
        lines.append(
            f"  oracle-noise envelope: {env['oracle_a']} vs "
            f"{env['oracle_b']}, worst final cum-regret ratio "
            f"{env['max_final_ratio_b_over_a']:.3f}, worst aligned gap "
            f"{env['max_aligned_gap']:.4f}")
    env = report.meta.get("prior_envelope")
    if env:
        lines.append(
            f"  surrogate-prior envelope: {env['prior_a']} vs "
            f"{env['prior_b']}, worst final cum-regret ratio "
            f"{env['max_final_ratio_b_over_a']:.3f}, worst aligned gap "
            f"{env['max_aligned_gap']:.4f}")
    for s in report.seeds:
        if s.parity:
            lines.append(f"  seed {s.seed}: PARITY "
                         f"({s.quantities.get('rounds_compared', '?')} "
                         "rounds)")
            continue
        lines.append(
            f"  seed {s.seed}: DIVERGED at round {s.first_divergent_round} "
            f"— first diverging quantity: {s.quantity} "
            f"[{s.classification}]")
        if s.note:
            lines.append(f"    {s.note}")
        for q, info in s.quantities.items():
            if "first_divergent_round" not in info:
                continue  # envelope entries carry their own note line
            d = info.get("max_abs_delta")
            lines.append(
                f"    {q}: first at round {info['first_divergent_round']}"
                + (f", max |Δ| = {d:.3e}" if d is not None else ""))
    lines.append("verdict: " + ("PARITY" if report.parity else "DIVERGED"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# record -> runnable experiment reconstruction + the CLI subcommand
# ---------------------------------------------------------------------------

def _args_from_record(record: RunRecord, data_dir: Optional[str] = None,
                      overrides: Optional[dict] = None):
    """Rebuild the argparse namespace a record was captured under: CLI
    defaults, then the fingerprinted knobs, then explicit overrides."""
    from coda_tpu.cli import parse_args

    args = parse_args([])
    run = record.meta.get("run", {})
    knobs = dict(record.meta.get("fingerprint", {}).get("knobs", {}))
    knobs.update(overrides or {})
    for k, v in knobs.items():
        setattr(args, k, v)
    args.task = run.get("task")
    args.synthetic = run.get("synthetic")
    if data_dir:
        args.data_dir = data_dir
    elif run.get("data_dir"):
        args.data_dir = run["data_dir"]
    # note: the knobs loop above also restores n_parallel — the recorded
    # replica-width hint that steers the auto eig_mode budget, so replay
    # rebuilds the selector on the recorded kernel tier
    return args


def load_record_environment(record: RunRecord,
                            data_dir: Optional[str] = None,
                            overrides: Optional[dict] = None,
                            check_digest: bool = True):
    """``(dataset, selector_factory, args)`` for a record — everything
    :func:`verify_replay` needs to re-execute the recorded program."""
    from coda_tpu.cli import build_selector_factory, load_dataset

    args = _args_from_record(record, data_dir, overrides)
    dataset = load_dataset(args)
    want = record.meta.get("fingerprint", {}).get("dataset", {}).get(
        "digest")
    if check_digest and want:
        got = dataset_digest(dataset.preds, dataset.labels)
        if got != want:
            raise ValueError(
                f"dataset digest mismatch: record was captured on "
                f"{want}, loaded data hashes to {got} — replaying against "
                "different data answers a different question "
                "(pass --allow-digest-mismatch to proceed anyway)")
    factory = build_selector_factory(args, dataset.name)
    return dataset, factory, args


def _auto_tol(record: RunRecord, overrides: dict,
              against: Optional[RunRecord] = None) -> float:
    """Bitwise when the two sides share a backend with unchanged knobs;
    the documented cross-backend score contract otherwise.

    In replay mode the "other side" is the current process; in --against
    mode it is the second RECORD — the current host's backend is
    irrelevant to a record-vs-record diff."""
    fp = record.meta.get("fingerprint", {})
    if against is not None:
        fp_b = against.meta.get("fingerprint", {})
        # knob dicts compare NORMALIZED (_record_knobs): a knob one
        # record predates is its replay default, not a difference
        same = (fp.get("backend") == fp_b.get("backend")
                and _record_knobs(record) == _record_knobs(against))
        return 0.0 if same else CROSS_BACKEND_SCORE_TOL
    import jax

    same_backend = fp.get("backend") == jax.default_backend()
    return 0.0 if (same_backend and not overrides) \
        else CROSS_BACKEND_SCORE_TOL


def _parse_overrides(pairs) -> dict:
    out = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--set expects KEY=VALUE, got {p!r}")
        k, v = p.split("=", 1)
        for cast in (int, float):
            try:
                v = cast(v)
                break
            except ValueError:
                continue
        if v in ("true", "True"):
            v = True
        elif v in ("false", "False"):
            v = False
        out[k] = v
    return out


def replay_main(argv=None) -> int:
    """``python -m coda_tpu.cli replay <record-dir> [...]``."""
    p = argparse.ArgumentParser(
        prog="coda_tpu.cli replay",
        description="re-execute a flight-recorder record and triage any "
                    "divergence (or diff two records with --against)")
    p.add_argument("record_dir", help="directory with record.json + "
                                      "rounds.npz (a --record-dir output)")
    p.add_argument("--against", default=None, metavar="DIR",
                   help="compare against this second record instead of "
                        "re-executing (e.g. a pallas capture vs an XLA "
                        "capture)")
    p.add_argument("--data-dir", default=None,
                   help="override the recorded data directory")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (cpu/tpu)")
    p.add_argument("--score-tol", default="auto",
                   help="float tolerance on score/posterior quantities; "
                        "'auto' = bitwise (0.0) on the recorded backend "
                        "with unchanged knobs, else the documented "
                        f"{CROSS_BACKEND_SCORE_TOL} cross-backend contract")
    p.add_argument("--seed", type=int, default=None,
                   help="replay only this recorded seed (default: all)")
    p.add_argument("--set", action="append", metavar="KEY=VALUE",
                   dest="overrides",
                   help="override a recorded knob for the replay (e.g. "
                        "eig_entropy=approx) — divergence triage then "
                        "isolates that knob's decision-trace impact")
    p.add_argument("--allow-digest-mismatch", action="store_true")
    p.add_argument("--out", default=None, metavar="REPORT.json",
                   help="write the triage report there as JSON")
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    record = RunRecord.load(args.record_dir)
    overrides = _parse_overrides(args.overrides)
    other = RunRecord.load(args.against) if args.against else None
    tol = (_auto_tol(record, overrides, against=other)
           if args.score_tol == "auto" else float(args.score_tol))

    if other is not None:
        report = compare_records(record, other, score_tol=tol)
    else:
        dataset, factory, rec_args = load_record_environment(
            record, data_dir=args.data_dir, overrides=overrides,
            check_digest=not args.allow_digest_mismatch)
        seeds = None if args.seed is None else [args.seed]
        report = verify_replay(record, factory, dataset.preds,
                               dataset.labels,
                               loss=getattr(rec_args, "loss", "acc"),
                               score_tol=tol, seeds=seeds)
    print(format_triage(report))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report.to_dict(), f, indent=2)
        print(f"triage report written to {args.out}")
    return 0 if report.parity else 2
