from coda_tpu.engine.loop import ExperimentResult, run_experiment, run_seeds

__all__ = ["ExperimentResult", "run_experiment", "run_seeds"]
