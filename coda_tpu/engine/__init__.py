from coda_tpu.engine.loop import (
    ExperimentResult,
    RoundTrace,
    RunTraceAux,
    make_step_fn,
    run_experiment,
    run_seeds,
    run_seeds_compiled,
    run_seeds_recorded,
)

_CHECKPOINT_EXPORTS = (
    "ExperimentCheckpointer",
    "latest_step",
    "make_resumable_runner",
    "run_experiment_resumable",
)

__all__ = [
    "ExperimentResult",
    "RoundTrace",
    "RunTraceAux",
    "make_step_fn",
    "run_experiment",
    "run_seeds",
    "run_seeds_compiled",
    "run_seeds_recorded",
    *_CHECKPOINT_EXPORTS,
]


def __getattr__(name):
    # checkpoint.py pulls in orbax; keep it lazy so the core experiment path
    # works on installs without orbax-checkpoint
    if name in _CHECKPOINT_EXPORTS:
        from coda_tpu.engine import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(name)
