"""Intra-run checkpoint / resume for labeling experiments (orbax-backed).

The reference has **no** intra-run checkpointing: selector state (Dirichlet
posteriors, labeled set) lives only in process memory, and resume granularity
is the whole seed-run via MLflow run status (reference ``main.py:155-157``;
see SURVEY.md §5 "Checkpoint / resume"). Here the selector state is already a
fixed-shape pytree, so a checkpoint is just that pytree plus the position in
the per-round RNG key table and the partial metric traces — tiny next to the
``(H, N, C)`` prediction tensor, which is *not* checkpointed (it is
deterministic input data, reloaded from the dataset file).

Execution model: the ``iters``-round experiment runs as a sequence of jitted
``lax.scan`` chunks of ``every`` rounds. After each chunk the carry (state,
cumulative regret) and the filled trace prefix are saved under
``<dir>/step_<r>``. On restart, the newest usable checkpoint is restored and
the scan continues from round ``r`` — replaying nothing. Selection traces
(indices, best-model) are identical to an uninterrupted run because the
per-round keys come from the same ``jax.random.split`` table (prefix-stable,
so a resume with a *smaller* ``iters`` restores an earlier checkpoint and is
still exact); float metrics agree to ~1 ulp — the chunked program and a
single monolithic scan are separately compiled, and XLA may schedule
reductions differently per scan length. A fingerprint of the selector
configuration is saved alongside and validated on resume, so checkpoints
from a different method/hyperparams/dataset shape fail loudly instead of
blending two configs into one trace.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import zlib
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import orbax.checkpoint as ocp

from coda_tpu.engine.loop import ExperimentResult, make_step_fn
from coda_tpu.selectors.protocol import Selector

_STEP_RE = re.compile(r"^step_(\d+)$")
_FINGERPRINT = "fingerprint.json"


def _saved_steps(ckpt_dir: str) -> list[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(
        int(m.group(1))
        for m in map(_STEP_RE.match, os.listdir(ckpt_dir))
        if m
    )


def latest_step(ckpt_dir: str, at_most: Optional[int] = None) -> Optional[int]:
    """The largest checkpointed round (optionally ≤ ``at_most``), or None."""
    steps = _saved_steps(ckpt_dir)
    if at_most is not None:
        steps = [s for s in steps if s <= at_most]
    return max(steps) if steps else None


class ExperimentCheckpointer:
    """Saves/restores the experiment pytree at round boundaries.

    Crash-safety comes from orbax's atomic tmp-dir-then-rename save; a
    partial save never appears under the final ``step_<r>`` name.
    """

    def __init__(self, ckpt_dir: str, keep: int = 2):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        self.keep = keep
        self._ckptr = ocp.PyTreeCheckpointer()

    def save(self, round_: int, tree) -> None:
        path = os.path.join(self.ckpt_dir, f"step_{round_}")
        if os.path.exists(path):  # stale complete save from an older run
            shutil.rmtree(path)
        self._ckptr.save(path, tree)
        self._gc()

    def restore(self, round_: int):
        return self._ckptr.restore(
            os.path.join(self.ckpt_dir, f"step_{round_}")
        )

    def _gc(self) -> None:
        steps = _saved_steps(self.ckpt_dir)
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(os.path.join(self.ckpt_dir, f"step_{s}"),
                          ignore_errors=True)


def _fingerprint(selector: Selector, labels, seed: int,
                 dataset_id: Optional[str] = None) -> dict:
    # labels CRC distinguishes same-shape tasks (e.g. the two cifar10_* tasks
    # have identical (H, N, C)); dataset_id catches renamed runs of the same
    # labels with different prediction tensors
    return {
        "selector": selector.name,
        "hyperparams": {k: repr(v)
                        for k, v in sorted(selector.hyperparams.items())},
        "_hyperparam_defaults": {
            k: repr(v) for k, v in sorted(selector.hyperparam_defaults.items())
        },
        "n_points": int(labels.shape[0]),
        "labels_crc32": int(zlib.crc32(
            np.ascontiguousarray(np.asarray(labels)).tobytes())),
        "dataset": dataset_id,
        "seed": int(seed),
    }


def _check_fingerprint(ckpt_dir: str, fp: dict) -> None:
    path = os.path.join(ckpt_dir, _FINGERPRINT)
    if os.path.exists(path):
        with open(path) as f:
            saved = json.load(f)
        # Hyperparams added after a checkpoint was written (new fields with
        # defaults, e.g. eig_mode) must not invalidate it — but ONLY while
        # the new field sits at its construction default; an explicit
        # override of a field the checkpoint predates is a real mismatch.
        saved_hp = saved.get("hyperparams", {})
        defaults = fp.get("_hyperparam_defaults", {})
        cur_hp = {
            k: v for k, v in fp["hyperparams"].items()
            if k in saved_hp or v != defaults.get(k, object())
        }
        cur = dict(fp, hyperparams=cur_hp)
        saved_cmp = {k: v for k, v in saved.items()
                     if k != "_hyperparam_defaults"}
        cur_cmp = {k: v for k, v in cur.items()
                   if k != "_hyperparam_defaults"}
        if saved_cmp != cur_cmp:
            raise ValueError(
                f"checkpoint dir {ckpt_dir!r} was written by a different "
                f"configuration:\n  saved:   {saved}\n  current: {fp}\n"
                "Use a fresh --checkpoint-dir (or delete this one)."
            )
    else:
        os.makedirs(ckpt_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(fp, f, indent=2)


_TRACE_NAMES = ("chosen_idx", "true_class", "best_model", "regret",
                "cumulative_regret", "select_prob")
_TRACE_DTYPES = (np.int32, np.int32, np.int32, np.float32, np.float32,
                 np.float32)


def make_resumable_runner(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    iters: int,
    every: int = 25,
    dataset_id: Optional[str] = None,
) -> Callable[[int, str], ExperimentResult]:
    """Build ``run(seed, ckpt_dir) -> ExperimentResult`` with shared jits.

    The chunk scan and init are compiled once and reused across all seeds
    (keys are jit *arguments*, not closure constants); only the ragged final
    chunk adds a second chunk compilation.
    """
    N = labels.shape[0]
    if iters > N:
        raise ValueError(
            f"iters={iters} exceeds the {N} labelable points; the unlabeled "
            "set would be exhausted mid-run"
        )
    budget = selector.hyperparams.get("budget")
    if budget is not None and iters > budget:
        raise ValueError(
            f"selector '{selector.name}' has a fixed label buffer of "
            f"{budget} but iters={iters}; rebuild it with budget >= iters"
        )
    best_loss = model_losses.min()
    step = make_step_fn(selector, labels, model_losses)

    @jax.jit
    def init_fn(k_init, k_prior):
        state0 = selector.init(k_init)
        best0, stoch0 = selector.best(state0, k_prior)
        return state0, model_losses[best0] - best_loss, stoch0

    @jax.jit
    def chunk_fn(state, cum, keys):
        (state, cum), outs = lax.scan(step, (state, cum), keys)
        return state, cum, outs

    # orbax restores pytrees as plain dicts; flatten the selector state for
    # saving and unflatten against the init treedef on restore so custom
    # containers (NamedTuples, dataclasses) survive the round-trip
    state_treedef = jax.tree.structure(
        jax.eval_shape(selector.init, jax.random.PRNGKey(0))
    )

    def run(seed: int, ckpt_dir: str) -> ExperimentResult:
        key = jax.random.PRNGKey(seed)
        k_init, k_prior, k_scan = jax.random.split(key, 3)
        round_keys = jax.random.split(k_scan, iters)

        _check_fingerprint(
            ckpt_dir, _fingerprint(selector, labels, seed, dataset_id))
        ckptr = ExperimentCheckpointer(ckpt_dir)
        traces = {n: np.zeros(iters, d)
                  for n, d in zip(_TRACE_NAMES, _TRACE_DTYPES)}

        start = latest_step(ckpt_dir, at_most=iters)
        if start is not None and start > 0:
            restored = ckptr.restore(start)
            if len(restored["state"]) != state_treedef.num_leaves:
                # a fingerprint from before a state field existed can match
                # while the pytree structure does not (e.g. the incremental
                # cache gained a leaf) — fail with an actionable message, not
                # a raw unflatten error
                raise ValueError(
                    f"checkpoint at {ckpt_dir!r} step {start} has "
                    f"{len(restored['state'])} state leaves but this "
                    f"selector build expects {state_treedef.num_leaves} — "
                    "it predates a selector-state layout change. Use a "
                    "fresh --checkpoint-dir (or delete this one)."
                )
            leaves = [jnp.asarray(restored["state"][f"{i:04d}"])
                      for i in range(len(restored["state"]))]
            state = jax.tree.unflatten(state_treedef, leaves)
            cum = jnp.asarray(restored["cum"])
            regret0 = np.float32(restored["regret0"])
            stoch = bool(restored["stochastic"])
            for n in _TRACE_NAMES:
                traces[n][:start] = restored["traces"][n][:start]
        else:
            start = 0
            state, regret0, stoch0 = init_fn(k_init, k_prior)
            cum = jnp.asarray(0.0, jnp.float32)
            regret0 = np.float32(regret0)
            stoch = bool(stoch0)

        for lo in range(start, iters, every):
            hi = min(lo + every, iters)
            state, cum, outs = chunk_fn(state, cum, round_keys[lo:hi])
            idxs, tcs, bests, regrets, cums, probs, stoch_c = outs
            for n, arr in zip(_TRACE_NAMES,
                              (idxs, tcs, bests, regrets, cums, probs)):
                traces[n][lo:hi] = np.asarray(arr)
            stoch = stoch or bool(np.asarray(stoch_c).any())
            if hi < iters:  # final result needs no checkpoint
                ckptr.save(hi, {
                    "state": {f"{i:04d}": leaf for i, leaf
                              in enumerate(jax.tree.leaves(state))},
                    "cum": cum,
                    "regret0": np.asarray(regret0, np.float32),
                    "stochastic": np.asarray(stoch),
                    "traces": traces,
                })

        return ExperimentResult(
            chosen_idx=jnp.asarray(traces["chosen_idx"]),
            true_class=jnp.asarray(traces["true_class"]),
            best_model=jnp.asarray(traces["best_model"]),
            regret=jnp.asarray(traces["regret"]),
            cumulative_regret=jnp.asarray(traces["cumulative_regret"]),
            select_prob=jnp.asarray(traces["select_prob"]),
            regret_at_0=jnp.asarray(regret0),
            stochastic=jnp.asarray(stoch or selector.always_stochastic),
        )

    return run


def run_experiment_resumable(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    iters: int,
    seed: int,
    ckpt_dir: str,
    every: int = 25,
    dataset_id: Optional[str] = None,
) -> ExperimentResult:
    """One-shot convenience wrapper around :func:`make_resumable_runner`."""
    return make_resumable_runner(selector, labels, model_losses, iters,
                                 every, dataset_id)(seed, ckpt_dir)
