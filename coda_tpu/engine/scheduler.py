"""Multi-device task-parallel scheduler for the in-process suite.

``SuiteRunner.run_batched`` dispatches every (family-chunk, method) pair
serially and blocks on the host copy before the next dispatch even starts —
on a v5e-8 that leaves 7 chips idle for the whole sweep. The 156 task-method
pairs are embarrassingly parallel across devices (no pair reads another's
results), so this module places independent dispatches on distinct local
devices and lets jax's async dispatch run them concurrently:

  * **Placement**: each chunk's stacked operands are committed to a target
    device with ``jax.device_put``; jit then executes the per-device
    executable there. Placement is a pure copy — scheduled results are
    bitwise identical to the serial path (same programs, same seed keys).
  * **LPT ordering**: chunks are dispatched longest-processing-time-first
    onto the least-loaded device (the classic greedy makespan heuristic),
    with per-chunk costs estimated from the ``per_family_warm_s`` /
    ``per_method_warm_s`` profiles the runner emits (persisted from prior
    runs or a committed bench artifact) and a uniform fallback for unseen
    families.
  * **Deferred harvesting**: results go into a pending-futures queue and
    are copied device-to-host asynchronously (``copy_to_host_async``), so
    the host-side ``np.stack`` of the next chunk's operands and the store
    logging of finished chunks overlap device compute instead of
    serializing with it.
  * **Memory budget**: ``max_inflight`` bounds queued chunks per device,
    and any method with a ``batch_caps`` entry is treated as memory-heavy
    (the caps exist precisely because those methods' per-replica state
    rivals the prediction tensor) — two heavy chunks are never co-resident
    on one device.

The sweep's semantics are unchanged: same chunking, same resume-skip
checks, same result unpacking — ``tests/test_scheduler.py`` pins bitwise
parity against the serial path on the 8-virtual-device CPU mesh. The
decision flight recorder rides the shared ``_launch_batch`` /
``_harvest_batch`` pair, so a ``SuiteRunner(record_dir=...)`` emits the
same per-(family, method) record streams under scheduled placement as
under serial dispatch — the probe's trace arrays join the deferred
``copy_to_host_async`` harvest, adding no extra syncs to the placement
loop (``tests/test_recorder.py`` pins stream coverage and bitwise result
parity for both paths).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from coda_tpu.engine.suite import _warm_profile, family_of


def resolve_devices(spec, jax=None) -> list:
    """Local jax devices for a ``devices=`` spec.

    ``'auto'`` -> all local devices; an int (or int-like string) -> the
    first N local devices; a sequence of device ids or Device objects ->
    exactly those. Raises on counts the host can't satisfy, so a
    mis-sized ``--suite-devices`` fails loudly instead of silently
    under-parallelizing.
    """
    if jax is None:
        import jax
    local = list(jax.local_devices())
    if spec is None or spec == "auto":
        return local
    if isinstance(spec, str):
        spec = int(spec)  # ValueError on junk is the right error
    if isinstance(spec, int):
        if not 1 <= spec <= len(local):
            raise ValueError(
                f"devices={spec} but this process has {len(local)} local "
                f"devices")
        return local[:spec]
    out = []
    by_id = {d.id: d for d in local}
    for d in spec:
        if isinstance(d, int):
            if d not in by_id:
                raise ValueError(f"no local device with id {d}")
            out.append(by_id[d])
        else:
            out.append(d)
    if not out:
        raise ValueError("empty device list")
    return out


def estimate_cost(family: str, method: str, n_tasks: int,
                  cost_profile: Optional[dict],
                  family_task_counts: Optional[dict] = None) -> float:
    """Relative LPT weight of one chunk (``n_tasks`` tasks of one family
    under one method).

    ``cost_profile`` is either a runner ``last_stats``-shaped dict with
    ``per_family_warm_s`` / ``per_method_warm_s`` keys, or a flat
    ``{family: seconds}`` mapping. A family's profiled seconds are a SUM
    over its tasks, so they are normalized by this run's task count for
    that family (``family_task_counts``) to get a per-task rate; method
    weights are normalized to mean 1 so they only redistribute, never
    rescale. Unseen families/methods fall back to the mean known rate
    (uniform when nothing is known) — LPT only needs relative order, so
    absolute scale is irrelevant.
    """
    prof = cost_profile or {}
    fam_p = prof.get("per_family_warm_s", prof)
    meth_p = prof.get("per_method_warm_s", {})
    fam_p = {k: float(v) for k, v in fam_p.items()
             if isinstance(v, (int, float))}
    rates = {}
    for fam, total in fam_p.items():
        cnt = (family_task_counts or {}).get(fam, 0)
        if cnt > 0:
            rates[fam] = total / cnt
    fallback = (sum(rates.values()) / len(rates)) if rates else 1.0
    rate = rates.get(family, fallback)
    w_m = 1.0
    if meth_p:
        vals = [float(v) for v in meth_p.values()]
        mean = sum(vals) / len(vals)
        if mean > 0 and method in meth_p:
            w_m = float(meth_p[method]) / mean
    return max(rate * w_m * n_tasks, 1e-9)


def plan_fleet_schedule(costs: Sequence[float],
                        host_weights: Sequence[float],
                        schedule: str = "lpt"):
    """Host-level half of two-level fleet placement: assign chunks to
    HOSTS by weighted least-normalized-load greedy.

    ``host_weights`` is each host's relative capacity — its local device
    count for a homogeneous fleet, or a measured throughput ratio for a
    mixed one (a v5e-8 host takes ~8x the work of a 1-chip host). LPT
    order + argmin of ``load[h] / weight[h]`` generalizes
    :func:`plan_schedule`'s makespan heuristic to unequal hosts; with
    all weights 1 it reduces to it exactly. Returns
    ``(order, host_assignment, loads)`` with loads UN-normalized (the
    estimated work per host a cross-host dispatcher ships).
    """
    if schedule not in ("lpt", "fifo"):
        raise ValueError(f"unknown schedule {schedule!r}; use 'lpt'|'fifo'")
    weights = [float(w) for w in host_weights]
    if not weights or any(w <= 0 for w in weights):
        raise ValueError(f"host weights must be positive, got {weights}")
    idx = list(range(len(costs)))
    if schedule == "lpt":
        idx.sort(key=lambda i: (-costs[i], i))
    loads = [0.0] * len(weights)
    assignment = [0] * len(costs)
    for i in idx:
        h = min(range(len(weights)),
                key=lambda j: (loads[j] / weights[j], j))
        assignment[i] = h
        loads[h] += costs[i]
    return idx, assignment, loads


def partition_hosts(n_devices: int, hosts) -> list[list[int]]:
    """Device-index groups for a ``hosts`` spec: an int splits the local
    devices into that many near-equal contiguous groups (the in-process
    stand-in for N fleet hosts — the virtual-device tests and the
    container demo); a sequence of sequences names explicit per-host
    device index sets (the multi-process fleet shape, where each entry
    is one host's local devices)."""
    if isinstance(hosts, int):
        if not 1 <= hosts <= n_devices:
            raise ValueError(f"hosts={hosts} but only {n_devices} devices")
        base, rem = divmod(n_devices, hosts)
        groups, i = [], 0
        for h in range(hosts):
            n = base + (1 if h < rem else 0)
            groups.append(list(range(i, i + n)))
            i += n
        return groups
    groups = [list(g) for g in hosts]
    flat = [d for g in groups for d in g]
    if not groups or any(not g for g in groups):
        raise ValueError("every host needs at least one device")
    if len(set(flat)) != len(flat) or any(
            not 0 <= d < n_devices for d in flat):
        raise ValueError(f"host device groups {groups} must be disjoint "
                         f"indices into the {n_devices} local devices")
    if len(flat) != n_devices:
        # the flattened plan indexes loads/est_device_load by absolute
        # device position — a non-covering spec would crash mid-run;
        # shrink `devices=` instead to use fewer
        raise ValueError(f"host device groups {groups} must cover all "
                         f"{n_devices} devices exactly")
    return groups


def plan_two_level(costs: Sequence[float], host_groups: Sequence[Sequence],
                   schedule: str = "lpt"):
    """Fleet placement composed down to flat device assignment: chunks go
    to hosts by :func:`plan_fleet_schedule` (weight = device count), then
    within each host to its devices by :func:`plan_schedule`. Returns the
    same ``(order, assignment, loads)`` shape as :func:`plan_schedule`
    over the GLOBAL device list, so the executing loop is placement-
    policy agnostic."""
    weights = [len(g) for g in host_groups]
    order, h_assign, _ = plan_fleet_schedule(costs, weights, schedule)
    n_dev = sum(weights)
    assignment = [0] * len(costs)
    loads = [0.0] * n_dev
    for hi, group in enumerate(host_groups):
        mine = [i for i in order if h_assign[i] == hi]
        if not mine:
            continue
        _, sub_assign, _ = plan_schedule([costs[i] for i in mine],
                                         len(group), schedule)
        for j, i in enumerate(mine):
            d = group[sub_assign[j]]
            assignment[i] = d
            loads[d] += costs[i]
    return order, assignment, loads


def plan_schedule(costs: Sequence[float], n_devices: int,
                  schedule: str = "lpt"):
    """Dispatch order + device assignment for chunk ``costs``.

    ``'lpt'`` sorts chunks by descending cost (ties keep input order) and
    greedily assigns each to the currently least-loaded device — the
    longest-processing-time-first makespan heuristic (≤ 4/3·OPT).
    ``'fifo'`` keeps the input order with the same least-loaded placement.
    Returns ``(order, assignment, loads)``: the dispatch order as indices
    into ``costs``, the device index per chunk (input order), and the
    estimated per-device load.
    """
    if schedule not in ("lpt", "fifo"):
        raise ValueError(f"unknown schedule {schedule!r}; use 'lpt'|'fifo'")
    idx = list(range(len(costs)))
    if schedule == "lpt":
        idx.sort(key=lambda i: (-costs[i], i))
    loads = [0.0] * n_devices
    assignment = [0] * len(costs)
    for i in idx:
        d = min(range(n_devices), key=lambda j: (loads[j], j))
        assignment[i] = d
        loads[d] += costs[i]
    return idx, assignment, loads


@dataclass
class _Chunk:
    """One schedulable dispatch: a todo-subset of one group, one method."""

    group: int
    todo: list
    method: str
    names: list        # the full group's names (todo indexes into it)
    shape: tuple
    family: str
    heavy: bool
    cost: float = 0.0


@dataclass
class _HostTask:
    """Host-side staging of one loaded task for the scheduler.

    The plan phase holds EVERY group at once (global LPT needs the full
    work list), so tensors must not sit in device memory meanwhile —
    loaders materialize onto the default device, and the full reference
    suite would blow one chip's HBM before the first dispatch. Copying to
    numpy here frees the loader's device buffers immediately; device
    memory then only ever holds in-flight chunks, and ``_launch_batch``'s
    per-chunk ``np.asarray`` becomes a no-op instead of a repeated
    device-to-host copy per (method, chunk)."""

    name: str
    preds: np.ndarray
    labels: np.ndarray

    @property
    def shape(self):
        return self.preds.shape


def _all_ready(pend, jax) -> bool:
    return all(leaf.is_ready()
               for leaf in jax.tree_util.tree_leaves((pend.r0, pend.rest)))


def run_scheduled(runner, groups, methods, *, store=None, force_rerun=False,
                  method_args=None, batch_caps=None, progress=print,
                  devices="auto", schedule="lpt", cost_profile=None,
                  max_inflight=2, hosts=None) -> dict:
    """``SuiteRunner.run_batched`` with task-parallel device placement.

    Same contract as the serial path (chunking, resume, result layout,
    bitwise-identical numbers); see the module docstring for what runs
    concurrently. Groups are fully loaded before the compute phase so the
    whole work list can be LPT-ordered globally — host memory briefly
    holds every group (device memory still only holds in-flight chunks);
    callers for whom that is too much should fall back to the serial
    path's one-group-at-a-time streaming.

    ``hosts`` opts into two-level FLEET placement: chunks are first
    assigned to hosts by weighted LPT (:func:`plan_fleet_schedule`,
    weight = the host's device count), then within each host to its
    devices. An int partitions the local devices into that many host
    groups (the in-process stand-in — on a multi-process fleet each
    process's local devices are one group, and the host-level plan is
    what a cross-host dispatcher ships to each serve replica's suite
    endpoint); a sequence of device-index sequences names the groups
    explicitly. Placement stays a pure copy either way — results remain
    bitwise identical to the serial path.
    """
    jax = runner._jax
    devs = resolve_devices(devices, jax)
    max_inflight = max(1, int(max_inflight))
    tele = getattr(runner, "telemetry", None)
    results: dict = {}
    pairs: list = []
    t_suite0 = time.perf_counter()
    t_load = 0.0

    # ---- plan phase: load groups, enumerate chunks (chunking identical
    # to the serial path so executables and T-keys match bitwise)
    group_data: list = []
    chunks: list = []
    fam_counts: dict = {}
    for gi, group in enumerate(groups):
        t0 = time.perf_counter()
        datasets = [d() if callable(d) else d for d in group]
        names, planned = runner._plan_group(
            datasets, methods, store, force_rerun, batch_caps, progress)
        # stage on host, dropping the loader's device-resident tensors
        datasets = [_HostTask(name=d.name, preds=np.asarray(d.preds),
                              labels=np.asarray(d.labels))
                    for d in datasets]
        t1 = time.perf_counter()
        t_load += t1 - t0
        if tele is not None:  # host lane: loads overlap device lanes' spans
            tele.spans.record(f"load/group{gi}", lane="host:suite",
                              t_start=t0, t_end=t1,
                              attrs={"tasks": [d.name for d in datasets]})
        group_data.append(datasets)
        for n in names:
            fam = family_of(n)
            fam_counts[fam] = fam_counts.get(fam, 0) + 1
        for method, todo in planned:
            chunks.append(_Chunk(
                group=gi, todo=list(todo), method=method, names=names,
                shape=tuple(datasets[0].shape),
                family=family_of(names[todo[0]]),
                heavy=method in (batch_caps or {})))
    for ch in chunks:
        ch.cost = estimate_cost(ch.family, ch.method, len(ch.todo),
                                cost_profile, fam_counts)
    host_groups = None
    if hosts is not None:
        host_groups = partition_hosts(len(devs), hosts)
        order, assignment, est_loads = plan_two_level(
            [c.cost for c in chunks], host_groups, schedule)
    else:
        order, assignment, est_loads = plan_schedule(
            [c.cost for c in chunks], len(devs), schedule)

    # ---- compute phase: throttled async dispatch + deferred harvest
    pending: dict = {i: [] for i in range(len(devs))}
    harvested: list = []
    timeline: dict = {d.id: [] for d in devs}
    remaining = [sum(1 for c in chunks if c.group == gi)
                 for gi in range(len(group_data))]
    for gi, n in enumerate(remaining):
        if n == 0:   # fully-finished group (resume): nothing will free it
            group_data[gi] = None
    t_compute0 = None

    def _harvest(di: int, pend) -> None:
        runner._harvest_batch(pend, store, pairs, results, progress)
        harvested.append(pend)
        timeline[devs[di].id].append({
            "method": pend.method, "tasks": list(pend.names),
            "start": round(pend.t_start - t_compute0, 4),
            "end": round(pend.t_end - t_compute0, 4),
            "est_cost": round(pend.cost, 4), "cold": pend.cold,
        })

    for ci in order:
        ch = chunks[ci]
        di = assignment[ci]
        q = pending[di]
        # throttle before staging the next chunk's HBM: at most
        # max_inflight chunks queued per device, and never two
        # memory-heavy chunks co-resident on one device
        while len(q) >= max_inflight or (
                ch.heavy and any(p.heavy for p in q)):
            _harvest(di, q.pop(0))
        # opportunistic drain: anything already finished anywhere frees
        # its device buffers and does its store logging now, overlapping
        # the dispatches below
        for dj, qj in pending.items():
            while qj and _all_ready(qj[0], jax):
                _harvest(dj, qj.pop(0))
        if t_compute0 is None:
            t_compute0 = time.perf_counter()
        pend = runner._launch_batch(
            ch.todo, ch.names, group_data[ch.group], ch.method,
            method_args, ch.shape, runner._seen_shapes,
            device=devs[di], cost=ch.cost)
        pend.heavy = ch.heavy
        q.append(pend)
        remaining[ch.group] -= 1
        if remaining[ch.group] == 0:
            group_data[ch.group] = None  # free the group's tensors
    # final drain, oldest dispatch first (approximates completion order)
    tail = sorted(((di, p) for di, q in pending.items() for p in q),
                  key=lambda t: t[1].t_start)
    for di, p in tail:
        _harvest(di, p)

    t_end = time.perf_counter()
    compute_wall = (t_end - t_compute0) if t_compute0 is not None else 0.0
    compute_device_s = sum(p.t_end - p.t_start for p in harvested)
    occupancy = {}
    for d in devs:
        busy, last = 0.0, None
        for rec in sorted(timeline[d.id], key=lambda r: r["start"]):
            s, e = rec["start"], rec["end"]
            if last is None or s > last:
                busy += e - s
                last = e
            elif e > last:   # overlapping in-flight intervals: count once
                busy += e - last
                last = e
        occupancy[d.id] = round(busy / compute_wall, 4) if compute_wall \
            else 0.0

    total = t_end - t_suite0
    warm_m, warm_f = _warm_profile(pairs)
    runner.last_stats = {
        "total_s": total, "load_s": t_load,
        "compute_s": compute_wall,
        "compute_device_s": compute_device_s,
        "pairs": pairs,
        "per_method_warm_s": warm_m, "per_family_warm_s": warm_f,
        "n_devices": len(devs), "schedule": schedule,
        "device_timeline": timeline, "occupancy": occupancy,
        "est_device_load": {devs[i].id: round(est_loads[i], 4)
                            for i in range(len(devs))},
    }
    if host_groups is not None:
        runner.last_stats["hosts"] = [
            [devs[d].id for d in g] for g in host_groups]
        runner.last_stats["host_load"] = [
            round(sum(est_loads[d] for d in g), 4) for g in host_groups]
    progress(f"suite[scheduled x{len(devs)}]: {len(results)} task-method "
             f"pairs in {total:.2f}s (compute wall {compute_wall:.2f}s, "
             f"device-seconds {compute_device_s:.2f}s, data load "
             f"{t_load:.2f}s, occupancy "
             f"{ {k: v for k, v in sorted(occupancy.items())} })")
    return results
