"""Experiment driver: the full labeling loop as one compiled ``lax.scan``.

The reference drives each round from Python — select, query oracle, update,
re-estimate best, log (reference ``main.py:55-105``) — paying a host↔device
round-trip per step. Here the oracle's labels are known up-front (they are
loaded with the dataset, ``coda/oracle.py:6-7``), so the entire experiment is
a pure function

    (preds, labels, hyperparams, seed) -> regret trace

compiled once: ``lax.scan`` over labeling rounds, ``vmap`` over seeds. On a
sharded mesh the same program runs SPMD with XLA inserting the collectives.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.losses import accuracy_loss
from coda_tpu.oracle import true_losses as compute_true_losses
from coda_tpu.selectors.protocol import Selector


class ExperimentResult(NamedTuple):
    """Per-round traces (leading axis = labeling round, length ``iters``)."""

    chosen_idx: jnp.ndarray    # (T,) int32 — which point was labeled
    true_class: jnp.ndarray    # (T,) int32 — its oracle label
    best_model: jnp.ndarray    # (T,) int32 — current best-model guess
    regret: jnp.ndarray        # (T,) float32
    cumulative_regret: jnp.ndarray  # (T,) float32
    select_prob: jnp.ndarray   # (T,) float32 — selection probability / q-value
    regret_at_0: jnp.ndarray   # scalar — prior regret before any labels
    stochastic: jnp.ndarray    # scalar bool — did RNG affect the run?


class RoundTrace(NamedTuple):
    """Flight-recorder provenance of one labeling round (leading axis = round
    under scan). Device-side: emitted as extra ``lax.scan`` outputs and
    harvested ONCE per run — O(rounds·k) host traffic, no per-round sync.
    See ``coda_tpu/telemetry/recorder.py`` for the on-disk schema and
    ``coda_tpu/engine/replay.py`` for the parity/triage consumer."""

    round_key: jnp.ndarray     # (T, 2) uint32 — the round's PRNG key counter
    topk_idx: jnp.ndarray      # (T, k) int32 — top-k candidate indices
    topk_score: jnp.ndarray    # (T, k) float32 — their acquisition scores
    chosen_score: jnp.ndarray  # (T,) float32 — score of the picked point
    runner_up_gap: jnp.ndarray  # (T,) float32 — top1 - top2 score margin
    pbest_max: jnp.ndarray     # (T,) float32 — max of posterior P(best); NaN
    #                             when the method exposes no posterior
    pbest_entropy: jnp.ndarray  # (T,) float32 — entropy (bits) of P(best)
    # did this round's scorer fall back to the full exact pass on a
    # violated surrogate contract (--eig-scorer surrogate:k)? Always
    # False for exact scorers and methods without the extras hook —
    # recorded per round so a committed surrogate capture carries its
    # fallback-rate evidence in the stream itself (record schema v3).
    surrogate_fallback: jnp.ndarray  # (T,) bool


class RunTraceAux(NamedTuple):
    """Per-run recorder sidecar: the round traces plus the init/prior key
    material replay needs to reconstruct the exact RNG stream."""

    trace: RoundTrace
    root_key: jnp.ndarray   # (2,) uint32 — PRNGKey(seed)
    init_key: jnp.ndarray   # (2,) uint32 — consumed by selector.init
    prior_key: jnp.ndarray  # (2,) uint32 — consumed by the round-0 best()


def key_bits(k) -> jnp.ndarray:
    """A key's raw uint32 counter words (identity for raw old-style keys,
    ``jax.random.key_data`` for typed keys)."""
    k = jnp.asarray(k)
    if jnp.issubdtype(k.dtype, jnp.integer):
        return k.astype(jnp.uint32)
    return jax.random.key_data(k)


def make_round_trace(selector: Selector, res, state_after, k,
                     trace_k: int) -> RoundTrace:
    """One round's provenance record (pure; shared by the recording scan
    step and the replay engine so both emit bit-identical trace math).

    ``state_after`` is the post-update state: the posterior digest describes
    the round's *outcome*, aligned with the ``best_model`` trace entry.
    Selectors that return no score vector still get a minimal record (their
    chosen idx/prob in slot 0)."""
    from coda_tpu.ops.masked import entropy2

    scores = res.scores
    # batched acquisition (acq_batch > 1): idx/prob carry a (q,) axis; the
    # round's "chosen" trace slot is the FIRST (unpenalized-argmax) pick
    idx0 = res.idx if res.idx.ndim == 0 else res.idx[0]
    prob0 = res.prob if res.prob.ndim == 0 else res.prob[0]
    if scores is None:
        topk_score = jnp.full((trace_k,), -jnp.inf,
                              jnp.float32).at[0].set(prob0)
        topk_idx = jnp.full((trace_k,), -1, jnp.int32).at[0].set(idx0)
        chosen = prob0.astype(jnp.float32)
    else:
        topk_score, topk_idx = lax.top_k(scores.astype(jnp.float32), trace_k)
        topk_idx = topk_idx.astype(jnp.int32)
        chosen = scores[idx0].astype(jnp.float32)
    gap = (topk_score[0] - topk_score[1] if trace_k >= 2
           else jnp.asarray(0.0, jnp.float32))
    get_pbest = selector.extras.get("get_pbest")
    if get_pbest is not None:
        pb = get_pbest(state_after).astype(jnp.float32)
        pbest_max = pb.max()
        pbest_entropy = entropy2(pb)
    else:
        pbest_max = jnp.asarray(jnp.nan, jnp.float32)
        pbest_entropy = jnp.asarray(jnp.nan, jnp.float32)
    # the surrogate scorer's per-round fallback flag (False for exact
    # scorers / methods without the hook) — the stream evidence behind
    # the committed fallback-rate contract (BENCH_SURROGATE_*)
    stats_fn = selector.extras.get("scorer_round_stats")
    fallback = (jnp.asarray(stats_fn(state_after), bool)
                if stats_fn is not None else jnp.asarray(False))
    return RoundTrace(
        round_key=key_bits(k),
        topk_idx=topk_idx,
        topk_score=topk_score,
        chosen_score=chosen,
        runner_up_gap=gap,
        pbest_max=pbest_max,
        pbest_entropy=pbest_entropy,
        surrogate_fallback=fallback,
    )


def make_step_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    trace_k: int = 0,
    acq_batch: int = 1,
):
    """One labeling round as a pure scan step.

    ``carry = (selector state, cumulative regret)``; per-round outputs are
    ``(idx, true_class, best, regret, cum, prob, stochastic)``. Shared by the
    single-shot scan (`build_experiment_fn`) and the chunked resumable runner
    (`coda_tpu.engine.checkpoint`), so both execute the identical program.

    ``trace_k > 0`` appends a :class:`RoundTrace` to the per-round outputs
    (the flight-recorder tap). The seven base outputs' dataflow is untouched
    — the trace only *reads* values the step already computes — so a
    recorded run's decision trajectory is the unrecorded program's, pinned
    by ``tests/test_recorder.py``.

    ``acq_batch = q > 1``: the round acquires q points in one scoring pass
    (``selectors/batch.py`` — a selector's native ``select_q`` or the
    generic greedy top-q) and applies all q oracle answers as ONE fused
    update; ``idx``/``true_class``/``prob`` then carry a trailing ``(q,)``
    axis and the cumulative-regret trace is LABEL-weighted (each round's
    regret counts its q labels, so budgets align with q=1 runs). ``q = 1``
    is this exact function's legacy body — same trace, bitwise.
    """
    best_loss = model_losses.min()

    if acq_batch > 1:
        from coda_tpu.selectors.batch import resolve_batch_fns

        sel_q, upd_q = resolve_batch_fns(selector, acq_batch)

        def step_q(carry, k):
            state, cum = carry
            k_sel, k_best = jax.random.split(k)
            with jax.named_scope("select_q"):
                res = sel_q(state, k_sel)
            tcs = labels[res.idx]                      # (q,)
            with jax.named_scope("update_q"):
                state = upd_q(state, res.idx, tcs, res.prob)
            with jax.named_scope("best"):
                best, b_stoch = selector.best(state, k_best)
            regret = model_losses[best] - best_loss
            cum = cum + acq_batch * regret             # label-weighted
            outs = (res.idx, tcs, best, regret, cum, res.prob,
                    res.stochastic | b_stoch)
            if trace_k:
                with jax.named_scope("record"):
                    outs = outs + (make_round_trace(selector, res, state,
                                                    k, trace_k),)
            return (state, cum), outs

        return step_q

    # named_scope stamps the phase names into HLO metadata, so a
    # --profile-dir device trace carries the same select/update/best
    # vocabulary as the host-side telemetry spans (ARCHITECTURE.md
    # §"Observability")
    def step(carry, k):
        state, cum = carry
        k_sel, k_best = jax.random.split(k)
        with jax.named_scope("select"):
            res = selector.select(state, k_sel)
        tc = labels[res.idx]
        with jax.named_scope("update"):
            state = selector.update(state, res.idx, tc, res.prob)
        with jax.named_scope("best"):
            best, b_stoch = selector.best(state, k_best)
        regret = model_losses[best] - best_loss
        cum = cum + regret
        outs = (res.idx, tc, best, regret, cum, res.prob,
                res.stochastic | b_stoch)
        if trace_k:
            with jax.named_scope("record"):
                outs = outs + (make_round_trace(selector, res, state, k,
                                                trace_k),)
        return (state, cum), outs

    return step


def _validate_rounds(selector: Selector, N: int, iters: int,
                     acq_batch: int) -> None:
    """``iters`` labeling ROUNDS at ``acq_batch`` labels each must fit the
    pool and any fixed label buffer."""
    n_labels = iters * acq_batch
    if n_labels > N:
        raise ValueError(
            f"iters={iters} x acq_batch={acq_batch} = {n_labels} labels "
            f"exceeds the {N} labelable points; the unlabeled set would "
            "be exhausted mid-run"
        )
    budget = selector.hyperparams.get("budget")
    if budget is not None and n_labels > budget:
        raise ValueError(
            f"selector '{selector.name}' has a fixed label buffer of "
            f"{budget} but iters={iters} x acq_batch={acq_batch} = "
            f"{n_labels} labels; rebuild it with budget >= {n_labels}"
        )


def build_experiment_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    iters: int = 100,
    acq_batch: int = 1,
) -> Callable[[jax.Array], ExperimentResult]:
    """Pure function key -> ExperimentResult for one seed."""
    best_loss = model_losses.min()
    N = labels.shape[0]
    _validate_rounds(selector, N, iters, acq_batch)

    step = make_step_fn(selector, labels, model_losses,
                        acq_batch=acq_batch)

    def experiment(key: jax.Array) -> ExperimentResult:
        k_init, k_prior, k_scan = jax.random.split(key, 3)
        state0 = selector.init(k_init)
        best0, stoch0 = selector.best(state0, k_prior)
        regret0 = model_losses[best0] - best_loss

        keys = jax.random.split(k_scan, iters)
        (_, _), (idxs, tcs, bests, regrets, cums, probs, stoch) = lax.scan(
            step, (state0, jnp.asarray(0.0, jnp.float32)), keys
        )
        return ExperimentResult(
            chosen_idx=idxs,
            true_class=tcs,
            best_model=bests,
            regret=regrets,
            cumulative_regret=cums,
            select_prob=probs,
            regret_at_0=regret0,
            stochastic=stoch.any() | stoch0
            | jnp.asarray(selector.always_stochastic),
        )

    return experiment


def build_recording_experiment_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    iters: int = 100,
    trace_k: int = 8,
    acq_batch: int = 1,
) -> Callable[[jax.Array], tuple]:
    """``key -> (ExperimentResult, RunTraceAux)`` — the flight-recorder
    variant of :func:`build_experiment_fn`.

    Identical experiment program with the per-round provenance tap enabled
    (``make_step_fn(trace_k=...)``): same keys, same selections, same
    metrics; the scan additionally stacks a :class:`RoundTrace` per round
    which the caller harvests once alongside the result."""
    best_loss = model_losses.min()
    N = labels.shape[0]
    _validate_rounds(selector, N, iters, acq_batch)
    trace_k = max(1, min(int(trace_k), N))
    step = make_step_fn(selector, labels, model_losses, trace_k=trace_k,
                        acq_batch=acq_batch)

    def experiment(key: jax.Array):
        k_init, k_prior, k_scan = jax.random.split(key, 3)
        state0 = selector.init(k_init)
        best0, stoch0 = selector.best(state0, k_prior)
        regret0 = model_losses[best0] - best_loss

        keys = jax.random.split(k_scan, iters)
        (_, _), (idxs, tcs, bests, regrets, cums, probs, stoch,
                 trace) = lax.scan(
            step, (state0, jnp.asarray(0.0, jnp.float32)), keys
        )
        result = ExperimentResult(
            chosen_idx=idxs,
            true_class=tcs,
            best_model=bests,
            regret=regrets,
            cumulative_regret=cums,
            select_prob=probs,
            regret_at_0=regret0,
            stochastic=stoch.any() | stoch0
            | jnp.asarray(selector.always_stochastic),
        )
        aux = RunTraceAux(trace=trace, root_key=key_bits(key),
                          init_key=key_bits(k_init),
                          prior_key=key_bits(k_prior))
        return result, aux

    return experiment


def _engine_cost_name(preds, seeds: int, iters: int, factory,
                      label: Optional[str] = None,
                      recorded: bool = False,
                      acq_batch: int = 1) -> str:
    # selector identity keeps two methods at the same (shape, seeds,
    # iters) from overwriting each other's cost-book entry; callers that
    # know the method name (cli) pass it, anonymous factories fall back
    # to the callable's name (a bare lambda stays ambiguous — cost
    # attribution is best-effort telemetry, never load-bearing)
    if label is None:
        label = getattr(factory, "__name__", None) or "anon"
    shape = "x".join(str(int(s)) for s in getattr(preds, "shape", ()))
    return (f"engine/run_seeds/{label}/{shape}/s{seeds}x{iters}"
            + (f"/q{acq_batch}" if acq_batch > 1 else "")
            + ("/rec" if recorded else ""))


def _aot(jit_fn, args: tuple, name: str):
    """AOT-compile, cost-harvest, and execute one engine entry program
    (``telemetry/costs.py``): same HLO, same compile — now with its
    FLOPs/bytes/peak-HBM attribution in the process cost book. Falls back
    to the plain jit call wherever AOT is unavailable."""
    from coda_tpu.telemetry.costs import aot_call

    return aot_call(jit_fn, args, name, site="engine")


def run_seeds_recorded(
    selector_factory: Callable[[jnp.ndarray], Selector],
    preds: jnp.ndarray,
    labels: jnp.ndarray,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
    trace_k: int = 8,
    cost_label: Optional[str] = None,
    acq_batch: int = 1,
):
    """:func:`run_seeds_compiled` with the flight recorder on: returns
    ``(ExperimentResult, RunTraceAux)``, both with a leading seed axis."""
    fn = make_batched_experiment_fn(selector_factory, iters, loss_fn,
                                    trace_k=trace_k, acq_batch=acq_batch)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(seeds)])
    return _aot(jax.jit(fn), (preds, labels, keys),
                _engine_cost_name(preds, seeds, iters, selector_factory,
                                  label=cost_label, recorded=True,
                                  acq_batch=acq_batch))


def run_experiment(
    selector: Selector,
    dataset,
    iters: int = 100,
    seed: int = 0,
    loss_fn: Callable = accuracy_loss,
    model_losses: Optional[jnp.ndarray] = None,
) -> ExperimentResult:
    """Run one seed of the labeling experiment, fully jit-compiled.

    NOTE: the selector's closure-captured prediction tensor is baked into
    the executable as a constant — and a jit-captured SHARDED array is
    silently committed to one device. For sharded/mesh execution use
    :func:`run_seeds_compiled` / :func:`make_batched_experiment_fn`, which
    take ``preds`` as a traced argument and keep GSPMD sharding live.
    """
    if model_losses is None:
        model_losses = compute_true_losses(dataset.preds, dataset.labels, loss_fn)
    fn = build_experiment_fn(selector, dataset.labels, model_losses, iters)
    return jax.jit(fn)(jax.random.PRNGKey(seed))


def run_seeds_compiled(
    selector_factory: Callable[[jnp.ndarray], Selector],
    preds: jnp.ndarray,
    labels: jnp.ndarray,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
    cost_label: Optional[str] = None,
    acq_batch: int = 1,
) -> ExperimentResult:
    """All seeds, with the prediction tensor as a *traced jit argument*.

    ``run_seeds`` takes an already-built selector, whose closures hold the
    concrete ``(H, N, C)`` array — jit then bakes it into the executable as a
    captured constant, which at DomainNet scale (10 GB fp32,
    reference ``paper/fig3.py:129-193``) doubles HBM and stalls lowering.
    Here the selector is constructed inside the traced function from the
    ``preds`` argument, so the tensor stays a runtime parameter. This is the
    production entry point for the CLI and bench.
    """
    fn = make_batched_experiment_fn(selector_factory, iters, loss_fn,
                                    acq_batch=acq_batch)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(seeds)])
    return _aot(jax.jit(fn), (preds, labels, keys),
                _engine_cost_name(preds, seeds, iters, selector_factory,
                                  label=cost_label, acq_batch=acq_batch))


def make_batched_experiment_fn(
    selector_factory: Callable[[jnp.ndarray], Selector],
    iters: int,
    loss_fn: Callable = accuracy_loss,
    trace_k: int = 0,
    acq_batch: int = 1,
):
    """``(preds, labels, keys, *extra) -> ExperimentResult`` (seed axis
    leading).

    Pure and preds-as-argument, so one ``jax.jit`` wrapper of the returned
    function serves *every same-shape task* from the compile cache — the
    basis of the in-process suite runner. ``extra`` forwards optional
    runtime hyperparameters to the factory (``selector_factory(preds,
    *extra)`` — e.g. ModelPicker's per-task ε as a traced scalar, so one
    executable serves every task instead of compiling per tuned value).

    ``trace_k > 0`` switches to the flight-recorder program: the returned
    function yields ``(ExperimentResult, RunTraceAux)`` instead (same
    decision trajectory; see :func:`build_recording_experiment_fn`).
    """
    def fn(preds, labels, keys, *extra):
        sel = selector_factory(preds, *extra)
        losses = compute_true_losses(preds, labels, loss_fn)
        exp = (build_recording_experiment_fn(sel, labels, losses, iters,
                                             trace_k=trace_k,
                                             acq_batch=acq_batch)
               if trace_k else build_experiment_fn(sel, labels, losses,
                                                   iters,
                                                   acq_batch=acq_batch))
        if keys.shape[0] == 1:
            # width-1 batches (the suite's seed-0 probe) skip the seed vmap:
            # under vmap both pallas kernels' custom_vmap rules fall back to
            # the XLA composition even though a single replica needs no
            # batching at all — the unwrapped call keeps the fast path
            # (fused scorer + DMA gather) engaged on TPU
            return jax.tree.map(lambda x: jnp.asarray(x)[None], exp(keys[0]))
        return jax.vmap(exp)(keys)

    return fn


def run_seeds(
    selector: Selector,
    dataset,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
    model_losses: Optional[jnp.ndarray] = None,
) -> ExperimentResult:
    """All seeds of one method in a single compiled vmap.

    Returns an ExperimentResult whose arrays have a leading ``(seeds,)`` axis.
    The reference runs seeds serially and skips seeds for deterministic
    methods (reference ``main.py:128-130``); here seeds are data-parallel and
    essentially free, so all requested seeds run — consumers can still use
    ``result.stochastic`` to collapse identical seeds.
    """
    if model_losses is None:
        model_losses = compute_true_losses(dataset.preds, dataset.labels, loss_fn)
    fn = build_experiment_fn(selector, dataset.labels, model_losses, iters)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(seeds)])
    return jax.jit(jax.vmap(fn))(keys)
