"""Experiment driver: the full labeling loop as one compiled ``lax.scan``.

The reference drives each round from Python — select, query oracle, update,
re-estimate best, log (reference ``main.py:55-105``) — paying a host↔device
round-trip per step. Here the oracle's labels are known up-front (they are
loaded with the dataset, ``coda/oracle.py:6-7``), so the entire experiment is
a pure function

    (preds, labels, hyperparams, seed) -> regret trace

compiled once: ``lax.scan`` over labeling rounds, ``vmap`` over seeds. On a
sharded mesh the same program runs SPMD with XLA inserting the collectives.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.losses import accuracy_loss
from coda_tpu.oracle import true_losses as compute_true_losses
from coda_tpu.selectors.protocol import Selector


class ExperimentResult(NamedTuple):
    """Per-round traces (leading axis = labeling round, length ``iters``)."""

    chosen_idx: jnp.ndarray    # (T,) int32 — which point was labeled
    true_class: jnp.ndarray    # (T,) int32 — its oracle label
    best_model: jnp.ndarray    # (T,) int32 — current best-model guess
    regret: jnp.ndarray        # (T,) float32
    cumulative_regret: jnp.ndarray  # (T,) float32
    select_prob: jnp.ndarray   # (T,) float32 — selection probability / q-value
    regret_at_0: jnp.ndarray   # scalar — prior regret before any labels
    stochastic: jnp.ndarray    # scalar bool — did RNG affect the run?


def make_step_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
):
    """One labeling round as a pure scan step.

    ``carry = (selector state, cumulative regret)``; per-round outputs are
    ``(idx, true_class, best, regret, cum, prob, stochastic)``. Shared by the
    single-shot scan (`build_experiment_fn`) and the chunked resumable runner
    (`coda_tpu.engine.checkpoint`), so both execute the identical program.
    """
    best_loss = model_losses.min()

    # named_scope stamps the phase names into HLO metadata, so a
    # --profile-dir device trace carries the same select/update/best
    # vocabulary as the host-side telemetry spans (ARCHITECTURE.md
    # §"Observability")
    def step(carry, k):
        state, cum = carry
        k_sel, k_best = jax.random.split(k)
        with jax.named_scope("select"):
            res = selector.select(state, k_sel)
        tc = labels[res.idx]
        with jax.named_scope("update"):
            state = selector.update(state, res.idx, tc, res.prob)
        with jax.named_scope("best"):
            best, b_stoch = selector.best(state, k_best)
        regret = model_losses[best] - best_loss
        cum = cum + regret
        return (state, cum), (res.idx, tc, best, regret, cum, res.prob,
                              res.stochastic | b_stoch)

    return step


def build_experiment_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    iters: int = 100,
) -> Callable[[jax.Array], ExperimentResult]:
    """Pure function key -> ExperimentResult for one seed."""
    best_loss = model_losses.min()
    N = labels.shape[0]
    if iters > N:
        raise ValueError(
            f"iters={iters} exceeds the {N} labelable points; the unlabeled "
            "set would be exhausted mid-run"
        )
    budget = selector.hyperparams.get("budget")
    if budget is not None and iters > budget:
        raise ValueError(
            f"selector '{selector.name}' has a fixed label buffer of "
            f"{budget} but iters={iters}; rebuild it with budget >= iters"
        )

    step = make_step_fn(selector, labels, model_losses)

    def experiment(key: jax.Array) -> ExperimentResult:
        k_init, k_prior, k_scan = jax.random.split(key, 3)
        state0 = selector.init(k_init)
        best0, stoch0 = selector.best(state0, k_prior)
        regret0 = model_losses[best0] - best_loss

        keys = jax.random.split(k_scan, iters)
        (_, _), (idxs, tcs, bests, regrets, cums, probs, stoch) = lax.scan(
            step, (state0, jnp.asarray(0.0, jnp.float32)), keys
        )
        return ExperimentResult(
            chosen_idx=idxs,
            true_class=tcs,
            best_model=bests,
            regret=regrets,
            cumulative_regret=cums,
            select_prob=probs,
            regret_at_0=regret0,
            stochastic=stoch.any() | stoch0
            | jnp.asarray(selector.always_stochastic),
        )

    return experiment


def run_experiment(
    selector: Selector,
    dataset,
    iters: int = 100,
    seed: int = 0,
    loss_fn: Callable = accuracy_loss,
    model_losses: Optional[jnp.ndarray] = None,
) -> ExperimentResult:
    """Run one seed of the labeling experiment, fully jit-compiled.

    NOTE: the selector's closure-captured prediction tensor is baked into
    the executable as a constant — and a jit-captured SHARDED array is
    silently committed to one device. For sharded/mesh execution use
    :func:`run_seeds_compiled` / :func:`make_batched_experiment_fn`, which
    take ``preds`` as a traced argument and keep GSPMD sharding live.
    """
    if model_losses is None:
        model_losses = compute_true_losses(dataset.preds, dataset.labels, loss_fn)
    fn = build_experiment_fn(selector, dataset.labels, model_losses, iters)
    return jax.jit(fn)(jax.random.PRNGKey(seed))


def run_seeds_compiled(
    selector_factory: Callable[[jnp.ndarray], Selector],
    preds: jnp.ndarray,
    labels: jnp.ndarray,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
) -> ExperimentResult:
    """All seeds, with the prediction tensor as a *traced jit argument*.

    ``run_seeds`` takes an already-built selector, whose closures hold the
    concrete ``(H, N, C)`` array — jit then bakes it into the executable as a
    captured constant, which at DomainNet scale (10 GB fp32,
    reference ``paper/fig3.py:129-193``) doubles HBM and stalls lowering.
    Here the selector is constructed inside the traced function from the
    ``preds`` argument, so the tensor stays a runtime parameter. This is the
    production entry point for the CLI and bench.
    """
    fn = make_batched_experiment_fn(selector_factory, iters, loss_fn)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(seeds)])
    return jax.jit(fn)(preds, labels, keys)


def make_batched_experiment_fn(
    selector_factory: Callable[[jnp.ndarray], Selector],
    iters: int,
    loss_fn: Callable = accuracy_loss,
):
    """``(preds, labels, keys, *extra) -> ExperimentResult`` (seed axis
    leading).

    Pure and preds-as-argument, so one ``jax.jit`` wrapper of the returned
    function serves *every same-shape task* from the compile cache — the
    basis of the in-process suite runner. ``extra`` forwards optional
    runtime hyperparameters to the factory (``selector_factory(preds,
    *extra)`` — e.g. ModelPicker's per-task ε as a traced scalar, so one
    executable serves every task instead of compiling per tuned value).
    """
    def fn(preds, labels, keys, *extra):
        sel = selector_factory(preds, *extra)
        losses = compute_true_losses(preds, labels, loss_fn)
        exp = build_experiment_fn(sel, labels, losses, iters)
        if keys.shape[0] == 1:
            # width-1 batches (the suite's seed-0 probe) skip the seed vmap:
            # under vmap both pallas kernels' custom_vmap rules fall back to
            # the XLA composition even though a single replica needs no
            # batching at all — the unwrapped call keeps the fast path
            # (fused scorer + DMA gather) engaged on TPU
            return jax.tree.map(lambda x: jnp.asarray(x)[None], exp(keys[0]))
        return jax.vmap(exp)(keys)

    return fn


def run_seeds(
    selector: Selector,
    dataset,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
    model_losses: Optional[jnp.ndarray] = None,
) -> ExperimentResult:
    """All seeds of one method in a single compiled vmap.

    Returns an ExperimentResult whose arrays have a leading ``(seeds,)`` axis.
    The reference runs seeds serially and skips seeds for deterministic
    methods (reference ``main.py:128-130``); here seeds are data-parallel and
    essentially free, so all requested seeds run — consumers can still use
    ``result.stochastic`` to collapse identical seeds.
    """
    if model_losses is None:
        model_losses = compute_true_losses(dataset.preds, dataset.labels, loss_fn)
    fn = build_experiment_fn(selector, dataset.labels, model_losses, iters)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(seeds)])
    return jax.jit(jax.vmap(fn))(keys)
