"""Cross-session surrogate prior benchmark -> BENCH_PRIOR_<b>_rNN.json.

The ``--surrogate-prior pool`` claim, measured and replay-verified
(ISSUE 18):

  * **warmup-cost reduction** (>= 3x, the amortization claim): a session
    seeded from a mature donor's pooled fit statistics pays >= 3x fewer
    exact warmup rounds than a cold session — counted round by round
    from the carried warm condition (``rounds + prior_rounds <
    SURROGATE_WARMUP_ROUNDS``), not inferred.
  * **regret envelope** (real-digits trace): the seeded run's final
    cumulative regret stays inside the surrogate envelope
    (1.05x + 0.02) of the COLD run at the same label budget — the prior
    moves when the surrogate starts carrying rounds, never what the
    trust gate lets it serve. Both runs are recorded, each self-replays
    bitwise, and the pool-vs-off pair triages as
    ``surrogate-prior-envelope`` through the real ``cli replay
    --against`` path.
  * **never unaudited**: on EVERY driven round (cold, seeded, and
    hostile-prior), the selected index's served score is bitwise the
    exact chain's value — 0 unaudited argmax picks, the invariant the
    whole transfer rides on.
  * **gate rejection**: a hostile prior (garbage normal equations with
    full warmup credit) is caught by the per-round contract — it
    increments ``prior_rejects`` and every rejected round's score
    vector is bitwise the exact pass's (the fallback safety net,
    exercised, not assumed).
  * **off parity**: ``--surrogate-prior off`` (the default) is pinned
    bitwise to the knob-less PR 14 program through the real
    ``cli replay --against --score-tol 0`` path.

Runnable standalone (CPU container: ~2 min full, ~40 s quick)::

    python scripts/bench_prior.py --out BENCH_PRIOR_CPU_r18.json \
        --records-dir runs/prior_r18
    python scripts/bench_prior.py --quick

The finished artifact is self-gated against its ``check_perf.py``
contract before the script exits.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the declared bounds are the GATE's, imported from the one place they
# are enforced (scripts/check_perf.py) so the generator can never embed
# verdicts computed under stale thresholds
from check_perf import (  # noqa: E402
    PRIOR_ENVELOPE_ABS as ENVELOPE_ABS,
    PRIOR_ENVELOPE_RATIO as ENVELOPE_RATIO,
    PRIOR_MIN_WARMUP_REDUCTION as MIN_REDUCTION,
)


def _knobs(args, **extra) -> dict:
    base = {"bench": "prior", "quick": bool(args.quick)}
    base.update(extra)
    return base


def _cli_replay(args_list) -> int:
    """The REAL ``cli replay`` path, as a subprocess (what the artifact's
    verification commands document)."""
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS",
                                                        "cpu"))
    r = subprocess.run(
        [sys.executable, "-m", "coda_tpu.cli", "replay"] + args_list,
        cwd=REPO, capture_output=True, text=True, timeout=900, env=env)
    sys.stderr.write(r.stdout[-2000:])
    return r.returncode


def _drive_audited(ds, hp, rounds: int, seed: int, prior=None) -> dict:
    """Drive one session round by round, auditing every selection: exact
    warmup rounds actually paid (the warm condition read off the carried
    fit), served-argmax-vs-exact bitwise agreement, and — on every
    fallback round — the full score vector against the exact pass."""
    import jax

    from coda_tpu.selectors import make_coda
    from coda_tpu.selectors.surrogate import SURROGATE_WARMUP_ROUNDS

    sel = make_coda(ds.preds, hp, prior=prior)
    st = jax.jit(sel.init)(jax.random.PRNGKey(seed))
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    score_exact = jax.jit(sel.extras["score_exact"])
    key = jax.random.PRNGKey(seed + 1)
    paid = unaudited = 0
    fell_back_exact = True
    for _ in range(rounds):
        fit = st.surrogate
        if int(fit.rounds) + int(fit.prior_rounds) < \
                SURROGATE_WARMUP_ROUNDS:
            paid += 1
        key, k = jax.random.split(key)
        res = slx(st, k)
        i = int(res.idx)
        exact = np.asarray(score_exact(st))
        got = np.asarray(st.eig_scores_cached)
        if exact[i].tobytes() != got[i].tobytes():
            unaudited += 1
        st = upd(st, res.idx, ds.labels[res.idx], res.prob)
        if bool(st.surrogate.last_fallback):
            # a rejected round must have produced the exact pass bitwise
            ex = np.asarray(score_exact(st))
            if ex.tobytes() != np.asarray(st.eig_scores_cached).tobytes():
                fell_back_exact = False
    fit = st.surrogate
    return {
        "rounds": rounds,
        "exact_warmup_rounds_paid": paid,
        "unaudited_argmax_picks": unaudited,
        "prior_credit": int(fit.prior_rounds),
        "prior_rejects": int(fit.prior_rejects),
        "fallbacks": int(fit.fallbacks),
        "fell_back_exact": fell_back_exact,
        "fit": {"A": np.asarray(fit.A, np.float64),
                "b": np.asarray(fit.b, np.float64),
                "n": float(fit.n), "rounds": float(fit.rounds)},
    }


def _run_warmup_and_gate(args, ds) -> tuple:
    """The driven halves: donor -> prior -> seeded warmup accounting,
    plus the hostile-prior gate-rejection probe. Returns (warmup, audit,
    gate_rejection, donor_prior)."""
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors import surrogate as sg

    scorer = f"surrogate:{args.k}"
    rounds = sg.SURROGATE_WARMUP_ROUNDS + (4 if args.quick else 10)
    hp_cold = CODAHyperparams(eig_scorer=scorer)
    cold = _drive_audited(ds, hp_cold, rounds, seed=0)
    donor = sg.clip_prior(sg.prior_from_fit(
        cold["fit"]["A"], cold["fit"]["b"], cold["fit"]["n"],
        cold["fit"]["rounds"]))
    hp_pool = CODAHyperparams(eig_scorer=scorer, surrogate_prior="pool")
    seeded = _drive_audited(ds, hp_pool, rounds, seed=1, prior=donor)

    # the hostile prior: near-singular normal equations with huge b and
    # full warmup credit — the per-round contract must catch it
    rng = np.random.default_rng(0)
    F = sg.N_FEATURES
    hostile = sg.prior_from_fit(np.eye(F) * 1e-6,
                                rng.normal(size=(F,)) * 1e4,
                                n=100.0, rounds=50.0)
    gate = _drive_audited(ds, hp_pool, 6, seed=2, prior=hostile)

    warmup = {
        "warmup_rounds": sg.SURROGATE_WARMUP_ROUNDS,
        "cold_exact_rounds": cold["exact_warmup_rounds_paid"],
        "seeded_exact_rounds": seeded["exact_warmup_rounds_paid"],
        "seeded_credit": seeded["prior_credit"],
        "reduction": (cold["exact_warmup_rounds_paid"]
                      / max(1, seeded["exact_warmup_rounds_paid"])),
        "donor_rounds_pooled": float(donor.rounds),
    }
    audit = {
        "rounds_driven": cold["rounds"] + seeded["rounds"]
        + gate["rounds"],
        "unaudited_argmax_picks": (cold["unaudited_argmax_picks"]
                                   + seeded["unaudited_argmax_picks"]
                                   + gate["unaudited_argmax_picks"]),
    }
    gate_rejection = {
        "prior_credit": gate["prior_credit"],
        "prior_rejects": gate["prior_rejects"],
        "fallbacks": gate["fallbacks"],
        "fell_back_exact": bool(gate["fell_back_exact"]
                                and cold["fell_back_exact"]
                                and seeded["fell_back_exact"]),
    }
    return warmup, audit, gate_rejection, donor


def _run_digits(args, ds, donor, fingerprint_holder: list) -> tuple:
    """The recorded halves on the digits trace: cold vs seeded regret +
    bitwise self-replays, the pool-vs-off triage through the real
    ``cli replay --against`` path, and the off-parity bitwise pin."""
    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.engine.replay import verify_replay
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors import surrogate as sg
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    iters = 40 if args.quick else 100
    seeds = 2 if args.quick else 3
    scorer = f"surrogate:{args.k}"
    digest = sg.prior_digest(donor)
    out: dict = {"task": ds.name, "shape": list(ds.shape),
                 "label_budget": iters, "seeds": seeds, "scorer": scorer,
                 "prior_digest": digest}
    # "cold" records the knob-less program (a pre-pool capture); "off"
    # records --surrogate-prior off explicitly: the two must be BITWISE
    # identical through cli replay --against (the off pin). "seeded"
    # runs the same program warm-started from the donor pool.
    configs = {"cold": (None, None), "off": ("off", None),
               "seeded": ("pool", donor)}
    records = {}
    for name, (knob, prior) in configs.items():
        hp_kwargs = dict(eig_scorer=scorer, n_parallel=seeds)
        if knob is not None:
            hp_kwargs["surrogate_prior"] = knob
        hp = CODAHyperparams(**hp_kwargs)
        factory = (lambda _hp, _p: (
            lambda preds: make_coda(preds, _hp, prior=_p)))(hp, prior)
        t0 = time.perf_counter()
        result, aux = run_seeds_recorded(
            factory, ds.preds, ds.labels, iters=iters, seeds=seeds,
            trace_k=8, cost_label=f"prior_digits_{name}")
        np.asarray(result.cumulative_regret)  # sync
        wall = time.perf_counter() - t0
        knobs = _knobs(args, capture="digits", method="coda", loss="acc",
                       iters=iters, seeds=seeds, n_parallel=seeds,
                       eig_scorer=scorer)
        if knob is not None:
            knobs["surrogate_prior"] = knob
        if prior is not None:
            knobs["surrogate_prior_digest"] = digest
        fp = environment_fingerprint(dataset=ds, knobs=knobs)
        if not fingerprint_holder:
            fingerprint_holder.append(environment_fingerprint(
                dataset=ds, knobs=_knobs(args)))
        record = RunRecord.from_result(
            result, aux, fp,
            run={"task": ds.name, "synthetic": None,
                 "data_dir": args.data_dir, "method": "coda",
                 "loss": "acc", "iters": iters, "seeds": seeds})
        rec_dir = os.path.join(args.records_dir, name)
        record.save(rec_dir)
        records[name] = rec_dir
        cum = np.asarray(result.cumulative_regret)[:, -1]
        entry = {
            "iters": iters, "wall_s": round(wall, 3),
            "record_dir": os.path.relpath(rec_dir, REPO),
            "final_cum_regret_mean": float(cum.mean()),
            "final_cum_regret_per_seed": [float(v) for v in cum],
        }
        rep = verify_replay(record, factory, ds.preds, ds.labels,
                            loss="acc", score_tol=0.0)
        entry["replay"] = {
            "parity": bool(rep.parity),
            "cli": f"cli replay {os.path.relpath(rec_dir, REPO)}",
        }
        out[name] = entry

    # pool vs off through the REAL cli replay --against path: the
    # surrogate_prior knob diff must auto-resolve to the envelope triage
    report_fp = os.path.join(args.records_dir, "against_cold.json")
    rc = _cli_replay([records["cold"], "--against", records["seeded"],
                      "--out", report_fp])
    with open(report_fp) as f:
        rep = json.load(f)
    cls = (rep.get("seeds") or [{}])[0].get("classification")
    cold_mean = out["cold"]["final_cum_regret_mean"]
    seeded_mean = out["seeded"]["final_cum_regret_mean"]
    within = seeded_mean <= ENVELOPE_RATIO * cold_mean + ENVELOPE_ABS
    out["against_cold"] = {
        "cli": (f"cli replay {os.path.relpath(records['cold'], REPO)} "
                f"--against "
                f"{os.path.relpath(records['seeded'], REPO)}"),
        "rc": rc,
        "classification": cls,
        "envelope": rep.get("meta", {}).get("prior_envelope"),
        "ratio_vs_cold": (seeded_mean / cold_mean if cold_mean > 0
                          else None),
        "within_envelope": bool(within),
    }
    # the off pin: --surrogate-prior off must be BITWISE the knob-less
    # program (score-tol forced to 0 — the bitwise claim, not a triage)
    rc_pin = _cli_replay([records["cold"], "--against", records["off"],
                          "--score-tol", "0"])
    pin = {
        "cli": (f"cli replay {os.path.relpath(records['cold'], REPO)} "
                f"--against {os.path.relpath(records['off'], REPO)} "
                "--score-tol 0"),
        "rc": rc_pin,
        "parity": rc_pin == 0,
        "score_tol": 0.0,
    }
    out["envelope"] = {"ratio": ENVELOPE_RATIO, "abs_slack": ENVELOPE_ABS,
                       "ok": bool(within)}
    return out, pin


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="artifact path (default BENCH_PRIOR_"
                         "<backend>_rNN.json in the repo root)")
    ap.add_argument("--records-dir", default=None,
                    help="where the flight-recorder records land "
                         "(default runs/prior_rNN under --out's "
                         "directory)")
    ap.add_argument("--data-dir", default=os.path.join(REPO, "data"))
    ap.add_argument("--quick", action="store_true",
                    help="smoke capture: smaller budgets (never gates "
                         "the full artifact — different fingerprint "
                         "knobs)")
    ap.add_argument("--round", type=int, default=18,
                    help="artifact round number for the default filename")
    ap.add_argument("--k", type=int, default=16,
                    help="surrogate shortlist width")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax

    backend = jax.default_backend().upper()
    out_path = args.out or os.path.join(
        REPO, f"BENCH_PRIOR_{backend}_r{args.round:02d}"
              + ("_quick" if args.quick else "") + ".json")
    if args.records_dir is None:
        args.records_dir = os.path.join(
            os.path.dirname(os.path.abspath(out_path)) or ".",
            "runs", f"prior{'_quick' if args.quick else ''}_r"
                    f"{args.round:02d}")

    from coda_tpu.cli import load_dataset

    ds = load_dataset(argparse.Namespace(
        task="digits", data_dir=args.data_dir, synthetic=None, mesh=None))

    fingerprint_holder: list = []
    t0 = time.perf_counter()
    warmup, audit, gate, donor = _run_warmup_and_gate(args, ds)
    digits, off_pin = _run_digits(args, ds, donor, fingerprint_holder)
    wall = time.perf_counter() - t0

    replays_ok = all(
        (digits.get(side) or {}).get("replay", {}).get("parity") is True
        for side in ("cold", "off", "seeded"))
    triaged = (digits.get("against_cold", {}).get("classification")
               == "surrogate-prior-envelope")
    ok = bool(digits["envelope"]["ok"] and replays_ok and triaged
              and warmup["reduction"] >= MIN_REDUCTION
              and audit["unaudited_argmax_picks"] == 0
              and gate["prior_rejects"] >= 1 and gate["fell_back_exact"]
              and off_pin["parity"])
    report = {
        "bench": "prior",
        "quick": bool(args.quick),
        "wall_s": round(wall, 2),
        "config": {
            "method": "coda",
            "transfer": "per-(task, pool-fingerprint) merged normal-"
                        "equation statistics (A, b, n) from closed/"
                        "demoted sessions; new sessions seed the carried "
                        "fit and earn warmup credit; the per-round "
                        "escape/audit/contract gate is unchanged, so "
                        "selection is never driven by an unaudited "
                        "score",
            "envelope": {"ratio": ENVELOPE_RATIO,
                         "abs_slack": ENVELOPE_ABS},
            "warmup_reduction_floor": MIN_REDUCTION,
        },
        "digits": digits,
        "warmup": warmup,
        "audit": audit,
        "gate_rejection": gate,
        "off_parity": off_pin,
        "regret_envelope_ok": bool(digits["envelope"]["ok"]),
        "replays_verified": bool(replays_ok),
        "divergences_triaged": bool(triaged),
        "fingerprint": fingerprint_holder[0] if fingerprint_holder
        else None,
        "ok": ok,
    }
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out_path} (ok={ok}, "
          f"reduction={warmup['reduction']:.1f}x, "
          f"envelope_ok={digits['envelope']['ok']}, "
          f"unaudited={audit['unaudited_argmax_picks']}, "
          f"prior_rejects={gate['prior_rejects']})")

    # self-gate: the artifact must satisfy its own check_perf contract
    # (quick captures carry no committed floors — structural gate only)
    if not args.quick:
        from check_perf import check_artifact, match_contract

        contract = match_contract(out_path)
        if contract is None:
            print("self-gate: no contract matches the artifact name")
            return 1
        violations = check_artifact(out_path, report, contract)
        for v in violations:
            print(f"self-gate: {v}")
        if violations:
            return 1
        print("self-gate clean")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
