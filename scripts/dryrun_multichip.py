"""Multi-chip dry runs: shard_map parity configs + the suite scheduler.

Wraps the driver entry ``__graft_entry__.dryrun_multichip`` (the toy and
realistic sharded-vs-single-device trace-parity configs, including the
shard_map pallas fast path) and adds the TASK-PARALLEL SCHEDULER config:
a multi-family suite dispatched across the n-device virtual mesh through
``SuiteRunner.run_batched(devices=...)``, checked BITWISE against the
serial path and timed against it, emitting ``MULTICHIP_r06.json``-style
evidence (parity verdicts, per-device occupancy, wall clocks).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/dryrun_multichip.py 8 --out MULTICHIP_SCHED_r06.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _ensure_virtual_devices(n: int) -> None:
    """Force an n-virtual-device CPU backend when no accelerator platform
    is configured (same trick as tests/conftest.py; must precede any jax
    import)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def scheduler_dryrun(n_devices: int) -> dict:
    """The scheduler config: multi-family suite over the virtual mesh.

    Serial ``run_batched`` is the reference; the scheduled run must match
    it bitwise (same executables, same keys — placement is a pure copy).
    Returns the evidence record for the MULTICHIP artifact."""
    import time

    import numpy as np

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner

    fam_a = [make_synthetic_task(seed=i, H=4, N=48, C=3, name=f"alpha_{i}")
             for i in range(3)]
    fam_b = [make_synthetic_task(seed=10 + i, H=3, N=32, C=4,
                                 name=f"beta_{i}") for i in range(2)]
    groups = [fam_a, fam_b]
    methods = ["iid", "uncertainty", "model_picker"]
    profile = {"per_family_warm_s": {"alpha": 3.0, "beta": 1.0}}

    serial = SuiteRunner(iters=4, seeds=3)
    t0 = time.perf_counter()
    r_ser = serial.run_batched(groups, methods, progress=lambda s: None)
    wall_serial = time.perf_counter() - t0

    sched = SuiteRunner(iters=4, seeds=3)
    t0 = time.perf_counter()
    r_sch = sched.run_batched(groups, methods, progress=lambda s: None,
                              devices=n_devices, cost_profile=profile)
    wall_sched = time.perf_counter() - t0

    assert set(r_ser) == set(r_sch)
    for key in r_ser:
        for a, b in zip(r_ser[key], r_sch[key]):
            a, b = np.asarray(a), np.asarray(b)
            assert a.tobytes() == b.tobytes(), (
                f"scheduled result diverges bitwise at {key}")
    stats = sched.last_stats
    print(f"dryrun_multichip[scheduler] OK: {len(r_sch)} pairs over "
          f"{stats['n_devices']} devices, bitwise parity vs serial PASSED "
          f"(serial {wall_serial:.2f}s, scheduled {wall_sched:.2f}s, "
          f"occupancy {stats['occupancy']})")
    return {
        "config": "scheduler",
        "pairs": len(r_sch),
        "n_devices": stats["n_devices"],
        "schedule": stats["schedule"],
        "bitwise_parity_vs_serial": True,
        "wall_serial_s": round(wall_serial, 3),
        "wall_scheduled_s": round(wall_sched, 3),
        "compute_s": round(stats["compute_s"], 3),
        "compute_device_s": round(stats["compute_device_s"], 3),
        "occupancy": stats["occupancy"],
        "est_device_load": stats["est_device_load"],
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("n_devices", nargs="?", type=int, default=8)
    p.add_argument("--out", default=None, metavar="MULTICHIP.json",
                   help="write the evidence record to this JSON file")
    p.add_argument("--skip-shard-map", action="store_true",
                   help="run only the scheduler config (the shard_map "
                        "configs re-run the full sharded experiments)")
    args = p.parse_args(argv)
    _ensure_virtual_devices(args.n_devices)

    line = {"n_devices": args.n_devices, "ok": True, "configs": []}
    if not args.skip_shard_map:
        import __graft_entry__

        __graft_entry__.dryrun_multichip(args.n_devices)
        line["configs"].append({"config": "shard_map toy+realistic",
                                "trace_parity": True})
    line["configs"].append(scheduler_dryrun(args.n_devices))
    print(json.dumps(line))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(line, f, indent=2)


if __name__ == "__main__":
    main()
