"""Multi-chip dry runs: shard_map parity configs + the suite scheduler.

The sharded-vs-serial and pallas-vs-XLA checks run ON TOP OF THE REPLAY
VERIFIER: each variant executes with the decision flight recorder enabled
(``engine/loop.py`` trace tap), and the comparisons go through
``engine/replay.compare_records`` — ONE code path for divergence location
and classification instead of the three hand-rolled assert blocks this
script and ``__graft_entry__`` used to carry. The contracts:

  * **sharded vs single-device** (same XLA lowering, GSPMD collectives):
    decision trace pinned at the documented ~1-ulp psum tolerance — any
    divergence beyond it fails with a triage naming the first round;
  * **pallas vs XLA** (cross-backend): the 2.34e-4 score contract; only
    ``tie-break-flip``-classified divergences are accepted, and best-model
    + regret stay pinned at the old strict bounds;
  * **scheduler vs serial**: bitwise (placement is a pure copy).

Also runs the TASK-PARALLEL SCHEDULER config: a multi-family suite
dispatched across the n-device virtual mesh through
``SuiteRunner.run_batched(devices=...)``, checked bitwise against the
serial path and timed against it, emitting ``MULTICHIP_r06.json``-style
evidence (parity verdicts, per-device occupancy, wall clocks).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/dryrun_multichip.py 8 --out MULTICHIP_SCHED_r08.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _ensure_virtual_devices(n: int) -> None:
    """Force an n-virtual-device CPU backend when no accelerator platform
    is configured (same trick as tests/conftest.py; must precede any jax
    import)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _record_variant(task, hp, iters: int, label: str):
    """One recorded execution of the experiment (preds as a traced jit
    argument so sharding stays live); returns a RunRecord."""
    import jax.numpy as jnp
    import jax

    from coda_tpu.engine.loop import make_batched_experiment_fn
    from coda_tpu.selectors import make_coda
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    fn = make_batched_experiment_fn(lambda p: make_coda(p, hp),
                                    iters=iters, trace_k=4)
    keys = jnp.stack([jax.random.PRNGKey(0)])
    result, aux = jax.jit(fn)(task.preds, task.labels, keys)
    fp = environment_fingerprint(knobs={"variant": label})
    fp["dataset"] = {"name": task.name,
                     "shape": list(task.preds.shape)}
    return RunRecord.from_result(result, aux, fp,
                                 run={"task": task.name, "iters": iters,
                                      "variant": label})


def _pins_ok(a, b) -> tuple:
    """The strict legacy pins: best-model trace exact, regret to the psum
    reduction-order bound (rtol=1e-6/atol=1e-7)."""
    import numpy as np

    best_ok = bool((a.arrays["best_model"] == b.arrays["best_model"]).all())
    reg_ok = bool(np.allclose(a.arrays["regret"], b.arrays["regret"],
                              rtol=1e-6, atol=1e-7))
    return best_ok, reg_ok


def shard_map_dryrun(n_devices: int, C: int, iters: int, num_points: int,
                     label: str, H: int = 0, N: int = 0,
                     H_per_model: int = 0, N_per_data: int = 0,
                     eig_chunk: int = 0) -> dict:
    """Sharded-vs-single and pallas-vs-XLA parity via the replay verifier.

    Same configs as ``__graft_entry__.dryrun_multichip`` (toy + realistic
    shapes), but every variant runs recorded and ALL comparisons are
    ``compare_records`` triage reports — a regression here names the first
    divergent round and quantity instead of dumping a raw assert."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.replay import compare_records, format_triage
    from coda_tpu.parallel import DATA_AXIS, MODEL_AXIS, make_mesh
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.telemetry.recorder import CROSS_BACKEND_SCORE_TOL

    devices = jax.devices()
    assert len(devices) >= n_devices, (
        f"need {n_devices} devices, have {len(devices)}")
    model = 2 if n_devices % 2 == 0 and n_devices >= 4 else 1
    data = n_devices // model
    mesh = make_mesh(data=data, model=model, devices=devices[:n_devices])
    H = H or H_per_model * model
    N = N or N_per_data * data
    N -= N % n_devices
    assert H % model == 0 and N > 0, (H, N, model, data)
    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    hp = CODAHyperparams(eig_chunk=eig_chunk or N, num_points=num_points)

    # reference: replicated single-device run
    single = type(task)(
        preds=jax.device_put(task.preds, devices[0]),
        labels=jax.device_put(task.labels, devices[0]),
        name=task.name)
    rec_single = _record_variant(single, hp, iters, "single")
    assert np.isfinite(rec_single.arrays["regret"]).all()

    # sharded run: (H, N, C) over (model, data); same program, XLA inserts
    # the collectives. Same-lowering contract: ~1-ulp psum reordering on
    # float quantities, decisions exact (1e-6 absolute covers the measured
    # reduction-order drift; chosen/best indices always compare exact).
    sharded = type(task)(
        preds=jax.device_put(task.preds,
                             NamedSharding(mesh,
                                           P(MODEL_AXIS, DATA_AXIS, None))),
        labels=jax.device_put(task.labels, NamedSharding(mesh, P(DATA_AXIS))),
        name=task.name)
    rec_sharded = _record_variant(sharded, hp, iters, "sharded")
    rep_shard = compare_records(rec_single, rec_sharded, score_tol=1e-6)
    if not rep_shard.parity:
        raise AssertionError(
            "sharded-vs-single decision trace diverged:\n"
            + format_triage(rep_shard))
    # the legacy pins stay at their strict bounds here too: regret's flat
    # 1e-6 in the triage comparison is looser than the historical
    # rtol=1e-6/atol=1e-7 psum-reduction-order bound
    best_ok, reg_ok = _pins_ok(rec_single, rec_sharded)
    assert best_ok and reg_ok, (
        f"sharded-vs-single pinned quantities regressed "
        f"(best_model exact: {best_ok}, regret 1e-6/1e-7: {reg_ok})")

    # pallas shard_map fast path (data-only mesh): CROSS-BACKEND contract —
    # scores to 2.34e-4, near-tie argmax flips allowed but only when the
    # triage classifies them as tie-break flips AND the legacy pins hold
    # (best-model exact, regret to 1e-6/1e-7)
    mesh_d = make_mesh(data=n_devices, devices=devices[:n_devices])
    hp_p = CODAHyperparams(eig_chunk=eig_chunk or N, num_points=num_points,
                           eig_mode="incremental", eig_backend="pallas",
                           shard_spec=f"data={n_devices}")
    data_sharded = type(task)(
        preds=jax.device_put(task.preds,
                             NamedSharding(mesh_d, P(None, DATA_AXIS, None))),
        labels=jax.device_put(task.labels,
                              NamedSharding(mesh_d, P(DATA_AXIS))),
        name=task.name)
    rec_pallas = _record_variant(data_sharded, hp_p, iters, "pallas")
    rep_pal = compare_records(rec_single, rec_pallas,
                              score_tol=CROSS_BACKEND_SCORE_TOL)
    flips = 0
    for s in rep_pal.seeds:
        if s.parity:
            continue
        if s.classification != "tie-break-flip":
            raise AssertionError(
                "pallas-vs-XLA diverged beyond the cross-backend score "
                "contract:\n" + format_triage(rep_pal))
        flips += 1
    best_ok, reg_ok = _pins_ok(rec_single, rec_pallas)
    assert best_ok and reg_ok, (
        f"pallas-vs-XLA flip broke the pinned quantities "
        f"(best_model exact: {best_ok}, regret 1e-6/1e-7: {reg_ok}):\n"
        + format_triage(rep_pal))

    print(f"dryrun_multichip[{label}] OK: mesh=({data}x{model}) "
          f"devices={n_devices} H={H} N={N} C={C} rounds={iters} — "
          f"replay-verifier parity: sharded==single within 1e-6 "
          f"(decisions exact), pallas within {CROSS_BACKEND_SCORE_TOL} "
          + (f"({flips} seed(s) with tie-break flips, best/regret pinned)"
             if flips else "(idx trace bitwise)"))
    return {
        "config": f"shard_map {label}",
        "n_devices": n_devices,
        "mesh": f"{data}x{model}",
        "H": H, "N": N, "C": C, "rounds": iters,
        "sharded_vs_single": "parity",
        "pallas_vs_xla": ("tie-break flips, best/regret pinned"
                          if flips else "parity"),
        "comparison_path": "engine.replay.compare_records",
    }


def scheduler_dryrun(n_devices: int) -> dict:
    """The scheduler config: multi-family suite over the virtual mesh.

    Serial ``run_batched`` is the reference; the scheduled run must match
    it bitwise (same executables, same keys — placement is a pure copy).
    Returns the evidence record for the MULTICHIP artifact."""
    import time

    import numpy as np

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner

    fam_a = [make_synthetic_task(seed=i, H=4, N=48, C=3, name=f"alpha_{i}")
             for i in range(3)]
    fam_b = [make_synthetic_task(seed=10 + i, H=3, N=32, C=4,
                                 name=f"beta_{i}") for i in range(2)]
    groups = [fam_a, fam_b]
    methods = ["iid", "uncertainty", "model_picker"]
    profile = {"per_family_warm_s": {"alpha": 3.0, "beta": 1.0}}

    serial = SuiteRunner(iters=4, seeds=3)
    t0 = time.perf_counter()
    r_ser = serial.run_batched(groups, methods, progress=lambda s: None)
    wall_serial = time.perf_counter() - t0

    sched = SuiteRunner(iters=4, seeds=3)
    t0 = time.perf_counter()
    r_sch = sched.run_batched(groups, methods, progress=lambda s: None,
                              devices=n_devices, cost_profile=profile)
    wall_sched = time.perf_counter() - t0

    assert set(r_ser) == set(r_sch)
    for key in r_ser:
        for a, b in zip(r_ser[key], r_sch[key]):
            a, b = np.asarray(a), np.asarray(b)
            assert a.tobytes() == b.tobytes(), (
                f"scheduled result diverges bitwise at {key}")
    stats = sched.last_stats
    print(f"dryrun_multichip[scheduler] OK: {len(r_sch)} pairs over "
          f"{stats['n_devices']} devices, bitwise parity vs serial PASSED "
          f"(serial {wall_serial:.2f}s, scheduled {wall_sched:.2f}s, "
          f"occupancy {stats['occupancy']})")
    return {
        "config": "scheduler",
        "pairs": len(r_sch),
        "n_devices": stats["n_devices"],
        "schedule": stats["schedule"],
        "bitwise_parity_vs_serial": True,
        "wall_serial_s": round(wall_serial, 3),
        "wall_scheduled_s": round(wall_sched, 3),
        "compute_s": round(stats["compute_s"], 3),
        "compute_device_s": round(stats["compute_device_s"], 3),
        "occupancy": stats["occupancy"],
        "est_device_load": stats["est_device_load"],
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("n_devices", nargs="?", type=int, default=8)
    p.add_argument("--out", default=None, metavar="MULTICHIP.json",
                   help="write the evidence record to this JSON file")
    p.add_argument("--skip-shard-map", action="store_true",
                   help="run only the scheduler config (the shard_map "
                        "configs re-run the full sharded experiments)")
    args = p.parse_args(argv)
    _ensure_virtual_devices(args.n_devices)

    line = {"n_devices": args.n_devices, "ok": True, "configs": []}
    if not args.skip_shard_map:
        # same two configs __graft_entry__.dryrun_multichip runs, but every
        # comparison goes through the replay verifier (see module docstring)
        line["configs"].append(shard_map_dryrun(
            args.n_devices, H_per_model=4, N_per_data=16, C=4, iters=8,
            num_points=64, label="toy"))
        line["configs"].append(shard_map_dryrun(
            args.n_devices, H=30, N=2048, C=10, iters=16,
            num_points=128, eig_chunk=512, label="realistic"))
    line["configs"].append(scheduler_dryrun(args.n_devices))
    print(json.dumps(line))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(line, f, indent=2)


if __name__ == "__main__":
    main()
