"""Observability bench -> OBS_FLEET_CPU_*.json (the ISSUE 19 evidence).

Four passes, one artifact, every claim mechanical:

  1. **Fleet tracing under chaos** — a 3-replica fleet behind the session
     router, driven through ``serve_loadgen``'s free-run loop with
     ``--fleet-chaos`` transport faults and ``--trace-sample``d label
     requests. The claim: 0 errors AND every sampled trace fetched back
     COMPLETE through the router's stitcher (route -> dispatch -> serve
     -> tick -> step, cross-process), AND every /metrics latency
     exemplar joins to retained spans. The full run repeats the pass
     with a mid-load ``--rolling-restart-at`` (completeness held by the
     router's span adoption; exemplars not claimed there — latency
     rings rebuild with the restarted apps).
  2. **Migration-spanning trace** — one session, one trace context,
     labels before AND after a forced ``migrate_session``: the stitched
     trace must show BOTH replicas' process lanes (plus the router's) —
     the "one causal trace per label decision survives failover" proof.
  3. **Non-perturbation** — the same deterministic single-worker workload
     run with tracing on (every label traced) and with ``--no-trace``:
     the recorder's session-stream decision rows must be IDENTICAL once
     the additive ``trace_id`` field is dropped — tracing reads the
     serving path, it never steers it. The traced pass must also show
     ``trace_id`` on every row (the join the recorder claim is made of).
     Overhead: min-of-N wall times, traced vs untraced, bounded ≤ 5%.
  4. **SLO fire/clear** — a router with second-scale burn windows over a
     replica with an injected ``slow_step`` tail: the ``label_p99``
     objective must FIRE (both windows burning) while the tail lasts and
     RESOLVE (fast-window hysteresis) once fast labels wash the ring,
     with both alert transitions persisted to the tracking store.

Run::

    JAX_PLATFORMS=cpu python scripts/bench_obs.py --out OBS_FLEET_CPU_r19.json
    python scripts/bench_obs.py --quick   # 2-replica smoke (not committed)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _loadgen_args(extra: list) -> object:
    from serve_loadgen import parse_args as lg_parse

    return lg_parse(["--synthetic", "4,64,4"] + extra)


# ---------------------------------------------------------------------------
# pass 1: fleet tracing under chaos (+ rolling restart)
# ---------------------------------------------------------------------------

def _one_fleet_run(extra: list) -> dict:
    from serve_loadgen import run_loadgen

    report = run_loadgen(_loadgen_args(extra))
    t = report.get("tracing") or {}
    return {
        "n_errors": report["n_errors"],
        "errors": report["errors"][:10],
        "n_retries": report["n_retries"],
        "requests_per_s": report["requests_per_s"],
        "rolling_restart": (report["fleet"] or {}).get("rolling_restart"),
        "chaos": ((report["fleet"] or {}).get("chaos") or {}).get("spec"),
        "dropped_sessions": (report["fleet"] or {}).get("dropped_sessions"),
        "tracing": {k: t.get(k) for k in (
            "sample_rate", "sampled", "complete", "fetch_errors",
            "completeness", "required_spans", "exemplars",
            "exemplars_joinable", "exemplar_joinability")},
        "sample_traces": (t.get("traces") or [])[:5],
    }


def fleet_pass(quick: bool) -> dict:
    n = 2 if quick else 3
    base = ["--fleet", str(n), "--workers", "4",
            "--sessions", "8" if quick else "24",
            "--labels", "4" if quick else "6",
            "--retries", "10", "--trace-sample", "0.25"]
    # sub-pass A (chaos, steady fleet): every sampled trace complete AND
    # the /metrics latency exemplars join back to retained spans
    chaos = _one_fleet_run(base + [
        "--fleet-chaos",
        "net_delay:every=11,ms=3" if quick else
        "partition:edge=r0,after=30,times=10;net_delay:every=11,ms=3"])
    out = {"replicas": n, "chaos_pass": chaos,
           "n_errors": chaos["n_errors"]}
    if quick:
        return out
    # sub-pass B (chaos + rolling restart): every replica is torn down
    # and rebuilt mid-load — completeness holds because restart_replica
    # hands each dying app's retained trace spans to the router's
    # collector. Exemplars are NOT claimed here: the latency rings are
    # rebuilt with the apps, so post-restart outliers are scarce by
    # construction (the exemplar claim lives in sub-pass A).
    restart = _one_fleet_run(base + [
        "--rolling-restart-at", "0.5",
        "--fleet-chaos", "net_delay:every=11,ms=3"])
    out["restart_pass"] = restart
    out["n_errors"] += restart["n_errors"]
    return out


# ---------------------------------------------------------------------------
# pass 2: one trace across a forced mid-session migration
# ---------------------------------------------------------------------------

def migration_trace_pass() -> dict:
    from coda_tpu.serve.fleet import build_fleet
    from coda_tpu.telemetry.trace import mint

    args = _loadgen_args(["--workers", "2", "--sessions", "2"])
    fleet = build_fleet(args, 2)
    fleet.start(warm=True)
    try:
        router = fleet.router
        out = router.open_session(seed=0)
        sid = out["session"]
        # placement is rendezvous-based until a migration pins it
        src = router.owner_of(sid)
        # ONE root context for the whole session's decision trace: every
        # label below parents into the same trace_id
        ctx = mint()
        out = router.label(sid, int(out["idx"]) % 4, trace_ctx=ctx)
        dst = next(r for r in fleet.replica_ids if r != src)
        router.migrate_session(sid, src, dst)
        out = router.label(sid, int(out["idx"]) % 4, trace_ctx=ctx)
        stitched = router.collect_trace(ctx.trace_id)
        names = [e["name"] for e in stitched["traceEvents"]
                 if e.get("ph") == "X"]
        procs = stitched["processes"]
        return {
            "trace_id": ctx.trace_id,
            "src": src, "dst": dst,
            "processes": procs,
            "n_spans": len(names),
            "replica_lanes": sorted(p for p in procs if p != "router"),
            # the claim: the router's lane plus BOTH replicas' lanes hold
            # spans of this one trace — the migration happened INSIDE it
            "spans_both_replicas": (src in procs and dst in procs),
            "router_lane": "router" in procs,
            "migration_verified":
                router.stats()["router"]["migration_verified"],
        }
    finally:
        fleet.drain()


# ---------------------------------------------------------------------------
# pass 3: non-perturbation (bitwise rows) + overhead
# ---------------------------------------------------------------------------

def _traced_workload(app, n_labels: int, traced: bool) -> tuple:
    """One deterministic single-stream session; returns (wall_s, sid)."""
    from coda_tpu.telemetry.trace import mint

    t0 = time.perf_counter()
    out = app.open_session(seed=0)
    sid = out["session"]
    for _ in range(n_labels):
        ctx = mint() if traced else None
        out = app.label(sid, int(out["idx"]) % 4, trace_ctx=ctx)
    app.close_session(sid)
    return time.perf_counter() - t0, sid


def _stream_rows(record_dir: str, sid: str) -> list:
    import glob
    import os

    rows = []
    for path in sorted(glob.glob(os.path.join(record_dir, "**", f"*{sid}*"),
                                 recursive=True)):
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                # only decision rows: meta/close markers carry wall-clock
                # provenance that legitimately differs between runs
                if "next_idx" in row:
                    rows.append(row)
    return rows


def bitwise_pass(n_labels: int = 24) -> dict:
    import os
    import tempfile

    from coda_tpu.serve.server import build_app

    runs = {}
    walls = {}
    with tempfile.TemporaryDirectory() as td:
        for mode, traced in (("traced", True), ("untraced", False)):
            rd = os.path.join(td, mode)
            args = _loadgen_args(["--workers", "1", "--sessions", "1"])
            args.record_dir = rd
            args.no_trace = not traced
            app = build_app(args)
            app.start(warm=True)
            try:
                wall, sid = _traced_workload(app, n_labels, traced)
            finally:
                app.drain()
            runs[mode] = _stream_rows(rd, sid)
            walls[mode] = wall
    traced_rows = runs["traced"]
    untraced_rows = runs["untraced"]
    rows_traced = all("trace_id" in r and r["trace_id"]
                      for r in traced_rows if r.get("do_update"))
    stripped = [{k: v for k, v in r.items() if k != "trace_id"}
                for r in traced_rows]
    identical = (json.dumps(stripped, sort_keys=True)
                 == json.dumps(untraced_rows, sort_keys=True))
    first_diff = None
    if not identical:
        for i, (a, b) in enumerate(zip(stripped, untraced_rows)):
            if a != b:
                first_diff = {"row": i, "traced": a, "untraced": b}
                break
        if first_diff is None:
            first_diff = {"row_counts": [len(stripped),
                                         len(untraced_rows)]}
    return {
        "labels": n_labels,
        "rows": [len(traced_rows), len(untraced_rows)],
        "rows_carry_trace_id": rows_traced,
        "identical": identical,
        "first_diff": first_diff,
        "wall_s": walls,
    }


def overhead_pass(n_labels: int = 200, reps: int = 4) -> dict:
    """min-of-``reps`` wall time of the identical serial workload, every
    label traced vs tracing disabled. Both apps stay alive and the reps
    ALTERNATE modes, so slow container drift hits both sides equally; min
    (not mean) because noise only ever ADDS time — the minima are the
    honest comparison."""
    from coda_tpu.serve.server import build_app

    apps = {}
    for mode, traced in (("untraced", False), ("traced", True)):
        args = _loadgen_args(["--workers", "1", "--sessions", "1"])
        args.no_trace = not traced
        apps[mode] = build_app(args)
        apps[mode].start(warm=True)
    walls: dict = {"traced": [], "untraced": []}
    try:
        for mode, traced in (("untraced", False), ("traced", True)):
            _traced_workload(apps[mode], 20, traced)  # page everything in
        for _ in range(reps):
            for mode, traced in (("untraced", False), ("traced", True)):
                wall, _sid = _traced_workload(apps[mode], n_labels, traced)
                walls[mode].append(wall)
    finally:
        for app in apps.values():
            app.drain()
    t, u = min(walls["traced"]), min(walls["untraced"])
    return {
        "labels": n_labels, "reps": reps,
        "traced_s": walls["traced"], "untraced_s": walls["untraced"],
        "traced_min_s": t, "untraced_min_s": u,
        "per_label_us": {"traced": t / n_labels * 1e6,
                         "untraced": u / n_labels * 1e6},
        # clamped at 0: a negative delta is container noise, not a
        # time-travelling tracer
        "overhead_frac": max(0.0, (t - u) / u),
    }


# ---------------------------------------------------------------------------
# pass 4: SLO fire + clear on an injected slow_step tail
# ---------------------------------------------------------------------------

def slo_pass() -> dict:
    import os
    import tempfile

    from coda_tpu.serve.router import InprocReplica, SessionRouter
    from coda_tpu.serve.server import build_app
    from coda_tpu.tracking.store import TrackingStore

    with tempfile.TemporaryDirectory() as td:
        db = os.path.join(td, "slo.sqlite")
        args = _loadgen_args(["--workers", "1", "--sessions", "1"])
        # the tail: the first 5 dispatches each sleep 400 ms — far past
        # the 250 ms label-p99 objective, gone once `times` is spent
        args.fault_spec = "slow_step:every=1,times=5,ms=400"
        app = build_app(args)
        app.start(warm=True)
        router = SessionRouter(
            slo_fast_s=2.0, slo_slow_s=6.0,
            slo_store=(lambda: TrackingStore(db)))
        router.add_replica("r0", InprocReplica("r0", app))
        router.start(poll_s=0.1)   # SLO sweep every 4th tick = 0.4 s
        fired_at = cleared_at = None
        try:
            out = router.open_session(seed=0)
            sid = out["session"]
            t0 = time.perf_counter()
            # phase 1: ride out the slow tail, then hold a slow-heavy
            # ring until the sweeper fires (p99 > bound -> bad=1 -> both
            # windows burn at 1/0.05 = 20x >= the fire threshold 8)
            for _ in range(12):
                out = router.label(sid, int(out["idx"]) % 4)
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline:
                snap = router.slo_snapshot()
                if snap["objectives"]["label_p99"]["firing"]:
                    fired_at = time.perf_counter() - t0
                    break
                time.sleep(0.1)
            fired_snap = router.slo_snapshot()
            # phase 2: wash the ring with fast labels until the 5 slow
            # samples sink below the p99 cut (5/600 < 1%), then wait out
            # the fast window's hysteresis for the resolve
            deadline = time.perf_counter() + 120
            while time.perf_counter() < deadline:
                for _ in range(50):
                    out = router.label(sid, int(out["idx"]) % 4)
                snap = router.slo_snapshot()
                st = snap["objectives"]["label_p99"]
                if not st["firing"] and st["cleared_total"] >= 1:
                    cleared_at = time.perf_counter() - t0
                    break
            final = router.slo_snapshot()
            router.close_session(sid)
        finally:
            router.drain()
            app.drain()
        # read the alerts BACK from the tracking store, on this thread's
        # own connection — the persistence half of the claim
        store = TrackingStore(db)
        persisted = {
            state: store.is_finished("serve_slo", f"alert-label_p99-{state}")
            for state in ("firing", "resolved")
        }
        store.close()
    st = final["objectives"]["label_p99"]
    return {
        "objective": "label_p99",
        "fault_spec": "slow_step:every=1,times=5,ms=400",
        "windows_s": final["windows_s"],
        "fired": st["fired_total"],
        "cleared": st["cleared_total"],
        "fired_at_s": fired_at,
        "cleared_at_s": cleared_at,
        "burn_fast_at_fire":
            fired_snap["objectives"]["label_p99"]["burn_fast"],
        "alerts": final["alerts"][-4:],
        "store_flushed": final["store"]["flushed"],
        "store_errors": final["store"]["errors"],
        "persisted": persisted,
        "persisted_both": all(persisted.values()),
    }


# ---------------------------------------------------------------------------

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="2-replica smoke pass (smaller workload; do not "
                        "commit the artifact)")
    p.add_argument("--out", default=None,
                   help="artifact path (default OBS_FLEET_CPU.json)")
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(None)
    from coda_tpu.telemetry.recorder import environment_fingerprint

    t0 = time.perf_counter()
    print("== pass 1/4: fleet tracing under chaos ==", flush=True)
    fleet = fleet_pass(args.quick)
    print(json.dumps(fleet["chaos_pass"]["tracing"]), flush=True)
    if "restart_pass" in fleet:
        print(json.dumps(fleet["restart_pass"]["tracing"]), flush=True)
    print("== pass 2/4: migration-spanning trace ==", flush=True)
    migration = migration_trace_pass()
    print(json.dumps({k: migration[k] for k in
                      ("processes", "spans_both_replicas")}), flush=True)
    print("== pass 3/4: non-perturbation + overhead ==", flush=True)
    bitwise = bitwise_pass()
    overhead = overhead_pass(n_labels=60 if args.quick else 200)
    print(json.dumps({"identical": bitwise["identical"],
                      "overhead_frac": overhead["overhead_frac"]}),
          flush=True)
    print("== pass 4/4: SLO fire/clear ==", flush=True)
    slo = slo_pass()
    print(json.dumps({k: slo[k] for k in
                      ("fired", "cleared", "persisted_both")}), flush=True)

    report = {
        "bench": "bench_obs",
        "quick": bool(args.quick),
        "fingerprint": environment_fingerprint(knobs={
            "bench": "bench_obs", "quick": bool(args.quick),
            "replicas": fleet["replicas"],
            "trace_sample": fleet["chaos_pass"]["tracing"]["sample_rate"],
            "task": "synthetic-4,64,4"}),
        "wall_s": time.perf_counter() - t0,
        "n_errors": fleet["n_errors"],
        "fleet": fleet,
        "migration_trace": migration,
        "bitwise": bitwise,
        "overhead": overhead,
        "slo": slo,
    }
    out = args.out or ("OBS_FLEET_CPU_quick.json" if args.quick
                       else "OBS_FLEET_CPU.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out} in {report['wall_s']:.1f}s")
    return report


if __name__ == "__main__":
    main()
