"""ImageNet-scale virtual-mesh EXECUTION check (BASELINE.json configs[4]).

The blueprint's largest config — M=500 x N=50k x C=1000 fp32 ~ 100 GB —
cannot materialize on one host, so its coverage so far is (a) resolver
pinning at the true shapes and (b) AOT memory analysis of the sharded
program (tests/test_sharding.py). This script closes the remaining gap:
it EXECUTES the factored and rowscan tiers at the real C=1000 x H=500
pool shape (N scaled to fit a host) on an 8-virtual-device CPU mesh,
records XLA's compiled memory analysis next to the analytic (C, H, G)
table budget the auto resolver uses, and asserts the run completes with
finite regrets. One JSON artifact (IMAGENET_VIRTUAL_r05.json).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/imagenet_virtual.py --out IMAGENET_VIRTUAL_r05.json

The tiers' temp scaling is the point: factored materializes four
(C, H, G) fp32 Beta tables (2 GiB at this pool — within budget), rowscan
visits one class row at a time (O(H·G) tables) and must show an
order-of-magnitude smaller temp footprint at the same math.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

# must precede any jax import (virtual devices are fixed at backend init)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np


def run_tier(eig_mode: str, H: int, N: int, C: int, iters: int,
             chunk: int) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import make_batched_experiment_fn
    from coda_tpu.parallel import make_mesh, preds_sharding
    from coda_tpu.parallel.mesh import DATA_AXIS
    from coda_tpu.selectors import CODAHyperparams, make_coda

    mesh = make_mesh(data=8)
    task = make_synthetic_task(seed=5, H=H, N=N, C=C,
                               name=f"imagenet_virtual_{eig_mode}")
    preds = jax.device_put(task.preds, preds_sharding(mesh))
    labels = jax.device_put(task.labels,
                            NamedSharding(mesh, P(DATA_AXIS)))

    hp = CODAHyperparams(eig_mode=eig_mode, eig_chunk=chunk)
    fn = jax.jit(make_batched_experiment_fn(
        lambda p: make_coda(p, hp), iters=iters))
    keys = jnp.stack([jax.random.PRNGKey(0)])

    print(f"[{eig_mode}] lowering+compiling...", flush=True)
    t0 = time.perf_counter()
    lowered = fn.lower(preds, labels, keys)
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    ma = compiled.memory_analysis()

    print(f"[{eig_mode}] compiled in {compile_s:.1f}s; executing...",
          flush=True)
    t0 = time.perf_counter()
    res = compiled(preds, labels, keys)
    regret = np.asarray(res.regret)
    run_s = time.perf_counter() - t0
    print(f"[{eig_mode}] ran in {run_s:.1f}s", flush=True)

    G = hp.num_points
    return {
        "eig_mode": eig_mode,
        "shape": {"H": H, "N": N, "C": C, "iters": iters, "chunk": chunk},
        "mesh": "data=8 (virtual CPU)",
        "analytic_table_bytes": 16 * C * H * G,  # 4 fp32 (C, H, G) tables
        "xla_temp_bytes_per_device": ma.temp_size_in_bytes if ma else None,
        "xla_argument_bytes_per_device": (
            ma.argument_size_in_bytes if ma else None),
        "compile_s": round(compile_s, 2),
        "run_s": round(run_s, 2),
        "regret_final": float(regret[0, -1]),
        "finite": bool(np.isfinite(regret).all()),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--small", action="store_true",
                    help="smoke-test shape (CI), not the artifact config")
    ap.add_argument("--iters", type=int, default=1)
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform("cpu")  # the site hook force-registers the axon TPU
    import jax

    if args.small:
        H, N, C, chunk = 20, 256, 40, 64
    else:
        # real pool dims (C=1000, H=500); N scaled ~200x to keep the
        # virtual-mesh EXECUTION tractable (8 virtual devices share one
        # host's cores and serialize per-chunk collectives — NOTES_r04
        # documents the pathology; an N=512 x 2-round factored run was
        # still grinding after 15 min. The tier memory contract this
        # artifact verifies — factored's (C, H, G) tables vs rowscan's
        # O(H·G) — is N-independent)
        H, N, C, chunk = 500, 256, 1000, 64

    out = {
        "config": "BASELINE.json configs[4]: ImageNet-1k scale pool "
                  "(C=1000, H=500; N scaled to fit one host)",
        "devices": len(jax.devices()),
        "tiers": [],
    }
    for m in ("factored", "rowscan"):
        out["tiers"].append(run_tier(m, H, N, C, args.iters, chunk))
        if args.out:  # incremental: a killed run keeps finished tiers
            with open(args.out + ".partial", "w") as f:
                json.dump(out, f, indent=2)
    fac, row = out["tiers"]
    # the tier contract: same math, order-of-magnitude different temps
    out["rowscan_temp_fraction_of_factored"] = round(
        row["xla_temp_bytes_per_device"] /
        max(1, fac["xla_temp_bytes_per_device"]), 4)
    out["ok"] = (fac["finite"] and row["finite"]
                 and row["xla_temp_bytes_per_device"]
                 < fac["xla_temp_bytes_per_device"])
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
        partial = args.out + ".partial"
        if os.path.exists(partial):
            os.remove(partial)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
