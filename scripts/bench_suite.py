"""North-star benchmark: a 26-task reference-shaped sweep in one process.

Generates 26 synthetic tasks shaped like the reference benchmark's families
(reference ``paper/tab1.py:82-90``: 12 DomainNet126 + 4 WILDS + 3 MSV +
7 GLUE; per-family sizes scaled to stream through one chip's HBM), then runs
every method x 5 seeds x 100 iters through the in-process suite runner and
prints ONE JSON line with the total wall-clock.

BASELINE.md's target: the full sweep under 60 s on a v5e-8. Compiles are
cached persistently (--compile-cache), so steady-state reruns measure pure
execution.

    python scripts/bench_suite.py [--small] [--methods iid,coda]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import zlib

sys.path.insert(0, ".")

# (family, count, H, N, C) — shapes follow the reference families, N scaled
FAMILIES = [
    ("domainnet", 12, 30, 20000, 126),
    ("wilds", 4, 20, 20000, 62),
    ("msv", 3, 80, 10000, 10),
    ("glue", 7, 30, 5000, 3),
]
SMALL_FAMILIES = [
    ("domainnet", 3, 8, 2000, 26),
    ("glue", 3, 8, 1000, 3),
]


BASELINE_ARTIFACT = "BENCH_SUITE_CPU_FULL_r04.json"
_DEFAULT_METHODS = "iid,uncertainty,coda,activetesting,vma,model_picker"


def _median_profile(reps: list) -> dict:
    """Per-key median across warm-rep profile dicts (a key missing from a
    rep counts as 0.0 — a skipped/merged dispatch, not missing data)."""
    import statistics

    keys = sorted({k for r in reps for k in r})
    return {k: round(statistics.median([r.get(k, 0.0) for r in reps]), 3)
            for k in keys}


def _profile_from_artifact(name: str):
    """Per-family/per-method WARM cost profile out of a committed bench
    artifact's pair records (the scheduler's LPT weights). Pre-profile
    artifacts (no pairs) or a missing file yield None — the scheduler
    then falls back to uniform costs."""
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        name)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        art = json.load(f)
    pairs = art.get("pairs")
    if not pairs:
        return None
    from coda_tpu.engine.suite import _warm_profile

    warm_m, warm_f = _warm_profile(pairs)
    if not warm_f:
        return None
    return {"per_family_warm_s": warm_f, "per_method_warm_s": warm_m}


def _vs_single_device(line: dict, runner, groups, methods, margs, caps,
                      sched_kw, reps: int = 3) -> None:
    """Measure the scheduled-vs-serial speedup off the hot jit cache.

    Median-of-``reps`` warm passes PER SIDE (single passes on a noisy
    shared host swing ±10%, enough to flip the ratio's direction): a
    serial warm-up first (pays any device-0 executables the scheduled
    run never compiled there), then serial and scheduled timed passes
    interleaved so slow drift hits both sides alike. Ratio > 1 means
    placement beat serial dispatch; the field stays honest on hosts
    where virtual devices share one core (ratio ~1)."""
    import statistics

    runner.run_batched(groups, methods, method_args=margs, batch_caps=caps,
                       progress=lambda s: None)  # serial warm-up
    serial, sched = [], []
    for _ in range(reps):
        runner.run_batched(groups, methods, method_args=margs,
                           batch_caps=caps, progress=lambda s: None)
        serial.append(runner.last_stats["compute_s"])
        runner.run_batched(groups, methods, method_args=margs,
                           batch_caps=caps, progress=lambda s: None,
                           **sched_kw)
        sched.append(runner.last_stats["compute_s"])
    serial_s, sched_s = statistics.median(serial), statistics.median(sched)
    if sched_s:
        line["vs_single_device"] = round(serial_s / sched_s, 3)
        line["vs_single_device_basis"] = (
            f"median-of-{reps} serial warm compute {round(serial_s, 2)}s / "
            f"scheduled warm compute {round(sched_s, 2)}s (same process, "
            f"hot jit cache)")


def _baseline_ratio(line: dict, args) -> None:
    """Populate ``vs_baseline`` from the committed CPU full-suite capture.

    The ratio is only meaningful when this run measured the SAME sweep the
    baseline did — the full FAMILIES config, all six methods, 5 seeds x
    100 iters — so anything else (``--small``, method subsets) keeps the
    0.0 = unknown sentinel. Steady-state compute is compared when this
    run captured one (``--warm-reps``); otherwise the cold compute value
    is used and labeled as such (conservative: cold includes compiles,
    the baseline number is steady-state).
    """
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        BASELINE_ARTIFACT)
    if (args.small or args.methods != _DEFAULT_METHODS or args.seeds != 5
            or args.iters != 100 or not os.path.exists(path)):
        return
    with open(path) as f:
        base = json.load(f)
    base_s = base.get("steady_state_compute_s") or base.get("value")
    ours = line.get("steady_state_compute_s")
    basis = "steady_state_compute_s"
    if not ours:
        ours = line.get("value")
        basis = "value (cold, incl. compiles)"
    if not (base_s and ours):
        return
    line["vs_baseline"] = round(float(base_s) / float(ours), 2)
    line["vs_baseline_source"] = (
        f"{BASELINE_ARTIFACT} steady_state_compute_s={base_s} (CPU, same "
        f"26-task FAMILIES sweep) / this run's {basis}")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--small", action="store_true")
    p.add_argument("--methods", default=_DEFAULT_METHODS)
    p.add_argument("--seeds", type=int, default=5)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--eig-chunk", type=int, default=2048)
    p.add_argument("--eig-backend", default=None,
                   choices=["auto", "jnp", "pallas"],
                   help="force CODA's scoring backend (default: the auto "
                        "resolver — jnp for vmapped batches). 'pallas' "
                        "engages the BATCHED kernels where the "
                        "padded-operand budget allows (msv/glue "
                        "families); over-budget shapes fall back to jnp "
                        "via the custom_vmap guard")
    p.add_argument("--eig-entropy", default=None,
                   choices=["exact", "approx"],
                   help="CODA's entropy lowering for the EIG scoring "
                        "pass: approx = the polynomial log2 fast path "
                        "(opt-in numerics, |Dscore| <= 1e-4) — the knob "
                        "for attacking the bf16 transcendental tail")
    p.add_argument("--compile-cache", default=".jax_cache")
    p.add_argument("--platform", default=None)
    p.add_argument("--mesh", default=None, metavar="AXIS=K,...",
                   help="shard each task over a device mesh, e.g. data=8 "
                        "(N over the data axis) — the v5e-8 target config")
    p.add_argument("--warm-rerun", action="store_true",
                   help="run the sweep again off the hot compile cache and "
                        "report the steady-state wall-clock (BASELINE.md's "
                        "<60 s v5e-8 target is steady-state)")
    p.add_argument("--warm-reps", type=int, default=None,
                   help="number of warm reruns; the steady-state number is "
                        "their MEDIAN (median-of-k discipline for numbers "
                        "captured through a flaky device tunnel)")
    p.add_argument("--out", default=None, metavar="BENCH_SUITE.json",
                   help="also write the full per-method/per-pair breakdown "
                        "to this JSON file")
    p.add_argument("--task-batch", action="store_true",
                   help="run same-shape (same-family) tasks as ONE vmapped "
                        "program per method (SuiteRunner.run_batched): two "
                        "dispatches per family-method instead of one-or-two "
                        "per task-method — the lever for hosts where "
                        "per-program dispatch latency dominates (e.g. a "
                        "tunneled device). Incompatible with --mesh.")
    p.add_argument("--batch-cap", type=int, default=0,
                   help="with --task-batch: max tasks per batched group "
                        "(0 = whole family) — the HBM valve for big "
                        "families")
    p.add_argument("--suite-devices", default=None, metavar="auto|N",
                   help="schedule independent family-method dispatches "
                        "across this many local devices ('auto' = all) — "
                        "the task-parallel scheduler; implies "
                        "--task-batch. Default: serial dispatch.")
    p.add_argument("--schedule", default="lpt", choices=["lpt", "fifo"],
                   help="with --suite-devices: dispatch order (lpt = "
                        "longest-processing-time-first off the committed "
                        "per-family warm profile, fifo = family order)")
    p.add_argument("--no-vs-single-device", action="store_true",
                   help="with --suite-devices: skip the extra serial "
                        "passes that measure the vs_single_device "
                        "speedup (3 warm passes on big sweeps)")
    p.add_argument("--telemetry-dir", default=None,
                   help="write trace.json (Perfetto per-device dispatch "
                        "lanes) + telemetry.json (recompile counts, HBM "
                        "watermarks) + metrics.prom there — the evidence "
                        "artifacts the occupancy numbers cite")
    args = p.parse_args(argv)
    if args.suite_devices is not None:
        args.task_batch = True  # the scheduler runs through run_batched
    if args.task_batch and args.mesh:
        p.error("--task-batch is per-device (the task axis would need "
                "its own mesh dimension); drop one of the flags")
    if args.warm_reps is not None and args.warm_reps < 1:
        p.error("--warm-reps must be >= 1")

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax

    if args.compile_cache:
        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner

    sharding = None
    if args.mesh:
        from coda_tpu.parallel import mesh_from_spec, preds_sharding

        sharding = preds_sharding(mesh_from_spec(args.mesh))

    fams = SMALL_FAMILIES if args.small else FAMILIES
    loaders = []
    groups = []  # per family, for --task-batch
    for fam, count, H, N, C in fams:
        fam_loaders = []
        for i in range(count):
            fam_loaders.append(
                # stable across processes (hash() is PYTHONHASHSEED-salted)
                lambda fam=fam, i=i, H=H, N=N, C=C: make_synthetic_task(
                    seed=zlib.crc32(f"{fam}_{i}".encode()) % (2**31),
                    H=H, N=N, C=C, name=f"{fam}_{i}", sharding=sharding,
                    unsharded_fallback=True,
                )
            )
        loaders += fam_loaders
        cap = args.batch_cap or len(fam_loaders)
        groups += [fam_loaders[j:j + cap]
                   for j in range(0, len(fam_loaders), cap)]

    telemetry = None
    if args.telemetry_dir:
        from coda_tpu.telemetry import Telemetry

        telemetry = Telemetry(out_dir=args.telemetry_dir)

    methods = args.methods.split(",")
    runner = SuiteRunner(iters=args.iters, seeds=args.seeds,
                         telemetry=telemetry)

    def coda_cap(H, N, C):
        # CODA sub-batches within a family so the (seeds-1)-wide rest batch
        # keeps every replica's (C, N, H) incremental cache inside the auto
        # eig_mode budget — past it the tier falls to the stateless
        # factored kernel, whose per-round transcendental tables cost far
        # more than the extra dispatches (the large DomainNet family is the
        # one this splits: cap 3 at the FULL shape)
        from coda_tpu.selectors.coda import _INCR_CACHE_MAX_BYTES

        per_task = max(1, args.seeds - 1) * 4 * H * N * C
        return max(1, int(_INCR_CACHE_MAX_BYTES // per_task))

    margs = {"eig_chunk": args.eig_chunk}
    if args.eig_backend:
        margs["eig_backend"] = args.eig_backend
    if args.eig_entropy:
        margs["eig_entropy"] = args.eig_entropy

    # LPT costs for the scheduler: the committed full-suite capture's pair
    # records, reduced to per-family/per-method warm profiles (uniform
    # fallback inside the scheduler when the artifact is absent)
    cost_profile = _profile_from_artifact(BASELINE_ARTIFACT) \
        if args.suite_devices is not None else None
    sched_kw = {}
    if args.suite_devices is not None:
        sched_kw = dict(devices=args.suite_devices, schedule=args.schedule,
                        cost_profile=cost_profile)

    t0 = time.perf_counter()
    if args.task_batch:
        results = runner.run_batched(
            groups, methods, method_args=margs,
            batch_caps={"coda": coda_cap}, **sched_kw)
    else:
        results = runner.run(loaders, methods, method_args=margs)
    wall = time.perf_counter() - t0
    n_pairs = len(results)
    stats = getattr(runner, "last_stats", {})

    # write the telemetry evidence NOW, from the primary run alone: the
    # warm reps and _vs_single_device passes below reuse the same runner,
    # and their extra dispatch spans (serial passes land on device 0's
    # lane) would break the trace's lanes == occupancy invariant that
    # makes the artifact citable. Detaching also keeps those timing
    # passes free of sampling overhead.
    tele_paths = {}
    if telemetry is not None:
        tele_paths = telemetry.write(extra={"bench": {
            "compute_s": round(stats.get("compute_s", wall), 2),
            "n_devices": stats.get("n_devices"),
            "occupancy": stats.get("occupancy")}})
        runner.telemetry = None

    # per-method totals + the compile/execute split: the first run of each
    # (method, shape) includes its jit compile, later same-shape tasks are
    # pure execution — "warm" extrapolates a steady-state rerun
    per_method: dict = {}
    warm_s = 0.0
    for p_ in stats.get("pairs", []):
        m = per_method.setdefault(
            p_["method"], {"seconds": 0.0, "pairs": 0, "cold_pairs": 0})
        m["seconds"] += p_["seconds"]
        m["pairs"] += 1
        if p_["cold"]:
            m["cold_pairs"] += 1
        else:
            warm_s += p_["seconds"]
    for m in per_method.values():
        m["seconds"] = round(m["seconds"], 3)

    line = {
        "metric": f"suite-26task-wall ({n_pairs} task-method pairs, "
                  f"{args.seeds} seeds, {args.iters} iters)",
        "value": round(stats.get("compute_s", wall), 2),
        "unit": "seconds (compute; total incl. synthetic datagen in "
                "total_wall)",
        "total_wall": round(wall, 2),
        "load_s": round(stats.get("load_s", 0.0), 2),
        "warm_pairs_s": round(warm_s, 2),
        "per_method_s": {k: v["seconds"] for k, v in per_method.items()},
        # the WARM (compile-free) breakdown of the cold pass — replaced by
        # the steady-state medians below when warm reps run
        "per_method_warm_s": stats.get("per_method_warm_s", {}),
        "per_family_warm_s": stats.get("per_family_warm_s", {}),
        "task_batched": bool(args.task_batch),
        "eig_entropy": args.eig_entropy or "exact",
        "vs_baseline": 0.0,
    }
    # provenance + cost attribution: the environment fingerprint makes the
    # capture cross-round comparable (scripts/check_perf.py keys regression
    # comparisons on it), and the cost section is the suite's per-
    # executable XLA attribution (FLOPs/bytes/peak-HBM/roofline per
    # compiled program, harvested at compile by the runner's CostTracked
    # wrappers)
    from coda_tpu.telemetry.costs import COSTS
    from coda_tpu.telemetry.recorder import environment_fingerprint

    line["fingerprint"] = environment_fingerprint(knobs={
        "methods": args.methods, "seeds": args.seeds, "iters": args.iters,
        "eig_chunk": args.eig_chunk, "eig_backend": args.eig_backend,
        "eig_entropy": args.eig_entropy, "small": args.small,
        "task_batch": bool(args.task_batch),
        "suite_devices": args.suite_devices, "schedule": args.schedule,
        "mesh": args.mesh})
    line["cost"] = COSTS.snapshot(site="suite")
    if args.suite_devices is not None:
        # wall vs summed device-seconds diverge exactly when placement
        # achieves concurrency; both are recorded so speedup math stays
        # honest (the satellite of the t_compute double-count fix)
        line["compute_device_s"] = round(
            stats.get("compute_device_s", 0.0), 2)
        line["n_devices"] = stats.get("n_devices", 1)
        line["schedule"] = stats.get("schedule")
        line["occupancy"] = stats.get("occupancy", {})
        line["vs_single_device"] = 0.0  # 0.0 = not measured

    if args.warm_rerun or args.warm_reps is not None:
        # warm passes off the hot in-process jit cache: pairs are pure
        # execution, but the lazy loaders REGENERATE each synthetic tensor,
        # so the wall includes datagen. steady_state_compute_s excludes it
        # and is the number comparable to the cold "value" (also compute-
        # only) and to BASELINE.md's <60 s steady-state target.
        import statistics

        computes, walls = [], []
        warm_method_reps: list = []
        warm_family_reps: list = []
        for _ in range(max(1, args.warm_reps or 1)):
            t0 = time.perf_counter()
            if args.task_batch:
                runner.run_batched(
                    groups, methods, method_args=margs,
                    batch_caps={"coda": coda_cap}, **sched_kw)
            else:
                runner.run(loaders, methods, method_args=margs)
            walls.append(round(time.perf_counter() - t0, 2))
            computes.append(round(runner.last_stats.get("compute_s", 0.0), 2))
            warm_method_reps.append(
                runner.last_stats.get("per_method_warm_s", {}))
            warm_family_reps.append(
                runner.last_stats.get("per_family_warm_s", {}))
        line["steady_state_compute_s"] = statistics.median(computes)
        line["steady_state_wall_incl_datagen"] = statistics.median(walls)
        line["steady_state_reps"] = len(computes)
        line["steady_state_compute_s_all"] = computes
        line["steady_state_wall_all"] = walls
        # every pair of a warm rep is compile-free, so the per-rep warm
        # profiles ARE steady-state; median-of-reps per key (the same
        # flaky-tunnel discipline as the headline number)
        line["per_method_warm_s"] = _median_profile(warm_method_reps)
        line["per_family_warm_s"] = _median_profile(warm_family_reps)
    if args.suite_devices is not None and not args.no_vs_single_device:
        _vs_single_device(line, runner, groups, methods, margs,
                          {"coda": coda_cap}, sched_kw)
    _baseline_ratio(line, args)
    if tele_paths:
        line["telemetry"] = tele_paths.get("telemetry")
    print(json.dumps(line))
    if args.out:
        import platform as _pl

        import jax as _jax

        detail = dict(line)
        detail["devices"] = [str(d) for d in _jax.devices()]
        detail["hostname"] = _pl.node()
        detail["per_method"] = per_method
        detail["pairs"] = stats.get("pairs", [])
        if args.suite_devices is not None:
            detail["device_timeline"] = stats.get("device_timeline", {})
            detail["est_device_load"] = stats.get("est_device_load", {})
        with open(args.out, "w") as f:
            json.dump(detail, f, indent=2)


if __name__ == "__main__":
    main()
