"""Aggregate child-run metrics into their parent runs.

For every experiment and each *parent* run (a run without the
``mlflow.parentRunId`` tag), compute the step-wise mean of the chosen
metrics across all of its child runs and write those means back onto the
parent run as ``mean_<metric>`` (capability parity with reference
``scripts/aggregate_results.py:30-94``, which does the same through the
MLflow client; here it is three SQL statements against the same schema).

Usage:
    python scripts/aggregate_results.py                    # regret metrics
    python scripts/aggregate_results.py m1 m2 --db x.sqlite
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from coda_tpu.tracking import TrackingStore  # noqa: E402
from coda_tpu.tracking.store import Run  # noqa: E402

DEFAULT_METRICS = ["regret", "cumulative regret"]


def aggregate_metrics(store: TrackingStore, metric_keys=None, quiet=False):
    """Step-wise mean of each metric over child runs, logged to parents."""
    metric_keys = metric_keys or DEFAULT_METRICS
    parents = store.query(
        """SELECT r.run_uuid, e.name FROM runs r
           JOIN experiments e ON r.experiment_id = e.experiment_id
           WHERE r.lifecycle_stage='active' AND r.run_uuid NOT IN
             (SELECT run_uuid FROM tags WHERE key='mlflow.parentRunId')"""
    )
    n_written = 0
    for parent_uuid, exp_name in parents:
        children = store.child_runs(parent_uuid)
        if not children:
            continue
        placeholders = ",".join("?" * len(children))
        for metric in metric_keys:
            rows = store.query(
                f"""SELECT step, AVG(value) FROM metrics
                    WHERE run_uuid IN ({placeholders}) AND key=? AND is_nan=0
                    GROUP BY step ORDER BY step""",
                (*children, metric),
            )
            if not rows:
                continue
            r = Run(store, parent_uuid)
            # write each mean at its actual step (the GROUP BY rows may have
            # gaps where every child logged NaN)
            r.log_metric_points(f"mean_{metric}", rows)
            n_written += len(rows)
            if not quiet:
                for step, v in rows:
                    print(f"[Exp {exp_name}] parent {parent_uuid[:8]} | "
                          f"step {step} mean_{metric} = {v:.6f}")
        store._conn.commit()
    return n_written


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("metrics", nargs="*", default=None)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)
    store = TrackingStore(args.db)
    n = aggregate_metrics(store, args.metrics or None, quiet=args.quiet)
    print(f"Wrote {n} aggregated metric points.")


if __name__ == "__main__":
    main()
