"""Export the native tracking store into a real MLflow tracking backend.

The native store (``coda_tpu/tracking/store.py``) implements the schema
subset the reference's analysis SQL needs, but not MLflow's alembic
version bookkeeping — ``mlflow ui`` refuses unversioned DBs. This script
replays every experiment/run/param/tag/metric through the *genuine* MLflow
client API into a fresh MLflow-owned backend, so the resulting store is
exactly what ``mlflow ui`` expects (the reference workflow, reference
``README.md:45``), with the experiment -> parent-run -> seed-child layout
preserved via the same ``mlflow.parentRunId`` / ``mlflow.runName`` tags.

    python scripts/export_mlflow.py --db coda.sqlite \
        --dest sqlite:///mlflow.sqlite
    mlflow ui --backend-store-uri sqlite:///mlflow.sqlite

Requires mlflow (not in TPU images — run wherever the UI runs).
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, ".")

# tags that MlflowClient.create_run manages itself or that we set explicitly
_CONTROLLED_TAGS = {"mlflow.runName", "mlflow.parentRunId"}


def export(db_path: str, dest_uri: str, progress=print) -> dict:
    """Replay ``db_path`` into the MLflow backend at ``dest_uri``.

    Returns {experiments, runs, metrics} counts. Parent runs are created
    before their children so ``mlflow.parentRunId`` tags resolve.
    """
    from mlflow.entities import Metric, Param, RunTag
    from mlflow.tracking import MlflowClient

    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(db_path)
    client = MlflowClient(tracking_uri=dest_uri)
    counts = {"experiments": 0, "runs": 0, "metrics": 0}

    experiments = store.query(
        "SELECT experiment_id, name FROM experiments"
        " WHERE lifecycle_stage='active' ORDER BY experiment_id")
    for exp_id, exp_name in experiments:
        existing = client.get_experiment_by_name(exp_name)
        dest_exp = (existing.experiment_id if existing
                    else client.create_experiment(exp_name))
        counts["experiments"] += 1

        runs = store.query(
            """SELECT r.run_uuid, r.status, r.start_time, r.end_time
               FROM runs r WHERE r.experiment_id=?
               AND r.lifecycle_stage='active' ORDER BY r.start_time""",
            (exp_id,))
        # parents (no mlflow.parentRunId tag) first, then children
        id_map: dict[str, str] = {}
        annotated = []
        for run_uuid, status, t0, t1 in runs:
            tags = dict(store.query(
                "SELECT key, value FROM tags WHERE run_uuid=?", (run_uuid,)))
            annotated.append((run_uuid, status, t0, t1, tags))
        annotated.sort(key=lambda r: "mlflow.parentRunId" in r[4])

        for run_uuid, status, t0, t1, tags in annotated:
            run_name = tags.get("mlflow.runName", run_uuid)
            dest_tags = {"mlflow.runName": run_name}
            parent = tags.get("mlflow.parentRunId")
            if parent is not None:
                if parent not in id_map:
                    progress(f"[export] {run_name}: parent {parent} missing;"
                             " exporting as top-level")
                else:
                    dest_tags["mlflow.parentRunId"] = id_map[parent]
            for k, v in tags.items():
                if k not in _CONTROLLED_TAGS:
                    dest_tags[k] = v
            run = client.create_run(dest_exp, start_time=t0 or 0,
                                    tags=dest_tags, run_name=run_name)
            id_map[run_uuid] = run.info.run_id
            counts["runs"] += 1

            params = store.query(
                "SELECT key, value FROM params WHERE run_uuid=?", (run_uuid,))
            metrics = store.query(
                "SELECT key, value, timestamp, step, is_nan FROM metrics"
                " WHERE run_uuid=? ORDER BY step", (run_uuid,))
            client.log_batch(
                run.info.run_id,
                metrics=[Metric(k, float("nan") if n else v, ts, step)
                         for k, v, ts, step, n in metrics],
                params=[Param(k, str(v)[:500]) for k, v in params],
                tags=[RunTag("exported_from", db_path)],
            )
            counts["metrics"] += len(metrics)
            client.set_terminated(run.info.run_id,
                                  status=status or "FINISHED",
                                  end_time=t1)
    store.close()
    progress(f"[export] {counts['experiments']} experiments, "
             f"{counts['runs']} runs, {counts['metrics']} metric points "
             f"-> {dest_uri}")
    return counts


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--db", default="coda.sqlite",
                   help="native tracking store to export")
    p.add_argument("--dest", default="sqlite:///mlflow.sqlite",
                   help="MLflow tracking URI to export into")
    args = p.parse_args(argv)
    export(args.db, args.dest)


if __name__ == "__main__":
    main()
