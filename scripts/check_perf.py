"""Committed-artifact perf gate: every BENCH_*/EVIDENCE_* claim, declared.

Every performance claim this repo makes lives in a committed JSON artifact
at the repo root — serve p99, suite wall-clock, headline steps/sec,
recorder overhead, the one-run evidence manifests. Before this gate only
ONE of them was checked (``check_serve_bench.py``); the rest could be
silently regenerated weaker, lose the fields their claim is made of, or
drift without anyone noticing. Like ``check_record_schema.py`` gates the
record schema, this module gates the artifacts:

  * a **declarative contract registry**: each artifact (filename pattern)
    maps to required fields, committed bounds, and a fingerprint policy.
    A ``BENCH_*.json`` / ``EVIDENCE_*.json`` at the repo root with NO
    matching contract FAILS the run — new artifacts must declare their
    claim to land;
  * a **fingerprint policy**: artifacts captured from round
    ``FINGERPRINT_REQUIRED_ROUND`` on must carry the recorder's
    ``environment_fingerprint`` (``telemetry/recorder.py``); older ones
    pass with an explicit recorded ``fingerprint: null`` grandfather
    note, never silently;
  * **same-fingerprint cross-round regression**: artifacts in the same
    contract group whose fingerprints describe the same environment AND
    the same capture knobs are compared round-over-round on the group's
    headline metric with an explicit tolerance — a regenerated capture
    that regressed past it fails tier-1.

Runnable standalone (no args = gate the whole repo root)::

    python scripts/check_perf.py
    python scripts/check_perf.py EVIDENCE_cpu_r11.json   # one artifact
    python scripts/check_perf.py --family serve          # one family only

``--family`` filters to one contract group (``serve``, ``batchq``, ...)
— the old ``check_serve_bench.py`` shim's standalone invocation is now
``--family serve``; the serve thresholds still live here under the same
names.
"""

from __future__ import annotations

import fnmatch
import glob
import hashlib
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Callable, Optional

# artifacts whose filename round is >= this must stamp the recorder's
# environment_fingerprint; earlier rounds (and that's every artifact the
# repo shipped before the observatory landed) are grandfathered with an
# explicit note. Filenames without an _rNN round count as new.
FINGERPRINT_REQUIRED_ROUND = 11

_ROUND_RE = re.compile(r"_r(\d+)")


def artifact_round(name: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else None


def get_path(report: dict, dotted: str):
    """``(found, value)`` for a dotted path into nested dicts."""
    cur = report
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return False, None
        cur = cur[part]
    return True, cur


_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "truthy": lambda a, b: bool(a),
}


@dataclass(frozen=True)
class Contract:
    """One artifact family's declared claim."""

    pattern: str                  # fnmatch over the basename; first match wins
    kind: str                     # human name of the artifact family
    required: tuple = ()          # dotted paths that must exist, non-null
    bounds: tuple = ()            # (dotted path, op, value) committed bounds
    checker: Optional[Callable] = None   # extra report -> [violations]
    fingerprint: str = "auto"     # "auto" | "required" | "grandfathered"
    group: Optional[str] = None   # cross-round regression group
    # (dotted metric path, "lower"|"higher" = which direction is better,
    # relative tolerance) — compared round-over-round within the group for
    # artifacts whose fingerprints match (environment + knobs)
    regress: Optional[tuple] = None
    note: str = ""


# ---------------------------------------------------------------------------
# the serve contract (folded in from scripts/check_serve_bench.py — the
# shim there re-exports these names so its documented invocation and the
# committed thresholds stay put)
# ---------------------------------------------------------------------------

# committed thresholds for BENCH_SERVE_CPU_r09.json (1-core CPU container,
# 256 sessions, synthetic 8,512,10, coda). The r06 baseline this gates the
# improvement against: p99 = 5587.7 ms at 64 sessions.
R06_P99_MS = 5587.7
MIN_IMPROVEMENT = 10.0          # the acceptance contract: >= 10x vs r06
MIN_SESSIONS = 256
P99_MS_MAX = R06_P99_MS / MIN_IMPROVEMENT   # = 558.8 ms
P50_MS_MAX = 420.0              # ~one slab step + formation, with headroom

_SERVE_REQUIRED = (
    "bench", "mode", "transport", "sessions", "labels_per_session",
    "wall_s", "sessions_per_s", "requests_per_s", "latency_ms", "n_errors",
    "server", "breakdown", "warm_pool", "config",
)
_SERVE_REQUIRED_SERVER = ("dispatches", "requests", "max_occupancy",
                          "mean_occupancy", "dispatch_latency",
                          "request_latency")
_SERVE_REQUIRED_BREAKDOWN = ("queue_wait", "dispatch", "step", "spans")


def serve_check_report(report: dict) -> list[str]:
    """Violations of one serve-bench report dict (empty = clean) — the
    r09 contract: schema fields the claim is made of, 0 errors, session
    floor, the committed p50/p99 bounds, and a fully-warm AOT pool."""
    out: list[str] = []
    for key in _SERVE_REQUIRED:
        if key not in report:
            out.append(f"missing field {key!r}")
    if out:
        return out  # field-dependent checks below would just cascade
    if report["bench"] != "serve_loadgen":
        out.append(f"bench {report['bench']!r} != 'serve_loadgen'")
    for key in _SERVE_REQUIRED_SERVER:
        if report["server"].get(key) is None:
            out.append(f"server.{key} missing/null")
    for key in _SERVE_REQUIRED_BREAKDOWN:
        if report["breakdown"].get(key) is None:
            out.append(f"breakdown.{key} missing/null (p99 attribution "
                       "must be mechanical)")
    p50 = (report["latency_ms"] or {}).get("p50")
    p99 = (report["latency_ms"] or {}).get("p99")
    if p50 is None or p99 is None:
        out.append("latency_ms.p50/p99 missing")
        return out
    # bounds: the committed claim
    if report["n_errors"] != 0:
        out.append(f"n_errors {report['n_errors']} != 0")
    if report["sessions"] < MIN_SESSIONS:
        out.append(f"sessions {report['sessions']} < {MIN_SESSIONS}")
    if p99 > P99_MS_MAX:
        out.append(f"p99 {p99:.1f} ms > {P99_MS_MAX:.1f} ms "
                   f"(the >= {MIN_IMPROVEMENT:.0f}x-vs-r06 bound)")
    if p50 > P50_MS_MAX:
        out.append(f"p50 {p50:.1f} ms > {P50_MS_MAX:.1f} ms")
    warm = report["warm_pool"] or {}
    if not warm.get("size"):
        out.append("warm_pool.size is 0/missing (AOT pool was not built)")
    if warm.get("misses"):
        out.append(f"warm_pool.misses {warm['misses']} != 0 "
                   "(a dispatch fell back to lazy jit)")
    return out


# ---------------------------------------------------------------------------
# the tiered-store contract (ISSUE 11 acceptance: BENCH_TIERED_* holds the
# ≥100k-open-sessions / bounded-RSS / wake-under-one-tick claim)
# ---------------------------------------------------------------------------

TIERED_MIN_OPEN_SESSIONS = 100_000
TIERED_MAX_RSS_BYTES = 2 * 1024 ** 3    # bounded RSS on the container
TIERED_MIN_HOT_HIT_RATE = 0.5           # Zipf hot set stays resident

_TIERED_REQUIRED_TIERING = (
    "open_sessions", "slab_occupancy", "tiers", "demotions", "wakes",
    "hibernates", "wake_latency", "hot_hit_rate", "tick_ms",
    "peak_rss_bytes",
)


def tiered_check_report(report: dict) -> list[str]:
    """Violations of one tiered-serve capture (empty = clean): the zipf
    workload shape, zero errors, the session floor, the RSS bound, the
    hot-set residency claim, wake-from-warm p99 under one batcher tick,
    and no 503 ever surfacing for a wakeable session."""
    out: list[str] = []
    if report.get("mode") != "zipf":
        out.append(f"mode {report.get('mode')!r} != 'zipf' (the tiering "
                   "claim needs the Zipf-arrival workload)")
    t = report.get("tiering")
    if not isinstance(t, dict):
        return out + ["tiering section missing"]
    for key in _TIERED_REQUIRED_TIERING:
        if t.get(key) is None:
            out.append(f"tiering.{key} missing/null")
    if out:
        return out
    if report.get("n_errors") != 0:
        out.append(f"n_errors {report.get('n_errors')} != 0")
    if t["open_sessions"] < TIERED_MIN_OPEN_SESSIONS:
        out.append(f"tiering.open_sessions {t['open_sessions']} < "
                   f"{TIERED_MIN_OPEN_SESSIONS}")
    if t["open_sessions"] <= t["slab_occupancy"]:
        out.append("open_sessions <= slab_occupancy: nothing ever lived "
                   "off-slab — the tiered store was not exercised")
    if t["peak_rss_bytes"] > TIERED_MAX_RSS_BYTES:
        out.append(f"tiering.peak_rss_bytes {t['peak_rss_bytes']:.0f} > "
                   f"the committed {TIERED_MAX_RSS_BYTES} bound")
    if t["hot_hit_rate"] < TIERED_MIN_HOT_HIT_RATE:
        out.append(f"tiering.hot_hit_rate {t['hot_hit_rate']:.3f} < "
                   f"{TIERED_MIN_HOT_HIT_RATE} (the hot set did not stay "
                   "resident under Zipf arrivals)")
    wake_p99 = (t.get("wake_latency") or {}).get("p99_ms")
    tick = t.get("tick_ms")
    if wake_p99 is None or tick is None:
        out.append("tiering.wake_latency.p99_ms / tick_ms missing")
    elif wake_p99 > tick:
        out.append(f"wake p99 {wake_p99:.1f} ms > one batcher tick "
                   f"({tick:.1f} ms)")
    if t.get("wake_failures"):
        out.append(f"tiering.wake_failures {t['wake_failures']} != 0 "
                   "(a wakeable session surfaced an error/503)")
    if not t.get("wakes"):
        out.append("tiering.wakes == 0 (no wake ever happened — the "
                   "claim is unexercised)")
    # spill v3 (ISSUE 18): captures that carry the reopen probe must
    # evidence the O(index) startup — the sidecar honored, not a full
    # frame scan (older captures predate the probe; absent = not checked)
    reopen = t.get("spill_reopen")
    if isinstance(reopen, dict):
        if reopen.get("startup_mode") != "index":
            out.append(
                f"tiering.spill_reopen.startup_mode "
                f"{reopen.get('startup_mode')!r} != 'index' (restart "
                "fell back to the full frame scan — the persisted "
                "sidecar index was not honored)")
        entries = reopen.get("entries")
        scanned = reopen.get("startup_scan_frames")
        if isinstance(entries, int) and isinstance(scanned, int) and \
                entries > 0 and scanned >= entries:
            out.append(
                f"tiering.spill_reopen.startup_scan_frames {scanned} >= "
                f"entries {entries}: startup re-parsed the whole store, "
                "not just the unindexed tail")
    return out


# ---------------------------------------------------------------------------
# the replicated-fleet contract (ISSUE 13 acceptance: BENCH_FLEET_* holds
# the near-linear-aggregate / zero-drop rolling-restart claim)
# ---------------------------------------------------------------------------

FLEET_MIN_REPLICAS = 3
# multi-core capture: aggregate rps at N replicas >= 0.75 * N * baseline
FLEET_MIN_EFFICIENCY = 0.75
# 1-core container (every replica shares the core): parity with one
# replica is the physically honest ceiling — the router must not cost it
FLEET_MIN_PARITY = 0.7

_FLEET_REQUIRED = (
    "replicas", "host_cores", "single_core", "per_replica",
    "request_share", "balance", "router", "rolling_restart",
    "dropped_sessions", "double_applied_labels", "router_spans",
    "scaling",
)


def fleet_check_report(report: dict) -> list[str]:
    """Violations of one fleet capture (empty = clean): the rolling
    restart of EVERY replica in sequence with zero dropped sessions and
    zero double-applied labels, every migration digest-verified, the
    request distribution actually spread, the router's added latency
    span-attributed, and the scaling claim (efficiency on multi-core,
    documented parity on the 1-core container)."""
    out: list[str] = []
    if report.get("mode") != "fleet":
        out.append(f"mode {report.get('mode')!r} != 'fleet'")
    f = report.get("fleet")
    if not isinstance(f, dict):
        return out + ["fleet section missing"]
    for key in _FLEET_REQUIRED:
        if f.get(key) is None:
            out.append(f"fleet.{key} missing/null")
    if out:
        return out
    if report.get("n_errors") != 0:
        out.append(f"n_errors {report.get('n_errors')} != 0")
    if f["replicas"] < FLEET_MIN_REPLICAS:
        out.append(f"fleet.replicas {f['replicas']} < "
                   f"{FLEET_MIN_REPLICAS}")
    if f["dropped_sessions"] != 0:
        out.append(f"fleet.dropped_sessions {f['dropped_sessions']} != 0")
    if f["double_applied_labels"] != 0:
        out.append(f"fleet.double_applied_labels "
                   f"{f['double_applied_labels']} != 0")
    rr = f.get("rolling_restart") or {}
    if rr.get("replicas_restarted") != f["replicas"]:
        out.append(f"rolling_restart.replicas_restarted "
                   f"{rr.get('replicas_restarted')!r} != fleet.replicas "
                   f"{f['replicas']} (every replica must cycle)")
    if rr.get("sessions_dropped"):
        out.append(f"rolling_restart.sessions_dropped "
                   f"{rr['sessions_dropped']} != 0")
    if rr.get("migration_failures"):
        out.append(f"rolling_restart.migration_failures "
                   f"{rr['migration_failures']} != 0")
    router = f.get("router") or {}
    migrations = (router.get("counters") or {}).get("migrations")
    verified = router.get("migration_verified")
    if not migrations:
        out.append("router.counters.migrations is 0/missing — the "
                   "restart cycled no live sessions, the zero-drop claim "
                   "is unexercised")
    elif verified != migrations:
        out.append(f"router.migration_verified {verified!r} != migrations "
                   f"{migrations} (every migration must restore via the "
                   "digest-verified snapshot or bitwise-replay path)")
    shares = f.get("request_share") or {}
    if len([s for s in shares.values() if s > 0]) < f["replicas"]:
        out.append("request_share: some replica served no requests — the "
                   "rendezvous spread is unexercised")
    spans = f.get("router_spans") or {}
    if not spans.get("n_route_spans"):
        out.append("router_spans.n_route_spans is 0/missing (added "
                   "latency must be span-attributed)")
    if spans.get("router_overhead_mean_ms") is None:
        out.append("router_spans.router_overhead_mean_ms missing")
    sc = f.get("scaling") or {}
    eff, parity = sc.get("efficiency"), sc.get("parity_ratio")
    if not isinstance(eff, (int, float)) or \
            not isinstance(parity, (int, float)):
        out.append("scaling.efficiency / parity_ratio missing (run the "
                   "loadgen with --fleet-baseline)")
    else:
        # the efficiency ceiling is min(1, cores/replicas): N replicas
        # cannot scale past the cores they share. The bound is 0.75 of
        # that ceiling — on a >=N-core host that is the committed 0.75,
        # on a core-limited host it is proportionally honest, and the
        # artifact must STATE its regime (single_core/host_cores).
        cores = f.get("host_cores") or 1
        ceiling = min(1.0, cores / f["replicas"])
        if eff < FLEET_MIN_EFFICIENCY * ceiling:
            out.append(
                f"scaling.efficiency {eff:.3f} < "
                f"{FLEET_MIN_EFFICIENCY} * {ceiling:.2f} (the "
                f"{cores}-core/{f['replicas']}-replica ceiling)")
        if cores == 1 and parity < FLEET_MIN_PARITY:
            # one core: aggregate parity with a single replica is the
            # additional claim (the router must not eat the budget)
            out.append(f"scaling.parity_ratio {parity:.3f} < "
                       f"{FLEET_MIN_PARITY} on the 1-core container "
                       "(the router cost more than the parity budget)")
    return out


# ---------------------------------------------------------------------------
# per-family checkers
# ---------------------------------------------------------------------------

def _recorder_check(report: dict) -> list[str]:
    """Every measured recorder-overhead config must sit under the
    committed bound."""
    out = []
    bound = report.get("bound")
    for i, cfg in enumerate(report.get("configs") or []):
        ov = cfg.get("overhead")
        if ov is None:
            out.append(f"configs[{i}].overhead missing")
        elif bound is not None and ov > bound:
            out.append(f"configs[{i}].overhead {ov} > bound {bound}")
    return out


def _wrapped_bench_check(report: dict) -> list[str]:
    """The r01-r05 driver-wrapped bench lines: exit 0 and a parsed
    positive steps/sec value."""
    out = []
    parsed = report.get("parsed") or {}
    v = parsed.get("value")
    if not isinstance(v, (int, float)) or not v > 0:
        out.append(f"parsed.value {v!r} is not a positive number")
    return out


# the committed sparse-posterior claims at the ImageNet pool shape
# (ISSUE 9 acceptance: the numbers IMAGENET_SPARSE_* artifacts must hold)
IMAGENET_SPARSE_MIN_SPEEDUP = 20.0      # round time vs the r05 dense capture
IMAGENET_SPARSE_MIN_BYTES_RATIO = 10.0  # posterior state bytes, dense/sparse
IMAGENET_SPARSE_SCORE_TOL = 2.34e-4     # the documented score contract


def _imagenet_sparse_check(report: dict) -> list[str]:
    """Beyond the declarative bounds: a dense-vs-sparse divergence must
    either be full parity or arrive CLASSIFIED as a near-tie flip by the
    replay triage — a score-delta/posterior-drift first divergence means
    the representation broke the contract, not a tie."""
    out = []
    rep = report.get("replay") or {}
    if not rep.get("parity"):
        cls = (rep.get("first_divergence") or {}).get("classification")
        if cls != "tie-break-flip":
            out.append("replay diverged with classification "
                       f"{cls!r} (only full parity or a triaged "
                       "tie-break-flip is within the sparse contract)")
    if (rep.get("score_tol") or 0) > IMAGENET_SPARSE_SCORE_TOL:
        out.append(f"replay.score_tol {rep.get('score_tol')} looser than "
                   f"the documented {IMAGENET_SPARSE_SCORE_TOL} contract")
    return out


# ---------------------------------------------------------------------------
# the batched-acquisition contract (ISSUE 12 acceptance: BENCH_BATCHQ_*
# holds the regret-parity envelope + the labels/s speedup floor)
# ---------------------------------------------------------------------------

# labels/s speedup at the artifact's headline q must clear frac * q
BATCHQ_SPEEDUP_FRAC = 0.6
# the declared real-digits regret envelope (label-weighted final
# cumulative regret at q vs q=1): ratio + absolute slack, matching the
# generator's declaration (scripts/bench_batchq.py)
BATCHQ_ENVELOPE_RATIO = 1.5
BATCHQ_ENVELOPE_ABS = 1.0


def batchq_check_report(report: dict) -> list[str]:
    """Violations of one batchq capture (empty = clean): the speedup
    floor at the headline q, the regret envelope held per batched q with
    every divergence replay-triaged through the ``--against`` path, and
    bitwise self-replay of every recorded q-wide program."""
    out: list[str] = []
    if report.get("quick"):
        return ["quick batchq captures must not be committed at the repo "
                "root (no committed floors were checked)"]
    im = report.get("imagenet") or {}
    q = im.get("q")
    speedup = report.get("labels_per_s_speedup")
    if not isinstance(q, int) or q < 8:
        out.append(f"imagenet.q {q!r} < 8 (the committed floor is "
                   "measured at q=8)")
    if not isinstance(speedup, (int, float)):
        out.append("labels_per_s_speedup missing")
    elif isinstance(q, int) and speedup < BATCHQ_SPEEDUP_FRAC * q:
        out.append(f"labels_per_s_speedup {speedup:.2f} < "
                   f"{BATCHQ_SPEEDUP_FRAC} * q = "
                   f"{BATCHQ_SPEEDUP_FRAC * q:.2f}")
    dig = report.get("digits") or {}
    per_q = dig.get("per_q") or {}
    if "1" not in per_q or len(per_q) < 2:
        out.append("digits.per_q must carry q=1 and at least one "
                   "batched q")
        return out
    base = (per_q.get("1") or {}).get("final_cum_regret_mean")
    for key, row in per_q.items():
        rep = row.get("replay") or {}
        if rep.get("parity") is not True:
            out.append(f"digits.per_q[{key}].replay.parity is not true "
                       "(every recorded q-wide program must self-replay "
                       "bitwise)")
        if key == "1":
            continue
        against = row.get("against_q1") or {}
        if against.get("classification") != "acq-batch-envelope":
            out.append(
                f"digits.per_q[{key}].against_q1.classification "
                f"{against.get('classification')!r} — the q-vs-1 "
                "divergence must be triaged through the replay "
                "--against knob-diff path")
        mean = row.get("final_cum_regret_mean")
        if not isinstance(mean, (int, float)) or \
                not isinstance(base, (int, float)):
            out.append(f"digits.per_q[{key}].final_cum_regret_mean "
                       "missing")
        elif mean > BATCHQ_ENVELOPE_RATIO * base + BATCHQ_ENVELOPE_ABS:
            out.append(
                f"digits.per_q[{key}] final cum regret {mean:.4f} "
                f"outside the committed envelope "
                f"({BATCHQ_ENVELOPE_RATIO} * {base:.4f} + "
                f"{BATCHQ_ENVELOPE_ABS})")
    return out


# ---------------------------------------------------------------------------
# the contract-gated EIG surrogate (ISSUE 15 acceptance: BENCH_SURROGATE_*
# holds the scoring-pass speedup + regret envelope + fallback-rate claims)
# ---------------------------------------------------------------------------

# the scoring pass itself (exact full sweep vs surrogate predict +
# shortlist refresh + gate) at the imagenet preset, measured on the same
# carried state
SURROGATE_MIN_SCORE_SPEEDUP = 3.0
# the real-digits regret envelope vs the exact scorer at the same label
# budget: ratio on the label-weighted final cumulative regret, plus a
# small absolute slack so near-zero regrets cannot turn a 0.01-vs-0.02
# difference into a 2x "violation" (the batchq precedent)
SURROGATE_ENVELOPE_RATIO = 1.05
SURROGATE_ENVELOPE_ABS = 0.02
# contract fallbacks after warmup: the surrogate must actually carry the
# rounds, not bounce off its own gate
SURROGATE_MAX_FALLBACK_RATE = 0.10


def surrogate_check_report(report: dict) -> list[str]:
    """Violations of one surrogate capture (empty = clean): the
    scoring-pass speedup floor at the imagenet preset, the digits regret
    envelope vs exact, the post-warmup fallback-rate bound (measured
    from the per-round stream evidence), bitwise self-replay of both
    recorded programs, the surrogate-vs-exact divergence triaged as
    ``eig-scorer-envelope`` through the ``--against`` path, AND the
    default (`--eig-scorer exact`) pinned bitwise-unchanged through the
    same path."""
    out: list[str] = []
    if report.get("quick"):
        return ["quick surrogate captures must not be committed at the "
                "repo root (no committed floors were checked)"]
    im = report.get("imagenet") or {}
    speedup = im.get("scoring_pass_speedup")
    if not isinstance(speedup, (int, float)):
        out.append("imagenet.scoring_pass_speedup missing")
    elif speedup < SURROGATE_MIN_SCORE_SPEEDUP:
        out.append(f"imagenet.scoring_pass_speedup {speedup:.2f} < "
                   f"{SURROGATE_MIN_SCORE_SPEEDUP}")
    rate = im.get("fallback_rate_post_warmup")
    if not isinstance(rate, (int, float)):
        out.append("imagenet.fallback_rate_post_warmup missing")
    elif rate > SURROGATE_MAX_FALLBACK_RATE:
        out.append(f"imagenet.fallback_rate_post_warmup {rate:.3f} > "
                   f"{SURROGATE_MAX_FALLBACK_RATE}")
    dig = report.get("digits") or {}
    base = (dig.get("exact") or {}).get("final_cum_regret_mean")
    surr = (dig.get("surrogate") or {}).get("final_cum_regret_mean")
    if not all(isinstance(v, (int, float)) for v in (base, surr)):
        out.append("digits.exact/surrogate.final_cum_regret_mean missing")
    elif surr > SURROGATE_ENVELOPE_RATIO * base + SURROGATE_ENVELOPE_ABS:
        out.append(
            f"digits surrogate final cum regret {surr:.4f} outside the "
            f"committed envelope ({SURROGATE_ENVELOPE_RATIO} * {base:.4f}"
            f" + {SURROGATE_ENVELOPE_ABS})")
    drate = (dig.get("surrogate") or {}).get("fallback_rate_post_warmup")
    if isinstance(drate, (int, float)) and \
            drate > SURROGATE_MAX_FALLBACK_RATE:
        out.append(f"digits surrogate fallback rate {drate:.3f} > "
                   f"{SURROGATE_MAX_FALLBACK_RATE}")
    for side in ("exact", "surrogate"):
        rep = (dig.get(side) or {}).get("replay") or {}
        if rep.get("parity") is not True:
            out.append(f"digits.{side}.replay.parity is not true (every "
                       "recorded program must self-replay bitwise)")
    against = dig.get("against_exact") or {}
    if against.get("classification") != "eig-scorer-envelope":
        out.append(
            f"digits.against_exact.classification "
            f"{against.get('classification')!r} — the surrogate-vs-exact "
            "divergence must be triaged through the replay --against "
            "knob-diff path as eig-scorer-envelope")
    pin = report.get("default_exact_pin") or {}
    if pin.get("parity") is not True:
        out.append("default_exact_pin.parity is not true (--eig-scorer "
                   "exact must be bitwise the default, verified through "
                   "the real cli replay --against path)")
    return out


# ---------------------------------------------------------------------------
# cross-session surrogate priors (ISSUE 18 acceptance: BENCH_PRIOR_*
# holds the warmup-cost reduction + regret envelope + gate-fallback +
# never-unaudited claims of --surrogate-prior pool)
# ---------------------------------------------------------------------------

# exact warmup rounds a pool-seeded session pays vs a cold one: the
# amortization claim (a mature pool grants the full warmup credit, so a
# seeded session pays >= 3x fewer exact warmup rounds)
PRIOR_MIN_WARMUP_REDUCTION = 3.0
# real-digits regret of the seeded run vs the cold run at the same label
# budget — the surrogate envelope's numbers (the prior changes WHEN the
# surrogate starts carrying rounds, never the audit/trust contract, so
# it inherits the same quality bound)
PRIOR_ENVELOPE_RATIO = 1.05
PRIOR_ENVELOPE_ABS = 0.02


def prior_check_report(report: dict) -> list[str]:
    """Violations of one surrogate-prior capture (empty = clean): the
    exact-warmup-rounds reduction floor, the seeded-vs-cold digits
    regret envelope, zero unaudited argmax picks across every driven
    round, the hostile-prior gate rejection actually falling back to
    the exact pass, bitwise self-replay of every recorded program, the
    pool-vs-off divergence triaged as ``surrogate-prior-envelope``
    through ``cli replay --against``, and ``--surrogate-prior off``
    bitwise-pinned to the knob-less program through the same real path
    at score-tol 0."""
    out: list[str] = []
    if report.get("quick"):
        return ["quick prior captures must not be committed at the repo "
                "root (no committed floors were checked)"]
    warm = report.get("warmup") or {}
    red = warm.get("reduction")
    if not isinstance(red, (int, float)):
        out.append("warmup.reduction missing")
    elif red < PRIOR_MIN_WARMUP_REDUCTION:
        out.append(f"warmup.reduction {red:.2f} < "
                   f"{PRIOR_MIN_WARMUP_REDUCTION} (the pool prior did "
                   "not amortize the exact warmup)")
    dig = report.get("digits") or {}
    base = (dig.get("cold") or {}).get("final_cum_regret_mean")
    seeded = (dig.get("seeded") or {}).get("final_cum_regret_mean")
    if not all(isinstance(v, (int, float)) for v in (base, seeded)):
        out.append("digits.cold/seeded.final_cum_regret_mean missing")
    elif seeded > PRIOR_ENVELOPE_RATIO * base + PRIOR_ENVELOPE_ABS:
        out.append(
            f"digits seeded final cum regret {seeded:.4f} outside the "
            f"committed envelope ({PRIOR_ENVELOPE_RATIO} * {base:.4f} + "
            f"{PRIOR_ENVELOPE_ABS})")
    audit = report.get("audit") or {}
    if audit.get("unaudited_argmax_picks") != 0:
        out.append(
            f"audit.unaudited_argmax_picks "
            f"{audit.get('unaudited_argmax_picks')!r} != 0 (a selection "
            "was driven by a score the exact chain never audited)")
    gate = report.get("gate_rejection") or {}
    if not gate.get("prior_rejects"):
        out.append("gate_rejection.prior_rejects is 0/missing (the "
                   "hostile-prior probe never tripped the contract)")
    if gate.get("fell_back_exact") is not True:
        out.append("gate_rejection.fell_back_exact is not true (a "
                   "rejected prior round must run the exact pass "
                   "bitwise)")
    for side in ("cold", "seeded"):
        rep = (dig.get(side) or {}).get("replay") or {}
        if rep.get("parity") is not True:
            out.append(f"digits.{side}.replay.parity is not true (every "
                       "recorded program must self-replay bitwise)")
    against = dig.get("against_cold") or {}
    if against.get("classification") != "surrogate-prior-envelope":
        out.append(
            f"digits.against_cold.classification "
            f"{against.get('classification')!r} — the pool-vs-off "
            "divergence must be triaged through the replay --against "
            "knob-diff path as surrogate-prior-envelope")
    pin = report.get("off_parity") or {}
    if pin.get("parity") is not True:
        out.append("off_parity.parity is not true (--surrogate-prior "
                   "off must be bitwise the knob-less PR 14 program, "
                   "verified through the real cli replay --against "
                   "--score-tol 0 path)")
    return out


# ---------------------------------------------------------------------------
# the crowd-oracle robustness contract (ISSUE 16: noisy / abstaining /
# asynchronous labelers with a learned annotator-reliability posterior)
# ---------------------------------------------------------------------------

# noisy-crowd regret envelope vs the clean oracle at the same label
# budget (label-aligned final cumulative regret): a reliability-weighted
# noisy crowd costs labels, not correctness — bounded ratio plus the
# near-zero-regret absolute slack (the batchq/surrogate precedent)
ORACLE_ENVELOPE_RATIO = 2.0
ORACLE_ENVELOPE_ABS = 1.0
# the Dawid-Skene recovery floor: learned per-annotator accuracies vs
# the planted confusion diagonals after the artifact's vote budget.
# Correlation + adversary separation are the recovery claims; the MAE
# bound only guards gross miscalibration — the posterior-mean diagonal
# is systematically shrunk toward 1/C by the Laplace prior and by
# soft-assignment teaching (imperfect aggregated labels spread mass
# off the true confusion row), so absolute agreement tighter than
# ~0.2 is not achievable without a supervised debias pass
ORACLE_MIN_RELIABILITY_CORR = 0.8
ORACLE_MAX_RELIABILITY_MAE = 0.25


def robustness_check_report(report: dict) -> list[str]:
    """Violations of one crowd-oracle robustness capture (empty = clean):
    clean-config bitwise parity through the real ``cli replay --against
    --score-tol 0`` path, the noisy regret envelope triaged as
    ``oracle-noise-envelope``, Dawid-Skene recovery of the planted pool
    (with every adversarial annotator ranked below every honest one),
    and the async serve matrix (out-of-order == in-order digest, 0
    lost / double-applied labels, parked answers surviving restore)."""
    out: list[str] = []
    clean = report.get("clean") or {}
    if clean.get("parity") is not True:
        out.append("clean.parity is not true (--oracle-noise clean must "
                   "verify bitwise against the knob-less record through "
                   "cli replay --against --score-tol 0)")
    noisy = report.get("noisy") or {}
    if noisy.get("classification") != "oracle-noise-envelope":
        out.append(f"noisy.classification "
                   f"{noisy.get('classification')!r} — the oracle-knob "
                   "diff must route to the regret-envelope triage")
    for i, seed in enumerate(noisy.get("per_seed") or []):
        ca, cb = seed.get("final_cum_a"), seed.get("final_cum_b")
        if not all(isinstance(v, (int, float)) for v in (ca, cb)):
            out.append(f"noisy.per_seed[{i}] missing final cum regrets")
        elif cb > ORACLE_ENVELOPE_RATIO * ca + ORACLE_ENVELOPE_ABS:
            out.append(
                f"noisy seed {i} final cum regret {cb:.4f} outside the "
                f"committed envelope ({ORACLE_ENVELOPE_RATIO} * {ca:.4f}"
                f" + {ORACLE_ENVELOPE_ABS})")
    if not noisy.get("per_seed"):
        out.append("noisy.per_seed missing/empty")
    rel = report.get("reliability") or {}
    corr, mae = rel.get("corr"), rel.get("mae")
    if not all(isinstance(v, (int, float)) for v in (corr, mae)):
        out.append("reliability.corr/mae missing")
    else:
        if corr < ORACLE_MIN_RELIABILITY_CORR:
            out.append(f"reliability.corr {corr:.3f} < "
                       f"{ORACLE_MIN_RELIABILITY_CORR} (the posterior "
                       "did not recover the planted pool)")
        if mae > ORACLE_MAX_RELIABILITY_MAE:
            out.append(f"reliability.mae {mae:.3f} > "
                       f"{ORACLE_MAX_RELIABILITY_MAE}")
    if rel.get("adversaries_separated") is not True:
        out.append("reliability.adversaries_separated is not true (an "
                   "adversarial annotator ranked above an honest one)")
    asyn = report.get("async") or {}
    if asyn.get("digest_match") is not True:
        out.append("async.digest_match is not true (out-of-order "
                   "deferred delivery must commit the in-order stream)")
    if asyn.get("parked_restored") is not True:
        out.append("async.parked_restored is not true (parked answers "
                   "must survive a crash-restore)")
    if not asyn.get("redelivered"):
        out.append("async.redelivered is 0/missing (the dedupe path "
                   "went unexercised)")
    return out


# ---------------------------------------------------------------------------
# the fault-matrix contracts (ISSUE 14: the fleet chaos matrix is a
# committed, machine-checked artifact like every perf claim)
# ---------------------------------------------------------------------------

#: the failure modes the committed fleet matrix must cover: the fencing
#: (split-brain) regression, the kill-mid-migration window, the in-doubt
#: journal recovery, the flap hysteresis, live transport chaos, and the
#: partition+heal proof
FLEET_MATRIX_REQUIRED_SCENARIOS = (
    "fleet_stale_owner_fence",
    "fleet_kill_replica_mid_migration",
    "fleet_router_restart_journal",
    "fleet_healthz_flap",
    "fleet_transport_chaos",
    "fleet_partition_heal",
)


def fleet_matrix_check(report: dict) -> list[str]:
    """Violations of one FAULT_MATRIX_FLEET_* artifact (empty = clean):
    every required scenario present and violation-free, zero dropped
    sessions, zero double-applies, every migration digest-verified, and
    the fencing scenario actually exercised (a matrix that never
    provoked a stale-owner rejection proves nothing about the fence)."""
    out: list[str] = []
    sc = report.get("scenarios")
    if not isinstance(sc, dict):
        return ["scenarios section missing"]
    for name in FLEET_MATRIX_REQUIRED_SCENARIOS:
        if name not in sc:
            out.append(f"scenario {name!r} missing — the committed "
                       "matrix must cover it")
    for name, s in sorted(sc.items()):
        v = (s or {}).get("violations")
        if v is None:
            out.append(f"scenarios.{name}.violations missing")
            continue
        for item in v:
            out.append(f"scenarios.{name}: {item}")
    summ = report.get("summary") or {}
    if summ.get("migration_verified") != summ.get("migrations"):
        out.append(f"summary.migration_verified "
                   f"{summ.get('migration_verified')!r} != migrations "
                   f"{summ.get('migrations')!r} (every migration must "
                   "restore digest-verified)")
    fenced = (sc.get("fleet_stale_owner_fence") or {}).get(
        "fencing_rejections")
    if not fenced:
        out.append("fleet_stale_owner_fence.fencing_rejections is "
                   "0/missing — the fence was never exercised")
    return out


def legacy_matrix_check(report: dict) -> list[str]:
    """The r10/r13 single-replica matrix layout: {scenario: violations}
    — committed only when every list is empty."""
    if not isinstance(report, dict) or not report:
        return ["empty fault matrix"]
    out: list[str] = []
    for name, v in sorted(report.items()):
        if not isinstance(v, list):
            out.append(f"{name}: violations is not a list")
            continue
        for item in v:
            out.append(f"{name}: {item}")
    return out


# ---------------------------------------------------------------------------
# the observability contract (ISSUE 19 acceptance: OBS_FLEET_* holds the
# complete-traces / non-perturbation / SLO fire+clear claims)
# ---------------------------------------------------------------------------

OBS_MAX_OVERHEAD = 0.05         # tracing wall-time overhead ceiling
OBS_MIN_SAMPLED = 8             # sampled traces per fleet sub-pass


def _obs_tracing_check(tag: str, sub: dict,
                       want_exemplars: bool) -> list[str]:
    """One fleet sub-pass's tracing claim: 0 errors, every sampled trace
    fetched back complete through the router's stitcher."""
    out: list[str] = []
    if not isinstance(sub, dict):
        return [f"{tag} missing"]
    if sub.get("n_errors") != 0:
        out.append(f"{tag}.n_errors {sub.get('n_errors')} != 0")
    if sub.get("dropped_sessions"):
        out.append(f"{tag}.dropped_sessions != 0")
    t = sub.get("tracing") or {}
    if (t.get("sampled") or 0) < OBS_MIN_SAMPLED:
        out.append(f"{tag}.tracing.sampled {t.get('sampled')} < "
                   f"{OBS_MIN_SAMPLED}")
    if t.get("completeness") != 1.0:
        out.append(f"{tag}.tracing.completeness {t.get('completeness')} "
                   "!= 1.0 (a sampled trace lost spans)")
    if t.get("fetch_errors"):
        out.append(f"{tag}.tracing.fetch_errors != 0")
    if want_exemplars:
        if not (t.get("exemplars") or 0):
            out.append(f"{tag}.tracing.exemplars is 0 (no /metrics "
                       "latency exemplar to join)")
        elif t.get("exemplar_joinability") != 1.0:
            out.append(f"{tag}.tracing.exemplar_joinability "
                       f"{t.get('exemplar_joinability')} != 1.0")
    return out


def obs_check_report(report: dict) -> list[str]:
    """Violations of one observability report (scripts/bench_obs.py)."""
    out: list[str] = []
    fleet = report.get("fleet") or {}
    out += _obs_tracing_check("fleet.chaos_pass",
                              fleet.get("chaos_pass"),
                              want_exemplars=True)
    # the committed (non-quick) artifact must also prove traces survive
    # a rolling restart via the router's span adoption
    if not report.get("quick"):
        sub = fleet.get("restart_pass")
        out += _obs_tracing_check("fleet.restart_pass", sub,
                                  want_exemplars=False)
        rr = (sub or {}).get("rolling_restart") or {}
        if rr.get("replicas_restarted") != fleet.get("replicas"):
            out.append("fleet.restart_pass: rolling restart did not "
                       "cycle every replica")
    mig = report.get("migration_trace") or {}
    if mig.get("spans_both_replicas") is not True:
        out.append("migration_trace.spans_both_replicas is not true "
                   "(the trace lost one side of the migration)")
    if mig.get("router_lane") is not True:
        out.append("migration_trace.router_lane is not true")
    if len(mig.get("processes") or ()) < 3:
        out.append(f"migration_trace.processes {mig.get('processes')} "
                   "has < 3 lanes (router + both replicas)")
    bit = report.get("bitwise") or {}
    if bit.get("identical") is not True:
        out.append("bitwise.identical is not true (tracing perturbed "
                   f"the decision stream: {bit.get('first_diff')})")
    if bit.get("rows_carry_trace_id") is not True:
        out.append("bitwise.rows_carry_trace_id is not true (traced "
                   "rows lost the recorder join)")
    ov = report.get("overhead") or {}
    frac = ov.get("overhead_frac")
    if not (isinstance(frac, (int, float)) and frac <= OBS_MAX_OVERHEAD):
        out.append(f"overhead.overhead_frac {frac} > {OBS_MAX_OVERHEAD}")
    slo = report.get("slo") or {}
    if not (slo.get("fired") or 0) >= 1:
        out.append("slo.fired < 1 (the burn-rate alert never fired)")
    if not (slo.get("cleared") or 0) >= 1:
        out.append("slo.cleared < 1 (the alert never resolved)")
    if slo.get("persisted_both") is not True:
        out.append("slo.persisted_both is not true (alert transitions "
                   "missing from the tracking store)")
    if slo.get("store_errors"):
        out.append(f"slo.store_errors {slo.get('store_errors')} != 0")
    return out


# ---------------------------------------------------------------------------
# the decision-quality contract (ISSUE 20 acceptance: QUALITY_FLEET_* holds
# the shadow-audit / calibration / drift-SLO / non-perturbation claims)
# ---------------------------------------------------------------------------

QUALITY_MAX_OVERHEAD = 0.05     # quality-plane wall-time overhead ceiling
QUALITY_MIN_AUDITS = 4          # shadow audits the clean fleet must land


def quality_check_report(report: dict) -> list[str]:
    """Violations of one decision-quality report (bench_quality.py)."""
    out: list[str] = []
    clean = report.get("clean_fleet") or {}
    if (clean.get("audits_total") or 0) < QUALITY_MIN_AUDITS:
        out.append(f"clean_fleet.audits_total {clean.get('audits_total')} "
                   f"< {QUALITY_MIN_AUDITS}")
    if clean.get("drained") is not True:
        out.append("clean_fleet.drained is not true (audit queue still "
                   "held work when the counters were read)")
    if clean.get("divergences_total") != 0:
        out.append(f"clean_fleet.divergences_total "
                   f"{clean.get('divergences_total')} != 0 (a clean "
                   "replay diverged from its recorder stream)")
    if clean.get("tampered_total") != 0:
        out.append("clean_fleet.tampered_total != 0 (the clean pass must "
                   "run without the tamper fault armed)")
    if not (clean.get("rounds_verified") or 0):
        out.append("clean_fleet.rounds_verified is 0 (no replayed round "
                   "was actually compared)")
    if (clean.get("verdict") or {}).get("audit") != "ok":
        out.append(f"clean_fleet.verdict.audit "
                   f"{(clean.get('verdict') or {}).get('audit')!r} "
                   "!= 'ok'")
    cal_fleet = clean.get("calibration") or {}
    if not cal_fleet:
        out.append("clean_fleet.calibration is empty (the streaming "
                   "monitor accumulated nothing)")
    for task, agg in cal_fleet.items():
        if not (agg.get("n") or 0):
            out.append(f"clean_fleet.calibration[{task}].n is 0")
        ece = agg.get("ece_max")
        if not (isinstance(ece, (int, float)) and 0.0 <= ece <= 1.0):
            out.append(f"clean_fleet.calibration[{task}].ece_max {ece} "
                       "not a finite ECE in [0, 1]")
    tamper = report.get("tamper") or {}
    if not (tamper.get("tampered_total") or 0) >= 1:
        out.append("tamper.tampered_total < 1 (the fault never fired)")
    if not (tamper.get("divergences_total") or 0) >= 1:
        out.append("tamper.divergences_total < 1 (a single-ulp tamper "
                   "slipped past the shadow audit)")
    if tamper.get("attributed_session") is not True:
        out.append("tamper.attributed_session is not true (divergence "
                   "not pinned to the tampered session)")
    if tamper.get("attributed_round") is not True:
        out.append("tamper.attributed_round is not true (divergence "
                   "not pinned to the tampered round)")
    if tamper.get("verdict_audit") != "diverged":
        out.append(f"tamper.verdict_audit {tamper.get('verdict_audit')!r} "
                   "!= 'diverged'")
    cal = report.get("calibration") or {}
    if cal.get("finite_ece") is not True:
        out.append("calibration.finite_ece is not true (ground-truth "
                   "P(best) calibration did not produce an ECE)")
    if not (cal.get("rounds_scored") or 0):
        out.append("calibration.rounds_scored is 0")
    slo = report.get("slo") or {}
    if not (slo.get("fired") or 0) >= 1:
        out.append("slo.fired < 1 (quality_drift never fired)")
    if not (slo.get("cleared") or 0) >= 1:
        out.append("slo.cleared < 1 (quality_drift never resolved)")
    if slo.get("persisted_both") is not True:
        out.append("slo.persisted_both is not true (alert transitions "
                   "missing from the tracking store)")
    if slo.get("store_errors"):
        out.append(f"slo.store_errors {slo.get('store_errors')} != 0")
    bit = report.get("bitwise") or {}
    if bit.get("identical") is not True:
        out.append("bitwise.identical is not true (the quality plane "
                   f"perturbed the decision stream: {bit.get('first_diff')})")
    if bit.get("update_rows_carry_pred_label_prob") is not True:
        out.append("bitwise.update_rows_carry_pred_label_prob is not "
                   "true (quality-on rows lost the calibration field)")
    if bit.get("off_rows_field_free") is not True:
        out.append("bitwise.off_rows_field_free is not true (quality-off "
                   "rows carry pred_label_prob — the additive-field "
                   "contract is broken)")
    ov = report.get("overhead") or {}
    frac = ov.get("overhead_frac")
    if not (isinstance(frac, (int, float)) and frac <= QUALITY_MAX_OVERHEAD):
        out.append(f"overhead.overhead_frac {frac} > "
                   f"{QUALITY_MAX_OVERHEAD}")
    return out


EVIDENCE_SCHEMA_VERSION = 1
EVIDENCE_COMPONENTS = ("bench", "bench_suite", "serve_loadgen",
                       "multichip_replay")
# components newer manifests carry; checked when present (r11 predates
# them, and an absent optional component is a capture-config choice the
# manifest's own "skipped" list records)
EVIDENCE_OPTIONAL_COMPONENTS = ("bench_imagenet", "serve_tiered",
                                "bench_batchq", "serve_fleet",
                                "serve_fleet_chaos", "bench_surrogate",
                                "oracle_noise", "bench_prior",
                                "serve_obs", "serve_quality")


def _evidence_check(report: dict) -> list[str]:
    """One-run evidence manifests (scripts/capture_evidence.py): every
    component captured ok, stamped with the manifest's environment, and
    each sub-report's own claim intact."""
    out = []
    arts = report.get("artifacts") or {}
    present_optional = [c for c in EVIDENCE_OPTIONAL_COMPONENTS
                        if c in arts]
    for comp in EVIDENCE_COMPONENTS + tuple(present_optional):
        a = arts.get(comp)
        if not isinstance(a, dict):
            out.append(f"artifacts.{comp} missing")
            continue
        if a.get("status") != "ok":
            out.append(f"artifacts.{comp}.status {a.get('status')!r} "
                       "!= 'ok'")
        if a.get("fingerprint_match") is False:
            out.append(f"artifacts.{comp} was captured in a different "
                       "environment than the manifest fingerprint")
        rep = a.get("report")
        if not isinstance(rep, dict):
            out.append(f"artifacts.{comp}.report missing")
    rep = (arts.get("serve_loadgen") or {}).get("report") or {}
    if rep and rep.get("n_errors") != 0:
        out.append(f"serve_loadgen.report.n_errors {rep.get('n_errors')} "
                   "!= 0")
    rep = (arts.get("serve_tiered") or {}).get("report") or {}
    if rep:
        if rep.get("n_errors") != 0:
            out.append(f"serve_tiered.report.n_errors "
                       f"{rep.get('n_errors')} != 0")
        if not ((rep.get("tiering") or {}).get("wakes")):
            out.append("serve_tiered.report.tiering.wakes is 0/missing "
                       "(the paged store went unexercised)")
    rep = (arts.get("bench_batchq") or {}).get("report") or {}
    if rep:
        if rep.get("ok") is not True:
            out.append("bench_batchq.report.ok is not true (regret "
                       "envelope / replay verification / speedup floor "
                       "broke in-capture)")
        if rep.get("replays_verified") is not True:
            out.append("bench_batchq.report.replays_verified is not true")
    rep = (arts.get("bench_surrogate") or {}).get("report") or {}
    if rep:
        if rep.get("ok") is not True:
            out.append("bench_surrogate.report.ok is not true (regret "
                       "envelope / replay verification / speedup floor "
                       "broke in-capture)")
        if rep.get("replays_verified") is not True:
            out.append("bench_surrogate.report.replays_verified is not "
                       "true")
    rep = (arts.get("bench_prior") or {}).get("report") or {}
    if rep:
        if rep.get("ok") is not True:
            out.append("bench_prior.report.ok is not true (warmup "
                       "reduction / regret envelope / gate rejection / "
                       "off parity broke in-capture)")
        if rep.get("replays_verified") is not True:
            out.append("bench_prior.report.replays_verified is not true")
        if (rep.get("audit") or {}).get("unaudited_argmax_picks") != 0:
            out.append("bench_prior.report.audit.unaudited_argmax_picks "
                       "!= 0")
    rep = (arts.get("serve_fleet") or {}).get("report") or {}
    if rep:
        fl = rep.get("fleet") or {}
        if rep.get("n_errors") != 0:
            out.append(f"serve_fleet.report.n_errors "
                       f"{rep.get('n_errors')} != 0")
        if fl.get("dropped_sessions"):
            out.append("serve_fleet.report.fleet.dropped_sessions != 0")
        if fl.get("double_applied_labels"):
            out.append("serve_fleet.report.fleet.double_applied_labels "
                       "!= 0")
        rr = fl.get("rolling_restart") or {}
        if rr.get("replicas_restarted") != fl.get("replicas"):
            out.append("serve_fleet: rolling restart did not cycle every "
                       "replica")
    rep = (arts.get("serve_fleet_chaos") or {}).get("report") or {}
    if rep:
        summ = rep.get("summary") or {}
        if summ.get("clean") is not True:
            out.append("serve_fleet_chaos.report.summary.clean is not "
                       "true (a chaos scenario left a violation)")
        sc = rep.get("scenarios") or {}
        if "fleet_partition_heal" not in sc:
            out.append("serve_fleet_chaos: the partition+heal proof "
                       "scenario is missing")
    rep = (arts.get("oracle_noise") or {}).get("report") or {}
    if rep:
        if rep.get("ok") is not True:
            out.append("oracle_noise.report.ok is not true (clean "
                       "parity / noisy envelope / reliability recovery "
                       "/ async delivery broke in-capture)")
        asyn = rep.get("async") or {}
        if asyn.get("lost") or asyn.get("double_applied"):
            out.append("oracle_noise.report.async lost/double-applied "
                       "labels != 0")
    rep = (arts.get("serve_obs") or {}).get("report") or {}
    if rep:
        out += [f"serve_obs: {v}" for v in obs_check_report(rep)]
    rep = (arts.get("bench") or {}).get("report") or {}
    if rep and not (isinstance(rep.get("value"), (int, float))
                    and rep["value"] > 0):
        out.append("bench.report.value is not a positive number")
    rep = (arts.get("bench_suite") or {}).get("report") or {}
    if rep and not (isinstance(rep.get("value"), (int, float))
                    and rep["value"] > 0):
        out.append("bench_suite.report.value is not a positive number")
    rep = (arts.get("multichip_replay") or {}).get("report") or {}
    if rep and rep.get("ok") is not True:
        out.append("multichip_replay.report.ok is not true")
    return out


# ---------------------------------------------------------------------------
# the registry: every committed artifact family, first match wins
# ---------------------------------------------------------------------------

CONTRACTS: tuple = (
    # -- serve loadgen captures --
    Contract(
        pattern="BENCH_SERVE_CPU_r06.json", kind="serve_loadgen_legacy",
        required=("bench", "mode", "transport", "sessions",
                  "labels_per_session", "wall_s", "latency_ms.p50",
                  "latency_ms.p99", "n_errors", "server.dispatches",
                  "config"),
        bounds=(("n_errors", "==", 0), ("sessions", ">=", 64)),
        group="serve", regress=("latency_ms.p99", "lower", 0.25),
        note="pre-warm-pool capture kept as the r09 improvement baseline"),
    Contract(
        pattern="BENCH_SERVE_*.json", kind="serve_loadgen",
        checker=serve_check_report,
        group="serve", regress=("latency_ms.p99", "lower", 0.25)),
    # -- replicated serve fleet (router + rolling restart) --
    Contract(
        pattern="BENCH_FLEET_*.json", kind="serve_fleet",
        required=("bench", "mode", "sessions", "wall_s", "n_errors",
                  "latency_ms", "requests_per_s", "fleet", "aggregate",
                  "config"),
        checker=fleet_check_report, fingerprint="required",
        group="fleet",
        regress=("requests_per_s", "higher", 0.25),
        note="N serve replicas behind the rendezvous router: zero-drop "
             "rolling restart of every replica, digest-verified "
             "migrations, span-attributed router latency, near-linear "
             "scaling (or documented 1-core parity)"),
    # -- tiered posterior state (hot/warm/cold paging) --
    Contract(
        pattern="BENCH_TIERED_*.json", kind="serve_tiered",
        required=("bench", "mode", "sessions", "wall_s", "n_errors",
                  "latency_ms", "server", "config", "tiering"),
        checker=tiered_check_report, fingerprint="required",
        group="tiered",
        regress=("tiering.wake_latency.p99_ms", "lower", 0.5),
        note="≥100k open sessions via hot/warm/cold paging: RSS bound, "
             "hot-set residency, wake-from-warm p99 under one tick"),
    # -- suite sweeps --
    Contract(
        pattern="BENCH_SUITE_*.json", kind="bench_suite",
        required=("metric", "value", "total_wall", "pairs",
                  "per_method_s"),
        bounds=(("value", ">", 0), ("pairs", "truthy", None)),
        group="suite", regress=("value", "lower", 0.25)),
    # -- bench.py headline captures --
    Contract(
        pattern="BENCH_TPU_HEADLINE_*.json", kind="bench_headline",
        required=("metric", "value", "unit", "timing.linearity.ok",
                  "compute.eig_mode", "devices.device_kind"),
        bounds=(("value", ">", 0), ("timing.linearity.ok", "==", True)),
        group="headline", regress=("value", "higher", 0.25)),
    Contract(
        pattern="BENCH_LOCAL_r03.json", kind="bench_headline",
        required=("metric", "value", "unit", "timing.linearity.ok",
                  "compute.eig_mode", "devices.device_kind"),
        bounds=(("value", ">", 0), ("timing.linearity.ok", "==", True)),
        group="headline", regress=("value", "higher", 0.25)),
    Contract(
        pattern="BENCH_CPU_SAMEHW_r03.json", kind="bench_samehw",
        required=("metric", "value", "unit", "vs_baseline",
                  "matched_linearity_ok", "compute", "devices"),
        bounds=(("value", ">", 0), ("matched_linearity_ok", "==", True),
                ("vs_baseline", ">=", 1.0)),
        note="same-hardware CPU comparison vs the PyTorch reference"),
    # -- batched top-q acquisition --
    Contract(
        pattern="BENCH_BATCHQ_*.json", kind="batchq",
        required=("bench", "wall_s", "config", "digits.label_budget",
                  "digits.per_q", "digits.envelope.ok",
                  "imagenet.q1.round_s_marginal",
                  "imagenet.labels_per_s_speedup",
                  "labels_per_s_speedup", "regret_envelope_ok",
                  "replays_verified", "divergences_triaged", "ok"),
        bounds=(("ok", "==", True),
                ("regret_envelope_ok", "==", True),
                ("replays_verified", "==", True),
                ("divergences_triaged", "==", True)),
        checker=batchq_check_report, fingerprint="required",
        group="batchq",
        regress=("labels_per_s_speedup", "higher", 0.25),
        note="q oracle labels per round: labels/s speedup >= 0.6*q at "
             "q=8 on the imagenet preset, real-digits regret within the "
             "declared envelope of q=1, divergences replay-triaged"),
    # -- contract-gated EIG surrogate --
    Contract(
        pattern="BENCH_SURROGATE_*.json", kind="surrogate",
        required=("bench", "wall_s", "config", "digits.label_budget",
                  "digits.exact.final_cum_regret_mean",
                  "digits.surrogate.final_cum_regret_mean",
                  "digits.against_exact.classification",
                  "imagenet.scoring_pass_speedup",
                  "imagenet.round_s_marginal",
                  "imagenet.fallback_rate_post_warmup",
                  "round_s_marginal", "default_exact_pin.parity",
                  "regret_envelope_ok", "replays_verified", "ok"),
        bounds=(("ok", "==", True),
                ("regret_envelope_ok", "==", True),
                ("replays_verified", "==", True),
                ("imagenet.scoring_pass_speedup", ">=",
                 SURROGATE_MIN_SCORE_SPEEDUP)),
        checker=surrogate_check_report, fingerprint="required",
        group="surrogate",
        regress=("round_s_marginal", "lower", 0.5),
        note="learned score amortization under the measured contract: "
             "scoring-pass speedup >= 3x at the imagenet preset, digits "
             "regret envelope vs exact held, post-warmup fallback rate "
             "<= 10%, default exact bitwise-pinned via cli replay "
             "--against"),
    # -- cross-session surrogate priors --
    Contract(
        pattern="BENCH_PRIOR_*.json", kind="prior",
        required=("bench", "wall_s", "config", "digits.label_budget",
                  "digits.cold.final_cum_regret_mean",
                  "digits.seeded.final_cum_regret_mean",
                  "digits.against_cold.classification",
                  "warmup.cold_exact_rounds",
                  "warmup.seeded_exact_rounds", "warmup.reduction",
                  "audit.unaudited_argmax_picks",
                  "gate_rejection.prior_rejects",
                  "gate_rejection.fell_back_exact",
                  "off_parity.parity",
                  "regret_envelope_ok", "replays_verified", "ok"),
        bounds=(("ok", "==", True),
                ("regret_envelope_ok", "==", True),
                ("replays_verified", "==", True),
                ("audit.unaudited_argmax_picks", "==", 0),
                ("warmup.reduction", ">=", PRIOR_MIN_WARMUP_REDUCTION)),
        checker=prior_check_report, fingerprint="required",
        group="prior",
        regress=("warmup.reduction", "higher", 0.5),
        note="fleet-amortized surrogate priors (ISSUE 18): a pool-"
             "seeded session pays >= 3x fewer exact warmup rounds, "
             "digits regret within the surrogate envelope of the cold "
             "run, zero unaudited argmax picks, hostile priors rejected "
             "by the per-round gate, off bitwise-pinned to PR 14 via "
             "cli replay --against --score-tol 0"),
    # -- recorder overhead --
    Contract(
        pattern="BENCH_RECORDER_*.json", kind="recorder_overhead",
        required=("metric", "bound", "configs"),
        bounds=(("bound", "<=", 0.05),),
        checker=_recorder_check),
    # -- true-size AOT capture --
    Contract(
        pattern="BENCH_TPU_TRUESIZE_*.json", kind="truesize",
        required=("task", "device", "configs", "ok"),
        bounds=(("ok", "==", True),)),
    # -- the r01-r05 driver-wrapped bench lines --
    Contract(
        pattern="BENCH_r0[1-5].json", kind="bench_wrapped",
        required=("cmd", "rc", "parsed"),
        bounds=(("rc", "==", 0),),
        checker=_wrapped_bench_check,
        note="driver-wrapped early-round bench lines"),
    # -- ImageNet-scale virtual-mesh captures --
    Contract(
        pattern="IMAGENET_VIRTUAL_*.json", kind="imagenet_virtual",
        required=("config", "devices", "tiers", "ok"),
        bounds=(("ok", "==", True),),
        note="dense-tier execution check at C=1000/H=500 (r05: the "
             "committed baseline the sparse capture improves on)"),
    Contract(
        pattern="IMAGENET_SPARSE_*.json", kind="imagenet_sparse",
        required=("config", "mesh", "shape.C", "shape.H",
                  "baseline.round_s", "sparse.wall_s", "sparse.finite",
                  "dense_ref.wall_s", "round_s_marginal",
                  "round_time_reduction_vs_r05",
                  "state.dense_posterior_bytes",
                  "state.sparse_posterior_bytes", "state.bytes_ratio",
                  "replay.max_abs_dscore", "replay.score_tol", "ok"),
        bounds=(("ok", "==", True),
                ("round_time_reduction_vs_r05", ">=",
                 IMAGENET_SPARSE_MIN_SPEEDUP),
                ("state.bytes_ratio", ">=",
                 IMAGENET_SPARSE_MIN_BYTES_RATIO),
                ("replay.max_abs_dscore", "<=",
                 IMAGENET_SPARSE_SCORE_TOL)),
        checker=_imagenet_sparse_check, fingerprint="required",
        group="imagenet_sparse",
        regress=("round_s_marginal", "lower", 0.5),
        note="sparse:K posterior at the r05 pool shape — round time, "
             "state bytes, and the replay-triaged score contract"),
    # -- crowd-oracle robustness matrix (ISSUE 16) --
    Contract(
        pattern="ROBUSTNESS_*.json", kind="oracle_robustness",
        required=("bench", "fingerprint.backend", "clean.parity",
                  "noisy.max_final_ratio", "reliability.corr",
                  "reliability.mae", "async.digest_match",
                  "async.lost", "async.double_applied", "ok"),
        bounds=(("bench", "==", "oracle_robustness"),
                ("ok", "==", True),
                ("clean.parity", "==", True),
                ("clean.replay_rc", "==", 0),
                ("clean.against_rc", "==", 0),
                ("async.lost", "==", 0),
                ("async.double_applied", "==", 0),
                ("async.n_errors", "==", 0)),
        checker=robustness_check_report, fingerprint="required",
        group="robustness",
        note="crowd-oracle matrix (ISSUE 16): clean-config bitwise "
             "parity, noisy regret envelope, Dawid-Skene recovery of "
             "the planted pool, async out-of-order delivery digest-"
             "equivalent with 0 lost/double-applied labels"),
    # -- fault matrices (recovery claims are gated artifacts too) --
    Contract(
        pattern="FAULT_MATRIX_FLEET_*.json", kind="fault_matrix_fleet",
        required=("bench", "fingerprint.backend", "scenarios",
                  "summary.scenarios", "summary.migrations",
                  "summary.migration_verified"),
        bounds=(("bench", "==", "fault_matrix_fleet"),
                ("summary.clean", "==", True),
                ("summary.violations", "==", 0),
                ("summary.dropped_sessions", "==", 0),
                ("summary.double_applied_labels", "==", 0)),
        checker=fleet_matrix_check, fingerprint="required",
        group="fault_matrix",
        note="fleet chaos matrix (ISSUE 14): epoch fencing, journal "
             "recovery at every phase, kill-mid-migration, healthz-flap "
             "hysteresis, transport chaos, partition+heal — all clean"),
    Contract(
        pattern="FAULT_MATRIX_*.json", kind="fault_matrix_legacy",
        checker=legacy_matrix_check, fingerprint="grandfathered",
        note="single-replica recovery matrix (r10/r13 layout: "
             "{scenario: violations}, committed clean)"),
    # -- fleet observability (distributed tracing + SLO watchtower) --
    Contract(
        pattern="OBS_FLEET_*.json", kind="serve_obs",
        required=("bench", "fingerprint.backend", "n_errors",
                  "fleet.chaos_pass.tracing.completeness",
                  "migration_trace.processes", "bitwise.identical",
                  "overhead.overhead_frac", "slo.fired", "slo.cleared",
                  "slo.persisted_both"),
        bounds=(("bench", "==", "bench_obs"), ("n_errors", "==", 0)),
        checker=obs_check_report, fingerprint="required",
        group="obs",
        regress=("overhead.overhead_frac", "lower", 1.0),
        note="fleet tracing + SLO watchtower (ISSUE 19): every sampled "
             "trace complete through the cross-process stitcher under "
             "chaos AND through a rolling restart (span adoption), one "
             "trace spanning a mid-session migration across both "
             "replicas' lanes, /metrics exemplars joinable, decision "
             "stream bitwise-identical with tracing on vs off, <= 5% "
             "overhead, burn-rate alert fired AND cleared on an "
             "injected slow_step tail with both transitions persisted "
             "to the tracking store"),
    # -- decision-quality plane (shadow audit + calibration + drift SLO) --
    Contract(
        pattern="QUALITY_*.json", kind="serve_quality",
        required=("bench", "fingerprint.backend",
                  "clean_fleet.audits_total",
                  "clean_fleet.divergences_total",
                  "tamper.attributed_session", "tamper.attributed_round",
                  "calibration.pooled.ece", "slo.fired", "slo.cleared",
                  "slo.persisted_both", "bitwise.identical",
                  "overhead.overhead_frac"),
        bounds=(("bench", "==", "bench_quality"),
                ("clean_fleet.divergences_total", "==", 0)),
        checker=quality_check_report, fingerprint="required",
        group="quality",
        regress=("overhead.overhead_frac", "lower", 1.0),
        note="decision-quality plane (ISSUE 20): every shadow-audited "
             "session replay bitwise-identical on a clean chaos fleet "
             "(0 divergences), an injected single-ulp stream tamper "
             "detected and attributed to the exact session and round, "
             "ground-truth P(best) calibration with a finite ECE, the "
             "quality_drift burn-rate alert fired AND cleared with both "
             "transitions read back from the tracking store, decision "
             "rows bitwise-identical with the plane on vs off, <= 5% "
             "overhead"),
    # -- one-run evidence manifests --
    Contract(
        pattern="EVIDENCE_*.json", kind="evidence_manifest",
        required=("schema_version", "round", "backend",
                  "fingerprint.backend", "artifacts"),
        bounds=(("schema_version", "==", EVIDENCE_SCHEMA_VERSION),),
        checker=_evidence_check, fingerprint="required",
        group="evidence",
        regress=("artifacts.serve_loadgen.report.latency_ms.p99",
                 "lower", 0.5)),
)


def match_contract(path: str) -> Optional[Contract]:
    base = os.path.basename(path)
    for c in CONTRACTS:
        if fnmatch.fnmatch(base, c.pattern):
            return c
    return None


# ---------------------------------------------------------------------------
# fingerprint policy + comparability key
# ---------------------------------------------------------------------------

def _fingerprint_of(report: dict) -> Optional[dict]:
    fp = report.get("fingerprint")
    return fp if isinstance(fp, dict) else None


def fingerprint_violations(path: str, report: dict,
                           contract: Contract,
                           notes: Optional[list] = None) -> list[str]:
    """Apply the contract's fingerprint policy; grandfather notes (the
    explicit ``fingerprint: null`` record, never silence) land in
    ``notes``."""
    fp = _fingerprint_of(report)
    policy = contract.fingerprint
    if policy == "auto":
        rnd = artifact_round(path)
        policy = ("grandfathered"
                  if rnd is not None and rnd < FINGERPRINT_REQUIRED_ROUND
                  else "required")
    if fp is None:
        if policy == "required":
            return ["missing environment fingerprint (artifacts from "
                    f"r{FINGERPRINT_REQUIRED_ROUND} on must stamp "
                    "telemetry.recorder.environment_fingerprint)"]
        if notes is not None:
            notes.append(f"{os.path.basename(path)}: fingerprint: null "
                         "(grandfathered pre-"
                         f"r{FINGERPRINT_REQUIRED_ROUND} artifact)")
        return []
    if not fp.get("backend"):
        return ["fingerprint present but carries no backend"]
    return []


def fingerprint_key(report: dict) -> Optional[tuple]:
    """Cross-round comparability key: same environment AND same capture
    knobs. Two artifacts compare only when both carry a fingerprint and
    these match — a quick capture never gates a full one, and a jax/
    jaxlib upgrade breaks comparability (the same environment axes
    ``capture_evidence.py`` verifies components against)."""
    fp = _fingerprint_of(report)
    if fp is None:
        return None
    knobs = fp.get("knobs") or {}
    digest = hashlib.sha256(
        json.dumps(knobs, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]
    return (fp.get("backend"), fp.get("device_kind"),
            fp.get("jax_version"), fp.get("jaxlib_version"),
            bool(fp.get("threefry_partitionable")),
            bool(fp.get("x64")), digest)


# ---------------------------------------------------------------------------
# checking
# ---------------------------------------------------------------------------

def check_artifact(path: str, report: dict, contract: Contract,
                   notes: Optional[list] = None) -> list[str]:
    """All violations of one artifact against its contract."""
    out: list[str] = []
    if not isinstance(report, dict):
        return ["artifact is not a JSON object"]
    for dotted in contract.required:
        found, value = get_path(report, dotted)
        if not found or value is None:
            out.append(f"missing required field {dotted!r}")
    for dotted, op, bound in contract.bounds:
        found, value = get_path(report, dotted)
        if not found or value is None:
            out.append(f"bound field {dotted!r} missing")
            continue
        try:
            ok = _OPS[op](value, bound)
        except TypeError:
            ok = False
        if not ok:
            out.append(f"{dotted} = {value!r} violates committed bound "
                       f"'{op} {bound}'" if op != "truthy"
                       else f"{dotted} = {value!r} is empty/false")
    if contract.checker is not None:
        out += contract.checker(report)
    out += fingerprint_violations(path, report, contract, notes)
    return out


def cross_round_violations(artifacts: list, notes: Optional[list] = None
                           ) -> list[str]:
    """Same-group, same-fingerprint round-over-round regression check.

    ``artifacts``: (path, report, contract) triples. Within each contract
    group, artifacts sharing a :func:`fingerprint_key` are ordered by
    their filename round and each consecutive pair is compared on the
    group's regression metric with its explicit relative tolerance.
    """
    out: list[str] = []
    by_key: dict = {}
    for path, report, contract in artifacts:
        if contract.group is None or contract.regress is None:
            continue
        rnd = artifact_round(path)
        fkey = fingerprint_key(report)
        if rnd is None or fkey is None:
            continue  # fingerprint-less artifacts never compare (by design)
        by_key.setdefault((contract.group, fkey), []).append(
            (rnd, path, report, contract))
    for (group, _), rows in sorted(by_key.items()):
        rows.sort(key=lambda r: r[0])
        for (r_old, p_old, rep_old, c_old), (r_new, p_new, rep_new, c_new) \
                in zip(rows, rows[1:]):
            metric, direction, tol = c_new.regress
            f_old, v_old = get_path(rep_old, metric)
            f_new, v_new = get_path(rep_new, metric)
            if not (f_old and f_new) or not all(
                    isinstance(v, (int, float)) for v in (v_old, v_new)):
                continue
            if direction == "lower":
                bad = v_new > v_old * (1.0 + tol)
            else:
                bad = v_new < v_old * (1.0 - tol)
            if bad:
                out.append(
                    f"{os.path.basename(p_new)}: {metric} = {v_new:g} "
                    f"regressed vs r{r_old:02d}'s {v_old:g} beyond the "
                    f"{tol:.0%} tolerance ({group} group, "
                    f"{'lower' if direction == 'lower' else 'higher'}-is-"
                    "better, same fingerprint)")
            elif notes is not None:
                notes.append(
                    f"{os.path.basename(p_new)}: {metric} {v_old:g} -> "
                    f"{v_new:g} vs r{r_old:02d} (within {tol:.0%})")
    return out


def discover(root: str) -> list[str]:
    """The gated artifact set at one repo root."""
    paths = []
    for pat in ("BENCH_*.json", "EVIDENCE_*.json", "IMAGENET_*.json",
                "FAULT_MATRIX_*.json", "OBS_*.json", "QUALITY_*.json",
                "ROBUSTNESS_*.json"):
        paths += glob.glob(os.path.join(root, pat))
    return sorted(paths)


def check_root(root: str, notes: Optional[list] = None,
               family: Optional[str] = None) -> list[str]:
    """Gate every committed artifact at ``root``: per-artifact contracts,
    contract coverage (an unregistered BENCH_/EVIDENCE_ file fails), and
    the cross-round regression comparison. ``family`` restricts to one
    contract group (coverage of other files is then not checked)."""
    out: list[str] = []
    triples = []
    for path in discover(root):
        base = os.path.basename(path)
        contract = match_contract(path)
        if contract is None:
            if family is None:
                out.append(f"{base}: no contract entry in "
                           "scripts/check_perf.py (new artifacts must "
                           "declare their claim — add a Contract for "
                           "this file)")
            continue
        if family is not None and contract.group != family:
            continue
        try:
            with open(path) as f:
                report = json.load(f)
        except Exception as e:
            out.append(f"{base}: unreadable: {e}")
            continue
        out += [f"{base}: {v}"
                for v in check_artifact(path, report, contract, notes)]
        triples.append((path, report, contract))
    out += cross_round_violations(triples, notes)
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    family = None
    if "--family" in argv:
        i = argv.index("--family")
        try:
            family = argv[i + 1]
        except IndexError:
            print("--family needs a contract group name (serve, batchq, "
                  "suite, ...)")
            return 64
        del argv[i:i + 2]
        groups = {c.group for c in CONTRACTS if c.group}
        if family not in groups:
            print(f"unknown family {family!r}; known: {sorted(groups)}")
            return 64
    notes: list = []
    if argv:
        bad = 0
        for path in argv:
            contract = match_contract(path)
            if contract is None:
                print(f"{path}: no contract entry matches this filename")
                bad += 1
                continue
            if family is not None and contract.group != family:
                print(f"{path}: contract group {contract.group!r} != "
                      f"requested family {family!r}")
                bad += 1
                continue
            try:
                with open(path) as f:
                    report = json.load(f)
            except Exception as e:
                print(f"{path}: unreadable: {e}")
                bad += 1
                continue
            for v in check_artifact(path, report, contract, notes):
                print(f"{path}: {v}")
                bad += 1
        for n in notes:
            print(f"note: {n}")
        if bad:
            print(f"perf gate FAILED: {bad} violation(s)")
            return 1
        print(f"perf gate clean: {len(argv)} artifact(s)")
        return 0
    violations = check_root(repo, notes, family=family)
    for n in notes:
        print(f"note: {n}")
    for v in violations:
        print(v)
    scope = f" ({family} family)" if family else ""
    n_artifacts = len(discover(repo))
    if violations:
        print(f"perf gate FAILED: {len(violations)} violation(s)"
              f"{scope}")
        return 1
    print(f"perf gate clean{scope}: {n_artifacts} committed artifact(s) "
          "discovered, every gated claim declared and within bounds")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
