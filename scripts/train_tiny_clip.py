"""Train REAL (tiny) CLIP checkpoints offline and render the digit images.

The reference's model pool is built by running pretrained HF zero-shot
models over an image folder (reference ``demo/hf_zeroshot.py:170-219``).
This environment has zero egress — no pretrained checkpoint is fetchable —
so this script produces the same *kind* of artifact from first principles:

  * renders sklearn's bundled NIST digits (real 8x8 scans) to PNG files,
    split exactly like ``scripts/make_real_task.py`` (same
    ``train_test_split(test_size=0.5, random_state=0, stratify)``), so the
    eval images are the same 899 points the ``digits`` task scores;
  * builds a genuine ``transformers.CLIPModel`` (2-layer ViT over 32x32
    renders + 2-layer text transformer, BPE tokenizer trained on the
    caption template) and trains it CONTRASTIVELY on the train-half
    captions ``"This is a photo of <digit>."`` — the standard CLIP
    objective, one image per class per batch so the in-batch negatives are
    clean;
  * saves each variant as a complete HF checkpoint directory
    (config + safetensors + processor + tokenizer) that
    ``transformers.pipeline("zero-shot-image-classification", model=dir)``
    loads exactly like a hub checkpoint — which is how
    ``demo/hf_zeroshot.py``'s ``_hf_pipeline_scorer`` then consumes it.

The variants span a real accuracy range (well-trained / second seed /
undertrained), giving the assembled pool genuine model-selection structure.

Usage:
  python scripts/train_tiny_clip.py                 # checkpoints + images
  python demo/hf_zeroshot.py --images-dir demo/digit_images \
      --classes 0 1 2 3 4 5 6 7 8 9 \
      --models demo/models/tiny-clip-a demo/models/tiny-clip-b \
               demo/models/tiny-clip-under --out data/digits_clip
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

TEMPLATE = "This is a photo of {}."
CLASSES = [str(d) for d in range(10)]


def digit_split(seed: int = 0, test_frac: float = 0.5):
    """The digits task's split, via the ONE shared helper
    (scripts/make_real_task.py::stratified_split) so the pool tensors and
    the rendered images can never desynchronize."""
    import sklearn.datasets

    from scripts.make_real_task import stratified_split

    data = sklearn.datasets.load_digits()
    x_tr, x_ev, y_tr, y_ev, i_tr, i_ev = stratified_split(
        data.data.astype(np.float32), data.target, test_frac, seed)
    return (x_tr, y_tr, i_tr), (x_ev, y_ev, i_ev)


def render_png(vec8x8: np.ndarray, path: str, upscale: int = 4) -> None:
    """One 64-dim digits row (0..16 ints) -> a 32x32 grayscale PNG."""
    from PIL import Image

    img = (vec8x8.reshape(8, 8) / 16.0 * 255.0).astype(np.uint8)
    Image.fromarray(img, mode="L").resize(
        (8 * upscale, 8 * upscale), Image.NEAREST
    ).save(path)


def render_eval_images(out_dir: str) -> tuple[list[str], np.ndarray]:
    """All eval-half digits as PNGs named by eval position (stable order:
    ``list_images`` sorts lexicographically, so zero-padded names keep the
    npz row order == filename order invariant the demo relies on)."""
    (_, _, _), (x_ev, y_ev, _) = digit_split()
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    # render UNCONDITIONALLY: a skip-if-exists here would pair stale pixels
    # with a freshly rewritten labels.npy after any split change — the
    # silent image/label desync the pool's length guard cannot catch
    for n, vec in enumerate(x_ev):
        p = os.path.join(out_dir, f"digit_{n:04d}.png")
        render_png(vec, p)
        paths.append(p)
    return paths, y_ev


def build_tokenizer(save_dir: str):
    """A real BPE tokenizer over the caption charset, CLIP-style specials."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from tokenizers.processors import TemplateProcessing
    from transformers import PreTrainedTokenizerFast

    corpus = [TEMPLATE.format(c) for c in CLASSES] + CLASSES
    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        vocab_size=128, special_tokens=["<unk>"],
    )
    tok.train_from_iterator(corpus, trainer)
    # bos/eos are appended AFTER training so eos gets the LARGEST vocab id:
    # CLIPTextModel's legacy pooling branch (eos_token_id == 2 checkpoints)
    # pools at input_ids.argmax(-1) — the original CLIP vocab kept eos as
    # the max id — and the modern branch searches for eos_token_id; putting
    # eos last satisfies both, otherwise the pooled feature reads a
    # constant mid-sentence token and every caption embeds identically
    # (loss freezes at ln C).
    tok.add_special_tokens(["<|startoftext|>", "<|endoftext|>"])
    bos = tok.token_to_id("<|startoftext|>")
    eos = tok.token_to_id("<|endoftext|>")
    assert eos == tok.get_vocab_size() - 1
    tok.post_processor = TemplateProcessing(
        single="<|startoftext|> $A <|endoftext|>",
        special_tokens=[("<|startoftext|>", bos), ("<|endoftext|>", eos)],
    )
    # the generic fast-tokenizer wrapper: CLIPTokenizerFast rejects any
    # backend that isn't byte-level-BPE-converted from the original
    # checkpoint format, but the pipeline only needs AutoTokenizer to
    # produce input_ids ending in eos (the position CLIP's text pooler
    # reads) — which the TemplateProcessing above guarantees
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        bos_token="<|startoftext|>", eos_token="<|endoftext|>",
        unk_token="<unk>", pad_token="<|endoftext|>",
        model_max_length=16,
        # CLIPModel.forward has no token_type_ids; the generic wrapper
        # would emit them and break the pipeline call
        model_input_names=["input_ids", "attention_mask"],
    )
    fast.save_pretrained(save_dir)
    return fast


def build_model(tokenizer, vision_layers: int, seed: int):
    import torch
    from transformers import CLIPConfig, CLIPModel

    torch.manual_seed(seed)
    vocab = len(tokenizer)  # INCLUDING post-train added specials (bos/eos)
    cfg = CLIPConfig.from_text_vision_configs
    from transformers import CLIPTextConfig, CLIPVisionConfig

    text_cfg = CLIPTextConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=16,
        bos_token_id=tokenizer.bos_token_id,
        eos_token_id=tokenizer.eos_token_id,
        pad_token_id=tokenizer.pad_token_id,
    )
    vision_cfg = CLIPVisionConfig(
        image_size=32, patch_size=8, hidden_size=64, intermediate_size=128,
        num_hidden_layers=vision_layers, num_attention_heads=2,
        num_channels=3,
    )
    config = cfg(text_cfg, vision_cfg, projection_dim=32)
    return CLIPModel(config)


def make_processor(save_dir: str):
    from transformers import CLIPImageProcessor

    proc = CLIPImageProcessor(
        size={"shortest_edge": 32}, crop_size={"height": 32, "width": 32},
        do_resize=True, do_center_crop=True, do_normalize=True,
        image_mean=[0.5, 0.5, 0.5], image_std=[0.5, 0.5, 0.5],
    )
    proc.save_pretrained(save_dir)
    return proc


def train_variant(
    name: str,
    out_root: str,
    steps: int,
    vision_layers: int,
    seed: int,
    lr: float = 1e-3,  # 3e-3 collapses this scale to the uniform optimum
) -> dict:
    """Contrastive training of one checkpoint; returns eval metadata."""
    import torch
    from PIL import Image

    save_dir = os.path.join(out_root, name)
    # gate the resume on train_meta.json — it is written LAST, so a run
    # interrupted after save_pretrained but before the meta write retrains
    # instead of crashing on the missing meta
    if os.path.exists(os.path.join(save_dir, "train_meta.json")):
        print(f"[train] {name}: exists, skipping")
        with open(os.path.join(save_dir, "train_meta.json")) as f:
            return json.load(f)

    (x_tr, y_tr, _), (x_ev, y_ev, _) = digit_split()
    tokenizer = build_tokenizer(save_dir)
    processor = make_processor(save_dir)
    model = build_model(tokenizer, vision_layers, seed)

    # precompute pixel_values once (PIL path == exactly what the pipeline
    # does at inference: 8x8 -> 32x32 nearest, L->RGB, normalize)
    def to_pixels(rows: np.ndarray) -> "torch.Tensor":
        imgs = []
        for vec in rows:
            a = (vec.reshape(8, 8) / 16.0 * 255.0).astype(np.uint8)
            imgs.append(
                Image.fromarray(a, "L").resize((32, 32), Image.NEAREST)
                .convert("RGB")
            )
        return processor(images=imgs, return_tensors="pt")["pixel_values"]

    pix_tr = to_pixels(x_tr)
    captions = [TEMPLATE.format(c) for c in CLASSES]
    text = tokenizer(captions, padding=True, return_tensors="pt")

    rng = np.random.default_rng(seed)
    by_class = [np.flatnonzero(y_tr == c) for c in range(10)]
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    model.train()
    for step in range(steps):
        # one random image per class: 10 clean in-batch negatives
        batch_idx = np.array([rng.choice(ix) for ix in by_class])
        out = model(
            input_ids=text["input_ids"],
            attention_mask=text["attention_mask"],
            pixel_values=pix_tr[batch_idx],
            return_loss=True,
        )
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        if step % 200 == 0:
            print(f"[train] {name} step {step}: loss {out.loss.item():.4f}")

    # zero-shot eval on the eval half (the same math the pipeline runs)
    model.eval()
    with torch.no_grad():
        tfeat = model.get_text_features(
            input_ids=text["input_ids"],
            attention_mask=text["attention_mask"],
        )
        tfeat = tfeat / tfeat.norm(dim=-1, keepdim=True)
        correct = 0
        for lo in range(0, len(x_ev), 256):
            ifeat = model.get_image_features(
                pixel_values=to_pixels(x_ev[lo:lo + 256]))
            ifeat = ifeat / ifeat.norm(dim=-1, keepdim=True)
            pred = (ifeat @ tfeat.T).argmax(-1).numpy()
            correct += int((pred == y_ev[lo:lo + 256]).sum())
    acc = correct / len(y_ev)

    model.save_pretrained(save_dir)
    meta = {"name": name, "steps": steps, "vision_layers": vision_layers,
            "seed": seed, "zero_shot_eval_acc": acc}
    with open(os.path.join(save_dir, "train_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    print(f"[train] {name}: zero-shot eval acc {acc:.4f} -> {save_dir}")
    return meta


VARIANTS = [
    # (name, steps, vision_layers, seed): a real accuracy spread
    ("tiny-clip-a", 4000, 2, 0),
    ("tiny-clip-b", 4000, 3, 1),
    ("tiny-clip-under", 250, 2, 2),
]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-root", default=os.path.join(REPO, "demo", "models"))
    ap.add_argument("--images-dir",
                    default=os.path.join(REPO, "demo", "digit_images"))
    args = ap.parse_args(argv)

    paths, y_ev = render_eval_images(args.images_dir)
    print(f"[images] {len(paths)} eval PNGs in {args.images_dir}")
    np.save(os.path.join(args.images_dir, "labels.npy"), y_ev)

    metas = [train_variant(n, args.out_root, s, vl, sd)
             for n, s, vl, sd in VARIANTS]
    print(json.dumps(metas, indent=1))


if __name__ == "__main__":
    main()
