"""Static perf-regression gate for the committed serve benchmark.

The serve tail-latency claim (ISSUE 6 / ROADMAP "Serve tail latency") is
backed by one committed artifact, ``BENCH_SERVE_CPU_r09.json`` — a
container-loadgen capture at >= 256 concurrent sessions. Like
``check_record_schema.py`` gates the flight-recorder schema, this checker
keeps that artifact honest: a regenerated bench that silently lost the
breakdown section, ran fewer sessions, recorded errors, or regressed past
the committed latency bounds fails tier-1 instead of drifting.

Two kinds of checks:

  * **schema** — the fields the claim is made of must exist: mode/
    transport, session count, error count, client p50/p99, the server
    dispatch metrics, the queue-wait / dispatch / step breakdown (the
    span-by-span p99 attribution), and the warm-pool evidence;
  * **bounds** — committed thresholds: 0 errors, >= MIN_SESSIONS
    concurrent sessions, p99 <= P99_MS_MAX (the >= 10x-vs-r06 contract
    with headroom for container noise), p50 <= P50_MS_MAX, and a fully
    warm pool (0 lazy-jit dispatch misses).

Runnable standalone::

    python scripts/check_serve_bench.py [BENCH_SERVE_CPU_r09.json ...]
"""

from __future__ import annotations

import json
import os
import sys

# committed thresholds for BENCH_SERVE_CPU_r09.json (1-core CPU container,
# 256 sessions, synthetic 8,512,10, coda). The r06 baseline this gates the
# improvement against: p99 = 5587.7 ms at 64 sessions.
R06_P99_MS = 5587.7
MIN_IMPROVEMENT = 10.0          # the acceptance contract: >= 10x vs r06
MIN_SESSIONS = 256
P99_MS_MAX = R06_P99_MS / MIN_IMPROVEMENT   # = 558.8 ms
P50_MS_MAX = 420.0              # ~one slab step + formation, with headroom

_REQUIRED = (
    "bench", "mode", "transport", "sessions", "labels_per_session",
    "wall_s", "sessions_per_s", "requests_per_s", "latency_ms", "n_errors",
    "server", "breakdown", "warm_pool", "config",
)
_REQUIRED_SERVER = ("dispatches", "requests", "max_occupancy",
                    "mean_occupancy", "dispatch_latency", "request_latency")
_REQUIRED_BREAKDOWN = ("queue_wait", "dispatch", "step", "spans")


def check_report(report: dict) -> list[str]:
    """Violations of one serve-bench report dict (empty = clean)."""
    out: list[str] = []
    for key in _REQUIRED:
        if key not in report:
            out.append(f"missing field {key!r}")
    if out:
        return out  # field-dependent checks below would just cascade
    if report["bench"] != "serve_loadgen":
        out.append(f"bench {report['bench']!r} != 'serve_loadgen'")
    for key in _REQUIRED_SERVER:
        if report["server"].get(key) is None:
            out.append(f"server.{key} missing/null")
    for key in _REQUIRED_BREAKDOWN:
        if report["breakdown"].get(key) is None:
            out.append(f"breakdown.{key} missing/null (p99 attribution "
                       "must be mechanical)")
    p50 = (report["latency_ms"] or {}).get("p50")
    p99 = (report["latency_ms"] or {}).get("p99")
    if p50 is None or p99 is None:
        out.append("latency_ms.p50/p99 missing")
        return out
    # bounds: the committed claim
    if report["n_errors"] != 0:
        out.append(f"n_errors {report['n_errors']} != 0")
    if report["sessions"] < MIN_SESSIONS:
        out.append(f"sessions {report['sessions']} < {MIN_SESSIONS}")
    if p99 > P99_MS_MAX:
        out.append(f"p99 {p99:.1f} ms > {P99_MS_MAX:.1f} ms "
                   f"(the >= {MIN_IMPROVEMENT:.0f}x-vs-r06 bound)")
    if p50 > P50_MS_MAX:
        out.append(f"p50 {p50:.1f} ms > {P50_MS_MAX:.1f} ms")
    warm = report["warm_pool"] or {}
    if not warm.get("size"):
        out.append("warm_pool.size is 0/missing (AOT pool was not built)")
    if warm.get("misses"):
        out.append(f"warm_pool.misses {warm['misses']} != 0 "
                   "(a dispatch fell back to lazy jit)")
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = argv or [os.path.join(repo, "BENCH_SERVE_CPU_r09.json")]
    bad = 0
    for path in paths:
        try:
            with open(path) as f:
                report = json.load(f)
        except Exception as e:
            print(f"{path}: unreadable: {e}")
            bad += 1
            continue
        for v in check_report(report):
            print(f"{path}: {v}")
            bad += 1
    if bad:
        print(f"serve bench check FAILED: {bad} violation(s)")
        return 1
    print(f"serve bench check clean: {len(paths)} artifact(s), "
          f"p99 bound {P99_MS_MAX:.1f} ms at >= {MIN_SESSIONS} sessions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
