"""Thin shim: the serve-bench gate now lives in ``check_perf.py``.

The r09 serve contract (schema + 0 errors + >= 256 sessions + the
p99 <= 558.8 ms / 10x-vs-r06 bound + a fully warm AOT pool) is one entry
in the generalized committed-artifact perf gate
(``scripts/check_perf.py``), which gates EVERY ``BENCH_*``/``EVIDENCE_*``
artifact at the repo root. This file keeps the documented standalone
invocation working and re-exports the committed thresholds —
the schema/bounds logic itself is no longer duplicated here.

Runnable standalone::

    python scripts/check_serve_bench.py [BENCH_SERVE_CPU_r09.json ...]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from check_perf import (  # noqa: E402  (re-exports; the shim's surface)
    MIN_IMPROVEMENT,
    MIN_SESSIONS,
    P50_MS_MAX,
    P99_MS_MAX,
    R06_P99_MS,
    serve_check_report as check_report,
)

__all__ = ["R06_P99_MS", "MIN_IMPROVEMENT", "MIN_SESSIONS", "P99_MS_MAX",
           "P50_MS_MAX", "check_report", "main"]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = argv or [os.path.join(repo, "BENCH_SERVE_CPU_r09.json")]
    bad = 0
    for path in paths:
        try:
            with open(path) as f:
                report = json.load(f)
        except Exception as e:
            print(f"{path}: unreadable: {e}")
            bad += 1
            continue
        for v in check_report(report):
            print(f"{path}: {v}")
            bad += 1
    if bad:
        print(f"serve bench check FAILED: {bad} violation(s)")
        return 1
    print(f"serve bench check clean: {len(paths)} artifact(s), "
          f"p99 bound {P99_MS_MAX:.1f} ms at >= {MIN_SESSIONS} sessions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
