"""Benchmark sweep: every task x method, skipping already-finished runs.

Capability parity with reference ``scripts/launch_all_methods.py`` — the
SLURM job fan-out (one srun per task-method, <=32 concurrent, DB-checked
resume, hyperparams regex-decoded from the method *name*) — re-architected
for the TPU execution model:

  * seeds are already data-parallel inside one process (``vmap`` in the
    engine), so the unit of work stays one task-method *process*;
  * fan-out is a local process pool by default (``--max-concurrent``), with
    ``--launcher srun ...`` available to prefix an arbitrary cluster
    launcher, subsuming the reference's hard-coded srun invocation;
  * resume discipline is identical: a task-method is skipped when every
    needed seed-child run is FINISHED in the tracking DB (reference
    ``run_needed``/``seed_run_status``, ``:13-43``) — a deterministic
    (non-``stochastic``) seed-0 child also marks the run complete, mirroring
    the reference driver's early stop (reference ``main.py:128-130``).

Method-name hyperparameter encoding (reference ``:155-182``), e.g.
``coda-lr=0.01-mult=2.0-no-prefilter`` decodes to
``--learning-rate 0.01 --multiplier 2.0 --prefilter-n 0``.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
import time

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

from coda_tpu.data import list_tasks  # noqa: E402


def decode_method_hparams(method: str) -> list[str]:
    """Decode hyperparameters embedded in the method name into CLI flags."""
    flags: list[str] = []
    for pattern, flag in [
        (r"-lr=([0-9.]+)", "--learning-rate"),
        (r"-alpha=([0-9.]+)", "--alpha"),
        (r"-mult=([0-9.]+)", "--multiplier"),
        (r"-q=([a-z]+)", "--q"),
        (r"-prefilter=([0-9]+)", "--prefilter-n"),
    ]:
        m = re.search(pattern, method)
        if m:
            flags += [flag, m.group(1)]
    if "-no-prefilter" in method:
        flags += ["--prefilter-n", "0"]
    if "-no-diag" in method:
        flags += ["--no-diag-prior"]
    return flags


def run_needed(store, task: str, method: str, seeds: int) -> bool:
    """True unless every needed seed-child run is FINISHED (a deterministic
    finished seed 0 also counts as complete, like the reference driver's
    early stop)."""
    for s in range(seeds):
        run_name = f"{task}-{method}-{s}"
        found = store.find_run(task, run_name)
        if not found or found[1] != "FINISHED":
            return True
        rows = store.query(
            "SELECT value FROM params WHERE run_uuid=? AND key='stochastic'",
            (found[0],),
        )
        if rows and rows[0][0] == "False":
            return False  # deterministic: remaining seeds identical
    return False


def build_cmd(args, task: str, method: str) -> list[str]:
    cmd = list(args.launcher.split()) if args.launcher else []
    cmd += [
        sys.executable, os.path.join(REPO, "main.py"),
        "--task", task,
        "--method", method,
        "--data-dir", args.pred_dir,
        "--seeds", str(args.seeds),
        "--iters", str(args.iters),
        "--tracking-db", args.db,
    ]
    if args.platform:
        cmd += ["--platform", args.platform]
    if args.mesh:
        cmd += ["--mesh", args.mesh]
    cmd += decode_method_hparams(method)
    return cmd


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pred-dir", default="data")
    p.add_argument("--methods",
                   default="iid,activetesting,vma,model_picker,uncertainty,coda")
    p.add_argument("--tasks", default="all")
    p.add_argument("--seeds", type=int, default=5)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--max-concurrent", type=int, default=4,
                   help="concurrent task-method processes on this host")
    p.add_argument("--polling-interval", type=float, default=2.0)
    p.add_argument("--launcher", default=None,
                   help="optional launcher prefix, e.g. 'srun -p part --mem=64GB'")
    p.add_argument("--platform", default=None, help="forwarded to main.py")
    p.add_argument("--mesh", default=None, help="forwarded to main.py")
    p.add_argument("--dry-run", action="store_true",
                   help="print the job list and exit")
    args = p.parse_args(argv)

    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(args.db)
    tasks = (list_tasks(args.pred_dir) if args.tasks == "all"
             else args.tasks.split(","))
    methods = [m.strip() for m in args.methods.split(",") if m.strip()]

    queue: list[tuple[str, str, list[str]]] = []
    for task in tasks:
        for method in methods:
            if not run_needed(store, task, method, args.seeds):
                print(f"Skipping {task}/{method}; all seeds finished")
                continue
            queue.append((task, method, build_cmd(args, task, method)))

    if not queue:
        print("No jobs to run!")
        return 0
    print(f"{len(queue)} jobs, max {args.max_concurrent} concurrent")
    if args.dry_run:
        for task, method, cmd in queue:
            print(f"  {task}/{method}: {' '.join(cmd)}")
        return 0

    running: dict[int, tuple[str, str, subprocess.Popen]] = {}
    idx = n_failed = 0
    while idx < len(queue) or running:
        while idx < len(queue) and len(running) < args.max_concurrent:
            task, method, cmd = queue[idx]
            proc = subprocess.Popen(cmd)
            running[proc.pid] = (task, method, proc)
            print(f"Launched {task}/{method} (pid {proc.pid})")
            idx += 1
        time.sleep(args.polling_interval)
        for pid in [pid for pid, (_, _, pr) in running.items()
                    if pr.poll() is not None]:
            task, method, proc = running.pop(pid)
            status = "done" if proc.returncode == 0 else f"FAILED ({proc.returncode})"
            n_failed += proc.returncode != 0
            print(f"Job {task}/{method}: {status}")
        done = idx - len(running)
        print(f"Progress: {done}/{len(queue)} completed, "
              f"{len(running)} running, {len(queue) - idx} pending")
    print("All jobs completed!" + (f" ({n_failed} failed)" if n_failed else ""))
    return 1 if n_failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
