"""Batched top-q acquisition benchmark -> BENCH_BATCHQ_<backend>_rNN.json.

The ``--acq-batch q`` claim, measured and replay-verified (ISSUE 12):

  * **regret parity** (real-digits trace): the SAME label budget spent q
    at a time must land within a declared envelope of the q=1 protocol's
    cumulative regret — batching trades per-label adaptivity for oracle
    parallelism, and the greedy information-overlap penalty is what keeps
    that trade small. Each q's recorded run is self-replayed bitwise
    (``cli replay``), and every q > 1 record is compared against the q=1
    record through ``cli replay --against`` — the knob-diff path resolves
    to the label-aligned regret-envelope triage, and THOSE numbers are
    what the artifact commits.
  * **throughput** (the imagenet preset, C=1000/H=500/N=256,
    posterior=sparse:32): marginal round seconds at q=1 vs q=8, measured
    scan-only (init outside the timed region, warm compiled executions,
    min of reps), turned into oracle-answers/s. The committed floor:
    labels/s speedup ≥ 0.6·q at q=8 — a q-wide round may cost at most
    ~1.67× a single-label round, because it runs ONE scoring pass + ONE
    fused multi-row update instead of q of each.

Runnable standalone (CPU container ~4-6 min full, ~40 s quick)::

    python scripts/bench_batchq.py --out BENCH_BATCHQ_CPU_r14.json \
        --records-dir runs/batchq_r14
    python scripts/bench_batchq.py --quick   # digits q=4 + smoke preset

The finished artifact is self-gated against its ``check_perf.py``
contract before the script exits (a capture that violates its own
committed bounds must never be written silently).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the declared bounds are the GATE's, imported from the one place they
# are enforced (scripts/check_perf.py) so the generator can never embed
# envelope/speedup verdicts computed under stale thresholds:
#   ENVELOPE_RATIO/ABS — the regret-parity envelope on the real-digits
#   trace (label-weighted final cum regret at q may exceed q=1's by at
#   most ratio x + abs slack; the slack keeps near-zero regrets from
#   turning a 0.01-vs-0.02 difference into a 2x "violation");
#   SPEEDUP_FRAC — labels/s speedup >= frac * q.
from check_perf import (  # noqa: E402
    BATCHQ_ENVELOPE_ABS as ENVELOPE_ABS,
    BATCHQ_ENVELOPE_RATIO as ENVELOPE_RATIO,
    BATCHQ_SPEEDUP_FRAC as SPEEDUP_FRAC,
)


def _coda_factory(q_hint: int, seeds: int, posterior: str = "dense",
                  eig_chunk: int = 1024):
    from coda_tpu.selectors import CODAHyperparams, make_coda

    hp = CODAHyperparams(posterior=posterior, eig_chunk=eig_chunk,
                         n_parallel=max(1, seeds))
    return lambda preds: make_coda(preds, hp)


def _knobs(args, **extra) -> dict:
    base = {"bench": "batchq", "quick": bool(args.quick)}
    base.update(extra)
    return base


def _run_digits(args, fingerprint_holder: list) -> dict:
    """The regret-parity half: q ∈ {1, 4[, 8]} on the real-digits trace
    at one shared label budget, recorded + replay-verified."""
    import jax  # noqa: F401  (session init before timing)

    from coda_tpu.cli import load_dataset
    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.engine.replay import compare_records, verify_replay
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    ds = load_dataset(argparse.Namespace(
        task="digits", data_dir=args.data_dir, synthetic=None, mesh=None))
    labels_budget = 60 if args.quick else 120
    seeds = 2 if args.quick else 3
    qs = (1, 4) if args.quick else (1, 4, 8)
    records: dict = {}
    out: dict = {"task": ds.name, "shape": list(ds.shape),
                 "label_budget": labels_budget, "seeds": seeds,
                 "qs": list(qs), "per_q": {}}
    factory = _coda_factory(1, seeds)
    for q in qs:
        iters = labels_budget // q
        t0 = time.perf_counter()
        result, aux = run_seeds_recorded(
            factory, ds.preds, ds.labels, iters=iters, seeds=seeds,
            trace_k=8, cost_label=f"batchq_digits_q{q}", acq_batch=q)
        np.asarray(result.cumulative_regret)  # sync
        wall = time.perf_counter() - t0
        # the record's knobs must be the CLI knob set (KNOB_FIELDS):
        # `cli replay <dir>` rebuilds the selector FROM them, so a record
        # without `method` would replay the default method and report a
        # fake divergence
        knobs = _knobs(args, capture="digits", method="coda",
                       loss="acc", acq_batch=q, iters=iters, seeds=seeds,
                       n_parallel=seeds, eig_chunk=1024)
        fp = environment_fingerprint(dataset=ds, knobs=knobs)
        if not fingerprint_holder:
            # the artifact-level stamp: same environment, capture knobs
            # reduced to the run-independent subset
            fingerprint_holder.append(environment_fingerprint(
                dataset=ds, knobs=_knobs(args)))
        record = RunRecord.from_result(
            result, aux, fp,
            run={"task": ds.name, "synthetic": None,
                 "data_dir": args.data_dir, "method": "coda",
                 "loss": "acc", "iters": iters, "seeds": seeds,
                 "acq_batch": q})
        rec_dir = os.path.join(args.records_dir, f"q{q}")
        record.save(rec_dir)
        records[q] = (record, rec_dir)
        # label-weighted final cumulative regret (the engine's q>1 trace
        # already weights; q=1 is the plain sum)
        cum = np.asarray(result.cumulative_regret)[:, -1]
        out["per_q"][str(q)] = {
            "iters": iters, "wall_s": round(wall, 3),
            "record_dir": os.path.relpath(rec_dir, REPO),
            "final_cum_regret_mean": float(cum.mean()),
            "final_cum_regret_per_seed": [float(v) for v in cum],
        }
        # bitwise self-replay through the identical q-wide program — the
        # same verify path `cli replay <dir>` runs
        rep = verify_replay(record, factory, ds.preds, ds.labels,
                            loss="acc", score_tol=0.0)
        out["per_q"][str(q)]["replay"] = {
            "parity": bool(rep.parity),
            "cli": f"cli replay {os.path.relpath(rec_dir, REPO)}",
        }
    # q-vs-1 through the --against path: the knob diff routes to the
    # label-aligned regret-envelope triage; commit its numbers
    base_record, base_dir = records[1]
    envelope_ok = True
    worst_ratio = 1.0
    for q in qs[1:]:
        rec, rec_dir = records[q]
        report = compare_records(base_record, rec)
        env = report.meta.get("batchq_envelope") or {}
        ratio = env.get("max_final_ratio_b_over_a")
        q1_mean = out["per_q"]["1"]["final_cum_regret_mean"]
        qm = out["per_q"][str(q)]["final_cum_regret_mean"]
        within = qm <= ENVELOPE_RATIO * q1_mean + ENVELOPE_ABS
        envelope_ok = envelope_ok and within
        if ratio is not None:
            worst_ratio = max(worst_ratio, ratio)
        out["per_q"][str(q)]["against_q1"] = {
            "cli": (f"cli replay {os.path.relpath(base_dir, REPO)} "
                    f"--against {os.path.relpath(rec_dir, REPO)}"),
            "classification": (report.seeds[0].classification
                               if report.seeds else None),
            "envelope": env,
            "ratio_vs_q1": (qm / q1_mean if q1_mean > 0 else None),
            "within_envelope": bool(within),
        }
    out["envelope"] = {"ratio": ENVELOPE_RATIO, "abs_slack": ENVELOPE_ABS,
                       "ok": bool(envelope_ok),
                       "worst_aligned_ratio": float(worst_ratio)}
    return out


def _marginal_round_s(sel, labels, model_losses, state0, q: int, R: int,
                      reps: int = 3) -> dict:
    """Marginal seconds per labeling ROUND, measured scan-only: the
    selector's init runs ONCE outside the timed region (it is identical
    at every q and ~100× a round at the preset shape — the diff-of-walls
    methodology drowned the signal in init variance on the shared
    container), the R-round ``lax.scan`` program is compiled and warmed,
    and the best of ``reps`` warm executions is taken (min is the honest
    estimator of compute cost under background noise)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from coda_tpu.engine.loop import make_step_fn

    step = make_step_fn(sel, labels, model_losses, acq_batch=q)

    @jax.jit
    def run(state, keys):
        (s, cum), _ = lax.scan(step, (state, jnp.asarray(0.0,
                                                         jnp.float32)),
                               keys)
        return cum, s.pi_hat

    keys = jax.random.split(jax.random.PRNGKey(1), R)
    t0 = time.perf_counter()
    jax.block_until_ready(run(state0, keys))      # compile + warm
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(run(state0, keys))
        best = min(best, (time.perf_counter() - t0) / R)
    return {"rounds": R, "reps": reps,
            "compile_and_first_run_s": round(compile_s, 2),
            "round_s_marginal": best,
            "labels_per_s": q / best if best > 0 else None}


def _run_preset(args) -> dict:
    """The throughput half: marginal rounds/s at q=1 vs q=8 on the
    imagenet preset (quick: q=4 on the smoke shape)."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.losses import accuracy_loss
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import CODAHyperparams, make_coda

    if args.quick:
        H, N, C, posterior, chunk, q_hi = 50, 256, 100, "sparse:16", 64, 4
        rounds = (8, 4)
    else:
        H, N, C, posterior, chunk, q_hi = 500, 256, 1000, "sparse:32", 64, 8
        rounds = (args.preset_rounds_q1, args.preset_rounds_q8)
    ds = make_synthetic_task(seed=0, H=H, N=N, C=C)
    hp = CODAHyperparams(posterior=posterior, eig_chunk=chunk,
                         n_parallel=1)
    sel = make_coda(ds.preds, hp)
    losses = true_losses(ds.preds, ds.labels, accuracy_loss)
    state0 = jax.jit(sel.init)(jax.random.PRNGKey(0))
    jax.block_until_ready(state0)
    q1 = _marginal_round_s(sel, ds.labels, losses, state0, q=1,
                           R=rounds[0])
    qh = _marginal_round_s(sel, ds.labels, losses, state0, q=q_hi,
                           R=rounds[1])
    speedup = (qh["labels_per_s"] / q1["labels_per_s"]
               if q1["labels_per_s"] and qh["labels_per_s"] else None)
    return {
        "preset": "imagenet_smoke" if args.quick else "imagenet",
        "shape": {"H": H, "N": N, "C": C},
        "posterior": posterior, "eig_chunk": chunk,
        "methodology": "scan-only marginal (init excluded, warm "
                       "executions, min of reps)",
        "q": q_hi,
        "q1": q1, f"q{q_hi}": qh,
        "labels_per_s_speedup": speedup,
        "speedup_floor": None if args.quick else SPEEDUP_FRAC * q_hi,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="artifact path (default BENCH_BATCHQ_<backend>"
                         "_rNN.json in cwd; quick default is a throwaway)")
    ap.add_argument("--records-dir", default=None,
                    help="where the flight-recorder records land "
                         "(default runs/batchq under --out's directory)")
    ap.add_argument("--data-dir", default=os.path.join(REPO, "data"))
    ap.add_argument("--quick", action="store_true",
                    help="smoke capture: digits q=4 at a smaller budget + "
                         "the imagenet_smoke shape (never gates the full "
                         "artifact — different fingerprint knobs)")
    ap.add_argument("--round", type=int, default=14,
                    help="artifact round number for the default filename")
    ap.add_argument("--preset-rounds-q1", type=int, default=16)
    ap.add_argument("--preset-rounds-q8", type=int, default=8)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax

    backend = jax.default_backend().upper()
    out_path = args.out or os.path.join(
        REPO, f"BENCH_BATCHQ_{backend}_r{args.round:02d}"
              + ("_quick" if args.quick else "") + ".json")
    if args.records_dir is None:
        args.records_dir = os.path.join(
            os.path.dirname(os.path.abspath(out_path)) or ".",
            "runs", f"batchq{'_quick' if args.quick else ''}_r"
                    f"{args.round:02d}")

    fingerprint_holder: list = []
    t0 = time.perf_counter()
    digits = _run_digits(args, fingerprint_holder)
    preset = _run_preset(args)
    wall = time.perf_counter() - t0

    replays_ok = all(v["replay"]["parity"]
                     for v in digits["per_q"].values())
    triaged = all(
        v.get("against_q1", {}).get("classification")
        == "acq-batch-envelope"
        for k, v in digits["per_q"].items() if k != "1")
    speedup = preset.get("labels_per_s_speedup")
    floor = preset.get("speedup_floor")
    speedup_ok = (True if floor is None
                  else (speedup is not None and speedup >= floor))
    ok = bool(digits["envelope"]["ok"] and replays_ok and triaged
              and speedup_ok)
    report = {
        "bench": "batchq",
        "quick": bool(args.quick),
        "wall_s": round(wall, 2),
        "config": {
            "method": "coda", "acquisition": "greedy EIG with "
            "information-overlap penalty (cached re-rank)",
            "update": "one fused multi-row posterior update per round",
            "envelope": {"ratio": ENVELOPE_RATIO,
                         "abs_slack": ENVELOPE_ABS},
            "speedup_floor_frac_of_q": SPEEDUP_FRAC,
        },
        "digits": digits,
        "imagenet": preset,
        "labels_per_s_speedup": speedup,
        "regret_envelope_ok": bool(digits["envelope"]["ok"]),
        "replays_verified": bool(replays_ok),
        "divergences_triaged": bool(triaged),
        "fingerprint": fingerprint_holder[0] if fingerprint_holder
        else None,
        "ok": ok,
    }
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out_path} (ok={ok}, speedup={speedup}, "
          f"envelope_ok={digits['envelope']['ok']})")

    # self-gate: the artifact must satisfy its own check_perf contract
    # (quick captures carry no committed floors — structural gate only)
    if not args.quick:
        from check_perf import check_artifact, match_contract

        contract = match_contract(out_path)
        if contract is None:
            print("self-gate: no contract matches the artifact name")
            return 1
        violations = check_artifact(out_path, report, contract)
        for v in violations:
            print(f"self-gate: {v}")
        if violations:
            return 1
        print("self-gate clean")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
