"""Reconcile analytic per-step FLOPs vs XLA cost-model numbers.

Compiles (CPU) three programs at the headline config and prints their XLA
cost-analysis flops/transcendentals:
  A. full experiment at iters=50  (what bench.py reports as scan_body_once)
  B. full experiment at iters=25  (confirm body counted once)
  C. the single scan STEP alone   (the true per-round work, XLA's count)
"""
import sys
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine.loop import build_experiment_fn, make_step_fn
from coda_tpu.oracle import true_losses
from coda_tpu.selectors import CODAHyperparams, make_coda

H, N, C = 1000, 50000, 10
task = make_synthetic_task(seed=0, H=H, N=N, C=C)
hp = CODAHyperparams()

def cost(fn, *args):
    c = fn.lower(*args).compile().cost_analysis()
    if isinstance(c, (list, tuple)):
        c = c[0] if c else {}
    return float(c.get("flops", 0)), float(c.get("transcendentals", 0)), float(c.get("bytes accessed", 0))

preds, labels = task.preds, task.labels
tl = true_losses(preds, labels)

def full(iters):
    def run(preds, labels, key):
        return build_experiment_fn(make_coda(preds, hp), labels, true_losses(preds, labels), iters=iters)(key)
    return jax.jit(run)

key = jax.random.PRNGKey(0)
fA = cost(full(50), preds, labels, key)
fB = cost(full(25), preds, labels, key)
print("full iters=50:", fA)
print("full iters=25:", fB)

def step_only(preds, labels, key):
    sel = make_coda(preds, hp)
    step = make_step_fn(sel, labels, true_losses(preds, labels))
    k_init, k_s = jax.random.split(key)
    state0 = sel.init(k_init)
    carry, out = step((state0, jnp.asarray(0.0, jnp.float32)), k_s)
    return out

# init-only program, so (init+step) - init = step body by XLA's own count
def init_only(preds, labels, key):
    sel = make_coda(preds, hp)
    k_init, _ = jax.random.split(key)
    return sel.init(k_init)

fC = cost(jax.jit(step_only), preds, labels, key)
fD = cost(jax.jit(init_only), preds, labels, key)
print("init+1step:", fC)
print("init only :", fD)
print("XLA step body (diff):", tuple(a-b for a,b in zip(fC, fD)))

from bench import _analytic_step_flops, _analytic_step_bytes
flops, mode, pi_res = _analytic_step_flops(H, N, C)
print("analytic:", (flops, mode, pi_res),
      _analytic_step_bytes(H, N, C, mode=mode, pi_update=pi_res))
