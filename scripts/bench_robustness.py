"""Crowd-oracle robustness matrix -> ROBUSTNESS_<b>_rNN.json.

The ISSUE-16 crowd subsystem's gated evidence, four claims in one
artifact:

  * **clean parity (bitwise)** — ``--oracle-noise clean`` is the plain
    oracle program: its record must verify bitwise against a knob-less
    record through the real ``cli replay --against --score-tol 0`` path,
    and the plain record must self-replay bitwise. The crowd layer adds
    NOTHING to the clean path.
  * **noisy regret envelope** — a noisy crowd (confusion-matrix
    annotators, abstentions, one adversary) vs the clean run compares
    through ``compare_records``'s ``oracle-noise-envelope`` triage; the
    label-aligned final cumulative-regret ratio must stay inside the
    committed envelope (``check_perf.ORACLE_ENVELOPE_RATIO/ABS``).
  * **reliability recovery** — the Dawid-Skene posterior
    (``coda_tpu/crowd/reliability.py``), fed only the votes it
    aggregates itself, must recover the PLANTED per-annotator diagonal
    accuracies (rank-correlate and bound the error) and push every
    adversarial annotator below every honest one.
  * **async delivery (serve)** — deferred / out-of-order / duplicated
    per-slot answers through ``POST /session/{id}/answer`` must commit
    the same per-round stream (digest-identical) as in-order delivery,
    with 0 lost and 0 double-applied labels, and parked answers must
    survive a crash-restore.

Runnable standalone (CPU container, ~2 min quick / ~6 min full)::

    python scripts/bench_robustness.py --quick
    python scripts/bench_robustness.py --out ROBUSTNESS_CPU_r18.json \
        --records-dir runs/robustness_r18

The finished artifact is self-gated against its ``check_perf.py``
contract before the script exits.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the declared bounds are the GATE's, imported from the one place they
# are enforced (scripts/check_perf.py) so the generator can never embed
# verdicts computed under stale thresholds
from check_perf import (  # noqa: E402
    ORACLE_ENVELOPE_ABS as ENVELOPE_ABS,
    ORACLE_ENVELOPE_RATIO as ENVELOPE_RATIO,
    ORACLE_MIN_RELIABILITY_CORR as MIN_CORR,
    ORACLE_MAX_RELIABILITY_MAE as MAX_MAE,
)

NOISY_SPEC = ("annotators=8,votes=3,acc=0.6:0.95,abstain=0.1,"
              "adversarial=1,trust=16,seed=0")
RELIABILITY_SPEC = ("annotators=8,votes=3,acc=0.55:0.95,abstain=0.05,"
                    "adversarial=2,trust=24,seed=1")
SERVE_SPEC = "annotators=6,votes=3,abstain=0.15,defer=0.4:3,seed=2"


def _run_cli(flags, timeout=900) -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([sys.executable, "-m", "coda_tpu.cli"] + flags,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=timeout, env=env)
    if r.returncode != 0:
        sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
    return r.returncode


def _base_flags(args) -> list:
    return ["--synthetic", args.shape, "--iters", str(args.iters),
            "--seeds", str(args.seeds), "--method", "coda",
            "--no-mlflow", "--platform", "cpu"]


# ---------------------------------------------------------------------------
# clean parity + noisy envelope (the recorded-experiment half)
# ---------------------------------------------------------------------------

def _record_three(args, rdir: str) -> dict:
    """Record plain / clean-crowd / noisy-crowd runs of the same config."""
    dirs = {"plain": os.path.join(rdir, "plain"),
            "clean": os.path.join(rdir, "clean"),
            "noisy": os.path.join(rdir, "noisy")}
    runs = {
        "plain": [],
        "clean": ["--oracle-noise", "clean"],
        "noisy": ["--oracle-noise", NOISY_SPEC],
    }
    for tag, extra in runs.items():
        rc = _run_cli(_base_flags(args)
                      + ["--record-dir", dirs[tag]] + extra)
        if rc != 0:
            raise SystemExit(f"recording the {tag} run failed (rc={rc})")
    return dirs


def _clean_parity(dirs: dict) -> dict:
    """The bitwise pin: plain self-replays; clean-crowd == plain through
    the real ``cli replay --against --score-tol 0`` path."""
    self_rc = _run_cli(["replay", dirs["plain"], "--platform", "cpu"])
    against_rc = _run_cli(["replay", dirs["clean"], "--against",
                           dirs["plain"], "--score-tol", "0",
                           "--platform", "cpu"])
    return {"replay_rc": self_rc, "against_rc": against_rc,
            "parity": self_rc == 0 and against_rc == 0}


def _noisy_envelope(dirs: dict) -> dict:
    """Noisy-vs-clean through ``compare_records``: the oracle-knob diff
    must route to the ``oracle-noise-envelope`` triage, and every seed's
    final label-aligned cumulative regret must stay inside the committed
    envelope ``cum_noisy <= RATIO * cum_clean + ABS``."""
    from coda_tpu.engine.replay import compare_records
    from coda_tpu.telemetry.recorder import RunRecord

    a = RunRecord.load(dirs["clean"])
    b = RunRecord.load(dirs["noisy"])
    report = compare_records(a, b)
    env = (report.meta or {}).get("oracle_envelope") or {}
    per_seed = env.get("seeds") or []
    within = []
    for info in per_seed:
        ca = float(info["final_cum_a"])
        cb = float(info["final_cum_b"])
        within.append(cb <= ENVELOPE_RATIO * ca + ENVELOPE_ABS)
    classifications = {s.classification for s in report.seeds}
    classification = (classifications.pop()
                      if len(classifications) == 1 else None)
    return {
        "spec": NOISY_SPEC,
        "classification": classification,
        "per_seed": per_seed,
        "max_final_ratio": env.get("max_final_ratio_b_over_a"),
        "envelope_ratio_bound": ENVELOPE_RATIO,
        "envelope_abs_bound": ENVELOPE_ABS,
        "envelope_ok": bool(
            classification == "oracle-noise-envelope"
            and per_seed and all(within)),
    }


# ---------------------------------------------------------------------------
# Dawid-Skene recovery of the planted pool
# ---------------------------------------------------------------------------

def _reliability_recovery(rounds: int, n_classes: int = 4) -> dict:
    """Feed the reliability posterior its own aggregated votes for
    ``rounds`` labeling rounds and compare the learned per-annotator
    accuracies against the PLANTED confusion diagonals."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.crowd import (aggregate_votes, annotator_accuracy,
                                init_reliability, make_annotators,
                                parse_oracle_spec, sample_votes)

    cfg = parse_oracle_spec(RELIABILITY_SPEC)
    conf = make_annotators(cfg, n_classes)
    planted = np.asarray(
        jnp.diagonal(conf, axis1=-2, axis2=-1).mean(-1))      # (A,)

    def step(carry, key):
        rel = carry
        k_z, k_votes = jax.random.split(key)
        z = jax.random.randint(k_z, (), 0, n_classes, dtype=jnp.int32)
        ann_ids, responses, answered = sample_votes(
            k_votes, conf, z, cfg)
        _, _, rel2 = aggregate_votes(rel, ann_ids, responses, answered,
                                     cfg)
        return rel2, None

    keys = jax.random.split(jax.random.PRNGKey(7), rounds)
    rel, _ = jax.lax.scan(step, init_reliability(cfg, n_classes), keys)
    learned = np.asarray(annotator_accuracy(rel))

    honest = np.arange(cfg.annotators) < cfg.annotators - cfg.adversarial
    corr = float(np.corrcoef(learned, planted)[0, 1])
    mae = float(np.abs(learned - planted).mean())
    separated = bool(learned[~honest].max() < learned[honest].min())
    return {
        "spec": RELIABILITY_SPEC, "rounds": rounds,
        "planted_accuracy": [round(float(v), 4) for v in planted],
        "learned_accuracy": [round(float(v), 4) for v in learned],
        "corr": corr, "mae": mae,
        "adversaries_separated": separated,
        "corr_bound": MIN_CORR, "mae_bound": MAX_MAE,
        "ok": bool(corr >= MIN_CORR and mae <= MAX_MAE and separated),
    }


# ---------------------------------------------------------------------------
# async serve delivery matrix
# ---------------------------------------------------------------------------

def _mkapp(record_dir: str, q: int, task):
    from coda_tpu.serve.server import ServeApp
    from coda_tpu.serve.state import SelectorSpec
    from coda_tpu.telemetry import SessionRecorder

    app = ServeApp(capacity=3, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=3,
                                            acq_batch=q),
                   recorder=SessionRecorder(out_dir=record_dir))
    app.add_task("t", task.preds)
    app.start()
    return app


def _stream_digest(app, sid) -> str:
    from coda_tpu.serve.recovery import data_rows

    rows = data_rows(app.recorder.history(sid))
    keys = ("n_labeled", "labeled_idx", "label", "next_idx", "next_prob",
            "best", "pbest_max")
    return hashlib.sha256(json.dumps(
        [{k: r.get(k) for k in keys} for r in rows],
        sort_keys=True).encode()).hexdigest()


def _drive_session(app, sid, first, sampler, n_classes, rounds, q,
                   in_order: bool, redeliver: bool, errors: list) -> dict:
    """Answer ``rounds`` rounds slot-by-slot; out-of-order mode delivers
    deferred answers late and redelivers ~every third answer after its
    round committed (the dedupe must read, never re-apply)."""
    stats = {"reorder_depth_max": 0, "redelivered": 0, "abstentions": 0}
    out = first
    for rnd in range(rounds):
        idxs = out["idx"] if q > 1 else [out["idx"]]
        held = []
        for j, idx in enumerate(idxs):
            true = int(idx) % n_classes
            for attempt in range(64):
                a = sampler.answer(sid, rnd, j, true, attempt=attempt)
                if a["verb"] != "abstain":
                    break
                stats["abstentions"] += 1
                app.answer(sid, j, abstain=True)
            held.append((a["defer"], j, a["label"]))
        order = sorted(held) if not in_order \
            else sorted(held, key=lambda t: t[1])
        delivered: list = []
        committed = []
        for d, j, lab in order:
            depth = sum(1 for k in delivered if k > j)
            stats["reorder_depth_max"] = max(stats["reorder_depth_max"],
                                             depth)
            rid = f"crowd:{sid}:{rnd}:{j}"
            res = app.answer(sid, j, label=lab, request_id=rid)
            delivered.append(j)
            committed.append((j, lab, rid))
        if res.get("verb") != "dispatched":
            errors.append(f"round {rnd}: last answer verb "
                          f"{res.get('verb')!r}")
        out = res
        if redeliver:
            for j, lab, rid in committed[::3]:
                dup = app.answer(sid, j, label=lab, request_id=rid)
                if not dup.get("duplicate"):
                    errors.append(f"round {rnd} slot {j}: redelivery was "
                                  "not deduped")
                stats["redelivered"] += 1
    return stats


def _async_matrix(args, rdir: str) -> dict:
    """Out-of-order + duplicated delivery vs in-order delivery: same
    committed stream, 0 lost / 0 double-applied; parked answers survive
    a crash-restore."""
    from coda_tpu.crowd import HostCrowdSampler, parse_oracle_spec
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import recovery

    q, rounds, n_classes = 3, args.serve_rounds, 4
    task = make_synthetic_task(0, H=8, N=64, C=n_classes)
    cfg = parse_oracle_spec(SERVE_SPEC)
    errors: list = []

    # the same deterministic sampler drives both delivery orders: the
    # answers are identical, only WHEN each one arrives differs
    d_ooo = os.path.join(rdir, "serve_ooo")
    app = _mkapp(d_ooo, q, task)
    first = app.open_session("t", seed=0)
    sid = first["session"]
    sampler = HostCrowdSampler(cfg, n_classes)
    # pin the session id into the draw key so both apps sample identically
    sampler_sid = "matrix"

    class _Pinned:
        def answer(self, _sid, rnd, j, true, attempt=0):
            return sampler.answer(sampler_sid, rnd, j, true,
                                  attempt=attempt)

    stats = _drive_session(app, sid, first, _Pinned(), n_classes, rounds,
                           q, in_order=False, redeliver=True,
                           errors=errors)
    n_ooo = app.store.get(sid).n_labeled
    dig_ooo = _stream_digest(app, sid)
    oracle_metrics = app.metrics.snapshot()["oracle"]

    d_ino = os.path.join(rdir, "serve_inorder")
    app2 = _mkapp(d_ino, q, task)
    first2 = app2.open_session("t", seed=0)
    sid2 = first2["session"]
    _drive_session(app2, sid2, first2, _Pinned(), n_classes, rounds, q,
                   in_order=True, redeliver=False, errors=errors)
    n_ino = app2.store.get(sid2).n_labeled
    dig_ino = _stream_digest(app2, sid2)

    # crash-restore of parked answers: park q-1 answers of the next
    # round, rebuild the app from the streams, finish the round
    restored_ok = False
    sess = app.store.get(sid)
    nxt = sess.last["next_idx"]
    for j in (1, 0):
        app.answer(sid, j, label=int(nxt[j]) % n_classes,
                   request_id=f"park:{j}")
    app3 = _mkapp(d_ooo, q, task)
    rep = recovery.restore_app_sessions(app3, d_ooo)
    if sid in rep["restored"]:
        s3 = app3.store.get(sid)
        parked_restored = sorted(s3.parked) == [0, 1]
        fin = app3.answer(sid, 2, label=int(nxt[2]) % n_classes,
                          request_id="park:2")
        restored_ok = bool(parked_restored
                           and fin.get("verb") == "dispatched"
                           and s3.n_labeled == (rounds + 1) * q)
    else:
        errors.append(f"crash-restore failed: {rep['failed']}")
    for a in (app, app2, app3):
        a.drain()

    lost = abs(rounds * q - n_ooo) + abs(rounds * q - n_ino)
    return {
        "spec": SERVE_SPEC, "rounds": rounds, "acq_batch": q,
        "digest_out_of_order": dig_ooo, "digest_in_order": dig_ino,
        "digest_match": dig_ooo == dig_ino,
        "labels_applied": int(n_ooo), "lost": int(lost),
        # applied-exactly-once: every duplicate redelivery was READ from
        # the committed round, never re-applied (label counts agree and
        # the streams are digest-identical)
        "redelivered": stats["redelivered"],
        "double_applied": int(lost if dig_ooo == dig_ino else 1),
        "reorder_depth_max": stats["reorder_depth_max"],
        "abstentions": stats["abstentions"],
        "parked_restored": restored_ok,
        "server_metrics": oracle_metrics,
        "errors": errors[:10], "n_errors": len(errors),
        "ok": bool(dig_ooo == dig_ino and lost == 0 and restored_ok
                   and not errors),
    }


# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="ROBUSTNESS_CPU_r18.json")
    p.add_argument("--quick", action="store_true",
                   help="small shapes / fewer rounds (smoke; still gated)")
    p.add_argument("--records-dir", default=None,
                   help="keep the run records here (default: a tempdir)")
    p.add_argument("--skip-gate", action="store_true",
                   help="write the artifact without self-gating (debug)")
    args = p.parse_args(argv)

    args.shape = "8,128,4" if args.quick else "8,256,4"
    args.iters = 20 if args.quick else 40
    args.seeds = 2 if args.quick else 3
    args.serve_rounds = 4 if args.quick else 8
    reliability_rounds = 150 if args.quick else 400

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rdir = args.records_dir or tempfile.mkdtemp(prefix="robustness_")
    os.makedirs(rdir, exist_ok=True)

    from coda_tpu.telemetry.recorder import environment_fingerprint

    print(f"[1/4] recording plain/clean/noisy runs ({args.shape}, "
          f"iters={args.iters}, seeds={args.seeds}) ...")
    dirs = _record_three(args, rdir)
    print("[2/4] clean parity (cli replay --against --score-tol 0) ...")
    clean = _clean_parity(dirs)
    print(f"      parity={clean['parity']}")
    noisy = _noisy_envelope(dirs)
    print(f"      noisy envelope: ratio="
          f"{noisy['max_final_ratio']} ok={noisy['envelope_ok']}")
    print(f"[3/4] Dawid-Skene recovery ({reliability_rounds} rounds) ...")
    reliability = _reliability_recovery(reliability_rounds)
    print(f"      corr={reliability['corr']:.3f} "
          f"mae={reliability['mae']:.3f} "
          f"separated={reliability['adversaries_separated']}")
    print("[4/4] async serve delivery matrix ...")
    async_m = _async_matrix(args, rdir)
    print(f"      digest_match={async_m['digest_match']} "
          f"lost={async_m['lost']} restored={async_m['parked_restored']}")

    ok = bool(clean["parity"] and noisy["envelope_ok"]
              and reliability["ok"] and async_m["ok"])
    report = {
        "bench": "oracle_robustness",
        "quick": bool(args.quick),
        "config": {"shape": args.shape, "iters": args.iters,
                   "seeds": args.seeds,
                   "serve_rounds": args.serve_rounds,
                   "reliability_rounds": reliability_rounds},
        "clean": clean,
        "noisy": noisy,
        "reliability": reliability,
        "async": async_m,
        "verify": [
            f"python -m coda_tpu.cli replay {dirs['plain']}",
            f"python -m coda_tpu.cli replay {dirs['clean']} "
            f"--against {dirs['plain']} --score-tol 0",
        ],
        "fingerprint": environment_fingerprint(knobs={
            "bench": "oracle_robustness", "quick": bool(args.quick),
            "shape": args.shape, "iters": args.iters,
            "seeds": args.seeds, "noisy_spec": NOISY_SPEC,
            "reliability_spec": RELIABILITY_SPEC,
            "serve_spec": SERVE_SPEC}),
        "ok": ok,
    }
    out_path = os.path.abspath(args.out)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out_path} (ok={ok})")
    if args.records_dir is None:
        shutil.rmtree(rdir, ignore_errors=True)

    # self-gate: the artifact must satisfy its own check_perf contract
    if not args.skip_gate:
        from check_perf import check_artifact, match_contract

        contract = match_contract(out_path)
        if contract is None:
            print("self-gate: no contract matches the artifact name")
            return 1
        violations = check_artifact(out_path, report, contract)
        for v in violations:
            print(f"self-gate: {v}")
        if violations:
            return 1
        print("self-gate clean")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
