"""Build a REAL text-classification task from documents bundled in the OS.

The reference benchmark's GLUE family is text classification at small C
(``/root/reference/paper/tab1.py:112-122``); its tensors are not fetchable
in this zero-egress environment (and neither is 20newsgroups —
``sklearn.datasets.fetch_20newsgroups`` downloads). This script
reconstructs the same *kind* of artifact from first principles on real
natural documents that ARE present: thousands of Python sources,
reStructuredText docs, XML, JSON and plain-text files shipped with the OS
image. The task is document-type identification (C=5) — real prose, real
code, real markup, genuine ground-truth labels from the file extension —
scored by a pool of genuinely different text models (TF-IDF character and
word features x NB/logreg/SGD/kNN/tree families, some deliberately weak),
trained on a 50% split. Output: the same ``<task>.npz`` format as
``make_real_task.py`` ((H, N, C) post-softmax preds + labels + classes).

Usage: python scripts/make_text_task.py [--out data/pyfiles.npz]
"""

from __future__ import annotations

import argparse
import glob
import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# document classes: extension -> label. Every root is part of the OS image
# (deterministic given the image), and the per-class cap keeps the task
# balanced against the ~28k .py surplus.
ROOTS = ["/opt/venv/lib", "/usr/share", "/usr/lib/python3",
         "/root/.pyenv/versions", "/etc"]
CLASSES = ["py", "rst", "xml", "json", "txt"]
PER_CLASS = 200
MIN_BYTES, MAX_BYTES, HEAD_BYTES = 512, 200_000, 4096


def collect_files(seed: int = 0) -> tuple[list[str], np.ndarray]:
    rng = np.random.default_rng(seed)
    paths, labels = [], []
    for ci, ext in enumerate(CLASSES):
        found = []
        for root in ROOTS:
            found += [
                f for f in glob.glob(os.path.join(root, "**", f"*.{ext}"),
                                     recursive=True)
                if MIN_BYTES < os.path.getsize(f) < MAX_BYTES
            ]
        found = sorted(set(found))
        if len(found) > PER_CLASS:
            found = [found[i] for i in
                     rng.choice(len(found), PER_CLASS, replace=False)]
        paths += found
        labels += [ci] * len(found)
    return paths, np.asarray(labels, np.int32)


def read_heads(paths: list[str]) -> list[str]:
    docs = []
    for p in paths:
        with open(p, "rb") as fh:
            docs.append(fh.read(HEAD_BYTES).decode("latin-1"))
    return docs


def model_pool(seed: int = 0):
    """(name, feature_key, estimator): char TF-IDF carries the syntax
    signal; word TF-IDF and small-SVD features make the weak half of the
    pool — the accuracy spread the selector has to resolve."""
    from sklearn.linear_model import LogisticRegression, SGDClassifier
    from sklearn.naive_bayes import GaussianNB, MultinomialNB
    from sklearn.neighbors import KNeighborsClassifier
    from sklearn.tree import DecisionTreeClassifier

    return [
        ("nb_char_a0.01", "char", MultinomialNB(alpha=0.01)),
        ("nb_char_a1", "char", MultinomialNB(alpha=1.0)),
        ("nb_char_a10", "char", MultinomialNB(alpha=10.0)),
        ("nb_word", "word", MultinomialNB()),
        ("logreg_char_c0.01", "char", LogisticRegression(
            C=0.01, max_iter=2000)),
        ("logreg_char_c1", "char", LogisticRegression(C=1.0, max_iter=2000)),
        ("logreg_char_c100", "char", LogisticRegression(
            C=100.0, max_iter=2000)),
        ("logreg_word", "word", LogisticRegression(C=1.0, max_iter=2000)),
        ("sgd_char", "char", SGDClassifier(
            loss="log_loss", random_state=seed)),
        ("knn5_svd", "svd", KNeighborsClassifier(5)),
        ("knn25_svd", "svd", KNeighborsClassifier(25)),
        ("tree_svd", "svd", DecisionTreeClassifier(
            max_depth=4, random_state=seed)),
        ("gnb_svd", "svd", GaussianNB()),
        ("sgd_word", "word", SGDClassifier(
            loss="log_loss", random_state=seed + 1)),
    ]


def build(out: str, test_frac: float = 0.5, seed: int = 0) -> dict:
    from sklearn.decomposition import TruncatedSVD
    from sklearn.feature_extraction.text import TfidfVectorizer
    from sklearn.model_selection import train_test_split

    paths, y = collect_files(seed)
    docs = read_heads(paths)
    d_tr, d_ev, y_tr, y_ev = train_test_split(
        docs, y, test_size=test_frac, random_state=seed, stratify=y)

    # features fit on the TRAIN half only (no eval leakage)
    char_v = TfidfVectorizer(analyzer="char", ngram_range=(2, 4),
                             max_features=20000, sublinear_tf=True)
    word_v = TfidfVectorizer(analyzer="word", max_features=5000)
    feats = {
        "char": (char_v.fit_transform(d_tr), char_v.transform(d_ev)),
        "word": (word_v.fit_transform(d_tr), word_v.transform(d_ev)),
    }
    svd = TruncatedSVD(n_components=20, random_state=seed)
    feats["svd"] = (svd.fit_transform(feats["char"][0]),
                    svd.transform(feats["char"][1]))

    pool = model_pool(seed)
    C = len(CLASSES)
    preds = np.zeros((len(pool), len(y_ev), C), dtype=np.float32)
    accs = {}
    for h, (name, fkey, est) in enumerate(pool):
        x_tr, x_ev = feats[fkey]
        est.fit(x_tr, y_tr)
        p = est.predict_proba(x_ev).astype(np.float32)
        assert p.shape == (len(y_ev), C), (name, p.shape)
        preds[h] = p / np.clip(p.sum(-1, keepdims=True), 1e-12, None)
        accs[name] = float((p.argmax(-1) == y_ev).mean())

    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    np.savez_compressed(
        out,
        preds=preds,
        labels=y_ev.astype(np.int32),
        classes=np.asarray(CLASSES),
        models=np.asarray([n for n, _, _ in pool]),
    )
    return accs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "data", "pyfiles.npz"))
    ap.add_argument("--test-frac", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    accs = build(args.out, args.test_frac, args.seed)
    print(f"wrote {args.out}")
    for name, acc in sorted(accs.items(), key=lambda kv: -kv[1]):
        print(f"  {name:18s} acc={acc:.4f}")
    best, worst = max(accs.values()), min(accs.values())
    print(f"pool: {len(accs)} models, best {best:.4f}, spread "
          f"{best - worst:.4f}")


if __name__ == "__main__":
    main()
