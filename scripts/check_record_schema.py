"""Static schema validation of flight-recorder artifacts.

The decision records (``coda_tpu/telemetry/recorder.py``) are replay
evidence: a record that silently drifted from the schema — missing version
stamp, renamed array, wrong dtype/rank, seed/round counts that disagree
between meta and arrays — would make ``cli replay`` triage garbage instead
of failing loudly. This checker walks a directory tree and validates every
artifact it finds against the versioned schema (record v1-v4 — v2 adds
the ``acq_batch`` stamp and q-wide decision arrays, v3 the per-round
``surrogate_fallback`` array of the contract-gated EIG surrogate, v4 the
OPTIONAL crowd-oracle arrays ``oracle_label``/``label_weight``; session
streams at the current version only):

  * ``record.json`` + ``rounds.npz`` pairs (batch/suite records): version
    stamp, required meta fields, every REQUIRED_ARRAYS entry present with
    the right dtype kind / rank / leading (seeds, rounds) extents, top-k
    extent consistent with ``trace_k``;
  * ``session_*.jsonl`` streams (serving records): every line JSON with a
    ``v`` version stamp; row lines carry the decision fields.

Wired into tier-1 (``tests/test_recorder.py``) the same way
``check_clocks.py`` is, and runnable standalone::

    python scripts/check_record_schema.py <dir> [<dir> ...]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

_ROW_FIELDS = ("n_labeled", "do_update", "next_idx", "next_prob", "best",
               # the v2 additions the version bump exists for: the replay
               # verifier's digest pair and the idempotency token (present
               # with value None when unused — absence is writer drift)
               "stochastic", "labeled_idx", "label", "prob", "request_id",
               "pbest_max", "pbest_entropy")


def _check_pred_label_prob(v) -> str:
    """Violation (or "") for the ADDITIVE-OPTIONAL ``pred_label_prob``
    row field (trace_id's contract: absent — not null — when the
    decision-quality plane is off, so off-streams stay bitwise identical;
    no version bump). When present it is the pre-update consensus
    posterior probability of the applied label: a [0, 1] float, or a
    q-wide list of them on a batch row."""
    vals = v if isinstance(v, list) else [v]
    if not vals:
        return "pred_label_prob: empty list"
    for x in vals:
        if not isinstance(x, (int, float)) or isinstance(x, bool):
            return f"pred_label_prob: non-numeric entry {x!r}"
        if not (0.0 <= float(x) <= 1.0):
            return f"pred_label_prob: {x!r} outside [0, 1]"
    return ""


def check_record(dir_path: str) -> list[str]:
    """Violations of one record.json + rounds.npz pair (empty = clean)."""
    import numpy as np

    from coda_tpu.telemetry.recorder import (
        REQUIRED_META,
        SUPPORTED_RECORD_VERSIONS,
        optional_arrays,
        required_arrays,
    )

    out: list[str] = []
    meta_fp = os.path.join(dir_path, "record.json")
    rounds_fp = os.path.join(dir_path, "rounds.npz")
    try:
        with open(meta_fp) as f:
            meta = json.load(f)
    except Exception as e:
        return [f"unreadable record.json: {e}"]
    v = meta.get("schema_version")
    if v is None:
        out.append("record.json has no schema_version stamp")
    elif v not in SUPPORTED_RECORD_VERSIONS:
        out.append(f"schema_version {v!r} not in supported "
                   f"{list(SUPPORTED_RECORD_VERSIONS)}")
    # v2+ must stamp acq_batch; v1 predates batching and reads as q=1
    q = meta.get("acq_batch", 1)
    if isinstance(v, int) and v >= 2 \
            and not isinstance(meta.get("acq_batch"), int):
        out.append(f"v{v} record.json missing integer 'acq_batch'")
        q = 1
    REQUIRED_ARRAYS = required_arrays(
        q if isinstance(q, int) else 1,
        schema_version=v if isinstance(v, int) else 1)
    # v4's crowd-oracle arrays: allowed (and validated) when present,
    # never demanded — clean records carry neither
    OPTIONAL_ARRAYS = optional_arrays(q if isinstance(q, int) else 1) \
        if isinstance(v, int) and v >= 4 else {}
    for key in REQUIRED_META:
        if key not in meta:
            out.append(f"record.json missing required field {key!r}")
    if not os.path.isfile(rounds_fp):
        out.append("rounds.npz missing")
        return out
    try:
        with np.load(rounds_fp) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as e:
        out.append(f"unreadable rounds.npz: {e}")
        return out
    S = meta.get("seeds")
    T = meta.get("rounds")
    k = meta.get("trace_k")
    for name, (kind, ndim) in REQUIRED_ARRAYS.items():
        a = arrays.get(name)
        if a is None:
            out.append(f"rounds.npz missing array {name!r}")
            continue
        if a.dtype.kind != kind:
            out.append(f"{name}: dtype kind {a.dtype.kind!r} != "
                       f"expected {kind!r}")
        if a.ndim != ndim:
            out.append(f"{name}: rank {a.ndim} != expected {ndim}")
            continue
        if isinstance(S, int) and a.shape[0] != S:
            out.append(f"{name}: leading seed extent {a.shape[0]} != "
                       f"meta seeds {S}")
        if ndim >= 2 and name not in ("root_key", "init_key", "prior_key") \
                and isinstance(T, int) and a.shape[1] != T:
            out.append(f"{name}: round extent {a.shape[1]} != "
                       f"meta rounds {T}")
        if name in ("topk_idx", "topk_score") and isinstance(k, int) \
                and a.ndim == 3 and a.shape[2] != k:
            out.append(f"{name}: top-k extent {a.shape[2]} != "
                       f"meta trace_k {k}")
        if name in ("chosen_idx", "true_class", "select_prob") \
                and isinstance(q, int) and q > 1 and a.ndim == 3 \
                and a.shape[2] != q:
            out.append(f"{name}: label-batch extent {a.shape[2]} != "
                       f"meta acq_batch {q}")
    for name, (kind, ndim) in OPTIONAL_ARRAYS.items():
        a = arrays.get(name)
        if a is None:
            continue
        if a.dtype.kind != kind:
            out.append(f"{name}: dtype kind {a.dtype.kind!r} != "
                       f"expected {kind!r}")
        if a.ndim != ndim:
            out.append(f"{name}: rank {a.ndim} != expected {ndim}")
        elif isinstance(S, int) and a.shape[0] != S:
            out.append(f"{name}: leading seed extent {a.shape[0]} != "
                       f"meta seeds {S}")
        elif isinstance(T, int) and a.shape[1] != T:
            out.append(f"{name}: round extent {a.shape[1]} != "
                       f"meta rounds {T}")
    extra = set(arrays) - set(REQUIRED_ARRAYS) - set(OPTIONAL_ARRAYS)
    if extra:
        out.append(f"unversioned field drift: unexpected arrays "
                   f"{sorted(extra)} (bump RECORD_SCHEMA_VERSION)")
    return out


def check_session_stream(fp: str) -> list[str]:
    """Violations of one serving-session JSONL stream."""
    from coda_tpu.telemetry.recorder import SUPPORTED_SESSION_VERSIONS

    out: list[str] = []
    try:
        with open(fp) as f:
            lines = f.readlines()
    except Exception as e:
        return [f"unreadable: {e}"]
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except Exception:
            out.append(f"line {i}: not JSON")
            continue
        v = row.get("v")
        if v is None:
            out.append(f"line {i}: no 'v' version stamp")
        elif v not in SUPPORTED_SESSION_VERSIONS:
            out.append(f"line {i}: v={v!r} not in supported "
                       f"{list(SUPPORTED_SESSION_VERSIONS)}")
        kind = row.get("kind")
        if kind is not None:
            # marker lines: the open header, the clean-close marker
            # (crash restore keys on its absence), the exported-session
            # tombstone, and v4's parked per-slot crowd answers; anything
            # else is drift
            if kind not in ("session_meta", "session_close",
                            "session_export", "answer_park"):
                out.append(f"line {i}: unknown row kind {kind!r} "
                           "(bump SESSION_SCHEMA_VERSION)")
            continue
        missing = [k for k in _ROW_FIELDS if k not in row]
        if missing:
            out.append(f"line {i}: row missing fields {missing}")
        if "pred_label_prob" in row:
            bad = _check_pred_label_prob(row["pred_label_prob"])
            if bad:
                out.append(f"line {i}: {bad}")
    return out


def check_tree(root: str) -> dict[str, list[str]]:
    """{relpath: violations} over every recorder artifact under ``root``."""
    bad: dict[str, list[str]] = {}
    n_checked = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if "record.json" in filenames:
            n_checked += 1
            v = check_record(dirpath)
            if v:
                bad[os.path.relpath(dirpath, root) or "."] = v
        for fn in sorted(filenames):
            if fn.startswith("session_") and fn.endswith(".jsonl"):
                n_checked += 1
                v = check_session_stream(os.path.join(dirpath, fn))
                if v:
                    bad[os.path.relpath(os.path.join(dirpath, fn), root)] = v
    check_tree.last_checked = n_checked  # introspection for callers/tests
    return bad


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python scripts/check_record_schema.py <dir> [...]")
        return 64
    total_bad = 0
    total_checked = 0
    for root in argv:
        bad = check_tree(root)
        total_checked += check_tree.last_checked
        for rel, violations in sorted(bad.items()):
            for v in violations:
                print(f"{os.path.join(root, rel)}: {v}")
                total_bad += 1
    if total_bad:
        print(f"record schema check FAILED: {total_bad} violation(s)")
        return 1
    from coda_tpu.telemetry.recorder import (
        RECORD_SCHEMA_VERSION,
        SESSION_SCHEMA_VERSION,
    )

    print(f"record schema check clean: {total_checked} artifact(s) "
          f"validated against record v{RECORD_SCHEMA_VERSION} / "
          f"stream v{SESSION_SCHEMA_VERSION}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
