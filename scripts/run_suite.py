"""In-process benchmark sweep: every task x method x seed in ONE process.

TPU-native counterpart of the reference's SLURM fan-out
(reference ``scripts/launch_all_methods.py``): instead of one cluster job
per task-method pair, the whole sweep runs in-process — seeds vmapped,
compiled programs shared across same-shape tasks, results in the same
MLflow-schema DB with DB-checked resume. Use ``launch_all_methods.py`` only
when tasks must spread across hosts.

    python scripts/run_suite.py --pred-dir data --db coda.sqlite \
        --methods iid,uncertainty,coda --seeds 5 --iters 100
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # repo-root invocation


DEFAULT_METHODS = "iid,uncertainty,coda,activetesting,vma,model_picker"


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pred-dir", default="data")
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--methods", default=DEFAULT_METHODS)
    p.add_argument("--tasks", default=None,
                   help="comma-separated subset (default: all in --pred-dir)")
    p.add_argument("--seeds", type=int, default=5)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--loss", default="acc")
    p.add_argument("--force-rerun", action="store_true")
    p.add_argument("--no-db", action="store_true")
    p.add_argument("--platform", default=None)
    p.add_argument("--mesh", default=None, metavar="AXIS=K,...",
                   help="shard each task tensor over a device mesh, "
                        "e.g. data=8 or data=4,model=2")
    p.add_argument("--task-batch", action="store_true",
                   help="batch same-size tasks into one vmapped program "
                        "per method (SuiteRunner.run_batched); groups by "
                        "file size — a size collision across shapes fails "
                        "loudly at dispatch. Incompatible with --mesh.")
    p.add_argument("--suite-devices", default=None, metavar="auto|N",
                   help="with --task-batch (implied): schedule independent "
                        "task-method dispatches across this many local "
                        "devices ('auto' = all) — the task-parallel "
                        "scheduler (engine/scheduler.py)")
    p.add_argument("--suite-hosts", type=int, default=None, metavar="H",
                   help="with --suite-devices: two-level FLEET placement — "
                        "chunks go to H host groups by weighted LPT "
                        "(weight = the group's device count), then to "
                        "devices within each group. The in-process "
                        "stand-in for placing dispatches across serve "
                        "fleet hosts (engine/scheduler.plan_fleet_schedule)")
    p.add_argument("--schedule", default="lpt", choices=["lpt", "fifo"],
                   help="with --suite-devices: dispatch order (lpt = "
                        "longest-processing-time-first off the per-family "
                        "warm cost profile)")
    p.add_argument("--cost-profile", default=None, metavar="BENCH.json",
                   help="with --suite-devices: JSON artifact carrying "
                        "per_family_warm_s/per_method_warm_s (a prior "
                        "bench_suite --out capture) to seed LPT costs; "
                        "default uniform")
    p.add_argument("--telemetry-dir", default=None,
                   help="write trace.json (Perfetto per-device dispatch "
                        "lanes) + telemetry.json (recompiles, HBM "
                        "watermarks) + metrics.prom there; scalars also "
                        "flush into --db")
    p.add_argument("--record-dir", default=None,
                   help="decision flight recorder: write each (task, "
                        "method) pair's seed-0 probe as a per-round "
                        "provenance record under per-(family, method) "
                        "streams <dir>/<family>__<method>/<task>/; "
                        "diff/verify with `python -m coda_tpu.cli replay`")
    p.add_argument("--record-topk", type=int, default=8,
                   help="top-k scores captured per round (--record-dir)")
    args = p.parse_args(argv)
    if args.suite_devices is not None:
        args.task_batch = True  # scheduling runs through run_batched
    if args.task_batch and args.mesh:
        p.error("--task-batch is per-device (the task axis would need its "
                "own mesh dimension); drop one of the flags")

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    from coda_tpu.data import Dataset, find_task_file, list_tasks
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.tracking import TrackingStore

    tasks = (args.tasks.split(",") if args.tasks
             else list_tasks(args.pred_dir))
    if not tasks:
        raise SystemExit(f"no tasks under {args.pred_dir}")
    # lazy loaders ordered by file size (shape proxy): tasks stream through
    # HBM one at a time, same-size tasks run consecutively for compile reuse
    import os

    paths = []
    for t in tasks:
        fp = find_task_file(args.pred_dir, t)
        if fp is None:
            raise SystemExit(f"no data file for task {t!r}")
        paths.append((os.path.getsize(fp), fp, t))
    sharding = None
    if args.mesh:
        from coda_tpu.parallel import mesh_from_spec, preds_sharding

        sharding = preds_sharding(mesh_from_spec(args.mesh))

    datasets = [
        (lambda fp=fp, t=t: Dataset.from_file(
            fp, name=t, sharding=sharding, unsharded_fallback=True))
        for _, fp, t in sorted(paths)
    ]

    telemetry = None
    if args.telemetry_dir:
        from coda_tpu.telemetry import Telemetry

        telemetry = Telemetry(out_dir=args.telemetry_dir)

    store = None if args.no_db else TrackingStore(args.db)
    runner = SuiteRunner(iters=args.iters, seeds=args.seeds, loss=args.loss,
                         telemetry=telemetry, record_dir=args.record_dir,
                         record_topk=args.record_topk)
    t0 = time.perf_counter()
    if args.task_batch:
        # group loaders by file size (the same shape proxy the sort uses);
        # run_batched validates real shape agreement per group
        groups: dict = {}
        for size, fp, t in sorted(paths):
            groups.setdefault(size, []).append(
                lambda fp=fp, t=t: Dataset.from_file(
                    fp, name=t, sharding=None, unsharded_fallback=True))
        cost_profile = None
        if args.cost_profile:
            with open(args.cost_profile) as f:
                cost_profile = json.load(f)
        results = runner.run_batched(
            list(groups.values()), args.methods.split(","), store=store,
            force_rerun=args.force_rerun, devices=args.suite_devices,
            schedule=args.schedule, cost_profile=cost_profile,
            hosts=args.suite_hosts)
    else:
        results = runner.run(datasets, args.methods.split(","), store=store,
                             force_rerun=args.force_rerun)
    wall = time.perf_counter() - t0
    stats = getattr(runner, "last_stats", {})
    line = {
        "metric": "suite-wall-clock",
        "tasks": len(datasets),
        "methods": len(args.methods.split(",")),
        "seeds": args.seeds,
        "iters": args.iters,
        "pairs_run": len(results),
        "value": round(wall, 2),
        "unit": "seconds",
    }
    if args.suite_devices is not None:
        line["n_devices"] = stats.get("n_devices")
        line["schedule"] = stats.get("schedule")
        line["occupancy"] = stats.get("occupancy")
        line["compute_s"] = round(stats.get("compute_s", 0.0), 2)
        line["compute_device_s"] = round(
            stats.get("compute_device_s", 0.0), 2)
    if args.record_dir:
        line["record_dir"] = args.record_dir
    if telemetry is not None:
        paths = telemetry.write(extra={"suite": {
            k: stats.get(k) for k in ("total_s", "compute_s",
                                      "compute_device_s", "n_devices",
                                      "schedule", "occupancy")
            if k in stats}})
        if store is not None:
            telemetry.flush_to_store(store, experiment="suite",
                                     run_name="suite-telemetry")
        line["telemetry"] = paths.get("telemetry")
    print(json.dumps(line))


if __name__ == "__main__":
    main()
