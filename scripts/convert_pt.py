"""Convert reference ``.pt`` prediction tensors to ``.npy`` (torch-free IO).

The benchmark data for the reference ships as torch-saved tensors
(``<task>.pt`` + ``<task>_labels.pt``); converting once to ``.npy`` lets the
TPU framework load them with plain numpy on hosts without torch.

Usage:
    python scripts/convert_pt.py data/cifar10_5592.pt            # one file
    python scripts/convert_pt.py --data-dir data --out-dir npy/  # whole dir
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def convert(pt_path: str, out_dir: str | None = None) -> str:
    import torch

    out_dir = out_dir or os.path.dirname(pt_path)
    os.makedirs(out_dir, exist_ok=True)
    base = os.path.splitext(os.path.basename(pt_path))[0]
    out = os.path.join(out_dir, base + ".npy")
    t = torch.load(pt_path, map_location="cpu", weights_only=True)
    arr = t.detach().cpu().numpy()
    # prediction tensors to fp32, label vectors to int32
    arr = arr.astype(np.int32) if arr.ndim == 1 else arr.astype(np.float32)
    np.save(out, arr)
    print(f"{pt_path} -> {out}  shape={arr.shape} dtype={arr.dtype}")
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("files", nargs="*", help=".pt files to convert")
    p.add_argument("--data-dir", default=None, help="convert every .pt here")
    p.add_argument("--out-dir", default=None)
    args = p.parse_args(argv)

    files = list(args.files)
    if args.data_dir:
        files += sorted(
            os.path.join(args.data_dir, f)
            for f in os.listdir(args.data_dir) if f.endswith(".pt")
        )
    if not files:
        p.error("no input files (pass paths or --data-dir)")
    for f in files:
        convert(f, args.out_dir)


if __name__ == "__main__":
    main()
