"""True-size DomainNet-scale task on ONE chip (VERDICT r4 item 4).

The reference's largest benchmark tensors are ~10 GB fp32
(sketch_real / painting_real, reference ``paper/fig3.py:129-193``); the
suite's FAMILIES config scales DomainNet 5-33x down so 26 tasks stream
through one chip. This script runs ONE task at the REAL size — (H=400,
N=50000, C=126) = 10.08 GB fp32, the sketch_real scale — end-to-end on
the chip: the prediction tensor is generated ON DEVICE (a 10 GB host
transfer through the tunnel would dominate everything), the auto
eig_mode budget picks the tier (factored — the 10 GB incremental cache
is over budget; its (C, H, G) tables are 206 MB), and a full CODA
labeling run executes with per-round marginal timing.

    python scripts/bench_truesize.py --out BENCH_TPU_TRUESIZE_r05.json

Also attempts the explicit incremental+bfloat16 configuration (10 GB
preds + 5 GB bf16 cache ~ 15 GB — at the edge of a v5e's 16 GB HBM) and
records success or the OOM, so the budget constants stay empirically
pinned.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def make_device_task(H: int, N: int, C: int, seed: int = 7):
    """Synthetic task generated on device (no host transfer).

    Same structure as data.make_synthetic_task (accuracy-spread models,
    peaked softmax at the predicted class) — host-side numpy there for
    trace reproducibility; here the 10 GB tensor must be born in HBM.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gen(key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        labels = jax.random.randint(k1, (N,), 0, C, dtype=jnp.int32)
        accs = jnp.linspace(0.35, 0.9, H)
        accs = jax.random.permutation(k2, accs)
        logits = jax.random.normal(k3, (H, N, C), dtype=jnp.float32)
        correct = jax.random.uniform(k4, (H, N)) < accs[:, None]
        offsets = jax.random.randint(k2, (H, N), 1, C)
        wrong = (labels[None, :] + offsets) % C
        pred_cls = jnp.where(correct, labels[None, :], wrong)
        logits = logits + 4.0 * jax.nn.one_hot(pred_cls, C,
                                               dtype=jnp.float32)
        return jax.nn.softmax(logits, axis=-1), labels

    return gen(jax.random.PRNGKey(seed))


def run_config(preds, labels, eig_opts: dict, iters_lo: int,
               iters_hi: int) -> dict:
    import jax
    import jax.numpy as jnp

    from coda_tpu.engine.loop import make_batched_experiment_fn
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import (
        resolve_eig_backend,
        resolve_eig_mode,
        resolve_pi_update,
    )

    H, N, C = preds.shape
    hp = CODAHyperparams(eig_chunk=2048, **eig_opts)
    mode = resolve_eig_mode(hp, H, N, C)
    rec: dict = {
        "eig_opts": eig_opts,
        "resolved": {
            "eig_mode": mode,
            "eig_backend": resolve_eig_backend(hp, mode, N),
            "pi_update": resolve_pi_update(hp, N),
        },
    }
    keys = jnp.stack([jax.random.PRNGKey(0)])

    def fn_for(iters):
        return jax.jit(make_batched_experiment_fn(
            lambda p: make_coda(p, hp), iters=iters))

    try:
        t0 = time.perf_counter()
        r = fn_for(iters_lo)(preds, labels, keys)
        reg_lo = np.asarray(r.regret)
        rec["compile_plus_first_run_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        r = fn_for(iters_lo)(preds, labels, keys)
        np.asarray(r.regret)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        r = fn_for(iters_hi)(preds, labels, keys)
        reg_hi = np.asarray(r.regret)
        t_hi = time.perf_counter() - t0
        rec.update({
            "iters": [iters_lo, iters_hi],
            "warm_wall_s": [round(t_lo, 2), round(t_hi, 2)],
            "marginal_s_per_round": round(
                (t_hi - t_lo) / (iters_hi - iters_lo), 4),
            "steps_per_sec_marginal": round(
                (iters_hi - iters_lo) / max(1e-9, t_hi - t_lo), 2),
            "regret_final": float(reg_hi[0, -1]),
            "finite": bool(np.isfinite(reg_hi).all()
                           and np.isfinite(reg_lo).all()),
            "ok": True,
        })
    except Exception as e:  # OOM lands here; record and continue
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--small", action="store_true",
                    help="smoke shape for CI, not the 10 GB artifact")
    ap.add_argument("--iters", type=int, nargs=2, default=(10, 30),
                    metavar=("LO", "HI"))
    args = ap.parse_args(argv)

    import jax

    H, N, C = (40, 2000, 26) if args.small else (400, 50_000, 126)
    dev = jax.devices()[0]
    t0 = time.perf_counter()
    preds, labels = make_device_task(H, N, C)
    preds.block_until_ready()
    gen_s = time.perf_counter() - t0

    out = {
        "task": f"sketch_real-scale synthetic ({H}x{N}x{C}, "
                f"{4 * H * N * C / 2**30:.2f} GiB fp32; reference "
                "sketch_real/painting_real are 9.99 GB — "
                "paper/fig3.py:129-193)",
        "device": dev.device_kind,
        "datagen_on_device_s": round(gen_s, 2),
        "configs": [],
    }
    lo, hi = args.iters
    # auto: the budget must route a 10 GB task to factored
    out["configs"].append(run_config(preds, labels, {}, lo, hi))
    # explicit incremental + bf16 cache: 10 GB preds + 5 GB cache — the
    # documented edge of one v5e's HBM; exact pi update (the delta path's
    # transposed layout would double the preds footprint)
    out["configs"].append(run_config(
        preds, labels,
        {"eig_mode": "incremental", "eig_cache_dtype": "bfloat16",
         "pi_update": "exact"}, lo, hi))
    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
    if stats:
        out["hbm_peak_bytes_in_use"] = stats.get("peak_bytes_in_use")
        out["hbm_bytes_limit"] = stats.get("bytes_limit")
    out["ok"] = out["configs"][0]["ok"]
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
