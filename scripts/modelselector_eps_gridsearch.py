"""Unsupervised ModelPicker epsilon tuning via grid search.

Capability parity with reference
``scripts/modelselector/modelselector_eps_gridsearch_v2.py``: a
majority-vote pseudo-oracle stands in for labels; for each candidate epsilon,
ModelPicker runs on random realisations (random subsets of the pool) and is
scored by how often its best-model guess lands in the truly-best set
(``avg_success``) and how fast the success rate crosses a threshold
(``fastest_t``, invalidated when the smoothed curve sits below threshold).
Results accumulate in ``best_epsilons.json`` with skip-if-present resume.

TPU-native execution: the reference runs eps x 1000 realisations x 1000
budget steps as nested Python loops (hours per task). Here one realisation
is a ``lax.scan`` over budget steps on the *hard* argmax predictions only
(ModelPicker never reads the soft scores), realisations batch under ``vmap``
(chunked with ``lax.map`` as a memory valve), and the epsilon grid is the
only Python loop. The whole search is a handful of compiled launches.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

DEFAULT_EPSILONS = ("0.35,0.36,0.37,0.38,0.39,0.40,0.41,0.42,0.43,0.44,"
                    "0.45,0.46,0.47,0.48,0.49")


def majority_vote_labels(hard_preds: np.ndarray, C: int) -> np.ndarray:
    """(N, H) int -> (N,) majority class per point (smallest wins ties,
    matching the reference's np.unique-based vote)."""
    N = hard_preds.shape[0]
    votes = np.zeros((N, C), np.int32)
    np.add.at(votes, (np.arange(N)[:, None], hard_preds), 1)
    return votes.argmax(axis=1).astype(np.int32)


def _run_realisations(hard_preds_sub, oracle_sub, C, gamma, budget, key,
                      real_chunk=64):
    """Batched ModelPicker runs. hard_preds_sub: (R, P, H); oracle: (R, P).

    Returns (success (R, T), acc (R, T)) — per step, whether the guess is in
    the truly-best set and its true (pseudo-oracle) accuracy.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from coda_tpu.ops.masked import masked_argmin_tiebreak
    from coda_tpu.selectors.modelpicker import expected_entropies

    R, P, H = hard_preds_sub.shape

    def one(args):
        hp, orc, k = args  # (P, H), (P,), key
        disagree = (hp != hp[:, :1]).any(axis=1)
        correct = (hp == orc[:, None])                  # (P, H)
        true_acc = correct.mean(axis=0)                 # (H,)
        best_set = true_acc == true_acc.max()

        def step(carry, k_step):
            unlabeled, posterior, counts = carry
            k_sel, k_best = jax.random.split(k_step)
            ent = expected_entropies(hp, posterior, gamma, C)
            cand = disagree & unlabeled
            cand = jnp.where(cand.any(), cand, unlabeled)
            idx, _ = masked_argmin_tiebreak(k_sel, ent, cand)
            agree = (hp[idx] == orc[idx]).astype(jnp.float32)
            posterior = posterior * jnp.power(gamma, agree)
            posterior = posterior / posterior.sum()
            counts = counts + agree.astype(jnp.int32)
            guess, _ = masked_argmin_tiebreak(
                k_best, -counts.astype(jnp.float32), jnp.ones((H,), bool))
            return ((unlabeled.at[idx].set(False), posterior, counts),
                    (best_set[guess], true_acc[guess]))

        keys = jax.random.split(k, budget)
        init = (jnp.ones((P,), bool), jnp.full((H,), 1.0 / H),
                jnp.zeros((H,), jnp.int32))
        _, (succ, acc) = lax.scan(step, init, keys)
        return succ, acc

    keys = jax.random.split(key, R)
    return jax.jit(
        lambda a: lax.map(one, a, batch_size=min(real_chunk, R))
    )((hard_preds_sub, oracle_sub, keys))


def smooth_data(x: np.ndarray, kernel_size: int = 5) -> np.ndarray:
    kernel = np.ones(kernel_size) / kernel_size
    pad = kernel_size // 2
    xp = np.pad(x, (pad, pad), "constant", constant_values=(x[0], x[-1]))
    return np.convolve(xp, kernel, "valid")


def run_grid_search(preds, eps_list, iterations=1000, pool_size=1000,
                    budget=1000, threshold=0.9, seed=0, real_chunk=64):
    """preds: (H, N, C) array-like. Returns the reference's result dict."""
    import jax
    import jax.numpy as jnp

    preds = np.asarray(preds)
    H, N, C = preds.shape
    hard = preds.argmax(-1).T.astype(np.int32)          # (N, H)
    majority = majority_vote_labels(hard, C)             # (N,)

    pool_size = min(pool_size, N)
    budget = min(budget, pool_size)
    rng = np.random.default_rng(seed)
    real_idx = np.stack([rng.permutation(N)[:pool_size]
                         for _ in range(iterations)])    # (R, P)
    hard_sub = jnp.asarray(hard[real_idx])               # (R, P, H)
    orc_sub = jnp.asarray(majority[real_idx])            # (R, P)

    results = {}
    for i, eps in enumerate(eps_list):
        gamma = (1.0 - eps) / eps
        succ, acc = _run_realisations(
            hard_sub, orc_sub, C, gamma, budget,
            jax.random.PRNGKey(seed * 1000 + i), real_chunk=real_chunk)
        success_mean = np.asarray(succ, dtype=np.float64).mean(axis=0)
        acc_mean = np.asarray(acc, dtype=np.float64).mean(axis=0)
        smooth = smooth_data(success_mean, kernel_size=5)
        avg_success = float(success_mean.mean())
        t_fast = int(np.argmax(success_mean >= threshold))
        if smooth[t_fast] <= threshold:
            t_fast = float("inf")
        results[eps] = {
            "success_mean": success_mean.tolist(),
            "acc_mean": acc_mean.tolist(),
            "avg_success": avg_success,
            "fastest_t": t_fast,
        }
        print(f"eps={eps:.3f} avg_success={avg_success:.3f} fastest_t={t_fast}")

    best_avg = max(results.items(), key=lambda x: x[1]["avg_success"])[0]
    best_fast = min(results.items(), key=lambda x: x[1]["fastest_t"])[0]
    print("\nOptimal epsilon (avg_success):", best_avg)
    print("Optimal epsilon (fastest):", best_fast)
    return {"best_avg": best_avg, "best_fast": best_fast, "metrics": results}


def load_results(path):
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {}


def save_result(path, key, res):
    """Reload-merge-write (the reference's concurrency workaround; kept, but
    atomic via replace so concurrent writers can't truncate each other)."""
    overall = load_results(path)
    overall[key] = {"best_avg": res["best_avg"], "best_fast": res["best_fast"]}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(overall, f, indent=2)
    os.replace(tmp, path)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preds", help="path to (H,N,C) tensor file")
    p.add_argument("--pred-dir", default="data")
    p.add_argument("--task", default=None)
    p.add_argument("--epsilons", default=DEFAULT_EPSILONS)
    p.add_argument("--iterations", type=int, default=1000)
    p.add_argument("--pool-size", type=int, default=1000)
    p.add_argument("--budget", type=int, default=1000)
    p.add_argument("--threshold", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--real-chunk", type=int, default=64,
                   help="realisations per compiled map step (memory valve)")
    p.add_argument("--results", default="best_epsilons.json")
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    from coda_tpu.data import Dataset

    eps_list = [float(e) for e in args.epsilons.split(",")]

    def search_one(key, path):
        overall = load_results(args.results)
        if key in overall:
            print(key, "already computed; skipping")
            return
        ds = Dataset.from_file(path)
        res = run_grid_search(
            ds.preds, eps_list, iterations=args.iterations,
            pool_size=args.pool_size, budget=args.budget,
            threshold=args.threshold, seed=args.seed,
            real_chunk=args.real_chunk)
        save_result(args.results, key, res)

    if args.task or args.preds:
        path = args.preds or None
        if args.task and not path:
            from coda_tpu.data import find_task_file

            path = find_task_file(args.pred_dir, args.task)
        if not path:
            p.error(f"no prediction file for task {args.task}")
        search_one(args.task or os.path.basename(path), path)
    else:
        from coda_tpu.data import find_task_file, list_tasks

        tasks = list_tasks(args.pred_dir)
        if not tasks:
            p.error("no prediction files found")
        for t in tasks:
            # key by bare task name so --task and directory-mode runs share
            # the same resume entries
            search_one(t, find_task_file(args.pred_dir, t))


if __name__ == "__main__":
    main()
