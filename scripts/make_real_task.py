"""Build a REAL (non-synthetic) model-selection task from bundled data.

The reference validates on 26 real prediction tensors downloaded from its
release artifacts (reference ``README.md:53``); none are fetchable in this
offline environment, so this script reconstructs the same *kind* of artifact
from first principles: a pool of genuinely different models — varied
families, capacities, and regularization, some strong and some deliberately
weak — trained on a real dataset bundled with sklearn, scored on a held-out
evaluation split. The output is a native ``<task>.npz`` (post-softmax
``(H, N, C)`` preds + labels + class names) consumed by ``main.py`` exactly
like any reference task tensor.

Datasets: ``digits`` (1797 8x8 scans, C=10), ``breast_cancer`` (569 points,
C=2 — the binary case that exercises the Beta/diag-prior edge on real
data), ``wine`` (178 points, C=3), ``iris`` (150 points, C=3; build with
``--test-frac 0.7`` so the 100-round budget fits), and ``digits_shift``
(models train on CLEAN scans, the eval half is rotated + noise-corrupted —
the reference benchmark's train-domain != eval-domain structure).

Usage: python scripts/make_real_task.py [--dataset digits] [--out data/digits.npz]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)


def model_pool(seed: int = 0):
    """A diverse pool: (name, estimator) pairs, all with predict_proba."""
    from sklearn.ensemble import (
        GradientBoostingClassifier,
        RandomForestClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.naive_bayes import GaussianNB
    from sklearn.neighbors import KNeighborsClassifier
    from sklearn.neural_network import MLPClassifier
    from sklearn.svm import SVC
    from sklearn.tree import DecisionTreeClassifier

    return [
        ("logreg_c0.01", LogisticRegression(C=0.01, max_iter=2000)),
        ("logreg_c1", LogisticRegression(C=1.0, max_iter=2000)),
        ("logreg_c100", LogisticRegression(C=100.0, max_iter=2000)),
        ("mlp_16", MLPClassifier((16,), max_iter=600, random_state=seed)),
        ("mlp_64", MLPClassifier((64,), max_iter=600, random_state=seed + 1)),
        ("mlp_64x32", MLPClassifier((64, 32), max_iter=600,
                                    random_state=seed + 2)),
        ("rf_depth3", RandomForestClassifier(
            n_estimators=50, max_depth=3, random_state=seed)),
        ("rf_depth10", RandomForestClassifier(
            n_estimators=100, max_depth=10, random_state=seed + 1)),
        ("gboost", GradientBoostingClassifier(
            n_estimators=60, max_depth=2, random_state=seed)),
        ("knn_3", KNeighborsClassifier(3)),
        ("knn_25", KNeighborsClassifier(25)),
        ("tree_depth4", DecisionTreeClassifier(
            max_depth=4, random_state=seed)),
        ("gauss_nb", GaussianNB()),
        ("svc_rbf", SVC(probability=True, random_state=seed)),
    ]


def model_pool_large(seed: int = 0):
    """An 80-model pool — the shape of the reference's MSV family
    (H=80, C=10, ``/root/reference/paper/fig3.py``): broad hyperparameter
    grids across eight families, spanning strong to deliberately weak."""
    from sklearn.discriminant_analysis import (
        LinearDiscriminantAnalysis,
        QuadraticDiscriminantAnalysis,
    )
    from sklearn.ensemble import (
        GradientBoostingClassifier,
        RandomForestClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.naive_bayes import GaussianNB
    from sklearn.neighbors import KNeighborsClassifier
    from sklearn.neural_network import MLPClassifier
    from sklearn.svm import SVC
    from sklearn.tree import DecisionTreeClassifier

    pool = []
    for c in np.logspace(-3, 3, 8):
        pool.append((f"logreg_c{c:.3g}", LogisticRegression(
            C=float(c), max_iter=2000)))
    for i, size in enumerate([(8,), (16,), (32,), (64,), (128,), (64, 32),
                              (32, 16), (128, 64), (16, 8), (256,),
                              (8, 8), (64, 64)]):
        pool.append((f"mlp_{'x'.join(map(str, size))}", MLPClassifier(
            size, max_iter=400, random_state=seed + i)))
    for depth in (2, 3, 5, 8, None):
        for n_est in (20, 100):
            pool.append((f"rf_d{depth}_n{n_est}", RandomForestClassifier(
                n_estimators=n_est, max_depth=depth, random_state=seed)))
    for depth in (1, 2, 3):
        for n_est in (20, 60):
            pool.append((f"gb_d{depth}_n{n_est}",
                         GradientBoostingClassifier(
                             n_estimators=n_est, max_depth=depth,
                             random_state=seed)))
    for k in (1, 3, 5, 9, 15, 25, 45, 75):
        pool.append((f"knn_{k}", KNeighborsClassifier(k)))
    for k in (3, 9, 25, 75):
        pool.append((f"knn_{k}_dist", KNeighborsClassifier(
            k, weights="distance")))
    for depth in (2, 3, 4, 6, 8, None):
        pool.append((f"tree_d{depth}", DecisionTreeClassifier(
            max_depth=depth, random_state=seed)))
    for vs in (1e-9, 1e-6, 1e-3, 1e-1, 1.0):
        pool.append((f"gnb_vs{vs:g}", GaussianNB(var_smoothing=vs)))
    for c in (0.1, 1.0, 10.0):
        for gamma in ("scale", 0.01, 0.1):
            pool.append((f"svc_c{c:g}_g{gamma}", SVC(
                C=c, gamma=gamma, probability=True, random_state=seed)))
    pool.append(("lda", LinearDiscriminantAnalysis()))
    pool.append(("qda", QuadraticDiscriminantAnalysis(reg_param=0.1)))
    from sklearn.ensemble import AdaBoostClassifier, ExtraTreesClassifier
    from sklearn.naive_bayes import BernoulliNB

    for n_est in (20, 50, 100):
        pool.append((f"ada_n{n_est}", AdaBoostClassifier(
            n_estimators=n_est, random_state=seed)))
    for depth in (3, 8, None):
        pool.append((f"xtree_d{depth}", ExtraTreesClassifier(
            n_estimators=50, max_depth=depth, random_state=seed)))
    for b in (0.25, 0.5):
        pool.append((f"bnb_b{b:g}", BernoulliNB(binarize=b)))
    for i in (100, 200):
        pool.append((f"mlp_32_s{i}", MLPClassifier(
            (32,), max_iter=400, random_state=seed + i)))
    assert len(pool) == 80, len(pool)
    return pool


DATASETS = {
    "digits": ("load_digits", 16.0),
    # the MSV-family shape (H=80 genuinely different models, C=10) on the
    # same real NIST scans — the reference benchmark's widest model axis
    "digits_h80": ("load_digits", 16.0),
    "breast_cancer": ("load_breast_cancer", None),  # None -> standardize
    "wine": ("load_wine", None),
    "iris": ("load_iris", None),
    # distribution shift: models train on CLEAN scans, the eval half is
    # corrupted (rotation + pixel noise) — the structure of the reference's
    # DomainNet/WILDS families (train domain != eval domain), where model
    # ranking under shift is the thing the selector must discover
    "digits_shift": ("load_digits", 16.0),
}


def stratified_split(x: np.ndarray, y: np.ndarray, test_frac: float = 0.5,
                     seed: int = 0):
    """THE train/eval split, shared with scripts/train_tiny_clip.py so the
    `digits` task tensors and the rendered digit images can never
    desynchronize. Returns (x_tr, x_ev, y_tr, y_ev, i_tr, i_ev)."""
    from sklearn.model_selection import train_test_split

    idx = np.arange(len(y))
    return train_test_split(
        x, y.astype(np.int32), idx,
        test_size=test_frac, random_state=seed, stratify=y,
    )


def shift_digits(x_ev: np.ndarray, seed: int = 0) -> np.ndarray:
    """Rotate each real 8x8 scan by a random +/-25..40 degrees and add
    pixel noise — a reproducible domain shift on real data."""
    from scipy.ndimage import rotate

    rng = np.random.default_rng(seed + 17)
    out = np.empty_like(x_ev)
    for i, vec in enumerate(x_ev):
        ang = rng.uniform(25.0, 40.0) * rng.choice([-1.0, 1.0])
        img = rotate(vec.reshape(8, 8), ang, reshape=False, order=1,
                     mode="constant", cval=0.0)
        img = img + rng.normal(0.0, 1.5, size=img.shape)
        out[i] = np.clip(img, 0.0, 16.0).reshape(-1)
    return out.astype(np.float32)


def build(out: str, test_frac: float = 0.5, seed: int = 0,
          dataset: str = "digits") -> dict:
    import sklearn.datasets

    loader, scale = DATASETS[dataset]
    data = getattr(sklearn.datasets, loader)()
    x = data.data.astype(np.float32)
    x_tr, x_ev, y_tr, y_ev, _, _ = stratified_split(
        x, data.target, test_frac, seed)
    if dataset == "digits_shift":  # corrupt the eval half BEFORE scaling
        x_ev = shift_digits(x_ev, seed)
    if scale:  # digits pixels are 0..16 (fixed scale)
        x_tr, x_ev = x_tr / scale, x_ev / scale
    else:  # tabular sets standardize with TRAIN statistics only (no
        #    eval-set leakage into the preprocessing models train on)
        mu, sd = x_tr.mean(0), np.clip(x_tr.std(0), 1e-6, None)
        x_tr, x_ev = (x_tr - mu) / sd, (x_ev - mu) / sd

    pool = (model_pool_large(seed) if dataset == "digits_h80"
            else model_pool(seed))
    C = len(data.target_names)
    preds = np.zeros((len(pool), len(y_ev), C), dtype=np.float32)
    accs = {}
    for h, (name, est) in enumerate(pool):
        est.fit(x_tr, y_tr)
        p = est.predict_proba(x_ev).astype(np.float32)
        # some estimators can drop classes absent from their training view;
        # guard the invariant the framework assumes
        assert p.shape == (len(y_ev), C), (name, p.shape)
        preds[h] = p / np.clip(p.sum(-1, keepdims=True), 1e-12, None)
        accs[name] = float((p.argmax(-1) == y_ev).mean())

    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    np.savez_compressed(
        out,
        preds=preds,
        labels=y_ev.astype(np.int32),
        classes=np.asarray([str(c) for c in data.target_names]),
        models=np.asarray([n for n, _ in pool]),
    )
    return accs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", default="digits", choices=sorted(DATASETS))
    ap.add_argument("--out", default=None,
                    help="output path (default data/<dataset>.npz)")
    ap.add_argument("--test-frac", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    out = args.out or os.path.join(REPO, "data", f"{args.dataset}.npz")
    accs = build(out, args.test_frac, args.seed, args.dataset)
    print(f"wrote {out}")
    for name, acc in sorted(accs.items(), key=lambda kv: -kv[1]):
        print(f"  {name:14s} acc={acc:.4f}")
    best = max(accs.values())
    spread = best - min(accs.values())
    print(f"pool: {len(accs)} models, best acc {best:.4f}, spread {spread:.4f}")


if __name__ == "__main__":
    main()
