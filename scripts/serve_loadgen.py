"""Closed-loop load generator for the serving layer -> BENCH_SERVE_*.json.

Spins up an in-process :class:`coda_tpu.serve.ServeApp` + HTTP server (or
targets a running one via ``--url``), then drives closed-loop sessions:
each opens, labels ``--labels`` proposed items (answering ``idx % C`` — the
serving cost is label-independent), and closes. Reports sessions/sec,
requests/sec, client-side latency percentiles, the server's own dispatch
metrics (batch occupancy — the number the subsystem exists to maximize),
and the **latency breakdown** (queue-wait vs dispatch vs slab-step, from
the server's phase rings and telemetry spans) into one JSON artifact — so
a p99 regression is attributable mechanically, not by eyeball.

Three arrival models:

  * default — ``--workers`` threads free-run through the session budget;
    occupancy emerges from the batcher's coalescing (the thread-client
    number, comparable to r06);
  * ``--mux`` — sessions are asyncio coroutines multiplexed on ONE event
    loop (``--workers`` bounds concurrent live sessions), driving the
    app's async verbs in-process or — with ``--http`` — one persistent
    keep-alive connection per session against the asyncio front door.
    This is how 256+ concurrent sessions are driven without 256 OS
    threads contending for the GIL, i.e. without the client becoming the
    tail;
  * ``--lockstep`` — workers rendezvous at a barrier each round while the
    batcher is paused, so every round's W requests ride ONE dispatch. This
    is the deterministic-occupancy mode the tier-1 smoke test pins ≥16
    sessions/dispatch with (in-process only).

    python scripts/serve_loadgen.py --mux --workers 256 --sessions 256 \
        --synthetic 8,512,10 --out BENCH_SERVE_cpu.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
import time

# importable from any cwd (the aggregate_results.py convention)
sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


# ---------------------------------------------------------------------------
# clients: in-process (drives a ServeApp directly) or HTTP (urllib, stdlib)
# ---------------------------------------------------------------------------

class InprocClient:
    def __init__(self, app):
        self.app = app

    def open(self, seed):
        return self.app.open_session(seed=seed)

    def label(self, sid, label):
        return self.app.label(sid, label)

    def close(self, sid):
        return self.app.close_session(sid)

    def stats(self):
        return self.app.stats()


class HttpClient:
    def __init__(self, url):
        self.url = url.rstrip("/")

    def _req(self, method, path, body=None):
        import urllib.request

        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def open(self, seed):
        return self._req("POST", "/session", {"seed": seed})

    def label(self, sid, label):
        return self._req("POST", f"/session/{sid}/label", {"label": label})

    def close(self, sid):
        return self._req("DELETE", f"/session/{sid}")

    def stats(self):
        return self._req("GET", "/stats")


class AsyncConn:
    """One persistent keep-alive connection to the asyncio front door —
    each mux session coroutine holds its own, so 256 concurrent sessions
    are 256 sockets on one event loop, not 256 threads."""

    def __init__(self, host, port):
        self.host, self.port = host, port
        self.r = self.w = None

    async def connect(self):
        self.r, self.w = await asyncio.open_connection(self.host, self.port)

    async def req(self, method, path, body=None):
        data = b"" if body is None else json.dumps(body).encode()
        head = (f"{method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(data)}\r\n\r\n")
        self.w.write(head.encode() + data)
        await self.w.drain()
        line = await self.r.readline()
        status = int(line.split()[1])
        clen = 0
        while True:
            h = await self.r.readline()
            if not h or h in (b"\r\n", b"\n"):
                break
            k, _, v = h.decode().partition(":")
            if k.strip().lower() == "content-length":
                clen = int(v)
        payload = await self.r.readexactly(clen) if clen else b"{}"
        return status, json.loads(payload)

    def close(self):
        if self.w is not None:
            self.w.close()


# ---------------------------------------------------------------------------
# the closed loop
# ---------------------------------------------------------------------------

def _free_run(client, n_classes, workers, sessions, labels_per_session,
              latencies, errors):
    """Default arrival model: W workers race through the session budget."""
    counter = {"next": 0}
    lock = threading.Lock()

    def take():
        with lock:
            s = counter["next"]
            if s >= sessions:
                return None
            counter["next"] = s + 1
            return s

    def worker():
        while True:
            seed = take()
            if seed is None:
                return
            sid = None
            try:
                t0 = time.perf_counter()
                out = client.open(seed)
                sid = out["session"]
                latencies.append(time.perf_counter() - t0)
                for _ in range(labels_per_session):
                    t0 = time.perf_counter()
                    out = client.label(sid, int(out["idx"]) % n_classes)
                    latencies.append(time.perf_counter() - t0)
                client.close(sid)
                sid = None
            except Exception as e:  # keep the run alive; report at the end
                errors.append(repr(e))
                if sid is not None:
                    # free the slot: capacity == workers, so one leaked
                    # session would starve every later open into SlabFull
                    try:
                        client.close(sid)
                    except Exception:
                        pass

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _mux(app, http_port, n_classes, concurrency, sessions,
         labels_per_session, latencies, errors, ramp_s=0.0):
    """Asyncio arrival model: every session is a coroutine, ``concurrency``
    of them live at once, all multiplexed on one event loop. In-process it
    drives the app's async verbs (the front door's own path, minus TCP);
    with an ``http_port`` each session holds one keep-alive connection to
    the real asyncio server."""

    async def one_inproc(seed):
        t0 = time.perf_counter()
        out = await app.open_session_async(seed=seed)
        latencies.append(time.perf_counter() - t0)
        sid = out["session"]
        try:
            for _ in range(labels_per_session):
                t0 = time.perf_counter()
                out = await app.label_async(sid, int(out["idx"]) % n_classes)
                latencies.append(time.perf_counter() - t0)
        finally:
            await asyncio.get_running_loop().run_in_executor(
                None, app.close_session, sid)

    async def one_http(seed):
        conn = AsyncConn("127.0.0.1", http_port)
        await conn.connect()
        sid = None
        try:
            t0 = time.perf_counter()
            status, out = await conn.req("POST", "/session", {"seed": seed})
            if status != 200:
                raise RuntimeError(f"open -> {status}: {out}")
            latencies.append(time.perf_counter() - t0)
            sid = out["session"]
            for _ in range(labels_per_session):
                t0 = time.perf_counter()
                status, out = await conn.req(
                    "POST", f"/session/{sid}/label",
                    {"label": int(out["idx"]) % n_classes})
                if status != 200:
                    raise RuntimeError(f"label -> {status}: {out}")
                latencies.append(time.perf_counter() - t0)
            await conn.req("DELETE", f"/session/{sid}")
            sid = None
        finally:
            if sid is not None:
                try:
                    await conn.req("DELETE", f"/session/{sid}")
                except Exception:
                    pass
            conn.close()

    one = one_http if http_port is not None else one_inproc

    async def main():
        sem = asyncio.Semaphore(concurrency)

        async def bounded(seed):
            if ramp_s > 0:
                # spread session arrivals over the ramp window: real fleets
                # don't open every session in the same microsecond, and a
                # thundering herd of admissions would otherwise dominate
                # the p99 with a startup transient instead of steady state
                await asyncio.sleep(seed * ramp_s / max(1, sessions))
            async with sem:
                try:
                    await one(seed)
                except Exception as e:
                    errors.append(repr(e))

        await asyncio.gather(*(bounded(s) for s in range(sessions)))

    asyncio.run(main())


def _lockstep(app, client, n_classes, workers, labels_per_session,
              latencies, errors):
    """Deterministic occupancy: open W sessions, then label all W in
    rounds, pausing the batcher while each round's requests queue up so
    every round is exactly ONE dispatch per bucket. In-process only (needs
    the batcher handle)."""
    sids = []
    for seed in range(workers):
        sids.append(client.open(seed)["session"])
    for _ in range(labels_per_session):
        app.batcher.pause()
        tickets = []
        t0 = time.perf_counter()
        for sid in sids:
            sess = app.store.get(sid)
            cur = sess.last
            tickets.append(app.batcher.submit_label(
                sess, idx=cur["next_idx"],
                label=int(cur["next_idx"]) % n_classes,
                prob=cur["next_prob"]))
        app.batcher.resume()
        for t in tickets:
            try:
                t.wait(60.0)
                latencies.append(time.perf_counter() - t0)
            except Exception as e:
                errors.append(repr(e))
    for sid in sids:
        client.close(sid)


def _span_breakdown(app) -> dict:
    """Mechanical p99 attribution from the telemetry spans: busy seconds
    of the batcher lane split into tick (dispatch incl. host fan-out) and
    step (compiled slab-step execution) — tick minus step is host-side
    build/fan-out, wall minus tick is queue/idle."""
    if app is None:
        return {}
    spans = app.telemetry.spans
    events = spans.events()
    tick_s = sum(t1 - t0 for name, lane, t0, t1, _ in events
                 if name.startswith("tick/"))
    step_s = sum(t1 - t0 for name, lane, t0, t1, _ in events
                 if name.startswith("step/"))
    n_ticks = sum(1 for name, *_ in events if name.startswith("tick/"))
    return {
        "tick_busy_s": tick_s,
        "step_busy_s": step_s,
        "host_overhead_s": max(0.0, tick_s - step_s),
        "n_tick_spans": n_ticks,
    }


def run_loadgen(args) -> dict:
    """Run the configured load and return the report dict (the script's
    JSON payload; the smoke test calls this directly)."""
    from coda_tpu.serve.server import build_app, make_server

    app = srv = None
    warm_s = None
    if args.url:
        client = HttpClient(args.url)
        n_classes = args.classes
    else:
        app = build_app(args)
        # warm synchronously so compilation is excluded from (and reported
        # next to) the traffic measurement — mirroring a production server
        # that passes its readiness gate before taking load
        app.start(warm=not args.no_warm)
        warm_s = (app.warm_info or {}).get("warm_s")
        meta = app.store.task_meta(app.default_task)
        n_classes = len(meta["class_names"])
        if args.http:
            srv = make_server(app, 0)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            client = HttpClient(
                f"http://127.0.0.1:{srv.server_address[1]}")
        else:
            client = InprocClient(app)

    latencies: list = []
    errors: list = []
    t_start = time.perf_counter()
    if args.lockstep:
        if app is None:
            raise SystemExit("--lockstep needs an in-process app (no --url)")
        n_sessions = args.workers
        _lockstep(app, client, n_classes, args.workers, args.labels,
                  latencies, errors)
        mode = "lockstep"
    elif args.mux:
        if app is None:
            raise SystemExit("--mux needs an in-process app (no --url)")
        n_sessions = args.sessions
        _mux(app, srv.server_address[1] if srv is not None else None,
             n_classes, args.workers, args.sessions, args.labels,
             latencies, errors, ramp_s=args.ramp_s)
        mode = "mux"
    else:
        n_sessions = args.sessions
        _free_run(client, n_classes, args.workers, args.sessions,
                  args.labels, latencies, errors)
        mode = "free_run"
    wall = time.perf_counter() - t_start

    stats = client.stats() if app is None else app.stats()
    spans = _span_breakdown(app)
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if app is not None:
        app.drain()

    lat_ms = np.asarray(latencies, np.float64) * 1e3
    n_requests = len(latencies)
    report = {
        "bench": "serve_loadgen",
        "mode": mode,
        "transport": ("http" if (args.url or args.http) else "inproc"),
        "workers": args.workers,
        "sessions": n_sessions,
        "labels_per_session": args.labels,
        "wall_s": wall,
        "warm_s": warm_s,
        "sessions_per_s": n_sessions / wall,
        "requests_per_s": n_requests / wall,
        "latency_ms": {
            "p50": float(np.percentile(lat_ms, 50)) if n_requests else None,
            "p99": float(np.percentile(lat_ms, 99)) if n_requests else None,
            "mean": float(lat_ms.mean()) if n_requests else None,
        },
        "errors": errors[:20],
        "n_errors": len(errors),
        "server": {
            "dispatches": stats.get("dispatches"),
            "requests": stats.get("requests"),
            "max_occupancy": stats.get("max_occupancy"),
            "mean_occupancy": stats.get("mean_occupancy"),
            "mean_queue_depth": stats.get("mean_queue_depth"),
            "dispatch_latency": stats.get("dispatch_latency"),
            "request_latency": stats.get("request_latency"),
        },
        # where a request's time went: queued behind a tick vs the
        # dispatch (host fan-out + step) vs the compiled step itself —
        # the rings give percentiles, the spans give busy-time totals
        "breakdown": {
            "queue_wait": stats.get("queue_wait"),
            "dispatch": stats.get("dispatch_latency"),
            "step": stats.get("step_latency"),
            "spans": spans,
        },
        "warm_pool": stats.get("warm_pool"),
        "config": {
            "method": args.method,
            "capacity": args.capacity,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "max_linger_ms": args.max_linger_ms,
            "step_impl": args.step_impl,
            "donate": not args.no_donate,
            "warm": not args.no_warm,
            "compilation_cache_dir": args.compilation_cache_dir,
            "ramp_s": args.ramp_s,
            "task": args.task or args.synthetic or "default",
        },
    }
    return report


def parse_args(argv=None):
    from coda_tpu.serve.server import parse_args as server_args

    # reuse the server's flags (task/method/capacity/batching) and add the
    # load shape on top
    base = server_args([])
    # None-default flags carry no type to clone; name the numeric ones
    numeric = {"max_linger_ms": float}
    p = argparse.ArgumentParser(description=__doc__)
    for a, v in vars(base).items():
        if a == "port":
            continue
        if isinstance(v, bool):
            p.add_argument("--" + a.replace("_", "-"), default=v,
                           action="store_true" if not v
                           else "store_false")
        else:
            p.add_argument("--" + a.replace("_", "-"),
                           default=v, type=(type(v) if v is not None
                                            else numeric.get(a, str)))
    p.add_argument("--workers", type=int, default=32,
                   help="free-run: OS threads; mux: max concurrent "
                        "session coroutines")
    p.add_argument("--sessions", type=int, default=64,
                   help="total sessions to run (free-run / mux modes)")
    p.add_argument("--labels", type=int, default=8,
                   help="labels per session")
    p.add_argument("--lockstep", action="store_true",
                   help="barrier arrivals: every round of W labels rides "
                        "one dispatch (deterministic occupancy)")
    p.add_argument("--mux", action="store_true",
                   help="asyncio arrival: sessions are coroutines on one "
                        "event loop (in-process verbs, or per-session "
                        "keep-alive connections with --http)")
    p.add_argument("--ramp-s", type=float, default=0.0,
                   help="mux: spread session arrivals over this many "
                        "seconds instead of a thundering herd at t=0")
    p.add_argument("--http", action="store_true",
                   help="drive the in-process app over real HTTP instead "
                        "of direct calls")
    p.add_argument("--url", default=None,
                   help="target a RUNNING server instead of in-process")
    p.add_argument("--classes", type=int, default=10,
                   help="label range when targeting --url (the remote "
                        "task's C)")
    p.add_argument("--out", default=None,
                   help="write the JSON report here "
                        "(default BENCH_SERVE_<mode>.json)")
    args = p.parse_args(argv)
    if args.capacity < args.workers and not args.url:
        # closed-loop workers each hold one live session; a smaller slab
        # would make backpressure part of the measurement
        args.capacity = args.workers
    return args


def main(argv=None):
    args = parse_args(argv)
    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    report = run_loadgen(args)
    out = args.out or f"BENCH_SERVE_{report['mode']}.json"
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
