"""Closed-loop load generator for the serving layer -> BENCH_SERVE_*.json.

Spins up an in-process :class:`coda_tpu.serve.ServeApp` + HTTP server (or
targets a running one via ``--url``), then drives closed-loop sessions:
each opens, labels ``--labels`` proposed items (answering ``idx % C`` — the
serving cost is label-independent), and closes. Reports sessions/sec,
requests/sec, client-side latency percentiles, the server's own dispatch
metrics (batch occupancy — the number the subsystem exists to maximize),
and the **latency breakdown** (queue-wait vs dispatch vs slab-step, from
the server's phase rings and telemetry spans) into one JSON artifact — so
a p99 regression is attributable mechanically, not by eyeball.

Three arrival models:

  * default — ``--workers`` threads free-run through the session budget;
    occupancy emerges from the batcher's coalescing (the thread-client
    number, comparable to r06);
  * ``--mux`` — sessions are asyncio coroutines multiplexed on ONE event
    loop (``--workers`` bounds concurrent live sessions), driving the
    app's async verbs in-process or — with ``--http`` — one persistent
    keep-alive connection per session against the asyncio front door.
    This is how 256+ concurrent sessions are driven without 256 OS
    threads contending for the GIL, i.e. without the client becoming the
    tail;
  * ``--lockstep`` — workers rendezvous at a barrier each round while the
    batcher is paused, so every round's W requests ride ONE dispatch. This
    is the deterministic-occupancy mode the tier-1 smoke test pins ≥16
    sessions/dispatch with (in-process only).

    python scripts/serve_loadgen.py --mux --workers 256 --sessions 256 \
        --synthetic 8,512,10 --out BENCH_SERVE_cpu.json

Chaos mode: combine ``--fault-spec`` (deterministic server-side fault
injection, ``coda_tpu/serve/faults.py``) with ``--retries``/
``--backoff-ms`` (client-side retry with idempotent ``request_id``
labels) — the run must then finish with 0 errors and every absorbed
retry counted in ``n_retries``::

    python scripts/serve_loadgen.py --synthetic 4,64,4 --workers 8 \
        --sessions 16 --fault-spec step_raise:after=40 --retries 8

Rolling-restart mode: ``--rolling-restart-at S`` drains the server mid-
run, exports every live session, restarts fresh, imports (each stream
independently replay-verified bitwise), and swaps the retrying clients
over — the report's ``migration`` section must then show
``exported == imported == replay_verified`` with 0 errors::

    python scripts/serve_loadgen.py --synthetic 4,64,4 --workers 8 \
        --sessions 24 --labels 6 --rolling-restart-at 0.5 --retries 10
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
import time
import uuid

# importable from any cwd (the aggregate_results.py convention)
sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


# ---------------------------------------------------------------------------
# clients: in-process (drives a ServeApp directly) or HTTP (urllib, stdlib)
# ---------------------------------------------------------------------------

class InprocClient:
    def __init__(self, app):
        self.app = app

    def open(self, seed):
        return self.app.open_session(seed=seed)

    def label(self, sid, label, request_id=None, trace=None):
        return self.app.label(sid, label, request_id=request_id,
                              trace_ctx=trace)

    def fetch_trace(self, trace_id):
        """(span names, contributing processes) for one trace id."""
        p = self.app.trace_by_id(trace_id)
        names = [e["name"] for e in p.get("events") or ()]
        return names, (["server"] if names else [])

    def labels(self, sid, labels, request_id=None):
        return self.app.labels(sid, labels, request_id=request_id)

    def answer(self, sid, slot, label=None, request_id=None,
               abstain=False):
        return self.app.answer(sid, slot, label=label,
                               request_id=request_id, abstain=abstain)

    def close(self, sid):
        app = self.app
        out = app.close_session(sid)
        if self.app is not app:
            # a rolling restart swapped the app while this close was in
            # flight: the session may already have been exported+imported,
            # so the close that just landed on the OLD store would leak
            # the migrated copy live on the new server — follow it there
            # (already-closed/never-imported is fine)
            try:
                self.app.close_session(sid)
            except Exception:
                pass
        return out

    def stats(self):
        return self.app.stats()


class RouterClient:
    """Drives a fleet through its session router (the fleet front door —
    in-process twin of pointing ``--url`` at a router's HTTP port)."""

    def __init__(self, router):
        self.router = router

    def open(self, seed):
        return self.router.open_session(seed=seed)

    def label(self, sid, label, request_id=None, trace=None):
        return self.router.label(sid, label, request_id=request_id,
                                 trace_ctx=trace)

    def fetch_trace(self, trace_id):
        """(span names, process lanes) from the router's stitched trace."""
        out = self.router.collect_trace(trace_id)
        names = [e["name"] for e in out.get("traceEvents") or ()
                 if e.get("ph") == "X"]
        return names, list(out.get("processes") or [])

    def labels(self, sid, labels, request_id=None):
        return self.router.labels(sid, labels, request_id=request_id)

    def close(self, sid):
        return self.router.close_session(sid)

    def stats(self):
        return self.router.stats()


class HttpClient:
    def __init__(self, url):
        self.url = url.rstrip("/")

    def _req(self, method, path, body=None, headers=None):
        import urllib.request

        data = None if body is None else json.dumps(body).encode()
        h = {"Content-Type": "application/json"}
        if headers:
            h.update(headers)
        req = urllib.request.Request(
            self.url + path, data=data, method=method, headers=h)
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def open(self, seed):
        return self._req("POST", "/session", {"seed": seed})

    def label(self, sid, label, request_id=None, trace=None):
        body = {"label": label}
        if request_id is not None:
            body["request_id"] = request_id
        headers = None
        if trace is not None:
            from coda_tpu.telemetry.trace import TRACE_HEADER

            headers = {TRACE_HEADER: trace.header()}
        return self._req("POST", f"/session/{sid}/label", body,
                         headers=headers)

    def fetch_trace(self, trace_id):
        """(span names, processes): a router front door answers the
        stitched Chrome file, a bare replica its own wire payload."""
        out = self._req("GET", f"/trace/id/{trace_id}")
        if "traceEvents" in out:
            names = [e["name"] for e in out["traceEvents"]
                     if e.get("ph") == "X"]
            return names, list(out.get("processes") or [])
        names = [e["name"] for e in out.get("events") or ()]
        return names, (["server"] if names else [])

    def labels(self, sid, labels, request_id=None):
        body = {"labels": list(labels)}
        if request_id is not None:
            body["request_id"] = request_id
        return self._req("POST", f"/session/{sid}/labels", body)

    def answer(self, sid, slot, label=None, request_id=None,
               abstain=False):
        body = {"slot": slot}
        if abstain:
            body["abstain"] = True
        else:
            body["label"] = label
        if request_id is not None:
            body["request_id"] = request_id
        return self._req("POST", f"/session/{sid}/answer", body)

    def close(self, sid):
        return self._req("DELETE", f"/session/{sid}")

    def stats(self):
        return self._req("GET", "/stats")


# ---------------------------------------------------------------------------
# client-side retry/backoff (the chaos-mode / rolling-restart companion)
# ---------------------------------------------------------------------------

#: HTTP statuses worth retrying: backpressure/draining/healing (503), a
#: stuck dispatch (504), and transient internal errors (500). 4xx client
#: errors are not retried — they would fail identically forever.
_RETRY_STATUSES = (500, 503, 504)


def _retryable(e: Exception) -> bool:
    import urllib.error

    if isinstance(e, urllib.error.HTTPError):
        return e.code in _RETRY_STATUSES
    if isinstance(e, (urllib.error.URLError, ConnectionError,
                      TimeoutError)):
        return True  # server restarting / socket dropped / dispatch stuck
    # in-process verbs raise these for the same transient conditions
    if isinstance(e, (ValueError, KeyError, TypeError)):
        return False
    return isinstance(e, Exception)


def with_retries(fn, retries: int, backoff_s: float, counter=None):
    """Run ``fn`` with exponential backoff on transient failures.

    Pair with an idempotent ``request_id`` on label calls: the server
    dedupes replays, so a retry can never double-apply an oracle answer
    to a posterior — which is what makes retrying SAFE, not just
    convenient."""
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            if attempt >= retries or not _retryable(e):
                raise
            if counter is not None:
                counter.append(repr(e))
            time.sleep(backoff_s * (2 ** attempt))
            attempt += 1


class TraceSampler:
    """``--trace-sample RATE``: deterministic stride sampling of label
    requests for client-minted trace contexts. The sampled trace_ids are
    fetched back through the front door after the run (stitched across
    every process lane by a router) and scored for completeness — the
    end-to-end proof that context propagation survived transport, batcher
    coalescing, and any mid-run failover."""

    def __init__(self, rate: float):
        self.rate = float(rate or 0.0)
        self.stride = max(1, round(1.0 / self.rate)) if self.rate > 0 else 0
        self._n = 0
        self._lock = threading.Lock()
        self.sampled: list = []

    def next_ctx(self):
        """A fresh root context for this label, or None (unsampled)."""
        if not self.stride:
            return None
        from coda_tpu.telemetry.trace import mint

        with self._lock:
            self._n += 1
            if self._n % self.stride:
                return None
            ctx = mint()
            self.sampled.append(ctx.trace_id)
            return ctx


def _trace_report(client, tracer, exemplar_tids, expect_router):
    """The report's ``tracing`` section: per-sampled-trace completeness
    (did the route/dispatch/serve/tick/step causal chain survive?) and
    exemplar joinability (does every /metrics outlier's trace_id resolve
    to retained spans?)."""
    required = ["serve/", "tick/", "step/"]
    if expect_router:
        required += ["route/", "dispatch/"]
    traces = []
    complete = fetch_errors = 0
    for tid in tracer.sampled:
        try:
            names, procs = client.fetch_trace(tid)
        except Exception as e:
            fetch_errors += 1
            traces.append({"trace_id": tid, "error": repr(e)})
            continue
        missing = [p for p in required
                   if not any(n.startswith(p) for n in names)]
        ok = not missing
        complete += ok
        entry = {"trace_id": tid, "spans": len(names),
                 "processes": procs, "complete": ok}
        if missing:
            entry["missing"] = missing
        traces.append(entry)
    joinable = 0
    ex_tids = sorted(set(exemplar_tids))
    for tid in ex_tids:
        try:
            names, _ = client.fetch_trace(tid)
            joinable += bool(names)
        except Exception:
            pass
    n = len(tracer.sampled)
    return {
        "sample_rate": tracer.rate,
        "sampled": n,
        "complete": complete,
        "fetch_errors": fetch_errors,
        "completeness": (complete / n) if n else None,
        "required_spans": required,
        "traces": traces[:32],
        "exemplars": len(ex_tids),
        "exemplars_joinable": joinable,
        "exemplar_joinability": (joinable / len(ex_tids)) if ex_tids
        else None,
    }


def _exemplar_tids(snap: dict) -> list:
    return [ex["trace_id"] for ex in (snap.get("exemplars") or {}).values()
            if ex and ex.get("trace_id")]


class AsyncConn:
    """One persistent keep-alive connection to the asyncio front door —
    each mux session coroutine holds its own, so 256 concurrent sessions
    are 256 sockets on one event loop, not 256 threads."""

    def __init__(self, host, port):
        self.host, self.port = host, port
        self.r = self.w = None

    async def connect(self):
        self.r, self.w = await asyncio.open_connection(self.host, self.port)

    async def req(self, method, path, body=None):
        data = b"" if body is None else json.dumps(body).encode()
        head = (f"{method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(data)}\r\n\r\n")
        self.w.write(head.encode() + data)
        await self.w.drain()
        line = await self.r.readline()
        status = int(line.split()[1])
        clen = 0
        while True:
            h = await self.r.readline()
            if not h or h in (b"\r\n", b"\n"):
                break
            k, _, v = h.decode().partition(":")
            if k.strip().lower() == "content-length":
                clen = int(v)
        payload = await self.r.readexactly(clen) if clen else b"{}"
        return status, json.loads(payload)

    def close(self):
        if self.w is not None:
            self.w.close()


# ---------------------------------------------------------------------------
# the closed loop
# ---------------------------------------------------------------------------

def _free_run(client, n_classes, workers, sessions, labels_per_session,
              latencies, errors, retries=0, backoff_s=0.05, retried=None,
              tracer=None):
    """Default arrival model: W workers race through the session budget."""
    counter = {"next": 0}
    lock = threading.Lock()

    def take():
        with lock:
            s = counter["next"]
            if s >= sessions:
                return None
            counter["next"] = s + 1
            return s

    def worker():
        while True:
            seed = take()
            if seed is None:
                return
            sid = None
            try:
                t0 = time.perf_counter()
                out = with_retries(lambda: client.open(seed),
                                   retries, backoff_s, retried)
                sid = out["session"]
                latencies.append(time.perf_counter() - t0)
                for _ in range(labels_per_session):
                    t0 = time.perf_counter()
                    # one request_id per LOGICAL label, stable across its
                    # retries: the server dedupes, so a retried label is
                    # applied to the posterior exactly once
                    lab, rid = int(out["idx"]) % n_classes, uuid.uuid4().hex
                    # the sampled context is minted ONCE per logical label
                    # (stable across retries, like the request_id): a
                    # retried label's attempts all land in one trace, so a
                    # mid-trace failover shows both replicas' lanes
                    tctx = tracer.next_ctx() if tracer is not None else None
                    out = with_retries(
                        lambda: client.label(sid, lab, request_id=rid,
                                             trace=tctx),
                        retries, backoff_s, retried)
                    latencies.append(time.perf_counter() - t0)
                # the double-apply sentinel: the server-side label count
                # must equal the labels this client issued — a broken
                # retry dedupe (or a lossy migration) shows up here
                n = out.get("n_labeled")
                if n is not None and n != labels_per_session:
                    errors.append(
                        f"session {sid}: server applied {n} labels, "
                        f"client issued {labels_per_session}")
                client.close(sid)
                sid = None
            except Exception as e:  # keep the run alive; report at the end
                errors.append(repr(e))
                if sid is not None:
                    # free the slot: capacity == workers, so one leaked
                    # session would starve every later open into SlabFull
                    try:
                        client.close(sid)
                    except Exception:
                        pass

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _batch_run(client, n_classes, workers, sessions, rounds, q,
               latencies, label_latencies, errors, retries=0,
               backoff_s=0.05, retried=None):
    """``--labels-per-round q`` mode: the free-run arrival model driving
    the batch-label verb — each session answers all q proposed items of a
    round through ONE ``POST /session/{id}/labels``, ``rounds`` times.
    Per-request latencies land in ``latencies`` (the existing rings);
    each request also contributes q amortized per-label samples
    (request latency / q) to ``label_latencies`` — the effective
    time-per-oracle-answer the batching exists to shrink."""
    counter = {"next": 0}
    lock = threading.Lock()

    def take():
        with lock:
            s = counter["next"]
            if s >= sessions:
                return None
            counter["next"] = s + 1
            return s

    def worker():
        while True:
            seed = take()
            if seed is None:
                return
            sid = None
            try:
                t0 = time.perf_counter()
                out = with_retries(lambda: client.open(seed),
                                   retries, backoff_s, retried)
                sid = out["session"]
                latencies.append(time.perf_counter() - t0)
                for _ in range(rounds):
                    t0 = time.perf_counter()
                    ans = [int(i) % n_classes for i in out["idx"]]
                    rid = uuid.uuid4().hex
                    out = with_retries(
                        lambda: client.labels(sid, ans, request_id=rid),
                        retries, backoff_s, retried)
                    dt = time.perf_counter() - t0
                    latencies.append(dt)
                    label_latencies.extend([dt / q] * q)
                n = out.get("n_labeled")
                if n is not None and n != rounds * q:
                    errors.append(
                        f"session {sid}: server applied {n} labels, "
                        f"client issued {rounds * q}")
                client.close(sid)
                sid = None
            except Exception as e:
                errors.append(repr(e))
                if sid is not None:
                    try:
                        client.close(sid)
                    except Exception:
                        pass

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _oracle_run(client, n_classes, workers, sessions, rounds, q,
                oracle_cfg, latencies, errors, crowd, retries=0,
                backoff_s=0.05, retried=None):
    """``--oracle-noise`` mode: the free-run arrival model driving the
    per-slot ``answer`` verb with a deterministic noisy crowd
    (``coda_tpu/crowd/oracle.py``'s :class:`HostCrowdSampler`).

    Each round, every proposed slot gets an answer from the sampled
    annotator: abstentions are posted (the slot stays open) and the item
    re-requested from another annotator; deferred answers are DELIVERED
    LATE — non-deferred slots post first in slot order, deferred ones
    after, sorted by depth — so the server's parking layer sees genuine
    out-of-order arrival. ``crowd`` accumulates the per-annotator answer
    mix, abstention count, and deferral/reorder depths the report prints
    next to the latency rings."""
    from coda_tpu.crowd import HostCrowdSampler

    sampler = HostCrowdSampler(oracle_cfg, n_classes)
    counter = {"next": 0}
    lock = threading.Lock()

    def take():
        with lock:
            s = counter["next"]
            if s >= sessions:
                return None
            counter["next"] = s + 1
            return s

    def bump(field, n=1):
        with lock:
            crowd[field] += n

    def high_water(field, v):
        with lock:
            crowd[field] = max(crowd[field], v)

    def worker():
        while True:
            seed = take()
            if seed is None:
                return
            sid = None
            try:
                t0 = time.perf_counter()
                out = with_retries(lambda: client.open(seed),
                                   retries, backoff_s, retried)
                sid = out["session"]
                latencies.append(time.perf_counter() - t0)
                for rnd in range(rounds):
                    idxs = out["idx"] if q > 1 else [out["idx"]]
                    held = []          # (defer_depth, slot, label)
                    for j, idx in enumerate(idxs):
                        true = int(idx) % n_classes
                        for attempt in range(64):
                            a = sampler.answer(sid, rnd, j, true,
                                               attempt=attempt)
                            with lock:
                                crowd["mix"][a["annotator"]] += 1
                            if a["verb"] != "abstain":
                                break
                            # post the abstention (the slot stays open)
                            # and re-request from another annotator
                            bump("abstentions")
                            with_retries(
                                lambda j=j: client.answer(
                                    sid, j, abstain=True),
                                retries, backoff_s, retried)
                        held.append((a["defer"], j, a["label"]))
                        if a["defer"]:
                            bump("deferred")
                            high_water("defer_depth_max", a["defer"])
                    # delivery order: prompt answers in slot order first,
                    # deferred ones late (by depth) — out-of-order arrival
                    delivered: list = []
                    for d, j, lab in sorted(held):
                        depth = sum(1 for k in delivered if k > j)
                        high_water("reorder_depth_max", depth)
                        rid = f"crowd:{sid}:{rnd}:{j}"
                        t0 = time.perf_counter()
                        out = with_retries(
                            lambda j=j, lab=lab, rid=rid: client.answer(
                                sid, j, label=lab, request_id=rid),
                            retries, backoff_s, retried)
                        latencies.append(time.perf_counter() - t0)
                        delivered.append(j)
                        bump("answers")
                    if out.get("verb") != "dispatched":
                        errors.append(
                            f"session {sid} round {rnd}: last answer did "
                            f"not complete the round ({out.get('verb')!r})")
                        break
                n = out.get("n_labeled")
                if n is not None and n != rounds * q:
                    errors.append(
                        f"session {sid}: server applied {n} labels, "
                        f"client issued {rounds * q}")
                client.close(sid)
                sid = None
            except Exception as e:
                errors.append(repr(e))
                if sid is not None:
                    try:
                        client.close(sid)
                    except Exception:
                        pass

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _mux(app, http_port, n_classes, concurrency, sessions,
         labels_per_session, latencies, errors, ramp_s=0.0,
         retries=0, backoff_s=0.05, retried=None):
    """Asyncio arrival model: every session is a coroutine, ``concurrency``
    of them live at once, all multiplexed on one event loop. In-process it
    drives the app's async verbs (the front door's own path, minus TCP);
    with an ``http_port`` each session holds one keep-alive connection to
    the real asyncio server."""

    async def _aretry(thunk):
        """Async twin of ``with_retries`` (same request_id across tries)."""
        attempt = 0
        while True:
            try:
                return await thunk()
            except Exception as e:
                if attempt >= retries or not _retryable(e):
                    raise
                if retried is not None:
                    retried.append(repr(e))
                await asyncio.sleep(backoff_s * (2 ** attempt))
                attempt += 1

    async def one_inproc(seed):
        t0 = time.perf_counter()
        out = await _aretry(lambda: app.open_session_async(seed=seed))
        latencies.append(time.perf_counter() - t0)
        sid = out["session"]
        try:
            for _ in range(labels_per_session):
                t0 = time.perf_counter()
                lab, rid = int(out["idx"]) % n_classes, uuid.uuid4().hex
                out = await _aretry(lambda: app.label_async(
                    sid, lab, request_id=rid))
                latencies.append(time.perf_counter() - t0)
        finally:
            await asyncio.get_running_loop().run_in_executor(
                None, app.close_session, sid)

    async def one_http(seed):
        conn = AsyncConn("127.0.0.1", http_port)
        await conn.connect()
        sid = None

        async def checked(method, path, body, what):
            try:
                status, out = await conn.req(method, path, body)
            except (ConnectionError, asyncio.IncompleteReadError,
                    OSError) as e:
                # dropped keep-alive (server restart): reconnect, then let
                # the retry loop resubmit with the SAME request_id
                await conn.connect()
                raise TimeoutError(f"{what} connection dropped: {e!r}")
            if status in _RETRY_STATUSES:
                raise TimeoutError(f"{what} -> {status}: {out}")  # retryable
            if status != 200:
                raise RuntimeError(f"{what} -> {status}: {out}")
            return out

        try:
            t0 = time.perf_counter()
            out = await _aretry(lambda: checked(
                "POST", "/session", {"seed": seed}, "open"))
            latencies.append(time.perf_counter() - t0)
            sid = out["session"]
            for _ in range(labels_per_session):
                t0 = time.perf_counter()
                lab, rid = int(out["idx"]) % n_classes, uuid.uuid4().hex
                out = await _aretry(lambda: checked(
                    "POST", f"/session/{sid}/label",
                    {"label": lab, "request_id": rid}, "label"))
                latencies.append(time.perf_counter() - t0)
            await conn.req("DELETE", f"/session/{sid}")
            sid = None
        finally:
            if sid is not None:
                try:
                    await conn.req("DELETE", f"/session/{sid}")
                except Exception:
                    pass
            conn.close()

    one = one_http if http_port is not None else one_inproc

    async def main():
        sem = asyncio.Semaphore(concurrency)

        async def bounded(seed):
            if ramp_s > 0:
                # spread session arrivals over the ramp window: real fleets
                # don't open every session in the same microsecond, and a
                # thundering herd of admissions would otherwise dominate
                # the p99 with a startup transient instead of steady state
                await asyncio.sleep(seed * ramp_s / max(1, sessions))
            async with sem:
                try:
                    await one(seed)
                except Exception as e:
                    errors.append(repr(e))

        await asyncio.gather(*(bounded(s) for s in range(sessions)))

    asyncio.run(main())


def _zipf_run(app, n_classes, workers, sessions, zipf_s, think_ms,
              requests, latencies, errors, retries=0, backoff_s=0.05,
              retried=None):
    """Zipf-arrival open-loop driver — the tiering workload.

    Two phases, both worker-pool coroutines on one event loop (100k+
    session counts must not mean 100k coroutine objects at once):

      * **populate** — open every session (admission past slab capacity
        demotes the coldest, never 503s), tracking each session's last
        proposed item client-side;
      * **traffic** — ``requests`` label requests whose target session is
        drawn from a Zipf(``zipf_s``) distribution over session ranks,
        with exponential think times (mean ``think_ms``). The skewed hot
        set stays slab-resident; the long tail pages out, and a request
        for a paged-out session transparently wakes it — the residency
        hit rate and wake counts come from the server's tier counters.

    Returns ``{wakes_populate, wakes_traffic, requests_traffic}`` for the
    report's hit-rate math."""
    rng = np.random.default_rng(0)
    last: dict = {}    # rank -> last proposed idx (client-side handle)
    sids: dict = {}    # rank -> session id

    async def _aretry(thunk):
        attempt = 0
        while True:
            try:
                return await thunk()
            except Exception as e:
                if attempt >= retries or not _retryable(e):
                    raise
                if retried is not None:
                    retried.append(repr(e))
                await asyncio.sleep(backoff_s * (2 ** attempt))
                attempt += 1

    async def _pool(n_items, worker):
        cursor = {"next": 0}

        async def one_worker():
            while True:
                i = cursor["next"]
                if i >= n_items:
                    return
                cursor["next"] = i + 1
                await worker(i)

        await asyncio.gather(*(one_worker() for _ in range(workers)))

    async def open_one(rank):
        try:
            t0 = time.perf_counter()
            out = await _aretry(lambda: app.open_session_async(seed=rank))
            latencies.append(time.perf_counter() - t0)
            sids[rank] = out["session"]
            last[rank] = int(out["idx"])
        except Exception as e:
            errors.append(f"open rank {rank}: {e!r}")

    # Zipf pmf over ranks 1..sessions (rank 0 hottest), sampled by
    # inverse CDF — precomputed draws keep the traffic deterministic
    pmf = (1.0 / np.arange(1, sessions + 1) ** float(zipf_s))
    cdf = np.cumsum(pmf / pmf.sum())
    draws = np.searchsorted(cdf, rng.random(requests))

    async def label_one(i):
        rank = int(draws[i])
        sid = sids.get(rank)
        if sid is None:
            return  # its open failed; already counted
        try:
            t0 = time.perf_counter()
            lab = last.get(rank, 0) % n_classes
            out = await _aretry(lambda: app.label_async(sid, lab))
            latencies.append(time.perf_counter() - t0)
            last[rank] = int(out["idx"])
        except Exception as e:
            errors.append(f"label rank {rank}: {e!r}")
        if think_ms > 0:
            await asyncio.sleep(rng.exponential(think_ms / 1e3))

    info: dict = {}

    async def main():
        await _pool(sessions, open_one)
        info["wakes_populate"] = app.metrics.wakes
        # optional warm-up labels per session are folded into traffic
        await _pool(requests, label_one)
        info["wakes_traffic"] = app.metrics.wakes - info["wakes_populate"]
        info["requests_traffic"] = requests

    asyncio.run(main())
    return info


def _lockstep(app, client, n_classes, workers, labels_per_session,
              latencies, errors):
    """Deterministic occupancy: open W sessions, then label all W in
    rounds, pausing the batcher while each round's requests queue up so
    every round is exactly ONE dispatch per bucket. In-process only (needs
    the batcher handle)."""
    sids = []
    for seed in range(workers):
        sids.append(client.open(seed)["session"])
    for _ in range(labels_per_session):
        app.batcher.pause()
        tickets = []
        t0 = time.perf_counter()
        for sid in sids:
            sess = app.store.get(sid)
            cur = sess.last
            tickets.append(app.batcher.submit_label(
                sess, idx=cur["next_idx"],
                label=int(cur["next_idx"]) % n_classes,
                prob=cur["next_prob"]))
        app.batcher.resume()
        for t in tickets:
            try:
                t.wait(60.0)
                latencies.append(time.perf_counter() - t0)
            except Exception as e:
                errors.append(repr(e))
    for sid in sids:
        client.close(sid)


def _span_breakdown(app) -> dict:
    """Mechanical p99 attribution from the telemetry spans: busy seconds
    of the batcher lane split into tick (dispatch incl. host fan-out) and
    step (compiled slab-step execution) — tick minus step is host-side
    build/fan-out, wall minus tick is queue/idle."""
    if app is None:
        return {}
    spans = app.telemetry.spans
    events = spans.events()
    tick_s = sum(t1 - t0 for name, lane, t0, t1, _ in events
                 if name.startswith("tick/"))
    step_s = sum(t1 - t0 for name, lane, t0, t1, _ in events
                 if name.startswith("step/"))
    n_ticks = sum(1 for name, *_ in events if name.startswith("tick/"))
    return {
        "tick_busy_s": tick_s,
        "step_busy_s": step_s,
        "host_overhead_s": max(0.0, tick_s - step_s),
        "n_tick_spans": n_ticks,
    }


def _rolling_restart(client, args, migration: dict, errors: list) -> None:
    """The drain -> export -> restart -> import cycle, under live load.

    At ``--rolling-restart-at`` seconds: quiesce the serving app (stop
    ticking, keep sessions; in-flight retries now see fast retryable
    errors), export every live session, stand up a FRESH app, import each
    payload (snapshot fast path or bitwise-verified stream replay), then
    swap the client over. Every exported stream is ALSO independently
    replay-verified against a fresh slab — the migration's evidence is a
    bitwise check, not an absence of errors. Retrying workers ride
    through; their idempotent request_ids make the handoff exactly-once.
    """
    from coda_tpu.serve import SessionStore, recovery
    from coda_tpu.serve.server import build_app

    time.sleep(args.rolling_restart_at)
    old = client.app
    # the demo must cut MID-LOAD: wait until the old server is actually
    # serving (first dispatches can outlast a small --rolling-restart-at),
    # so there are live sessions to migrate, not an idle slab
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        with old.store.lock:
            n_live = len(old.store._sessions)
        if n_live >= max(1, args.workers // 2) and \
                old.metrics.snapshot()["requests"] > 0:
            break
        time.sleep(0.005)
    t0 = time.perf_counter()
    try:
        # hard cut: a soft drain would race the retrying clients (they
        # keep completing and closing sessions while the queue empties),
        # leaving nothing live to migrate; the stranded tickets fail
        # retryably and land on the new server
        old.quiesce(timeout=10, hard=True)
        requests_at_cut = old.metrics.snapshot().get("requests")
        payloads = recovery.export_all(old)
        new = build_app(args)
        new.start(warm=not args.no_warm)
        # independent bitwise verification of every exported stream (the
        # import repeats this for the replay path and digest-checks the
        # snapshot path; doing it standalone makes the evidence explicit)
        vstore = SessionStore(capacity=2)
        vstore.register_task(new.default_task,
                             new.store._tasks[new.default_task])
        verified = 0
        for p in payloads:
            meta = {"task": new.default_task, "method": p["method"],
                    "spec_kwargs": p["spec_kwargs"], "seed": p["seed"]}
            recovery.verify_session_stream(vstore, meta, p["rows"],
                                           sid=p["session"])
            verified += 1
        via: dict = {}
        reclosed = 0

        def still_open(sid):
            # parked (warm/cold) sessions are open sessions too — a
            # rolling restart migrates all three tiers
            return old.store.alive(sid) or (
                old.tiers is not None and old.tiers.parked(sid))

        for p in payloads:
            if not still_open(p["session"]):
                # closed on the OLD app after export_all captured it (the
                # worker's final label landed just before the cut): the
                # client is done with this session — importing it would
                # leak an unclosable slot on the new server
                reclosed += 1
                continue
            info = new.import_session(p)
            via[info["restored_via"]] = via.get(info["restored_via"], 0) + 1
        client.app = new      # the handoff: retries land on the new app
        # reconcile closes that raced the import loop itself: any close
        # issued against the old app before the handoff must follow its
        # session to the new server
        for p in payloads:
            sid = p["session"]
            if not still_open(sid) and new.store.alive(sid):
                new.close_session(sid)
                reclosed += 1
        migration.update(
            at_s=args.rolling_restart_at,
            requests_at_cut=requests_at_cut,
            exported=len(payloads),
            imported=sum(via.values()),
            reclosed=reclosed,
            restored_via=via,
            replay_verified=verified,
            seconds=time.perf_counter() - t0,
        )
        # the old app's batcher is stopped and its sessions are handed
        # off; release its executor without writing close markers (the
        # sessions are LIVE — on the new server)
        old._executor.shutdown(wait=False)
    except Exception as e:
        errors.append(f"rolling restart failed: {e!r}")


def _router_span_breakdown(router) -> dict:
    """The router's added latency, attributed from its trace spans: every
    routed verb is a ``route/<verb>`` span NESTING a ``dispatch/<rid>``
    span for the replica call — outer minus inner is the router's own
    overhead (locate, gates, accounting), mechanically."""
    events = router.telemetry.spans.events()
    route_s = sum(t1 - t0 for name, lane, t0, t1, _ in events
                  if name.startswith("route/"))
    disp_s = sum(t1 - t0 for name, lane, t0, t1, _ in events
                 if name.startswith("dispatch/"))
    n_route = sum(1 for name, *_ in events if name.startswith("route/"))
    overhead = max(0.0, route_s - disp_s)
    return {
        "route_busy_s": route_s,
        "replica_dispatch_busy_s": disp_s,
        "router_overhead_s": overhead,
        "n_route_spans": n_route,
        "router_overhead_mean_ms": (overhead / n_route * 1e3
                                    if n_route else None),
    }


def _fleet_workload(args, n_replicas, latencies, errors, retried,
                    migration, tracer=None):
    """One fleet pass: build N replicas + router, drive the free-run
    workload through the router, optionally rolling-restart every replica
    mid-run. Returns (fleet, wall_s, rolling_report)."""
    import copy
    import math

    from coda_tpu.serve.fleet import build_fleet

    backoff_s = args.backoff_ms / 1e3
    chaos = getattr(args, "fleet_chaos", None)
    # hold AGGREGATE slab capacity constant across replica counts: each
    # replica serves ~1/N of the sessions, so it gets ~1/N of the slab —
    # the deployment-realistic split, and the only apples-to-apples
    # scaling comparison (the masked slab step costs O(capacity) per
    # tick whether or not the slots are live, so N full-capacity
    # replicas on one core would pay N x the step work for the same
    # request stream)
    args = copy.copy(args)
    args.capacity = max(2, math.ceil(args.capacity / n_replicas))
    fleet = build_fleet(args, n_replicas, fault_spec=chaos)
    fleet.start(warm=not args.no_warm)
    client = RouterClient(fleet.router)
    meta = fleet.apps[fleet.replica_ids[0]].store.task_meta(
        fleet.apps[fleet.replica_ids[0]].default_task)
    n_classes = len(meta["class_names"])
    rolling: dict = {}

    def _restart_when_loaded():
        time.sleep(args.rolling_restart_at)
        # cut MID-LOAD: wait until the fleet actually serves sessions
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            agg = fleet.router.stats()["aggregate"]
            if agg["open_sessions"] >= max(1, args.workers // 2) and \
                    agg["requests"] > 0:
                break
            time.sleep(0.01)
        try:
            rolling.update(fleet.rolling_restart(warm=not args.no_warm))
        except Exception as e:
            errors.append(f"fleet rolling restart failed: {e!r}")

    restarter = None
    if getattr(args, "rolling_restart_at", None) is not None:
        restarter = threading.Thread(target=_restart_when_loaded,
                                     daemon=True, name="fleet-restart")
        restarter.start()
    t0 = time.perf_counter()
    _free_run(client, n_classes, args.workers, args.sessions, args.labels,
              latencies, errors, retries=args.retries, backoff_s=backoff_s,
              retried=retried, tracer=tracer)
    if restarter is not None:
        restarter.join(timeout=120)
    wall = time.perf_counter() - t0
    if rolling:
        migration.update(rolling)
    return fleet, wall, rolling


def _run_fleet_loadgen(args) -> dict:
    """``--fleet N``: the replicated-serve demo. Drives the router front
    door with the free-run closed loop, reports per-replica request
    distribution, migration accounting (every one digest-verified), the
    router's span-attributed added latency, and — with
    ``--fleet-baseline`` — the aggregate-vs-single-replica scaling the
    linearity claim is made of."""
    import os

    n = int(args.fleet)
    scaling = None
    if getattr(args, "fleet_baseline", False):
        # the scaling claim is measured on two RESTART-FREE passes (the
        # rolling restart is a separate claim — folding its warm-pool
        # recompiles into the fleet pass would understate throughput):
        # same workload, same router in front, 1 replica vs N replicas —
        # the only variable is the replica count
        passes = {}
        for label, n_pass in (("baseline", 1), ("fleet", n)):
            p_lat: list = []
            p_err: list = []
            p_ret: list = []
            fl, p_wall, _ = _fleet_workload(
                _no_restart(args), n_pass, p_lat, p_err, p_ret, {})
            fl.drain()
            passes[label] = {
                "replicas": n_pass,
                "wall_s": p_wall,
                "requests_per_s": len(p_lat) / p_wall,
                "n_errors": len(p_err),
                "latency_ms": _lat_ms(p_lat),
            }
        b_rps = passes["baseline"]["requests_per_s"]
        f_rps = passes["fleet"]["requests_per_s"]
        scaling = {
            "baseline": passes["baseline"],
            "fleet_pass": passes["fleet"],
            "fleet_requests_per_s": f_rps,
            "parity_ratio": f_rps / b_rps,
            # the linearity claim: aggregate vs N x one replica. On a
            # single-core container every replica shares the one core,
            # so parity (ratio ~1) is the physically honest ceiling —
            # single_core records which regime this capture is in.
            "efficiency": f_rps / (n * b_rps),
        }

    latencies: list = []
    errors: list = []
    retried: list = []
    migration: dict = {}
    tracer = TraceSampler(getattr(args, "trace_sample", 0.0))
    fleet, wall, rolling = _fleet_workload(args, n, latencies, errors,
                                           retried, migration,
                                           tracer=tracer)
    stats = fleet.router.stats()
    tracing = None
    if tracer.stride:
        ex_tids = [t for snap in stats["replicas"].values()
                   if "error" not in snap for t in _exemplar_tids(snap)]
        tracing = _trace_report(RouterClient(fleet.router), tracer,
                                ex_tids, expect_router=True)
    spans = _router_span_breakdown(fleet.router)
    per_replica: dict = {}
    total_req = 0
    for rid, snap in stats["replicas"].items():
        if "error" in snap:
            per_replica[rid] = snap
            continue
        req = int(snap.get("requests") or 0)
        total_req += req
        per_replica[rid] = {
            "requests": req,
            "dispatches": snap.get("dispatches"),
            "open_sessions": snap.get("open_sessions"),
            "request_latency": snap.get("request_latency"),
            "sessions_opened": snap.get("sessions_opened"),
            "peer_pages": snap.get("peer_pages"),
        }
    # distribution from the ROUTER's cumulative per-replica forwarding
    # counters: replica-side counters reset when a rolling restart swaps
    # in a fresh app, the router's view spans the whole run
    routed_to = stats["router"]["requests_to"]
    total_routed = sum(routed_to.values()) or 1
    shares = {rid: n_r / total_routed for rid, n_r in routed_to.items()}
    rc = stats["router"]["counters"]
    double_applied = [e for e in errors if "server applied" in e]
    unknown = [e for e in errors if "UnknownSession" in e]
    n_req = len(latencies)
    fleet_rps = n_req / wall
    from coda_tpu.telemetry.recorder import environment_fingerprint

    fingerprint = environment_fingerprint(knobs={
        "method": args.method, "capacity": args.capacity,
        "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
        "sessions": args.sessions, "labels": args.labels,
        "workers": args.workers, "mode": "fleet", "fleet": n,
        "rolling_restart_at": getattr(args, "rolling_restart_at", None),
        "fleet_chaos": getattr(args, "fleet_chaos", None),
        "task": args.task or args.synthetic or "default"})
    report = {
        "bench": "serve_loadgen",
        "fingerprint": fingerprint,
        "mode": "fleet",
        "transport": "inproc",
        "workers": args.workers,
        "sessions": args.sessions,
        "labels_per_session": args.labels,
        "wall_s": wall,
        "sessions_per_s": args.sessions / wall,
        "requests_per_s": fleet_rps,
        "latency_ms": _lat_ms(latencies),
        "errors": errors[:20],
        "n_errors": len(errors),
        "n_retries": len(retried),
        "retried": retried[:20],
        "migration": migration or None,
        # --trace-sample evidence: per-sampled-trace completeness through
        # the stitched router collector + exemplar -> trace joinability
        "tracing": tracing,
        "fleet": {
            "replicas": n,
            "capacity_per_replica": max(2, -(-args.capacity // n)),
            "host_cores": os.cpu_count(),
            # the hardware regime, stated precisely: single_core = ONE
            # core (parity with one replica is the claim there);
            # core_limited = fewer cores than replicas (the efficiency
            # ceiling is cores/replicas, and the gate scales its bound)
            "single_core": (os.cpu_count() or 1) == 1,
            "core_limited": (os.cpu_count() or 1) < n,
            "per_replica": per_replica,
            "request_share": shares,
            "balance": (min(shares.values()) / max(shares.values())
                        if shares and max(shares.values()) > 0 else None),
            "router": {
                "counters": rc,
                "migrations_via": stats["router"]["migrations_via"],
                "migration_verified":
                    stats["router"]["migration_verified"],
                "requests_to": stats["router"]["requests_to"],
            },
            "rolling_restart": rolling or None,
            # the zero-drop / exactly-once evidence: no session vanished
            # (UnknownSession after open), no label applied twice or lost
            # (the n_labeled sentinel), every migration digest-verified
            # (import's snapshot-digest or bitwise-replay path)
            "dropped_sessions": len(unknown)
            + rc.get("sessions_dropped", 0),
            "double_applied_labels": len(double_applied),
            "router_spans": spans,
            "scaling": scaling,
            # the chaos-mode evidence (--fleet-chaos): which edge faults
            # actually fired, how many transport retries absorbed them,
            # breaker states at the end, and the fencing counter — the
            # "0 errors under injected partitions" claim's mechanism
            "chaos": None if not getattr(args, "fleet_chaos", None) else {
                "spec": args.fleet_chaos,
                "faults": (fleet.router.faults.snapshot()
                           if fleet.router.faults is not None else []),
                "transport_retries":
                    stats["router"].get("transport_retries"),
                "breakers": stats["router"].get("breakers"),
                "fencing_rejections":
                    rc.get("fencing_rejections", 0),
            },
        },
        "aggregate": stats["aggregate"],
        "config": {
            "method": args.method,
            "capacity": args.capacity,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "retries": args.retries,
            "rolling_restart_at": getattr(args, "rolling_restart_at",
                                          None),
            "task": args.task or args.synthetic or "default",
        },
    }
    fleet.drain()
    return report


def _no_restart(args):
    import copy

    a = copy.copy(args)
    a.rolling_restart_at = None
    # the scaling baseline measures clean-path throughput: chaos is the
    # separate claim (0 errors UNDER faults), never folded into it
    a.fleet_chaos = None
    return a


def _lat_ms(latencies) -> dict:
    lat_ms = np.asarray(latencies, np.float64) * 1e3
    n = len(latencies)
    return {
        "p50": float(np.percentile(lat_ms, 50)) if n else None,
        "p99": float(np.percentile(lat_ms, 99)) if n else None,
        "mean": float(lat_ms.mean()) if n else None,
    }


def run_loadgen(args) -> dict:
    """Run the configured load and return the report dict (the script's
    JSON payload; the smoke test calls this directly)."""
    from coda_tpu.serve.server import build_app, make_server

    if getattr(args, "fleet", None):
        if args.url or args.http or args.mux or args.lockstep or \
                getattr(args, "zipf", None) is not None or \
                getattr(args, "oracle_noise", None) or \
                (getattr(args, "labels_per_round", None) or 1) > 1:
            raise SystemExit("--fleet drives the in-process router with "
                             "the free-run loop; drop --url/--http/--mux/"
                             "--lockstep/--zipf/--labels-per-round/"
                             "--oracle-noise")
        if getattr(args, "rolling_restart_at", None) is not None \
                and args.retries < 1:
            raise SystemExit("--rolling-restart-at needs --retries >= 1")
        if getattr(args, "fleet_chaos", None) and args.retries < 1:
            raise SystemExit("--fleet-chaos needs --retries >= 1: the "
                             "injected transport faults surface as "
                             "retryable errors by design")
        return _run_fleet_loadgen(args)
    if getattr(args, "fleet_chaos", None):
        raise SystemExit("--fleet-chaos is a --fleet mode (per-edge "
                         "router↔replica faults); for single-replica "
                         "faults use --fault-spec")
    app = srv = None
    warm_s = None
    lpr = getattr(args, "labels_per_round", None)
    oracle_cfg = None
    if getattr(args, "oracle_noise", None):
        from coda_tpu.crowd import parse_oracle_spec

        oracle_cfg = parse_oracle_spec(args.oracle_noise)
        if args.lockstep or args.mux or getattr(args, "zipf", None) \
                is not None:
            raise SystemExit("--oracle-noise drives the per-slot answer "
                             "verb with its own arrival model; drop "
                             "--lockstep/--mux/--zipf")
        if lpr is not None and lpr > 1:
            args.acq_batch = lpr
        if args.lockstep or args.mux or getattr(args, "zipf", None) \
                is not None:
            # those arrival models drive the single-label verb, which a
            # q-wide session refuses — reject the combination instead of
            # producing a 100%-error report
            raise SystemExit("--labels-per-round has its own arrival "
                             "model; drop --lockstep/--mux/--zipf")
        # the batch-label workload needs batch-label sessions: the served
        # spec's acq_batch IS the per-round width (build_app reads it)
        args.acq_batch = lpr
    if args.url:
        client = HttpClient(args.url)
        n_classes = args.classes
    else:
        app = build_app(args)
        # warm synchronously so compilation is excluded from (and reported
        # next to) the traffic measurement — mirroring a production server
        # that passes its readiness gate before taking load
        app.start(warm=not args.no_warm)
        warm_s = (app.warm_info or {}).get("warm_s")
        meta = app.store.task_meta(app.default_task)
        n_classes = len(meta["class_names"])
        if args.http:
            srv = make_server(app, 0)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            client = HttpClient(
                f"http://127.0.0.1:{srv.server_address[1]}")
        else:
            client = InprocClient(app)

    latencies: list = []
    errors: list = []
    retried: list = []
    backoff_s = args.backoff_ms / 1e3
    migration: dict = {}
    if getattr(args, "rolling_restart_at", None) is not None:
        if app is None or args.http or args.mux or args.lockstep:
            raise SystemExit("--rolling-restart-at needs the in-process "
                             "free-run client (no --url/--http/--mux/"
                             "--lockstep)")
        if args.retries < 1:
            raise SystemExit("--rolling-restart-at needs --retries >= 1: "
                             "requests in the drain window are refused "
                             "with a retryable error, not queued")
        threading.Thread(
            target=_rolling_restart,
            args=(client, args, migration, errors),
            daemon=True, name="loadgen-migrate").start()
    tracer = TraceSampler(getattr(args, "trace_sample", 0.0))
    if tracer.stride and (args.lockstep or args.mux or
                          getattr(args, "zipf", None) is not None or
                          oracle_cfg is not None or
                          (lpr is not None and lpr > 1)):
        raise SystemExit("--trace-sample rides the free-run label loop; "
                         "drop --lockstep/--mux/--zipf/--oracle-noise/"
                         "--labels-per-round")
    t_start = time.perf_counter()
    zipf_info: dict = {}
    if args.lockstep:
        if app is None:
            raise SystemExit("--lockstep needs an in-process app (no --url)")
        n_sessions = args.workers
        _lockstep(app, client, n_classes, args.workers, args.labels,
                  latencies, errors)
        mode = "lockstep"
    elif getattr(args, "zipf", None) is not None:
        if app is None:
            raise SystemExit("--zipf needs an in-process app (no --url)")
        if app.tiers is None:
            raise SystemExit("--zipf exercises the tiered store; drop "
                             "--no-tiering")
        n_sessions = args.sessions
        n_requests_target = (args.requests if args.requests is not None
                             else args.sessions * args.labels)
        zipf_info = _zipf_run(
            app, n_classes, args.workers, args.sessions, args.zipf,
            args.think_ms, n_requests_target, latencies, errors,
            retries=args.retries, backoff_s=backoff_s, retried=retried)
        mode = "zipf"
    elif args.mux:
        if app is None:
            raise SystemExit("--mux needs an in-process app (no --url)")
        n_sessions = args.sessions
        _mux(app, srv.server_address[1] if srv is not None else None,
             n_classes, args.workers, args.sessions, args.labels,
             latencies, errors, ramp_s=args.ramp_s,
             retries=args.retries, backoff_s=backoff_s, retried=retried)
        mode = "mux"
    elif oracle_cfg is not None:
        n_sessions = args.sessions
        q = lpr if (lpr is not None and lpr > 1) else 1
        crowd = {"mix": np.zeros(oracle_cfg.annotators, np.int64),
                 "answers": 0, "abstentions": 0, "deferred": 0,
                 "defer_depth_max": 0, "reorder_depth_max": 0}
        _oracle_run(client, n_classes, args.workers, args.sessions,
                    args.labels, q, oracle_cfg, latencies, errors, crowd,
                    retries=args.retries, backoff_s=backoff_s,
                    retried=retried)
        mode = "oracle"
    elif lpr is not None and lpr > 1:
        n_sessions = args.sessions
        label_latencies: list = []
        _batch_run(client, n_classes, args.workers, args.sessions,
                   args.labels, lpr, latencies, label_latencies, errors,
                   retries=args.retries, backoff_s=backoff_s,
                   retried=retried)
        mode = "batch"
    else:
        n_sessions = args.sessions
        _free_run(client, n_classes, args.workers, args.sessions,
                  args.labels, latencies, errors,
                  retries=args.retries, backoff_s=backoff_s,
                  retried=retried, tracer=tracer)
        mode = "free_run"
    wall = time.perf_counter() - t_start

    if migration and isinstance(client, InprocClient):
        app = client.app   # stats/drain target the post-migration server
    stats = client.stats() if app is None else app.stats()
    tracing = None
    if tracer.stride:
        # fetch BEFORE shutdown/drain: traces live in the server's span
        # recorder, and --url fetches ride the live HTTP front door
        tracing = _trace_report(client, tracer, _exemplar_tids(stats),
                                expect_router=False)
    spans = _span_breakdown(app)
    # tiered-store evidence (the --zipf workload's whole point): open
    # sessions across all three tiers vs slab occupancy, paging counters,
    # residency hit rate, wake latency vs one batcher tick, and the peak
    # RSS the >=100k-session memory claim is gated on
    tiering = None
    if mode == "zipf" and app is not None:
        from coda_tpu.telemetry.registry import sample_process_rss

        sample_process_rss(app.telemetry.registry)
        try:
            samples = app.telemetry.registry.gauge(
                "process_peak_rss_bytes").samples()
            peak_rss = max(v for _, v in samples) if samples else None
        except Exception:
            peak_rss = None
        wl = stats.get("wake_latency") or {}
        req_t = zipf_info.get("requests_traffic") or 0
        wakes_t = zipf_info.get("wakes_traffic") or 0
        tick_ms = (stats.get("dispatch_latency") or {}).get("p99_ms")
        wake_p99 = wl.get("p99_ms")
        tiering = {
            "open_sessions": stats.get("open_sessions"),
            "slab_occupancy": stats.get("slab_occupancy"),
            "tiers": stats.get("tiers"),
            "demotions": stats.get("demotions"),
            "hibernates": stats.get("hibernates"),
            "wakes": stats.get("wakes"),
            "wakes_from_warm": stats.get("wakes_from_warm"),
            "wakes_from_cold": stats.get("wakes_from_cold"),
            "wakes_via_replay": stats.get("wakes_via_replay"),
            "wake_failures": stats.get("wake_failures"),
            "wake_latency": wl,
            # 503s for wakeable sessions are forbidden by the tiering
            # contract: admission demotes instead of refusing
            "admission_rejects": stats.get("sessions_rejected"),
            "requests_traffic": req_t,
            "wakes_traffic": wakes_t,
            "hot_hit_rate": (1.0 - wakes_t / req_t) if req_t else None,
            "tick_ms": tick_ms,
            "wake_p99_vs_tick": (wake_p99 / tick_ms
                                 if wake_p99 and tick_ms else None),
            "peak_rss_bytes": peak_rss,
            "zipf_s": getattr(args, "zipf", None),
            "think_ms": getattr(args, "think_ms", 0.0),
            # spill store v3 evidence: sharded segments, garbage awaiting
            # compaction, and whether THIS process started O(index)
            "spill": stats.get("spill"),
        }
    spill_dir = (app.tiers._spill.dir
                 if app is not None and app.tiers is not None
                 and getattr(app.tiers, "_spill", None) is not None
                 else None)
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if app is not None:
        app.drain()
    if tiering is not None and spill_dir is not None:
        # startup-cost probe: drain closed the store (index flushed); a
        # fresh open of the same directory must be O(index) — read the
        # sidecar, verify tails, NO full segment scan
        from coda_tpu.serve.spill import SpillStore

        t0 = time.perf_counter()
        probe = SpillStore(spill_dir, compact=False)
        reopen_s = time.perf_counter() - t0
        st = probe.stats()
        probe.close()
        tiering["spill_reopen"] = {
            "reopen_s": reopen_s,
            "startup_mode": st["startup_mode"],
            "startup_scan_frames": st["startup_scan_frames"],
            "entries": st["entries"], "segments": st["segments"],
        }

    lat_ms = np.asarray(latencies, np.float64) * 1e3
    n_requests = len(latencies)
    # provenance stamp: the shared environment fingerprint that makes this
    # capture attributable and lets scripts/check_perf.py run same-
    # fingerprint cross-round regression comparisons on serve latency
    from coda_tpu.telemetry.recorder import environment_fingerprint

    fingerprint = environment_fingerprint(knobs={
        "method": args.method, "capacity": args.capacity,
        "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
        "max_linger_ms": args.max_linger_ms,
        "sessions": args.sessions, "labels": args.labels,
        "workers": args.workers, "step_impl": args.step_impl,
        # the workload-shaping axes too: two captures that differ in
        # arrival model or transport must never share a regression key
        "mode": mode,
        "transport": "http" if (args.url or args.http) else "inproc",
        "ramp_s": args.ramp_s,
        "zipf": getattr(args, "zipf", None),
        "think_ms": getattr(args, "think_ms", 0.0),
        "requests": getattr(args, "requests", None),
        "labels_per_round": lpr,
        "oracle_noise": getattr(args, "oracle_noise", None),
        "task": args.task or args.synthetic or "default"})
    # per-bucket executable cost attribution (warm-pool harvest): which
    # side of the roofline the slab step sits on, machine-read
    bucket_costs = [
        {"task": b.get("task"), "method": b.get("method"),
         "cost": b.get("cost")}
        for b in stats.get("buckets", [])] or None
    report = {
        "bench": "serve_loadgen",
        "fingerprint": fingerprint,
        "bucket_costs": bucket_costs,
        "mode": mode,
        "transport": ("http" if (args.url or args.http) else "inproc"),
        "workers": args.workers,
        "sessions": n_sessions,
        "labels_per_session": args.labels,
        "wall_s": wall,
        "warm_s": warm_s,
        "sessions_per_s": n_sessions / wall,
        "requests_per_s": n_requests / wall,
        "latency_ms": {
            "p50": float(np.percentile(lat_ms, 50)) if n_requests else None,
            "p99": float(np.percentile(lat_ms, 99)) if n_requests else None,
            "mean": float(lat_ms.mean()) if n_requests else None,
        },
        "errors": errors[:20],
        "n_errors": len(errors),
        # transient failures absorbed by client-side retry/backoff (chaos
        # mode / rolling restarts): these are NOT errors — every one was
        # eventually served, idempotently via its request_id
        "n_retries": len(retried),
        "retried": retried[:20],
        # the rolling-restart cycle's evidence (when --rolling-restart-at
        # ran): exported == imported == replay_verified means zero dropped
        # sessions and every migrated stream bitwise-verified
        "migration": migration or None,
        # --trace-sample evidence: sampled label traces fetched back from
        # the front door, scored for serve/tick/step completeness, plus
        # exemplar -> trace joinability
        "tracing": tracing,
        # tiered-store evidence (--zipf mode): open sessions vs slab
        # occupancy, paging counters, hot-set residency hit rate, wake
        # latency vs one tick, and peak RSS
        "tiering": tiering,
        # batch-label evidence (--labels-per-round q): oracle-answer
        # throughput and the amortized per-label latency distribution,
        # alongside the per-request rings above
        "batch": None if mode != "batch" else {
            "labels_per_round": lpr,
            "labels_total": n_sessions * args.labels * lpr,
            "labels_per_s": n_sessions * args.labels * lpr / wall,
            "per_label_latency_ms": {
                "p50": float(np.percentile(
                    np.asarray(label_latencies) * 1e3, 50))
                if label_latencies else None,
                "p99": float(np.percentile(
                    np.asarray(label_latencies) * 1e3, 99))
                if label_latencies else None,
                "mean": float(np.mean(label_latencies) * 1e3)
                if label_latencies else None,
            },
        },
        # crowd-oracle evidence (--oracle-noise): the client-side answer
        # mix per annotator, abstention rate, and deferral/reorder depths
        # next to the latency rings, plus the server's parking counters
        # (answers parked, rounds completed via deferred delivery, dedupe
        # rejections — the exactly-once evidence under reordering)
        "oracle": None if oracle_cfg is None else {
            "spec": args.oracle_noise,
            "annotators": oracle_cfg.annotators,
            "answers": int(crowd["answers"]),
            "annotator_mix": [int(v) for v in crowd["mix"]],
            "abstentions": int(crowd["abstentions"]),
            "abstention_rate": (crowd["abstentions"]
                                / max(1, int(crowd["mix"].sum()))),
            "deferred": int(crowd["deferred"]),
            "defer_depth_max": int(crowd["defer_depth_max"]),
            "reorder_depth_max": int(crowd["reorder_depth_max"]),
            "server": stats.get("oracle"),
        },
        "server": {
            "dispatches": stats.get("dispatches"),
            "requests": stats.get("requests"),
            "max_occupancy": stats.get("max_occupancy"),
            "mean_occupancy": stats.get("mean_occupancy"),
            "mean_queue_depth": stats.get("mean_queue_depth"),
            "dispatch_latency": stats.get("dispatch_latency"),
            "request_latency": stats.get("request_latency"),
        },
        # where a request's time went: queued behind a tick vs the
        # dispatch (host fan-out + step) vs the compiled step itself —
        # the rings give percentiles, the spans give busy-time totals
        "breakdown": {
            "queue_wait": stats.get("queue_wait"),
            "dispatch": stats.get("dispatch_latency"),
            "step": stats.get("step_latency"),
            "spans": spans,
        },
        "warm_pool": stats.get("warm_pool"),
        "config": {
            "method": args.method,
            "capacity": args.capacity,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "max_linger_ms": args.max_linger_ms,
            "step_impl": args.step_impl,
            "donate": not args.no_donate,
            "warm": not args.no_warm,
            "compilation_cache_dir": args.compilation_cache_dir,
            "ramp_s": args.ramp_s,
            "retries": args.retries,
            "fault_spec": getattr(args, "fault_spec", None),
            "rolling_restart_at": getattr(args, "rolling_restart_at",
                                          None),
            "task": args.task or args.synthetic or "default",
        },
    }
    return report


def parse_args(argv=None):
    from coda_tpu.serve.server import parse_args as server_args

    # reuse the server's flags (task/method/capacity/batching) and add the
    # load shape on top
    base = server_args([])
    # None-default flags carry no type to clone; name the numeric ones
    numeric = {"max_linger_ms": float}
    p = argparse.ArgumentParser(description=__doc__)
    for a, v in vars(base).items():
        if a == "port":
            continue
        if isinstance(v, bool):
            p.add_argument("--" + a.replace("_", "-"), default=v,
                           action="store_true" if not v
                           else "store_false")
        else:
            p.add_argument("--" + a.replace("_", "-"),
                           default=v, type=(type(v) if v is not None
                                            else numeric.get(a, str)))
    p.add_argument("--workers", type=int, default=32,
                   help="free-run: OS threads; mux: max concurrent "
                        "session coroutines")
    p.add_argument("--sessions", type=int, default=64,
                   help="total sessions to run (free-run / mux modes)")
    p.add_argument("--labels", type=int, default=8,
                   help="labels per session (with --labels-per-round: "
                        "ROUNDS per session, each carrying q labels)")
    p.add_argument("--labels-per-round", type=int, default=None,
                   metavar="Q",
                   help="batch-label mode: serve acq_batch=Q sessions and "
                        "answer each round's Q proposed items through ONE "
                        "POST /session/{id}/labels (the fused multi-row "
                        "update); reports labels/s and the amortized "
                        "per-label latency next to the per-request rings. "
                        "With --url the remote server must already run "
                        "--acq-batch Q")
    p.add_argument("--lockstep", action="store_true",
                   help="barrier arrivals: every round of W labels rides "
                        "one dispatch (deterministic occupancy)")
    p.add_argument("--mux", action="store_true",
                   help="asyncio arrival: sessions are coroutines on one "
                        "event loop (in-process verbs, or per-session "
                        "keep-alive connections with --http)")
    p.add_argument("--ramp-s", type=float, default=0.0,
                   help="mux: spread session arrivals over this many "
                        "seconds instead of a thundering herd at t=0")
    p.add_argument("--zipf", type=float, default=None, metavar="S",
                   help="Zipf-arrival mode (the tiering workload): open "
                        "--sessions sessions (admission demotes past slab "
                        "capacity, never 503s), then drive --requests "
                        "labels whose target session is Zipf(S)-skewed — "
                        "the hot set stays resident, the tail pages out "
                        "and wakes on touch; reports residency hit rate, "
                        "wake counts/latency, and peak RSS (in-process "
                        "only)")
    p.add_argument("--think-ms", type=float, default=0.0,
                   help="zipf: mean per-request exponential think time")
    p.add_argument("--requests", type=int, default=None,
                   help="zipf: total label requests in the traffic phase "
                        "(default sessions * labels)")
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="replicated-serve mode: build N in-process "
                        "replicas behind a rendezvous session router "
                        "(serve/fleet.py) and drive the router with the "
                        "free-run loop; reports per-replica request "
                        "distribution, migration counts (each digest-"
                        "verified), and the router's span-attributed "
                        "added latency. With --rolling-restart-at, every "
                        "replica is restarted IN SEQUENCE mid-load (the "
                        "zero-drop fleet demo)")
    p.add_argument("--fleet-chaos", default=None, metavar="SPEC",
                   help="with --fleet: per-edge transport fault spec "
                        "(serve/faults.py grammar with the net_* names, "
                        "edge=<replica> / task=<verb> filters), e.g. "
                        "'partition:edge=r0,after=20,times=30;"
                        "net_delay:every=7,ms=5'. The run must still "
                        "finish with 0 errors — retries, breakers, and "
                        "the ownership fence absorb the chaos; the "
                        "report's fleet.chaos section shows how")
    p.add_argument("--fleet-baseline", action="store_true",
                   help="with --fleet: first run the identical workload "
                        "on a 1-replica fleet (same router in front) and "
                        "report scaling efficiency = fleet rps / (N x "
                        "baseline rps) — the linearity claim, mechanical")
    p.add_argument("--retries", type=int, default=0,
                   help="client-side retries per request on transient "
                        "failures (503/504/500/conn-drop), exponential "
                        "backoff; labels carry an idempotent request_id "
                        "so a retry can never double-apply an oracle "
                        "answer (chaos-mode / rolling-restart companion)")
    p.add_argument("--backoff-ms", type=float, default=50.0,
                   help="base retry backoff (doubles per attempt)")
    p.add_argument("--rolling-restart-at", type=float, default=None,
                   metavar="S",
                   help="at S seconds into the run: quiesce the server, "
                        "export every live session, stand up a fresh one, "
                        "import (replay-verified), and swap the clients "
                        "over — the zero-drop migration demo (in-process "
                        "free-run only; needs --retries)")
    p.add_argument("--oracle-noise", default=None, metavar="SPEC",
                   help="crowd-oracle mode: answer every proposed slot "
                        "through POST /session/{id}/answer with a "
                        "deterministic noisy crowd (coda_tpu/crowd "
                        "spec grammar, e.g. 'annotators=8,abstain=0.1,"
                        "defer=0.3:4'); abstentions re-request the item, "
                        "deferred answers are delivered late/out of "
                        "order; reports per-annotator answer mix, "
                        "abstention rate, and reorder depth next to the "
                        "latency rings (with --labels-per-round Q the "
                        "rounds are Q wide)")
    p.add_argument("--trace-sample", type=float, default=0.0,
                   metavar="RATE",
                   help="sample this fraction of label requests with a "
                        "client-minted trace context (deterministic "
                        "stride, free-run / --fleet modes); after the run "
                        "every sampled trace is fetched back from the "
                        "front door (stitched across process lanes by a "
                        "router) and the report's tracing section scores "
                        "completeness + exemplar->trace joinability")
    p.add_argument("--http", action="store_true",
                   help="drive the in-process app over real HTTP instead "
                        "of direct calls")
    p.add_argument("--url", default=None,
                   help="target a RUNNING server instead of in-process")
    p.add_argument("--classes", type=int, default=10,
                   help="label range when targeting --url (the remote "
                        "task's C)")
    p.add_argument("--out", default=None,
                   help="write the JSON report here "
                        "(default BENCH_SERVE_<mode>.json)")
    args = p.parse_args(argv)
    if args.capacity < args.workers and not args.url:
        # closed-loop workers each hold one live session; a smaller slab
        # would make backpressure part of the measurement
        args.capacity = args.workers
    return args


def main(argv=None):
    args = parse_args(argv)
    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    report = run_loadgen(args)
    out = args.out or f"BENCH_SERVE_{report['mode']}.json"
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
