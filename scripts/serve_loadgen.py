"""Closed-loop load generator for the serving layer -> BENCH_SERVE_*.json.

Spins up an in-process :class:`coda_tpu.serve.ServeApp` + HTTP server (or
targets a running one via ``--url``), then drives W closed-loop workers:
each opens a session, labels ``--labels`` proposed items (answering
``idx % C`` — the serving cost is label-independent), and closes. Reports
sessions/sec, requests/sec, client-side latency percentiles, and the
server's own dispatch metrics (batch occupancy — the number the subsystem
exists to maximize) into one JSON artifact.

Two arrival models:

  * default — workers free-run; occupancy emerges from the batcher's
    ``max_wait`` coalescing window (the realistic number);
  * ``--lockstep`` — workers rendezvous at a barrier each round while the
    batcher is paused, so every round's W requests ride ONE dispatch. This
    is the deterministic-occupancy mode the tier-1 smoke test pins ≥16
    sessions/dispatch with (in-process only).

    python scripts/serve_loadgen.py --workers 32 --sessions 64 \
        --synthetic 8,512,10 --out BENCH_SERVE_cpu.json
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

# importable from any cwd (the aggregate_results.py convention)
sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


# ---------------------------------------------------------------------------
# client: in-process (drives a ServeApp directly) or HTTP (urllib, stdlib)
# ---------------------------------------------------------------------------

class InprocClient:
    def __init__(self, app):
        self.app = app

    def open(self, seed):
        return self.app.open_session(seed=seed)

    def label(self, sid, label):
        return self.app.label(sid, label)

    def close(self, sid):
        return self.app.close_session(sid)

    def stats(self):
        return self.app.stats()


class HttpClient:
    def __init__(self, url):
        self.url = url.rstrip("/")

    def _req(self, method, path, body=None):
        import urllib.request

        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def open(self, seed):
        return self._req("POST", "/session", {"seed": seed})

    def label(self, sid, label):
        return self._req("POST", f"/session/{sid}/label", {"label": label})

    def close(self, sid):
        return self._req("DELETE", f"/session/{sid}")

    def stats(self):
        return self._req("GET", "/stats")


# ---------------------------------------------------------------------------
# the closed loop
# ---------------------------------------------------------------------------

def _free_run(client, n_classes, workers, sessions, labels_per_session,
              latencies, errors):
    """Default arrival model: W workers race through the session budget."""
    counter = {"next": 0}
    lock = threading.Lock()

    def take():
        with lock:
            s = counter["next"]
            if s >= sessions:
                return None
            counter["next"] = s + 1
            return s

    def worker():
        while True:
            seed = take()
            if seed is None:
                return
            sid = None
            try:
                t0 = time.perf_counter()
                out = client.open(seed)
                sid = out["session"]
                latencies.append(time.perf_counter() - t0)
                for _ in range(labels_per_session):
                    t0 = time.perf_counter()
                    out = client.label(sid, int(out["idx"]) % n_classes)
                    latencies.append(time.perf_counter() - t0)
                client.close(sid)
                sid = None
            except Exception as e:  # keep the run alive; report at the end
                errors.append(repr(e))
                if sid is not None:
                    # free the slot: capacity == workers, so one leaked
                    # session would starve every later open into SlabFull
                    try:
                        client.close(sid)
                    except Exception:
                        pass

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _lockstep(app, client, n_classes, workers, labels_per_session,
              latencies, errors):
    """Deterministic occupancy: open W sessions, then label all W in
    rounds, pausing the batcher while each round's requests queue up so
    every round is exactly ONE dispatch per bucket. In-process only (needs
    the batcher handle)."""
    sids = []
    for seed in range(workers):
        sids.append(client.open(seed)["session"])
    for _ in range(labels_per_session):
        app.batcher.pause()
        tickets = []
        t0 = time.perf_counter()
        for sid in sids:
            sess = app.store.get(sid)
            cur = sess.last
            tickets.append(app.batcher.submit_label(
                sess, idx=cur["next_idx"],
                label=int(cur["next_idx"]) % n_classes,
                prob=cur["next_prob"]))
        app.batcher.resume()
        for t in tickets:
            try:
                t.wait(60.0)
                latencies.append(time.perf_counter() - t0)
            except Exception as e:
                errors.append(repr(e))
    for sid in sids:
        client.close(sid)


def run_loadgen(args) -> dict:
    """Run the configured load and return the report dict (the script's
    JSON payload; the smoke test calls this directly)."""
    from coda_tpu.serve.server import build_app, make_server

    app = srv = None
    if args.url:
        client = HttpClient(args.url)
        n_classes = args.classes
    else:
        app = build_app(args).start()
        meta = app.store.task_meta(app.default_task)
        n_classes = len(meta["class_names"])
        if args.http:
            srv = make_server(app, 0)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            client = HttpClient(
                f"http://127.0.0.1:{srv.server_address[1]}")
        else:
            client = InprocClient(app)

    latencies: list = []
    errors: list = []
    t_start = time.perf_counter()
    if args.lockstep:
        if app is None:
            raise SystemExit("--lockstep needs an in-process app (no --url)")
        n_sessions = args.workers
        _lockstep(app, client, n_classes, args.workers, args.labels,
                  latencies, errors)
    else:
        n_sessions = args.sessions
        _free_run(client, n_classes, args.workers, args.sessions,
                  args.labels, latencies, errors)
    wall = time.perf_counter() - t_start

    stats = client.stats()
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if app is not None:
        app.drain()

    lat_ms = np.asarray(latencies, np.float64) * 1e3
    n_requests = len(latencies)
    report = {
        "bench": "serve_loadgen",
        "mode": "lockstep" if args.lockstep else "free_run",
        "transport": ("http" if (args.url or args.http) else "inproc"),
        "workers": args.workers,
        "sessions": n_sessions,
        "labels_per_session": args.labels,
        "wall_s": wall,
        "sessions_per_s": n_sessions / wall,
        "requests_per_s": n_requests / wall,
        "latency_ms": {
            "p50": float(np.percentile(lat_ms, 50)) if n_requests else None,
            "p99": float(np.percentile(lat_ms, 99)) if n_requests else None,
            "mean": float(lat_ms.mean()) if n_requests else None,
        },
        "errors": errors[:20],
        "n_errors": len(errors),
        "server": {
            "dispatches": stats.get("dispatches"),
            "requests": stats.get("requests"),
            "max_occupancy": stats.get("max_occupancy"),
            "mean_occupancy": stats.get("mean_occupancy"),
            "mean_queue_depth": stats.get("mean_queue_depth"),
            "dispatch_latency": stats.get("dispatch_latency"),
            "request_latency": stats.get("request_latency"),
        },
        "config": {
            "method": args.method,
            "capacity": args.capacity,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "task": args.task or args.synthetic or "default",
        },
    }
    return report


def parse_args(argv=None):
    from coda_tpu.serve.server import parse_args as server_args

    # reuse the server's flags (task/method/capacity/batching) and add the
    # load shape on top
    base = server_args([])
    p = argparse.ArgumentParser(description=__doc__)
    for a, v in vars(base).items():
        if a != "port":
            p.add_argument("--" + a.replace("_", "-"),
                           default=v, type=(type(v) if v is not None
                                            else str))
    p.add_argument("--workers", type=int, default=32)
    p.add_argument("--sessions", type=int, default=64,
                   help="total sessions to run (free-run mode)")
    p.add_argument("--labels", type=int, default=8,
                   help="labels per session")
    p.add_argument("--lockstep", action="store_true",
                   help="barrier arrivals: every round of W labels rides "
                        "one dispatch (deterministic occupancy)")
    p.add_argument("--http", action="store_true",
                   help="drive the in-process app over real HTTP instead "
                        "of direct calls")
    p.add_argument("--url", default=None,
                   help="target a RUNNING server instead of in-process")
    p.add_argument("--classes", type=int, default=10,
                   help="label range when targeting --url (the remote "
                        "task's C)")
    p.add_argument("--out", default=None,
                   help="write the JSON report here "
                        "(default BENCH_SERVE_<mode>.json)")
    args = p.parse_args(argv)
    if args.capacity < args.workers and not args.url:
        # closed-loop workers each hold one live session; a smaller slab
        # would make backpressure part of the measurement
        args.capacity = args.workers
    return args


def main(argv=None):
    args = parse_args(argv)
    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    report = run_loadgen(args)
    out = args.out or f"BENCH_SERVE_{report['mode']}.json"
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
