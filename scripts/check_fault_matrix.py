"""Fault-injection matrix gate: every injection point ends recovered.

The recovery layer (``coda_tpu/serve/recovery.py``) is only as real as the
failures it has actually been driven through. This checker runs the full
fault matrix — each ``serve/faults.py`` injection point against an
in-process server under retrying closed-loop traffic — and fails on:

  * an **unrecovered session**: any client request that still errors after
    retries, any session that did not reach its label budget, any bucket
    left terminally failed by a fault that has a recovery path;
  * **silent degradation**: a healed/poisoned posterior that replay
    verification does NOT flag (the ``step_nan`` scenario *must* produce a
    digest divergence — if the corrupted stream replays "clean", the
    digest check is dead and corruption would ship silently);
  * **double application**: more labels applied to a posterior than the
    client issued logical labels (retry dedupe broken).

Scenarios (fault → expected recovery → verification):

  ===================  =============================  ====================
  step_raise           bucket quarantine → slab heal  streams replay clean
  step_nan             none (corruption is recorded)  replay MUST diverge
  record_eio           stream degrades to memory-only session completes
  slow_step            none needed                    0 errors, all served
  demote_during_label  demotion wins → wake-on-label, streams replay clean,
                       or loses cleanly to the pin    exact label counts
  crash_before_tick    restart + restore from streams all sessions rebuilt
  crash_after_tick     restart + restore from streams all sessions rebuilt
  ===================  =============================  ====================

The two crash scenarios spawn a child process that kills itself at the
injected tick boundary (exit 17); ``--skip-crash`` omits them (the tier-1
wiring test does, since ``tests/test_recovery.py`` covers crash recovery
with a full bitwise-vs-control comparison). Runnable standalone::

    python scripts/check_fault_matrix.py [--skip-crash]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import uuid

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# the matrix shape: small enough to compile fast, big enough that every
# fault lands under multi-session traffic
H, N, C = 4, 48, 4
CAPACITY = 6
SESSIONS = 6
ROUNDS = 4
RETRIES = 10
BACKOFF_S = 0.03


def _make_app(fault_spec, record_dir=None, capacity=CAPACITY):
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import SelectorSpec, ServeApp
    from coda_tpu.telemetry import SessionRecorder

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    recorder = SessionRecorder(out_dir=record_dir) if record_dir else None
    app = ServeApp(capacity=capacity, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=capacity),
                   fault_spec=fault_spec, recorder=recorder)
    app.add_task(task.name, task.preds)
    app.start(warm=True)
    return app, task


def _drive(app, n_sessions=SESSIONS, rounds=ROUNDS, retries=RETRIES):
    """Closed-loop retrying traffic (the loadgen's client discipline:
    idempotent request_id per logical label). Returns (sids, errors)."""
    from scripts.serve_loadgen import with_retries

    sids = [None] * n_sessions
    errors: list = []

    def worker(i):
        try:
            out = with_retries(lambda: app.open_session(seed=i),
                               retries, BACKOFF_S)
            sids[i] = out["session"]
            for _ in range(rounds):
                lab = int(out["idx"]) % C
                rid = uuid.uuid4().hex
                out = with_retries(
                    lambda: app.label(sids[i], lab, request_id=rid),
                    retries, BACKOFF_S)
        except Exception as e:
            errors.append(f"session {i}: {e!r}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sids, errors


def _common_checks(app, sids, errors, scenario) -> list[str]:
    out = []
    for e in errors:
        out.append(f"{scenario}: unrecovered request after retries — {e}")
    for i, sid in enumerate(sids):
        if sid is None:
            out.append(f"{scenario}: session {i} never opened")
            continue
        n = app.store.get(sid).n_labeled
        if n != ROUNDS:
            out.append(f"{scenario}: session {sid} applied {n} labels, "
                       f"client issued {ROUNDS} (lost or double-applied)")
    return out


def _verify_streams(app, sids):
    """Offline bitwise replay of each session's stream against a FRESH
    slab; returns {sid: None | 'divergence reason'}."""
    from coda_tpu.serve import SessionStore
    from coda_tpu.serve.recovery import verify_session_stream

    store = SessionStore(capacity=2)
    preds = app.store._tasks[app.default_task]
    store.register_task(app.default_task, preds)
    verdicts = {}
    for sid in sids:
        meta = {"task": app.default_task, "method": app.spec.method,
                "spec_kwargs": [list(kv) for kv in app.spec.kwargs],
                "seed": app.store.get(sid).seed}
        try:
            verify_session_stream(store, meta, app.recorder.history(sid),
                                  sid=sid)
            verdicts[sid] = None
        except Exception as e:
            verdicts[sid] = repr(e)
    return verdicts


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

def scenario_step_raise() -> list[str]:
    """Step failure consuming donated carries → quarantine → digest-
    verified slab rebuild; traffic rides through on retries."""
    app, _ = _make_app("step_raise:after=3")
    try:
        sids, errors = _drive(app)
        out = _common_checks(app, sids, errors, "step_raise")
        b = app.store.buckets()[0]
        if b.heals < 1:
            out.append("step_raise: fault fired but no slab heal ran")
        if b.failed is not None:
            out.append(f"step_raise: bucket degraded to terminal: "
                       f"{b.failed}")
        if b.quarantined is not None:
            out.append("step_raise: bucket still quarantined after drive")
        for sid, verdict in _verify_streams(app, filter(None, sids)).items():
            if verdict is not None:
                out.append(f"step_raise: healed session {sid} failed "
                           f"replay verification — {verdict}")
        if app.healthz()["status"] != "ok":
            out.append(f"step_raise: healthz {app.healthz()} after heal")
        return out
    finally:
        app.drain(timeout=10)


def scenario_step_nan() -> list[str]:
    """Silent posterior corruption: the run completes (NaN is not an
    exception), but replay verification MUST flag the poisoned stream —
    a clean verdict here means corruption ships silently."""
    app, _ = _make_app("step_nan:after=3")
    try:
        sids, errors = _drive(app)
        out = [f"step_nan: {e}" for e in errors]
        verdicts = _verify_streams(app, filter(None, sids))
        n_flagged = sum(1 for v in verdicts.values() if v is not None)
        if n_flagged < 1:
            out.append(
                "step_nan: SILENT DEGRADATION — a NaN-poisoned round was "
                "recorded but replay verification flagged nothing (the "
                "digest check is dead)")
        return out
    finally:
        app.drain(timeout=10)


def scenario_record_eio() -> list[str]:
    """Recorder disk write fails → the stream degrades to memory-only,
    the session keeps serving, and the degradation is visible."""
    with tempfile.TemporaryDirectory() as d:
        app, _ = _make_app("record_eio:after=2", record_dir=d)
        try:
            sids, errors = _drive(app)
            out = _common_checks(app, sids, errors, "record_eio")
            if app.recorder.degraded_streams < 1:
                out.append("record_eio: fault fired but no stream was "
                           "marked degraded")
            if "recorder_degraded" not in app.healthz()["problems"]:
                out.append(f"record_eio: degradation invisible on "
                           f"/healthz: {app.healthz()}")
            # in-memory histories stay authoritative: still replayable
            for sid, verdict in _verify_streams(
                    app, filter(None, sids)).items():
                if verdict is not None:
                    out.append(f"record_eio: session {sid} memory stream "
                               f"failed replay — {verdict}")
            return out
        finally:
            app.drain(timeout=10)


def scenario_slow_step() -> list[str]:
    """A stalling step is tail pain, not a fault: everything completes."""
    app, _ = _make_app("slow_step:every=2,ms=40,times=6")
    try:
        sids, errors = _drive(app)
        return _common_checks(app, sids, errors, "slow_step")
    finally:
        app.drain(timeout=10)


def scenario_demote_during_label() -> list[str]:
    """A tier demotion injected at the exact moment a label arrives
    (serve/tiering.py): when the session is quiescent the demotion WINS
    and the label transparently wakes it back; when a ticket is in flight
    the demotion LOSES cleanly to the pin. Either way: no lost label, no
    double-apply, every stream still replays bitwise."""
    app, _ = _make_app("demote_during_label:every=2,times=12")
    try:
        sids, errors = _drive(app)
        out = _common_checks(app, sids, errors, "demote_during_label")
        fired = sum(f["fired"] for f in app.faults.snapshot()
                    if f["name"] == "demote_during_label")
        if fired < 1:
            out.append("demote_during_label: fault never fired")
        if app.metrics.demotions < 1:
            out.append("demote_during_label: no injected demotion ever "
                       "won (the wake-on-label path went unexercised)")
        if app.metrics.wakes < 1:
            out.append("demote_during_label: demotions won but no label "
                       "ever woke its session")
        for sid, verdict in _verify_streams(app, filter(None, sids)).items():
            if verdict is not None:
                out.append(f"demote_during_label: session {sid} failed "
                           f"replay verification after paging — {verdict}")
        return out
    finally:
        app.drain(timeout=10)


_CRASH_CHILD = r"""
import sys
from scripts.check_fault_matrix import _make_app, _drive
app, _ = _make_app(sys.argv[1], record_dir=sys.argv[2])
_drive(app, retries=0)          # the injected crash kills us mid-drive
app.drain(timeout=10)           # only reached if the fault never fired
print("NO_CRASH")
"""


def scenario_crash(site: str) -> list[str]:
    """Process death at a tick boundary → restart restores every live
    session from its JSONL stream, replay-verified."""
    from coda_tpu.serve.recovery import iter_session_streams

    scenario = site
    out: list[str] = []
    with tempfile.TemporaryDirectory() as d:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child = subprocess.run(
            [sys.executable, "-c", _CRASH_CHILD, f"{site}:after=3", d],
            env=env, cwd=repo, capture_output=True, text=True, timeout=600)
        if child.returncode != 17:
            return [f"{scenario}: child exited {child.returncode}, "
                    f"expected the injected crash (17): "
                    f"{child.stderr[-500:]}"]
        streams = list(iter_session_streams(d))
        if not streams:
            return [f"{scenario}: crashed child left no session streams"]
        app, _ = _make_app(None, record_dir=d)
        try:
            report = app.restore_sessions(d)
            if report["failed"]:
                out.append(f"{scenario}: restore failures: "
                           f"{report['failed']}")
            n_live = len(report["restored"])
            if n_live + report["skipped_closed"] != len(streams):
                out.append(f"{scenario}: {len(streams)} streams but only "
                           f"{n_live} restored + "
                           f"{report['skipped_closed']} closed")
            # restored sessions must still serve
            for sid in report["restored"]:
                sess = app.store.get(sid)
                if sess.last:
                    app.label(sid, int(sess.last["next_idx"]) % C)
            return out
        finally:
            app.drain(timeout=10)


# ---------------------------------------------------------------------------

SCENARIOS = {
    "step_raise": scenario_step_raise,
    "step_nan": scenario_step_nan,
    "record_eio": scenario_record_eio,
    "slow_step": scenario_slow_step,
    "demote_during_label": scenario_demote_during_label,
    "crash_before_tick": lambda: scenario_crash("crash_before_tick"),
    "crash_after_tick": lambda: scenario_crash("crash_after_tick"),
}


def run_matrix(skip_crash: bool = False) -> dict[str, list[str]]:
    """{scenario: violations} (empty lists = clean)."""
    results = {}
    for name, fn in SCENARIOS.items():
        if skip_crash and name.startswith("crash_"):
            continue
        results[name] = fn()
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--skip-crash", action="store_true",
                   help="omit the two subprocess crash scenarios")
    p.add_argument("--out", default=None,
                   help="write the {scenario: violations} JSON here")
    args = p.parse_args(argv)

    results = run_matrix(skip_crash=args.skip_crash)
    bad = 0
    for name, violations in results.items():
        for v in violations:
            print(f"FAIL {v}")
            bad += 1
        if not violations:
            print(f"ok   {name}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
    if bad:
        print(f"fault matrix FAILED: {bad} violation(s)")
        return 1
    print(f"fault matrix clean: {len(results)} scenario(s), every "
          "injection point recovered or attributably detected")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
