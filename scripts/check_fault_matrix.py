"""Fault-injection matrix gate: every injection point ends recovered.

The recovery layer (``coda_tpu/serve/recovery.py``) is only as real as the
failures it has actually been driven through. This checker runs the full
fault matrix — each ``serve/faults.py`` injection point against an
in-process server under retrying closed-loop traffic — and fails on:

  * an **unrecovered session**: any client request that still errors after
    retries, any session that did not reach its label budget, any bucket
    left terminally failed by a fault that has a recovery path;
  * **silent degradation**: a healed/poisoned posterior that replay
    verification does NOT flag (the ``step_nan`` scenario *must* produce a
    digest divergence — if the corrupted stream replays "clean", the
    digest check is dead and corruption would ship silently);
  * **double application**: more labels applied to a posterior than the
    client issued logical labels (retry dedupe broken).

Scenarios (fault → expected recovery → verification):

  ===================  =============================  ====================
  step_raise           bucket quarantine → slab heal  streams replay clean
  step_nan             none (corruption is recorded)  replay MUST diverge
  record_eio           stream degrades to memory-only session completes
  slow_step            none needed                    0 errors, all served
  demote_during_label  demotion wins → wake-on-label, streams replay clean,
                       or loses cleanly to the pin    exact label counts
  crash_before_tick    restart + restore from streams all sessions rebuilt
  crash_after_tick     restart + restore from streams all sessions rebuilt
  ===================  =============================  ====================

The two crash scenarios spawn a child process that kills itself at the
injected tick boundary (exit 17); ``--skip-crash`` omits them (the tier-1
wiring test does, since ``tests/test_recovery.py`` covers crash recovery
with a full bitwise-vs-control comparison).

**Fleet matrix** (``--fleet``): the same discipline one level up — every
fleet-layer failure mode (ISSUE 14) driven against a 2-replica fleet
behind the rendezvous router, each scenario ending with every session
reachable, label counts exact, ``migration_verified == migrations`` and
zero double-applies:

  ==========================  ========================================
  fleet_stale_owner_fence     partition eats the migration's source
                              fence; the stale copy revives and a write
                              is attempted at it with the router's
                              stamp — the epoch fence MUST reject it
                              (the split-brain double-apply regression)
  fleet_kill_replica_mid_     the destination is SIGKILLed between
  migration                   export and import — the move degrades to
                              didn't-move, the source serves on
  fleet_router_restart_       the router dies mid-migration at each
  journal                     journal phase (intent/exported/imported);
                              a fresh router's journal recovery must
                              restore or finalize, exactly once
  fleet_healthz_flap          a flapping /healthz probe must NOT churn
                              the routing set (eviction hysteresis)
  fleet_transport_chaos       drop + delay + duplicate on live label
                              traffic: retries + request_id dedupe
                              absorb everything, exactly-once holds
  fleet_partition_heal        a replica partitions for a window and
                              heals: breaker trips, traffic fails over
                              or waits, 0 errors end to end
  ==========================  ========================================

``--fleet --out FAULT_MATRIX_FLEET_<backend>_rNN.json`` writes the
committed artifact ``scripts/check_perf.py`` gates. Runnable standalone::

    python scripts/check_fault_matrix.py [--skip-crash] [--fleet]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# the matrix shape: small enough to compile fast, big enough that every
# fault lands under multi-session traffic
H, N, C = 4, 48, 4
CAPACITY = 6
SESSIONS = 6
ROUNDS = 4
RETRIES = 10
BACKOFF_S = 0.03


def _make_app(fault_spec, record_dir=None, capacity=CAPACITY):
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import SelectorSpec, ServeApp
    from coda_tpu.telemetry import SessionRecorder

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    recorder = SessionRecorder(out_dir=record_dir) if record_dir else None
    app = ServeApp(capacity=capacity, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=capacity),
                   fault_spec=fault_spec, recorder=recorder)
    app.add_task(task.name, task.preds)
    app.start(warm=True)
    return app, task


def _drive(app, n_sessions=SESSIONS, rounds=ROUNDS, retries=RETRIES):
    """Closed-loop retrying traffic (the loadgen's client discipline:
    idempotent request_id per logical label). Returns (sids, errors)."""
    from scripts.serve_loadgen import with_retries

    sids = [None] * n_sessions
    errors: list = []

    def worker(i):
        try:
            out = with_retries(lambda: app.open_session(seed=i),
                               retries, BACKOFF_S)
            sids[i] = out["session"]
            for _ in range(rounds):
                lab = int(out["idx"]) % C
                rid = uuid.uuid4().hex
                out = with_retries(
                    lambda: app.label(sids[i], lab, request_id=rid),
                    retries, BACKOFF_S)
        except Exception as e:
            errors.append(f"session {i}: {e!r}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sids, errors


def _common_checks(app, sids, errors, scenario) -> list[str]:
    out = []
    for e in errors:
        out.append(f"{scenario}: unrecovered request after retries — {e}")
    for i, sid in enumerate(sids):
        if sid is None:
            out.append(f"{scenario}: session {i} never opened")
            continue
        n = app.store.get(sid).n_labeled
        if n != ROUNDS:
            out.append(f"{scenario}: session {sid} applied {n} labels, "
                       f"client issued {ROUNDS} (lost or double-applied)")
    return out


def _verify_streams(app, sids):
    """Offline bitwise replay of each session's stream against a FRESH
    slab; returns {sid: None | 'divergence reason'}."""
    from coda_tpu.serve import SessionStore
    from coda_tpu.serve.recovery import verify_session_stream

    store = SessionStore(capacity=2)
    preds = app.store._tasks[app.default_task]
    store.register_task(app.default_task, preds)
    verdicts = {}
    for sid in sids:
        meta = {"task": app.default_task, "method": app.spec.method,
                "spec_kwargs": [list(kv) for kv in app.spec.kwargs],
                "seed": app.store.get(sid).seed}
        try:
            verify_session_stream(store, meta, app.recorder.history(sid),
                                  sid=sid)
            verdicts[sid] = None
        except Exception as e:
            verdicts[sid] = repr(e)
    return verdicts


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

def scenario_step_raise() -> list[str]:
    """Step failure consuming donated carries → quarantine → digest-
    verified slab rebuild; traffic rides through on retries."""
    app, _ = _make_app("step_raise:after=3")
    try:
        sids, errors = _drive(app)
        out = _common_checks(app, sids, errors, "step_raise")
        b = app.store.buckets()[0]
        if b.heals < 1:
            out.append("step_raise: fault fired but no slab heal ran")
        if b.failed is not None:
            out.append(f"step_raise: bucket degraded to terminal: "
                       f"{b.failed}")
        if b.quarantined is not None:
            out.append("step_raise: bucket still quarantined after drive")
        for sid, verdict in _verify_streams(app, filter(None, sids)).items():
            if verdict is not None:
                out.append(f"step_raise: healed session {sid} failed "
                           f"replay verification — {verdict}")
        if app.healthz()["status"] != "ok":
            out.append(f"step_raise: healthz {app.healthz()} after heal")
        return out
    finally:
        app.drain(timeout=10)


def scenario_step_nan() -> list[str]:
    """Silent posterior corruption: the run completes (NaN is not an
    exception), but replay verification MUST flag the poisoned stream —
    a clean verdict here means corruption ships silently."""
    app, _ = _make_app("step_nan:after=3")
    try:
        sids, errors = _drive(app)
        out = [f"step_nan: {e}" for e in errors]
        verdicts = _verify_streams(app, filter(None, sids))
        n_flagged = sum(1 for v in verdicts.values() if v is not None)
        if n_flagged < 1:
            out.append(
                "step_nan: SILENT DEGRADATION — a NaN-poisoned round was "
                "recorded but replay verification flagged nothing (the "
                "digest check is dead)")
        return out
    finally:
        app.drain(timeout=10)


def scenario_record_eio() -> list[str]:
    """Recorder disk write fails → the stream degrades to memory-only,
    the session keeps serving, and the degradation is visible."""
    with tempfile.TemporaryDirectory() as d:
        app, _ = _make_app("record_eio:after=2", record_dir=d)
        try:
            sids, errors = _drive(app)
            out = _common_checks(app, sids, errors, "record_eio")
            if app.recorder.degraded_streams < 1:
                out.append("record_eio: fault fired but no stream was "
                           "marked degraded")
            if "recorder_degraded" not in app.healthz()["problems"]:
                out.append(f"record_eio: degradation invisible on "
                           f"/healthz: {app.healthz()}")
            # in-memory histories stay authoritative: still replayable
            for sid, verdict in _verify_streams(
                    app, filter(None, sids)).items():
                if verdict is not None:
                    out.append(f"record_eio: session {sid} memory stream "
                               f"failed replay — {verdict}")
            return out
        finally:
            app.drain(timeout=10)


def scenario_slow_step() -> list[str]:
    """A stalling step is tail pain, not a fault: everything completes."""
    app, _ = _make_app("slow_step:every=2,ms=40,times=6")
    try:
        sids, errors = _drive(app)
        return _common_checks(app, sids, errors, "slow_step")
    finally:
        app.drain(timeout=10)


def scenario_demote_during_label() -> list[str]:
    """A tier demotion injected at the exact moment a label arrives
    (serve/tiering.py): when the session is quiescent the demotion WINS
    and the label transparently wakes it back; when a ticket is in flight
    the demotion LOSES cleanly to the pin. Either way: no lost label, no
    double-apply, every stream still replays bitwise."""
    app, _ = _make_app("demote_during_label:every=2,times=12")
    try:
        sids, errors = _drive(app)
        out = _common_checks(app, sids, errors, "demote_during_label")
        fired = sum(f["fired"] for f in app.faults.snapshot()
                    if f["name"] == "demote_during_label")
        if fired < 1:
            out.append("demote_during_label: fault never fired")
        if app.metrics.demotions < 1:
            out.append("demote_during_label: no injected demotion ever "
                       "won (the wake-on-label path went unexercised)")
        if app.metrics.wakes < 1:
            out.append("demote_during_label: demotions won but no label "
                       "ever woke its session")
        for sid, verdict in _verify_streams(app, filter(None, sids)).items():
            if verdict is not None:
                out.append(f"demote_during_label: session {sid} failed "
                           f"replay verification after paging — {verdict}")
        return out
    finally:
        app.drain(timeout=10)


_CRASH_CHILD = r"""
import sys
from scripts.check_fault_matrix import _make_app, _drive
app, _ = _make_app(sys.argv[1], record_dir=sys.argv[2])
_drive(app, retries=0)          # the injected crash kills us mid-drive
app.drain(timeout=10)           # only reached if the fault never fired
print("NO_CRASH")
"""


def scenario_crash(site: str) -> list[str]:
    """Process death at a tick boundary → restart restores every live
    session from its JSONL stream, replay-verified."""
    from coda_tpu.serve.recovery import iter_session_streams

    scenario = site
    out: list[str] = []
    with tempfile.TemporaryDirectory() as d:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child = subprocess.run(
            [sys.executable, "-c", _CRASH_CHILD, f"{site}:after=3", d],
            env=env, cwd=repo, capture_output=True, text=True, timeout=600)
        if child.returncode != 17:
            return [f"{scenario}: child exited {child.returncode}, "
                    f"expected the injected crash (17): "
                    f"{child.stderr[-500:]}"]
        streams = list(iter_session_streams(d))
        if not streams:
            return [f"{scenario}: crashed child left no session streams"]
        app, _ = _make_app(None, record_dir=d)
        try:
            report = app.restore_sessions(d)
            if report["failed"]:
                out.append(f"{scenario}: restore failures: "
                           f"{report['failed']}")
            n_live = len(report["restored"])
            if n_live + report["skipped_closed"] != len(streams):
                out.append(f"{scenario}: {len(streams)} streams but only "
                           f"{n_live} restored + "
                           f"{report['skipped_closed']} closed")
            # restored sessions must still serve
            for sid in report["restored"]:
                sess = app.store.get(sid)
                if sess.last:
                    app.label(sid, int(sess.last["next_idx"]) % C)
            return out
        finally:
            app.drain(timeout=10)


# ---------------------------------------------------------------------------
# the fleet matrix (ISSUE 14): chaos against the replicated fleet
# ---------------------------------------------------------------------------

FLEET_ROUNDS = 3


def _make_fleet(tmpdir, n=2, fault_spec=None, hysteresis=2, capacity=6,
                poll_s=None, fast_transport=True):
    """A 2-replica in-process fleet with per-replica record dirs and the
    router's migration journal armed (``<tmpdir>/router_migrations.log``)."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import Fleet, SelectorSpec, ServeApp
    from coda_tpu.telemetry import SessionRecorder

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)

    def factory(rid):
        rec = SessionRecorder(out_dir=os.path.join(tmpdir, rid))
        app = ServeApp(capacity=capacity, max_wait=0.001,
                       spec=SelectorSpec.create("coda",
                                                n_parallel=capacity),
                       recorder=rec)
        app.add_task(task.name, task.preds)
        return app

    fleet = Fleet(factory, n_replicas=n,
                  journal_path=os.path.join(tmpdir,
                                            "router_migrations.log"),
                  fault_spec=fault_spec, health_hysteresis=hysteresis)
    if fast_transport:
        # matrix-speed knobs: the policies under test are the same, only
        # the waits shrink (breaker heals in 50 ms, backoff base 10 ms)
        for h in fleet.router.replicas.values():
            t = getattr(h, "transport", None)
            if t is not None:
                t.backoff_s = 0.01
                t.breaker.cooldown_s = 0.05
    fleet.start(warm=True, **({"poll_s": poll_s} if poll_s else {}))
    return fleet


def _drive_router(router, n_sessions=4, rounds=FLEET_ROUNDS, retries=12,
                  backoff_s=0.03):
    """Closed-loop retrying traffic through the router front door (one
    idempotent request_id per logical label). Returns (sids, errors)."""
    from scripts.serve_loadgen import with_retries

    sids = [None] * n_sessions
    errors: list = []

    def worker(i):
        try:
            out = with_retries(lambda: router.open_session(seed=i),
                               retries, backoff_s)
            sids[i] = out["session"]
            for _ in range(rounds):
                lab = int(out["idx"]) % C
                rid = uuid.uuid4().hex
                out = with_retries(
                    lambda: router.label(sids[i], lab, request_id=rid),
                    retries, backoff_s)
            if out["n_labeled"] != rounds:
                errors.append(
                    f"session {sids[i]}: server applied "
                    f"{out['n_labeled']} labels, client issued {rounds}")
        except Exception as e:
            errors.append(f"session {i}: {e!r}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sids, errors


def _fleet_reachability(router, sids, rounds=FLEET_ROUNDS) -> list:
    """Every session must still answer through the router with the exact
    committed label count — the all-scenarios postcondition."""
    out = []
    for sid in sids:
        if sid is None:
            continue
        try:
            b = router.best(sid)
        except Exception as e:
            out.append(f"session {sid} unreachable after recovery: {e!r}")
            continue
        if b["n_labeled"] != rounds:
            out.append(f"session {sid}: {b['n_labeled']} labels committed"
                       f", client issued {rounds} (lost or double)")
    return out


def scenario_fleet_stale_owner(tmpdir) -> tuple:
    """The acceptance regression at matrix level: partition → migrate
    (the source fence is eaten) → heal (the stale copy revives) → old-
    owner write attempt with the router's stamp → the epoch fence MUST
    reject it, and the router-mediated retry commits exactly once."""
    from coda_tpu.serve.state import StaleOwner

    fleet = _make_fleet(tmpdir, fault_spec="net_drop:task=fence,times=8")
    r = fleet.router
    out: list = []
    stats: dict = {}
    try:
        o = r.open_session(seed=0)
        sid = o["session"]
        o = r.label(sid, int(o["idx"]) % C, request_id=uuid.uuid4().hex)
        src = r._locate(sid)
        dst = [x for x in fleet.replica_ids if x != src][0]
        info = r.migrate_session(sid, src, dst)
        if info.get("migrated") != sid:
            out.append(f"stale_owner: migration did not commit: {info}")
            return out, stats
        if not info.get("fence_pending"):
            out.append("stale_owner: the injected partition should have "
                       "eaten the source fence, but it landed")
        # the partition heals AND the source restarts (losing its
        # in-memory hold): the stale copy is live again
        src_app = fleet.apps[src]
        with src_app.store.lock:
            src_app._holds.clear()
        epoch = r._epochs.get(sid)
        try:
            fleet.router.replicas[src].label(
                sid, 0, request_id=uuid.uuid4().hex, epoch=epoch)
            out.append("stale_owner: SPLIT BRAIN — the stale copy "
                       "COMMITTED a fenced label (the epoch fence is "
                       "dead)")
        except StaleOwner:
            pass  # the fence held
        except Exception as e:
            out.append(f"stale_owner: expected StaleOwner, got {e!r}")
        # the same logical write through the router: re-located to the
        # new owner and committed exactly once
        o = r.label(sid, int(o["idx"]) % C, request_id=uuid.uuid4().hex)
        if o["n_labeled"] != 2:
            out.append(f"stale_owner: {o['n_labeled']} labels after 2 "
                       "issued (lost or double-applied)")
        fenced = src_app.metrics.snapshot()["fencing_rejections"]
        if fenced < 1:
            out.append("stale_owner: the replica never counted a "
                       "fencing rejection")
        stats = {"fencing_rejections": fenced,
                 "fence_failures": r.counters["fence_failures"],
                 "migrations": r.counters["migrations"],
                 "migration_verified": sum(r.migrations_via.values())}
        return out, stats
    finally:
        fleet.drain(timeout=10)


def scenario_fleet_kill_mid_migration(tmpdir) -> tuple:
    """SIGKILL of the destination replica between a migration's export
    and its import (the seeded ``kill_replica``/``migrate_mid`` fault):
    the move must degrade to didn't-move — the source's held copy
    resumes, nothing is dropped — and the revived replica rejoins."""
    from coda_tpu.serve.faults import FaultInjector

    fleet = _make_fleet(tmpdir, poll_s=0.05)
    r = fleet.router
    out: list = []
    stats: dict = {}
    try:
        o = r.open_session(seed=0)
        sid = o["session"]
        o = r.label(sid, int(o["idx"]) % C, request_id=uuid.uuid4().hex)
        src = r._locate(sid)
        dst = [x for x in fleet.replica_ids if x != src][0]
        r.faults = FaultInjector(f"kill_replica:edge={dst}")
        info = r.migrate_session(sid, src, dst)
        if fleet.kills.get(dst, 0) != 1:
            out.append("kill_mid_migration: the fault never killed the "
                       "destination")
        if "failed" not in info:
            out.append(f"kill_mid_migration: migration against a dead "
                       f"destination should fail didn't-move: {info}")
        if r.counters["sessions_dropped"]:
            out.append("kill_mid_migration: a session was counted "
                       "dropped")
        # the source serves on, exactly-once
        o = r.label(sid, int(o["idx"]) % C, request_id=uuid.uuid4().hex)
        if o["n_labeled"] != 2:
            out.append(f"kill_mid_migration: {o['n_labeled']} labels "
                       "after 2 issued")
        # the dead replica revives (crash restore from its record dir)
        # and health re-admits it after the hysteresis window
        r.faults = None
        fleet.revive_replica(dst)
        for _ in range(3):
            r.check_health()
        if dst not in r.routable():
            out.append("kill_mid_migration: revived replica never "
                       "rejoined routing")
        o = r.label(sid, int(o["idx"]) % C, request_id=uuid.uuid4().hex)
        if o["n_labeled"] != 3:
            out.append(f"kill_mid_migration: {o['n_labeled']} labels "
                       "after 3 issued (post-revive)")
        stats = {"kills": dict(fleet.kills),
                 "migration_failures": r.counters["migration_failures"],
                 "sessions_dropped": r.counters["sessions_dropped"]}
        return out, stats
    finally:
        fleet.drain(timeout=10)


def scenario_fleet_router_restart_journal(tmpdir) -> tuple:
    """The router is SIGKILLed mid-migration at each journal phase; a
    fresh router over the same replicas + journal resolves every
    in-doubt move to didn't-move (intent/exported) or moved-exactly-once
    (imported), with the session reachable and exact either way."""
    import shutil

    from coda_tpu.serve import InprocReplica, SessionRouter
    from coda_tpu.serve.journal import payload_digest

    out: list = []
    stats: dict = {"phases": {}}
    for phase in ("intent", "exported", "imported"):
        d = os.path.join(tmpdir, f"journal_{phase}")
        os.makedirs(d, exist_ok=True)
        fleet = _make_fleet(d)
        r = fleet.router
        r2 = None
        try:
            o = r.open_session(seed=0)
            sid = o["session"]
            o = r.label(sid, int(o["idx"]) % C,
                        request_id=uuid.uuid4().hex)
            src = r._locate(sid)
            dst = [x for x in fleet.replica_ids if x != src][0]
            # run the migration's steps BY HAND up to `phase`, then
            # "die": this reproduces byte-for-byte the journal + replica
            # state a SIGKILL at that point leaves behind
            epoch_next = r._epochs.get(sid, 0) + 1
            mid = r.journal.begin(sid, src, dst, epoch_next)
            if phase in ("exported", "imported"):
                payload = dict(
                    r.replicas[src].export_for_migration(sid),
                    epoch=epoch_next)   # the source is now HELD
                r.journal.record(mid, "exported",
                                 digest=payload_digest(payload),
                                 n_labeled=payload.get("n_labeled"))
            if phase == "imported":
                r.replicas[dst].import_payload(payload)
                r.journal.record(mid, "imported")
            r.stop()   # the old router is dead; its gate died with it
            r2 = SessionRouter(
                {rid: InprocReplica(rid, app)
                 for rid, app in fleet.apps.items()},
                journal_path=os.path.join(d, "router_migrations.log"))
            rep = r2.recover_from_journal()
            expect = "finalized" if phase == "imported" else "restored"
            if sid not in rep.get(expect, []):
                out.append(f"journal[{phase}]: expected {expect}, got "
                           f"{rep}")
            if phase == "imported":
                if not fleet.apps[dst].store.alive(sid):
                    out.append(f"journal[{phase}]: finalized session "
                               "not live on the destination")
                if fleet.apps[src].store.alive(sid) or \
                        fleet.apps[src].tiers.parked(sid):
                    out.append(f"journal[{phase}]: the source copy "
                               "survived finalization (split brain)")
                if r2._epochs.get(sid) != epoch_next:
                    out.append(f"journal[{phase}]: recovered epoch "
                               f"{r2._epochs.get(sid)} != {epoch_next}")
            else:
                if fleet.apps[src].held(sid):
                    out.append(f"journal[{phase}]: the source hold was "
                               "never lifted — the session is wedged")
            # the client's next label commits exactly once, wherever
            # the recovery left the session
            o2 = r2.label(sid, int(o["idx"]) % C,
                          request_id=uuid.uuid4().hex)
            if o2["n_labeled"] != 2:
                out.append(f"journal[{phase}]: {o2['n_labeled']} labels "
                           "after 2 issued")
            stats["phases"][phase] = {
                "resolved": rep["resolved"],
                "journal_replays": r2.counters["journal_replays"]}
        finally:
            if r2 is not None:
                r2.drain()
            fleet.drain(timeout=10)
            shutil.rmtree(d, ignore_errors=True)
    return out, stats


def scenario_fleet_healthz_flap(tmpdir) -> tuple:
    """A flapping /healthz must NOT churn the routing set: with
    hysteresis K=2 an alternating probe never evicts, so no needless
    drain-and-migrate runs and traffic is untouched."""
    fleet = _make_fleet(tmpdir,
                        fault_spec="flap_healthz:edge=r0,every=2,times=64",
                        hysteresis=2, poll_s=0.02)
    r = fleet.router
    try:
        sids, errors = _drive_router(r, n_sessions=4)
        out = list(errors)
        time.sleep(0.3)   # a few dozen flapping poll cycles
        fired = sum(f["fired"] for f in r.faults.snapshot()
                    if f["name"] == "flap_healthz")
        if fired < 4:
            out.append(f"healthz_flap: the flap only fired {fired} "
                       "times (unexercised)")
        if r.counters["evictions"]:
            out.append(f"healthz_flap: {r.counters['evictions']} "
                       "eviction(s) from a flapping probe — hysteresis "
                       "is dead and the keyspace churned")
        if r.counters["migrations"]:
            out.append(f"healthz_flap: {r.counters['migrations']} "
                       "needless migration(s) triggered by the flap")
        out += _fleet_reachability(r, sids)
        return out, {"flaps_fired": fired,
                     "evictions": r.counters["evictions"]}
    finally:
        fleet.drain(timeout=10)


def scenario_fleet_transport_chaos(tmpdir) -> tuple:
    """Drop + delay + duplicate on live label traffic: transport
    retries absorb the drops, the request_id dedupe absorbs the
    duplicates, and every session ends with the exact label count."""
    fleet = _make_fleet(
        tmpdir,
        fault_spec="net_drop:every=9,times=8;"
                   "net_delay:every=5,ms=4,times=24;"
                   "net_dup:every=7,times=8,task=label")
    r = fleet.router
    try:
        sids, errors = _drive_router(r, n_sessions=4)
        out = list(errors)
        fired = {f["name"]: f["fired"] for f in r.faults.snapshot()}
        for name in ("net_drop", "net_delay", "net_dup"):
            if not fired.get(name):
                out.append(f"transport_chaos: {name} never fired "
                           "(unexercised)")
        retries = sum(
            (r.stats()["router"].get("transport_retries") or {}).values())
        out += _fleet_reachability(r, sids)
        return out, {"faults_fired": fired,
                     "transport_retries": retries,
                     "dropped_sessions": r.counters["sessions_dropped"]}
    finally:
        fleet.drain(timeout=10)


def scenario_fleet_partition_heal(tmpdir) -> tuple:
    """One replica partitions for an arrival window and heals, in three
    deterministic phases: (1) clean traffic, with at least one session
    GUARANTEED on the soon-partitioned replica (migrated there if HRW
    put none); (2) labels under the partition — the breaker trips,
    fail-fast bounds the amplification, client retries wait the outage
    out; (3) the window burns through (heals) and traffic completes
    clean. 0 errors and exact counts end to end — the partition+heal
    proof ``capture_evidence.py`` ships."""
    from coda_tpu.serve.faults import FaultInjector
    from scripts.serve_loadgen import with_retries

    fleet = _make_fleet(tmpdir, poll_s=0.03)
    r = fleet.router
    out: list = []
    try:
        sessions: dict = {}
        for i in range(4):
            o = with_retries(lambda: r.open_session(seed=i), 8, 0.03)
            sessions[o["session"]] = o

        def label_all(expected):
            for sid in list(sessions):
                o = sessions[sid]
                lab = int(o["idx"]) % C
                rid = uuid.uuid4().hex
                o = with_retries(
                    lambda: r.label(sid, lab, request_id=rid), 16, 0.05)
                sessions[sid] = o
                if o["n_labeled"] != expected:
                    out.append(
                        f"partition_heal: session {sid} committed "
                        f"{o['n_labeled']} labels, client issued "
                        f"{expected} (lost or double)")

        label_all(1)   # phase 1: clean
        if not any(r._locate(sid) == "r1" for sid in sessions):
            # HRW put nothing on r1: move one there (clean migration)
            # so the partition provably has traffic to eat
            sid = next(iter(sessions))
            info = r.migrate_session(sid, r._locate(sid), "r1")
            if "migrated" not in info:
                out.append(f"partition_heal: setup migration failed: "
                           f"{info}")
        # the partition: a 30-arrival outage window on edge r1 (every
        # verb, healthz included), installed on the shared fault domain
        window = 30
        inj = FaultInjector(f"partition:edge=r1,times={window}")
        r.faults = inj
        for h in r.replicas.values():
            h.transport.faults = inj
        label_all(2)   # phase 2: under the partition, retries absorb
        # phase 3: wait for the heal (the breaker's half-open probes and
        # the health poller burn the remaining window arrivals)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            fired = sum(f["fired"] for f in inj.snapshot()
                        if f["name"] == "partition")
            if fired >= window:
                break
            time.sleep(0.05)
        label_all(3)   # post-heal: clean again
        fired = sum(f["fired"] for f in inj.snapshot()
                    if f["name"] == "partition")
        if fired < 1:
            out.append("partition_heal: the partition never fired")
        t1 = r.replicas["r1"].transport.snapshot()
        detected = (t1["breaker_trips"] > 0
                    or r.counters["evictions"] > 0
                    or t1["retries_total"] > 0)
        if not detected:
            out.append("partition_heal: the partition was invisible to "
                       "breaker, eviction, AND retries")
        out += _fleet_reachability(r, list(sessions))
        return out, {"partition_fired": fired,
                     "partition_window": window,
                     "breaker_trips": t1["breaker_trips"],
                     "evictions": r.counters["evictions"],
                     "transport_retries": t1["retries_total"]}
    finally:
        fleet.drain(timeout=10)


FLEET_SCENARIOS = {
    "fleet_stale_owner_fence": scenario_fleet_stale_owner,
    "fleet_kill_replica_mid_migration": scenario_fleet_kill_mid_migration,
    "fleet_router_restart_journal": scenario_fleet_router_restart_journal,
    "fleet_healthz_flap": scenario_fleet_healthz_flap,
    "fleet_transport_chaos": scenario_fleet_transport_chaos,
    "fleet_partition_heal": scenario_fleet_partition_heal,
}


def run_fleet_matrix(only=None) -> dict:
    """{scenario: {"violations": [...], ...stats}} for the fleet matrix
    (each scenario in its own temp dir; ``only`` filters by name)."""
    import tempfile as _tf

    results: dict = {}
    for name, fn in FLEET_SCENARIOS.items():
        if only and name not in only:
            continue
        with _tf.TemporaryDirectory() as d:
            violations, stats = fn(d)
        results[name] = dict({"violations": violations}, **stats)
    return results


def build_fleet_artifact(results: dict) -> dict:
    """The committed FAULT_MATRIX_FLEET_* artifact: scenario verdicts +
    the summary fields scripts/check_perf.py gates, fingerprint-stamped."""
    from coda_tpu.telemetry.recorder import environment_fingerprint

    migrations = sum(int(s.get("migrations") or 0)
                     for s in results.values())
    verified = sum(int(s.get("migration_verified") or 0)
                   for s in results.values())
    return {
        "bench": "fault_matrix_fleet",
        "fingerprint": environment_fingerprint(knobs={
            "capture": "check_fault_matrix", "fleet": True,
            "shape": [H, N, C], "rounds": FLEET_ROUNDS}),
        "scenarios": results,
        "summary": {
            "scenarios": len(results),
            "clean": all(not s["violations"] for s in results.values()),
            "violations": sum(len(s["violations"])
                              for s in results.values()),
            "migrations": migrations,
            "migration_verified": verified,
            "fencing_rejections": sum(
                int(s.get("fencing_rejections") or 0)
                for s in results.values()),
            "dropped_sessions": sum(
                int(s.get("dropped_sessions") or 0)
                for s in results.values()),
            "double_applied_labels": sum(
                1 for s in results.values() for v in s["violations"]
                if "labels after" in v or "double" in v),
        },
    }


# ---------------------------------------------------------------------------

SCENARIOS = {
    "step_raise": scenario_step_raise,
    "step_nan": scenario_step_nan,
    "record_eio": scenario_record_eio,
    "slow_step": scenario_slow_step,
    "demote_during_label": scenario_demote_during_label,
    "crash_before_tick": lambda: scenario_crash("crash_before_tick"),
    "crash_after_tick": lambda: scenario_crash("crash_after_tick"),
}


def run_matrix(skip_crash: bool = False) -> dict[str, list[str]]:
    """{scenario: violations} (empty lists = clean)."""
    results = {}
    for name, fn in SCENARIOS.items():
        if skip_crash and name.startswith("crash_"):
            continue
        results[name] = fn()
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--skip-crash", action="store_true",
                   help="omit the two subprocess crash scenarios")
    p.add_argument("--fleet", action="store_true",
                   help="run the FLEET chaos matrix instead (2 replicas "
                        "behind the router: fencing, journal recovery, "
                        "breaker, partition+heal); --out then writes the "
                        "committed FAULT_MATRIX_FLEET_* artifact")
    p.add_argument("--only", default=None,
                   help="comma-separated scenario filter (fleet mode)")
    p.add_argument("--out", default=None,
                   help="write the results JSON here (single-replica "
                        "mode: {scenario: violations}; --fleet: the "
                        "gated artifact)")
    args = p.parse_args(argv)

    if args.fleet:
        only = set(args.only.split(",")) if args.only else None
        results = run_fleet_matrix(only=only)
        artifact = build_fleet_artifact(results)
        bad = 0
        for name, sc in results.items():
            for v in sc["violations"]:
                print(f"FAIL {v}")
                bad += 1
            if not sc["violations"]:
                print(f"ok   {name}")
        if args.out:
            with open(args.out, "w") as f:
                json.dump(artifact, f, indent=2)
            print(f"wrote {args.out}")
        if bad:
            print(f"fleet fault matrix FAILED: {bad} violation(s)")
            return 1
        print(f"fleet fault matrix clean: {len(results)} scenario(s) — "
              "every partition, kill, and in-doubt journal window ended "
              "with sessions reachable, labels exact, zero double-applies")
        return 0

    results = run_matrix(skip_crash=args.skip_crash)
    bad = 0
    for name, violations in results.items():
        for v in violations:
            print(f"FAIL {v}")
            bad += 1
        if not violations:
            print(f"ok   {name}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
    if bad:
        print(f"fault matrix FAILED: {bad} violation(s)")
        return 1
    print(f"fault matrix clean: {len(results)} scenario(s), every "
          "injection point recovered or attributably detected")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
