"""One-run evidence harness: the whole perf claim set in one invocation.

The ROADMAP's "Evidence refresh on real silicon" item asks for exactly
this: batch ``bench_suite`` + ``serve_loadgen`` + ``bench.py
--eig-entropy approx`` + the multichip replay dryrun into ONE capture
script, so the next silicon window produces the full evidence set in one
run instead of four hand-driven ones that each forget a flag. The output
is a single versioned manifest::

    EVIDENCE_<backend>_rNN.json
    {
      "schema_version": 1, "round": "rNN", "backend": "...",
      "quick": true|false,
      "fingerprint": {...environment_fingerprint...},   # the shared stamp
      "artifacts": {
        "bench":            {"status": "ok", "wall_s": ..., "report": {...},
                             "fingerprint_match": true},
        "bench_suite":      {...},
        "serve_loadgen":    {...},
        "multichip_replay": {...},
      },
      "skipped": [...],    # anything --quick left out, recorded not silent
    }

Every sub-report is stamped by its own script with the recorder's
``environment_fingerprint`` (``telemetry/recorder.py``);
``fingerprint_match`` records whether its environment axes (backend,
device kind, jax versions, x64, threefry) agree with the manifest's — a
manifest whose components ran on different silicon fails the gate.

The manifest is itself a gated artifact: ``scripts/check_perf.py`` has an
``EVIDENCE_*`` contract (all components ok, fingerprints matching, serve
errors 0, positive headline values), and this script self-gates the
manifest before exiting — a capture that would not pass the committed
gate exits non-zero.

    python scripts/capture_evidence.py --quick        # CPU-container proof
    python scripts/capture_evidence.py                # full silicon capture

Components run as subprocesses (each script pins its own platform and
jax config exactly as it does standalone, so the captured numbers are
the numbers the standalone invocation would produce).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SCHEMA_VERSION = 1

# the environment axes a component's fingerprint must share with the
# manifest's for the capture to count as one-environment evidence (knobs
# legitimately differ per component — they describe the workload)
_ENV_AXES = ("backend", "jax_version", "jaxlib_version", "device_kind",
             "threefry_partitionable", "x64")


def fingerprint_match(manifest_fp: dict, sub_fp) -> bool:
    if not isinstance(sub_fp, dict):
        return False
    return all(manifest_fp.get(a) == sub_fp.get(a) for a in _ENV_AXES)


def _parse_last_json_line(text: str):
    """The reporting convention of every bench script here: ONE JSON line
    on stdout (possibly after progress prints) — take the last parseable
    one."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def _run_component(name: str, cmd: list, timeout_s: float,
                   out_file: str = None, env=None) -> dict:
    """Run one capture subprocess; returns the manifest component entry
    (status ok/failed/timeout, wall seconds, the parsed report)."""
    t0 = time.perf_counter()
    print(f"[capture] {name}: {' '.join(cmd)}", flush=True)
    try:
        proc = subprocess.run(cmd, cwd=REPO, timeout=timeout_s,
                              capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired:
        return {"status": f"timeout>{timeout_s:.0f}s", "wall_s": timeout_s,
                "report": None}
    wall = time.perf_counter() - t0
    report = None
    if out_file and os.path.exists(out_file):
        try:
            with open(out_file) as f:
                report = json.load(f)
        except ValueError:
            report = None
    if report is None:
        report = _parse_last_json_line(proc.stdout)
    entry = {"status": "ok" if proc.returncode == 0 and report is not None
             else f"failed:rc={proc.returncode}",
             "wall_s": round(wall, 2), "report": report}
    if proc.returncode != 0 or report is None:
        entry["stderr_tail"] = proc.stderr.strip().splitlines()[-6:]
    return entry


def component_commands(quick: bool, tmpdir: str, platform: str = None
                       ) -> dict:
    """(cmd, out_file, timeout) per component. Quick = CPU-container-sized
    configs (the zero-to-manifest proof); full = the r09-class capture
    set for a real silicon window."""
    py = sys.executable
    plat = (["--platform", platform] if platform else [])
    if quick:
        return {
            "bench": (
                [py, "bench.py", "--small", "--skip-reference",
                 "--reps", "2", "--eig-entropy", "approx"] + plat,
                None, 600),
            "bench_suite": (
                [py, "scripts/bench_suite.py", "--small",
                 "--methods", "iid,coda", "--seeds", "2", "--iters", "5",
                 "--out", os.path.join(tmpdir, "suite.json")] + plat,
                os.path.join(tmpdir, "suite.json"), 900),
            "serve_loadgen": (
                [py, "scripts/serve_loadgen.py", "--synthetic", "8,256,10",
                 "--sessions", "8", "--workers", "8", "--labels", "4",
                 "--out", os.path.join(tmpdir, "serve.json")] + plat,
                os.path.join(tmpdir, "serve.json"), 900),
            # the tiered store at smoke scale: sessions >> capacity, Zipf
            # traffic, wakes exercised (the 100k claim is the committed
            # BENCH_TIERED_* capture; this proves the machinery in-run)
            "serve_tiered": (
                [py, "scripts/serve_loadgen.py", "--synthetic", "4,48,4",
                 "--zipf", "1.3", "--sessions", "96", "--workers", "8",
                 "--labels", "0", "--requests", "192", "--capacity", "16",
                 "--retries", "8", "--tier-free-frac", "0.25",
                 "--idle-warm-s", "2", "--idle-cold-s", "4",
                 "--max-warm", "32",
                 "--tier-spill-dir", os.path.join(tmpdir, "spill"),
                 "--out", os.path.join(tmpdir, "tiered.json")] + plat,
                os.path.join(tmpdir, "tiered.json"), 900),
            "multichip_replay": (
                [py, "scripts/dryrun_multichip.py", "2", "--skip-shard-map",
                 "--out", os.path.join(tmpdir, "multichip.json")],
                os.path.join(tmpdir, "multichip.json"), 900),
            # the large-C rung at its scaled-down-C stand-in (same tier
            # and kernels; the full C=1000 shape is the non-quick config
            # and the committed IMAGENET_SPARSE_* capture)
            "bench_imagenet": (
                [py, "bench.py", "--config", "imagenet_smoke",
                 "--posterior", "sparse:16", "--skip-reference",
                 "--reps", "2"] + plat,
                None, 900),
            # batched acquisition at smoke scale: digits q=4 envelope +
            # the smoke-shape throughput probe (the committed floors live
            # in the full BENCH_BATCHQ_* capture)
            "bench_batchq": (
                [py, "scripts/bench_batchq.py", "--quick",
                 "--out", os.path.join(tmpdir, "batchq.json"),
                 "--records-dir", os.path.join(tmpdir, "batchq_records")]
                + plat,
                os.path.join(tmpdir, "batchq.json"), 900),
            # the contract-gated EIG surrogate at smoke scale: digits
            # regret envelope + the smoke-shape scoring-pass probe (the
            # committed >= 3x floor lives in the full BENCH_SURROGATE_*
            # capture)
            "bench_surrogate": (
                [py, "scripts/bench_surrogate.py", "--quick",
                 "--out", os.path.join(tmpdir, "surrogate.json"),
                 "--records-dir",
                 os.path.join(tmpdir, "surrogate_records")] + plat,
                os.path.join(tmpdir, "surrogate.json"), 900),
            # cross-session surrogate priors at smoke scale: warmup
            # amortization + gate rejection + off parity at a smaller
            # budget (the committed >= 3x reduction floor lives in the
            # full BENCH_PRIOR_* capture)
            "bench_prior": (
                [py, "scripts/bench_prior.py", "--quick",
                 "--out", os.path.join(tmpdir, "prior.json"),
                 "--records-dir",
                 os.path.join(tmpdir, "prior_records")] + plat,
                os.path.join(tmpdir, "prior.json"), 900),
            # the replicated fleet at proof scale: 2 replicas behind the
            # rendezvous router, rolling restart of both mid-load, every
            # migration digest-verified (the committed 3-replica claim is
            # BENCH_FLEET_*)
            "serve_fleet": (
                [py, "scripts/serve_loadgen.py", "--synthetic", "4,48,4",
                 "--fleet", "2", "--sessions", "12", "--workers", "4",
                 "--labels", "40", "--capacity", "8", "--retries", "8",
                 "--rolling-restart-at", "0.3",
                 "--compilation-cache-dir",
                 os.path.join(tmpdir, "fleet_cache"),
                 "--out", os.path.join(tmpdir, "fleet.json")] + plat,
                os.path.join(tmpdir, "fleet.json"), 900),
            # fleet chaos at proof scale: the partition+heal scenario
            # (breaker trips, retries absorb, 0 errors) plus the
            # stale-owner fencing regression — the full 6-scenario
            # matrix is the committed FAULT_MATRIX_FLEET_* artifact
            "serve_fleet_chaos": (
                [py, "scripts/check_fault_matrix.py", "--fleet",
                 "--only", "fleet_partition_heal,fleet_stale_owner_fence",
                 "--out", os.path.join(tmpdir, "fleet_chaos.json")],
                os.path.join(tmpdir, "fleet_chaos.json"), 900),
            # the crowd-oracle robustness matrix at smoke scale: clean
            # bitwise parity, noisy regret envelope, Dawid-Skene
            # recovery, async out-of-order delivery (the committed
            # bounds live in the full ROBUSTNESS_* capture)
            "oracle_noise": (
                [py, "scripts/bench_robustness.py", "--quick",
                 "--out", os.path.join(tmpdir, "robustness.json"),
                 "--records-dir",
                 os.path.join(tmpdir, "robustness_records")],
                os.path.join(tmpdir, "robustness.json"), 900),
            # observability at proof scale: 2-replica chaos tracing,
            # migration-spanning trace, bitwise on-vs-off, SLO
            # fire/clear (the committed 3-replica + rolling-restart
            # claim is OBS_FLEET_*)
            "serve_obs": (
                [py, "scripts/bench_obs.py", "--quick",
                 "--out", os.path.join(tmpdir, "obs.json")],
                os.path.join(tmpdir, "obs.json"), 900),
            # decision quality at proof scale: 2-replica shadow audit,
            # tamper attribution, quality SLO fire/clear, bitwise
            # on-vs-off (the committed 3-replica claim is QUALITY_FLEET_*)
            "serve_quality": (
                [py, "scripts/bench_quality.py", "--quick",
                 "--out", os.path.join(tmpdir, "quality.json")],
                os.path.join(tmpdir, "quality.json"), 900),
        }
    return {
        # the r09 evidence set the ROADMAP asks for, in one run
        "bench": (
            [py, "bench.py", "--skip-reference", "--eig-entropy", "approx"]
            + plat, None, 3600),
        "bench_suite": (
            [py, "scripts/bench_suite.py", "--task-batch", "--warm-reps",
             "3", "--out", os.path.join(tmpdir, "suite.json")] + plat,
            os.path.join(tmpdir, "suite.json"), 7200),
        "serve_loadgen": (
            [py, "scripts/serve_loadgen.py", "--synthetic", "8,512,10",
             "--mux", "--sessions", "256", "--workers", "256",
             "--labels", "8", "--capacity", "256", "--max-batch", "256",
             "--max-wait-ms", "15", "--max-linger-ms", "250",
             "--out", os.path.join(tmpdir, "serve.json")] + plat,
            os.path.join(tmpdir, "serve.json"), 3600),
        # the full ≥1M-open-sessions tiered capture (the BENCH_TIERED_*
        # configuration: spill v3 sharded segments, O(index) reopen)
        "serve_tiered": (
            [py, "scripts/serve_loadgen.py", "--synthetic", "4,48,4",
             "--zipf", "1.5", "--sessions", "1000000", "--workers", "64",
             "--labels", "0", "--requests", "20000", "--capacity", "128",
             "--retries", "8", "--tier-free-frac", "0.5",
             "--idle-warm-s", "5", "--idle-cold-s", "10",
             "--max-warm", "2048", "--think-ms", "1",
             "--tier-spill-dir", os.path.join(tmpdir, "spill"),
             "--out", os.path.join(tmpdir, "tiered.json")] + plat,
            os.path.join(tmpdir, "tiered.json"), 7200),
        "multichip_replay": (
            [py, "scripts/dryrun_multichip.py", "8",
             "--out", os.path.join(tmpdir, "multichip.json")],
            os.path.join(tmpdir, "multichip.json"), 3600),
        # the large-C rung at the real IMAGENET_VIRTUAL_r05 pool shape
        "bench_imagenet": (
            [py, "bench.py", "--config", "imagenet",
             "--posterior", "sparse:32", "--skip-reference"] + plat,
            None, 3600),
        # batched acquisition in full: digits q ∈ {4, 8} regret envelope
        # + the q=8 imagenet-preset labels/s floor, replay-triaged
        "bench_batchq": (
            [py, "scripts/bench_batchq.py",
             "--out", os.path.join(tmpdir, "batchq.json"),
             "--records-dir", os.path.join(tmpdir, "batchq_records")]
            + plat,
            os.path.join(tmpdir, "batchq.json"), 3600),
        # the contract-gated EIG surrogate in full: digits 100-round
        # envelope + the imagenet-preset surrogate:64-vs-exact scoring
        # pass, replay-triaged (the BENCH_SURROGATE_* configuration)
        "bench_surrogate": (
            [py, "scripts/bench_surrogate.py",
             "--out", os.path.join(tmpdir, "surrogate.json"),
             "--records-dir", os.path.join(tmpdir, "surrogate_records")]
            + plat,
            os.path.join(tmpdir, "surrogate.json"), 3600),
        # cross-session surrogate priors in full: the >= 3x warmup
        # amortization floor, seeded-vs-cold digits envelope, gate
        # rejection, off parity (the BENCH_PRIOR_* configuration)
        "bench_prior": (
            [py, "scripts/bench_prior.py",
             "--out", os.path.join(tmpdir, "prior.json"),
             "--records-dir", os.path.join(tmpdir, "prior_records")]
            + plat,
            os.path.join(tmpdir, "prior.json"), 3600),
        # the full 3-replica fleet demo (the BENCH_FLEET_* configuration):
        # rolling restart of every replica in sequence under live load,
        # zero drops / zero double-applies, scaling vs the 1-replica
        # baseline (--fleet-baseline)
        "serve_fleet": (
            [py, "scripts/serve_loadgen.py", "--synthetic", "8,256,10",
             "--fleet", "3", "--fleet-baseline", "--sessions", "24",
             "--workers", "8", "--labels", "60", "--capacity", "18",
             "--retries", "10", "--rolling-restart-at", "0.3",
             "--compilation-cache-dir",
             os.path.join(tmpdir, "fleet_cache"),
             "--out", os.path.join(tmpdir, "fleet.json")] + plat,
            os.path.join(tmpdir, "fleet.json"), 3600),
        # the full fleet chaos matrix (the FAULT_MATRIX_FLEET_*
        # configuration): fencing, journal recovery at every phase,
        # kill-mid-migration, flap hysteresis, transport chaos,
        # partition+heal — all scenarios must end clean
        "serve_fleet_chaos": (
            [py, "scripts/check_fault_matrix.py", "--fleet",
             "--out", os.path.join(tmpdir, "fleet_chaos.json")],
            os.path.join(tmpdir, "fleet_chaos.json"), 3600),
        # the full crowd-oracle robustness matrix (the ROBUSTNESS_*
        # configuration): clean parity bitwise, the committed noisy
        # regret envelope, Dawid-Skene recovery of the planted pool,
        # async out-of-order delivery digest-equivalent
        "oracle_noise": (
            [py, "scripts/bench_robustness.py",
             "--out", os.path.join(tmpdir, "robustness.json"),
             "--records-dir",
             os.path.join(tmpdir, "robustness_records")],
            os.path.join(tmpdir, "robustness.json"), 3600),
        # observability in full (the OBS_FLEET_* configuration): the
        # 3-replica chaos + rolling-restart tracing passes, the
        # migration-spanning trace, bitwise non-perturbation + the
        # <= 5% overhead bound, SLO fire/clear persisted to the store
        "serve_obs": (
            [py, "scripts/bench_obs.py",
             "--out", os.path.join(tmpdir, "obs.json")],
            os.path.join(tmpdir, "obs.json"), 3600),
        # decision quality in full (the QUALITY_FLEET_* configuration):
        # every close shadow-audited on a 3-replica chaos fleet with 0
        # divergences, single-ulp tamper attribution, ground-truth
        # P(best) calibration, quality SLO fire/clear persisted to the
        # store, bitwise non-perturbation + the <= 5% overhead bound
        "serve_quality": (
            [py, "scripts/bench_quality.py",
             "--out", os.path.join(tmpdir, "quality.json")],
            os.path.join(tmpdir, "quality.json"), 3600),
    }


def build_manifest(round_tag: str, fingerprint: dict, components: dict,
                   quick: bool, skipped=()) -> dict:
    """Assemble the manifest from component entries, stamping each with
    its fingerprint-match verdict against the shared environment."""
    artifacts = {}
    for name, entry in components.items():
        entry = dict(entry)
        rep = entry.get("report")
        sub_fp = rep.get("fingerprint") if isinstance(rep, dict) else None
        if sub_fp is not None:
            entry["fingerprint_match"] = fingerprint_match(fingerprint,
                                                           sub_fp)
        else:
            # components that carry no own stamp (the multichip dryrun
            # pre-dates fingerprinting) inherit the manifest's — recorded
            # as such, not pretended
            entry["fingerprint_match"] = None
            entry["fingerprint_inherited"] = True
        artifacts[name] = entry
    return {
        "schema_version": SCHEMA_VERSION,
        "round": round_tag,
        "backend": fingerprint.get("backend"),
        "quick": bool(quick),
        "fingerprint": fingerprint,
        "artifacts": artifacts,
        "skipped": list(skipped),
    }


def next_round(repo: str, backend: str) -> str:
    """First free rNN for this backend's EVIDENCE series (floor r11 — the
    round the observatory landed)."""
    rounds = [11]
    for p in glob.glob(os.path.join(repo, f"EVIDENCE_{backend}_*.json")):
        m = re.search(r"_r(\d+)\.json$", os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)) + 1)
    return f"r{max(rounds):02d}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="CPU-container-sized configs (the one-invocation "
                        "zero-to-manifest proof); default is the full "
                        "silicon capture set")
    p.add_argument("--round", default=None, metavar="rNN",
                   help="evidence round tag (default: next free number, "
                        "floor r11)")
    p.add_argument("--out", default=None,
                   help="manifest path (default "
                        "EVIDENCE_<backend>_<round>.json at the repo root)")
    p.add_argument("--platform", default=None,
                   help="forwarded to every component that takes "
                        "--platform (cpu/tpu)")
    p.add_argument("--skip", default="", metavar="a,b",
                   help="comma-separated components to skip (recorded in "
                        "the manifest's 'skipped' — a skipped component "
                        "fails the gate, so this is for debugging, not "
                        "for shipping)")
    args = p.parse_args(argv)

    # the shared environment stamp, taken by THIS process (same container/
    # host as the components; knobs describe the capture itself so quick
    # and full rounds never cross-compare in the gate)
    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    from coda_tpu.telemetry.recorder import environment_fingerprint

    fingerprint = environment_fingerprint(knobs={
        "capture": "capture_evidence", "quick": bool(args.quick)})
    backend = fingerprint["backend"]
    round_tag = args.round or next_round(REPO, backend)
    out = args.out or os.path.join(REPO,
                                   f"EVIDENCE_{backend}_{round_tag}.json")

    skip = {s for s in args.skip.split(",") if s}
    components: dict = {}
    skipped: list = sorted(skip)
    if args.quick:
        # quick runs only the scheduler config of the multichip dryrun;
        # the shard_map parity configs are full-capture work — recorded
        # as skipped so the cap is visible, not silent
        skipped.append("multichip_replay.shard_map_configs")
    with tempfile.TemporaryDirectory() as tmpdir:
        for name, (cmd, out_file, timeout_s) in component_commands(
                args.quick, tmpdir, args.platform).items():
            if name in skip:
                continue
            components[name] = _run_component(name, cmd, timeout_s,
                                              out_file)
            print(f"[capture] {name}: {components[name]['status']} "
                  f"({components[name]['wall_s']}s)", flush=True)

    manifest = build_manifest(round_tag, fingerprint, components,
                              args.quick, skipped=skipped)
    with open(out, "w") as f:
        json.dump(manifest, f, indent=2)
    print(f"[capture] wrote {out}")

    # self-gate: the manifest must pass the committed-artifact contract it
    # will be held to in tier-1 — a capture that wouldn't is not evidence
    from check_perf import check_artifact, match_contract

    # a custom --out name may not match the EVIDENCE_* glob; the manifest
    # is still held to the EVIDENCE contract, never skipped (and never an
    # AttributeError after an hours-long full capture)
    contract = match_contract(out) or match_contract("EVIDENCE_x.json")
    violations = check_artifact(out, manifest, contract)
    for v in violations:
        print(f"[capture] GATE: {v}")
    if violations:
        print(f"[capture] manifest FAILS its own contract "
              f"({len(violations)} violation(s)) — not evidence")
        return 1
    print("[capture] manifest passes scripts/check_perf.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
