"""Per-component marginal-cost profile of one CODA labeling round.

Times each stage of the incremental-EIG step (cache scoring — jnp and
pallas backends —, cache row refresh, pi-hat column refresh, masked
argmax) plus the full scan step, using the loop-in-jit discipline that
survives this environment's device tunnel: every stage runs ``n`` times
inside one ``lax.fori_loop`` with a data dependence threaded through a
scalar carry, the program's single scalar output is materialized on the
host (forcing the whole chain to execute), and the reported cost is the
marginal (hi - lo) / (n_hi - n_lo) — fixed dispatch/transfer overhead
cancels. A bare ``block_until_ready`` is NOT trusted: through the
experimental axon tunnel it demonstrably returns before the device queue
drains (see BENCH notes in VERDICT round 2).

    python scripts/profile_step.py                  # headline M=1k,N=50k
    python scripts/profile_step.py --shape 32,2000,10 --platform cpu
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

sys.path.insert(0, ".")


def marginal_ms(body, carry0, n_hi: int, n_lo: int, reps: int,
                ops: tuple = (), setup=None) -> dict:
    """Median marginal per-iteration cost of ``body`` in milliseconds.

    ``body(carry, i, *ops) -> carry`` must thread a data dependence through
    the carry (multiply-by-tiny, add — anything XLA cannot fold away).

    ``ops`` are the loop-invariant tensors the body reads. They MUST be
    passed here — not closed over — so they lower as jit *arguments*:
    closure-captured arrays become HLO constants, and through the axon
    tunnel the remote-compile request then ships the full tensor bytes
    (2 GB at headline), which demonstrably breaks the tunnel transport
    (round-4 log: `remote_compile ... Broken pipe`).

    ``setup(*ops) -> body2`` optionally builds the per-iteration body
    ONCE inside the jitted program but OUTSIDE the loop (the engine's
    selector_factory pattern): one-time construction work (prior build,
    cache init) is traced outside the While body so it cannot be charged
    to the marginal even if XLA declines to hoist it.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    def run(n: int) -> list:
        @jax.jit
        def f(c0, *ops):
            b = setup(*ops) if setup is not None else (
                lambda c, i: body(c, i, *ops))
            out = lax.fori_loop(0, n, lambda i, c: b(c, i), c0)
            # reduce the final carry to ONE scalar on device: materializing
            # a 2 GB cache carry to host costs ~75 s (with multi-second
            # jitter) through the axon tunnel, swamping any marginal. The
            # full-tree sum forces every carried tensor to be computed, and
            # the reduction runs once OUTSIDE the loop, so its cost cancels
            # in (hi - lo).
            leaves = [x.astype(jnp.float32).sum() if hasattr(x, "astype")
                      else jnp.float32(x) for x in jax.tree.leaves(out)]
            return sum(leaves, jnp.float32(0))

        out = f(carry0, *ops)
        np.asarray(out)  # warm-up, forced to completion
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(f(carry0, *ops))
            ts.append(time.perf_counter() - t0)
        return ts

    from bench import _mad  # the unit-tested MAD helper (repo root on path)

    ts_hi, ts_lo = run(n_hi), run(n_lo)
    hi, lo = statistics.median(ts_hi), statistics.median(ts_lo)
    noise = max(_mad(ts_hi), _mad(ts_lo))
    return {
        "ms_per_iter": (hi - lo) / (n_hi - n_lo) * 1e3,
        "wall_hi_s": round(hi, 4),
        "wall_lo_s": round(lo, 4),
        "n_hi": n_hi,
        "n_lo": n_lo,
        # the marginal is real only when the growth clears the rep noise;
        # a single rep has no noise estimate, so it can never resolve
        "resolved": bool(reps >= 2 and hi - lo > 4.0 * noise),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--shape", default="1000,50000,10",
                    help="H,N,C of the synthetic task")
    ap.add_argument("--eig-chunk", type=int, default=2048)
    ap.add_argument("--num-points", type=int, default=256)
    ap.add_argument("--n-hi", type=int, default=10)
    ap.add_argument("--n-lo", type=int, default=2)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--skip", default="",
                    help="comma list of stages to skip (e.g. pallas)")
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.ops.confusion import (
        create_confusion_matrices,
        ensemble_preds,
        initialize_dirichlets,
    )
    from coda_tpu.ops.masked import masked_argmax_tiebreak
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import (
        _normalize_pi,
        build_eig_cache,
        eig_scores_from_cache,
        pi_unnorm,
        update_eig_cache,
        update_pi_hat_column,
    )

    H, N, C = (int(x) for x in args.shape.split(","))
    G, CH = args.num_points, args.eig_chunk
    skip = set(filter(None, args.skip.split(",")))
    print(f"devices: {jax.devices()}", file=sys.stderr)

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    preds = jax.device_put(task.preds)
    hard = preds.argmax(-1).T.astype(jnp.int32)
    ens = ensemble_preds(preds).argmax(-1)
    soft = create_confusion_matrices(ens, preds, mode="soft")
    # the same prior construction make_coda performs, derived from the
    # default hyperparams so the per-stage operands can never desync from
    # the "full" stage's real selector
    hp0 = CODAHyperparams(eig_chunk=CH, num_points=G)
    dir0 = hp0.multiplier * initialize_dirichlets(
        soft, 1.0 - hp0.alpha, hp0.disable_diag_prior)
    unnorm = pi_unnorm(dir0, preds)
    pi_xi, pi = _normalize_pi(unnorm)
    rows, hyp = jax.jit(
        lambda d, h: build_eig_cache(d, h, num_points=G, chunk=CH)
    )(dir0, hard)
    np.asarray(rows)

    eps = jnp.float32(1e-20)  # runtime value: XLA cannot fold the dependence
    results = {}

    def stage(name, body, carry0, ops=(), setup=None):
        if name.split(":")[0] in skip:
            return
        r = marginal_ms(body, carry0, args.n_hi, args.n_lo, args.reps,
                        ops=ops, setup=setup)
        results[name] = {"ms_per_iter": round(r["ms_per_iter"], 3),
                         "resolved": r["resolved"]}
        flag = "" if r["resolved"] else "  [below noise floor]"
        print(f"{name:34s} {r['ms_per_iter']:9.3f} ms/iter  "
              f"(hi={r['wall_hi_s']}s lo={r['wall_lo_s']}s){flag}",
              file=sys.stderr)

    def body_score(c, i, rows, hyp, pi, pi_xi):
        s = eig_scores_from_cache(rows, hyp, pi + c * eps, pi_xi, chunk=CH)
        return c + s[0] * eps

    stage(f"score:jnp chunk={CH}", body_score, jnp.float32(0),
          ops=(rows, hyp, pi, pi_xi))

    def body_pallas(c, i, rows, hyp, pi, pi_xi):
        from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas

        s = eig_scores_cache_pallas(rows, hyp, pi + c * eps, pi_xi, block=CH)
        return c + s[0] * eps

    stage("pallas:score", body_pallas, jnp.float32(0),
          ops=(rows, hyp, pi, pi_xi))

    def body_upd(carry, i, dir0, hard):
        r, h = carry
        return update_eig_cache(dir0, i % C, hard, r, h, num_points=G)

    stage("update:eig-cache row refresh", body_upd, (rows, hyp),
          ops=(dir0, hard))

    # pure DUS cost of the cache-carry update, two layouts: if XLA cannot
    # alias the dynamic-update-slice in the loop carry it degrades to a
    # full cache copy per round (~5 ms at headline on a v5e). The carried
    # layout is (C, N, H) — leading-axis DUS, the classic in-place-safe
    # pattern; the (N, C, H) mid-axis variant is kept as the comparison
    # point (it also pays the 16-sublane pad at small C)
    def body_dus_lead(h, i):
        row = h[(i + 1) % C] * jnp.float32(0.999)
        return h.at[i % C].set(row)

    stage("carry:DUS leading-axis (C,N,H)", body_dus_lead, hyp)

    hypT = jnp.transpose(hyp, (1, 0, 2))             # (N, C, H)

    def body_dus_mid(h, i):
        row = h[:, (i + 1) % C, :] * jnp.float32(0.999)
        return h.at[:, i % C, :].set(row)

    stage("carry:DUS mid-axis (N,C,H)", body_dus_mid, hypT)

    # composed row-refresh + scoring, per backend, carrying the cache like
    # the real scan does: if a backend's score call cannot alias the
    # DUS-updated carry buffer (e.g. an opaque custom call forcing a
    # layout/copy), the composition costs MORE than the sum of its
    # isolated stages — exactly the regression signature to look for
    def _compose(score_fn, order: str):
        """order='update_first' mirrors an update->score chain;
        'score_first' mirrors the real scan (select reads the carried
        cache, update DUSes it afterwards)."""
        def body(carry, i, dir0, hard, pi, pi_xi):
            rows_c, hyp_c, c = carry
            if order == "update_first":
                rows2, hyp2 = update_eig_cache(dir0, i % C, hard,
                                               rows_c, hyp_c, num_points=G)
                s = score_fn(rows2, hyp2, pi + c * eps, pi_xi)
            else:
                s = score_fn(rows_c, hyp_c, pi + c * eps, pi_xi)
                rows2, hyp2 = update_eig_cache(dir0, i % C, hard,
                                               rows_c, hyp_c, num_points=G)
            return rows2, hyp2, c + s[0] * eps

        return body

    def _score_jnp(r, h, p, px):
        return eig_scores_from_cache(r, h, p, px, chunk=CH)

    def _score_pallas(r, h, p, px):
        from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas

        return eig_scores_cache_pallas(r, h, p, px, block=CH)

    for order in ("update_first", "score_first"):
        stage(f"compose:{order} jnp", _compose(_score_jnp, order),
              (rows, hyp, jnp.float32(0)), ops=(dir0, hard, pi, pi_xi))
        stage(f"compose:{order} pallas", _compose(_score_pallas, order),
              (rows, hyp, jnp.float32(0)), ops=(dir0, hard, pi, pi_xi))

    # the PRODUCTION pallas path: refresh einsums feed the fused kernel,
    # which scores while writing ONLY the refreshed row through the
    # donated cache (row-only aliased write) — compare against the
    # compose: stages to see what the fusion + row-write save
    def body_fused(carry, i, dir0, hard, pi, pi_xi):
        from coda_tpu.ops.pallas_eig import eig_scores_refresh_pallas
        from coda_tpu.selectors.coda import update_eig_cache_parts

        rows_c, hyp_c, c = carry
        row_t, hyp_t = update_eig_cache_parts(dir0, i % C, hard,
                                              num_points=G)
        rows2 = rows_c.at[i % C].set(row_t)
        s, hyp2 = eig_scores_refresh_pallas(
            rows2, hyp_c, hyp_t, i % C, pi + c * eps, pi_xi, block=CH)
        return rows2, hyp2, c + s[0] * eps

    stage("pallas:fused refresh+score", body_fused,
          (rows, hyp, jnp.float32(0)), ops=(dir0, hard, pi, pi_xi))

    # fused-COMPUTE refresh (round 5): the replacement row computed
    # IN-KERNEL from the Beta tables — the refresh einsums disappear
    # from XLA entirely (opt-in numerics, --eig-refresh fused)
    def body_fusedcompute(carry, i, dir0, hard, pi, pi_xi):
        from coda_tpu.ops.beta import dirichlet_to_beta
        from coda_tpu.ops.pbest import compute_pbest
        from coda_tpu.ops.pallas_eig import (
            eig_scores_refresh_compute_pallas,
        )

        rows_c, hyp_c, c = carry
        a_cc, b_cc = dirichlet_to_beta(dir0)
        a_t = jnp.take(a_cc, i % C, axis=1)
        b_t = jnp.take(b_cc, i % C, axis=1)
        rows2 = rows_c.at[i % C].set(
            compute_pbest(a_t, b_t, num_points=G))
        s, hyp2 = eig_scores_refresh_compute_pallas(
            rows2, hyp_c, a_t, b_t, hard, i % C, pi + c * eps, pi_xi,
            num_points=G, block=CH)
        return rows2, hyp2, c + s[0] * eps

    stage("pallas:fused-compute refresh+score", body_fusedcompute,
          (rows, hyp, jnp.float32(0)), ops=(dir0, hard, pi, pi_xi))

    def body_pi(u, i, dir0, preds):
        _, _, u2 = update_pi_hat_column(dir0, i % C, preds, u)
        return u2

    stage("update:pi-hat column (exact)", body_pi, unnorm, ops=(dir0, preds))

    from coda_tpu.selectors.coda import update_pi_hat_column_delta

    preds_by_class = jnp.transpose(preds, (2, 0, 1))

    def body_pi_delta(u, i, hard, preds_by_class):
        _, _, u2 = update_pi_hat_column_delta(
            i % C, hard[i % N], preds_by_class, u, hp0.learning_rate)
        return u2

    stage("update:pi-hat column (delta)", body_pi_delta, unnorm,
          ops=(hard, preds_by_class))

    from coda_tpu.ops.pallas_gather import (
        gather_rows_sum_prepped,
        prep_gather_layout,
    )

    preds_flat = jax.jit(prep_gather_layout)(preds_by_class)

    def body_pi_delta_pallas(u, i, hard, preds_flat):
        _, _, u2 = update_pi_hat_column_delta(
            i % C, hard[i % N], preds_flat, u, hp0.learning_rate,
            gather_fn=lambda f, s: gather_rows_sum_prepped(f, s, N))
        return u2

    stage("pallas:pi-hat delta (DMA gather)", body_pi_delta_pallas, unnorm,
          ops=(hard, preds_flat))

    scores0 = jax.jit(
        lambda r, h, p, px: eig_scores_from_cache(r, h, p, px, chunk=CH)
    )(rows, hyp, pi, pi_xi)
    cand = jnp.ones((N,), bool)

    def body_am(c, i, scores0, cand):
        idx, _ = masked_argmax_tiebreak(
            jax.random.PRNGKey(0), scores0 + c * eps, cand,
            rtol=1e-8, atol=1e-8,
        )
        return c + idx.astype(jnp.float32) * eps

    stage("select:masked argmax", body_am, jnp.float32(0),
          ops=(scores0, cand))

    # the full scan step, for the unexplained-residual check: the sum of
    # the stages above should account for most of this. Setup (sel.init
    # rebuilds its own (N, C, H) cache, ~2 GB at headline scale) only runs
    # when the stage isn't skipped.
    if "full" not in skip:
        # free the standalone-stage tensors first: hyp + hypT + the
        # selector state's own cache + a loop-carry copy + preds is >10 GB
        # at headline — over a v5e's 16 GB HBM (observed ResourceExhausted)
        for buf in (hyp, hypT, rows, unnorm, scores0, preds_by_class):
            buf.delete()
        del hyp, hypT, rows, unnorm, scores0, preds_by_class
        labels = jax.device_put(jnp.asarray(task.labels))
        state0 = jax.jit(
            lambda p, k: make_coda(p, hp0).init(k)
        )(preds, jax.random.PRNGKey(0))
        jax.tree.map(np.asarray, state0)

        # build the selector from ``preds`` INSIDE the traced program (the
        # engine's selector_factory pattern) so the 2 GB tensor lowers as
        # an argument, not an HLO constant — and OUTSIDE the loop via the
        # setup hook so the one-time prior construction cannot be charged
        # to the marginal
        def setup_full(preds, labels):
            sel = make_coda(preds, hp0)

            def body_full(carry, i):
                state, c = carry
                res = sel.select(
                    state, jax.random.fold_in(jax.random.PRNGKey(1), i))
                state = sel.update(state, res.idx, labels[res.idx], res.prob)
                best, _ = sel.best(state, jax.random.PRNGKey(2))
                return state, c + best.astype(jnp.float32) * eps

            return body_full

        stage("full:select+update+best step", None,
              (state0, jnp.float32(0)), ops=(preds, labels),
              setup=setup_full)

    print(json.dumps({"shape": [H, N, C], "eig_chunk": CH, "num_points": G,
                      "backend": jax.default_backend(),
                      "ms_per_iter": results}))


if __name__ == "__main__":
    main()
