"""Tracking-DB janitor: delete everything, or selected tasks/methods.

Capability parity with reference ``scripts/clear_db.py``: ``--all`` removes
the DB file after confirmation; ``--tasks``/``--methods`` delete matching
runs (methods match parent-run names ``<task>-<method>`` and their children)
and empty experiments.

Usage:
    python scripts/clear_db.py --all
    python scripts/clear_db.py --tasks cifar10_5592 --methods coda,iid -y
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from coda_tpu.tracking import TrackingStore  # noqa: E402


def confirm(prompt: str) -> bool:
    return input(prompt + " [y/N] ").lower() in {"y", "yes"}


def delete_all(db_path: str, skip_confirm=False):
    if not os.path.exists(db_path):
        print("Database already empty.")
        return
    targets = [db_path] + [db_path + sfx for sfx in ("-wal", "-shm")
                           if os.path.exists(db_path + sfx)]
    if not skip_confirm and not confirm(
        f"Are you sure you want to delete {', '.join(targets)}?"
    ):
        print("Aborted.")
        return
    for path in targets:
        os.remove(path)
    print("Deleted", ", ".join(targets))


def delete_selected(db_path: str, tasks, methods, skip_confirm=False):
    store = TrackingStore(db_path)
    clauses, params = [], []
    if tasks:
        clauses.append(
            "e.name IN (%s)" % ",".join("?" * len(tasks)))
        params += tasks
    if methods:
        method_clause = " OR ".join(
            ["t.value LIKE ?"] * len(methods))
        clauses.append(f"({method_clause})")
        # substring match, like the reference janitor's `method in run_name`
        # (reference scripts/clear_db.py:68) — canonical CODA runs carry
        # hyperparam suffixes, e.g. `<task>-coda-lr=0.01-mult=2.0-no-prefilter`
        params += [f"%-{m}%" for m in methods]
    where = " AND ".join(clauses) if clauses else "1=1"

    parents = store.query(
        f"""SELECT r.run_uuid, e.name, t.value FROM runs r
            JOIN experiments e ON r.experiment_id = e.experiment_id
            JOIN tags t ON t.run_uuid = r.run_uuid AND t.key='mlflow.runName'
            WHERE r.run_uuid NOT IN
              (SELECT run_uuid FROM tags WHERE key='mlflow.parentRunId')
            AND {where}""",
        tuple(params),
    )
    doomed = []
    for parent_uuid, exp, run_name in parents:
        doomed.append((parent_uuid, exp, run_name))
        doomed += [(c, exp, f"{run_name} (child)")
                   for c in store.child_runs(parent_uuid)]
    if not doomed:
        print("Nothing matches.")
        return
    print(f"Will delete {len(doomed)} runs:")
    for _, exp, name in doomed[:20]:
        print(f"  {exp} / {name}")
    if len(doomed) > 20:
        print(f"  ... and {len(doomed) - 20} more")
    if not skip_confirm and not confirm("Proceed?"):
        print("Aborted.")
        return
    uuids = [d[0] for d in doomed]
    ph = ",".join("?" * len(uuids))
    for table in ("metrics", "params", "tags"):
        store._conn.execute(
            f"DELETE FROM {table} WHERE run_uuid IN ({ph})", uuids)
    store._conn.execute(f"DELETE FROM runs WHERE run_uuid IN ({ph})", uuids)
    store._conn.execute(
        "DELETE FROM experiments WHERE experiment_id NOT IN "
        "(SELECT DISTINCT experiment_id FROM runs)")
    store._conn.commit()
    print(f"Deleted {len(doomed)} runs.")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--all", action="store_true", dest="all_")
    p.add_argument("--tasks", default=None, help="comma-separated task names")
    p.add_argument("--methods", default=None, help="comma-separated methods")
    p.add_argument("-y", "--yes", action="store_true", help="skip confirm")
    args = p.parse_args(argv)

    if args.all_:
        delete_all(args.db, skip_confirm=args.yes)
    elif args.tasks or args.methods:
        tasks = args.tasks.split(",") if args.tasks else None
        methods = args.methods.split(",") if args.methods else None
        delete_selected(args.db, tasks, methods, skip_confirm=args.yes)
    else:
        p.error("Specify --all or --tasks/--methods")


if __name__ == "__main__":
    main()
