"""Head-to-head: PyTorch reference CODA vs ours on iris / digits_shift."""
import sys, numpy as np
sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/reference")
import jax; jax.config.update("jax_platforms", "cpu")

task_name = sys.argv[1]
rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 60

import torch
from coda.coda import CODA as RefCODA
from coda.oracle import Oracle as RefOracle
import coda.options as ref_options

z = np.load(f"/root/repo/data/{task_name}.npz")
preds_np, labels_np = z["preds"], z["labels"]

class DS:
    def __init__(s):
        s.preds = torch.from_numpy(preds_np.copy())
        s.labels = torch.from_numpy(labels_np.astype(np.int64).copy())
        s.device = torch.device("cpu")
ds = DS()

import argparse
ref_args = argparse.Namespace(alpha=0.9, learning_rate=0.01, multiplier=2.0,
                              prefilter_n=0, no_diag_prior=False, q="eig")
sel = RefCODA.from_args(ds, ref_args)
oracle = RefOracle(ds, ref_options.LOSS_FNS["acc"])
tl = oracle.true_losses(ds.preds)
best_loss = tl.min().item()

np.random.seed(0); torch.manual_seed(0)
import random; random.seed(0)
ref_regret, ref_idx = [], []
for m in range(rounds):
    idx, prob = sel.get_next_item_to_label()
    tc = oracle(idx)
    sel.add_label(idx, tc, prob)
    best = sel.get_best_model_prediction()
    ref_regret.append(tl[best].item() - best_loss)
    ref_idx.append(int(idx))
print(f"ref  {task_name}: cum regret x100 @ {rounds} = {100*sum(ref_regret):.1f}")

# ours
from coda_tpu.data import Dataset
from coda_tpu.engine import run_experiment
from coda_tpu.selectors import make_coda, CODAHyperparams
dsj = Dataset.from_file(f"/root/repo/data/{task_name}.npz")
res = run_experiment(make_coda(dsj.preds, CODAHyperparams()), dsj, iters=rounds, seed=0)
ours_cum = float(np.asarray(res.cumulative_regret)[-1])
print(f"ours {task_name}: cum regret x100 @ {rounds} = {100*ours_cum:.1f}")
oi = np.asarray(res.chosen_idx)
same = int((oi[:len(ref_idx)] == np.array(ref_idx)).sum())
print(f"selection agreement: {same}/{rounds}")
