"""Decision-quality bench -> QUALITY_FLEET_CPU_*.json (the ISSUE 20 evidence).

Five passes, one artifact, every claim mechanical:

  1. **Clean fleet shadow audit** — a multi-replica fleet behind the
     session router under transport chaos, every closed session shadow-
     audited (``audit_frac=1``). The claim: every audited replay is
     bitwise identical to its recorder stream (0 divergences), and the
     streaming calibration monitor accumulated per-task ECE/Brier on
     every replica.
  2. **Tamper attribution** — the same auditor over a server whose
     ``stream_tamper`` fault flips a SINGLE float32 ulp in one recorded
     round: the audit must DIVERGE and attribute the divergence to the
     exact session id and round index.
  3. **Ground-truth calibration** — a recorded suite run of the paper
     method, folded through ``record_calibration``: P(best)-vs-
     realized-best reliability with a finite ECE over every round.
  4. **Quality SLO fire/clear** — the ``quality_drift`` objective driven
     through a second-scale :class:`SloSweeper`: it must FIRE while a
     drift detector reports firing and RESOLVE once clean samples wash
     the burn windows, with BOTH alert transitions read back from the
     tracking store.
  5. **Non-perturbation** — the identical deterministic single-worker
     workload with the quality plane on and off (``--no-quality``): the
     recorder's decision rows must be IDENTICAL once the additive
     ``pred_label_prob`` field is dropped — the plane observes the
     serving path, it never steers it. Overhead: min-of-N wall times,
     on vs off, bounded <= 5%.

Run::

    JAX_PLATFORMS=cpu python scripts/bench_quality.py \
        --out QUALITY_FLEET_CPU_r20.json
    python scripts/bench_quality.py --quick   # smoke (not committed)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _loadgen_args(extra: list) -> object:
    from serve_loadgen import parse_args as lg_parse

    return lg_parse(["--synthetic", "4,64,4"] + extra)


def _drain_quality(apps, timeout: float = 60.0) -> bool:
    """Block until every replica's audit queue is empty (audits are
    background work; the claims below read their counters)."""
    ok = True
    for app in apps:
        q = getattr(app, "quality", None)
        if q is not None:
            ok = q.drain(timeout) and ok
    return ok


# ---------------------------------------------------------------------------
# pass 1: clean fleet, every close shadow-audited, zero divergences
# ---------------------------------------------------------------------------

def clean_fleet_pass(quick: bool) -> dict:
    import numpy as np

    from coda_tpu.serve.fleet import build_fleet

    n = 2 if quick else 3
    sessions = 6 if quick else 12
    rounds = 4 if quick else 6
    args = _loadgen_args(["--workers", "4"])
    args.quality_audit_frac = 1.0  # audit EVERY close: the 0-divergence
    # claim must not ride on a lucky sample
    fleet = build_fleet(args, n,
                        fault_spec="net_delay:every=11,ms=3")
    fleet.start(warm=False)
    try:
        router = fleet.router
        rng = np.random.default_rng(11)
        sids = [router.open_session(seed=s)["session"]
                for s in range(sessions)]
        for _ in range(rounds):
            for sid in sids:
                router.label(sid, int(rng.integers(0, 4)))
        for sid in sids:
            router.close_session(sid)
        drained = _drain_quality(fleet.apps.values())
        card = router.quality_scorecard()
    finally:
        fleet.drain()
    per = {}
    audits = divergences = tampered = verified = 0
    calibration = {}
    for rid, snap in card["replicas"].items():
        audit = (snap.get("audit") or {}) if isinstance(snap, dict) else {}
        per[rid] = {
            "audits_total": audit.get("audits_total", 0),
            "rounds_verified": audit.get("rounds_verified", 0),
            "divergences_total": audit.get("divergences_total", 0),
            "calibration": snap.get("calibration") if isinstance(snap, dict)
            else None,
        }
        audits += audit.get("audits_total", 0) or 0
        divergences += audit.get("divergences_total", 0) or 0
        tampered += audit.get("tampered_total", 0) or 0
        verified += audit.get("rounds_verified", 0) or 0
        for task, cal in (snap.get("calibration") or {}).items():
            agg = calibration.setdefault(task, {"n": 0, "ece": []})
            agg["n"] += cal.get("n", 0) or 0
            if cal.get("ece") is not None:
                agg["ece"].append(cal["ece"])
    for task, agg in calibration.items():
        agg["ece_max"] = max(agg.pop("ece"), default=None)
    return {
        "replicas": n, "sessions": sessions, "rounds": rounds,
        "chaos": "net_delay:every=11,ms=3",
        "drained": drained,
        "audits_total": audits,
        "rounds_verified": verified,
        "divergences_total": divergences,
        "tampered_total": tampered,
        "per_replica": per,
        "calibration": calibration,
        "verdict": card["verdict"],
    }


# ---------------------------------------------------------------------------
# pass 2: single-ulp tamper detected with exact attribution
# ---------------------------------------------------------------------------

def tamper_pass() -> dict:
    import numpy as np

    from coda_tpu.serve.server import build_app

    args = _loadgen_args(["--workers", "1"])
    args.quality_audit_frac = 1.0
    args.fault_spec = "stream_tamper:every=1"
    app = build_app(args)
    app.start(warm=False)
    try:
        rng = np.random.default_rng(13)
        out = app.open_session(seed=3)
        sid = out["session"]
        for _ in range(6):
            out = app.label(sid, int(rng.integers(0, 4)))
        n_rows = len([r for r in app.recorder.history(sid)
                      if "kind" not in r])
        app.close_session(sid)
        assert app.quality is not None
        app.quality.drain(60)
        audit = app.quality.snapshot()["audit"]
        verdict = app.quality_scorecard()["verdict"]
    finally:
        app.drain()
    divs = audit.get("last_divergences") or []
    div = divs[-1] if divs else {}
    return {
        "fault_spec": "stream_tamper:every=1",
        "session": sid,
        "decision_rows": n_rows,
        "tampered_total": audit["tampered_total"],
        "divergences_total": audit["divergences_total"],
        "divergence": div,
        # the attribution claim: the flagged replay names the tampered
        # session AND the tampered round (tamper_rows_ulp hits the
        # middle decision row)
        "attributed_session": div.get("session") == sid,
        "attributed_round": div.get("round") == n_rows // 2,
        "verdict_audit": verdict["audit"],
    }


# ---------------------------------------------------------------------------
# pass 3: P(best)-vs-realized-best calibration of a ground-truth record
# ---------------------------------------------------------------------------

def calibration_pass(quick: bool) -> dict:
    import os
    import tempfile

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.replay import record_calibration
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.telemetry.recorder import RunRecord

    task = make_synthetic_task(seed=0, H=6, N=64, C=4, name="calib_0")
    iters = 12 if quick else 24
    seeds = 2 if quick else 4
    with tempfile.TemporaryDirectory() as td:
        runner = SuiteRunner(iters=iters, seeds=seeds, record_dir=td,
                             record_topk=3)
        runner.run_batched([[task]], ["coda"], progress=lambda s: None)
        rec_dir = os.path.join(td, "calib__coda", "calib_0")
        record = RunRecord.load(rec_dir)
        cal = record_calibration(record)
    pooled = cal["pooled"]
    return {
        "method": "coda", "task": "synthetic-6,64,4",
        "iters": iters, "seeds": seeds,
        "pooled": pooled,
        "per_seed_n": [s["n"] for s in cal["seeds"]],
        "finite_ece": (pooled["ece"] is not None
                       and 0.0 <= pooled["ece"] <= 1.0),
        "rounds_scored": pooled["n"],
    }


# ---------------------------------------------------------------------------
# pass 4: quality SLO fire + clear, both transitions read back from store
# ---------------------------------------------------------------------------

def slo_pass() -> dict:
    import os
    import tempfile

    from coda_tpu.telemetry.quality import quality_slos
    from coda_tpu.telemetry.slo import SloSweeper
    from coda_tpu.tracking.store import TrackingStore

    drift = {"statistic": 9.0, "fired_total": 1, "cleared_total": 0,
             "observations": 9, "kind": "cusum", "last_value": 1.0}

    def fleet(firing):
        return {"replicas": {"r0": {"quality": {
            "audit": {"audits_total": 4, "divergences_recent": 0},
            "calibration": {},
            "drift": {"prior_staleness": dict(drift, firing=firing)}}}}}

    with tempfile.TemporaryDirectory() as td:
        db = os.path.join(td, "quality_slo.sqlite")
        t = [0.0]
        sweeper = SloSweeper(quality_slos(), fast_s=10.0, slow_s=20.0,
                             clock=lambda: t[0],
                             store=(lambda: TrackingStore(db)))
        events = []
        fired_at = cleared_at = None
        # phase 1: a drift detector firing on the replica burns the
        # quality_drift budget at 1/0.01 = 100x >= the fire threshold
        for _ in range(5):
            t[0] += 1.0
            for ev in sweeper.observe(fleet(True)):
                events.append(ev)
                if ev["state"] == "firing" and fired_at is None:
                    fired_at = t[0]
        # phase 2: clean samples wash both burn windows -> resolve
        for _ in range(40):
            t[0] += 1.0
            for ev in sweeper.observe(fleet(False)):
                events.append(ev)
                if ev["state"] == "resolved" and cleared_at is None:
                    cleared_at = t[0]
            if cleared_at is not None:
                break
        snap = sweeper.snapshot()
        # the persistence half of the claim: both transitions read BACK
        # from the tracking store on a fresh connection
        store = TrackingStore(db)
        persisted = {
            state: store.is_finished(
                "serve_slo", f"alert-quality_drift-{state}")
            for state in ("firing", "resolved")
        }
        store.close()
    st = snap["objectives"]["quality_drift"]
    return {
        "objective": "quality_drift",
        "windows_s": snap["windows_s"],
        "fired": st["fired_total"],
        "cleared": st["cleared_total"],
        "fired_at_s": fired_at,
        "cleared_at_s": cleared_at,
        "transitions": [{k: e[k] for k in ("slo", "state", "burn_fast")}
                        for e in events],
        "store_flushed": snap["store"]["flushed"],
        "store_errors": snap["store"]["errors"],
        "persisted": persisted,
        "persisted_both": all(persisted.values()),
    }


# ---------------------------------------------------------------------------
# pass 5: non-perturbation (bitwise rows) + overhead
# ---------------------------------------------------------------------------

def _quality_workload(app, n_labels: int) -> tuple:
    """One deterministic single-stream session; returns (wall_s, sid)."""
    t0 = time.perf_counter()
    out = app.open_session(seed=0)
    sid = out["session"]
    for _ in range(n_labels):
        out = app.label(sid, int(out["idx"]) % 4)
    app.close_session(sid)
    return time.perf_counter() - t0, sid


def _stream_rows(record_dir: str, sid: str) -> list:
    import glob
    import os

    rows = []
    for path in sorted(glob.glob(os.path.join(record_dir, "**", f"*{sid}*"),
                                 recursive=True)):
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                # only decision rows: meta/close markers carry wall-clock
                # provenance that legitimately differs between runs
                if "next_idx" in row:
                    rows.append(row)
    return rows


def bitwise_pass(n_labels: int = 24) -> dict:
    import os
    import tempfile

    from coda_tpu.serve.server import build_app

    runs = {}
    with tempfile.TemporaryDirectory() as td:
        for mode, on in (("quality_on", True), ("quality_off", False)):
            rd = os.path.join(td, mode)
            args = _loadgen_args(["--workers", "1"])
            args.record_dir = rd
            args.no_quality = not on
            args.quality_audit_frac = 1.0
            app = build_app(args)
            app.start(warm=False)
            try:
                _wall, sid = _quality_workload(app, n_labels)
                if app.quality is not None:
                    app.quality.drain(60)
            finally:
                app.drain()
            runs[mode] = _stream_rows(rd, sid)
    on_rows = runs["quality_on"]
    off_rows = runs["quality_off"]
    update_rows = [r for r in on_rows if r.get("do_update")]
    rows_carry_prob = bool(update_rows) and all(
        "pred_label_prob" in r
        and 0.0 <= float(r["pred_label_prob"]) <= 1.0
        for r in update_rows)
    off_clean = not any("pred_label_prob" in r for r in off_rows)
    stripped = [{k: v for k, v in r.items() if k != "pred_label_prob"}
                for r in on_rows]
    identical = (json.dumps(stripped, sort_keys=True)
                 == json.dumps(off_rows, sort_keys=True))
    first_diff = None
    if not identical:
        for i, (a, b) in enumerate(zip(stripped, off_rows)):
            if a != b:
                first_diff = {"row": i, "on": a, "off": b}
                break
        if first_diff is None:
            first_diff = {"row_counts": [len(stripped), len(off_rows)]}
    return {
        "labels": n_labels,
        "rows": [len(on_rows), len(off_rows)],
        "update_rows_carry_pred_label_prob": rows_carry_prob,
        "off_rows_field_free": off_clean,
        "identical": identical,
        "first_diff": first_diff,
    }


def overhead_pass(n_labels: int = 200, reps: int = 8) -> dict:
    """min-of-``reps`` wall time of the identical serial workload, quality
    plane on vs off. Both apps stay alive and the reps ALTERNATE modes,
    so slow container drift hits both sides equally; min (not mean)
    because noise only ever ADDS time — the minima are the honest
    comparison."""
    from coda_tpu.serve.server import build_app

    apps = {}
    for mode, on in (("off", False), ("on", True)):
        args = _loadgen_args(["--workers", "1"])
        args.no_quality = not on
        # overhead measures the HOT path (pre-dispatch consensus fold +
        # calibration row): audits are close-time background work
        args.quality_audit_frac = 0.0
        apps[mode] = build_app(args)
        apps[mode].start(warm=False)
    walls: dict = {"on": [], "off": []}
    try:
        for mode in ("off", "on"):
            _quality_workload(apps[mode], 20)  # page everything in
        for _ in range(reps):
            for mode in ("off", "on"):
                wall, _sid = _quality_workload(apps[mode], n_labels)
                walls[mode].append(wall)
    finally:
        for app in apps.values():
            app.drain()
    on, off = min(walls["on"]), min(walls["off"])
    return {
        "labels": n_labels, "reps": reps,
        "on_s": walls["on"], "off_s": walls["off"],
        "on_min_s": on, "off_min_s": off,
        "per_label_us": {"on": on / n_labels * 1e6,
                         "off": off / n_labels * 1e6},
        # clamped at 0: a negative delta is container noise, not a
        # time-travelling monitor
        "overhead_frac": max(0.0, (on - off) / off),
    }


# ---------------------------------------------------------------------------

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="2-replica smoke pass (smaller workload; do not "
                        "commit the artifact)")
    p.add_argument("--out", default=None,
                   help="artifact path (default QUALITY_FLEET_CPU.json)")
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(None)
    from coda_tpu.telemetry.recorder import environment_fingerprint

    t0 = time.perf_counter()
    print("== pass 1/5: clean fleet shadow audit ==", flush=True)
    clean = clean_fleet_pass(args.quick)
    print(json.dumps({k: clean[k] for k in
                      ("audits_total", "divergences_total", "verdict")}),
          flush=True)
    print("== pass 2/5: tamper attribution ==", flush=True)
    tamper = tamper_pass()
    print(json.dumps({k: tamper[k] for k in
                      ("tampered_total", "divergences_total",
                       "attributed_session", "attributed_round")}),
          flush=True)
    print("== pass 3/5: ground-truth calibration ==", flush=True)
    calibration = calibration_pass(args.quick)
    print(json.dumps({"pooled": calibration["pooled"]}), flush=True)
    print("== pass 4/5: quality SLO fire/clear ==", flush=True)
    slo = slo_pass()
    print(json.dumps({k: slo[k] for k in
                      ("fired", "cleared", "persisted_both")}), flush=True)
    print("== pass 5/5: non-perturbation + overhead ==", flush=True)
    bitwise = bitwise_pass()
    overhead = overhead_pass(n_labels=60 if args.quick else 200,
                             reps=3 if args.quick else 8)
    print(json.dumps({"identical": bitwise["identical"],
                      "overhead_frac": overhead["overhead_frac"]}),
          flush=True)

    report = {
        "bench": "bench_quality",
        "quick": bool(args.quick),
        "fingerprint": environment_fingerprint(knobs={
            "bench": "bench_quality", "quick": bool(args.quick),
            "replicas": clean["replicas"],
            "audit_frac": 1.0,
            "task": "synthetic-4,64,4"}),
        "wall_s": time.perf_counter() - t0,
        "clean_fleet": clean,
        "tamper": tamper,
        "calibration": calibration,
        "slo": slo,
        "bitwise": bitwise,
        "overhead": overhead,
    }
    out = args.out or ("QUALITY_FLEET_CPU_quick.json" if args.quick
                       else "QUALITY_FLEET_CPU.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out} in {report['wall_s']:.1f}s")
    return report


if __name__ == "__main__":
    main()
