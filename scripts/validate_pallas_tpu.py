"""Validate the fused pallas EIG scorer on REAL TPU silicon.

Round-3 verdict: the kernel had only ever run in interpret mode — Mosaic
compilation, real tiling, and on-device numerics were unverified. This
script is the hardware half of that proof, run the moment the tunnel is
healthy:

  1. Mosaic-compile `eig_scores_cache_pallas` (interpret=False) at the
     headline incremental shape and at a ragged/non-aligned shape.
  2. Compare scores against the jnp reference path ON DEVICE (same cache
     tensors): max abs diff and argmax agreement.
  3. Time both paths with the loop-in-jit discipline (fori_loop with a
     data dependence, marginal cost between two loop lengths — a bare
     block_until_ready through the axon tunnel returns before the queue
     drains).

Prints one JSON line; non-zero exit if compilation or numerics fail.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def _timed_loop(fn_scores, rows, hyp, pi, pi_xi, n: int) -> float:
    """Wall-clock of n dependent applications, result materialized."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def loop(rows, hyp, pi, pi_xi):
        def body(_, carry):
            acc, pi = carry
            s = fn_scores(rows, hyp, pi, pi_xi)
            # thread a data dependence through pi so iterations can't be
            # collapsed or reordered; keep it tiny so numerics stay sane
            # (one scalar suffices, and broadcasts for any pi rank)
            pi = pi + 1e-12 * s.reshape(-1)[0]
            return acc + s.sum(), pi

        acc, _ = jax.lax.fori_loop(
            0, n, body, (jnp.asarray(0.0, jnp.float32), pi))
        return acc

    t0 = time.perf_counter()
    out = loop(rows, hyp, pi, pi_xi)
    np.asarray(out)  # materialize through the tunnel
    return time.perf_counter() - t0


def run_shape(N: int, C: int, H: int, reps_hi: int = 8,
              reps_lo: int = 2) -> dict:
    import jax
    import jax.numpy as jnp

    from coda_tpu.ops.pallas_eig import choose_block, eig_scores_cache_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    rows = jax.nn.softmax(jax.random.normal(k1, (C, H)), axis=-1)
    hyp = jax.nn.softmax(jax.random.normal(k2, (C, N, H)), axis=-1)
    pi = jax.nn.softmax(jax.random.normal(k3, (C,)))
    pi_xi = jax.nn.softmax(jax.random.normal(k4, (N, C)), axis=-1)

    B = choose_block(N, C, H)
    rec: dict = {"shape": {"N": N, "C": C, "H": H}, "block": B}

    # 1. Mosaic compile + run (interpret=False on TPU)
    t0 = time.perf_counter()
    s_pl = np.asarray(eig_scores_cache_pallas(rows, hyp, pi, pi_xi))
    rec["mosaic_compile_and_first_run_s"] = round(time.perf_counter() - t0, 3)

    # 2. numerics vs the jnp path, on device
    s_jnp = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi))
    rec["max_abs_diff"] = float(np.max(np.abs(s_pl - s_jnp)))
    rec["argmax_agree"] = bool(s_pl.argmax() == s_jnp.argmax())
    rec["scale"] = float(np.abs(s_jnp).mean())

    # 2b. the fast-entropy lowering (eig_entropy='approx'): the pallas
    #     approx kernel against BOTH the jnp approx composition (the two
    #     lowerings of the same polynomial chain must agree tightly) and
    #     the exact scores (the committed |Dscore| <= 1e-4 opt-in bound)
    s_ap = np.asarray(eig_scores_cache_pallas(rows, hyp, pi, pi_xi,
                                              approx=True))
    s_ap_jnp = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi,
                                                approx=True))
    rec["approx_pallas_vs_jnp_max_abs_diff"] = float(
        np.max(np.abs(s_ap - s_ap_jnp)))
    rec["approx_vs_exact_max_abs_diff"] = float(
        np.max(np.abs(s_ap - s_jnp)))
    rec["approx_argmax_agree"] = bool(s_ap.argmax() == s_jnp.argmax())

    # 3. marginal timing, loop-in-jit (same discipline for every path;
    #    pallas_approx is the --eig-entropy approx silicon number — the
    #    lever against the ~1.2 ms VPU transcendental tail)
    def jnp_fn(r, h, p, px):
        return eig_scores_from_cache(r, h, p, px)

    def pl_fn(r, h, p, px):
        return eig_scores_cache_pallas(r, h, p, px)

    def pl_approx_fn(r, h, p, px):
        return eig_scores_cache_pallas(r, h, p, px, approx=True)

    for name, fn in (("jnp", jnp_fn), ("pallas", pl_fn),
                     ("pallas_approx", pl_approx_fn)):
        _timed_loop(fn, rows, hyp, pi, pi_xi, reps_lo)  # warm
        hi = _timed_loop(fn, rows, hyp, pi, pi_xi, reps_hi)
        lo = _timed_loop(fn, rows, hyp, pi, pi_xi, reps_lo)
        rec[f"{name}_marginal_ms"] = round(
            1e3 * (hi - lo) / (reps_hi - reps_lo), 3)

    # 4. the FUSED refresh+score kernel (aliased two-output form): Mosaic
    #    compile + numerics vs DUS-then-score, on device — including the
    #    aliased cache pass-through (unwritten rows must carry over)
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_pallas

    k5, k6 = jax.random.split(jax.random.PRNGKey(1))
    hyp_t = jax.nn.softmax(jax.random.normal(k5, (N, H)), axis=-1)
    c = jnp.int32(C - 1)
    t0 = time.perf_counter()
    s_fu, hyp_fu = jax.jit(eig_scores_refresh_pallas)(
        rows, hyp, hyp_t, c, pi, pi_xi)
    s_fu = np.asarray(s_fu)
    rec["fused_mosaic_compile_and_first_run_s"] = round(
        time.perf_counter() - t0, 3)
    hyp_ref2 = hyp.at[c].set(hyp_t)
    s_ref2 = np.asarray(eig_scores_from_cache(rows, hyp_ref2, pi, pi_xi))
    rec["fused_max_abs_diff"] = float(np.max(np.abs(s_fu - s_ref2)))
    rec["fused_argmax_agree"] = bool(s_fu.argmax() == s_ref2.argmax())
    # aliased pass-through: an untouched row and the refreshed row, spot-
    # checked via device-side comparisons (full host pulls are tunnel-slow)
    rec["fused_row_updated"] = bool(np.asarray(
        jnp.allclose(hyp_fu[c], hyp_t, atol=0)))
    rec["fused_rows_carried"] = bool(np.asarray(
        jnp.array_equal(hyp_fu[0], hyp_ref2[0])))

    # 5. the fused-COMPUTE kernel (eig_refresh='fused'): the replacement
    #    row is computed IN-KERNEL from Beta tables — validate its scores
    #    AND refreshed row against the XLA-HIGHEST precomputed path on
    #    device (the documented opt-in tolerance: in-kernel fp32 dots vs
    #    6-pass einsums)
    from coda_tpu.ops.beta import dirichlet_to_beta
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_compute_pallas
    from coda_tpu.selectors.coda import update_eig_cache_parts

    dir_ = jax.random.uniform(k6, (H, C, C)) * 3.0 + 0.5
    hard = jax.random.randint(jax.random.PRNGKey(2), (N, H), 0, C
                              ).astype(jnp.int32)
    a_cc, b_cc = dirichlet_to_beta(dir_)
    a_t, b_t = a_cc[:, c], b_cc[:, c]
    t0 = time.perf_counter()
    s_fc, hyp_fc = jax.jit(eig_scores_refresh_compute_pallas)(
        rows, hyp, a_t, b_t, hard, c, pi, pi_xi)
    s_fc = np.asarray(s_fc)
    rec["fusedcompute_mosaic_compile_and_first_run_s"] = round(
        time.perf_counter() - t0, 3)
    _, hyp_t_ref = update_eig_cache_parts(dir_, c, hard)
    hyp_ref3 = hyp.at[c].set(hyp_t_ref)
    s_ref3 = np.asarray(eig_scores_from_cache(rows, hyp_ref3, pi, pi_xi))
    rec["fusedcompute_max_abs_diff"] = float(np.max(np.abs(s_fc - s_ref3)))
    rec["fusedcompute_argmax_agree"] = bool(
        s_fc.argmax() == s_ref3.argmax())
    rec["fusedcompute_row_max_abs_diff"] = float(np.asarray(
        jnp.max(jnp.abs(hyp_fc[c] - hyp_t_ref))))
    rec["fusedcompute_rows_carried"] = bool(np.asarray(
        jnp.array_equal(hyp_fc[0], hyp_ref3[0])))
    return rec


def run_batched_shape(S: int, N: int, C: int, H: int, reps_hi: int = 8,
                      reps_lo: int = 2) -> dict:
    """The BATCHED kernels (vmapped caller -> custom_vmap -> batch-grid
    pallas): Mosaic compile, numerics vs the vmapped jnp path, and
    marginal timing of both — the suite's vmapped-seed / stacked-task
    production shape."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.ops.pallas_eig import (
        eig_scores_cache_pallas,
        eig_scores_refresh_pallas,
    )
    from coda_tpu.selectors.coda import eig_scores_from_cache

    ks = jax.random.split(jax.random.PRNGKey(2), 4)
    rows = jax.nn.softmax(jax.random.normal(ks[0], (S, C, H)), axis=-1)
    hyp = jax.nn.softmax(jax.random.normal(ks[1], (S, C, N, H)), axis=-1)
    pi = jax.nn.softmax(jax.random.normal(ks[2], (S, C)), axis=-1)
    pi_xi = jax.nn.softmax(jax.random.normal(ks[3], (S, N, C)), axis=-1)

    from coda_tpu.ops.pallas_eig import batched_pallas_viable

    rec: dict = {"shape": {"S": S, "N": N, "C": C, "H": H},
                 # False = the padded-operand budget routed this shape to
                 # the jnp fallback (e.g. the DomainNet batch: the
                 # (S, C, N, 1) operand's 128x lane pad OOMed a v5e)
                 "pallas_engaged": batched_pallas_viable(S, C, N, H, 4)}
    score_v = jax.jit(jax.vmap(
        lambda r, h, p, px: eig_scores_cache_pallas(r, h, p, px)))
    t0 = time.perf_counter()
    s_pl = np.asarray(score_v(rows, hyp, pi, pi_xi))
    rec["mosaic_compile_and_first_run_s"] = round(time.perf_counter() - t0, 3)
    jnp_v = jax.jit(jax.vmap(
        lambda r, h, p, px: eig_scores_from_cache(r, h, p, px)))
    s_jnp = np.asarray(jnp_v(rows, hyp, pi, pi_xi))
    rec["max_abs_diff"] = float(np.max(np.abs(s_pl - s_jnp)))
    rec["argmax_agree"] = bool(
        (s_pl.argmax(axis=1) == s_jnp.argmax(axis=1)).all())

    def pl_fn(r, h, p, px):
        return jax.vmap(
            lambda r2, h2, p2, px2: eig_scores_cache_pallas(
                r2, h2, p2, px2))(r, h, p, px).sum(0)

    def jnp_fn(r, h, p, px):
        return jax.vmap(
            lambda r2, h2, p2, px2: eig_scores_from_cache(
                r2, h2, p2, px2))(r, h, p, px).sum(0)

    for name, fn in (("jnp", jnp_fn), ("pallas", pl_fn)):
        _timed_loop(fn, rows, hyp, pi, pi_xi, reps_lo)
        hi = _timed_loop(fn, rows, hyp, pi, pi_xi, reps_hi)
        lo = _timed_loop(fn, rows, hyp, pi, pi_xi, reps_lo)
        rec[f"{name}_marginal_ms"] = round(
            1e3 * (hi - lo) / (reps_hi - reps_lo), 3)

    # batched fused refresh+score
    k5 = jax.random.PRNGKey(3)
    hyp_t = jax.nn.softmax(jax.random.normal(k5, (S, N, H)), axis=-1)
    cs = (jnp.arange(S, dtype=jnp.int32) * 7) % C
    fused_v = jax.jit(jax.vmap(
        lambda r, h, ht, c, p, px: eig_scores_refresh_pallas(
            r, h, ht, c, p, px)))
    t0 = time.perf_counter()
    s_fu, hyp_fu = fused_v(rows, hyp, hyp_t, cs, pi, pi_xi)
    s_fu = np.asarray(s_fu)
    rec["fused_mosaic_compile_and_first_run_s"] = round(
        time.perf_counter() - t0, 3)
    hyp_ref2 = jax.vmap(lambda h, c, ht: h.at[c].set(ht))(hyp, cs, hyp_t)
    s_ref2 = np.asarray(jnp_v(rows, hyp_ref2, pi, pi_xi))
    rec["fused_max_abs_diff"] = float(np.max(np.abs(s_fu - s_ref2)))
    rec["fused_argmax_agree"] = bool(
        (s_fu.argmax(axis=1) == s_ref2.argmax(axis=1)).all())
    rec["fused_row_updated"] = bool(np.asarray(jax.vmap(
        lambda hf, c, ht: jnp.allclose(hf[c], ht, atol=0))(
        hyp_fu, cs, hyp_t).all()))
    rec["fused_rows_carried"] = bool(np.asarray(jax.vmap(
        lambda hf, hr: jnp.array_equal(hf[0], hr[0]))(
        hyp_fu, hyp_ref2).all()))
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--tol", type=float, default=2e-5,
                    help="max abs score diff vs the jnp path")
    ap.add_argument("--approx-tol", type=float, default=1e-4,
                    help="max abs score diff of the eig_entropy='approx' "
                         "lowering vs the exact path (the committed "
                         "opt-in bound; measured ~2e-5)")
    ap.add_argument("--eig-entropy", default="exact",
                    choices=["exact", "approx"],
                    help="recorded in the artifact so a capture names "
                         "which lowering its headline numbers target "
                         "(both variants are always validated and timed)")
    ap.add_argument("--batched-only", action="store_true",
                    help="run only the batched-kernel section")
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    out = {"device": dev.device_kind, "platform": dev.platform,
           "interpret": not on_tpu, "eig_entropy": args.eig_entropy,
           "shapes": []}
    # On TPU: the headline incremental shape + a deliberately ragged one
    # (N % 8 != 0, C not x8, H not x128) to exercise Mosaic's edge
    # handling. Off-TPU the kernel runs in the per-element interpreter,
    # where headline shapes are infeasible — small shapes smoke the script
    # itself (the hardware claims are TPU-only anyway).
    shapes = ([(50_000, 10, 1000), (1013, 7, 130)] if on_tpu
              else [(512, 10, 96), (101, 7, 130)])
    if not args.batched_only:
        for (N, C, H) in shapes:
            out["shapes"].append(run_shape(N, C, H))

    # batched shapes: the suite's production configurations — a DomainNet
    # family probe batch (T=12 tasks x width 1), its rest batch (cap 3 x
    # width 4), and a small-batch headline-like shape (2 x 2 GB caches)
    out["batched_shapes"] = []
    bshapes = ([(12, 20000, 126, 30), (5, 10000, 10, 80),
                (2, 50_000, 10, 1000)] if on_tpu
               else [(3, 256, 5, 12)])
    for (S, N, C, H) in bshapes:
        out["batched_shapes"].append(run_batched_shape(S, N, C, H))

    ok = all(s["max_abs_diff"] <= args.tol and s["argmax_agree"]
             and s["fused_max_abs_diff"] <= args.tol
             and s["fused_argmax_agree"] and s["fused_row_updated"]
             and s["fused_rows_carried"]
             for s in out["shapes"] + out["batched_shapes"])
    # the fast-entropy lowering: pallas and jnp approx must agree like the
    # exact pair, and approx-vs-exact must hold the committed opt-in bound
    ok = ok and all(
        s["approx_pallas_vs_jnp_max_abs_diff"] <= args.tol
        and s["approx_vs_exact_max_abs_diff"] <= args.approx_tol
        and s["approx_argmax_agree"]
        for s in out["shapes"])
    # the fused-COMPUTE kernel carries the documented opt-in tolerance
    # (in-kernel fp32 dots vs XLA-HIGHEST einsums): scores ~1e-4, row
    # values ~1e-5 of O(1/H)-scale probabilities
    ok = ok and all(
        s["fusedcompute_max_abs_diff"] <= 50 * args.tol
        and s["fusedcompute_argmax_agree"]
        and s["fusedcompute_row_max_abs_diff"] <= 50 * args.tol
        and s["fusedcompute_rows_carried"]
        for s in out["shapes"])
    out["ok"] = ok
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
