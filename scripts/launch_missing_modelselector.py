"""Run the epsilon grid search for every task missing from the results JSON.

Capability parity with reference
``scripts/modelselector/launch_missing_modelselector.py``: scans the data
directory, skips tasks already present in ``best_epsilons.json``, and runs
the grid search for the rest — as local subprocesses by default (the TPU
sweep needs no cluster scheduler for this; seeds/realisations are already
vmapped inside one process), or under any launcher prefix via ``--launcher``.

Usage:
    python scripts/launch_missing_modelselector.py --pred-dir data
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

SCRIPTS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(SCRIPTS))

from coda_tpu.data import DATA_EXTS, list_tasks  # noqa: E402


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pred-dir", default="data")
    p.add_argument("--results", default="best_epsilons.json")
    p.add_argument("--launcher", default=None,
                   help="optional launcher prefix, e.g. 'srun -p part'")
    p.add_argument("--max-concurrent", type=int, default=1)
    p.add_argument("--gridsearch-args", default="",
                   help="extra args forwarded to the grid search script")
    args = p.parse_args(argv)

    existing = set()
    if os.path.exists(args.results):
        with open(args.results) as f:
            for k in json.load(f):
                existing.add(os.path.splitext(k)[0] if k.endswith(DATA_EXTS)
                             else k)

    tasks = list_tasks(args.pred_dir)
    todo = [t for t in tasks if t not in existing]
    if not todo:
        print("Nothing missing.")
        return

    import time

    procs: list[subprocess.Popen] = []
    for task in todo:
        cmd = (list(args.launcher.split()) if args.launcher else []) + [
            sys.executable,
            os.path.join(SCRIPTS, "modelselector_eps_gridsearch.py"),
            "--task", task,
            "--pred-dir", args.pred_dir,
            "--results", args.results,
        ] + args.gridsearch_args.split()
        while sum(p_.poll() is None for p_ in procs) >= args.max_concurrent:
            time.sleep(1.0)
        print("Launching:", " ".join(cmd))
        procs.append(subprocess.Popen(cmd))
    for pr in procs:
        pr.wait()


if __name__ == "__main__":
    main()
