"""Contract-gated EIG surrogate benchmark -> BENCH_SURROGATE_<b>_rNN.json.

The ``--eig-scorer surrogate:k`` claim, measured and replay-verified
(ISSUE 15):

  * **regret parity** (real-digits 100-round trace): the surrogate-scored
    run must land within the committed envelope of the exact scorer's
    cumulative regret at the same label budget — the trust gate's whole
    point is that selection quality is not traded away. Both runs are
    recorded, each self-replays bitwise (``cli replay``), the
    surrogate-vs-exact pair is compared through the real
    ``cli replay --against`` path (the knob diff auto-resolves to the
    label-aligned ``eig-scorer-envelope`` triage), and the DEFAULT
    (``--eig-scorer exact``) is pinned bitwise-unchanged against a
    knob-less record through the same path.
  * **scoring-pass speedup** (the imagenet preset, C=1000/H=500/N=256,
    posterior=sparse:32, surrogate:64): the exact full O(N·C·H) cache
    sweep vs the surrogate pass (features -> ridge predict -> exact
    shortlist refresh -> gate -> refold), timed on the SAME carried
    post-warmup state, min of warm reps. The committed floor: >= 3x.
  * **fallback rate**: post-warmup contract fallbacks must stay <= 10%
    of rounds (a surrogate that bounces off its own gate amortizes
    nothing) — measured from the carried fit counters at the preset and
    from the per-round ``surrogate_fallback`` stream on digits.

Runnable standalone (CPU container: the preset init dominates, ~8 min
full; ~1 min quick)::

    python scripts/bench_surrogate.py --out BENCH_SURROGATE_CPU_r17.json \
        --records-dir runs/surrogate_r17
    python scripts/bench_surrogate.py --quick   # digits smoke + smoke shape

The finished artifact is self-gated against its ``check_perf.py``
contract before the script exits.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the declared bounds are the GATE's, imported from the one place they
# are enforced (scripts/check_perf.py) so the generator can never embed
# verdicts computed under stale thresholds
from check_perf import (  # noqa: E402
    SURROGATE_ENVELOPE_ABS as ENVELOPE_ABS,
    SURROGATE_ENVELOPE_RATIO as ENVELOPE_RATIO,
    SURROGATE_MAX_FALLBACK_RATE as MAX_FALLBACK_RATE,
    SURROGATE_MIN_SCORE_SPEEDUP as MIN_SPEEDUP,
)


def _knobs(args, **extra) -> dict:
    base = {"bench": "surrogate", "quick": bool(args.quick)}
    base.update(extra)
    return base


def _fallback_rate(record) -> float:
    """Post-warmup contract-fallback rate from the record's per-round
    ``surrogate_fallback`` stream (schema v3)."""
    from coda_tpu.selectors.surrogate import SURROGATE_WARMUP_ROUNDS

    fb = np.asarray(record.arrays["surrogate_fallback"], bool)
    post = fb[:, SURROGATE_WARMUP_ROUNDS:]
    return float(post.mean()) if post.size else 0.0


def _cli_replay(args_list) -> int:
    """The REAL ``cli replay`` path, as a subprocess (what the artifact's
    verification commands document)."""
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS",
                                                        "cpu"))
    r = subprocess.run(
        [sys.executable, "-m", "coda_tpu.cli", "replay"] + args_list,
        cwd=REPO, capture_output=True, text=True, timeout=900, env=env)
    sys.stderr.write(r.stdout[-2000:])
    return r.returncode


def _run_digits(args, fingerprint_holder: list) -> tuple:
    """The regret half: exact vs surrogate on the real-digits trace at
    one label budget, recorded + replay-verified; plus the default-knob
    bitwise pin."""
    from coda_tpu.cli import load_dataset
    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.engine.replay import verify_replay
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    ds = load_dataset(argparse.Namespace(
        task="digits", data_dir=args.data_dir, synthetic=None, mesh=None))
    iters = 40 if args.quick else 100
    seeds = 2 if args.quick else 3
    scorer = "surrogate:16" if args.quick else f"surrogate:{args.digits_k}"
    out: dict = {"task": ds.name, "shape": list(ds.shape),
                 "label_budget": iters, "seeds": seeds, "scorer": scorer}
    records = {}
    # "default" records the knob-less program (a pre-knob capture);
    # "exact" records --eig-scorer exact explicitly: the two must be
    # BITWISE identical through cli replay --against (the default pin)
    configs = {"default": None, "exact": "exact", "surrogate": scorer}
    for name, knob in configs.items():
        hp_kwargs = dict(n_parallel=seeds)
        if knob is not None:
            hp_kwargs["eig_scorer"] = knob
        hp = CODAHyperparams(**hp_kwargs)
        factory = (lambda _hp: (lambda preds: make_coda(preds, _hp)))(hp)
        t0 = time.perf_counter()
        result, aux = run_seeds_recorded(
            factory, ds.preds, ds.labels, iters=iters, seeds=seeds,
            trace_k=8, cost_label=f"surrogate_digits_{name}")
        np.asarray(result.cumulative_regret)  # sync
        wall = time.perf_counter() - t0
        knobs = _knobs(args, capture="digits", method="coda", loss="acc",
                       iters=iters, seeds=seeds, n_parallel=seeds,
                       eig_chunk=1024)
        if knob is not None:
            knobs["eig_scorer"] = knob
        fp = environment_fingerprint(dataset=ds, knobs=knobs)
        if not fingerprint_holder:
            fingerprint_holder.append(environment_fingerprint(
                dataset=ds, knobs=_knobs(args)))
        record = RunRecord.from_result(
            result, aux, fp,
            run={"task": ds.name, "synthetic": None,
                 "data_dir": args.data_dir, "method": "coda",
                 "loss": "acc", "iters": iters, "seeds": seeds})
        rec_dir = os.path.join(args.records_dir, name)
        record.save(rec_dir)
        records[name] = (record, rec_dir, factory)
        cum = np.asarray(result.cumulative_regret)[:, -1]
        entry = {
            "iters": iters, "wall_s": round(wall, 3),
            "record_dir": os.path.relpath(rec_dir, REPO),
            "final_cum_regret_mean": float(cum.mean()),
            "final_cum_regret_per_seed": [float(v) for v in cum],
        }
        if name == "surrogate":
            entry["fallback_rate_post_warmup"] = _fallback_rate(record)
        # bitwise self-replay through the identical program — the same
        # verify path `cli replay <dir>` runs
        rep = verify_replay(record, factory, ds.preds, ds.labels,
                            loss="acc", score_tol=0.0)
        entry["replay"] = {
            "parity": bool(rep.parity),
            "cli": f"cli replay {os.path.relpath(rec_dir, REPO)}",
        }
        out[name] = entry

    # surrogate vs exact through the REAL cli replay --against path: the
    # eig_scorer knob diff must auto-resolve to the envelope triage
    _, exact_dir, _ = records["exact"]
    _, surr_dir, _ = records["surrogate"]
    _, default_dir, _ = records["default"]
    report_fp = os.path.join(args.records_dir, "against_exact.json")
    rc = _cli_replay([exact_dir, "--against", surr_dir,
                      "--out", report_fp])
    with open(report_fp) as f:
        rep = json.load(f)
    env = rep.get("meta", {}).get("scorer_envelope") or {}
    cls = (rep.get("seeds") or [{}])[0].get("classification")
    exact_mean = out["exact"]["final_cum_regret_mean"]
    surr_mean = out["surrogate"]["final_cum_regret_mean"]
    within = surr_mean <= ENVELOPE_RATIO * exact_mean + ENVELOPE_ABS
    out["against_exact"] = {
        "cli": (f"cli replay {os.path.relpath(exact_dir, REPO)} "
                f"--against {os.path.relpath(surr_dir, REPO)}"),
        "rc": rc,
        "classification": cls,
        "envelope": env,
        "ratio_vs_exact": (surr_mean / exact_mean if exact_mean > 0
                           else None),
        "within_envelope": bool(within),
    }
    # the default pin: --eig-scorer exact must be BITWISE the knob-less
    # program (rc 0 = full parity through the same real path; score-tol
    # forced to 0 — the auto tolerance would relax on the knob diff and
    # weaken the bitwise claim)
    rc_pin = _cli_replay([default_dir, "--against", exact_dir,
                          "--score-tol", "0"])
    pin = {
        "cli": (f"cli replay {os.path.relpath(default_dir, REPO)} "
                f"--against {os.path.relpath(exact_dir, REPO)} "
                "--score-tol 0"),
        "rc": rc_pin,
        "parity": rc_pin == 0,
        "score_tol": 0.0,
    }
    out["envelope"] = {"ratio": ENVELOPE_RATIO, "abs_slack": ENVELOPE_ABS,
                       "ok": bool(within)}
    return out, pin


def _time_min(fn, arg, reps: int = 7) -> float:
    import jax

    jax.block_until_ready(fn(arg))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(arg))
        best = min(best, time.perf_counter() - t0)
    return best


def _run_preset(args) -> dict:
    """The throughput half at the imagenet preset: scoring-pass speedup
    (exact sweep vs surrogate pass on the same carried state), the
    post-warmup fallback rate from the carried fit counters, and the
    marginal surrogate round seconds (the cross-round regression
    metric)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import make_step_fn
    from coda_tpu.losses import accuracy_loss
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.surrogate import SURROGATE_WARMUP_ROUNDS

    if args.quick:
        H, N, C, posterior, chunk, k = 50, 256, 100, "sparse:16", 64, 32
        measured_rounds = 10
    else:
        H, N, C, posterior, chunk, k = 500, 256, 1000, "sparse:32", 64, 64
        measured_rounds = args.preset_rounds
    ds = make_synthetic_task(seed=0, H=H, N=N, C=C)
    hp = CODAHyperparams(posterior=posterior, eig_chunk=chunk,
                         eig_scorer=f"surrogate:{k}", n_parallel=1)
    sel = make_coda(ds.preds, hp)
    losses = true_losses(ds.preds, ds.labels, accuracy_loss)
    t0 = time.perf_counter()
    state0 = jax.jit(sel.init)(jax.random.PRNGKey(0))
    jax.block_until_ready(state0)
    init_s = time.perf_counter() - t0

    step = make_step_fn(sel, ds.labels, losses)

    @jax.jit
    def run(state, keys):
        (s, cum), _ = lax.scan(step, (state, jnp.asarray(0.0,
                                                         jnp.float32)),
                               keys)
        return s, cum

    # warmup + measured rounds in one scan; the final carry's fit
    # counters are the fallback evidence
    R = SURROGATE_WARMUP_ROUNDS + measured_rounds
    keys = jax.random.split(jax.random.PRNGKey(1), R)
    t0 = time.perf_counter()
    state, _ = run(state0, keys)
    jax.block_until_ready(state)
    compile_s = time.perf_counter() - t0
    fit = state.surrogate
    rounds = int(fit.rounds)
    fallbacks = int(fit.fallbacks)
    rate = fallbacks / max(1, rounds - SURROGATE_WARMUP_ROUNDS)

    # scoring-pass speedup: exact full sweep vs the surviving-round
    # surrogate pass, SAME carried post-warmup state, min of warm reps
    score_exact = jax.jit(sel.extras["score_exact"])
    tcs0 = jnp.zeros((1,), jnp.int32)
    score_surr = jax.jit(lambda s: sel.extras["score_surrogate"](s, tcs0))
    t_exact = _time_min(score_exact, state)
    t_surr = _time_min(score_surr, state)
    speedup = t_exact / t_surr if t_surr > 0 else None

    # marginal surrogate round seconds, scan-only (bench_batchq's
    # methodology: init outside, warm executions, min of reps)
    R_m = 8
    keys_m = jax.random.split(jax.random.PRNGKey(2), R_m)
    jax.block_until_ready(run(state, keys_m))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(run(state, keys_m))
        best = min(best, (time.perf_counter() - t0) / R_m)
    return {
        "preset": "imagenet_smoke" if args.quick else "imagenet",
        "shape": {"H": H, "N": N, "C": C},
        "posterior": posterior, "eig_chunk": chunk,
        "scorer": f"surrogate:{k}",
        "warmup_rounds": SURROGATE_WARMUP_ROUNDS,
        "measured_rounds": rounds - SURROGATE_WARMUP_ROUNDS,
        "init_s": round(init_s, 2),
        "compile_and_first_run_s": round(compile_s, 2),
        "fallbacks_post_warmup": fallbacks,
        "fallback_rate_post_warmup": rate,
        "scoring_pass_exact_ms": round(t_exact * 1e3, 2),
        "scoring_pass_surrogate_ms": round(t_surr * 1e3, 2),
        "scoring_pass_speedup": speedup,
        "speedup_floor": None if args.quick else MIN_SPEEDUP,
        "round_s_marginal": best,
        "methodology": "scoring passes timed on the same carried "
                       "post-warmup state (min of warm reps); round "
                       "marginal scan-only, init excluded",
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="artifact path (default BENCH_SURROGATE_"
                         "<backend>_rNN.json in the repo root)")
    ap.add_argument("--records-dir", default=None,
                    help="where the flight-recorder records land "
                         "(default runs/surrogate_rNN under --out's "
                         "directory)")
    ap.add_argument("--data-dir", default=os.path.join(REPO, "data"))
    ap.add_argument("--quick", action="store_true",
                    help="smoke capture: digits at a smaller budget + "
                         "the smoke shape (never gates the full "
                         "artifact — different fingerprint knobs)")
    ap.add_argument("--round", type=int, default=17,
                    help="artifact round number for the default filename")
    ap.add_argument("--digits-k", type=int, default=32,
                    help="surrogate shortlist width for the digits half")
    ap.add_argument("--preset-rounds", type=int, default=20,
                    help="post-warmup rounds measured at the preset")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    import jax

    backend = jax.default_backend().upper()
    out_path = args.out or os.path.join(
        REPO, f"BENCH_SURROGATE_{backend}_r{args.round:02d}"
              + ("_quick" if args.quick else "") + ".json")
    if args.records_dir is None:
        args.records_dir = os.path.join(
            os.path.dirname(os.path.abspath(out_path)) or ".",
            "runs", f"surrogate{'_quick' if args.quick else ''}_r"
                    f"{args.round:02d}")

    fingerprint_holder: list = []
    t0 = time.perf_counter()
    digits, default_pin = _run_digits(args, fingerprint_holder)
    preset = _run_preset(args)
    wall = time.perf_counter() - t0

    replays_ok = all(
        (digits.get(side) or {}).get("replay", {}).get("parity") is True
        for side in ("default", "exact", "surrogate"))
    triaged = (digits.get("against_exact", {}).get("classification")
               == "eig-scorer-envelope")
    speedup = preset.get("scoring_pass_speedup")
    floor = preset.get("speedup_floor")
    speedup_ok = (True if floor is None
                  else (speedup is not None and speedup >= floor))
    rate_ok = (preset.get("fallback_rate_post_warmup", 1.0)
               <= MAX_FALLBACK_RATE)
    ok = bool(digits["envelope"]["ok"] and replays_ok and triaged
              and speedup_ok and rate_ok and default_pin["parity"])
    report = {
        "bench": "surrogate",
        "quick": bool(args.quick),
        "wall_s": round(wall, 2),
        "config": {
            "method": "coda",
            "scorer": "closed-form ridge over 16 cheap per-candidate "
                      "features; exact chain refreshes the top-k "
                      "shortlist + rotating audit set under the "
                      "measured contract (2.34e-4 on ranks that "
                      "matter); violated contract falls back to the "
                      "full exact pass and refolds the fit",
            "envelope": {"ratio": ENVELOPE_RATIO,
                         "abs_slack": ENVELOPE_ABS},
            "speedup_floor": MIN_SPEEDUP,
            "max_fallback_rate": MAX_FALLBACK_RATE,
        },
        "digits": digits,
        "imagenet": preset,
        "round_s_marginal": preset["round_s_marginal"],
        "default_exact_pin": default_pin,
        "regret_envelope_ok": bool(digits["envelope"]["ok"]),
        "replays_verified": bool(replays_ok),
        "divergences_triaged": bool(triaged),
        "fingerprint": fingerprint_holder[0] if fingerprint_holder
        else None,
        "ok": ok,
    }
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out_path} (ok={ok}, speedup={speedup}, "
          f"envelope_ok={digits['envelope']['ok']}, "
          f"fallback_rate={preset.get('fallback_rate_post_warmup')})")

    # self-gate: the artifact must satisfy its own check_perf contract
    # (quick captures carry no committed floors — structural gate only)
    if not args.quick:
        from check_perf import check_artifact, match_contract

        contract = match_contract(out_path)
        if contract is None:
            print("self-gate: no contract matches the artifact name")
            return 1
        violations = check_artifact(out_path, report, contract)
        for v in violations:
            print(f"self-gate: {v}")
        if violations:
            return 1
        print("self-gate clean")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
