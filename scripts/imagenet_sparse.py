"""ImageNet-scale sparse-posterior capture (IMAGENET_SPARSE_*.json).

Same container + synthetic-pool methodology as
``scripts/imagenet_virtual.py`` / ``IMAGENET_VIRTUAL_r05.json`` — the
real C=1000 x H=500 pool shape (N scaled to one host, same task seed) —
running the tier that artifact showed the framework NEEDS at this scale:
the incremental EIG with the ``sparse:K`` posterior representation,
where a labeling round touches one compact class row per model instead
of carrying the dense 2 GB ``(H, C, C)`` tensor through the scan.

One deliberate methodology difference, recorded in the artifact: this
capture executes on ONE host device, because the ROADMAP's claim for
this shape is the one-chip interactivity target ("<1 s/round at C=1000
on one chip"). r05's 8-virtual-device mesh existed to verify the dense
tiers' temp-memory scaling and is itself the committed round-time
baseline at this shape; replicating the 2 GB dense prior across 8
virtual devices on one host (16+ GB of replicated init work for a
representation whose point is to delete that tensor) measures the
emulation, not the tier.

Protocol:

  * the sparse config runs the SAME compiled recording program at 1 and
    at ``1 + ROUNDS`` scan steps; the wall-clock DIFFERENCE isolates the
    marginal per-round cost from the one-time init (cache build + first
    dispatch) — the same two-length protocol bench.py uses, sized at 50
    rounds so the delta clears container noise;
  * a dense-posterior run of the SAME incremental tier is recorded at the
    long length, and the two flight-recorder records are compared through
    the REAL ``cli replay --against`` path: the auto tolerance keys off
    the fingerprinted ``posterior`` knob (dense-vs-sparse compares under
    the documented 2.34e-4 score contract, not a fake bitwise bar), and
    any first divergence arrives classified by the triage;
  * posterior state bytes are reported analytically
    (``ops.sparse_rows.posterior_nbytes``) next to XLA's compiled
    argument/temp memory analysis of both programs.

The committed claims (gated by ``scripts/check_perf.py``): round time
>= 20x below the r05 dense capture's best tier at the same shape,
posterior state bytes >= 10x below dense, max |Δscore| within the
2.34e-4 contract.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/imagenet_sparse.py --out IMAGENET_SPARSE_CPU_r12.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

# the committed baseline this capture improves on: the best (rowscan)
# tier of IMAGENET_VIRTUAL_r05.json at the same shape and mesh
R05_BASELINE = {"artifact": "IMAGENET_VIRTUAL_r05.json",
                "eig_mode": "rowscan", "round_s": 736.36}
ROUNDS = 50          # marginal-measurement delta (iters 1 -> 51)
TRACE_K = 8


def _build(task, posterior: str, iters: int, chunk: int):
    import jax
    import jax.numpy as jnp

    from coda_tpu.engine.loop import make_batched_experiment_fn
    from coda_tpu.selectors import CODAHyperparams, make_coda

    hp = CODAHyperparams(eig_mode="incremental", eig_chunk=chunk,
                         posterior=posterior)
    fn = jax.jit(make_batched_experiment_fn(
        lambda p: make_coda(p, hp), iters=iters, trace_k=TRACE_K))
    keys = jnp.stack([jax.random.PRNGKey(0)])
    return fn, (task.preds, task.labels, keys)


def run_config(task, posterior: str, iters: int, chunk: int) -> dict:
    """Compile + execute one recorded config; returns timing, memory
    analysis, and the (result, aux) pair for record building."""
    import jax

    fn, args = _build(task, posterior, iters, chunk)
    label = f"{posterior}/i{iters}"
    print(f"[{label}] lowering+compiling...", flush=True)
    t0 = time.perf_counter()
    compiled = fn.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    print(f"[{label}] compiled in {compile_s:.1f}s; executing...",
          flush=True)
    t0 = time.perf_counter()
    res, aux = compiled(*args)
    res, aux = jax.tree.map(np.asarray, (res, aux))  # host-materialized
    wall_s = time.perf_counter() - t0
    print(f"[{label}] ran in {wall_s:.1f}s", flush=True)
    return {
        "posterior": posterior, "iters": iters,
        "compile_s": round(compile_s, 2), "wall_s": round(wall_s, 2),
        "xla_temp_bytes_per_device": ma.temp_size_in_bytes if ma else None,
        "xla_argument_bytes_per_device": (
            ma.argument_size_in_bytes if ma else None),
        "regret_final": float(np.asarray(res.regret)[0, -1]),
        "finite": bool(np.isfinite(np.asarray(res.regret)).all()),
        "_res": res, "_aux": aux,
    }


def _record_of(entry: dict, task, knobs: dict):
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    fp = environment_fingerprint(dataset=task, knobs=knobs)
    return RunRecord.from_result(
        entry["_res"], entry["_aux"], fp,
        run={"task": task.name, "iters": entry["iters"], "seeds": 1,
             "synthetic": True})


def _max_score_delta(rec_a, rec_b) -> float:
    """max |Δ| over the recorded score quantities (rank-aligned top-k
    scores + the chosen score), the number the contract bounds."""
    worst = 0.0
    for q in ("topk_score", "chosen_score"):
        a, b = np.asarray(rec_a.arrays[q]), np.asarray(rec_b.arrays[q])
        finite = np.isfinite(a) & np.isfinite(b)
        if finite.any():
            worst = max(worst, float(np.max(np.abs(a[finite] - b[finite]))))
    return worst


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--small", action="store_true",
                    help="smoke-test shape (CI), not the artifact config")
    ap.add_argument("--sparse-k", type=int, default=32)
    ap.add_argument("--record-root", default=None,
                    help="where the two flight-recorder records land "
                         "(default: <out>.records/ or a temp dir)")
    args = ap.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform("cpu")  # the site hook force-registers the axon TPU
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.replay import replay_main
    from coda_tpu.ops.sparse_rows import posterior_nbytes
    from coda_tpu.telemetry.recorder import CROSS_BACKEND_SCORE_TOL

    if args.small:
        H, N, C, chunk, k = 20, 256, 40, 64, 8
    else:
        # the r05 pool dims; N scaled exactly as that artifact records
        H, N, C, chunk, k = 500, 256, 1000, 64, args.sparse_k
    sparse_spec = f"sparse:{k}"
    task = make_synthetic_task(seed=5, H=H, N=N, C=C,
                               name="imagenet_sparse")

    iters_long = 1 + ROUNDS
    sparse_short = run_config(task, sparse_spec, 1, chunk)
    sparse_long = run_config(task, sparse_spec, iters_long, chunk)
    dense_short = run_config(task, "dense", 1, chunk)
    dense_long = run_config(task, "dense", iters_long, chunk)

    round_s = (sparse_long["wall_s"] - sparse_short["wall_s"]) / ROUNDS
    dense_round_s = (dense_long["wall_s"] - dense_short["wall_s"]) / ROUNDS
    base_knobs = {"method": "coda", "eig_mode": "incremental",
                  "eig_chunk": chunk, "iters": iters_long, "seeds": 1}
    rec_sparse = _record_of(sparse_long, task,
                            dict(base_knobs, posterior=sparse_spec))
    rec_dense = _record_of(dense_long, task,
                           dict(base_knobs, posterior="dense"))

    root = args.record_root or ((args.out or "IMAGENET_SPARSE")
                                + ".records")
    dir_sparse = os.path.join(root, "sparse")
    dir_dense = os.path.join(root, "dense")
    rec_sparse.save(dir_sparse)
    rec_dense.save(dir_dense)

    # the REAL replay CLI path: auto tolerance keys off the fingerprinted
    # posterior knob (dense-vs-sparse -> the documented score contract)
    report_path = os.path.join(root, "replay_report.json")
    rc = replay_main([dir_sparse, "--against", dir_dense,
                      "--score-tol", "auto", "--out", report_path])
    with open(report_path) as f:
        triage = json.load(f)
    max_dscore = _max_score_delta(rec_sparse, rec_dense)

    post_dense = posterior_nbytes(H, C, None)
    post_sparse = posterior_nbytes(H, C, k)
    first = (triage["seeds"][0] if triage.get("seeds") else {})
    divergence_ok = bool(triage.get("parity")) or (
        first.get("classification") == "tie-break-flip")

    out = {
        "config": "IMAGENET_VIRTUAL_r05.json pool shape (C=%d, H=%d, "
                  "N=%d), incremental tier, posterior=%s"
                  % (C, H, N, sparse_spec),
        "devices": len(jax.devices()),
        "mesh": "single host device (the ROADMAP one-chip interactivity "
                "target; r05's data=8 virtual mesh verified dense-tier "
                "temp scaling and is the round-time baseline here)",
        "shape": {"H": H, "N": N, "C": C, "chunk": chunk,
                  "rounds_measured": ROUNDS},
        "baseline": dict(R05_BASELINE),
        "sparse": {
            k2: v for k2, v in sparse_long.items()
            if not k2.startswith("_")},
        "sparse_short": {
            k2: v for k2, v in sparse_short.items()
            if not k2.startswith("_")},
        "dense_ref": {
            k2: v for k2, v in dense_long.items()
            if not k2.startswith("_")},
        "dense_ref_short": {
            k2: v for k2, v in dense_short.items()
            if not k2.startswith("_")},
        "round_s_marginal": round(round_s, 4),
        # the same-setup comparison: dense INCREMENTAL on the same single
        # device (the strongest dense config, much faster than r05's
        # forced factored/rowscan tiers) vs sparse
        "dense_round_s_marginal": round(dense_round_s, 4),
        "round_time_reduction_vs_dense_ref": round(
            dense_round_s / max(round_s, 1e-9), 2),
        "round_time_reduction_vs_r05": round(
            R05_BASELINE["round_s"] / max(round_s, 1e-9), 2),
        "state": {
            "dense_posterior_bytes": post_dense,
            "sparse_posterior_bytes": post_sparse,
            "bytes_ratio": round(post_dense / post_sparse, 2),
        },
        "replay": {
            "cli": "cli replay %s --against %s --score-tol auto"
                   % (dir_sparse, dir_dense),
            "score_tol": (triage.get("score_tol")
                          if triage.get("score_tol") is not None
                          else CROSS_BACKEND_SCORE_TOL),
            "parity": bool(triage.get("parity")),
            "rc": rc,
            "max_abs_dscore": max_dscore,
            "first_divergence": ({
                "round": first.get("first_divergent_round"),
                "quantity": first.get("quantity"),
                "classification": first.get("classification")}
                if not triage.get("parity") else None),
            "knob_diff": (triage.get("meta") or {}).get("knob_diff"),
        },
    }
    from coda_tpu.telemetry.recorder import environment_fingerprint

    out["fingerprint"] = environment_fingerprint(
        dataset=task, knobs={"capture": "imagenet_sparse",
                             "posterior": sparse_spec, "small": args.small,
                             "rounds": ROUNDS, "chunk": chunk})
    out["ok"] = bool(
        sparse_long["finite"] and dense_long["finite"]
        and max_dscore <= CROSS_BACKEND_SCORE_TOL
        and divergence_ok
        # the byte-ratio and round-time contracts are claims about the
        # artifact shape; the CI smoke shape only proves the pipeline
        and (args.small or (out["state"]["bytes_ratio"] >= 10.0
                            and out["round_time_reduction_vs_r05"]
                            >= 20.0)))
    print(json.dumps({k2: v for k2, v in out.items()}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {args.out}", file=sys.stderr)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
